"""Continuous-batching scheduler over a paged KV cache.

The chunk list becomes a prefill/decode work queue over fixed decode slots
(SURVEY.md §2.2: the reference's asyncio-semaphore fan-out,
llm_executor.py:133-147, re-based onto batch-slot + page admission control):

* a request is admitted when a slot is free AND the page pool can hold its
  prompt + token budget (admission = free KV pages, the semaphore analog);
* prefill runs one bucketed [1, S] forward writing K/V straight into the
  sequence's pages and samples the first token on device;
* all active slots decode together in blocks of ``decode_block`` steps per
  dispatch (one ``lax.scan`` on device; the host syncs once per block);
* decode attention cost is proportional to LIVE context: the page window
  passed to the decode program is bucketed to the widest active sequence
  (compile-per-bucket), and on TPU the ragged Pallas kernel walks only each
  row's real pages (ops/paged_attention.py);
* a finished slot frees its pages and the next queued request is admitted —
  prefill and decode interleave across requests.

Static shapes throughout: prompt buckets and page-window buckets are powers
of two, the decode block is fixed — a handful of XLA compilations total,
reused for the whole run.
"""

from __future__ import annotations

import logging
import threading
import time
from collections import deque
from dataclasses import dataclass, field
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

from lmrs_tpu.config import EngineConfig, ModelConfig
from lmrs_tpu.engine.api import (GenerationRequest, GenerationResult,
                                 apply_stop_sequences, preamble_key,
                                 preamble_text, remaining_budget)
from lmrs_tpu.engine.kv_cache import (OutOfPages, PagedKVCache, SequencePages,
                                      audit_allocator)
from lmrs_tpu.engine.prefix_cache import PrefixCache
from lmrs_tpu.fleet.qos import maybe_qos
from lmrs_tpu.models.transformer import forward_paged
from lmrs_tpu.ops.paged_attention import pack_spans, pow2_bucket
from lmrs_tpu.obs import (POW2_TOKEN_BUCKETS, RATIO_BUCKETS, CostLedger,
                          DispatchAttribution, MetricsRegistry, SLOEngine,
                          dump_postmortem, get_tracer, maybe_anatomy, req_tid)
from lmrs_tpu.ops.sampling import sample_logits
from lmrs_tpu.testing import faults
from lmrs_tpu.utils.env import env_bool, env_float, env_int, env_str

logger = logging.getLogger("lmrs.scheduler")

# bucket edges shared with the kernel family and the bucket-economics
# accounting (ops/paged_attention.pow2_bucket — one definition)
_pow2_bucket = pow2_bucket


# NOTE: quarter-step sequence buckets (p*1.25/1.5/1.75 between powers of
# two) were tried to cut prefill padding for prompts just past a power of
# two — measured 3x WORSE end-to-end: the extra compile shapes thrash the
# multi-second XLA compiles at runtime.  Pure pow2 buckets stay.


@dataclass
class _SlotState:
    req: GenerationRequest
    prompt_ids: list[int]  # ids to prefill (after a preemption: prompt + prior)
    max_new: int
    seq: SequencePages
    generated: list[int] = field(default_factory=list)
    kv_len: int = 0
    done: bool = False
    t_start: float = 0.0
    # chunked prefill (SARATHI-style): a slot is admitted in "prefill" phase
    # and advances one prompt chunk per scheduler iteration, so active decode
    # slots keep decoding between chunks instead of stalling behind one long
    # prompt.  ``prefill_pos`` = prompt tokens already written to KV.
    phase: str = "prefill"
    prefill_pos: int = 0
    # tracing anchors (obs/trace.py): admission and prefill-complete times
    # for this SLOT LIFE — a preemption continuation opens fresh spans
    t_admit: float = 0.0
    t_decode_start: float = 0.0
    # preemption bookkeeping: a preempted slot re-enters the queue with its
    # generated-so-far tokens folded into ``prompt_ids`` (the continuation
    # re-prefills them); ``n_prompt`` keeps the ORIGINAL prompt length for
    # accounting and ``prior`` the tokens generated before the preemption.
    n_prompt: int = 0
    prior: list[int] = field(default_factory=list)
    # tree speculation (ISSUE 19): accepted tokens of a NON-FIRST chain
    # sit at that chain's span-offset KV columns, so the row's next span
    # re-sends them as leading "healing" query tokens (base = kv_len -
    # len(spec_heal)) to rewrite K/V at their true columns; ``spec_ema``
    # is the windowed acceptance rate feeding the adaptive depth ramp,
    # ``spec_hoff`` the history-buffer offset of a cross-refresh draft
    # hint seeded ahead of the prompt, ``spec_hint`` its token ids.
    spec_heal: list[int] = field(default_factory=list)
    spec_ema: float = 0.5
    spec_depth: int = 0
    spec_probe: int = 0  # steps spent at depth 0 (periodic re-probe timer)
    spec_hoff: int = 0
    spec_hint: list[int] = field(default_factory=list)


class ContinuousScheduler:
    """Host-side scheduling loop over device-side prefill/decode programs."""

    def __init__(self, engine_cfg: EngineConfig, model_cfg: ModelConfig,
                 params, tokenizer, mesh=None):
        self.cfg = engine_cfg
        self.model_cfg = model_cfg
        self.params = params
        self.tokenizer = tokenizer
        self.mesh = mesh  # tensor-parallel serving: params + pages sharded
        self.B = max(1, engine_cfg.max_batch_slots)
        self.max_len = model_cfg.max_seq_len
        # decode steps per dispatch: the host syncs once per block, so on
        # high-latency links (tunneled chips, remote hosts) a bigger block
        # amortizes the round trip; overshoot past a slot's budget is
        # trimmed in _maybe_finish and its pages are pre-reserved in admit()
        self.decode_block = max(1, engine_cfg.decode_block)
        # speculation: each scan step verifies spec_k drafts + 1 bonus, so
        # fewer steps per dispatch keep tokens-per-block ~= decode_block
        self.spec_k = max(0, engine_cfg.speculate_k)
        self.decode_steps = (max(1, self.decode_block // (self.spec_k + 1))
                             if self.spec_k else self.decode_block)
        self.prefill_chunk = max(64, engine_cfg.prefill_chunk)
        # Defer the prefill first-token fetch into the decode block's
        # transfer (one fewer host RTT per admission wave).  Tradeoff: a
        # request finishing ON its first token (tok0==EOS, or max_new<=1)
        # burns one decode-block dispatch whose tokens are trimmed — rare
        # for summarization workloads.  LMRS_DEFER_TOK0=0 restores the
        # synchronous fetch for A/B measurement.
        self.defer_tok0 = env_bool("LMRS_DEFER_TOK0", True)
        ps = engine_cfg.page_size
        max_pages_per_slot = -(-self.max_len // ps)
        # Pool sizing: an explicit num_pages (> 1) is an HBM budget and is
        # honored, floored at one full-length sequence + the reserved null
        # page — under pressure, slots grow on demand and the youngest is
        # preempted (vLLM-style) instead of over-provisioning.  num_pages <= 1
        # asks for worst-case sizing (every slot can hold a full sequence;
        # preemption can then never trigger).
        if engine_cfg.num_pages > 1:
            num_pages = max(engine_cfg.num_pages, max_pages_per_slot + 1)
        else:
            num_pages = self.B * max_pages_per_slot + 1
        # int8 KV pages (EngineConfig.kv_quantize): per-(slot, kv head,
        # channel) scales fixed at prefill ride [L, B, K, hd] buffers
        # through the dispatch programs (ops/quant.py KV section)
        self._kv_quant = engine_cfg.kv_quantize
        if self._kv_quant and ps % 32:
            # int8 VMEM tiles are (32, 128): the RMW window machinery needs
            # 32-row-aligned windows that never straddle a page
            raise ValueError(f"kv_quantize=int8 needs page_size % 32 == 0 "
                             f"(got {ps})")
        self.cache = PagedKVCache(model_cfg, num_pages, ps, max_pages_per_slot,
                                  mesh=mesh,
                                  kv_dtype="int8" if self._kv_quant else None)
        if self._kv_quant:
            sshape = (model_cfg.n_layers, self.B, model_cfg.n_kv_heads,
                      model_cfg.hd)
            self.kscale = jnp.ones(sshape, jnp.float32)
            self.vscale = jnp.ones(sshape, jnp.float32)
        else:
            self.kscale = self.vscale = None
        # LMRS_FORCE_KERNELS=interpret: run the Pallas kernels in interpret
        # mode regardless of platform — the CPU-mesh test path for the
        # shard_map-wrapped kernels (tests can't see a real TPU)
        self._interpret = (env_str("LMRS_FORCE_KERNELS").lower()
                           == "interpret")
        self._use_ragged = self._pick_kernel()
        # Multi-row decode page walk (ops/paged_attention.py): G batch rows
        # per ragged-decode program, sharing one DMA pipeline — amortizes
        # the per-row program fixed cost that dominated the 8B decode
        # intercept (docs/PERF.md r5).  Dispatches permute rows through a
        # host-side length-balanced assignment (balanced_row_order) so a
        # straggler row cannot serialize its group.  LMRS_MULTIROW=0 is
        # the kill switch (exact per-row grid + unpermuted dispatch, the
        # LMRS_PACK_PREFILL A/B convention).
        self._row_group = 1
        if env_bool("LMRS_MULTIROW", True):
            self._row_group = max(1, min(engine_cfg.decode_row_group, self.B))
        # flash prefill: same tp-only-mesh limit as the ragged gate (under a
        # mesh the kernel runs via shard_map over the tp head axis); also
        # cleared if lowering fails at runtime
        self._use_flash = self._tp_only_mesh()
        # Packed prefill: concatenate same-wave fresh prompts into one [1, S]
        # row with segment-id masking — the dense matmuls (QKV/FFN/head) then
        # run on real tokens only instead of ~pow2-bucket padding per prompt
        # (measured ~43% padded q rows at the bench shape).  LMRS_PACK_PREFILL=0
        # restores per-prompt prefill for A/B measurement.
        self._pack_prefill = env_bool("LMRS_PACK_PREFILL", True)
        # int8 KV composes with packing since r4 (VERDICT r3 item 3): the
        # packed program computes per-SEGMENT scales and scatters them into
        # each segment's slot row — no gate needed
        # Serving-side context parallelism (SURVEY.md §5.7 tier b): under an
        # sp>1 mesh, LONG fresh prefills run cache-aware ring attention —
        # the sequence shards over sp, K/V still scatter into the page pool.
        # Short prompts (< _ring_min) keep the packed/flash path: at those
        # lengths ring hops buy no memory and cost ppermute latency.
        # Chunked (window) prefill cannot ride the ring (the window K/V is
        # pool-side, not sequence-sharded), so under sp the whole prompt
        # prefills in ONE ring dispatch: ring replaces chunking as the
        # long-prompt strategy.
        self._sp = 1 if mesh is None else mesh.shape.get("sp", 1)
        self._use_ring = self._sp > 1
        if self._kv_quant and self._use_ring:
            raise ValueError(
                "kv_quantize=int8 does not support ring (sp) prefill yet: "
                "scales are per-slot and ring writes are sequence-sharded")
        # kv_quantize=int8 composes with speculative decoding since r5:
        # the multi-token verify kernel carries the same per-channel
        # dequant folds as the single-token fused kernel (q-prescale /
        # accumulator-postscale are row-count-agnostic) and its RMW
        # quantizes draft rows with the slot's frozen scales
        # (ops/paged_attention.paged_decode_pallas_multi).
        self._ring_min = 1024
        # Fail fast at construction: ring buckets are rounded UP to a
        # multiple of sp at dispatch, which stays <= max_len only when
        # max_len itself divides.  Without this check a long chunk would
        # have to fall back to fully-materialized attention — on exactly
        # the configs ring exists for, that is the OOM path (VERDICT r2
        # weak #6: impossible by construction, not by coincidence).
        if self._use_ring and self.max_len % self._sp:
            raise ValueError(
                f"max_seq_len={self.max_len} is not divisible by sp="
                f"{self._sp}; ring prefill shards the sequence over sp — "
                "pick a max_seq_len that divides (pow2 lengths with pow2 "
                "sp always do)")
        if self._use_ring and self.prefill_chunk < self.max_len:
            logger.info("sp=%d mesh: chunked prefill disabled in favor of "
                        "one-dispatch ring prefill", self._sp)
            self.prefill_chunk = self.max_len
        # Shared-prefix KV cache (engine/prefix_cache.py): completed prompt
        # prefixes stay in the pool as ref-counted pages keyed by a radix
        # tree; admission clones the matched page-table prefix and enters
        # the chunked-prefill path at the match boundary.  LMRS_PREFIX_CACHE=0
        # is the A/B kill switch (same convention as LMRS_PACK_PREFILL).
        pc_on = (engine_cfg.prefix_cache
                 and env_bool("LMRS_PREFIX_CACHE", True))
        if pc_on and self._kv_quant:
            # int8 KV scales are per-SLOT, frozen at prefill: a hit slot
            # would dequantize donor-quantized pages with its own scales
            logger.info("prefix cache disabled: incompatible with int8 KV "
                        "(per-slot scales cannot cover donor pages)")
            pc_on = False
        if pc_on and self._use_ring:
            # cache hits enter the windowed-continuation prefill, which
            # cannot ride the ring (window K/V is pool-side, not
            # sequence-sharded)
            logger.info("prefix cache disabled under sp>1 mesh")
            pc_on = False
        # SARATHI-style mixed batches (config.EngineConfig.mixed_batch):
        # while any slot is mid-prefill AND any slot is decoding, each
        # step dispatches ONE fused multi-token batch — every live decode
        # row carries one real token, one prefilling slot carries a prompt
        # slice clipped to `mixed_token_budget - decode_tokens` — through
        # the ragged multi-token path (paged_decode_pallas_multi /
        # paged_decode_multi_xla; the row-group kernels already
        # parametrize per-row token counts).  Decode cadence never pauses
        # for an admission and prefill rides the decode step's spare
        # FLOPs.  LMRS_MIXED=0 is the kill switch (exact alternating
        # dispatch, the LMRS_PACK_PREFILL A/B convention).  Gated off:
        #  * int8 KV — a mixed chunk dispatches through the frozen-scale
        #    decode path and could never OWN its slot's prefill scales;
        #  * sp>1 meshes — ring prefill replaced chunking, so there is no
        #    prompt slice to piggyback.
        # Speculation yields during mixed steps: decode rows advance one
        # token per step (drafting needs the device history buffer
        # appended in-scan; mixed steps re-seed it instead) and full spec
        # blocks resume once the admission wave's prefill drains — greedy
        # outputs are identical either way (exact-distribution verify).
        # Ragged span dispatch (RPA, ISSUE 16): ONE kernel family where
        # every dispatch is a list of (row, query-span) pairs — decode is
        # q_len=1 rows, verify q_len=k+1 rows, a mixed step decode rows
        # plus one prefill-slice row, continuation chunks long-span rows.
        # Compile buckets collapse to (pow2 total-query-tokens, pow2 page
        # window).  LMRS_RPA=0 restores every legacy path byte-for-byte.
        # RPA lifts two of the gates above: int8 KV x mixed (per-row
        # frozen scales ride the span descriptor — a fresh-start slice
        # owns its slot's scales exactly like a fresh prefill) and
        # spec x mixed (decode rows carry verify spans in-graph, so spec
        # no longer yields during prefill windows).
        self._rpa = env_bool("LMRS_RPA", True)
        self._rpa_fns: dict[tuple, object] = {}
        self._mixed = (engine_cfg.mixed_batch and env_bool("LMRS_MIXED", True)
                       and (self._rpa or not self._kv_quant)
                       and not self._use_ring)
        self.mixed_token_budget = max(32, engine_cfg.mixed_token_budget)
        self._mixed_fns: dict[tuple[int, int], object] = {}
        # Tree speculation on the span family (ISSUE 19): the linear draft
        # becomes LMRS_SPEC_TREE_WIDTH root-branching chains drafted
        # in-graph from the device history buffer and verified in ONE
        # ("rpa_spec", tpb, w) span dispatch whose causal mask follows
        # parent pointers (ancestor bitmasks, ragged_spans_xla).  Requires
        # the span dispatch + mixed routing (a token tree IS a span);
        # LMRS_SPEC_TREE=0 restores the linear spec path byte-for-byte
        # and speculate_k=0 keeps everything inert.
        self._spec_width = env_int("LMRS_SPEC_TREE_WIDTH", 2, lo=1, hi=8)
        # ancestor bitmasks are int32 over span-local offsets: the span is
        # [heal (<= depth), cur, width x depth], so clamp width until
        # 1 + depth*(width+1) fits in 32 bits; a depth that cannot fit
        # even one chain falls back to linear speculation
        while (self._spec_width > 1
               and 1 + self.spec_k * (self._spec_width + 1) > 32):
            self._spec_width -= 1
        self._spec_tree = (bool(self.spec_k) and self._rpa and self._mixed
                           and 1 + self.spec_k * (self._spec_width + 1) <= 32
                           and env_bool("LMRS_SPEC_TREE", True))
        # adaptive per-request depth: a windowed acceptance EMA per slot
        # ramps chain depth up on accept streaks and down to off on
        # acceptance collapse or page pressure (LMRS_SPEC_ADAPTIVE=0
        # pins every row at full depth)
        self._spec_adaptive = (self._spec_tree
                               and env_bool("LMRS_SPEC_ADAPTIVE", True))
        # per-heal-length (pos_off, ancestor-bitmask) span templates —
        # host-side operand build is a dict lookup + two copies per row
        self._spec_tmpl: dict[int, tuple[np.ndarray, np.ndarray]] = {}
        # prefix cache constructed AFTER the metrics registry below (the
        # host-RAM spill tier feeds registry instruments); _pc_on carries
        # the gate decision down
        self._prefix_cache: PrefixCache | None = None
        self._pc_on = pc_on
        # Host-RAM KV spill tier (engine/host_kv.py): LMRS_HOST_KV=0 /
        # host_kv=False restores evict-means-gone byte-for-byte;
        # LMRS_HOST_KV_SYNC=1 blocks each prefetch scatter (A/B fallback
        # for the default async overlap).
        self._host_kv_sync = env_bool("LMRS_HOST_KV_SYNC", False)
        # Published radix summary (prefix-aware fleet routing,
        # docs/SERVING.md): distinct request preambles seen by this
        # engine, keyed by api.preamble_key — the router fetches
        # ``prefix_summary()`` through /healthz and routes
        # sticky-by-expected-prefix-hit.  Written by the scheduler thread
        # (_note_preamble); read by HTTP handler threads through the
        # guarded, memoized prefix_summary() snapshot.
        self._preambles: dict[str, dict] = {}
        self._preamble_tick = 0
        self._summary_memo: tuple[float, list] | None = None
        self._key = jax.random.PRNGKey(engine_cfg.seed + 17)
        # Request abort (VERDICT r3 item 4): ids land here from any thread
        # (set.add is atomic under the GIL — the HTTP server cancels from a
        # handler thread while run() owns the scheduling loop) and are
        # swept at the next block boundary: the slot's pages free
        # immediately instead of decoding an abandoned request to
        # max_tokens.  The reference got this for free from asyncio — a
        # dropped connection cancelled the task (llm_executor.py:290-296);
        # a continuous-batching engine must build it.
        self._cancelled: set[int] = set()
        self._prefill_fns: dict[int, object] = {}
        self._prefill_window_fns: dict[tuple[int, int], object] = {}
        self._packed_prefill_fns: dict[int, object] = {}
        self._decode_fns: dict[int, object] = {}
        self._ran_ok: set = set()  # fn-cache keys that have executed once
        self._spec_buf = None  # device token-history buffer (speculation)
        # rows whose device history row went stale during mixed steps
        # (decode advanced outside the spec scan): re-seeded LAZILY at
        # the next spec block, once per row per mixed window — an eager
        # per-step seed would be O(B*max_len) host uploads per token
        self._spec_stale: set[int] = set()
        self._on_tokens = None  # per-block streaming callback (run()-scoped)
        self._streamed: dict[int, str] = {}
        # Engine metrics (SURVEY.md §5.5: tokens/s, occupancy, HBM analog),
        # migrated from the former raw dict onto a typed registry
        # (obs/metrics.py): counters/gauges keep the old dict's exact key
        # semantics via the ``metrics`` snapshot property, histograms
        # replace the former unbounded-ish _ttft/_block_gaps sample lists
        # (same bounded reservoir, plus fixed buckets for Prometheus).
        self.registry = MetricsRegistry()
        c, g, h = (self.registry.counter, self.registry.gauge,
                   self.registry.histogram)
        self._c_prefill_tokens = c("lmrs_prefill_tokens_total",
                                   "prompt tokens prefilled", "tokens")
        self._c_decode_tokens = c("lmrs_decode_tokens_total",
                                  "tokens generated by decode blocks",
                                  "tokens")
        self._c_decode_dispatches = c("lmrs_decode_dispatches_total",
                                      "decode-block dispatches issued")
        self._c_run_seconds = c("lmrs_run_seconds_total",
                                "scheduler wall-clock inside run()",
                                "seconds")
        # time inside blocking device fetches (run() path only): the device
        # is busy (or draining the tunnel) while the host waits here, so
        # run_seconds - blocked_seconds is the host-side share — bookkeeping
        # the device sits idle for (r5: ~17% of 8B map wall; the
        # attribution number for any overlap lever)
        self._c_blocked_seconds = c("lmrs_blocked_seconds_total",
                                    "host time blocked in device fetches",
                                    "seconds")
        self._c_spec_accepted = c("lmrs_spec_accepted_tokens_total",
                                  "draft tokens accepted (speculation)",
                                  "tokens")
        self._c_preemptions = c("lmrs_preemptions_total",
                                "slots evicted to the queue under page "
                                "pressure")
        self._c_stalls = c("lmrs_stalls_total",
                           "dispatches a slot sat out waiting for pages")
        self._c_cancelled = c("lmrs_cancelled_total",
                              "requests aborted via cancel()")
        # deadline lifecycle (GenerationRequest.deadline_s): in-flight
        # expiries swept at block boundaries, admission-time sheds, and the
        # slack requests arrive with (how close to the line the fleet runs)
        self._c_deadline = c("lmrs_deadline_exceeded_total",
                             "requests expired in flight "
                             "(finish_reason=deadline)")
        self._c_shed = c("lmrs_requests_shed_total",
                         "requests shed at admission "
                         "(finish_reason=shed)")
        self._h_deadline_remaining = h("lmrs_deadline_remaining_seconds",
                                       help="remaining deadline budget at "
                                            "admission", unit="seconds")
        # prefix-cache counters (present even when the cache is off, so
        # bench windowing can always delta them): admissions that queried
        # the radix tree, admissions that matched, and prompt tokens whose
        # prefill was skipped via cached pages
        self._c_prefix_queries = c("lmrs_prefix_queries_total",
                                   "admissions that queried the prefix tree")
        self._c_prefix_hits = c("lmrs_prefix_hits_total",
                                "admissions that matched a cached prefix")
        self._c_prefix_tokens = c("lmrs_prefix_tokens_reused_total",
                                  "prompt tokens served from cached pages",
                                  "tokens")
        # host-RAM spill tier (engine/host_kv.py): device-evicted cache
        # pages captured host-side and prefetched back on later matches —
        # present even when the tier is off, so bench windowing can
        # always delta them (same convention as the prefix counters)
        self._c_spill_pages = c("lmrs_prefix_spill_pages_total",
                                "prefix-cache pages captured into the "
                                "host-RAM spill tier at eviction", "pages")
        self._c_spill_dropped = c("lmrs_prefix_spill_dropped_pages_total",
                                  "spilled pages dropped from the host "
                                  "pool (budget LRU / subtree drops)",
                                  "pages")
        self._h_spill_capture = h("lmrs_prefix_spill_capture_seconds",
                                  help="device→host capture of one "
                                       "spilled node's pages",
                                  unit="seconds")
        self._c_prefetch_pages = c("lmrs_prefix_prefetch_pages_total",
                                   "spilled pages restored into device "
                                   "pages on a radix match", "pages")
        self._c_prefetch_tokens = c("lmrs_prefix_tokens_prefetched_total",
                                    "prompt tokens restored from the host "
                                    "tier instead of re-prefilled",
                                    "tokens")
        self._c_spilled_hits = c("lmrs_prefix_spilled_hits_total",
                                 "admissions whose prefix match extended "
                                 "into spilled segments")
        self._h_prefetch = h("lmrs_prefix_prefetch_seconds",
                             help="host→device prefetch issue per "
                                  "admission (async unless "
                                  "LMRS_HOST_KV_SYNC)", unit="seconds")
        self._g_host_pool = g("lmrs_prefix_host_pool_bytes",
                              "bytes currently held by the host-RAM KV "
                              "spill pool", "bytes")
        # disk spill tier (host_kv.DiskKVPool, ROADMAP item 4) — present
        # even when the tier is off, same delta-ability convention
        self._c_disk_demoted = c("lmrs_kv_disk_demoted_pages_total",
                                 "spilled pages demoted host→disk under "
                                 "host-pool budget pressure", "pages")
        self._c_disk_promoted = c("lmrs_kv_disk_promoted_pages_total",
                                  "disk-tier pages promoted back via the "
                                  "prefetch path (disk→host→device)",
                                  "pages")
        self._c_disk_dropped = c("lmrs_kv_disk_dropped_pages_total",
                                 "disk-tier pages dropped (disk budget "
                                 "LRU / subtree drops)", "pages")
        self._c_disk_read_fail = c("lmrs_kv_disk_read_failures_total",
                                   "disk spill reads that failed "
                                   "(missing/torn/corrupt file) and "
                                   "degraded to re-prefill")
        self._g_disk_bytes = g("lmrs_kv_disk_bytes",
                               "bytes currently held by the disk spill "
                               "pool", "bytes")
        # cross-host KV migration (docs/SERVING.md KV fabric): page sets
        # exported to / imported from sibling hosts through /v1/kv
        self._c_migrate_exports = c("lmrs_kv_migrate_exports_total",
                                    "warm page sets exported for "
                                    "cross-host migration")
        self._c_migrate_imports = c("lmrs_kv_migrate_imports_total",
                                    "migrated page sets imported into "
                                    "the prefix cache")
        self._c_migrate_tokens = c("lmrs_kv_migrate_tokens_total",
                                   "prompt tokens installed warm via "
                                   "cross-host migration", "tokens")
        if self._pc_on:
            pool = None
            cb = None
            pb = 0
            if engine_cfg.host_kv and engine_cfg.host_kv_gb > 0:
                from lmrs_tpu.engine.host_kv import DiskKVPool, HostKVPool

                disk = None
                if engine_cfg.kv_disk and engine_cfg.kv_disk_gb > 0:
                    disk = DiskKVPool(int(engine_cfg.kv_disk_gb * 2**30),
                                      engine_cfg.kv_disk_dir)
                pool = HostKVPool(int(engine_cfg.host_kv_gb * 2**30),
                                  disk=disk)
                cb = self.cache.export_pages
                pb = self.cache.page_payload_bytes()
            self._prefix_cache = PrefixCache(
                self.cache.allocator, ps,
                max_pages=engine_cfg.prefix_cache_max_pages,
                spill_pool=pool, capture_cb=cb, page_bytes=pb,
                metrics={"spill_pages": self._c_spill_pages,
                         "spill_dropped": self._c_spill_dropped,
                         "spill_capture_s": self._h_spill_capture,
                         "pool_bytes": self._g_host_pool,
                         "disk_demoted": self._c_disk_demoted,
                         "disk_promoted": self._c_disk_promoted,
                         "disk_dropped": self._c_disk_dropped,
                         "disk_read_fail": self._c_disk_read_fail,
                         "disk_bytes": self._g_disk_bytes})
            self.cache.reclaim_cb = self._prefix_cache.evict
        # mixed-batch dispatch: real tokens (decode + piggybacked prefill
        # slice) over the step's token budget, and the prompt tokens whose
        # prefill rode a decode step instead of a dedicated prefill wave
        self._h_mixed_fill = h("lmrs_mixed_batch_fill_ratio",
                               buckets=RATIO_BUCKETS,
                               help="real tokens over mixed_token_budget "
                                    "per mixed fused dispatch")
        self._c_piggybacked = c("lmrs_prefill_tokens_piggybacked_total",
                                "prompt tokens prefilled inside mixed "
                                "decode steps", "tokens")
        # ragged span dispatch: real query tokens per RPA dispatch (the
        # padding complement of the pow2 total-token bucket), and the
        # headline compile-zoo number — distinct (bucket, window) program
        # shapes built so far (the legacy per-phase matrix this replaces
        # compiled decode + spec + mixed + chunk families separately)
        self._h_rpa_span = h("lmrs_rpa_span_tokens",
                             buckets=POW2_TOKEN_BUCKETS,
                             help="real query-span tokens per ragged span "
                                  "dispatch", unit="tokens")
        self._c_rpa_shapes = c("lmrs_rpa_compile_shapes_total",
                               "distinct ragged-span program shapes "
                               "compiled", "shapes")
        # tree speculation (ISSUE 19): drafted tree size per row, accepted
        # root-to-leaf depth per row, and the tree-span dispatch count —
        # present even when tree spec is off, so bench windowing can
        # always delta them (the prefix-counter convention)
        self._h_spec_nodes = h("lmrs_spec_tree_nodes",
                               help="drafted tree nodes per decode row "
                                    "per tree-spec dispatch", unit="nodes")
        self._h_spec_depth = h("lmrs_spec_accept_depth",
                               help="accepted draft tokens per decode row "
                                    "per tree-spec dispatch", unit="tokens")
        self._c_spec_tree_disp = c("lmrs_spec_tree_dispatches_total",
                                   "tree-speculative span dispatches")
        self._g_peak_pages = g("lmrs_peak_pages_in_use",
                               "max KV pages simultaneously allocated",
                               "pages")
        self._g_peak_slots = g("lmrs_peak_active_slots",
                               "max simultaneously-occupied batch slots")
        # TTFT: scheduler-enqueue -> first host-visible token per fresh
        # request; block gap: seconds between consecutive decode dispatches
        # within a run — the cadence a streaming client receives delta
        # batches at (VERDICT r4 item 5: always on, never script-only)
        self._h_ttft = h("lmrs_ttft_seconds",
                         help="time to first token (engine-side)",
                         unit="seconds")
        self._h_block_gap = h("lmrs_decode_block_gap_seconds",
                              help="gap between consecutive decode "
                                   "dispatches", unit="seconds")
        self._h_queue_wait = h("lmrs_queue_wait_seconds",
                               help="enqueue -> slot admission wait",
                               unit="seconds")
        self._h_prefill_batch = h("lmrs_prefill_batch_tokens",
                                  buckets=POW2_TOKEN_BUCKETS,
                                  help="real prompt tokens per prefill "
                                       "dispatch", unit="tokens")
        self._h_occupancy = h("lmrs_decode_occupancy_ratio",
                              buckets=RATIO_BUCKETS,
                              help="fraction of batch slots live per "
                                   "decode dispatch")
        # multi-row kernel group occupancy: live rows over the dispatched
        # group capacity (ceil(rows/G)*G) — the padding waste the
        # row-group layout introduces; only observed when grouping is on
        self._h_group_occupancy = h("lmrs_decode_group_occupancy_ratio",
                                    buckets=RATIO_BUCKETS,
                                    help="live rows over row-group "
                                         "capacity per decode dispatch")
        self._tr = get_tracer()  # refreshed at each run()
        # Deadline bookkeeping: fastest TTFT ever observed on this engine —
        # the OPTIMISTIC admission estimate (shed only what is provably
        # unmeetable; the mean would embed multi-second first-compile
        # samples and shed healthy requests).  _any_deadline gates the
        # per-iteration expiry sweep so deadline-free workloads pay zero.
        self._ttft_min = float("inf")
        self._any_deadline = False
        # auditor bookkeeping: result records that OVERWROTE an existing
        # result (every submitted id must terminate exactly once)
        self._audit_double_finish = 0
        # Disaggregated handoff (docs/SERVING.md): sequences whose pages
        # are PINNED for export — prefill finished, first token sampled,
        # payload captured host-side, waiting for the decode pod's ack.
        # rid -> {seq, payload, deadline_t, t_pinned}.  The lock covers
        # the dict AND the run-liveness flag: export/release run on HTTP
        # handler threads while the scheduler loop pins and sweeps.  Like
        # cancel(), off-thread releases never touch the allocator while a
        # run is live — a released record is parked on _release_deferred
        # and its pages freed by the scheduler thread at the next block
        # boundary (the allocator and prefix-cache refcounts have no
        # internal synchronization).  With no run live the free happens
        # inline, under the lock, so a starting run (which flips
        # _run_live under the same lock before its first allocation)
        # can never overlap it.  audit() accounts both classes as
        # pinned-for-export holders.
        self._pinned: dict[int, dict] = {}  # guarded-by: _pinned_lock
        # guarded-by: _pinned_lock
        self._release_deferred: list[tuple[int, dict, bool]] = []
        self._run_live = False  # guarded-by: _pinned_lock
        self._pinned_lock = threading.Lock()
        self._c_handoff_exports = c("lmrs_handoff_exports_total",
                                    "requests pinned for prefill→decode "
                                    "handoff")
        self._c_handoff_imports = c("lmrs_handoff_imports_total",
                                    "sequences imported from a handoff "
                                    "payload")
        self._c_handoff_orphaned = c("lmrs_handoff_orphaned_pages_total",
                                     "pinned pages reclaimed by the "
                                     "orphan sweep (ticket never acked)",
                                     "pages")
        self._g_pinned_pages = g("lmrs_handoff_pinned_pages",
                                 "KV pages currently pinned for export",
                                 "pages")
        self._h_handoff_capture = h("lmrs_handoff_capture_seconds",
                                    help="pin-time host capture of an "
                                         "exported page set",
                                    unit="seconds")
        self._h_handoff_import = h("lmrs_handoff_import_seconds",
                                   help="device scatter of an imported "
                                        "page set at admission",
                                   unit="seconds")
        # Live performance attribution (obs/perf.py): per-dispatch
        # FLOPs/bytes from the roofline model, measured dispatch walls
        # (minus host RTT) -> lmrs_prefill_mfu_ratio /
        # lmrs_decode_hbm_util_ratio / lmrs_step_gap_ms.  Pending-flops
        # bookkeeping: prefill dispatches issued this iteration are
        # sequenced on device before the decode block that fetches their
        # tok0s, so their model FLOPs are attributed to that block's wall.
        self._perf = DispatchAttribution(model_cfg, engine_cfg,
                                         self.registry)
        self._attr_pending_flops = 0.0
        self._attr_prefill_cold = False  # a compiling shape in the wave
        self._attr_last_gb = 0.0  # last block's model bytes (span arg)
        # Request-cost ledger (obs/ledger.py): every dispatch wall —
        # already phase-split by the attribution above — apportions one
        # level further down, to the live rows, accumulating an honest
        # per-request device-time bill with a conservation invariant in
        # audit().  LMRS_COST_LEDGER=0 turns every note into a no-op
        # (pure host bookkeeping; outputs byte-identical either way).
        self._cost = CostLedger(self.registry)
        # Fair-share QoS (fleet/qos.py): admission picks by (class rank,
        # windowed device-seconds / weight, FIFO) and preemption
        # victimizes over-quota bulk work first.  The ledger's per-
        # dispatch apportionment feeds the policy's sliding window (the
        # observer fires outside the ledger lock).  LMRS_QOS=0 leaves
        # _qos None and every hook below is a single is-None branch —
        # byte-for-byte today's FIFO admission and youngest-victim rule.
        self._qos = maybe_qos(self.registry)
        if self._qos is not None:
            self._cost.observer = self._qos.note_usage
        # per-row prefill work issued since the last consumption —
        # (req, tokens, flops) mirrors of _attr_pending_flops, consumed
        # by whichever dispatch fetch charges the wave's wall
        self._cost_pending_prefill: list[tuple] = []
        # (wall_s, decode_cost_s, prefill_cost_s, prefill_rows) of the
        # last decode/spec dispatch, consumed by run()'s emitted loop
        # where the per-row token counts become known
        self._cost_step: tuple | None = None
        # SLO engine (obs/slo.py): burn-rate health states over the
        # stream's own TTFT / block-gap / outcome samples; /healthz and
        # the router's placement penalty read slo_report().
        self._slo = SLOEngine(self.registry, metrics_cb=lambda: self.metrics)
        # Step-anatomy profiler (obs/anatomy.py): every run() iteration is
        # split into named host segments via _an.seg(...), conservation-
        # audited (wall == segments + residual) in audit(), plus bucket
        # economics for the ragged-span pow2 family.  LMRS_ANATOMY=0
        # swaps in the shared null object — no metrics registered, every
        # call a no-op, outputs and wire byte-identical.
        self._an = maybe_anatomy(self.registry,
                                 metrics_cb=lambda: self.metrics)
        # LMRS_PROFILE_ON_SLOW_STEP: a decode block slower than the
        # threshold (warm shapes only) triggers ONE jax.profiler capture
        # per process into LMRS_PROFILE_DIR — the "why was that step
        # slow" hook that needs no redeploy
        self._slow_step_fired = False
        # Hang survival (engine/watchdog.py): the dispatch loop stamps a
        # monotonic heartbeat each iteration; JaxEngine's WatchdogRunner
        # watches it and declares a wedge when no progress lands within
        # the threshold.  LMRS_WATCHDOG=0 removes the watchdog entirely —
        # run() then executes inline on the caller thread, byte-for-byte
        # today's dispatch path (the acceptance A/B).
        self.watchdog = None
        if env_bool("LMRS_WATCHDOG", True):
            from lmrs_tpu.engine.watchdog import DispatchWatchdog

            self.watchdog = DispatchWatchdog()
        self._c_watchdog_fires = c("lmrs_watchdog_fires_total",
                                   "dispatch wedges declared by the "
                                   "watchdog (run abandoned, engine "
                                   "degraded fail-fast)")
        self._c_wedged = c("lmrs_wedged_requests_total",
                           "requests terminated finish_reason=\"wedged\" "
                           "by the watchdog sweep")

    @property
    def metrics(self) -> dict:
        """Raw cumulative metric values under the pre-registry key names —
        the read-only snapshot tests and bench windowing delta (the former
        mutable dict's exact keys and value types)."""
        return {
            "prefill_tokens": int(self._c_prefill_tokens.value),
            "decode_tokens": int(self._c_decode_tokens.value),
            "decode_dispatches": int(self._c_decode_dispatches.value),
            "occupancy_sum": self._h_occupancy.sum,
            "peak_pages_in_use": int(self._g_peak_pages.value),
            "run_seconds": self._c_run_seconds.value,
            "spec_accepted_tokens": int(self._c_spec_accepted.value),
            "preemptions": int(self._c_preemptions.value),
            "stalls": int(self._c_stalls.value),
            "peak_active_slots": int(self._g_peak_slots.value),
            "cancelled": int(self._c_cancelled.value),
            "deadline_exceeded": int(self._c_deadline.value),
            "shed": int(self._c_shed.value),
            "blocked_seconds": self._c_blocked_seconds.value,
            "prefix_queries": int(self._c_prefix_queries.value),
            "prefix_hits": int(self._c_prefix_hits.value),
            "prefix_tokens_reused": int(self._c_prefix_tokens.value),
            "prefix_spilled_hits": int(self._c_spilled_hits.value),
            "prefix_tokens_prefetched": int(self._c_prefetch_tokens.value),
            "prefix_spill_pages": int(self._c_spill_pages.value),
            "prefix_prefetch_pages": int(self._c_prefetch_pages.value),
            "group_occupancy_sum": self._h_group_occupancy.sum,
            "group_dispatches": int(self._h_group_occupancy.count),
            "handoff_exports": int(self._c_handoff_exports.value),
            "handoff_imports": int(self._c_handoff_imports.value),
            "handoff_orphaned_pages": int(self._c_handoff_orphaned.value),
            "handoff_pinned_pages": int(self._g_pinned_pages.value),
            "mixed_dispatches": int(self._h_mixed_fill.count),
            "mixed_fill_sum": self._h_mixed_fill.sum,
            "prefill_tokens_piggybacked": int(self._c_piggybacked.value),
            "rpa_dispatches": int(self._h_rpa_span.count),
            "rpa_span_tokens": self._h_rpa_span.sum,
            "rpa_compile_shapes": int(self._c_rpa_shapes.value),
            "spec_tree_dispatches": int(self._c_spec_tree_disp.value),
            "spec_tree_nodes_sum": self._h_spec_nodes.sum,
            "spec_tree_rows": int(self._h_spec_nodes.count),
            "spec_accept_depth_sum": self._h_spec_depth.sum,
            "watchdog_fires": int(self._c_watchdog_fires.value),
            "wedged_requests": int(self._c_wedged.value),
        }

    def metrics_registry(self) -> MetricsRegistry:
        """Engine-protocol optional hook: the registry behind
        ``metrics_report()``, for Prometheus exposition (serving/server.py
        content-negotiates ``GET /metrics`` over it)."""
        return self.registry

    def perf_attribution_report(self) -> dict:
        """Live per-phase roofline attribution (obs/perf.py) — the
        ``perf_attribution`` block of metrics_report() and bench detail."""
        return self._perf.report()

    def _tid(self, req: GenerationRequest) -> int:
        """The request's span-track id: keyed on its distributed trace id
        when it carries one (one causal chain fleet-wide, stable across
        pods and run epochs) — else the legacy per-run request-id track.
        Call only under an ``if self._tr:`` guard."""
        if req.trace_id:
            return self._tr.track_for(req.trace_id)
        return req_tid(req.request_id)

    def _consume_prefill_attr(self) -> tuple[float, bool]:
        """Take (and reset) the pending prefill-FLOPs attribution: the
        model FLOPs of every prefill dispatch issued since the last
        consumption, plus whether any of them was a compiling (cold)
        shape — cold waves never produce MFU samples."""
        flops, cold = self._attr_pending_flops, self._attr_prefill_cold
        self._attr_pending_flops = 0.0
        self._attr_prefill_cold = False
        return flops, cold

    def _consume_prefill_cost(self) -> list[tuple]:
        """Take (and reset) the per-row prefill cost rows mirroring
        _consume_prefill_attr — the ledger's row-level view of the same
        pending work."""
        rows, self._cost_pending_prefill = self._cost_pending_prefill, []
        return rows

    def _roofline_phase_costs(self, nbytes: float,
                              flops: float) -> tuple[float, float]:
        """(decode_cost_s, prefill_cost_s): each phase's own roofline
        time — the exact-split denominators the ledger apportions dispatch
        walls by (obs/perf.note_mixed_step's rule, one level down)."""
        spec = self._perf._spec()
        return (max(nbytes, 0.0) / spec.peak_hbm_bw,
                max(flops, 0.0) / spec.peak_flops)

    # ------------------------------------------------ cost / SLO surfaces

    def usage_report(self) -> dict:
        """Per-tenant cost rollups (the ``GET /v1/usage`` document)."""
        return self._cost.usage_report()

    def slo_report(self) -> dict:
        """Burn-rate SLO evaluation (the ``/healthz`` ``slo`` block)."""
        return self._slo.report()

    def qos_report(self) -> dict:
        """Fair-share window state (the ``GET /v1/usage`` ``qos`` block)."""
        if self._qos is None:
            return {"object": "qos", "enabled": False}
        return self._qos.report()

    def anatomy_report(self, before: dict | None = None) -> dict:
        """Step-anatomy decomposition + ragged bucket economics (the
        ``GET /v1/anatomy`` document and the ``anatomy`` block of
        metrics_report()/bench detail).  ``before`` is an
        ``anatomy_snapshot()`` window anchor; the RTT rides along so the
        report can flag a stale sample instead of letting it skew the
        dispatch/fetch split (obs/anatomy.py)."""
        return self._an.report(before, rtt=self._perf.rtt_sample())

    def anatomy_snapshot(self) -> dict:
        """Window anchor for ``anatomy_report(before=...)`` (bench /
        serving_latency delta their measurement window off this)."""
        return self._an.snapshot()

    def cost_finish(self, req: GenerationRequest, res: GenerationResult
                    ) -> None:
        """Finalize a request's ledger entry for a result synthesized
        OUTSIDE the scheduler loop (the watchdog's wedge sweep): attaches
        the usage bill and feeds the SLO outcome stream, same as
        _record_result does for loop-delivered results."""
        res.usage = self._cost.finish(req, res)
        self._slo.note_result(res.finish_reason, res.completion_tokens,
                              res.error)

    def _maybe_profile_slow_step(self, wall_s: float, warm: bool) -> None:
        """LMRS_PROFILE_ON_SLOW_STEP trigger: the first WARM decode block
        slower than the threshold starts one bounded jax.profiler capture
        (LMRS_PROFILE_DIR, default <tmp>/lmrs_profile) — once per
        process, so a persistently slow engine cannot profile forever."""
        if self._slow_step_fired:
            return
        from lmrs_tpu.obs.perf import (default_profile_dir,
                                       slow_step_threshold_s,
                                       start_profile_capture)

        thresh = slow_step_threshold_s()
        if not thresh or not warm or wall_s <= thresh:
            return
        self._slow_step_fired = True
        dur = env_float("LMRS_PROFILE_CAPTURE_S", 3.0, lo=0.1, hi=60.0)
        ok, msg = start_profile_capture(default_profile_dir(), dur)
        logger.warning("slow decode block (%.3fs > %.3fs threshold): "
                       "profiler capture %s (%s)", wall_s, thresh,
                       "started" if ok else "NOT started", msg)

    def _wd_grace_cold(self) -> None:
        """The next dispatch compiles a new shape: open the watchdog's
        one-shot compile grace window so a legitimate multi-second (or
        multi-minute) XLA compile can never read as a wedge.  Call sites
        are exactly the existing cold-shape checks (``_ran_ok``)."""
        if self.watchdog is not None:
            self.watchdog.grace_cold()

    def _note_ran_ok(self, key) -> None:
        """Mark a dispatch shape proven AND close the cold-compile grace
        window it opened: the compile is done, so the wedge detector
        re-arms immediately — a stall in the same iteration (or the next
        loop-top heartbeat) must still be caught."""
        self._ran_ok.add(key)
        if self.watchdog is not None:
            self.watchdog.grace_end()

    def _invalidate_compiled(self) -> None:
        """ONE compile-cache invalidation for every first-run-lowering
        fallback site (formerly triplicated across the decode / spec /
        mixed handlers, each independently clearing the caches whose
        programs captured ``use_ragged`` at build time).  Flipping the
        kernel gate must drop ALL of them — decode + spec (one dict),
        mixed, and the ragged span programs — or a stale program would
        keep dispatching the kernel the fallback just proved unlowerable."""
        self._use_ragged = False
        self._decode_fns.clear()   # plain decode + ("specfn", w) entries
        self._mixed_fns.clear()    # mixed fns captured use_ragged too
        self._rpa_fns.clear()      # span programs rebuild on the XLA path

    def _timed_get(self, x):
        """``jax.device_get`` with the blocking wait charged to the
        ``blocked_seconds`` metric (device-busy attribution; see the
        metric's init comment)."""
        t0 = time.time()
        out = jax.device_get(x)
        # clamped: counters refuse to decrease, and a backwards clock step
        # (NTP correction mid-fetch) must cost a sample, not the whole run
        self._c_blocked_seconds.inc(max(0.0, time.time() - t0))
        return out

    def metrics_report(self) -> dict:
        """Derived engine metrics, cumulative over every run() on this
        scheduler (the same lifetime semantics as the executor's token
        counters, llm_executor.py:86-90): throughput (tokens/s over
        scheduler wall-clock), mean decode batch occupancy (fraction of
        slots live per dispatch), and peak KV page utilization over the
        usable pool (the HBM-pressure analog)."""
        m = self.metrics
        secs = max(m["run_seconds"], 1e-9)
        return {
            "prefill_tokens": m["prefill_tokens"],
            "decode_tokens": m["decode_tokens"],
            "prefill_tokens_per_sec": round(m["prefill_tokens"] / secs, 1),
            "decode_tokens_per_sec": round(m["decode_tokens"] / secs, 1),
            "mean_decode_occupancy": round(
                m["occupancy_sum"] / max(m["decode_dispatches"], 1), 3),
            "peak_kv_page_utilization": round(
                m["peak_pages_in_use"] / (self.cache.num_pages - 1), 3),
            "scheduler_seconds": round(m["run_seconds"], 3),
            "blocked_seconds": round(m["blocked_seconds"], 3),
            "host_seconds": round(
                max(m["run_seconds"] - m["blocked_seconds"], 0.0), 3),
            "preemptions": m["preemptions"],
            "stalls": m["stalls"],
            "cancelled": m["cancelled"],
            "deadline_exceeded": m["deadline_exceeded"],
            "shed": m["shed"],
            "peak_active_slots": m["peak_active_slots"],
            "ttft_ms": self._h_ttft.percentile_report(),
            "decode_block_gap_ms": self._h_block_gap.percentile_report(),
            # Gap-scope label (docs/PERF.md "two block-gap numbers"):
            # gaps are sampled between consecutive decode dispatches
            # WITHIN each run().  On a steady serving stream that is the
            # per-block cadence a client sees; on a batch/bench workload
            # the same samples include whole admission/prefill waves
            # between decode dispatches (BENCH8B_r05's 7.65 s p50 is
            # wave-level queueing, NOT serving cadence — the capture's
            # 363 ms is).  Consumers must not compare across scopes.
            "decode_block_gap_scope": "within-run dispatch gaps "
                                      "(wave-level on batch workloads; "
                                      "steady-state only on serving "
                                      "captures)",
            "queue_wait_ms": self._h_queue_wait.percentile_report(),
            "mixed_batch": self._mixed_report(),
            "rpa": self._rpa_report(),
            "host_kv": self._host_kv_report(),
            "perf_attribution": self._perf.report(),
            "cost": self._cost.report(),
            "slo": self._slo.report(),
            # kill-switch shape contract: NO anatomy key at all under
            # LMRS_ANATOMY=0 — the pre-anatomy report is byte-identical
            **({"anatomy": self.anatomy_report()}
               if self._an.enabled else {}),
            **({"spec_accepted_tokens": m["spec_accepted_tokens"]}
               if self.spec_k else {}),
            **({"spec_tree": self._spec_tree_report()}
               if self.spec_k else {}),
            **({"prefix_cache": self._prefix_cache_report()}
               if self._prefix_cache is not None else {}),
        }

    def _mixed_report(self, before: dict | None = None) -> dict:
        """Mixed-batch block of metrics_report() / bench detail / the
        serving A/B harness: whether mixed dispatch is armed, how many
        fused steps ran, budget fill, and the prompt tokens that rode
        decode steps.  With ``before`` (a ``metrics`` snapshot) the work
        fields are WINDOWED to the delta since the snapshot — the one
        implementation of the windowed fill formula, so bench and the
        A/B harness can never drift apart."""
        m = self.metrics
        b = before or {}
        disp = m["mixed_dispatches"] - b.get("mixed_dispatches", 0)
        fill = m["mixed_fill_sum"] - b.get("mixed_fill_sum", 0.0)
        return {
            "enabled": self._mixed,
            "token_budget": self.mixed_token_budget,
            "dispatches": disp,
            "fill_ratio": round(fill / disp, 3) if disp else 0.0,
            "prefill_tokens_piggybacked": (
                m["prefill_tokens_piggybacked"]
                - b.get("prefill_tokens_piggybacked", 0)),
        }

    def _rpa_report(self, before: dict | None = None) -> dict:
        """Ragged-span block of metrics_report() / bench detail: whether
        RPA dispatch is armed, how many span dispatches ran, the real
        query tokens they carried, and the HEADLINE number — distinct
        compiled program shapes (the legacy per-phase matrix compiled
        decode + spec + mixed + chunk families; the span family is
        (pow2 tokens, pow2 window) only).  Same windowed-``before``
        convention as ``_mixed_report``; compile shapes stay cumulative —
        a zoo is a lifetime property, not a window one."""
        m = self.metrics
        b = before or {}
        return {
            "enabled": self._rpa,
            "dispatches": (m["rpa_dispatches"]
                           - b.get("rpa_dispatches", 0)),
            "span_tokens": int(m["rpa_span_tokens"]
                               - b.get("rpa_span_tokens", 0.0)),
            "compile_shapes": m["rpa_compile_shapes"],
        }

    def _spec_tree_report(self, before: dict | None = None) -> dict:
        """Tree-speculation block of metrics_report() / bench detail /
        the decode_split tree arm: whether the tree path is armed, how
        many tree-span dispatches ran, mean drafted nodes and accepted
        depth per row, and accepted tokens per dispatched row (the
        perf_sentry ``spec_tree.accept_per_step`` trajectory metric).
        Same windowed-``before`` convention as ``_mixed_report``."""
        m = self.metrics
        b = before or {}
        disp = m["spec_tree_dispatches"] - b.get("spec_tree_dispatches", 0)
        rows = m["spec_tree_rows"] - b.get("spec_tree_rows", 0)
        nodes = m["spec_tree_nodes_sum"] - b.get("spec_tree_nodes_sum", 0.0)
        depth = (m["spec_accept_depth_sum"]
                 - b.get("spec_accept_depth_sum", 0.0))
        acc = (m["spec_accepted_tokens"]
               - b.get("spec_accepted_tokens", 0))
        return {
            "enabled": self._spec_tree,
            "width": self._spec_width,
            "adaptive": self._spec_adaptive,
            "dispatches": disp,
            "mean_nodes": round(nodes / rows, 3) if rows else 0.0,
            "mean_accept_depth": round(depth / rows, 3) if rows else 0.0,
            "accept_per_step": round(acc / rows, 3) if rows else 0.0,
        }

    def _prefix_cache_report(self) -> dict:
        """Prefix-cache block of metrics_report(): hit rate over admissions,
        tokens reused from cached pages (== prefill tokens saved — exactly
        the prompt tokens the scheduler never dispatched), and the cache's
        current/ cumulative page footprint."""
        m = self.metrics
        s = self._prefix_cache.stats()
        return {
            "hit_rate": round(m["prefix_hits"] / m["prefix_queries"], 3)
            if m["prefix_queries"] else 0.0,
            "hits": m["prefix_hits"],
            "queries": m["prefix_queries"],
            "tokens_reused": m["prefix_tokens_reused"],
            "prefill_tokens_saved": m["prefix_tokens_reused"],
            "spilled_hits": m["prefix_spilled_hits"],
            "tokens_prefetched": m["prefix_tokens_prefetched"],
            "cached_pages": s["cached_pages"],
            "evicted_pages": s["evicted_pages"],
        }

    def _host_kv_report(self, before: dict | None = None) -> dict:
        """Host-RAM spill tier block of metrics_report() / bench detail:
        whether the tier is armed, its budget and occupancy, and the
        spill/prefetch work counters.  With ``before`` (a ``metrics`` snapshot) the work
        fields are WINDOWED to the delta since the snapshot — one
        implementation for bench and the report, same convention as
        ``_mixed_report``."""
        pc = self._prefix_cache
        armed = pc is not None and pc.pool is not None
        m = self.metrics
        b = before or {}
        out = {
            "enabled": armed,
            "budget_gb": round(self.cfg.host_kv_gb, 3) if armed else 0.0,
            "spilled_hits": (m["prefix_spilled_hits"]
                             - b.get("prefix_spilled_hits", 0)),
            "tokens_prefetched": (m["prefix_tokens_prefetched"]
                                  - b.get("prefix_tokens_prefetched", 0)),
            "spill_pages": (m["prefix_spill_pages"]
                            - b.get("prefix_spill_pages", 0)),
            "prefetch_pages": (m["prefix_prefetch_pages"]
                               - b.get("prefix_prefetch_pages", 0)),
        }
        if armed:
            out["spilled_pages_resident"] = pc.spilled_pages()
            out["pool_bytes"] = pc.pool.used_bytes
            out["pool_entries"] = len(pc.pool)
            out["dropped_pages_total"] = pc.pool.dropped_pages_total
            if pc.disk is not None:
                # disk-tier keys appear only when the tier is armed:
                # LMRS_KV_DISK=0 keeps this block byte-identical
                out["disk_pages_resident"] = pc.disk_pages()
                out.update(pc.disk.stats())
        return out

    def reset_latency_stats(self) -> None:
        """Drop accumulated TTFT / block-gap / queue-wait observations.
        Benchmarks call this after warmup so compile-time dispatch gaps
        (orders of magnitude above steady state) don't pollute the
        percentiles — or the Prometheus buckets."""
        self._h_ttft.reset()
        self._h_block_gap.reset()
        self._h_queue_wait.reset()
        # live-attribution distributions ride the same warmup isolation
        # (the totals counters stay cumulative, like every counter here)
        self._perf.h_mfu.reset()
        self._perf.h_hbm.reset()
        self._perf.h_gap.reset()

    def _pick_kernel(self) -> bool:
        from lmrs_tpu.utils.platform import on_tpu

        if self.cfg.scheduler == "continuous":
            # ragged kernel wants MXU-friendly head_dim, a TPU backend (or
            # forced interpret mode), and a mesh whose only sharded serving
            # axis is tp — the kernel then runs per kv-head shard inside
            # shard_map (ops/paged_attention.paged_decode_fused_sharded);
            # XLA cannot auto-partition a pallas_call, but pages are already
            # kv-head-sharded so each shard's walk is local.  The fused
            # write RMWs an 8-row-aligned DMA window, which only stays
            # inside the page when the page size is a multiple of 8.
            return ((on_tpu() or self._interpret)
                    and self.model_cfg.hd % 128 == 0
                    and self.cfg.page_size % 8 == 0 and self._tp_only_mesh())
        return False

    def _single_device(self) -> bool:
        return self.mesh is None or self.mesh.devices.size == 1

    def _tp_only_mesh(self) -> bool:
        """True when there is no mesh, a 1-device mesh, or a mesh whose only
        >1 axes are ``tp``/``sp`` — the layouts the shard_map-wrapped
        kernels support.  Pages shard over tp and replicate over sp, so
        each sp replica runs the kernel on identical inputs (duplicated
        but parallel work — same wall time as sp=1, and decode keeps the
        fused kernel instead of regressing to the gather fallback just
        because sp was enabled for prefill CP)."""
        if self._single_device():
            return True
        return self.mesh.devices.size == (self.mesh.shape.get("tp", 1)
                                          * self.mesh.shape.get("sp", 1))

    def _kernel_mesh(self):
        """Mesh to hand the Pallas paths: None on a single device (plain
        pallas_call), the tp mesh otherwise (shard_map wrapping)."""
        return None if self._single_device() else self.mesh

    # ----------------------------------------------------------- public API

    def cancel(self, request_id: int) -> None:
        """Abort ``request_id`` (of the CURRENT run) at the next block
        boundary: a live slot is finished early with
        ``finish_reason="cancelled"`` and its pages freed; a queued entry
        never prefills.  Callable from any thread (the HTTP server cancels
        from a handler thread on client disconnect); unknown or already-
        finished ids are a no-op.  Tokens generated before the sweep are
        kept in the result — they are real output a streaming client may
        already hold."""
        self._cancelled.add(request_id)

    def run(self, requests: list[GenerationRequest],
            on_result=None, on_tokens=None) -> list[GenerationResult]:
        """Run the stream to completion and return results in request order.

        ``on_result(result, submit)``, when given, is invoked INSIDE the
        scheduling loop as each request completes; the callback may call
        ``submit(more_requests)`` to feed new work into the same stream —
        this is how the reduce tree rides the map stage's batch slots
        instead of waiting behind a full-queue barrier (map→reduce
        overlap).  Single-threaded: callbacks run between dispatches, so
        they need no locking but must be quick.  request_ids must be
        unique across everything submitted to one run().

        ``on_tokens(request_id, text_delta)``, when given, fires after each
        decode-block dispatch with the newly generated text for every slot
        that advanced (SSE streaming on the serving front-end).  Deltas are
        cut from the stop-trimmed, budget-capped text, so their
        concatenation equals the final result's ``text`` exactly — a
        streaming client never sees tokens past a stop sequence.  A
        preempted slot resumes deltas where it left off (progress is
        tracked per request id, not per slot).
        """
        t_run = time.time()
        # taken BEFORE the first allocator touch: an off-thread
        # release_handoff freeing inline holds this lock, so it either
        # completes before we flip the flag or sees it set and defers
        with self._pinned_lock:
            self._run_live = True
        # per-run tracer capture: the CLI/bench enable tracing before the
        # engine runs; a None tracer keeps every site a single branch
        tr = self._tr = get_tracer()
        # NOTE: the cancel set is deliberately NOT cleared here.  A client
        # disconnect can race the run boundary (cancel lands after
        # generate_batch is invoked but before run() begins executing); a
        # start-of-run clear would erase that legitimate cancel and the
        # abandoned request would decode to max_tokens after all.  Cross-run
        # id collisions are prevented by callers instead: the HTTP batcher
        # assigns globally-unique wave rids, and the end-of-run clear (the
        # finally below) drops ids that were never matched.
        self._on_tokens = on_tokens
        self._streamed: dict[int, str] = {}  # rid -> text already emitted
        # slot rows don't survive runs: stale-history marks from a prior
        # run's mixed window mean nothing for this run's occupants
        self._spec_stale.clear()
        # queue entries: (req, prefill_ids, max_new, n_prompt,
        # prior_generated, t_start) — the last three are preemption-
        # continuation state (len(ids), [], None for fresh requests)
        queue: deque[tuple] = deque()
        all_requests = list(requests)
        # rid -> enqueue time, consumed at the request's FIRST generated
        # token (TTFT sample).  Run-local: ids cancelled while queued just
        # leave their entry to be dropped with the dict.
        t_enq: dict[int, float] = {}
        last_block_t: float | None = None  # prev decode-dispatch timestamp

        # deadline-free runs skip the per-iteration expiry sweep entirely
        self._any_deadline = any(r.deadline_s is not None for r in requests)

        def submit(new_requests: list[GenerationRequest]) -> None:
            for req in new_requests:
                ids, max_new = self._encode(req)
                queue.append((req, ids, max_new, len(ids), [], None))
                all_requests.append(req)
                t_enq[req.request_id] = time.time()
                if req.deadline_s is not None:
                    self._any_deadline = True
                if tr:
                    tr.instant("enqueue", ts=t_enq[req.request_id],
                               tid=self._tid(req),
                               args={"prompt_tokens": len(ids)})

        fresh: deque[int] = deque()  # completed rids awaiting delivery
        for req in requests:
            ids, max_new = self._encode(req)
            queue.append((req, ids, max_new, len(ids), [], None))
            t_enq[req.request_id] = time.time()
            if tr:
                tr.instant("enqueue", ts=t_enq[req.request_id],
                           tid=self._tid(req),
                           args={"prompt_tokens": len(ids)})

        slots: list[_SlotState | None] = [None] * self.B
        last_tok = np.zeros((self.B,), np.int32)
        kv_lens = np.zeros((self.B,), np.int32)
        active = np.zeros((self.B,), bool)
        temps = np.zeros((self.B,), np.float32)
        top_k = np.zeros((self.B,), np.int32)
        top_p = np.ones((self.B,), np.float32)
        results: dict[int, GenerationResult] = {}

        usable_pages = self.cache.num_pages - 1  # minus reserved null page

        def admit():
            for b in range(self.B):
                if slots[b] is not None:
                    continue
                # Fair-share admission (fleet/qos.py): promote the policy's
                # pick from the queue's head window to the front — best
                # (class rank, normalized windowed usage, FIFO) entry.
                # The remaining entries keep their relative order (this is
                # a targeted promotion, not a rotation — skipped entries
                # must not migrate to the back and starve).  Head window
                # bounded so a deep backlog costs O(window) per slot, not
                # O(queue).  _qos is None under LMRS_QOS=0: FIFO exactly.
                if self._qos is not None and len(queue) > 1:
                    win = min(len(queue), 64)
                    k = self._qos.pick_index(
                        [queue[i][0] for i in range(win)])
                    if k:
                        ent = queue[k]
                        del queue[k]
                        queue.appendleft(ent)
                        if tr:
                            # fleet-drift contract (trace.py): a QoS
                            # promotion is an auditable scheduling decision
                            tr.instant("qos_reorder",
                                       args={"picked": k, "window": win,
                                             "tenant": ent[0].tenant
                                             or "default"})
                # Deadline admission control (load shedding): drop head
                # entries whose remaining budget cannot cover the TTFT
                # estimate — a fast explicit rejection BEFORE prefill beats
                # letting a saturated pod convert overload into queue wait
                # that expires in a slot anyway.
                while queue and self._any_deadline:
                    rem = remaining_budget(queue[0][0])
                    if rem is None or rem >= self._ttft_estimate(
                            len(queue[0][1])):
                        break
                    self._expire_queue_entry(queue, 0, results, fresh)
                if not queue:
                    break
                req, ids, max_new, n_prompt, prior, t0 = queue[0]
                if req.handoff_state is not None:
                    # disaggregated decode role: the head entry's KV pages
                    # arrive by import, not prefill (the slot enters decode
                    # phase directly).  False = page back-pressure: stop
                    # admitting and wait, same as the prefill path below.
                    if not self._admit_import(b, queue, slots, results,
                                              fresh, kv_lens, last_tok,
                                              active, temps, top_k, top_p):
                        break
                    continue
                # Prefix-cache probe: clone the longest cached page prefix
                # (ref-counted, read-only) and start prefill at the match
                # boundary.  match_hier() always leaves >= 1 prompt token
                # to prefill (the sampled-first-token chunk), so a "full"
                # hit is a one-chunk tail prefill straight into decode.
                # ``spill_chain`` is the host-tier extension: spilled
                # segments that will PREFETCH into freshly allocated pages
                # instead of re-prefilling (no references held — dropping
                # the chain on back-pressure costs nothing).
                cached_pages: list[int] = []
                cached_tokens = 0
                spill_chain: list = []
                if self._prefix_cache is not None:
                    cached_pages, cached_tokens, spill_chain = \
                        self._prefix_cache.match_hier(ids)
                # Admission reserves PROMPT pages only; decode capacity is
                # grown per block (_ensure_decode_capacity), with youngest-
                # slot preemption under pressure.  No fail-fast branch here:
                # a slot never holds more than max_pages_per_slot pages
                # (sequences cap at max_len) and the pool floor guarantees
                # usable_pages >= max_pages_per_slot, so every request can
                # complete alone in the pool — oversized prompts were
                # truncated at submit and oversized decodes trim at max_len
                # (ADVICE r2: the former "can NEVER complete" branch was
                # unreachable under these invariants).  Cached pages only
                # tighten this: match covers at most len(ids)-1 tokens, so
                # need >= 1 fresh page always remains to allocate.
                need = min(self.cache.pages_needed(len(ids)),
                           self.cache.max_pages_per_slot) - len(cached_pages)
                if need > self.cache.allocator.free_count:
                    if self._prefix_cache is not None:
                        # LRU-evict refcount-zero cache before declaring
                        # back-pressure: retained pages must never starve
                        # admission (the matched pages themselves are
                        # pinned by the extra match reference)
                        self._prefix_cache.evict(
                            need - self.cache.allocator.free_count)
                    if need > self.cache.allocator.free_count:
                        if cached_pages:  # release the match references
                            self.cache.allocator.free(cached_pages)
                        break  # back-pressure: wait for pages to free up
                queue.popleft()
                try:
                    # NB: named fresh_pages, not fresh — admit() closes
                    # over run()'s ``fresh`` results deque
                    fresh_pages = self.cache.alloc_pages(need)
                except OutOfPages:
                    # pressure raced (or was injected) past the free-count
                    # check above: release the match references, requeue at
                    # the head, and wait — back-pressure, never failure
                    if cached_pages:
                        self.cache.allocator.free(cached_pages)
                    queue.appendleft((req, ids, max_new, n_prompt, prior, t0))
                    break
                prefetched_tokens = 0
                if spill_chain:
                    # spilled hit: restore each segment into its share of
                    # the fresh pages (async scatter, overlapped with the
                    # dispatch cadence); a failed/dropped segment truncates
                    # the match there and its pages become prefill tail —
                    # admission never wedges on the host tier
                    (cached_pages, fresh_pages, cached_tokens,
                     prefetched_tokens) = self._prefetch_spilled(
                        spill_chain, cached_pages, fresh_pages,
                        cached_tokens)
                seq = SequencePages(pages=cached_pages + fresh_pages)
                # counted at ADMISSION, not per probe: a back-pressured
                # request re-probes every scheduler tick until pages free
                # up, and retry ticks must not dilute the hit rate
                if self._prefix_cache is not None:
                    self._c_prefix_queries.inc()
                    if cached_tokens:
                        self._c_prefix_hits.inc()
                        self._c_prefix_tokens.inc(cached_tokens)
                        self._cost.note_saved(
                            req,
                            prefix_tokens=cached_tokens - prefetched_tokens,
                            prefetched_tokens=prefetched_tokens,
                            prefetched_bytes=(
                                self.cache.pages_needed(prefetched_tokens)
                                * self.cache.page_payload_bytes()
                                if prefetched_tokens else 0.0))
                # a continuation keeps its ORIGINAL t_start: device_seconds
                # then spans the whole request, and the slot stays "old" for
                # youngest-victim selection (a refreshed t_start would make
                # the same request the perpetual preemption victim)
                now = time.time()
                if req.deadline_s is not None:
                    self._h_deadline_remaining.observe(req.deadline_s - now)
                st = _SlotState(req=req, prompt_ids=ids, max_new=max_new,
                                seq=seq,
                                t_start=t0 if t0 is not None else now,
                                n_prompt=n_prompt, prior=list(prior))
                st.t_admit = now
                if self._spec_tree:
                    # tree speculation starts at full depth (the adaptive
                    # ramp takes over per accepted step); a cross-refresh
                    # draft hint tokenizes ONCE here, clipped so hint +
                    # prompt + budget still fit the history buffer
                    st.spec_depth = self.spec_k
                    if req.draft_hint:
                        room = (self.max_len - len(ids) - max_new - 1
                                - self._spec_width * self.spec_k)
                        if room > 0:
                            st.spec_hint = self.tokenizer.encode(
                                req.draft_hint)[:room]
                rid = req.request_id
                # queue wait = enqueue -> FIRST admission.  Continuation
                # detection is ``t0`` (the carried original t_start), NOT
                # ``prior``: a slot preempted before its deferred first
                # token re-queues with prior=[] but t0 set, and must not
                # re-sample an enqueue->re-admission wait
                t_q = t_enq.get(rid)
                if t_q is not None and t0 is None:
                    self._h_queue_wait.observe(now - t_q)
                    self._cost.note_queue_wait(req, now - t_q)
                    if tr:
                        tr.complete("queue_wait", t_q, now,
                                    tid=self._tid(req))
                if tr:
                    tr.instant("admit", ts=now, tid=self._tid(req),
                               args={"slot": b,
                                     "continuation": t0 is not None})
                    if cached_tokens:
                        tr.instant("prefix_match", ts=now,
                                   tid=self._tid(req),
                                   args={"tokens_reused": cached_tokens,
                                         "tokens_prefetched":
                                             prefetched_tokens})
                # a cache hit enters the existing chunked-prefill machinery
                # at the match boundary: the first chunk dispatches as a
                # windowed continuation attending the cloned pages
                st.prefill_pos = cached_tokens
                slots[b] = st  # phase="prefill"; device work happens in the loop
                # a decode dispatch can run while this slot is still
                # mid-prefill (chunked prefill): its row must carry length
                # 0, not the previous occupant's stale length — the ragged
                # kernel derives its page-walk bound from kv_lens and a
                # stale value over-runs the [B, w] table in SMEM
                kv_lens[b] = 0
                last_tok[b] = 0
                temps[b] = req.temperature
                top_k[b] = req.top_k
                top_p[b] = min(max(req.top_p, 0.0), 1.0)
                # usable pages only: the reserved null page is neither
                # allocatable nor counted, so utilization can reach 0 and 1
                in_use = usable_pages - self.cache.allocator.free_count
                self._g_peak_pages.track_max(in_use)
                self._g_peak_slots.track_max(
                    sum(s is not None for s in slots))

        wd = self.watchdog
        if wd is not None:
            wd.run_started()
        try:
            while True:
                # step anatomy (obs/anatomy.py): one iteration record per
                # pass; every ``continue``/bottom closes it with iter_end
                # (classed), the exit break discards it, and the finally
                # aborts whatever a fault left open
                self._an.iter_begin()
                with self._an.seg("admit"):
                    # injection site: a fired plan fails this scheduler
                    # iteration the way a bad dispatch would — exercising
                    # the pool-recovery path in the except below
                    faults.fire("scheduler.step")
                    # injection site + heartbeat (hang survival, engine/
                    # watchdog.py): a "stall" plan here wedges the loop the
                    # way a hung chip would — no beat lands, the watchdog
                    # declares the wedge.  With LMRS_WATCHDOG=0 the same
                    # stall simply hangs the run (today's behavior).
                    faults.fire("scheduler.heartbeat")
                    if wd is not None:
                        wd.beat()
                    # sweep cancellations first (block boundary): their
                    # results are then delivered with this iteration's
                    # fresh batch
                    if self._cancelled:
                        self._sweep_cancelled(queue, slots, results, active,
                                              fresh, kv_lens, last_tok)
                    # acked/orphaned handoff releases parked by handler/
                    # sweeper threads free here, on the scheduler thread
                    # (see release_handoff) — their pages rejoin the pool
                    # within one block of the ack
                    if self._release_deferred:
                        self._drain_released()
                    # deadline expiry rides the same block-boundary cadence
                    # as the cancel sweep: an in-flight request expires
                    # within one decode block of its deadline
                    if self._any_deadline:
                        self._sweep_deadlines(queue, slots, results, active,
                                              fresh, kv_lens, last_tok)
                # deliver fresh results first: the callback may submit new work,
                # which the loop-exit check below must see (a reduce batch
                # submitted by the LAST map result must still run)
                with self._an.seg("io"):
                    if on_result is not None:
                        while fresh:
                            on_result(results[fresh.popleft()], submit)
                if not (queue or any(s is not None for s in slots)):
                    self._an.iter_discard()
                    break
                with self._an.seg("admit"):
                    admit()
                # SARATHI mixed step: when a prompt is mid-prefill WHILE
                # other slots decode, fuse one prompt slice into the
                # decode step as a single multi-token dispatch — decode
                # cadence continues through the admission instead of
                # draining behind a packed prefill wave.  Falls through to
                # the alternating path when there is nothing to mix (pure
                # prefill / pure decode iterations are unchanged, so
                # LMRS_MIXED=0 restores today's dispatch byte-for-byte).
                if self._mixed:
                    # anatomy: the mixed handler re-segments its own
                    # draft/dispatch/fetch/finish internally; the "plan"
                    # wrapper catches the remaining operand plumbing
                    with self._an.seg("plan"):
                        did, last_block_t = self._mixed_iteration(
                            slots, queue, results, fresh, kv_lens, last_tok,
                            active, temps, top_k, top_p, t_enq, last_block_t)
                    if did:
                        self._an.iter_end("spec" if self.spec_k else "mixed")
                        continue
                # advance every prefilling slot by ONE prompt chunk, then give
                # decode a turn — long prompts never monopolize the device.
                # Same-shape chunks batch into one dispatch (a [N,S] prefill
                # feeds the MXU far better than N serialized [1,S] programs).
                # First tokens are NOT fetched here: every host bookkeeping step
                # except generated.append(tok0) is tok0-independent, so tok0
                # stays on device, is scattered into the decode dispatch's
                # last_tok input, and rides back in the decode block's single
                # device_get — one fewer ~full-RTT host sync per admission wave.
                t_pf = time.time()  # prefill-wave dispatch-issue anchor
                with self._an.seg("plan"):
                    # operand build inside; the jitted calls re-segment
                    # themselves as "dispatch" (pause semantics)
                    pending = self._advance_prefills(slots)
                deferred: list[tuple[int, int, int]] = []  # (slot, pend idx, row)
                with self._an.seg("finish"):
                    for p, (tok0_dev, rows) in enumerate(pending):
                        for b, row in rows:
                            st = slots[b]
                            st.phase = "decode"
                            st.t_decode_start = time.time()
                            if tr:
                                tr.complete(
                                    "prefill", st.t_admit, st.t_decode_start,
                                    tid=self._tid(st.req),
                                    args={"prompt_tokens":
                                          len(st.prompt_ids)})
                            st.kv_len = len(st.prompt_ids)
                            kv_lens[b] = st.kv_len
                            active[b] = True
                            # donate the prompt's full-page prefix to the
                            # prefix cache NOW (not at finish): the dispatch
                            # writing these pages is already issued, and
                            # later admissions in the same run can hit
                            # immediately
                            self._cache_insert(st)
                            deferred.append((b, p, row))
                if pending and (self.spec_k or not self.defer_tok0
                                or any(slots[b] is not None
                                       and slots[b].req.handoff_export
                                       for b, _, _ in deferred)):
                    # speculation seeds a host-built history row per admission —
                    # it needs tok0 values now, so it keeps the synchronous
                    # fetch (also selectable via LMRS_DEFER_TOK0=0 for A/B runs).
                    # Handoff-export slots force it too: their budget is 1, so
                    # the sync fetch finishes (pins) them here and the prefill
                    # pod never burns a decode-block dispatch on tokens the
                    # handoff would trim anyway.
                    with self._an.seg("fetch"):
                        fetched = self._timed_get([t for t, _ in pending])
                    # clean prefill MFU sample: the wall from dispatch
                    # issue to this fetch covers exactly the prefill
                    # compute (+1 RTT) — the prefill pod's whole life
                    t_fetch = time.time()
                    with self._an.seg("finish"):
                        flops, cold = self._consume_prefill_attr()
                        self._perf.note_prefill_sync(flops, t_pf, t_fetch,
                                                     warm=not cold)
                        self._cost.note_step(
                            max(0.0, t_fetch - t_pf),
                            prefill_rows=self._consume_prefill_cost(),
                            prefill_cost_s=1.0)
                        for (b, p, row) in deferred:
                            st = slots[b]
                            tok0 = int(fetched[p][row])
                            st.generated.append(tok0)
                            self._note_first_token(st, t_enq)
                            last_tok[b] = tok0
                            with self._an.seg("draft"):
                                self.seed_history(b, st)
                            self._maybe_finish(b, slots, results, active,
                                               fresh, kv_lens, last_tok)
                    deferred = []
                    pending = []
                if not any(active):
                    self._an.iter_end("prefill")
                    continue
                # grow every decode slot's pages to cover the coming block;
                # under pool pressure the youngest decode slot is preempted
                # back to the queue (its pending tok0, if any, is simply
                # re-sampled when it re-prefills)
                with self._an.seg("admit"):
                    stalled = self._ensure_decode_capacity(
                        slots, queue, kv_lens, last_tok, active)
                if not any(active):
                    if deferred:
                        # no dispatch will carry these first tokens: fetch them
                        # now — a stalled slot's tok0 is real output and must
                        # not be dropped (preempted slots resample theirs)
                        with self._an.seg("fetch"):
                            fetched = self._timed_get([t for t, _ in pending])
                        t_fetch = time.time()
                        with self._an.seg("finish"):
                            flops, cold = self._consume_prefill_attr()
                            self._perf.note_prefill_sync(flops, t_pf, t_fetch,
                                                         warm=not cold)
                            self._cost.note_step(
                                max(0.0, t_fetch - t_pf),
                                prefill_rows=self._consume_prefill_cost(),
                                prefill_cost_s=1.0)
                            for (b, p, row) in deferred:
                                if slots[b] is None:
                                    continue
                                tok0 = int(fetched[p][row])
                                slots[b].generated.append(tok0)
                                self._note_first_token(slots[b], t_enq)
                                last_tok[b] = tok0
                                self._maybe_finish(b, slots, results, active,
                                                   fresh, kv_lens, last_tok)
                    for b in stalled:  # re-arm before looping back
                        if slots[b] is not None:
                            active[b] = True
                    self._an.iter_end("prefill")
                    continue
                if self.spec_k and self._spec_tree:
                    # tree speculation (ISSUE 19): pure-decode spec steps
                    # route through the ragged-span family too — the
                    # legacy spec block must never see a row whose heal
                    # prefix or hint-offset history columns only the tree
                    # path understands.  The span handler owns its own
                    # occupancy/gap/dispatch metrics.  A False return
                    # means every row stalled under page pressure: loop
                    # (preemption guarantees progress, same as the legacy
                    # stall spin).
                    with self._an.seg("plan"):
                        did, last_block_t = self._rpa_mixed_iteration(
                            None, slots, queue, results, fresh, kv_lens,
                            last_tok, active, temps, top_k, top_p, t_enq,
                            last_block_t)
                    self._an.iter_end("spec")
                    continue
                n_live = int(np.sum(active))
                self._h_occupancy.observe(n_live / self.B)
                self._c_decode_dispatches.inc()
                now = time.time()
                if last_block_t is not None:
                    self._h_block_gap.observe(now - last_block_t)
                    self._slo.observe_gap(now - last_block_t)
                last_block_t = now
                # anatomy: the block methods re-segment their own draft/
                # dispatch/fetch internally; the "plan" wrapper catches
                # the operand build + result scatter plumbing around them
                if self.spec_k:
                    with self._an.seg("plan"):
                        emitted = self._spec_decode_block(
                            slots, last_tok, kv_lens, active, temps, top_k,
                            top_p)
                else:
                    with self._an.seg("plan"):
                        toks, n_valid, tok0s = self._decode_block(
                            slots, last_tok, kv_lens, active, temps, top_k,
                            top_p, pending)
                        emitted = [toks[b, : int(n_valid[b])].tolist()
                                   for b in range(self.B)]
                with self._an.seg("finish"):
                    if self._cost.enabled and self._cost_step is not None:
                        # the dispatch wall stashed by _decode_block /
                        # _spec_decode_block meets its per-row token counts
                        # here — one ledger note per dispatch, issued BEFORE
                        # any of this iteration's finishes (the mixed path's
                        # ordering): a row finishing on this very block must
                        # have its final share billed while its entry is
                        # still open, not re-created as an orphan after
                        # finish() already rolled it up
                        wall, dcost, pcost, prows = self._cost_step
                        self._cost_step = None
                        self._cost.note_step(
                            wall,
                            decode_rows=[(slots[b].req, len(emitted[b]),
                                          len(slots[b].seq.pages))
                                         for b in range(self.B)
                                         if slots[b] is not None
                                         and active[b]],
                            prefill_rows=prows,
                            decode_cost_s=dcost, prefill_cost_s=pcost)
                    if not self.spec_k:
                        for (b, p, row) in deferred:
                            if slots[b] is None:
                                continue  # preempted: tok0 resampled later
                            tok0 = int(tok0s[p][row])
                            slots[b].generated.append(tok0)
                            self._note_first_token(slots[b], t_enq)
                            last_tok[b] = tok0
                            if not active[b]:
                                # STALLED this dispatch (no pages to grow):
                                # the slot emitted nothing, but its first
                                # token is real output — record it and
                                # check for an early finish; the emitted
                                # loop below skips inactive rows
                                self._maybe_finish(b, slots, results, active,
                                                   fresh, kv_lens, last_tok)
                    block_tokens = 0
                    for b in range(self.B):
                        st = slots[b]
                        if st is None or not active[b]:
                            continue
                        new = emitted[b]
                        st.generated.extend(new)
                        st.kv_len += len(new)
                        kv_lens[b] = st.kv_len
                        last_tok[b] = st.generated[-1] if st.generated else 0
                        self._c_decode_tokens.inc(len(new))
                        block_tokens += len(new)
                        if tr and new:
                            tr.instant("decode_block", ts=now,
                                       tid=self._tid(st.req),
                                       args={"tokens": len(new)})
                        self._maybe_finish(b, slots, results, active, fresh,
                                           kv_lens, last_tok)
                    if tr:
                        # scheduler-track span: dispatch issue through
                        # host-side result processing; start timestamps are
                        # the former LMRS_TRACE_DISPATCH list
                        # (Tracer.timestamps).  hbm_gb = the block's model
                        # byte cost (perf attribution; 0 for spec blocks,
                        # whose model differs)
                        tr.complete("decode_block", now, time.time(),
                                    args={"active": n_live,
                                          "tokens": block_tokens,
                                          "hbm_gb": self._attr_last_gb})
                    for b in stalled:  # stalled rows rejoin the next dispatch
                        if slots[b] is not None:
                            active[b] = True
                self._an.iter_end("spec" if self.spec_k else "plain")

        except Exception as run_exc:
            # Dispatch/step failure mid-run.  The exception re-raises —
            # every caller (MapExecutor, the HTTP batcher) already
            # translates engine exceptions into per-request error results —
            # but the ENGINE must survive for the next batch, so restore
            # the pool invariants first: live slots' pages free, the queue
            # drops (entries hold no pages), the device pools reallocate
            # (a failed DONATED dispatch leaves k/v consumed), and the
            # prefix cache — whose pages point into the discarded pool
            # content — drops its retained nodes.
            # Flight recorder FIRST (obs/flight.py): the postmortem must
            # capture the metrics/spans AS THE FAULT LEFT THEM, before
            # recovery rewrites the pool state.  No-op unless
            # LMRS_POSTMORTEM_DIR is armed; never raises.
            dump_postmortem(
                "dispatch_fault", metrics=self.metrics,
                extra={"error": f"{type(run_exc).__name__}: {run_exc}",
                       "live_slots": sum(s is not None for s in slots),
                       "queued": len(queue)})
            for b in range(self.B):
                if slots[b] is not None:
                    try:
                        self.cache.close_sequence(slots[b].seq)
                    except ValueError:
                        logger.exception(
                            "slot %d page release failed in recovery", b)
                    slots[b] = None
            queue.clear()
            # pinned-for-export KV content dies with the re-zeroed pool,
            # so the records are dropped (next ticket fetch 410s → the
            # router re-prefills) — but their PAGES must free through the
            # allocator, which survives reallocate() (it only re-zeros
            # the k/v buffers): clearing without close_sequence would
            # leak refcount-held pages forever.  Freed BEFORE the prefix-
            # cache clear: clear() skips nodes a live holder still shares,
            # so a pinned seq released after it would strand a cache node
            # pointing at discarded pool content.  Snapshot-and-clear is
            # atomic under the pin lock, so a racing off-thread release
            # (which pops/parks under the same lock) can never slip a
            # record past the sweep.
            with self._pinned_lock:
                dropped = ([r["seq"] for r in self._pinned.values()]
                           + [rec["seq"]
                              for _, rec, _ in self._release_deferred])
                self._pinned.clear()
                self._release_deferred.clear()
            for seq in dropped:
                try:
                    self.cache.close_sequence(seq)
                except ValueError:
                    logger.exception("pinned handoff page release failed "
                                     "in recovery")
            if dropped:
                logger.warning("pool recovery dropped %d pinned handoffs",
                               len(dropped))
                self._update_pinned_gauge()
            if self._prefix_cache is not None:
                self._prefix_cache.clear()
            self.cache.reallocate()
            if self._kv_quant:
                self.kscale = jnp.ones_like(self.kscale)
                self.vscale = jnp.ones_like(self.vscale)
            self._spec_buf = None  # donated with the pools; reseeds lazily
            raise
        finally:
            # runs on normal completion AND mid-run failure: a dead
            # callback, stale streamed text, or stale cancel ids must not
            # leak into a later run.  There is deliberately NO start-of-run
            # clear (see the NOTE at the top of run()): ids raced in
            # between runs persist until THIS clear fires at the end of
            # the next run, which is harmless because the HTTP batcher's
            # wave rids are globally unique — a stale id can never match a
            # future request.
            # clamped (same reason as _timed_get) — doubly important here:
            # this runs in a finally, where a raise would mask the real error
            self._c_run_seconds.inc(max(0.0, time.time() - t_run))
            # an iteration a fault left open contributes NOTHING to the
            # anatomy totals (iter_abort discards) — conservation survives
            # the chaos arms by construction; no-op after a clean close
            self._an.iter_abort()
            if wd is not None:
                wd.run_ended()
            self._on_tokens = None
            self._streamed = {}
            self._cancelled.clear()
            # un-consumed ledger rows must not leak across runs (a run
            # abandoned mid-wave would bill its rows to the next run's
            # first dispatch)
            self._cost_pending_prefill = []
            self._cost_step = None
            with self._pinned_lock:
                self._run_live = False
            # releases parked during the run free here, on the scheduler
            # thread, so nothing stays deferred between runs
            self._drain_released()
        return [results[r.request_id] for r in all_requests]

    def _sweep_cancelled(self, queue, slots, results, active, fresh,
                         kv_lens, last_tok) -> None:
        """Apply pending cancel() calls at a block boundary: free live
        slots' pages, drop queued entries, record results.  Snapshot the id
        set first — cancel() may add concurrently from another thread, and
        ids added mid-sweep are simply handled next iteration."""
        pending = set(self._cancelled)
        hit: set[int] = set()
        for i in range(len(queue) - 1, -1, -1):
            req = queue[i][0]
            if req.request_id in pending:
                _, _, max_new, n_prompt, prior, _ = queue[i]
                del queue[i]
                # route the preemption-carry tokens through the same
                # trimming as the slot path — a preempted slot can't have
                # hit EOS/stop/budget (it would have finished instead),
                # but the two cancel paths must not be able to diverge if
                # preemption semantics ever change
                gen, text, stop_hit, _ = self._trim_tokens(
                    list(prior), max_new, req.stop)
                self._record_result(results, GenerationResult(
                    request_id=req.request_id,
                    text=text,
                    prompt_tokens=n_prompt,
                    completion_tokens=len(gen),
                    finish_reason="cancelled",
                    stop_sequence=stop_hit,
                ), req=req)
                fresh.append(req.request_id)
                hit.add(req.request_id)
                self._c_cancelled.inc()
                if self._tr:  # cancelled while still queued: no spans open
                    self._tr.instant("cancel",
                                     tid=self._tid(req),
                                     args={"state": "queued"})
        for b in range(self.B):
            st = slots[b]
            if st is None or st.req.request_id not in pending:
                continue
            gen, text, stop_hit, _ = self._trimmed_output(st)
            self._finish_slot(b, slots, results, active, fresh, kv_lens,
                              last_tok, gen, text, stop_hit, "cancelled")
            hit.add(st.req.request_id)
            self._c_cancelled.inc()
            logger.debug("cancelled request %d (slot %d)",
                         st.req.request_id, b)
        self._cancelled -= hit

    def _record_result(self, results: dict, res: GenerationResult,
                       req: GenerationRequest | None = None) -> None:
        """The ONE write path into a run's result dict: every submitted id
        must terminate exactly once, so an overwrite is recorded for the
        auditor instead of silently replacing the first outcome.  Also
        the one place every terminal outcome meets the cost ledger (the
        usage bill attaches here) and the SLO outcome stream."""
        if res.request_id in results:
            self._audit_double_finish += 1
            logger.error("request %d terminated more than once "
                         "(%s over %s)", res.request_id, res.finish_reason,
                         results[res.request_id].finish_reason)
        if req is not None:
            res.usage = self._cost.finish(req, res)
        self._slo.note_result(res.finish_reason, res.completion_tokens,
                              res.error)
        results[res.request_id] = res

    # ------------------------------------------------------------ deadlines

    def _ttft_estimate(self, n_tokens: int) -> float:
        """Optimistic engine-side TTFT estimate for admission shedding: the
        fastest TTFT this engine has ever delivered (it reflects the real
        chips, compiled programs, and host link), else the perf-model
        prefill roofline bound (utils/perf_model).  Optimistic by design —
        a request shed on this number is PROVABLY unmeetable, while a mean
        would embed multi-second first-compile samples and shed healthy
        traffic."""
        if self._ttft_min != float("inf"):
            return self._ttft_min
        from lmrs_tpu.utils.perf_model import chip_spec, prefill_flops

        return prefill_flops(self.model_cfg, max(1, n_tokens),
                             head_tokens=1) / chip_spec().peak_flops

    def _expire_queue_entry(self, queue, i: int, results, fresh) -> None:
        """Terminate queue entry ``i`` that cannot (or can no longer) meet
        its deadline.  Fresh requests shed before any prefill
        (``finish_reason="shed"``, zero engine work); a preemption
        continuation already produced output, so it finishes as
        ``"deadline"`` keeping the trimmed prior tokens."""
        req, _ids, max_new, n_prompt, prior, t0 = queue[i]
        del queue[i]
        continuation = t0 is not None
        gen, text, stop_hit, _ = self._trim_tokens(list(prior), max_new,
                                                   req.stop)
        reason = "deadline" if continuation else "shed"
        self._record_result(results, GenerationResult(
            request_id=req.request_id,
            text=text if continuation else "",
            prompt_tokens=n_prompt,
            completion_tokens=len(gen) if continuation else 0,
            finish_reason=reason,
            stop_sequence=stop_hit if continuation else None,
        ), req=req)
        fresh.append(req.request_id)
        (self._c_deadline if continuation else self._c_shed).inc()
        if self._tr:
            self._tr.instant(reason, tid=self._tid(req),
                             args={"queued": True})

    def _sweep_deadlines(self, queue, slots, results, active, fresh,
                         kv_lens, last_tok) -> None:
        """Expire deadline-passed requests at a block boundary, riding the
        cancel machinery: live slots finish with ``finish_reason=
        "deadline"`` (pages freed, partial output kept — same teardown as a
        cancel, _finish_slot); queued entries terminate without prefilling.
        The WHOLE queue is scanned, not just the head: an entry stuck
        behind back-pressure must not have to reach the head to expire."""
        now = time.time()
        expired = 0
        for i in range(len(queue) - 1, -1, -1):
            req = queue[i][0]
            if req.deadline_s is not None and req.deadline_s <= now:
                self._expire_queue_entry(queue, i, results, fresh)
                expired += 1
        for b in range(self.B):
            st = slots[b]
            if (st is None or st.req.deadline_s is None
                    or st.req.deadline_s > now):
                continue
            gen, text, stop_hit, _ = self._trimmed_output(st)
            self._finish_slot(b, slots, results, active, fresh, kv_lens,
                              last_tok, gen, text, stop_hit, "deadline")
            self._c_deadline.inc()
            expired += 1
            logger.debug("request %d expired in flight (slot %d)",
                         st.req.request_id, b)
        # deadline-expiry STORM: one sweep reaping >= LMRS_DEADLINE_STORM
        # requests (default 3) means the pod is converting overload into
        # expired work — freeze the evidence (no-op when the flight
        # recorder is unarmed)
        if expired:
            storm = env_int("LMRS_DEADLINE_STORM", 3, lo=0)
            if storm > 0 and expired >= storm:
                dump_postmortem("deadline_storm", metrics=self.metrics,
                                extra={"expired_this_sweep": expired,
                                       "queued": len(queue)})

    # ---------------------------------------------------------------- audit

    def audit(self, live_seqs=None) -> list[str]:
        """Cross-layer invariant auditor (tests/test_chaos.py closes every
        soak scenario on it).  Checks, returning one string per violation
        (empty list = clean):

        * page conservation — free + live + prefix-cached pages cover the
          pool exactly (kv_cache.audit_allocator);
        * refcount balance — each page's allocator refcount equals its
          accounted holders (live sequences + radix-tree retention);
        * radix-tree structure — edge labels, child keys, parent links,
          no double retention (prefix_cache.audit);
        * termination discipline — no request of any run on this scheduler
          ever terminated more than once (_record_result bookkeeping);
        * pinned-for-export pages (disaggregated handoff) — sequences
          pinned awaiting a decode-pod ack hold exactly one reference per
          page, accounted like live sequences, so refcount balance and
          page conservation hold ACROSS the handoff transaction.

        Between runs (the default) there are no live sequences; pass
        ``live_seqs`` to audit mid-run state from a callback."""
        holders: dict[int, int] = {}
        with self._pinned_lock:
            pinned_seqs = ([r["seq"] for r in self._pinned.values()]
                           + [rec["seq"]
                              for _, rec, _ in self._release_deferred])
        for seq in list(live_seqs or ()) + pinned_seqs:
            for p in seq.pages:
                holders[p] = holders.get(p, 0) + 1
        violations: list[str] = []
        if self._prefix_cache is not None:
            violations += self._prefix_cache.audit()
            for p in self._prefix_cache.retained_pages():
                holders[p] = holders.get(p, 0) + 1
        violations += audit_allocator(self.cache.allocator,
                                      self.cache.num_pages, holders)
        if self._audit_double_finish:
            violations.append(f"{self._audit_double_finish} result "
                              "record(s) overwrote an existing result "
                              "(termination-exactly-once broken)")
        violations += self._cost.audit()
        # anatomy conservation: iteration wall == segment sums + residual
        # (obs/anatomy.py; totals only advance at iter_end, so this is
        # safe to call mid-run from a callback)
        violations += self._an.audit()
        if violations:
            # an invariant break is exactly the moment the last-N spans
            # and counters matter; no-op unless the recorder is armed
            dump_postmortem("audit_failure", metrics=self.metrics,
                            extra={"violations": violations})
        return violations

    def _trimmed_output(self, st: _SlotState):
        """(gen, text, stop_hit, hit_eos) for a slot's output so far —
        budget-trimmed, EOS-trimmed, stop-sequence-applied.  The ONE
        implementation of output trimming, shared by the normal finish
        path, the per-block streaming cut, and both cancel-sweep paths
        (live slots here; queued preempted entries via _trim_tokens)."""
        return self._trim_tokens(st.prior + st.generated, st.max_new,
                                 st.req.stop)

    def _note_first_token(self, st: _SlotState, t_enq: dict) -> None:
        """Record a TTFT sample at a request's FIRST host-visible token.
        The clock starts at SCHEDULER enqueue (run()/submit() encode), so
        the sample covers queue wait + prefill + first decode block within
        this engine stream; time spent upstream (the HTTP batcher's
        ~20 ms micro-batch window, or waiting behind a PREVIOUS wave's
        run()) is not included — this is an engine metric, not a wire
        metric.  ``prior`` non-empty means a preemption continuation whose
        real first token was already recorded in an earlier slot life."""
        t0 = t_enq.pop(st.req.request_id, None)
        if t0 is not None and not st.prior:
            now = time.time()
            self._ttft_min = min(self._ttft_min, now - t0)
            self._h_ttft.observe(now - t0)
            self._slo.observe_ttft(now - t0)
            if self._tr:
                self._tr.instant("first_token", ts=now,
                                 tid=self._tid(st.req))

    def _trim_tokens(self, gen: list[int], max_new: int, stop):
        gen = gen[:max_new]
        eos = self.tokenizer.eos_id
        hit_eos = eos in gen
        if hit_eos:
            gen = gen[: gen.index(eos)]
        text, stop_hit = apply_stop_sequences(
            self.tokenizer.decode(gen), stop)
        return gen, text, stop_hit, hit_eos

    def _finish_slot(self, b, slots, results, active, fresh, kv_lens,
                     last_tok, gen, text, stop_hit, finish_reason) -> None:
        """Record a slot's result and tear the slot down (pages freed,
        freed-row invariant applied).  Shared by _maybe_finish and the
        cancel sweep so finish semantics can never diverge."""
        st = slots[b]
        now = time.time()
        self._record_result(results, GenerationResult(
            request_id=st.req.request_id,
            text=text,
            prompt_tokens=st.n_prompt,
            completion_tokens=len(gen),
            finish_reason=finish_reason,
            stop_sequence=stop_hit,
            device_seconds=now - st.t_start,
        ), req=st.req)
        if self._tr:
            tid = self._tid(st.req)
            if st.t_decode_start:  # close the decode span of this slot life
                self._tr.complete("decode", st.t_decode_start, now, tid=tid,
                                  args={"completion_tokens": len(gen)})
            self._tr.instant(
                "cancel" if finish_reason == "cancelled" else "finish",
                ts=now, tid=tid,
                args={"reason": finish_reason,
                      "completion_tokens": len(gen)})
        if fresh is not None:
            fresh.append(st.req.request_id)
        self.cache.close_sequence(st.seq)
        slots[b] = None
        active[b] = False
        # freed rows must carry length 0 (same invariant as admission): a
        # stale length makes every later decode dispatch walk null pages
        # for this row, and OOB safety should not rest on the kernel clamp
        if kv_lens is not None:
            kv_lens[b] = 0
            last_tok[b] = 0

    # ------------------------------------------- disaggregated handoff

    def _orig_budget(self, req: GenerationRequest) -> int:
        """The request's REAL token budget (before the handoff_export
        clamp to 1 in _encode) — what the ticket forwards to the decode
        pod, and the is-there-anything-left-to-hand-off test."""
        return min(req.max_new_tokens, self.cfg.max_tokens,
                   self.max_len - 1)

    def _pin_handoff(self, b, slots, results, active, fresh, kv_lens,
                     last_tok, gen, text) -> None:
        """Finish a prefill-role slot as ``finish_reason="handoff"``: the
        payload (page data + resume state) is captured host-side NOW, on
        the scheduler thread — later exports then never touch the device,
        so a handler-thread fetch cannot race a dispatch that donates the
        pools.  The sequence's pages stay allocated (the pinned-for-export
        class) until release_handoff (decode ack) or the orphan sweep.
        Capture failure (injected ``handoff.export`` fault or a real
        gather error) degrades to a marked per-request error — the router
        re-prefills elsewhere; the pool stays clean."""
        st = slots[b]
        rid = st.req.request_id
        now = time.time()
        keep = self.cache.pages_needed(len(st.prompt_ids))
        try:
            t0 = time.time()
            payload = self.cache.export_sequence(st.seq, len(st.prompt_ids))
            if self._kv_quant:
                # per-slot scales, frozen at prefill: the decode pod
                # scatters them into ITS slot's scale rows at admission.
                # One batched fetch — on a tunneled chip each device_get
                # is a full host RTT the dispatch loop stalls on
                ks, vs = self._timed_get((self.kscale[:, b],
                                          self.vscale[:, b]))
                payload["kscale"] = np.asarray(ks)
                payload["vscale"] = np.asarray(vs)
            self._h_handoff_capture.observe(time.time() - t0)
        except Exception as e:  # noqa: BLE001 - degrade per request
            logger.warning("handoff export capture failed for request %d",
                           rid, exc_info=True)
            self._record_result(results, GenerationResult(
                request_id=rid, prompt_tokens=st.n_prompt,
                finish_reason="error",
                error=f"handoff export failed: {type(e).__name__}: {e}"),
                req=st.req)
            if fresh is not None:
                fresh.append(rid)
            self.cache.close_sequence(st.seq)
            slots[b] = None
            active[b] = False
            if kv_lens is not None:
                kv_lens[b] = 0
                last_tok[b] = 0
            return
        # resume state: exactly the tokens whose KV is exported, plus the
        # sampled-but-not-yet-written first token the decode pod feeds
        payload["tokens"] = [int(t) for t in st.prompt_ids]
        payload["generated"] = [int(t) for t in gen]
        payload["n_prompt"] = st.n_prompt
        # the trace rides the payload across the pod boundary: the decode
        # pod's import continues this request's span chain under the SAME
        # trace id even when the ticket is followed without the router
        if st.req.trace_id:
            payload["trace_id"] = st.req.trace_id
        # the tenant label crosses the pod boundary the same way: the
        # decode pod bills its share of the request to the same tenant
        if st.req.tenant:
            payload["tenant"] = st.req.tenant
        # ... and the QoS class: the decode leg competes in the class
        # the prefill leg was admitted under (fleet/qos.py)
        if st.req.qos_class:
            payload["qos_class"] = st.req.qos_class
        # budget-overshoot pages (decode-capacity growth past the prompt)
        # are NOT part of the handoff — release them before pinning
        if len(st.seq.pages) > keep:
            self.cache.allocator.free(st.seq.pages[keep:])
            st.seq.pages = st.seq.pages[:keep]
        st.seq.length = len(st.prompt_ids)
        rem = remaining_budget(st.req)
        ttl = self.cfg.handoff_ttl_s
        if rem is not None:
            # deadline budgets forward through the ticket: pages pinned
            # past the request's own deadline are already worthless
            ttl = max(0.5, min(ttl, rem))
        with self._pinned_lock:
            self._pinned[rid] = {"seq": st.seq, "payload": payload,
                                 "deadline_t": now + ttl, "t_pinned": now}
        self._update_pinned_gauge()
        self._c_handoff_exports.inc()
        self._record_result(results, GenerationResult(
            request_id=rid, text=text, prompt_tokens=st.n_prompt,
            completion_tokens=len(gen), finish_reason="handoff",
            device_seconds=now - st.t_start), req=st.req)
        if self._tr:
            tid = self._tid(st.req)
            if st.t_decode_start:
                self._tr.complete("decode", st.t_decode_start, now, tid=tid,
                                  args={"completion_tokens": len(gen)})
            self._tr.instant("handoff_export", ts=now, tid=tid,
                             args={"pages": len(st.seq.pages),
                                   "kv_len": len(st.prompt_ids)})
        if fresh is not None:
            fresh.append(rid)
        slots[b] = None
        active[b] = False
        if kv_lens is not None:
            kv_lens[b] = 0
            last_tok[b] = 0

    def _update_pinned_gauge(self) -> None:
        with self._pinned_lock:
            total = sum(len(r["seq"].pages) for r in self._pinned.values())
        self._g_pinned_pages.set(total)

    def export_handoff(self, request_id: int) -> dict:
        """Wire payload of a pinned export (serving-layer ticket fetch).
        Reads the host-side copy captured at pin time — no device access,
        so handler threads never race the dispatch loop — and is
        repeatable: a retried transfer re-reads the same payload.  Raises
        ``KeyError`` for unknown/released ids (the ticket 410 path)."""
        with self._pinned_lock:
            return self._pinned[request_id]["payload"]

    def release_handoff(self, request_id: int, orphaned: bool = False) -> int:
        """Release a pinned export's pages: the decode side acked (or,
        with ``orphaned=True``, the ticket deadline expired un-acked and
        the sweep is reclaiming).  Idempotent — unknown ids no-op, so a
        duplicate ack can never double-free.  Returns pages released.

        Callable from any thread.  While a run is live the actual free is
        DEFERRED to the scheduler thread's next block boundary (the
        allocator and prefix-cache refcounts are unsynchronized — only
        the dispatch loop may touch them mid-run); idle, the free happens
        inline under the pin lock, which a starting run must take before
        its first allocation."""
        with self._pinned_lock:
            rec = self._pinned.pop(request_id, None)
            if rec is None:
                return 0
            n = len(rec["seq"].pages)
            if self._run_live:
                self._release_deferred.append((request_id, rec, orphaned))
            else:
                self.cache.close_sequence(rec["seq"])
        self._update_pinned_gauge()
        if orphaned:
            self._c_handoff_orphaned.inc(n)
            logger.warning("handoff %d orphaned: %d pinned pages reclaimed",
                           request_id, n)
        if self._tr:
            trace = rec["payload"].get("trace_id")
            tid = (self._tr.track_for(trace) if trace
                   else req_tid(request_id))
            self._tr.instant("handoff_release", tid=tid,
                             args={"pages": n, "orphaned": orphaned})
        return n

    def _drain_released(self) -> None:
        """Free pages of releases parked while the run was live.  Runs on
        the scheduler thread only (block boundaries + end of run).  The
        frees happen UNDER the pin lock: the end-of-run drain executes
        after _run_live flips False, when an HTTP ack can already free
        inline — the shared lock serializes the two (the allocator has no
        synchronization of its own)."""
        with self._pinned_lock:
            items, self._release_deferred = self._release_deferred, []
            for rid, rec, _orphaned in items:
                try:
                    self.cache.close_sequence(rec["seq"])
                except ValueError:
                    logger.exception("deferred handoff release of request "
                                     "%d failed", rid)

    def sweep_handoffs(self, now: float | None = None) -> int:
        """Reclaim pinned exports whose ticket deadline expired (the
        orphan sweeper's engine half).  Returns pages released."""
        now = time.time() if now is None else now
        with self._pinned_lock:
            expired = [rid for rid, r in self._pinned.items()
                       if r["deadline_t"] <= now]
        return sum(self.release_handoff(rid, orphaned=True)
                   for rid in expired)

    def pinned_handoffs(self) -> dict[int, int]:
        """rid -> pinned page count snapshot (tests + metrics)."""
        with self._pinned_lock:
            return {rid: len(r["seq"].pages)
                    for rid, r in self._pinned.items()}

    # ------------------------------------------- cross-host KV migration

    def kv_export(self, preamble: str) -> dict | None:
        """Page-set export for cross-host KV migration (docs/SERVING.md
        "KV fabric"): the warm radix state of one published preamble
        hash — resident pages gathered device→host, spilled/disk
        segments read from their tiers — framed as one wire payload a
        sibling's ``kv_import`` installs.  This host's cache is left
        untouched (migration COPIES warmth; the drained host's state
        drops with the host).

        Control-plane only: callable while no run is live (a draining
        host has stopped serving; the router migrates between runs) —
        returns None mid-run, for unknown/cold preambles, and with the
        prefix cache off.  A torn disk entry truncates the set (fewer
        migrated tokens, never a failed export); the ``migrate.export``
        fault site fires before any capture work.

        Holds the pin lock for the duration: a run flips ``_run_live``
        under the same lock before its first allocation, so an export
        can never overlap a starting dispatch loop (the allocator and
        radix tree have no synchronization of their own)."""
        with self._pinned_lock:
            if self._run_live:
                return None
            return self._kv_export_locked(preamble)

    def _kv_export_locked(self, preamble: str) -> dict | None:
        if self._prefix_cache is None:
            return None
        ent = self._preambles.get(preamble)
        if ent is None:
            return None
        faults.fire("migrate.export")
        ids = list(ent["ids"])
        ps = self.cfg.page_size
        pages, matched, chain = self._prefix_cache.match_hier(ids)
        k_parts: list[np.ndarray] = []
        v_parts: list[np.ndarray] = []
        tokens = 0
        try:
            if matched:
                pay = self.cache.export_pages(pages)
                k_parts.append(pay["k"])
                v_parts.append(pay["v"])
                tokens += matched
        finally:
            if matched:
                self.cache.allocator.free(pages)
        for node, n_tok in chain:
            pay = self._prefix_cache.spill_payload(node)
            if pay is None:
                break
            k_parts.append(np.asarray(pay["k"]))
            v_parts.append(np.asarray(pay["v"]))
            tokens += n_tok
        if tokens == 0:
            return None
        k = (k_parts[0] if len(k_parts) == 1
             else np.concatenate(k_parts, axis=1))
        v = (v_parts[0] if len(v_parts) == 1
             else np.concatenate(v_parts, axis=1))
        kh, _ps, hd = (int(x) for x in self.cache.k.shape[1:])
        self._c_migrate_exports.inc()
        return {
            "kind": "kv_pageset",
            "version": 1,
            "preamble": preamble,
            "tokens": tokens,
            "ids": [int(t) for t in ids[:tokens]],
            "n_pages": tokens // ps,
            "page_size": ps,
            "n_layers": self.cache.n_layers,
            "n_kv_heads": kh,
            "head_dim": hd,
            "dtype": str(self.cache.k.dtype),
            "k": k,
            "v": v,
        }

    def kv_import(self, payload: dict) -> int:
        """Install a migrated page set into this engine's prefix cache:
        allocate device pages, scatter the payload (sync — control
        plane, not the hot path), insert under the payload's token ids,
        and publish the preamble into the routed summary so follow-up
        requests see it warm here.  Returns tokens now warm.

        Rejection discipline mirrors ``import_sequence``: geometry/
        dtype/framing mismatches raise ``ValueError`` (the router's
        cold-migration fallback owns the retry), pool pressure raises
        ``OutOfPages`` after a reclaim attempt, and a live run raises
        ``RuntimeError`` (busy — the caller retries between runs).  The
        ``migrate.import`` fault site fires before any mutation.

        Like ``kv_export``, holds the pin lock for the duration so a
        starting run can never overlap the scatter/insert."""
        with self._pinned_lock:
            if self._run_live:
                raise RuntimeError("engine busy; kv import retries between "
                                   "runs")
            return self._kv_import_locked(payload)

    def _kv_import_locked(self, payload: dict) -> int:
        if self._prefix_cache is None:
            raise ValueError("prefix cache off; nothing to import into")
        faults.fire("migrate.import")
        kh, ps, hd = (int(x) for x in self.cache.k.shape[1:])
        want = {"page_size": self.cache.page_size,
                "n_layers": self.cache.n_layers, "n_kv_heads": kh,
                "head_dim": hd, "dtype": str(self.cache.k.dtype)}
        for key, val in want.items():
            got = payload.get(key)
            if got != val:
                raise ValueError(
                    f"incompatible kv payload: {key}={got!r}, this pool "
                    f"has {val!r}")
        ids = [int(t) for t in payload.get("ids", ())]
        n = int(payload.get("n_pages", 0) or 0)
        tokens = int(payload.get("tokens", 0) or 0)
        if n <= 0 or tokens != n * ps or len(ids) != tokens:
            raise ValueError(
                f"inconsistent kv payload framing: {n} pages / {tokens} "
                f"tokens / {len(ids)} ids (page_size {ps})")
        k = np.asarray(payload["k"])
        v = np.asarray(payload["v"])
        shape = (self.cache.n_layers, n, kh, ps, hd)
        if k.shape != shape or v.shape != shape:
            raise ValueError(
                f"kv payload shape {k.shape} != expected {shape}")
        if n > self.cache.allocator.free_count:
            self._prefix_cache.evict(n - self.cache.allocator.free_count)
        pages = self.cache.alloc_pages(n)
        try:
            self.cache.import_pages(
                pages, {"k": k, "v": v, "dtype": payload["dtype"]},
                sync=True)
            self._prefix_cache.insert(ids, pages, max_tokens=tokens)
        finally:
            # the cache holds its own refs on adopted pages; ours drop
            self.cache.allocator.free(pages)
        key = payload.get("preamble")
        if isinstance(key, str) and key:
            self._preamble_tick += 1
            self._preambles[key] = {"ids": tuple(ids),
                                    "tick": self._preamble_tick}
            self._summary_memo = None
        self._c_migrate_imports.inc()
        self._c_migrate_tokens.inc(tokens)
        return tokens

    def _admit_import(self, b, queue, slots, results, fresh, kv_lens,
                      last_tok, active, temps, top_k, top_p) -> bool:
        """Admit the queue head's IMPORTED sequence (disaggregated decode
        role): scatter the transferred pages into the local pool and enter
        the slot directly in decode phase — no prefill ever dispatches for
        it.  Returns False on page back-pressure (the entry stays queued
        and admission waits, exactly like the prefill path); a payload
        failure (corrupt, incompatible pool geometry, token mismatch, or
        an injected ``handoff.import`` fault) terminates the entry with a
        MARKED error result — the router's re-prefill fallback owns the
        retry, and the pool stays clean either way."""
        req, ids, max_new, n_prompt, prior, t0 = queue[0]
        state = req.handoff_state
        # continue the exporter's trace: the payload carries the trace id
        # across the pod boundary, so the decode-side spans land on the
        # SAME fleet-wide chain (a request arriving with its own id —
        # the router re-sent the header — keeps it; they are equal anyway)
        if not req.trace_id and isinstance(state.get("trace_id"), str):
            req.trace_id = state["trace_id"]
        if not req.tenant and isinstance(state.get("tenant"), str):
            req.tenant = state["tenant"]
        try:
            need = int(state.get("n_pages", 0) or 0)
        except (TypeError, ValueError):
            need = -1
        if not 0 < need <= min(self.cache.max_pages_per_slot,
                               self.cache.num_pages - 1):
            # an unsatisfiable page claim must error-terminate, never wait:
            # treating it as back-pressure would wedge the queue head
            # forever and starve everything behind it
            queue.popleft()
            self._record_result(results, GenerationResult(
                request_id=req.request_id, prompt_tokens=n_prompt,
                finish_reason="error",
                error=f"handoff import failed: page claim {need} exceeds "
                      "this pool's capacity (geometry drift or corrupt "
                      "ticket)"), req=req)
            fresh.append(req.request_id)
            return True
        if need > self.cache.allocator.free_count:
            if self._prefix_cache is not None:
                self._prefix_cache.evict(
                    need - self.cache.allocator.free_count)
            if need > self.cache.allocator.free_count:
                return False
        queue.popleft()
        t_imp = time.time()
        try:
            gen = [int(t) for t in state.get("generated", ())]
            toks = [int(t) for t in state.get("tokens", ())]
            kv_len = int(state.get("kv_len", -1))
            if toks != list(ids):
                # tokenizer/config drift between pods: the imported KV
                # covers different token ids than this pod derives from
                # the same prompt — resuming would be silent corruption
                raise ValueError(
                    f"token mismatch: payload covers {len(toks)} prompt "
                    f"tokens, this pod encodes {len(ids)}"
                    + ("" if len(toks) != len(ids)
                       else " (same count, different ids)"))
            if kv_len != len(ids):
                raise ValueError(
                    f"inconsistent payload: kv_len {kv_len} != "
                    f"{len(ids)} prompt tokens")
            if not gen:
                raise ValueError("handoff state carries no resume token")
            scales = None
            if self._kv_quant:
                # int8 pool: the exporter's per-slot scales are REQUIRED
                # and shape-checked here, inside the marked-error guard —
                # silently keeping the previous slot occupant's scales
                # would dequantize the imported pages into garbage
                want = ((int(self.kscale.shape[0]),)
                        + tuple(int(s) for s in self.kscale.shape[2:]))
                try:
                    ks = np.asarray(state["kscale"], dtype=np.float32)
                    vs = np.asarray(state["vscale"], dtype=np.float32)
                except (KeyError, TypeError, ValueError) as e:
                    raise ValueError(
                        f"int8 pool payload missing/bad scales: {e}") from e
                if ks.shape != want or vs.shape != want:
                    raise ValueError(
                        f"scale shape {ks.shape}/{vs.shape} != pool's "
                        f"{want}")
                scales = (ks, vs)
            seq = self.cache.import_sequence(state)
            # consumed: if this slot is later PREEMPTED, its continuation
            # entry (prompt + generated so far) must re-admit through the
            # normal prefill path — routing it back through here would
            # fail the token-mismatch guard against the original prompt
            req.handoff_state = None
        except OutOfPages:
            queue.appendleft((req, ids, max_new, n_prompt, prior, t0))
            return False
        except Exception as e:  # noqa: BLE001 - degrade per request
            logger.warning("handoff import failed for request %d",
                           req.request_id, exc_info=True)
            self._record_result(results, GenerationResult(
                request_id=req.request_id, prompt_tokens=n_prompt,
                finish_reason="error",
                error=f"handoff import failed: {type(e).__name__}: {e}"),
                req=req)
            fresh.append(req.request_id)
            return True
        now = time.time()
        if req.deadline_s is not None:
            self._h_deadline_remaining.observe(req.deadline_s - now)
        st = _SlotState(req=req, prompt_ids=ids, max_new=max_new, seq=seq,
                        t_start=now, n_prompt=n_prompt)
        st.phase = "decode"
        st.prefill_pos = len(ids)
        st.kv_len = kv_len
        st.generated = gen
        st.t_admit = now
        st.t_decode_start = now
        slots[b] = st
        kv_lens[b] = st.kv_len
        last_tok[b] = gen[-1]
        active[b] = True
        temps[b] = req.temperature
        top_k[b] = req.top_k
        top_p[b] = min(max(req.top_p, 0.0), 1.0)
        if scales is not None:
            # the exporter's per-slot scales (validated above), scattered
            # into THIS slot's rows — imported int8 pages dequantize with
            # their own scales
            self.kscale = self.kscale.at[:, b].set(jnp.asarray(scales[0]))
            self.vscale = self.vscale.at[:, b].set(jnp.asarray(scales[1]))
        self.seed_history(b, st)
        self._c_handoff_imports.inc()
        self._h_handoff_import.observe(time.time() - t_imp)
        self._g_peak_pages.track_max(self.cache.num_pages - 1
                                     - self.cache.allocator.free_count)
        self._g_peak_slots.track_max(sum(s is not None for s in slots))
        if self._tr:
            self._tr.instant("handoff_import", ts=now,
                             tid=self._tid(req),
                             args={"slot": b, "kv_len": kv_len,
                                   "pages": len(seq.pages)})
        # stream the already-generated first token immediately (the slot
        # cannot be finished here: the pin guard excluded EOS/stop/budget-
        # complete first tokens from ever becoming handoffs)
        self._maybe_finish(b, slots, results, active, fresh, kv_lens,
                           last_tok)
        return True

    # ------------------------------------------------------------ internals

    def _encode(self, req: GenerationRequest) -> tuple[list[int], int]:
        text = (req.system_prompt + "\n\n" if req.system_prompt else "") + req.prompt
        ids = [self.tokenizer.bos_id] + self.tokenizer.encode(text)
        # max_new additionally caps at max_len-1: a budget >= the context
        # window would make the truncation limit below non-positive, turning
        # the middle-truncation slice into prompt DUPLICATION (negative-index
        # wraparound) or an empty prompt — and the admission invariant
        # ("every submitted request fits") rests on limit >= 1
        max_new = min(req.max_new_tokens, self.cfg.max_tokens,
                      self.max_len - 1)
        limit = self.max_len - max_new
        if len(ids) > limit:
            head, tail = limit // 2, limit - limit // 2
            ids = ids[:head] + ids[-tail:]
        if req.handoff_export:
            # prefill role: stop after the first token (the ticket carries
            # the rest of the budget).  Clamped AFTER the truncation math —
            # the prompt cut must be byte-identical to what a colocated run
            # (or the decode pod re-encoding this prompt) produces, or the
            # imported KV would disagree with the decode side's token ids.
            max_new = 1
        return ids, max_new

    # ---------------------------------------------------- roofline probe

    def roofline_microbench(self, prefill_reps: int = 8,
                            decode_reps: int = 4) -> dict:
        """Device-level prefill MFU + decode HBM utilization on the live
        engine (bench.py detail block; VERDICT r1 item 1).

        Lives here, next to the compiled programs it measures, so the
        dispatch-tuple contract stays in one file.  Chains R dispatches
        through the donated KV pools (each call consumes the previous
        call's pools) and fetches ONE dependent value at the end, so the
        host RTT amortizes over the chain — ``block_until_ready`` does NOT
        synchronize through tunneled chips (docs/PERF.md); RTT is measured
        separately and subtracted.  The pool must be idle (no live slots).

        On ANY failure the pools are reallocated before re-raising: a
        mid-chain error leaves ``cache.k/v`` pointing at donated buffers,
        and without recovery every later dispatch — including the caller's
        primary workload — would fail on them.
        """
        try:
            return self._roofline_microbench(prefill_reps, decode_reps)
        except Exception:
            self.cache.reallocate()
            raise

    def _roofline_microbench(self, prefill_reps: int,
                             decode_reps: int) -> dict:
        from lmrs_tpu.utils.perf_model import (
            chip_spec, decode_step_bytes, kv_bytes_per_token, prefill_flops,
            weight_bytes,
        )

        cfg_m = self.model_cfg
        spec = chip_spec()
        # drop retained prefix-cache pages: the decode probe sizes itself to
        # the FREE pool, and a warm cache would silently shrink the roofline
        # point (the cache rebuilds on the next real run)
        if self._prefix_cache is not None:
            self._prefix_cache.clear()
        # median trivial dependent fetch = host<->device round trip
        x = jnp.zeros((8,), jnp.float32)
        np.asarray(jax.device_get(x + 1))  # warm the tiny program
        rtts = []
        for _ in range(3):
            t0 = time.time()
            np.asarray(jax.device_get(x + 1))
            rtts.append(time.time() - t0)
        rtt = sorted(rtts)[1]
        out: dict = {"chip": spec.kind, "chip_known": spec.known,
                     "host_rtt_ms": round(rtt * 1e3, 1)}

        # ---- prefill: one [1, S] fresh dispatch at the full bucket ------
        S = self.max_len
        fn = self._get_prefill_fn(
            S, use_ring=self._use_ring and S >= self._ring_min)
        rng = np.random.default_rng(0)
        tokens = jnp.asarray(rng.integers(1, 255, (1, S), dtype=np.int32))
        seq = self.cache.open_sequence(S)
        try:
            table = jnp.asarray(self.cache.page_table_array([seq]))
            ones = jnp.ones((1,), jnp.float32)
            args = (tokens, jnp.zeros((1,), jnp.int32),
                    jnp.full((1,), S, jnp.int32),
                    jnp.full((1,), seq.capacity(self.cache.page_size),
                             jnp.int32),
                    table, jax.random.PRNGKey(7), ones,
                    jnp.zeros((1,), jnp.int32), ones)
            k, v = self.cache.k, self.cache.v
            # scale_rows = B: the probe's scale scatter is dropped (its rows
            # are not real slots), but the donated buffers must be carried
            srow = jnp.full((1,), self.B, jnp.int32)
            tok0, k, v, self.kscale, self.vscale = fn(
                self.params, k, v, self.kscale, self.vscale, srow, *args)
            np.asarray(jax.device_get(tok0))
            t0 = time.time()
            for _ in range(prefill_reps):
                tok0, k, v, self.kscale, self.vscale = fn(
                    self.params, k, v, self.kscale, self.vscale, srow, *args)
            np.asarray(jax.device_get(tok0))
            per_prefill = max((time.time() - t0 - rtt) / prefill_reps, 1e-9)
            self.cache.k, self.cache.v = k, v
        finally:
            self.cache.close_sequence(seq)

        # head_tokens=1: fresh prefill gathers the last row before the LM
        # head (forward_paged last_pos), so the full-vocab head is not run
        fl = prefill_flops(cfg_m, S, head_tokens=1)
        out["prefill_tokens_per_sec"] = round(S / per_prefill, 1)
        out["model_flops_utilization"] = round(
            fl / per_prefill / spec.peak_flops, 4)
        out["prefill_ms"] = round(per_prefill * 1e3, 2)

        # ---- decode: full-width batched steps at steady-state context ---
        # Sized to the AVAILABLE pool (ADVICE r2): opening B full-length
        # sequences raises OutOfPages on any budget-sized pool (num_pages>1);
        # the probe measures steady-state bandwidth, which scales with live
        # tokens, so a smaller per-slot context is still a valid roofline
        # point — step_bytes below uses the same live-token total.  When the
        # pool can't back even one page per slot, the extra rows run masked
        # on the null page (length 0) rather than raising.
        B = self.B
        free = self.cache.allocator.free_count
        if free == 0:
            # probing an exhausted pool would raise OutOfPages on the very
            # first open_sequence; an all-masked decode measures nothing,
            # so report the skip instead of crashing the detail block
            out["decode_probe_skipped"] = "no free KV pages"
            return out
        rows = min(B, free)
        per_slot = max(1, min(self.cache.max_pages_per_slot, free // rows))
        live = min(int(S * 0.75), per_slot * self.cache.page_size)
        seqs = [self.cache.open_sequence(live) for _ in range(rows)]
        try:
            w = self.cache.max_pages_per_slot
            onesB = jnp.ones((B,), jnp.float32)
            row_live = np.zeros((B,), np.int32)
            row_live[:rows] = live
            table_rows = list(seqs) + [None] * (B - rows)  # null-page rows
            dargs = (jnp.asarray(rng.integers(1, 255, (B,), dtype=np.int32)),
                     jnp.asarray(row_live),
                     jnp.asarray(self.cache.page_table_array(table_rows)[:, :w]),
                     jnp.asarray(row_live > 0), jax.random.PRNGKey(8), onesB,
                     jnp.zeros((B,), jnp.int32), onesB)
            dfn = self._get_decode_fn(w)
            k, v = self.cache.k, self.cache.v
            srowsd = jnp.arange(self.B, dtype=jnp.int32)
            toks, n_valid, k, v = dfn(
                self.params, k, v, self.kscale, self.vscale, srowsd,
                *dargs)  # warm
            np.asarray(jax.device_get(n_valid))
            t0 = time.time()
            for _ in range(decode_reps):
                toks, n_valid, k, v = dfn(
                    self.params, k, v, self.kscale, self.vscale, srowsd,
                    *dargs)
            np.asarray(jax.device_get(n_valid))
            wall = time.time() - t0 - rtt
            self.cache.k, self.cache.v = k, v
        finally:
            for s_ in seqs:
                self.cache.close_sequence(s_)

        per_step = max(wall / (decode_reps * self.decode_block), 1e-9)
        step_bytes = decode_step_bytes(cfg_m, rows * live,
                                       quantized=bool(self.cfg.quantize),
                                       kv_quantized=bool(self._kv_quant))
        out["decode_tokens_per_sec"] = round(rows / per_step, 1)
        out["decode_step_ms"] = round(per_step * 1e3, 3)
        out["hbm_bw_utilization"] = round(
            step_bytes / per_step / spec.peak_hbm_bw, 4)
        out["decode_step_gb"] = round(step_bytes / 1e9, 2)
        out["weight_gb"] = round(weight_bytes(cfg_m) / 1e9, 2)
        out["kv_kb_per_token"] = round(kv_bytes_per_token(cfg_m) / 1e3, 1)
        return out

    def rowcost_microbench(self, lo: int = 64, hi: int = 256,
                           reps: int = 3) -> dict:
        """Per-row fixed cost of the ragged decode attention at this
        engine's exact shape (kv heads, head dim, page size, slot count),
        grouped vs per-row — the bench-detail attribution for the
        multi-row page walk.  One attention layer's fused kernel chained
        inside a jitted ``fori_loop`` (output feeds the next q, pools ride
        the carry), timed via the shared RTT-cancelling chain method
        (utils/perf_model.time_chain — the same implementation
        decode_rowcost.py uses, so the two probes' us/row numbers stay
        comparable).

        Probes standalone bf16 pools (one live page per row), never the
        engine's own cache: it can run between waves without disturbing
        live state.  Returns {} off-TPU or under a multi-device mesh —
        interpret-mode chains would measure the emulator."""
        from lmrs_tpu.utils.perf_model import time_chain
        from lmrs_tpu.utils.platform import on_tpu

        if not (self._use_ragged and on_tpu() and self._single_device()):
            return {}
        from lmrs_tpu.ops.paged_attention import paged_decode_pallas_fused

        cfg_m = self.model_cfg
        kh, hd, ps = cfg_m.n_kv_heads, cfg_m.hd, self.cfg.page_size
        B = self.B
        rng = np.random.default_rng(0)
        q0 = jnp.asarray(rng.standard_normal((B, cfg_m.n_heads, hd)),
                         jnp.bfloat16)
        kn = jnp.asarray(rng.standard_normal((B, kh, hd)), jnp.bfloat16)
        vn = jnp.asarray(rng.standard_normal((B, kh, hd)), jnp.bfloat16)
        kp0 = jnp.asarray(rng.standard_normal((B + 1, kh, ps, hd)),
                          jnp.bfloat16)
        vp0 = jnp.asarray(rng.standard_normal((B + 1, kh, ps, hd)),
                          jnp.bfloat16)
        pt = jnp.asarray((1 + np.arange(B))[:, None], jnp.int32)
        kl = jnp.full((B,), min(64, ps), jnp.int32)

        def make_chain(iters: int, g: int):
            @jax.jit
            def chain(q, kp, vp):
                def body(_, carry):
                    q, kp, vp = carry
                    out, kp, vp = paged_decode_pallas_fused(
                        q, kn, vn, kp, vp, pt, kl, row_group=g)
                    return (out.astype(q.dtype), kp, vp)

                return jax.lax.fori_loop(0, iters, body, (q, kp, vp))

            return lambda: chain(q0, kp0, vp0)[0]

        out: dict = {"decode_row_group": self._row_group}
        arms = {"per_row": 1}
        if self._row_group > 1:
            arms["grouped"] = self._row_group
        for name, g in arms.items():
            per_kernel = time_chain(
                lambda iters, g=g: make_chain(iters, g), lo, hi, reps)
            out[f"decode_row_us_{name}"] = round(per_kernel / B * 1e6, 3)
        if self._rpa:
            # unified span kernel, q_len=1 rows — the per-row number
            # perf_sentry tracks against the retired fused path
            # (decode_row_us_rpa: a regression here fails the report arm)
            from lmrs_tpu.ops.paged_attention import ragged_spans_pallas
            q_starts_np, total = pack_spans(np.ones((B,), np.int32))
            qf0 = jnp.asarray(rng.standard_normal(
                (total, cfg_m.n_heads, hd)), jnp.bfloat16)
            knf = jnp.asarray(rng.standard_normal((total, kh, hd)),
                              jnp.bfloat16)
            vnf = jnp.asarray(rng.standard_normal((total, kh, hd)),
                              jnp.bfloat16)
            qs = jnp.asarray(q_starts_np)
            ql = jnp.ones((B,), jnp.int32)

            def make_chain_rpa(iters: int):
                @jax.jit
                def chain(q, kp, vp):
                    def body(_, carry):
                        q, kp, vp = carry
                        o, kp, vp = ragged_spans_pallas(
                            q, knf, vnf, kp, vp, pt, kl, qs, ql)
                        return (o.astype(q.dtype), kp, vp)

                    return jax.lax.fori_loop(0, iters, body, (q, kp, vp))

                return lambda: chain(qf0, kp0, vp0)[0]

            per_kernel = time_chain(make_chain_rpa, lo, hi, reps)
            out["decode_row_us_rpa"] = round(per_kernel / B * 1e6, 3)
        return out

    # ------------------------------------------- page growth / preemption

    def _ensure_decode_capacity(self, slots, queue, kv_lens, last_tok,
                                active, extra_tokens: int | None = None
                                ) -> list[int]:
        """Grow each active decode slot's pages to cover the coming decode
        block — ``extra_tokens`` overrides the default block growth (a
        mixed fused step advances decode rows by ONE token, so it grows by
        one).  On pool exhaustion,
        preempt the YOUNGEST decode slot — free its pages and requeue it at
        the queue head as a continuation (prompt + generated-so-far
        re-prefills once pages free up) — and retry.  When no OTHER decode
        slot exists (the pages are held by mid-prefill slots), the slot is
        STALLED for this dispatch instead of discarding its own progress:
        its row is masked off, and the masked row's dummy writes land on
        the null page (unallocated table columns are zero).  Returns the
        stalled rows; the caller re-activates them after the dispatch.
        Deadlock-free: the pool holds at least one full-length sequence
        (pool sizing in __init__), so a slot alone in the pool always
        grows, and prefill slots always finish without growth."""
        block = (self.decode_block + self.spec_k if extra_tokens is None
                 else extra_tokens)
        stalled: list[int] = []
        for b in range(self.B):
            st = slots[b]
            if st is None or not active[b] or st.phase != "decode":
                continue
            target = min(st.kv_len + block, self.max_len)
            while True:
                try:
                    self.cache.grow(st.seq, target)
                    break
                except OutOfPages:
                    if self._qos is not None:
                        victim = self._qos_victim_slot(slots, active,
                                                       exclude=b)
                    else:
                        victim = self._youngest_decode_slot(slots, active,
                                                            exclude=b)
                    if victim is None:
                        stalled.append(b)
                        active[b] = False
                        self._c_stalls.inc()
                        break
                    self._preempt(victim, slots, queue, kv_lens, last_tok,
                                  active)
        return stalled

    def _qos_victim_slot(self, slots, active, exclude: int) -> int | None:
        """QoS preemption policy (fleet/qos.py): the WORST active decode
        slot by (batch class first, highest normalized windowed usage,
        youngest) — over-quota bulk work pays for the pool before a live
        session does.  Uniform traffic ties the first two keys and the
        rule degenerates to the youngest-slot order below."""
        best, best_key = None, None
        for b in range(self.B):
            st = slots[b]
            if (b == exclude or st is None or not active[b]
                    or st.phase != "decode"):
                continue
            key = self._qos.victim_key(st.req, st.t_start)
            if best_key is None or key >= best_key:
                best, best_key = b, key
        if best is not None:
            self._qos.note_preempt()
            if self._tr:
                # fleet-drift contract (trace.py): a QoS preemption is an
                # auditable scheduling decision, visible in the trace
                self._tr.instant("qos_preempt",
                                 args={"slot": best,
                                       "tenant": slots[best].req.tenant
                                       or "default"})
        return best

    def _youngest_decode_slot(self, slots, active, exclude: int) -> int | None:
        """Latest-admitted active decode slot, or None if only ``exclude``
        (the slot being grown) qualifies."""
        best, best_t = None, -1.0
        for b in range(self.B):
            st = slots[b]
            if (b == exclude or st is None or not active[b]
                    or st.phase != "decode"):
                continue
            if st.t_start >= best_t:
                best, best_t = b, st.t_start
        return best

    def _prefetch_spilled(self, chain, cached_pages: list[int],
                          fresh: list[int], cached_tokens: int):
        """Restore the matched spilled segments (host tier → device) into
        their share of the freshly allocated pages, in positional order.
        Each successful segment promotes its radix node back to resident
        on those pages (prefix_cache.prefetch_into) and extends the
        usable match; the FIRST failure — the ``prefix.prefetch`` fault,
        or an entry the host budget dropped between match and here —
        truncates the match at that segment, whose pages (and every later
        segment's) simply become prefill tail.  Returns
        ``(cached_pages, fresh_tail, cached_tokens, prefetched_tokens)``."""
        ps = self.cfg.page_size
        used = 0
        got_tokens = 0
        t0 = time.time()
        for node, n_tok in chain:
            npg = n_tok // ps
            dest = fresh[used: used + npg]
            try:
                # injection site: fires BEFORE any mutation for this
                # segment — a fault costs exactly the segment's reuse,
                # never a wedged admission
                faults.fire("prefix.prefetch")
                self._prefix_cache.prefetch_into(node, dest, self.cache,
                                                 sync=self._host_kv_sync)
            except Exception:  # noqa: BLE001 - degrade to re-prefill
                logger.warning("KV prefetch failed; re-prefilling the "
                               "spilled segment", exc_info=True)
                break
            used += npg
            got_tokens += n_tok
        if used:
            self._h_prefetch.observe(time.time() - t0)
            self._c_prefetch_pages.inc(used)
            self._c_prefetch_tokens.inc(got_tokens)
            self._c_spilled_hits.inc()
            # perf attribution: the scatter's HBM bytes ride into the
            # next block's wall — count them and keep that block from
            # polluting the clean-sample EMA
            self._perf.note_prefetch(used * self.cache.page_payload_bytes())
        return (cached_pages + fresh[:used], fresh[used:],
                cached_tokens + got_tokens, got_tokens)

    def _note_preamble(self, req: GenerationRequest) -> None:
        """Record a request's shared preamble for the published radix
        summary (prefix_summary): key = api.preamble_key over the same
        text region _cache_insert donates; the encoded token ids are kept
        so summary publication can re-probe LIVE resident/spilled
        coverage against the tree.  Bounded LRU (32 preambles — a fleet
        shares a handful of map/reduce/system preambles by design)."""
        key = preamble_key(req.system_prompt, req.prompt, req.cache_prefix)
        if key is None:
            return
        self._preamble_tick += 1
        ent = self._preambles.get(key)
        if ent is None:
            text = preamble_text(req.system_prompt, req.prompt,
                                 req.cache_prefix)
            ids = tuple([self.tokenizer.bos_id]
                        + self.tokenizer.encode(text))
            # tick stamped BEFORE the LRU trim: a zero-tick insert would
            # make the brand-new entry the min-by-tick victim and the
            # summary would stop learning past 32 preambles
            ent = {"ids": ids, "tick": self._preamble_tick}
            self._preambles[key] = ent
            while len(self._preambles) > 32:
                oldest = min(self._preambles,
                             key=lambda k: self._preambles[k]["tick"])
                del self._preambles[oldest]
        ent["tick"] = self._preamble_tick

    def prefix_summary(self, top_k: int = 16) -> list[dict]:
        """Compact radix summary for the control plane (served through
        /healthz and the JSON /metrics page): the top-K recently seen
        preamble hashes with their depth and LIVE resident/spilled
        coverage (prefix_cache.peek — full-page capacity view).  The
        router routes sticky-by-expected-prefix-hit on these
        (serving/router.py).  Callable from HTTP handler threads while
        the scheduler runs: reads are guarded snapshots, memoized for
        1 s, and degrade to the previous summary on a raced mutation."""
        if self._prefix_cache is None:
            return []
        now = time.time()
        memo = self._summary_memo
        if memo is not None and now - memo[0] < 1.0:
            return memo[1]
        out: list[dict] = []
        try:
            entries = sorted(self._preambles.items(),
                             key=lambda kv: -kv[1]["tick"])[:top_k]
            for key, ent in entries:
                cov = self._prefix_cache.peek(list(ent["ids"]))
                out.append({"hash": key,
                            "depth_tokens": len(ent["ids"]),
                            "tick": ent["tick"], **cov})
        except RuntimeError:  # dict/tree resized mid-walk: keep the last
            return memo[1] if memo is not None else []
        self._summary_memo = (now, out)
        return out

    def _cache_insert(self, st: _SlotState) -> None:
        """Donate a fully-prefilled slot's prompt-page prefix to the prefix
        cache.  The ``cache_prefix`` request hint (leading PROMPT chars
        expected to be shared) caps adoption so per-chunk unique bodies
        don't bloat the tree.  A hint of 0 means the prompt body shares
        nothing — the shared system preamble (always encoded FIRST by
        _encode) is still donated; only when there is no system prompt
        either is there nothing to cache."""
        if self._prefix_cache is None:
            return
        # summary bookkeeping rides the donation point: the preamble just
        # became (or refreshed as) cached content worth routing onto
        self._note_preamble(st.req)
        cap = None
        hint = st.req.cache_prefix
        if hint is not None:
            if hint < 0:
                return
            # token-level cap: bos + encoded system preamble + shared prompt
            # head (api.preamble_text — the SAME region the routing key
            # hashes, so placement and donation can never drift apart).
            # Approximate at the char boundary by design (the cap rounds
            # up to a page inside insert) — see GenerationRequest.
            text = preamble_text(st.req.system_prompt, st.req.prompt, hint)
            if not text:
                return  # hint 0 and no system prompt: nothing shared
            cap = 1 + len(self.tokenizer.encode(text))
        try:
            self._prefix_cache.insert(st.prompt_ids, st.seq.pages,
                                      max_tokens=cap)
        except Exception:
            # caching is an optimization: an insertion fault (injected or
            # real) must cost a cache hit, never the request
            logger.warning("prefix-cache insert failed; request continues "
                           "uncached", exc_info=True)

    def _preempt(self, b, slots, queue, kv_lens, last_tok, active) -> None:
        st = slots[b]
        # keep the victim's prompt prefix cached: its continuation (and any
        # same-preamble neighbor) re-matches instead of re-prefilling; the
        # pages stay evictable, so this never blocks the reclaim that the
        # preemption itself is after
        if st.phase == "decode":
            self._cache_insert(st)
        self.cache.close_sequence(st.seq)
        # continuation: generated tokens fold into the prefill ids, original
        # prompt length and prior output ride along for accounting/finish.
        # Insert ordered by t_start among the continuations already at the
        # queue head (a bare appendleft re-queued multiple same-pass victims
        # youngest-first — a fairness inversion under sustained pressure,
        # ADVICE r2): older continuations keep queue priority.
        entry = (st.req, st.prompt_ids + st.generated, st.max_new,
                 st.n_prompt, st.prior + st.generated, st.t_start)
        pos = 0
        while (pos < len(queue) and queue[pos][5] is not None
               and queue[pos][5] <= st.t_start):
            pos += 1
        queue.insert(pos, entry)
        slots[b] = None
        active[b] = False
        kv_lens[b] = 0  # same invariant as admission/_maybe_finish: a freed
        last_tok[b] = 0  # row must never carry a stale length into a kernel
        self._c_preemptions.inc()
        if self._tr:
            now = time.time()
            tid = self._tid(st.req)
            if st.t_decode_start:  # close this slot life's decode span
                self._tr.complete("decode", st.t_decode_start, now, tid=tid,
                                  args={"preempted": True})
            self._tr.instant("preempt", ts=now, tid=tid,
                             args={"slot": b,
                                   "generated_so_far": len(st.prior)
                                   + len(st.generated)})
        logger.debug("preempted slot %d (request %d) under page pressure",
                     b, st.req.request_id)

    def _maybe_finish(self, b, slots, results, active, fresh=None,
                      kv_lens=None, last_tok=None):
        st = slots[b]
        # decode runs in fixed blocks, so a slot can overshoot its budget by
        # up to decode_block-1 tokens between host syncs — trim to budget
        # (_trimmed_output).  prior = tokens generated before a preemption
        # (already re-prefilled as part of prompt_ids; still OUTPUT tokens).
        gen, text, stop_hit, hit_eos = self._trimmed_output(st)
        finished = hit_eos or stop_hit or len(gen) >= st.max_new
        if self._on_tokens is not None:
            # stream the block's new text: cut from the trimmed text, so the
            # deltas' concatenation is exactly the final result text.  A
            # multi-byte UTF-8 sequence straddling a block boundary decodes
            # as trailing U+FFFD until its bytes complete — hold those back
            # (they'd change retroactively); a real U+FFFD flushes at finish.
            # Guarded against non-prefix-stable decoders (HF tokenizers'
            # cleanup can rewrite earlier characters as tokens arrive): a
            # delta is emitted ONLY while the new text extends what was
            # already sent — on violation the stream FREEZES (undershoots)
            # rather than ever emitting characters that later change; the
            # non-streamed result text stays authoritative.
            sent = self._streamed.get(st.req.request_id, "")
            frontier = len(text)
            if not finished:
                while frontier > len(sent) and text[frontier - 1] == "�":
                    frontier -= 1
                if st.req.stop:
                    # a stop string can straddle block boundaries: a future
                    # match starts past len(text) - len(stop), so keeping
                    # max(len)-1 chars unstreamed guarantees no emitted char
                    # ever precedes a later truncation point
                    hold = max((len(s) for s in st.req.stop if s),
                               default=1) - 1
                    frontier = min(frontier, len(text) - hold)
            if frontier > len(sent) and text.startswith(sent):
                self._on_tokens(st.req.request_id, text[len(sent):frontier])
                self._streamed[st.req.request_id] = text[:frontier]
        if finished:
            if (st.req.handoff_export and not hit_eos and stop_hit is None
                    and not st.prior
                    and len(gen) < self._orig_budget(st.req)):
                # prefill role: the request is NOT complete — its budget
                # was clamped to 1 at encode; pin the pages for export
                # instead of freeing them.  A first token that IS terminal
                # (EOS, stop hit, or a genuine 1-token budget) takes the
                # normal finish below: there is nothing left to hand off
                # and the serving layer returns the completion directly.
                self._pin_handoff(b, slots, results, active, fresh,
                                  kv_lens, last_tok, gen, text)
                return
            finish = "stop" if (hit_eos or stop_hit) else "length"
            self._finish_slot(b, slots, results, active, fresh, kv_lens,
                              last_tok, gen, text, stop_hit, finish)

    # ------------------------------------------------- mixed dispatch

    def _pick_mixed_prefill(self, slots) -> int | None:
        """The prefilling slot whose slice rides this mixed step: oldest
        admission first (FIFO — every admitted prompt advances within a
        bounded number of steps), ties on slot index.  ONE slot per step
        by design (SARATHI): the slice is clipped to the step budget
        anyway, and a single contiguous slice keeps the fused program's
        shape zoo to (slice bucket, page window) pairs."""
        best, best_t = None, float("inf")
        for b in range(self.B):
            st = slots[b]
            if st is None or st.phase != "prefill":
                continue
            if st.t_admit < best_t:
                best, best_t = b, st.t_admit
        return best

    def _mixed_iteration(self, slots, queue, results, fresh, kv_lens,
                         last_tok, active, temps, top_k, top_p, t_enq,
                         last_block_t):
        """One SARATHI mixed step: every live decode row advances ONE
        token and one prefilling slot's next prompt slice (clipped to
        ``mixed_token_budget - decode_tokens``) rides the SAME fused
        multi-token dispatch — decode cadence continues through the
        admission.  Returns ``(handled, last_block_t)``; ``handled=False``
        (nothing to mix, or the budget left no room for a slice) falls
        back to the alternating path with no state disturbed beyond
        capacity growth.

        Speculation note: decode rows advance un-speculated during mixed
        steps (the device history buffer is re-seeded per advanced row so
        full spec blocks resume cleanly once the prefill drains); greedy
        outputs are unchanged either way — exact-distribution verify
        emits exactly the greedy tokens."""
        pf = self._pick_mixed_prefill(slots)
        has_decode = any(
            slots[b] is not None and active[b]
            and slots[b].phase == "decode" for b in range(self.B))
        if pf is None or not has_decode:
            return False, last_block_t
        if self._rpa:
            # ragged span dispatch (LMRS_RPA, the default): the mixed step
            # is a span list through the unified kernel — and under
            # speculation the decode rows carry verify spans, so spec no
            # longer yields during prefill windows
            return self._rpa_mixed_iteration(
                pf, slots, queue, results, fresh, kv_lens, last_tok,
                active, temps, top_k, top_p, t_enq, last_block_t)

        def rearm(stalled):
            for b in stalled:  # stalled rows rejoin the next dispatch
                if slots[b] is not None:
                    active[b] = True

        # grow decode rows by the ONE token this step appends; under pool
        # pressure the youngest decode slot preempts, exactly as a block
        # dispatch would (prefill-phase slots are never victims)
        stalled = self._ensure_decode_capacity(slots, queue, kv_lens,
                                               last_tok, active,
                                               extra_tokens=1)
        rows = [b for b in range(self.B)
                if slots[b] is not None and active[b]
                and slots[b].phase == "decode"]
        budget_left = self.mixed_token_budget - len(rows)
        if not rows or budget_left < 16:
            # every decode row stalled (alternating path owns the stall
            # recovery) or the live rows already exhaust the budget
            # (budget misconfigured below the slot count): alternate this
            # step rather than dispatch a degenerate slice
            rearm(stalled)
            return False, last_block_t

        st_pf = slots[pf]
        pos = st_pf.prefill_pos
        c = min(len(st_pf.prompt_ids) - pos, budget_left,
                self.prefill_chunk)
        t_bucket = min(_pow2_bucket(c, 16), self.max_len)
        c = min(c, t_bucket)  # pow2 bucket >= c whenever max_len is pow2
        is_final = pos + c >= len(st_pf.prompt_ids)

        # [B, T] operands: decode rows carry their pending token at index
        # 0, the prefill row its slice at 0..C-1.  Padding tokens write at
        # positions past each row's live length — the row's own not-yet-
        # reached positions (overwritten by the next real token at that
        # position) or, past its allocated pages/table span, the null page
        # — and the per-token causal limit (position < base + j + 1)
        # masks them from every real query, so no ragged per-row width is
        # needed.  Rows carrying no work keep lens 0: the kernel's
        # n_pages==0 fast path zeroes their output without a walk.
        T = t_bucket
        tokens = np.zeros((self.B, T), np.int32)
        base = np.zeros((self.B,), np.int32)
        lens_inc = np.zeros((self.B,), np.int32)
        last_idx = np.zeros((self.B,), np.int32)
        table_rows = [None] * self.B
        max_pages = 1
        live_tokens = 0
        for b in rows:
            st = slots[b]
            tokens[b, 0] = last_tok[b]
            base[b] = st.kv_len
            lens_inc[b] = st.kv_len + T
            table_rows[b] = st.seq
            live_tokens += st.kv_len
            max_pages = max(max_pages,
                            self.cache.pages_needed(st.kv_len + 1))
        tokens[pf, :c] = st_pf.prompt_ids[pos: pos + c]
        base[pf] = pos
        lens_inc[pf] = pos + T
        last_idx[pf] = c - 1
        table_rows[pf] = st_pf.seq
        max_pages = max(max_pages, self.cache.pages_needed(pos + c))
        w = min(_pow2_bucket(max_pages, 4), self.cache.max_pages_per_slot)
        table = self.cache.page_table_array(table_rows)

        self._h_occupancy.observe(len(rows) / self.B)
        self._c_decode_dispatches.inc()
        self._h_mixed_fill.observe(
            (len(rows) + c) / self.mixed_token_budget)
        self._c_piggybacked.inc(c)
        self._c_prefill_tokens.inc(c)
        self._h_prefill_batch.observe(c)
        if (self._row_group > 1 and self._use_ragged
                and self._kernel_mesh() is None):
            # same convention as the spec block: rows dispatch in slot
            # order (no balanced permutation — the mixed shape is B-wide
            # and the prefill row pins its slot anyway)
            g = self._row_group
            self._h_group_occupancy.observe(
                (len(rows) + 1) / (-(-self.B // g) * g))
        now = time.time()
        if last_block_t is not None:
            self._h_block_gap.observe(now - last_block_t)
            self._slo.observe_gap(now - last_block_t)
        last_block_t = now
        flops = self._perf.prefill_flops(c, kv_start=pos)
        if self._tr:
            self._tr.instant("prefill_dispatch",
                             args={"rows": 1, "tokens": c, "bucket": T,
                                   "mixed": True,
                                   "flops_g": round(flops / 1e9, 3)})
        st_pf.prefill_pos = pos + c

        self._key, sub = jax.random.split(self._key)
        args = (self.params, self.cache.k, self.cache.v,
                jnp.asarray(tokens), jnp.asarray(base),
                jnp.asarray(lens_inc), jnp.asarray(last_idx),
                jnp.asarray(table[:, :w]), sub, jnp.asarray(temps),
                jnp.asarray(top_k), jnp.asarray(top_p))
        key_ = ("mixed", T, w)
        warm = key_ in self._ran_ok
        if not warm:
            self._wd_grace_cold()
        t_disp = time.time()
        with self._an.seg("dispatch"):
            try:
                nxt, self.cache.k, self.cache.v = \
                    self._get_mixed_fn(T, w)(*args)
            except Exception:
                # same contract as the decode/spec fallbacks: degrade only
                # on a first-run lowering failure of the multi-token
                # kernel (donation happens at execution, args still
                # valid); a failure on a proven shape re-raises
                if not self._use_ragged or key_ in self._ran_ok:
                    raise
                logger.warning("mixed multi-token kernel failed to lower; "
                               "falling back to XLA multi decode",
                               exc_info=True)
                self._invalidate_compiled()
                nxt, self.cache.k, self.cache.v = \
                    self._get_mixed_fn(T, w)(*args)
        self._note_ran_ok(key_)
        with self._an.seg("fetch"):
            nxt = np.asarray(self._timed_get(nxt))
        t_done = time.time()

        # exact-split attribution: the fused step's per-row token counts
        # are known, so no decode-share estimate is involved (note_block's
        # EMA decomposition stays for the sequenced-prefill block path)
        with self._an.seg("finish"):
            extra_flops, cold_pf = self._consume_prefill_attr()
            nb = self._perf.note_mixed_step(
                t_disp, t_done, len(rows), live_tokens,
                flops + extra_flops, warm=warm and not cold_pf)
            self._attr_last_gb = round(nb / 1e9, 3)
            if self._cost.enabled:
                # fused-step ledger note: every decode row advanced
                # exactly one token; the piggybacked slice joins the
                # pending prefill rows (the ISSUE's exact per-row split,
                # no estimates)
                dcost, pcost = self._roofline_phase_costs(
                    nb, flops + extra_flops)
                self._cost.note_step(
                    max(0.0, t_done - t_disp),
                    decode_rows=[(slots[b].req, 1,
                                  len(slots[b].seq.pages))
                                 for b in rows],
                    prefill_rows=(self._consume_prefill_cost()
                                  + [(st_pf.req, c, flops)]),
                    decode_cost_s=dcost, prefill_cost_s=pcost)

            for b in rows:
                st = slots[b]
                tok = int(nxt[b])
                st.generated.append(tok)
                st.kv_len += 1
                kv_lens[b] = st.kv_len
                last_tok[b] = tok
                self._c_decode_tokens.inc(1)
                if self._tr:
                    self._tr.instant("decode_block", ts=now,
                                     tid=self._tid(st.req),
                                     args={"tokens": 1})
                self._maybe_finish(b, slots, results, active, fresh,
                                   kv_lens, last_tok)
                if self.spec_k:
                    self._spec_stale.add(b)
            if is_final:
                # the slice completed the prompt: enter decode with the
                # first token this very step sampled (index C-1 = the
                # last prompt token's row — the fresh-prefill sampling
                # contract)
                st = st_pf
                st.phase = "decode"
                st.t_decode_start = time.time()
                if self._tr:
                    self._tr.complete("prefill", st.t_admit,
                                      st.t_decode_start,
                                      tid=self._tid(st.req),
                                      args={"prompt_tokens":
                                            len(st.prompt_ids)})
                st.kv_len = len(st.prompt_ids)
                kv_lens[pf] = st.kv_len
                active[pf] = True
                self._cache_insert(st)
                tok0 = int(nxt[pf])
                st.generated.append(tok0)
                self._note_first_token(st, t_enq)
                last_tok[pf] = tok0
                if self.spec_k:
                    self._spec_stale.add(pf)
                self._maybe_finish(pf, slots, results, active, fresh,
                                   kv_lens, last_tok)
            if self._tr:
                self._tr.complete("decode_block", now, time.time(),
                                  args={"active": len(rows),
                                        "tokens": len(rows),
                                        "hbm_gb": self._attr_last_gb,
                                        "mixed": True,
                                        "prefill_tokens": c})
            rearm(stalled)
        return True, last_block_t

    def _get_mixed_fn(self, t: int, w: int):
        """Fused mixed-step program: one [B, T] multi-token dispatch where
        decode rows carry ONE real token (index 0) and the piggybacked
        prefill row its slice (indices 0..C-1), through the ragged
        multi-token row-group path — the kernel already parametrizes
        per-row token counts via per-token causal limits, so decode and
        prefill rows differ only in how many of their T positions are
        real.  Samples one token per row at its host-provided last real
        index (the LM head runs on that row only — at real vocabularies a
        full [B, T, V] head would be the packing win given back).
        Compiled per (slice bucket, page window): the bounded mixed shape
        zoo (log2 slice buckets x log2 windows)."""
        key_ = (t, w)
        if key_ in self._mixed_fns:
            return self._mixed_fns[key_]
        cfg = self.model_cfg
        max_len = self.max_len
        rope_max = self.max_len
        # same gate as the spec verify fn: the multi-token kernel has no
        # shard_map wrapper, so under a real multi-device mesh the XLA
        # multi path serves (one window gather — still not the per-layer
        # window_prefill gather)
        use_ragged = self._use_ragged and self._kernel_mesh() is None
        interp = self._interpret
        row_group = self._row_group

        @partial(jax.jit, donate_argnums=(1, 2))
        def mixed_step(params, k_pages, v_pages, tokens, base, lens_inc,
                       last_idx, table, key, temps, tk, tp):
            # rope positions: each row's tokens sit at consecutive
            # absolute positions from its own base (kv_len for decode
            # rows, the slice start for the prefill row); the write span
            # derives from lens_inc inside the multi path (UNclamped per
            # its contract — max_pos masks any overhang)
            positions = jnp.minimum(
                base[:, None] + jnp.arange(t)[None, :], max_len - 1)
            out = forward_paged(
                params, cfg, tokens, positions, k_pages, v_pages, table,
                lens_inc, rope_max, use_ragged_kernel=use_ragged,
                multi_decode=True, interpret=interp, last_pos=last_idx,
                decode_row_group=row_group,
            )
            logits, k_pages, v_pages = out[:3]
            # single step, no scan/vmap wrapper: sample_logits' lax.cond
            # fast paths are safe here (ops/sampling.py NOTE)
            nxt = sample_logits(logits[:, 0], key, temps, tk, tp)
            return nxt, k_pages, v_pages

        logger.info("compiling mixed step: B=%d slice_bucket=%d window=%d "
                    "pages (ragged_kernel=%s row_group=%d)", self.B, t, w,
                    use_ragged, row_group)
        self._mixed_fns[key_] = mixed_step
        return mixed_step

    # ------------------------------------------- ragged span dispatch (RPA)

    def _get_rpa_fn(self, tpb: int, w: int):
        """Unified ragged-span program (ISSUE 16 tentpole): every dispatch
        is a list of (row, query-span) pairs over the paged pool — each
        row carries (q_start, q_len, kv base, page-table slice) and
        per-token causal limits mask the padding, so plain decode is
        q_len=1 rows, verify q_len=k+1 rows (the spec variant below), a
        mixed step decode rows plus one prefill-slice row, and
        continuation chunks long-span rows.  ONE compile bucket family:
        (pow2 total-query-tokens, pow2 page window) replaces the
        per-phase decode/spec/mixed/chunk matrix.  Samples one token per
        dispatch row at its host-provided flat gather index."""
        key_ = ("rpa", tpb, w)
        if key_ in self._rpa_fns:
            return self._rpa_fns[key_]
        cfg = self.model_cfg
        max_len = self.max_len
        rope_max = self.max_len
        use_ragged = self._use_ragged and self._kernel_mesh() is None
        interp = self._interpret
        kv_q = bool(self._kv_quant)

        @partial(jax.jit, donate_argnums=(1, 2, 3, 4) if kv_q else (1, 2))
        def rpa_step(params, k_pages, v_pages, kscale, vscale, srows,
                     tokens, q_starts, q_lens, row_flat, base, gather_idx,
                     table, key, temps, tk, tp):
            nb = base.shape[0]
            rf = jnp.clip(row_flat, 0, nb - 1)
            off = jnp.arange(tpb) - q_starts[rf]
            # rope positions: each span token sits at consecutive absolute
            # positions from its row's own kv base (the context BEFORE
            # this dispatch); out-of-span tokens clamp to 0 — they are
            # masked from every real query and their writes park on the
            # null page, so the value never matters
            positions = jnp.clip(base[rf] + off, 0, max_len - 1)[None]
            out = forward_paged(
                params, cfg, tokens, positions, k_pages, v_pages, table,
                base, rope_max, use_ragged_kernel=use_ragged,
                interpret=interp, packed_last_idx=gather_idx,
                kv_scales=(kscale, vscale) if kv_q else None,
                scale_rows=srows if kv_q else None,
                spans=(q_starts, q_lens, row_flat),
            )
            logits, k_pages, v_pages = out[:3]
            if kv_q:
                kscale, vscale = out[3]
            # single step, no scan/vmap wrapper: sample_logits' lax.cond
            # fast paths are safe here (ops/sampling.py NOTE)
            nxt = sample_logits(logits[0], key, temps, tk, tp)
            return nxt, k_pages, v_pages, kscale, vscale

        logger.info("compiling ragged span step: B=%d token_bucket=%d "
                    "window=%d pages (ragged_kernel=%s)", self.B, tpb, w,
                    use_ragged)
        self._c_rpa_shapes.inc()
        self._rpa_fns[key_] = rpa_step
        return rpa_step

    def _get_rpa_spec_fn(self, tpb: int, w: int):
        """Spec-aware ragged span step (the spec x mixed unlock): decode
        rows carry (1 + spec_k)-token verify spans — the current token
        plus k n-gram drafts looked up IN-GRAPH from the device history
        buffer — while the piggybacked prefill slice rides the same
        dispatch, so speculation no longer yields during prefill windows
        and mixed steps stop marking rows spec-stale (the buffer appends
        in-graph).  Non-decode rows verify with n_valid=0: the machinery
        emits exactly ONE token from their last-span-position
        distribution — for the prefill row that is its sampled first
        token, through the same exact-distribution verify that keeps
        greedy outputs identical to every legacy path."""
        key_ = ("rpa_spec", tpb, w)
        if key_ in self._rpa_fns:
            return self._rpa_fns[key_]
        cfg = self.model_cfg
        max_len = self.max_len
        rope_max = self.max_len
        use_ragged = self._use_ragged and self._kernel_mesh() is None
        interp = self._interpret
        kv_q = bool(self._kv_quant)
        k = self.spec_k
        ngram = max(2, self.cfg.speculate_ngram)
        eos_id = self.tokenizer.eos_id

        if self._spec_tree:
            # Tree-spec variant (ISSUE 19 tentpole): decode rows carry a
            # (heal + 1 + W*k)-token span — leading "healing" re-sends of a
            # previously accepted non-first chain, the current token, then
            # W root-branching depth-k chains drafted IN-GRAPH by top-W
            # n-gram lookup.  Branch visibility follows parent pointers via
            # the host-built ancestor bitmasks (``anc``), rope positions are
            # depth-based via the host-built ``pos_off`` (write columns stay
            # span-offset — the caller's heal protocol fixes non-first-chain
            # columns on the next dispatch), and acceptance is the exact
            # sequential multi-candidate rule (ops/speculative.verify_tree),
            # so greedy outputs stay token-identical to every other path.
            # Same ("rpa_spec", tpb, w) bucket family — no new compile axis.
            W = self._spec_width
            from lmrs_tpu.ops.sampling import filtered_probs
            from lmrs_tpu.ops.speculative import (draft_tree_lookup,
                                                  verify_tree)

            @partial(jax.jit,
                     donate_argnums=(1, 2, 3, 4, 5) if kv_q else (1, 2, 3))
            def rpa_tree_step(params, k_pages, v_pages, buf, kscale, vscale,
                              srows, tokens, q_starts, q_lens, row_flat,
                              base, is_dec, cur_tok, hl, hoff, depth,
                              pos_off, anc, gather_idx, table, key, temps,
                              tk, tp):
                nb = base.shape[0]
                b_rows = jnp.arange(nb)[:, None]
                kvl = base + hl  # true kv_len (base excludes the heal span)
                # current token enters the history at its kv position plus
                # the row's cross-refresh hint offset (decode rows only)
                col0 = jnp.where(is_dec,
                                 jnp.minimum(kvl + hoff, max_len - 1),
                                 max_len)
                buf = buf.at[jnp.arange(nb), col0].set(cur_tok, mode="drop")
                chains, n_valid = draft_tree_lookup(
                    buf, kvl + hoff + 1, k, W, pad_id=eos_id, n=ngram,
                    depth=depth)
                n_valid = jnp.where(is_dec[:, None], n_valid, 0)
                # scatter [cur, chains] after each decode span's heal
                # prefix (heal tokens were host-built into ``tokens``)
                offs_t = jnp.arange(1 + W * k)[None, :]
                span_idx = jnp.where(is_dec[:, None],
                                     q_starts[:, None] + hl[:, None]
                                     + offs_t, tpb)
                tokens = tokens.at[0, span_idx].set(
                    jnp.concatenate(
                        [cur_tok[:, None], chains.reshape(nb, W * k)], 1),
                    mode="drop")
                rf = jnp.clip(row_flat, 0, nb - 1)
                positions = jnp.clip(base[rf] + pos_off, 0,
                                     max_len - 1)[None]
                out = forward_paged(
                    params, cfg, tokens, positions, k_pages, v_pages,
                    table, base, rope_max, use_ragged_kernel=use_ragged,
                    interpret=interp, packed_last_idx=gather_idx,
                    kv_scales=(kscale, vscale) if kv_q else None,
                    scale_rows=srows if kv_q else None,
                    spans=(q_starts, q_lens, row_flat), span_anc=anc,
                )
                logits, k_pages, v_pages = out[:3]
                if kv_q:
                    kscale, vscale = out[3]
                probs = jax.vmap(filtered_probs,
                                 in_axes=(1, None, None, None),
                                 out_axes=1)(
                    logits[0].reshape(nb, 1 + W * k, -1), temps, tk, tp)
                key, sub = jax.random.split(key)
                emit, count, chain, adepth = verify_tree(
                    probs, chains, n_valid, sub)
                # accepted tokens extend the history at hint-offset columns
                offs = jnp.arange(k + 1)[None, :]
                cols = jnp.minimum(kvl[:, None] + hoff[:, None] + 1 + offs,
                                   max_len - 1)
                cols = jnp.where((offs < count[:, None]) & is_dec[:, None],
                                 cols, max_len)
                buf = buf.at[b_rows, cols].set(emit, mode="drop")
                return (emit, count, chain, adepth, buf, k_pages, v_pages,
                        kscale, vscale)

            logger.info("compiling ragged span tree-spec step: B=%d "
                        "token_bucket=%d window=%d pages k=%d width=%d "
                        "(ragged_kernel=%s)", self.B, tpb, w, k, W,
                        use_ragged)
            self._c_rpa_shapes.inc()
            self._rpa_fns[key_] = rpa_tree_step
            return rpa_tree_step

        from lmrs_tpu.ops.sampling import filtered_probs
        from lmrs_tpu.ops.speculative import draft_lookup, verify_tokens

        @partial(jax.jit,
                 donate_argnums=(1, 2, 3, 4, 5) if kv_q else (1, 2, 3))
        def rpa_spec_step(params, k_pages, v_pages, buf, kscale, vscale,
                          srows, tokens, q_starts, q_lens, row_flat, base,
                          is_dec, cur_tok, gather_idx, table, key, temps,
                          tk, tp):
            nb = base.shape[0]
            b_rows = jnp.arange(nb)[:, None]
            offs = jnp.arange(k + 1)[None, :]
            # current token enters the history at index == its KV position
            # (decode rows only: other rows' columns land OOB and drop)
            col0 = jnp.where(is_dec, jnp.minimum(base, max_len - 1),
                             max_len)
            buf = buf.at[jnp.arange(nb), col0].set(cur_tok, mode="drop")
            draft, n_valid = draft_lookup(buf, base + 1, k, pad_id=eos_id,
                                          n=ngram)
            n_valid = jnp.where(is_dec, n_valid, 0)
            # scatter [current, drafts] into the decode spans of the flat
            # token row (prefill/pad rows keep their host-built tokens)
            span_idx = jnp.where(is_dec[:, None],
                                 q_starts[:, None] + offs, tpb)
            tokens = tokens.at[0, span_idx].set(
                jnp.concatenate([cur_tok[:, None], draft], axis=1),
                mode="drop")
            rf = jnp.clip(row_flat, 0, nb - 1)
            off = jnp.arange(tpb) - q_starts[rf]
            positions = jnp.clip(base[rf] + off, 0, max_len - 1)[None]
            out = forward_paged(
                params, cfg, tokens, positions, k_pages, v_pages, table,
                base, rope_max, use_ragged_kernel=use_ragged,
                interpret=interp, packed_last_idx=gather_idx,
                kv_scales=(kscale, vscale) if kv_q else None,
                scale_rows=srows if kv_q else None,
                spans=(q_starts, q_lens, row_flat),
            )
            logits, k_pages, v_pages = out[:3]
            if kv_q:
                kscale, vscale = out[3]
            # filtered_probs is deliberately cond-free, so this vmap over
            # the token axis is safe (ops/sampling.py NOTE)
            probs = jax.vmap(filtered_probs, in_axes=(1, None, None, None),
                             out_axes=1)(
                logits[0].reshape(nb, k + 1, -1), temps, tk, tp)
            key, sub = jax.random.split(key)
            emit, count = verify_tokens(probs, draft, n_valid, sub)
            # accepted tokens extend the history (decode rows only; the
            # final emitted token lands exactly at the next step's write
            # index — idempotent, same as the spec scan)
            cols = jnp.minimum(base[:, None] + 1 + offs, max_len - 1)
            cols = jnp.where((offs < count[:, None]) & is_dec[:, None],
                             cols, max_len)
            buf = buf.at[b_rows, cols].set(emit, mode="drop")
            return emit, count, buf, k_pages, v_pages, kscale, vscale

        logger.info("compiling ragged span spec step: B=%d token_bucket=%d "
                    "window=%d pages k=%d (ragged_kernel=%s)", self.B, tpb,
                    w, k, use_ragged)
        self._c_rpa_shapes.inc()
        self._rpa_fns[key_] = rpa_spec_step
        return rpa_spec_step

    def _tree_span_template(self, hl: int):
        """(pos_off, ancestor-bitmask) template for a tree-spec decode
        span with ``hl`` leading heal tokens: span-local layout is
        [heal_0..heal_{hl-1}, cur, chain_0 (k), ..., chain_{W-1} (k)].
        Heal tokens and cur keep the anc == 0 sentinel (plain causal
        rule); chain c's node j sees the heal+cur prefix plus its own
        chain up to itself.  Rope positions are DEPTH-based — chain c
        node j sits at kv offset hl+1+j regardless of c — while K/V
        writes land at span-offset columns (the heal protocol's whole
        reason to exist).  Bit 31 is reachable (hl=k, the capacity
        bound), so masks build in uint32 and reinterpret as int32."""
        tmpl = self._spec_tmpl.get(hl)
        if tmpl is None:
            W, k = self._spec_width, self.spec_k
            n = hl + 1 + W * k
            pos = np.zeros((n,), np.int32)
            anc = np.zeros((n,), np.uint32)
            pos[: hl + 1] = np.arange(hl + 1)
            prefix = (1 << (hl + 1)) - 1
            for c in range(W):
                bits = prefix
                for j in range(k):
                    o = hl + 1 + c * k + j
                    pos[o] = hl + 1 + j
                    bits |= 1 << o
                    anc[o] = bits
            self._spec_tmpl[hl] = tmpl = (pos, anc.view(np.int32))
        return tmpl

    def _spec_ramp(self, st: _SlotState, depth_used: int) -> int:
        """Next-step draft depth for one row off its acceptance EMA
        (LMRS_SPEC_ADAPTIVE): accept streaks deepen the chains toward
        spec_k, collapse ramps down to OFF, and an off row re-probes at
        half depth every 8 steps so a workload shift can re-arm it."""
        k = self.spec_k
        if depth_used == 0:
            st.spec_probe += 1
            if st.spec_probe >= 8:
                st.spec_probe = 0
                st.spec_ema = 0.5
                return max(1, k // 2)
            return 0
        st.spec_probe = 0
        if st.spec_ema >= 0.6:
            return min(depth_used + 1, k)
        if st.spec_ema < 0.2:
            return 0
        if st.spec_ema < 0.35:
            return max(depth_used - 1, 1)
        return depth_used

    def _rpa_mixed_iteration(self, pf, slots, queue, results, fresh,
                             kv_lens, last_tok, active, temps, top_k,
                             top_p, t_enq, last_block_t):
        """One ragged-span mixed step (the RPA default): every live decode
        row advances as a span — ONE token plain, a (1 + spec_k)-token
        verify span under speculation — and one prefilling slot's next
        slice rides the SAME dispatch as a long span row.  Two legacy
        composition gates are gone here: int8 KV pools mix (a fresh-start
        slice owns its slot's frozen scales through the span descriptor,
        every other row clamps to them — the PERF.md follow-up) and spec
        blocks no longer yield during prefill windows.  Same
        (handled, last_block_t) contract as _mixed_iteration."""
        spec = bool(self.spec_k)
        tree = spec and self._spec_tree
        k = self.spec_k
        W = self._spec_width

        def rearm(stalled):
            for b in stalled:  # stalled rows rejoin the next dispatch
                if slots[b] is not None:
                    active[b] = True

        adv = (1 + W * k) if tree else (1 + k if spec else 1)
        stalled = self._ensure_decode_capacity(slots, queue, kv_lens,
                                               last_tok, active,
                                               extra_tokens=adv)
        rows = [b for b in range(self.B)
                if slots[b] is not None and active[b]
                and slots[b].phase == "decode"]
        depth_of: dict[int, int] = {}
        hl_of: dict[int, int] = {}
        pressure = False
        if tree:
            # page pressure collapses draft depth to 0 for THIS dispatch
            # (the span family still runs when a heal is pending);
            # acceptance collapse ramps per-row depth to 0 via _spec_ramp.
            # When every row sits at depth 0 with no heal pending, the
            # step routes through the PLAIN span program (adv=1) and the
            # rows are marked spec-stale (the history buffer misses the
            # append).
            pressure = (self._spec_adaptive
                        and self.cache.allocator.free_count < self.B)
            for b in rows:
                st = slots[b]
                hl_of[b] = len(st.spec_heal)
                depth_of[b] = 0 if pressure else min(st.spec_depth, k)
            spec_live = any(depth_of[b] > 0 or hl_of[b] > 0 for b in rows)
        else:
            spec_live = spec
        use_spec = spec and spec_live
        tree_live = tree and use_spec
        if not use_spec:
            adv = 1

        def q_of(b):
            return hl_of[b] + adv if tree_live else adv

        dec_tokens = sum(q_of(b) for b in rows)
        budget_left = self.mixed_token_budget - dec_tokens
        if not rows or (pf is not None and budget_left < 16):
            rearm(stalled)
            return False, last_block_t
        if use_spec:
            with self._an.seg("draft"):
                if self._spec_buf is None:
                    self._spec_buf = jnp.zeros((self.B, self.max_len),
                                               jnp.int32)
                if self._spec_stale:
                    # same lazy re-seed as _spec_decode_block: rows
                    # advanced outside the device-appended paths since
                    # the last verify
                    for b in sorted(self._spec_stale):
                        if (slots[b] is not None
                                and slots[b].phase == "decode"):
                            self.seed_history(b, slots[b])
                    self._spec_stale.clear()

        if pf is not None:
            st_pf = slots[pf]
            pos = st_pf.prefill_pos
            c = min(len(st_pf.prompt_ids) - pos, budget_left,
                    self.prefill_chunk)
            is_final = pos + c >= len(st_pf.prompt_ids)
        else:
            # pure-decode tree-spec step: the alternating path routes
            # here under LMRS_SPEC_TREE so heal/hint column state never
            # meets the legacy spec block
            st_pf, pos, c, is_final = None, 0, 0, False

        q_lens_np = np.zeros((self.B,), np.int32)
        base_np = np.zeros((self.B,), np.int32)
        is_dec_np = np.zeros((self.B,), bool)
        hl_np = np.zeros((self.B,), np.int32)
        hoff_np = np.zeros((self.B,), np.int32)
        depth_np = np.zeros((self.B,), np.int32)
        table_rows = [None] * self.B
        max_pages = 1
        live_tokens = 0
        for b in rows:
            st = slots[b]
            q_lens_np[b] = q_of(b)
            # a heal span re-sends a non-first accepted chain's tokens as
            # leading queries with base = kv_len - heal: their K/V rewrite
            # at the true columns (rope intact) before any read this
            # dispatch — write-before-read in the XLA span path
            base_np[b] = st.kv_len - (hl_of[b] if tree_live else 0)
            is_dec_np[b] = True
            if tree_live:
                hl_np[b] = hl_of[b]
                hoff_np[b] = st.spec_hoff
                depth_np[b] = depth_of[b]
            table_rows[b] = st.seq
            live_tokens += st.kv_len
            max_pages = max(max_pages,
                            self.cache.pages_needed(st.kv_len + adv))
        if pf is not None:
            q_lens_np[pf] = c
            base_np[pf] = pos
            table_rows[pf] = st_pf.seq
            max_pages = max(max_pages, self.cache.pages_needed(pos + c))
        w = min(_pow2_bucket(max_pages, 4), self.cache.max_pages_per_slot)
        table = self.cache.page_table_array(table_rows)

        # host-side span packing: QT-aligned starts, pow2 total bucket —
        # the padding complement is what lmrs_rpa_span_tokens measures
        q_starts_np, total = pack_spans(q_lens_np)
        tpb = _pow2_bucket(total, 16)
        tokens_np = np.zeros((1, tpb), np.int32)
        row_flat_np = np.full((tpb,), self.B, np.int32)
        pos_off_np = anc_np = None
        if tree_live:
            pos_off_np = np.zeros((tpb,), np.int32)
            anc_np = np.zeros((tpb,), np.int32)
        for b in rows:
            s = q_starts_np[b]
            tokens_np[0, s] = last_tok[b]
            row_flat_np[s: s + q_lens_np[b]] = b
            if tree_live:
                # heal tokens ride host-side (cur + chains scatter
                # in-graph after them); positions and ancestor bitmasks
                # come from the per-heal-length span template
                hl_b = hl_of[b]
                tokens_np[0, s: s + hl_b] = slots[b].spec_heal
                t_pos, t_anc = self._tree_span_template(hl_b)
                pos_off_np[s: s + len(t_pos)] = t_pos
                anc_np[s: s + len(t_anc)] = t_anc
        if pf is not None:
            tokens_np[0, q_starts_np[pf]: q_starts_np[pf] + c] = \
                st_pf.prompt_ids[pos: pos + c]
            row_flat_np[q_starts_np[pf]: q_starts_np[pf] + c] = pf
            if tree_live:
                # the prefill slice keeps linear positions and the
                # anc == 0 sentinel (plain causal rule — slices can be
                # longer than the 32-offset bitmask)
                pos_off_np[q_starts_np[pf]: q_starts_np[pf] + c] = \
                    np.arange(c, dtype=np.int32)
        last_of = (q_starts_np + np.maximum(q_lens_np, 1) - 1).astype(
            np.int32)
        if tree_live:
            offs = np.arange(1 + W * k)[None, :]
            gidx = np.where(is_dec_np[:, None],
                            q_starts_np[:, None] + hl_np[:, None] + offs,
                            last_of[:, None]).reshape(-1).astype(np.int32)
        elif use_spec:
            offs = np.arange(self.spec_k + 1)[None, :]
            gidx = np.where(is_dec_np[:, None],
                            q_starts_np[:, None] + offs,
                            last_of[:, None]).reshape(-1).astype(np.int32)
        else:
            gidx = last_of

        real = dec_tokens + c
        # bucket economics (obs/anatomy.py): this dispatch pays for a
        # tpb-token bucket but carries ``real`` span tokens
        self._an.note_bucket(tpb, w, real)
        self._h_occupancy.observe(len(rows) / self.B)
        self._c_decode_dispatches.inc()
        self._h_mixed_fill.observe(real / self.mixed_token_budget)
        self._h_rpa_span.observe(real)
        now = time.time()
        if last_block_t is not None:
            self._h_block_gap.observe(now - last_block_t)
            self._slo.observe_gap(now - last_block_t)
        last_block_t = now
        flops = 0.0
        if pf is not None:
            self._c_piggybacked.inc(c)
            self._c_prefill_tokens.inc(c)
            self._h_prefill_batch.observe(c)
            flops = self._perf.prefill_flops(c, kv_start=pos)
            if self._tr:
                self._tr.instant("prefill_dispatch",
                                 args={"rows": 1, "tokens": c,
                                       "bucket": tpb, "mixed": True,
                                       "rpa": True,
                                       "flops_g": round(flops / 1e9, 3)})
            st_pf.prefill_pos = pos + c
        if tree_live:
            self._c_spec_tree_disp.inc()

        self._key, sub = jax.random.split(self._key)
        srows = jnp.arange(self.B, dtype=jnp.int32)
        common = (jnp.asarray(tokens_np), jnp.asarray(q_starts_np),
                  jnp.asarray(q_lens_np), jnp.asarray(row_flat_np),
                  jnp.asarray(base_np))
        key_ = ("rpa_spec", tpb, w) if use_spec else ("rpa", tpb, w)
        warm = key_ in self._ran_ok
        if not warm:
            self._wd_grace_cold()
        t_disp = time.time()

        def dispatch():
            if tree_live:
                return self._get_rpa_spec_fn(tpb, w)(
                    self.params, self.cache.k, self.cache.v,
                    self._spec_buf, self.kscale, self.vscale, srows,
                    *common, jnp.asarray(is_dec_np),
                    jnp.asarray(last_tok), jnp.asarray(hl_np),
                    jnp.asarray(hoff_np), jnp.asarray(depth_np),
                    jnp.asarray(pos_off_np), jnp.asarray(anc_np),
                    jnp.asarray(gidx), jnp.asarray(table[:, :w]), sub,
                    jnp.asarray(temps), jnp.asarray(top_k),
                    jnp.asarray(top_p))
            if use_spec:
                return self._get_rpa_spec_fn(tpb, w)(
                    self.params, self.cache.k, self.cache.v,
                    self._spec_buf, self.kscale, self.vscale, srows,
                    *common, jnp.asarray(is_dec_np),
                    jnp.asarray(last_tok), jnp.asarray(gidx),
                    jnp.asarray(table[:, :w]), sub, jnp.asarray(temps),
                    jnp.asarray(top_k), jnp.asarray(top_p))
            return self._get_rpa_fn(tpb, w)(
                self.params, self.cache.k, self.cache.v,
                self.kscale, self.vscale, srows,
                *common, jnp.asarray(gidx),
                jnp.asarray(table[:, :w]), sub, jnp.asarray(temps),
                jnp.asarray(top_k), jnp.asarray(top_p))

        with self._an.seg("dispatch"):
            try:
                out = dispatch()
            except Exception:
                # the shared first-run-lowering contract: degrade only
                # before this shape has ever run (donation happens at
                # execution, so the args are still valid); proven shapes
                # re-raise
                if not self._use_ragged or key_ in self._ran_ok:
                    raise
                logger.warning("ragged span kernel failed to lower; "
                               "falling back to the XLA span path",
                               exc_info=True)
                self._invalidate_compiled()
                out = dispatch()
        if not warm:
            # cold key: the dispatch call just blocked on the XLA compile
            # — bill it to this bucket's compile economics
            self._an.note_compile(tpb, w, time.time() - t_disp)
        self._note_ran_ok(key_)
        with self._an.seg("fetch"):
            if tree_live:
                (emit, count, chain, adepth, self._spec_buf, self.cache.k,
                 self.cache.v, ks, vs) = out
                emit, count, chain, adepth = self._timed_get(
                    (emit, count, chain, adepth))
                emit, count = np.asarray(emit), np.asarray(count)
                chain, adepth = np.asarray(chain), np.asarray(adepth)
            elif use_spec:
                (emit, count, self._spec_buf, self.cache.k, self.cache.v,
                 ks, vs) = out
                emit, count = self._timed_get((emit, count))
                emit, count = np.asarray(emit), np.asarray(count)
            else:
                nxt, self.cache.k, self.cache.v, ks, vs = out
                nxt = np.asarray(self._timed_get(nxt))
        if self._kv_quant:
            self.kscale, self.vscale = ks, vs
        t_done = time.time()

        with self._an.seg("finish"):
            # exact-split attribution with SPAN-LEVEL token counts: the
            # decode side of a span step is adv tokens per live row, not
            # one
            extra_flops, cold_pf = self._consume_prefill_attr()
            nb = self._perf.note_mixed_step(
                t_disp, t_done, len(rows), live_tokens, flops + extra_flops,
                warm=warm and not cold_pf, span_tokens=dec_tokens)
            self._attr_last_gb = round(nb / 1e9, 3)
            if self._cost.enabled:
                dcost, pcost = self._roofline_phase_costs(
                    nb, flops + extra_flops)
                self._cost.note_step(
                    max(0.0, t_done - t_disp),
                    decode_rows=[(slots[b].req,
                                  int(count[b]) if use_spec else 1,
                                  len(slots[b].seq.pages)) for b in rows],
                    prefill_rows=(self._consume_prefill_cost()
                                  + ([(st_pf.req, c, flops)]
                                     if pf is not None else [])),
                    decode_cost_s=dcost, prefill_cost_s=pcost)

            for b in rows:
                st = slots[b]
                if use_spec:
                    cnt = int(count[b])
                    new = [int(t) for t in emit[b, :cnt]]
                    self._c_spec_accepted.inc(max(0, cnt - 1))
                    if cnt > 1:
                        self._cost.note_saved(st.req, spec_tokens=cnt - 1)
                    if tree_live:
                        cs, ad = int(chain[b]), int(adepth[b])
                        # a non-first accepted chain's drafts sit at THAT
                        # chain's span-offset KV columns: re-send them as
                        # the next span's heal prefix so they rewrite at
                        # the true columns
                        st.spec_heal = (new[:ad] if cs > 0 and ad > 0
                                        else [])
                        d_used = depth_of[b]
                        self._h_spec_nodes.observe(1 + W * d_used)
                        self._h_spec_depth.observe(ad)
                        if not pressure:
                            if d_used > 0:
                                st.spec_ema = (0.8 * st.spec_ema
                                               + 0.2 * ad / d_used)
                            if self._spec_adaptive:
                                st.spec_depth = self._spec_ramp(st, d_used)
                else:
                    new = [int(nxt[b])]
                    if tree:
                        # plain-routed idle tree step: the history buffer
                        # missed this append — re-seed before the next
                        # spec-live dispatch; the depth-0 probe timer
                        # keeps ticking so speculation can re-arm
                        self._spec_stale.add(b)
                        if self._spec_adaptive and not pressure:
                            st.spec_depth = self._spec_ramp(st, 0)
                st.generated.extend(new)
                st.kv_len += len(new)
                kv_lens[b] = st.kv_len
                last_tok[b] = st.generated[-1] if st.generated else 0
                self._c_decode_tokens.inc(len(new))
                if self._tr:
                    self._tr.instant("decode_block", ts=now,
                                     tid=self._tid(st.req),
                                     args={"tokens": len(new)})
                self._maybe_finish(b, slots, results, active, fresh,
                                   kv_lens, last_tok)
            if is_final:
                # the slice completed the prompt: enter decode with the
                # first token this very step sampled at its last span
                # position
                st = st_pf
                st.phase = "decode"
                st.t_decode_start = time.time()
                if self._tr:
                    self._tr.complete("prefill", st.t_admit,
                                      st.t_decode_start,
                                      tid=self._tid(st.req),
                                      args={"prompt_tokens":
                                            len(st.prompt_ids)})
                st.kv_len = len(st.prompt_ids)
                kv_lens[pf] = st.kv_len
                active[pf] = True
                self._cache_insert(st)
                tok0 = int(emit[pf, 0]) if use_spec else int(nxt[pf])
                st.generated.append(tok0)
                self._note_first_token(st, t_enq)
                last_tok[pf] = tok0
                if spec:
                    # the verify graph cannot have appended pf's history
                    # (its span was a prompt slice): seed once at the
                    # prefill -> decode transition, like any admission
                    with self._an.seg("draft"):
                        self.seed_history(pf, st)
                self._maybe_finish(pf, slots, results, active, fresh,
                                   kv_lens, last_tok)
            if self._tr:
                self._tr.complete("decode_block", now, time.time(),
                                  args={"active": len(rows),
                                        "tokens": dec_tokens,
                                        "hbm_gb": self._attr_last_gb,
                                        "mixed": pf is not None,
                                        "rpa": True,
                                        "spec_tree": tree_live,
                                        "prefill_tokens": c})
            rearm(stalled)
        return True, last_block_t

    # ------------------------------------------------------------- prefill

    def _advance_prefills(self, slots) -> list[tuple[object, list[tuple[int, int]]]]:
        """Advance every prefilling slot by one prompt chunk and return
        [(tok0_device_array, [(slot, row)])] for the slots whose whole prompt
        is now in KV.  The first-token arrays are NOT fetched — the caller
        threads them into the decode dispatch and fetches them with the
        decode block's own transfer (each device_get on a tunneled chip
        costs a full host-link RTT).

        Prompts that fit one chunk take the fresh-prefill program (attends
        the chunk directly); longer prompts run the windowed continuation
        program per chunk (attends the page window, which includes earlier
        chunks' KV).  Chunks with the same (program, bucket) run as ONE
        batched dispatch; the batch dim is either 1 or B (padded) so each
        shape compiles at most twice — XLA compiles are seconds-long and a
        per-group-size shape zoo would thrash the cache at runtime.
        """
        groups: dict[tuple, list] = {}
        fresh_pack: list[tuple[int, object, list[int]]] = []
        for b in range(self.B):
            st = slots[b]
            if st is None or st.phase != "prefill":
                continue
            ids = st.prompt_ids
            pos = st.prefill_pos
            chunk = ids[pos: pos + self.prefill_chunk]
            is_final = pos + len(chunk) >= len(ids)
            fresh = pos == 0 and is_final  # whole prompt in one dispatch
            # long prompts under an sp mesh go to the ring path un-packed
            if (fresh and self._pack_prefill
                    and not (self._use_ring and len(chunk) >= self._ring_min)):
                fresh_pack.append((b, st, chunk))
                continue
            s_bucket = min(_pow2_bucket(len(chunk), 64), self.max_len)
            # Ring routing is decided by the REAL chunk length, not the
            # bucket (ADVICE r2): a 600-token prompt bucketing to 1024 must
            # not pay ppermute hops to ring-shard mostly-padding.  Ring
            # buckets round up to a multiple of sp so every shard is equal —
            # guaranteed <= max_len by the constructor divisibility check.
            ring = (fresh and self._use_ring
                    and len(chunk) >= self._ring_min)
            if ring:
                s_bucket = min(-(-s_bucket // self._sp) * self._sp,
                               self.max_len)
            if fresh:
                w = self.cache.max_pages_per_slot
            else:
                need_pages = self.cache.pages_needed(pos + len(chunk))
                w = min(_pow2_bucket(need_pages, 4), self.cache.max_pages_per_slot)
            groups.setdefault((fresh, s_bucket, w, ring), []).append(
                (b, st, chunk, pos, is_final))

        # dispatch each group (async), collecting unfetched [N] token arrays
        pending: list[tuple[object, list[tuple[int, int]]]] = []

        # packed fresh prompts: bins of <= max_len tokens, each ONE [1, S]
        # dispatch; a bin left with a single prompt takes the per-prompt
        # program (identical work, already compiled for the common case).
        # Under an sp mesh, bins cap at _ring_min so packed rows stay short
        # enough that skipping the ring is the right call for them.
        cap = self._ring_min if self._use_ring else self.max_len
        for bin_items in self._pack_bins(fresh_pack, cap):
            if len(bin_items) == 1:
                b, st, chunk = bin_items[0]
                s_bucket = min(_pow2_bucket(len(chunk), 64), self.max_len)
                # bin items are < _ring_min by the fresh_pack gate: no ring
                groups.setdefault(
                    (True, s_bucket, self.cache.max_pages_per_slot, False), []
                ).append((b, st, chunk, 0, True))
            else:
                pending.append(self._dispatch_packed(bin_items))
        for (fresh, s_bucket, w, ring), items in groups.items():
            if (not fresh and self._rpa and self._use_ragged
                    and self._kernel_mesh() is None):
                # windowed continuation chunks ride the unified span
                # program: the per-(s_bucket, w) chunked-prefill matrix
                # (_prefill_window_fns) never compiles under RPA — chunks
                # share the mixed step's (token bucket, window) family
                entry = self._dispatch_rpa_chunks(items)
                if entry[1]:
                    pending.append(entry)
                continue
            n = 1 if len(items) == 1 else self.B
            tokens = np.full((n, s_bucket), self.tokenizer.pad_id, np.int32)
            start = np.zeros((n,), np.int32)
            length = np.ones((n,), np.int32)  # pad rows: 1 token on the null page
            alloc = np.full((n,), self.cache.page_size, np.int32)
            table = np.zeros((n, self.cache.max_pages_per_slot), np.int32)
            temps = np.ones((n,), np.float32)
            tks = np.zeros((n,), np.int32)
            tps = np.ones((n,), np.float32)
            # dispatch row -> slot id for the scale buffers: pad rows point
            # one past the end (scatter drops them, gather clamps — their
            # writes land on the null page anyway)
            srows = np.full((n,), self.B, np.int32)
            table[: len(items)] = self.cache.page_table_array(
                [st.seq for _, st, _, _, _ in items])
            for row, (b, st, chunk, pos, _) in enumerate(items):
                tokens[row, : len(chunk)] = chunk
                start[row] = pos
                length[row] = len(chunk)
                alloc[row] = st.seq.capacity(self.cache.page_size)
                temps[row] = st.req.temperature
                tks[row] = st.req.top_k
                tps[row] = min(max(st.req.top_p, 0.0), 1.0)
                srows[row] = b
                st.prefill_pos = pos + len(chunk)
                self._c_prefill_tokens.inc(len(chunk))
            batch_tokens = sum(len(c) for _, _, c, _, _ in items)
            self._h_prefill_batch.observe(batch_tokens)
            # roofline attribution: real-token FLOPs of this dispatch
            # (window chunks additionally attend their cached prefix),
            # consumed by whichever block fetches the wave's results —
            # the ledger keeps the same work per ROW for its split
            flops = 0.0
            for _, st_i, c_i, p_i, _ in items:
                f_i = self._perf.prefill_flops(len(c_i), kv_start=p_i)
                flops += f_i
                if self._cost.enabled:
                    self._cost_pending_prefill.append(
                        (st_i.req, len(c_i), f_i))
            self._attr_pending_flops += flops
            if self._tr:
                self._tr.instant("prefill_dispatch",
                                 args={"rows": len(items),
                                       "tokens": batch_tokens,
                                       "bucket": s_bucket,
                                       "fresh": bool(fresh),
                                       "flops_g": round(flops / 1e9, 3)})
            self._key, sub = jax.random.split(self._key)
            args = (
                self.params, self.cache.k, self.cache.v,
                self.kscale, self.vscale, jnp.asarray(srows),
                jnp.asarray(tokens), jnp.asarray(start), jnp.asarray(length),
                jnp.asarray(alloc), jnp.asarray(table[:, :w]), sub,
                jnp.asarray(temps), jnp.asarray(tks), jnp.asarray(tps),
            )
            key_ = ("prefill", fresh, s_bucket, w, ring)
            if key_ not in self._ran_ok:
                self._attr_prefill_cold = True  # compiling: no MFU sample
                self._wd_grace_cold()
            with self._an.seg("dispatch"):
                try:
                    fn = (self._get_prefill_fn(s_bucket, use_ring=ring)
                          if fresh
                          else self._get_prefill_window_fn(s_bucket, w))
                    tok0, self.cache.k, self.cache.v, \
                        self.kscale, self.vscale = fn(*args)
                except Exception:
                    # compile-time lowering failure of the flash prefill
                    # kernel: rebuild without it and retry (cache buffers
                    # were not yet donated — donation happens at
                    # execution).  Anything after a successful run of this
                    # shape is a real error: re-raise.
                    if not self._use_flash or key_ in self._ran_ok:
                        raise
                    logger.warning("flash prefill kernel failed to lower; "
                                   "falling back to XLA attention",
                                   exc_info=True)
                    self._use_flash = False
                    self._prefill_fns.clear()
                    self._prefill_window_fns.clear()
                    self._packed_prefill_fns.clear()
                    fn = (self._get_prefill_fn(s_bucket, use_ring=ring)
                          if fresh
                          else self._get_prefill_window_fn(s_bucket, w))
                    tok0, self.cache.k, self.cache.v, \
                        self.kscale, self.vscale = fn(*args)
            self._note_ran_ok(key_)
            rows = [(b, row) for row, (b, _, _, _, is_final) in enumerate(items)
                    if is_final]
            if rows:
                pending.append((tok0, rows))

        return pending

    def _dispatch_rpa_chunks(self, items) -> tuple[object, list]:
        """Windowed continuation chunks as ragged SPANS (LMRS_RPA with the
        kernel armed): every chunk is one long-span row of a single
        unified dispatch.  Returns the ``(tok0_device_array, [(slot,
        row)])`` pending-entry contract of ``_advance_prefills``; the
        sampled array is B-wide and indexed by SLOT (rows ARE slots
        here).  A first-run lowering failure degrades through
        ``_invalidate_compiled`` and retries on the XLA span path — the
        rare-case memory cost of its window materialization is accepted
        for the retry only; subsequent waves route back through the
        legacy window programs because ``_use_ragged`` is now off."""
        q_lens_np = np.zeros((self.B,), np.int32)
        base_np = np.zeros((self.B,), np.int32)
        is_final_rows: list[tuple[int, int]] = []
        table_rows = [None] * self.B
        max_pages = 1
        batch_tokens = 0
        flops = 0.0
        for (b, st, chunk, pos, is_final) in items:
            q_lens_np[b] = len(chunk)
            base_np[b] = pos
            table_rows[b] = st.seq
            max_pages = max(max_pages,
                            self.cache.pages_needed(pos + len(chunk)))
            batch_tokens += len(chunk)
            if is_final:
                is_final_rows.append((b, b))
        w = min(_pow2_bucket(max_pages, 4), self.cache.max_pages_per_slot)
        table = self.cache.page_table_array(table_rows)
        q_starts_np, total = pack_spans(q_lens_np)
        tpb = _pow2_bucket(total, 16)
        tokens_np = np.zeros((1, tpb), np.int32)
        row_flat_np = np.full((tpb,), self.B, np.int32)
        temps = np.ones((self.B,), np.float32)
        tks = np.zeros((self.B,), np.int32)
        tps = np.ones((self.B,), np.float32)
        for (b, st, chunk, pos, _) in items:
            s, c = int(q_starts_np[b]), len(chunk)
            tokens_np[0, s: s + c] = chunk
            row_flat_np[s: s + c] = b
            temps[b] = st.req.temperature
            tks[b] = st.req.top_k
            tps[b] = min(max(st.req.top_p, 0.0), 1.0)
            st.prefill_pos = pos + c
            self._c_prefill_tokens.inc(c)
            f_i = self._perf.prefill_flops(c, kv_start=pos)
            flops += f_i
            if self._cost.enabled:
                self._cost_pending_prefill.append((st.req, c, f_i))
        gidx = (q_starts_np + np.maximum(q_lens_np, 1) - 1).astype(np.int32)
        self._h_prefill_batch.observe(batch_tokens)
        self._h_rpa_span.observe(batch_tokens)
        self._attr_pending_flops += flops
        if self._tr:
            self._tr.instant("prefill_dispatch",
                             args={"rows": len(items),
                                   "tokens": batch_tokens, "bucket": tpb,
                                   "fresh": False, "rpa": True,
                                   "flops_g": round(flops / 1e9, 3)})
        self._key, sub = jax.random.split(self._key)
        srows = jnp.arange(self.B, dtype=jnp.int32)
        args = (self.params, self.cache.k, self.cache.v,
                self.kscale, self.vscale, srows,
                jnp.asarray(tokens_np), jnp.asarray(q_starts_np),
                jnp.asarray(q_lens_np), jnp.asarray(row_flat_np),
                jnp.asarray(base_np), jnp.asarray(gidx),
                jnp.asarray(table[:, :w]), sub, jnp.asarray(temps),
                jnp.asarray(tks), jnp.asarray(tps))
        key_ = ("rpa", tpb, w)
        warm = key_ in self._ran_ok
        if not warm:
            self._attr_prefill_cold = True  # compiling: no MFU sample
            self._wd_grace_cold()
        # bucket economics: chunked-prefill spans ride the same ragged
        # (token bucket, page window) family as the mixed step — real
        # tokens vs the tpb pad tail is the padding-waste trade PR 16 made
        self._an.note_bucket(tpb, w, batch_tokens)
        t_disp = time.time()
        with self._an.seg("dispatch"):
            try:
                tok0, self.cache.k, self.cache.v, ks, vs = \
                    self._get_rpa_fn(tpb, w)(*args)
            except Exception:
                if not self._use_ragged or key_ in self._ran_ok:
                    raise
                logger.warning("ragged span kernel failed to lower; "
                               "falling back to the XLA span path",
                               exc_info=True)
                self._invalidate_compiled()
                tok0, self.cache.k, self.cache.v, ks, vs = \
                    self._get_rpa_fn(tpb, w)(*args)
        if not warm:
            # cold-key dispatch wall ~= compile time (tracing + lowering
            # block the call; execution is async)
            self._an.note_compile(tpb, w, time.time() - t_disp)
        self._note_ran_ok(key_)
        if self._kv_quant:
            self.kscale, self.vscale = ks, vs
        return tok0, is_final_rows

    @staticmethod
    def _pack_bins(items: list, capacity: int) -> list[list]:
        """First-fit-decreasing bin packing of (slot, state, chunk) items by
        chunk length.  Segment count per bin is bounded by B (items are
        slots), so the packed program's shapes stay (s_bucket, B)."""
        bins: list[tuple[int, list]] = []  # (used, items)
        for it in sorted(items, key=lambda t: -len(t[2])):
            n = len(it[2])
            for i, (used, lst) in enumerate(bins):
                if used + n <= capacity:
                    lst.append(it)
                    bins[i] = (used + n, lst)
                    break
            else:
                bins.append((n, [it]))
        return [lst for _, lst in bins]

    def _dispatch_packed(self, items: list) -> tuple[object, list[tuple[int, int]]]:
        """One packed prefill dispatch: concatenate the items' prompts into a
        [1, S] row (segment ids, within-segment positions, host-built
        per-token page ids) and sample each segment's first token from its
        last row.  Returns the (unfetched tok0 [B], [(slot, segment)])
        pending entry, same contract as the per-prompt programs."""
        ps = self.cache.page_size
        s_real = sum(len(c) for _, _, c in items)
        # bins are capped at max_len tokens, so the clamp never truncates.
        # Bucket floor max_len//4: tail bins otherwise mint a fresh pow2
        # shape per wave, and at real model sizes each novel shape is a
        # multi-second XLA compile mid-run (same tradeoff as the quarter-
        # step bucket NOTE above) — at most 3 packed shapes ever compile.
        s_bucket = min(max(_pow2_bucket(s_real, 64), self.max_len // 4),
                       self.max_len)
        tokens = np.full((1, s_bucket), self.tokenizer.pad_id, np.int32)
        positions = np.zeros((1, s_bucket), np.int32)
        seg_ids = np.full((1, s_bucket), -1, np.int32)  # pad: matches nothing
        token_pages = np.zeros((1, s_bucket), np.int32)  # pad -> null page
        last_idx = np.zeros((self.B,), np.int32)
        temps = np.ones((self.B,), np.float32)
        tks = np.zeros((self.B,), np.int32)
        tps = np.ones((self.B,), np.float32)
        # segment -> slot for the KV scale buffers (int8 KV): unused
        # segments point one past the end (scale scatter drops them)
        srows = np.full((self.B,), self.B, np.int32)
        off = 0
        for si, (b, st, chunk) in enumerate(items):
            n = len(chunk)
            within = np.arange(n, dtype=np.int32)
            tokens[0, off: off + n] = chunk
            positions[0, off: off + n] = within
            seg_ids[0, off: off + n] = si
            token_pages[0, off: off + n] = np.asarray(
                st.seq.pages, np.int32)[within // ps]
            last_idx[si] = off + n - 1
            temps[si] = st.req.temperature
            tks[si] = st.req.top_k
            tps[si] = min(max(st.req.top_p, 0.0), 1.0)
            srows[si] = b
            st.prefill_pos = n
            self._c_prefill_tokens.inc(n)
            off += n
        self._h_prefill_batch.observe(s_real)
        flops = 0.0
        for _, st_i, c_i in items:
            f_i = self._perf.prefill_flops(len(c_i))
            flops += f_i
            if self._cost.enabled:
                self._cost_pending_prefill.append((st_i.req, len(c_i), f_i))
        self._attr_pending_flops += flops
        if self._tr:
            self._tr.instant("prefill_dispatch",
                             args={"rows": len(items), "tokens": s_real,
                                   "bucket": s_bucket, "packed": True,
                                   "flops_g": round(flops / 1e9, 3)})
        self._key, sub = jax.random.split(self._key)
        args = (
            self.params, self.cache.k, self.cache.v,
            self.kscale, self.vscale, jnp.asarray(srows),
            jnp.asarray(tokens), jnp.asarray(positions),
            jnp.asarray(token_pages), jnp.asarray(seg_ids),
            jnp.asarray(last_idx), jnp.asarray([s_real], np.int32), sub,
            jnp.asarray(temps), jnp.asarray(tks), jnp.asarray(tps),
        )
        key_ = ("packed", s_bucket)
        if key_ not in self._ran_ok:
            self._attr_prefill_cold = True  # compiling: no MFU sample
            self._wd_grace_cold()
        with self._an.seg("dispatch"):
            try:
                tok0, self.cache.k, self.cache.v, \
                    self.kscale, self.vscale = \
                    self._get_packed_prefill_fn(s_bucket)(*args)
            except Exception:
                # same contract as the fresh-prefill fallback: only
                # degrade on a first-run lowering failure of the flash
                # kernel (the packed XLA attention then serves); a failure
                # on a proven shape re-raises
                if not self._use_flash or key_ in self._ran_ok:
                    raise
                logger.warning("packed flash prefill failed to lower; "
                               "falling back to XLA packed attention",
                               exc_info=True)
                self._use_flash = False
                self._prefill_fns.clear()
                self._prefill_window_fns.clear()
                self._packed_prefill_fns.clear()
                tok0, self.cache.k, self.cache.v, \
                    self.kscale, self.vscale = \
                    self._get_packed_prefill_fn(s_bucket)(*args)
        self._note_ran_ok(key_)
        return tok0, [(b, si) for si, (b, _, _) in enumerate(items)]

    def _get_packed_prefill_fn(self, s_bucket: int):
        if s_bucket in self._packed_prefill_fns:
            return self._packed_prefill_fns[s_bucket]
        cfg = self.model_cfg
        rope_max = self.max_len
        use_flash = self._use_flash
        mesh_ = self._kernel_mesh()
        interp = self._interpret
        kv_q = bool(self._kv_quant)

        @partial(jax.jit, donate_argnums=(1, 2, 3, 4) if kv_q else (1, 2))
        def packed_prefill(params, k_pages, v_pages, kscale, vscale,
                           scale_rows, tokens, positions, token_pages,
                           seg_ids, last_idx, length, key, temp, tk, tp):
            out = forward_paged(
                params, cfg, tokens, positions, k_pages, v_pages,
                jnp.zeros((1, 1), jnp.int32),  # tables unused: token_pages
                length, rope_max, use_ragged_kernel=False,
                use_flash=use_flash, mesh=mesh_, interpret=interp,
                token_pages=token_pages, segment_ids=seg_ids,
                packed_last_idx=last_idx,
                kv_scales=(kscale, vscale) if kv_q else None,
                scale_rows=scale_rows,
            )
            logits, k_pages, v_pages = out[:3]
            kscale, vscale = out[3] if kv_q else (None, None)
            tok0 = sample_logits(logits[0], key, temp, tk, tp)  # [B]
            return tok0, k_pages, v_pages, kscale, vscale

        logger.info("compiling packed prefill: bucket=%d segments<=%d "
                    "(flash=%s)", s_bucket, self.B, use_flash)
        self._packed_prefill_fns[s_bucket] = packed_prefill
        return packed_prefill

    def _get_prefill_fn(self, s_bucket: int, use_ring: bool = False):
        """Fresh-prefill program.  ``use_ring`` is decided by the CALLER
        from the real chunk length (ADVICE r2: bucket-based gating sent
        600-token prompts through ppermute hops); ring buckets arrive
        pre-rounded to a multiple of sp — enforced, never warned."""
        fn_key = (s_bucket, use_ring)
        if fn_key in self._prefill_fns:
            return self._prefill_fns[fn_key]
        cfg = self.model_cfg
        rope_max = self.max_len
        use_flash = self._use_flash  # captured: rebuilt fns see the fallback
        mesh_ = self._kernel_mesh()
        interp = self._interpret
        kv_q = bool(self._kv_quant)
        if use_ring and s_bucket % self._sp:
            raise ValueError(
                f"ring prefill bucket {s_bucket} not divisible by "
                f"sp={self._sp} — dispatch must round ring buckets up")

        @partial(jax.jit, donate_argnums=(1, 2, 3, 4) if kv_q else (1, 2))
        def prefill(params, k_pages, v_pages, kscale, vscale, scale_rows,
                    tokens, start, length, alloc_tokens, table, key, temp,
                    tk, tp):
            positions = jnp.broadcast_to(
                jnp.arange(tokens.shape[1])[None], tokens.shape)
            # Padded tail positions can exceed this sequence's allocated
            # pages (prompt bucket > budget); clamp their page writes INTO
            # the owned region — garbage there is masked by kv_lens, whereas
            # an out-of-table write would corrupt another sequence's page.
            write_pos = jnp.minimum(positions, alloc_tokens[:, None] - 1)
            out = forward_paged(
                params, cfg, tokens, write_pos, k_pages, v_pages, table,
                length, rope_max, use_ragged_kernel=False, use_flash=use_flash,
                mesh=mesh_, interpret=interp, use_ring=use_ring,
                last_pos=length - 1,  # LM head on the sampled row only
                kv_scales=(kscale, vscale) if kv_q else None,
                scale_rows=scale_rows,
            )
            logits, k_pages, v_pages = out[:3]
            kscale, vscale = out[3] if kv_q else (None, None)
            tok0 = sample_logits(logits[:, 0], key, temp, tk, tp)
            return tok0, k_pages, v_pages, kscale, vscale

        logger.info("compiling paged prefill: bucket=%d (flash=%s ring=%s)",
                    s_bucket, use_flash, use_ring)
        self._prefill_fns[fn_key] = prefill
        return prefill

    def _get_prefill_window_fn(self, s_bucket: int, w: int):
        """Continuation-prefill program: chunk at absolute positions
        [start, start+length) attending the page window (chunked prefill)."""
        key_ = (s_bucket, w)
        if key_ in self._prefill_window_fns:
            return self._prefill_window_fns[key_]
        cfg = self.model_cfg
        rope_max = self.max_len
        kv_q = bool(self._kv_quant)

        @partial(jax.jit, donate_argnums=(1, 2, 3, 4) if kv_q else (1, 2))
        def prefill_chunk(params, k_pages, v_pages, kscale, vscale,
                          scale_rows, tokens, start, length, alloc_tokens,
                          table, key, temp, tk, tp):
            positions = start[:, None] + jnp.broadcast_to(
                jnp.arange(tokens.shape[1])[None], tokens.shape)
            write_pos = jnp.minimum(positions, alloc_tokens[:, None] - 1)
            out = forward_paged(
                params, cfg, tokens, write_pos, k_pages, v_pages, table,
                start + length, rope_max, use_ragged_kernel=False,
                window_prefill=True,
                last_pos=length - 1,  # local row index within this chunk
                kv_scales=(kscale, vscale) if kv_q else None,
                scale_rows=scale_rows,
            )
            logits, k_pages, v_pages = out[:3]
            kscale, vscale = out[3] if kv_q else (None, None)
            tok0 = sample_logits(logits[:, 0], key, temp, tk, tp)
            return tok0, k_pages, v_pages, kscale, vscale

        logger.info("compiling chunked prefill: bucket=%d window=%d pages",
                    s_bucket, w)
        self._prefill_window_fns[key_] = prefill_chunk
        return prefill_chunk

    # -------------------------------------------------------------- decode

    def _decode_window(self, slots, extra_tokens: int):
        """(w, table) for one decode dispatch: page window bucketed to the
        widest active sequence plus ``extra_tokens`` of block growth.  Slots
        still in prefill phase get the null page table: the decode program's
        masked dummy writes must land on page 0, never on pages holding
        their half-prefilled KV."""
        decode_seqs = [
            s.seq if (s is not None and s.phase == "decode") else None
            for s in slots
        ]
        max_pages = 1
        for st in slots:
            if st is not None and st.phase == "decode":
                need = self.cache.pages_needed(st.kv_len + extra_tokens)
                max_pages = max(max_pages, need)
        w = min(_pow2_bucket(max_pages, 4), self.cache.max_pages_per_slot)
        return w, self.cache.page_table_array(decode_seqs)

    def _decode_block(self, slots, last_tok, kv_lens, active, temps, top_k,
                      top_p, pending=()):
        """One decode-block dispatch.  ``pending`` carries unfetched
        first-token arrays from this iteration's prefills: their values are
        scattered into the ``last_tok`` input on device (no host sync) and
        fetched together with the block's outputs in the one device_get."""
        w, table = self._decode_window(slots, self.decode_block)
        B = self.B
        # attribution inputs, taken from the caller's FULL slot arrays
        # before any compaction/permutation below rewrites them
        attr_live_rows = int(np.sum(active))
        attr_live_tokens = int(np.sum(kv_lens[active]))
        # Compact-batch drain: the decode program's cost scales with its
        # batch dim even for masked rows, so when few slots are live (queue
        # drained, reduce-tree tails) gather the live rows into one fixed
        # 8-row batch and scatter results back.  bc is pinned to 8 — exactly
        # one extra compiled shape per window; a pow2 ladder of compact
        # sizes would thrash multi-second runtime compiles (see the
        # quarter-step bucket NOTE above).  Skipped while prefill tok0s are
        # pending: those live on device and the compact gather is host-side.
        rows = np.flatnonzero(active)
        bc = 8 if (B > 8 and len(rows) <= 8 and not pending) else B
        if bc < B:
            n = len(rows)
            c_tok = np.zeros((bc,), np.int32)
            c_len = np.zeros((bc,), np.int32)
            c_act = np.zeros((bc,), bool)
            c_tab = np.zeros((bc, w), np.int32)  # pad rows: null page table
            c_tmp = np.zeros((bc,), np.float32)
            c_tk = np.zeros((bc,), np.int32)
            c_tp = np.ones((bc,), np.float32)
            c_tok[:n] = last_tok[rows]
            c_len[:n] = kv_lens[rows]
            c_act[:n] = True
            c_tab[:n] = table[rows, :w]
            c_tmp[:n] = temps[rows]
            c_tk[:n] = top_k[rows]
            c_tp[:n] = top_p[rows]
            last_tok, kv_lens, active = c_tok, c_len, c_act
            table, temps, top_k, top_p = c_tab, c_tmp, c_tk, c_tp
        # dispatch row -> slot for the KV scale buffers (compact-batch rows
        # are a gathered subset of slots; pad rows clamp harmlessly)
        if bc < B:
            srows = np.full((bc,), B, np.int32)
            srows[: len(rows)] = rows
        else:
            srows = np.arange(B, dtype=np.int32)
        # Multi-row kernel: length-balance the row→group assignment so a
        # straggler row can't serialize its group's shared DMA pipeline
        # (ops/paged_attention.balanced_row_order).  Pure host-side numpy
        # reorder of the dispatch rows; srows carries the slot mapping
        # through, so scales and the result scatter-back need no special
        # casing.  Greedy outputs are row-order-invariant; sampled rows
        # draw different (equally valid) tokens — LMRS_MULTIROW=0 restores
        # the unpermuted per-row dispatch exactly.
        perm = None
        if self._row_group > 1 and self._use_ragged:
            # grouping lives in the ragged kernel only: the XLA fallback
            # dispatch stays unpermuted (it has no groups to balance)
            from lmrs_tpu.ops.paged_attention import balanced_row_order
            # clamp to the dispatch width like the kernel does (compact
            # drain can pin bc below the configured group size); an
            # unclamped denominator would under-report occupancy exactly
            # where operators read it to pick G
            g = min(self._row_group, bc)
            self._h_group_occupancy.observe(
                len(rows) / (-(-bc // g) * g))
            perm = balanced_row_order(np.where(active, kv_lens, 0), g)
            if np.array_equal(perm, np.arange(len(perm))):
                perm = None
            else:
                last_tok = last_tok[perm]
                kv_lens = kv_lens[perm]
                active = active[perm]
                table = table[perm]
                temps, top_k, top_p = temps[perm], top_k[perm], top_p[perm]
                srows = srows[perm]
        lt = jnp.asarray(last_tok)
        for tok0_dev, prows in pending:  # on-device scatter, no host sync
            idx = np.array([b for b, _ in prows], np.int32)
            if perm is not None:
                # pending tok0s target SLOTS; map to their dispatch rows
                inv = np.empty(len(perm), np.int32)
                inv[perm] = np.arange(len(perm), dtype=np.int32)
                idx = inv[idx]
            idx = jnp.asarray(idx)
            src = tok0_dev[jnp.asarray(np.array([r for _, r in prows], np.int32))]
            lt = lt.at[idx].set(src)
        self._key, sub = jax.random.split(self._key)
        args = (
            self.params, self.cache.k, self.cache.v,
            self.kscale, self.vscale, jnp.asarray(srows),
            lt, jnp.asarray(kv_lens),
            jnp.asarray(table[:, :w]), jnp.asarray(active), sub,
            jnp.asarray(temps), jnp.asarray(top_k), jnp.asarray(top_p),
        )
        decode_warm = ("decode", bc, w) in self._ran_ok
        if not decode_warm:
            self._wd_grace_cold()
        t_disp = time.time()
        with self._an.seg("dispatch"):
            try:
                out = self._get_decode_fn(w)(*args)
            except Exception:
                # Only degrade on a compile-time lowering failure of the
                # ragged kernel (first call of this window shape — donation
                # happens at execution, so args are still valid).  A failure
                # after a shape has run successfully is a real runtime
                # error: re-raise rather than retrying against possibly-
                # donated buffers.
                if not self._use_ragged or ("decode", bc, w) in self._ran_ok:
                    raise
                logger.warning("ragged decode kernel failed to lower; "
                               "falling back to XLA paged decode",
                               exc_info=True)
                self._invalidate_compiled()
                out = self._get_decode_fn(w)(*args)
        self._note_ran_ok(("decode", bc, w))
        toks, n_valid, self.cache.k, self.cache.v = out
        with self._an.seg("fetch"):
            toks, n_valid, *tok0s = self._timed_get(  # one transfer
                (toks, n_valid, *[t for t, _ in pending]))
        toks, n_valid = np.asarray(toks), np.asarray(n_valid)
        t_done = time.time()
        with self._an.seg("finish"):
            # live roofline attribution: the fetch above waited out this
            # block's device work (plus any same-iteration prefill
            # sequenced before it — its FLOPs are pending and charged here)
            flops, cold_pf = self._consume_prefill_attr()
            nb = self._perf.note_block(
                t_disp, t_done, self.decode_block, attr_live_rows,
                attr_live_tokens, flops,
                warm=decode_warm and not cold_pf)
            self._attr_last_gb = round(nb / 1e9, 3)
            if self._cost.enabled:
                dcost, pcost = self._roofline_phase_costs(nb, flops)
                self._cost_step = (max(0.0, t_done - t_disp), dcost, pcost,
                                   self._consume_prefill_cost())
            self._maybe_profile_slow_step(t_done - t_disp,
                                          decode_warm and not cold_pf)
        if bc < B or perm is not None:
            # scatter compact and/or group-permuted results back to
            # full-width slot arrays (srows maps dispatch row -> slot;
            # rows >= B are compact-batch pads)
            full_t = np.zeros((B, toks.shape[1]), toks.dtype)
            full_n = np.zeros((B,), n_valid.dtype)
            sel = srows < B
            full_t[srows[sel]] = toks[sel]
            full_n[srows[sel]] = n_valid[sel]
            return full_t, full_n, tok0s
        return toks, n_valid, tok0s

    def _get_decode_fn(self, w: int):
        if w in self._decode_fns:
            return self._decode_fns[w]
        cfg = self.model_cfg
        n_steps = self.decode_block
        eos_id = self.tokenizer.eos_id
        max_len = self.max_len
        rope_max = self.max_len
        use_ragged = self._use_ragged
        mesh_ = self._kernel_mesh()
        interp = self._interpret
        row_group = self._row_group

        kv_q = bool(self._kv_quant)

        @partial(jax.jit, donate_argnums=(1, 2))
        def decode(params, k_pages, v_pages, kscale, vscale, scale_rows,
                   last_tok, kv_lens, table, active, key, temps, tk, tp):
            def step(carry, _):
                k_pages, v_pages, tok, lens, done, key = carry
                pos = jnp.minimum(lens, max_len - 1)[:, None]
                out = forward_paged(
                    params, cfg, tok[:, None], pos, k_pages, v_pages, table,
                    jnp.minimum(lens + 1, max_len), rope_max,
                    use_ragged_kernel=use_ragged,
                    mesh=mesh_, interpret=interp,
                    kv_scales=(kscale, vscale) if kv_q else None,
                    scale_rows=scale_rows if kv_q else None,
                    decode_row_group=row_group,
                )
                logits, k_pages, v_pages = out[:3]
                key, sub = jax.random.split(key)
                # scan context, NOT vmap: sample_logits gates its full-
                # vocab sort behind lax.cond fast paths that vmap would
                # silently lower to compute-both-branches (ops/sampling.py;
                # test_model.test_sampler_cond_survives_scheduler_contexts)
                nxt = sample_logits(logits[:, 0], sub, temps, tk, tp)
                nxt = jnp.where(done, eos_id, nxt)
                newly_done = jnp.logical_or(done, nxt == eos_id)
                lens = jnp.where(done, lens, lens + 1)
                return (k_pages, v_pages, nxt, lens, newly_done, key), (nxt, ~done)

            carry = (k_pages, v_pages, last_tok, kv_lens, ~active, key)
            (k_pages, v_pages, _, _, _, _), (toks, valid) = jax.lax.scan(
                step, carry, None, length=n_steps)
            toks = jnp.transpose(toks)
            valid = jnp.transpose(valid)
            return toks, jnp.sum(valid, axis=1), k_pages, v_pages

        logger.info("compiling paged decode: B=%d steps=%d window=%d pages "
                    "(ragged_kernel=%s row_group=%d)", self.B, n_steps, w,
                    use_ragged, row_group)
        self._decode_fns[w] = decode
        return decode

    # -------------------------------------------- speculative decode (k > 0)

    def seed_history(self, b: int, st: _SlotState) -> None:
        """Load slot b's token history into the device-resident buffer (one
        row upload at decode admission; the device appends from then on).
        Under tree speculation a cross-refresh draft hint (the previous
        refresh's summary, live/session.py) seeds AHEAD of the real
        history: the buffer column of the token at kv position p becomes
        p + spec_hoff, and the n-gram lookup window covers the hint — a
        near-perfect draft source for the next refresh's continuation."""
        if not self.spec_k:
            return
        if self._spec_buf is None:
            self._spec_buf = jnp.zeros((self.B, self.max_len), jnp.int32)
        row = np.zeros((self.max_len,), np.int32)
        hint = st.spec_hint if self._spec_tree else []
        hist = st.prompt_ids + st.generated
        hoff = min(len(hint), max(0, self.max_len - len(hist)))
        row[:hoff] = hint[:hoff]
        hist = hist[-(self.max_len - hoff):] if hoff < self.max_len else []
        row[hoff: hoff + len(hist)] = hist
        st.spec_hoff = hoff
        self._spec_buf = self._spec_buf.at[b].set(jnp.asarray(row))

    def _spec_decode_block(self, slots, last_tok, kv_lens, active, temps,
                           top_k, top_p) -> list[list[int]]:
        """One speculative decode dispatch; returns the per-slot emitted
        token lists.  The token-history buffer lives on device (seeded per
        row at decode admission, appended by the device inside the block) —
        no per-dispatch O(B*max_len) upload."""
        with self._an.seg("draft"):
            if self._spec_stale:
                # rows advanced by mixed steps since the last spec block:
                # their history rows missed the in-scan appends — re-seed
                # once per row here, at spec resumption, not per mixed step
                for b in sorted(self._spec_stale):
                    if slots[b] is not None and slots[b].phase == "decode":
                        self.seed_history(b, slots[b])
                self._spec_stale.clear()
        w, table = self._decode_window(slots,
                                       self.decode_block + self.spec_k)
        # the verify kernel passes the grouping but not the balanced
        # permutation: the token-history buffer is device-resident and
        # slot-indexed, so rows dispatch in slot order here.  Same gate as
        # _get_spec_decode_fn's use_ragged: under a multi-device mesh the
        # verify runs the ungrouped XLA path, and a sample here would
        # report padding waste for a dispatch that had no group layout
        if (self._row_group > 1 and self._use_ragged
                and self._kernel_mesh() is None):
            g = self._row_group
            self._h_group_occupancy.observe(
                int(np.sum(active)) / (-(-self.B // g) * g))
        self._key, sub = jax.random.split(self._key)
        args = (
            self.params, self.cache.k, self.cache.v, self._spec_buf,
            self.kscale, self.vscale,
            jnp.arange(self.B, dtype=jnp.int32),  # dispatch row -> slot
            jnp.asarray(last_tok), jnp.asarray(kv_lens),
            jnp.asarray(table[:, :w]), jnp.asarray(active), sub,
            jnp.asarray(temps), jnp.asarray(top_k), jnp.asarray(top_p),
        )
        if ("specfn", w) not in self._ran_ok:
            self._wd_grace_cold()
        t_disp = time.time()
        with self._an.seg("dispatch"):
            try:
                out = self._get_spec_decode_fn(w)(*args)
            except Exception:
                # same contract as the plain decode fallback: degrade only
                # on a first-run lowering failure of the multi-verify
                # kernel (args not yet donated); a failure on a proven
                # shape re-raises
                if not self._use_ragged or ("specfn", w) in self._ran_ok:
                    raise
                logger.warning("multi-verify kernel failed to lower; "
                               "falling back to XLA multi decode",
                               exc_info=True)
                self._invalidate_compiled()
                out = self._get_spec_decode_fn(w)(*args)
        self._note_ran_ok(("specfn", w))
        toks, counts, self._spec_buf, self.cache.k, self.cache.v = out
        with self._an.seg("fetch"):
            toks, counts = self._timed_get((toks, counts))  # one transfer
        t_done = time.time()
        with self._an.seg("finish"):
            # spec blocks contribute step gaps but no byte/FLOP samples
            # (the verify-step byte model differs); pending prefill FLOPs
            # are consumed — still counted, never sampled — so they cannot
            # mis-attribute to a later plain block
            self._perf.note_gap(t_disp, t_done)
            flops, _ = self._consume_prefill_attr()
            if flops > 0:
                self._perf.c_flops.inc(flops)
            self._attr_last_gb = 0.0
            if self._cost.enabled:
                # no byte model for the verify step: phase costs 0 degrade
                # the ledger split to per-row token counts (documented)
                self._cost_step = (max(0.0, t_done - t_disp), 0.0, 0.0,
                                   self._consume_prefill_cost())
        emitted: list[list[int]] = []
        for b in range(self.B):
            row: list[int] = []
            accepted = 0
            for s in range(counts.shape[1]):
                c = int(counts[b, s])
                row.extend(int(t) for t in toks[b, s, :c])
                self._c_spec_accepted.inc(max(0, c - 1))
                accepted += max(0, c - 1)
            if accepted and slots[b] is not None:
                self._cost.note_saved(slots[b].req, spec_tokens=accepted)
            emitted.append(row)
        return emitted

    def _get_spec_decode_fn(self, w: int):
        key_ = ("specfn", w)
        if key_ in self._decode_fns:
            return self._decode_fns[key_]
        cfg = self.model_cfg
        n_steps = self.decode_steps
        k = self.spec_k
        ngram = max(2, self.cfg.speculate_ngram)
        eos_id = self.tokenizer.eos_id
        max_len = self.max_len
        rope_max = self.max_len
        # ragged multi-token verify: same gate as the decode kernel (the
        # multi kernel is its generalization); under a real multi-device
        # mesh the XLA multi path serves (one window gather — still not
        # window_prefill).  _kernel_mesh(), not self.mesh: a 1-device mesh
        # is single-device everywhere else too.
        use_ragged = self._use_ragged and self._kernel_mesh() is None
        interp = self._interpret
        row_group = self._row_group
        kv_q = bool(self._kv_quant)

        from lmrs_tpu.ops.sampling import filtered_probs
        from lmrs_tpu.ops.speculative import draft_lookup, verify_tokens

        @partial(jax.jit, donate_argnums=(1, 2, 3))
        def spec_decode(params, k_pages, v_pages, buf, kscale, vscale,
                        srows, last_tok, kv_lens, table, active, key,
                        temps, tk, tp):
            b_rows = jnp.arange(buf.shape[0])[:, None]
            offs = jnp.arange(k + 1)[None, :]

            def step(carry, _):
                k_pages, v_pages, buf, tok, lens, done, key = carry
                # current token enters the history at index == its KV position
                buf = buf.at[b_rows[:, 0], jnp.minimum(lens, max_len - 1)].set(tok)
                draft, n_valid = draft_lookup(buf, lens + 1, k, pad_id=eos_id,
                                              n=ngram)

                toks_in = jnp.concatenate([tok[:, None], draft], axis=1)
                positions = jnp.minimum(lens[:, None] + offs, max_len - 1)
                # kv_lens UNCLAMPED: the multi path derives the write base
                # as kv_lens - (k+1), which must be the true position even
                # when drafts overhang max_len (the max_pos cap masks the
                # overhang; a clamped length would slide the write span
                # backwards over real cache entries)
                out = forward_paged(
                    params, cfg, toks_in, positions, k_pages, v_pages, table,
                    lens + 1 + k, rope_max,
                    use_ragged_kernel=use_ragged, multi_decode=True,
                    interpret=interp,
                    kv_scales=(kscale, vscale) if kv_q else None,
                    scale_rows=srows if kv_q else None,
                    decode_row_group=row_group,
                )
                # scales are read-only in decode (frozen at prefill):
                # out[3:] returns them unchanged when kv_q
                logits, k_pages, v_pages = out[:3]
                # filtered_probs is deliberately cond-free, so this vmap
                # over the token axis is safe; sample_logits (lax.cond
                # fast paths) must never be called under it
                # (ops/sampling.py NOTE)
                probs = jax.vmap(filtered_probs, in_axes=(1, None, None, None),
                                 out_axes=1)(logits, temps, tk, tp)
                key, sub = jax.random.split(key)
                emit, count = verify_tokens(probs, draft, n_valid, sub)
                emit = jnp.where(done[:, None], eos_id, emit)
                count = jnp.where(done, 0, count)

                hit_eos = jnp.any((offs < count[:, None]) & (emit == eos_id), 1)
                newly_done = jnp.logical_or(done, hit_eos)
                # accepted tokens extend the history (the final emitted token
                # lands exactly at the next step's write index — idempotent)
                cols = jnp.minimum(lens[:, None] + 1 + offs, max_len - 1)
                buf = buf.at[b_rows, cols].set(emit)
                lens = jnp.minimum(lens + count, max_len)
                nxt = jnp.take_along_axis(
                    emit, jnp.maximum(count - 1, 0)[:, None], 1)[:, 0]
                nxt = jnp.where(done, tok, nxt)
                return (k_pages, v_pages, buf, nxt, lens, newly_done, key), (emit, count)

            carry = (k_pages, v_pages, buf, last_tok, kv_lens, ~active, key)
            (k_pages, v_pages, buf, *_), (toks, counts) = jax.lax.scan(
                step, carry, None, length=n_steps)
            # [steps, B, k+1] -> [B, steps, k+1]; counts [steps, B] -> [B, steps]
            return (jnp.transpose(toks, (1, 0, 2)), jnp.transpose(counts),
                    buf, k_pages, v_pages)

        logger.info("compiling speculative decode: B=%d steps=%d k=%d "
                    "window=%d pages", self.B, n_steps, k, w)
        self._decode_fns[key_] = spec_decode
        return spec_decode
