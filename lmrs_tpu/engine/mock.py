"""Deterministic no-device engine — the CPU-only test path.

Successor of the reference's mock backend (llm_executor.py:411-432 +
result_aggregator.py:243-245): with no API key the reference returns a canned
response so the whole pipeline runs offline.  Here the mock is a first-class
backend (BASELINE.json config #1) that additionally produces *content-bearing*
summaries — a deterministic extractive sketch of the prompt's transcript — so
reduce-stage logic and ROUGE-style parity harnesses have real signal to chew
on instead of a constant string.
"""

from __future__ import annotations

import hashlib
import re
import time

from lmrs_tpu.data.tokenizer import ApproxTokenizer
from lmrs_tpu.engine.api import (GenerationRequest, GenerationResult,
                                 apply_stop_sequences)
from lmrs_tpu.obs import get_tracer, req_tid
from lmrs_tpu.testing import faults

_TS_RE = re.compile(r"\[(?:\d+:)?\d{2}:\d{2}\]")


class MockEngine:
    """Offline deterministic engine.

    fail_pattern: substring that triggers a simulated failure — the fault
    injection hook the reference lacks (SURVEY.md §5.3 "no fault injection").
    """

    def __init__(self, seed: int = 0, latency_s: float = 0.0, fail_pattern: str | None = None):
        self.seed = seed
        self.latency_s = latency_s
        self.fail_pattern = fail_pattern
        self._tok = ApproxTokenizer()
        # ids cancel() was called for — generation is instantaneous here, so
        # the hook only records (tests assert the server propagated a
        # disconnect) and flags ids not yet generated in this batch
        self.cancelled: set[int] = set()

    def generate_batch(self, requests: list[GenerationRequest],
                       on_result=None, on_tokens=None) -> list[GenerationResult]:
        # cancel-set lifecycle mirrors ContinuousScheduler.run(): no
        # start-of-batch clear (a cancel can legitimately race the batch
        # boundary) but a full clear in the finally, so stale ids never
        # cancel a later batch's same-numbered request or accumulate
        # unboundedly; callers keep ids unique across cancels (the HTTP
        # batcher's rids are global)
        # injection site: same engine-level batch fault as JaxEngine — the
        # no-device arm of the chaos soak (tests/test_chaos.py)
        faults.fire("engine.batch")

        def one(req: GenerationRequest) -> GenerationResult:
            tr = get_tracer()
            t0 = time.time()
            res = self._one(req)
            if tr:  # minimal lifecycle: the mock has no queue or slots
                tid = req_tid(req.request_id)
                tr.complete("generate", t0, time.time(), tid=tid,
                            args={"completion_tokens": res.completion_tokens})
                tr.instant("cancel" if res.finish_reason == "cancelled"
                           else "finish", tid=tid,
                           args={"reason": res.finish_reason})
            if on_tokens is not None and res.text:
                # no incremental decode in the mock: one delta per result
                on_tokens(res.request_id, res.text)
            return res

        try:
            if on_result is not None:
                from lmrs_tpu.engine.api import drain_with_callback

                return drain_with_callback(
                    lambda reqs: [one(r) for r in reqs], requests, on_result)
            return [one(r) for r in requests]
        finally:
            self.cancelled.clear()

    def shutdown(self) -> None:
        pass

    def cancel(self, request_id: int) -> None:
        """Engine optional abort hook (see engine/api.py).  Recorded; any
        request of the current batch not yet generated when its id lands
        here comes back finish_reason="cancelled"."""
        self.cancelled.add(request_id)

    def engine_metrics(self) -> dict:
        return {}

    def _one(self, req: GenerationRequest) -> GenerationResult:
        def expired() -> bool:
            return (req.deadline_s is not None
                    and time.time() >= req.deadline_s)

        # deadline lifecycle on the no-device path, same split as the
        # scheduler: expired BEFORE any work -> shed (zero-cost explicit
        # rejection); expired during the simulated generation latency ->
        # deadline (work was spent)
        if expired():
            return GenerationResult(request_id=req.request_id,
                                    finish_reason="shed")
        if self.latency_s:
            time.sleep(self.latency_s)
            if expired():
                return GenerationResult(request_id=req.request_id,
                                        finish_reason="deadline")
        if req.request_id in self.cancelled:
            return GenerationResult(request_id=req.request_id,
                                    finish_reason="cancelled")
        if self.fail_pattern and self.fail_pattern in req.prompt:
            return GenerationResult(
                request_id=req.request_id,
                finish_reason="error",
                error="mock: injected failure",
            )
        text, stop_hit = apply_stop_sequences(
            self._extractive_sketch(req.prompt), req.stop)
        return GenerationResult(
            request_id=req.request_id,
            text=text,
            prompt_tokens=self._tok.count(req.prompt),
            completion_tokens=self._tok.count(text),
            finish_reason="stop",
            stop_sequence=stop_hit,
        )

    def _extractive_sketch(self, prompt: str) -> str:
        """First/middle/last content sentences + every timestamp, capped.

        Deterministic in (prompt, seed); no randomness so repeated runs are
        byte-identical (test requirement, SURVEY.md §4).
        """
        # Pull out the transcript / summaries body if the prompt embeds one.
        body = prompt
        for marker in ("Transcript section:", "Partial summaries:", "Intermediate summaries:"):
            if marker in body:
                body = body.split(marker, 1)[-1]
        sentences = [s.strip() for s in re.split(r"(?<=[.!?])\s+", body) if len(s.strip()) > 30]
        stamps = _TS_RE.findall(body)
        digest = hashlib.sha256(f"{self.seed}:{prompt}".encode()).hexdigest()[:8]
        picked = []
        if sentences:
            idx = sorted({0, len(sentences) // 2, len(sentences) - 1})
            picked = [sentences[i] for i in idx]
        lines = [f"[mock-{digest}] Summary:"]
        lines += [f"- {s[:240]}" for s in picked]
        if stamps:
            uniq = list(dict.fromkeys(stamps))[:12]  # cap so reduce inputs stay bounded
            lines.append("Timestamps: " + " ".join(uniq))
        return "\n".join(lines)
