"""Deterministic no-device engine — the CPU-only test path.

Successor of the reference's mock backend (llm_executor.py:411-432 +
result_aggregator.py:243-245): with no API key the reference returns a canned
response so the whole pipeline runs offline.  Here the mock is a first-class
backend (BASELINE.json config #1) that additionally produces *content-bearing*
summaries — a deterministic extractive sketch of the prompt's transcript — so
reduce-stage logic and ROUGE-style parity harnesses have real signal to chew
on instead of a constant string.
"""

from __future__ import annotations

import hashlib
import re
import threading
import time

from lmrs_tpu.data.tokenizer import ApproxTokenizer
from lmrs_tpu.engine.api import (GenerationRequest, GenerationResult,
                                 apply_stop_sequences, preamble_key,
                                 preamble_text)
from lmrs_tpu.obs import get_tracer, req_tid
from lmrs_tpu.obs.anatomy import CLASSES, SEGMENTS, _pct, anatomy_enabled
from lmrs_tpu.testing import faults
from lmrs_tpu.utils.perf_model import pow2_bucket

_TS_RE = re.compile(r"\[(?:\d+:)?\d{2}:\d{2}\]")


def _mock_tid(tr, req: GenerationRequest) -> int:
    """The request's span-track id — same rule as the scheduler's
    ``_tid``: keyed on the propagated trace id when present (one causal
    chain fleet-wide; the stitcher's join key), else the legacy
    request-id track."""
    return (tr.track_for(req.trace_id) if req.trace_id
            else req_tid(req.request_id))


class MockEngine:
    """Offline deterministic engine.

    fail_pattern: substring that triggers a simulated failure — the fault
    injection hook the reference lacks (SURVEY.md §5.3 "no fault injection").
    """

    # disaggregated handoff is supported: the mock's "KV state" is its
    # deterministic completion text, pinned/transferred/resumed through
    # the same ticket lifecycle the paged engines use (the no-device arm
    # of the two-process topology gate)
    supports_handoff = True

    # the mock's emulated cache geometry (deterministic; no device):
    # "HBM" holds this many preamble tokens resident before LRU entries
    # spill to the emulated host pool, and each token claims this many
    # host-pool bytes against ``host_kv_gb``
    EMU_RESIDENT_TOKENS = 2048
    EMU_BYTES_PER_TOKEN = 1024
    EMU_PAGE_TOKENS = 128

    # deterministic emulated device-time: the mock "spends" this many
    # seconds per token, so usage bills are byte-reproducible across
    # arms and hosts (the A/B harnesses compare exact rollup sums)
    EMU_SECONDS_PER_TOKEN = 1e-6

    def __init__(self, seed: int = 0, latency_s: float = 0.0,
                 fail_pattern: str | None = None,
                 handoff_ttl_s: float = 60.0,
                 mixed_batch: bool | None = None,
                 mixed_token_budget: int = 256,
                 prefix_cache: bool = True,
                 host_kv: bool | None = None,
                 host_kv_gb: float = 1.0,
                 cost_ledger: bool | None = None,
                 slo: bool | None = None,
                 slots: int = 0,
                 qos: bool | None = None,
                 speculate_k: int = 0):
        from lmrs_tpu.utils.env import env_bool, env_int

        self.seed = seed
        self.latency_s = latency_s
        self.fail_pattern = fail_pattern
        self.handoff_ttl_s = handoff_ttl_s
        # SARATHI mixed-batch emulation (the scheduler's admission
        # interleave, on the no-device arm): the mock generates each
        # request instantly, so nothing can actually stall — what CI
        # needs is the same KNOB surface and accounting the jax engine
        # exposes.  When armed, every same-batch request admitted behind
        # the first is accounted as prefilling in budget-clipped slices
        # that ride the earlier requests' decode steps; deterministic,
        # text-identical either way (serving/jobs tests exercise the A/B
        # arms and the metrics block on CPU).  The LMRS_MIXED kill switch
        # composes with the config flag exactly as in the scheduler: env
        # 0 always disarms, config False always disarms.
        self.mixed_batch = (env_bool("LMRS_MIXED", True)
                            and (mixed_batch is None or bool(mixed_batch)))
        self.mixed_token_budget = max(32, int(mixed_token_budget))
        # Prefix-cache + host-RAM spill tier emulation (the scheduler's
        # knob surface on the no-device arm, same composition rules:
        # LMRS_PREFIX_CACHE / LMRS_HOST_KV env always disarm, config
        # always disarms).  Deterministic and output-free — the mock's
        # text never changes; what CI gets is the same accounting,
        # radix-summary publication, and budget behavior the jax engine
        # exposes, so the full routing+spill flow runs deviceless.
        self.prefix_cache = (env_bool("LMRS_PREFIX_CACHE", True)
                             and bool(prefix_cache))
        self.host_kv = (self.prefix_cache
                        and env_bool("LMRS_HOST_KV", True)
                        and (host_kv is None or bool(host_kv))
                        and host_kv_gb > 0)
        self.host_kv_budget_bytes = int(max(0.0, host_kv_gb) * 2**30)
        self._prefix_lock = threading.Lock()
        # key -> {"tokens", "tier" ("resident"|"spilled"), "tick"}
        self._prefix: dict[str, dict] = {}  # guarded-by: _prefix_lock
        self._prefix_tick = 0               # guarded-by: _prefix_lock
        self._prefix_queries = 0            # guarded-by: _prefix_lock
        self._prefix_hits = 0               # guarded-by: _prefix_lock
        self._prefix_tokens_reused = 0      # guarded-by: _prefix_lock
        self._spilled_hits = 0              # guarded-by: _prefix_lock
        self._tokens_prefetched = 0         # guarded-by: _prefix_lock
        self._spill_pages = 0               # guarded-by: _prefix_lock
        self._prefetch_pages = 0            # guarded-by: _prefix_lock
        self._host_dropped_pages = 0        # guarded-by: _prefix_lock
        self._migrate_exports = 0           # guarded-by: _prefix_lock
        self._migrate_imports = 0           # guarded-by: _prefix_lock
        self._migrate_tokens = 0            # guarded-by: _prefix_lock
        self._mixed_lock = threading.Lock()
        self._mixed_dispatches = 0  # guarded-by: _mixed_lock
        self._mixed_piggybacked = 0  # guarded-by: _mixed_lock
        self._mixed_fill_sum = 0.0  # guarded-by: _mixed_lock
        # Ragged-span (RPA) knob parity: the jax scheduler routes every
        # mixed/continuation dispatch through one span-program family
        # when LMRS_RPA is on.  The mock mirrors the knob and the
        # accounting block (span tokens, distinct pow2 compile shapes)
        # so deviceless CI can assert the metrics surface and the
        # LMRS_RPA=0 kill switch end-to-end; text is untouched.
        self.rpa = env_bool("LMRS_RPA", True) and self.mixed_batch
        self._rpa_span_tokens = 0      # guarded-by: _mixed_lock
        self._rpa_dispatches = 0       # guarded-by: _mixed_lock
        self._rpa_shapes: set = set()  # guarded-by: _mixed_lock
        # Tree-speculation parity (the scheduler's spec-tree surface on
        # the no-device arm): same gate composition (speculate_k arms,
        # LMRS_SPEC_TREE=0 disarms, width clamped so the ancestor
        # bitmask capacity 1 + k*(W+1) fits in 32 bits) and the same
        # report block keys, deterministically emulated — a request
        # carrying a draft hint "accepts" full depth (the cross-refresh
        # hint restating itself), one without accepts half, so deviceless
        # CI can assert both the knob surface and the hint plumbing
        # end-to-end.  Text is untouched (advisory by contract).
        self.spec_k = max(0, int(speculate_k))
        self.spec_width = env_int("LMRS_SPEC_TREE_WIDTH", 2, lo=1, hi=8)
        while (self.spec_width > 1
               and 1 + self.spec_k * (self.spec_width + 1) > 32):
            self.spec_width -= 1
        self.spec_tree = (self.spec_k > 0 and self.rpa
                          and 1 + self.spec_k * (self.spec_width + 1) <= 32
                          and env_bool("LMRS_SPEC_TREE", True))
        self.spec_adaptive = (self.spec_tree
                              and env_bool("LMRS_SPEC_ADAPTIVE", True))
        self._spec_dispatches = 0     # guarded-by: _mixed_lock
        self._spec_rows = 0           # guarded-by: _mixed_lock
        self._spec_nodes_sum = 0      # guarded-by: _mixed_lock
        self._spec_depth_sum = 0      # guarded-by: _mixed_lock
        self._spec_accepted = 0       # guarded-by: _mixed_lock
        # draft hints seen by generated requests, in generation order —
        # the test hook for cross-refresh drafting (tests assert the live
        # layer's previous-summary hint actually reached the engine)
        self.draft_hints: list[str] = []
        # Step-anatomy parity (obs/anatomy.py): the same report shape the
        # scheduler's profiler exposes, deterministically emulated — every
        # segment derives from token counts at EMU_SECONDS_PER_TOKEN,
        # never wall clocks, so two arms running identical traffic
        # produce byte-identical anatomy documents, and wall == segment
        # sum exactly (residual 0) by construction.  LMRS_ANATOMY=0
        # disarms the whole surface (report shape / wire parity with the
        # scheduler's kill switch).
        self._an_lock = threading.Lock()
        self._an_segs = {s: 0.0 for s in SEGMENTS}  # guarded-by: _an_lock
        self._an_cls: dict[str, list] = {c: [] for c in CLASSES}
        self._an_buckets: dict[tuple[int, int], dict] = {}
        self._tok = ApproxTokenizer()
        # Cost ledger + SLO parity (obs/ledger.py, obs/slo.py): the SAME
        # accounting/knob surface as the jax scheduler, deterministically
        # emulated — per-request device-seconds derive from token counts
        # (EMU_SECONDS_PER_TOKEN), never wall clocks, so the whole
        # usage -> /v1/usage -> router-aggregation -> SLO-routing flow
        # runs deviceless in CI with exact, reproducible sums.  The env
        # kill switches compose exactly as in the scheduler: LMRS_
        # COST_LEDGER=0 / LMRS_SLO=0 always disarm, constructor False
        # always disarms.
        from lmrs_tpu.obs.ledger import CostLedger
        from lmrs_tpu.obs.slo import SLOEngine

        cl_on = (env_bool("LMRS_COST_LEDGER", True)
                 and (cost_ledger is None or bool(cost_ledger)))
        slo_on = (env_bool("LMRS_SLO", True)
                  and (slo is None or bool(slo)))
        # frozen ledger clock: residency-derived meters (host-pool
        # byte-seconds) read 0 so usage sums stay byte-reproducible —
        # the mock bills work, never wall time
        self.ledger = CostLedger(enabled=cl_on, clock=lambda: 0.0)
        self.slo = SLOEngine(enabled=slo_on)
        # rid -> prompt tokens the prefix cache / prefetch served, so
        # _bill skips them like the real scheduler (saved tokens never
        # enter a prefill dispatch — they must not bill device time)
        self._billing_saved: dict[int, int] = {}  # guarded-by: _prefix_lock
        # ids cancel() was called for — generation is instantaneous here, so
        # the hook only records (tests assert the server propagated a
        # disconnect) and flags ids not yet generated in this batch
        self.cancelled: set[int] = set()
        # rid -> pinned handoff state (see _one); the lock mirrors the
        # scheduler's pinned-export contract — handler threads release
        # while generate_batch pins
        self._pinned: dict[int, dict] = {}
        self._pinned_lock = threading.Lock()
        # Multi-tenant QoS parity (fleet/qos.py): the same fair-share
        # admission surface as the jax scheduler.  slots=0 (default) is
        # byte-identical to the pre-QoS mock: every generate_batch call
        # runs immediately, no gate, no reordering.  slots>0 bounds the
        # number of concurrently *running* requests across handler
        # threads; waiting tickets are admitted FIFO when QoS is
        # disarmed and in fair-share order (class, windowed usage,
        # arrival) when armed — the contention source the fairness A/B
        # needs on a deviceless host.
        from lmrs_tpu.fleet.qos import maybe_qos

        self.qos = maybe_qos() if (qos is None or bool(qos)) else None
        if self.qos is not None:
            # same lock-ordering contract as the scheduler: the ledger
            # fires the observer after releasing its own lock
            self.ledger.observer = self.qos.note_usage
        self.slots = max(0, int(slots))
        self._adm_cv = threading.Condition()
        self._adm_queue: list = []  # waiting (seq, req) tickets  guarded-by: _adm_cv
        self._adm_seq = 0           # guarded-by: _adm_cv
        self._adm_running = 0       # guarded-by: _adm_cv

    def _adm_pick_locked(self):
        """Next ticket to admit.  FIFO by arrival seq when QoS is
        disarmed; the policy's fair-share order when armed.  The queue
        list stays append-ordered, so list index == FIFO rank and
        pick_index's tie-break matches arrival order."""
        # holds-lock: _adm_cv
        if self.qos is None:
            return self._adm_queue[0]
        return self._adm_queue[self.qos.pick_index(
            [t[1] for t in self._adm_queue])]

    def _admit_wait(self, req: GenerationRequest) -> None:
        """Block until a run slot is free and this request is the
        admission policy's pick.  No-op when slots=0 (unlimited)."""
        if self.slots <= 0:
            return
        with self._adm_cv:
            ticket = (self._adm_seq, req)
            self._adm_seq += 1
            self._adm_queue.append(ticket)
            while not (self._adm_running < self.slots
                       and self._adm_pick_locked() is ticket):
                # timed wait: a lost wakeup only delays, never deadlocks
                self._adm_cv.wait(timeout=0.2)
            self._adm_queue.remove(ticket)
            self._adm_running += 1
            # another slot may still be free for the next pick
            self._adm_cv.notify_all()

    def _admit_release(self) -> None:
        if self.slots <= 0:
            return
        with self._adm_cv:
            self._adm_running -= 1
            self._adm_cv.notify_all()

    def qos_report(self) -> dict:
        """Per-tenant fair-share snapshot — same shape as the
        scheduler's (served under /v1/usage as the "qos" block)."""
        if self.qos is None:
            return {"object": "qos", "enabled": False}
        return self.qos.report()

    def generate_batch(self, requests: list[GenerationRequest],
                       on_result=None, on_tokens=None) -> list[GenerationResult]:
        # cancel-set lifecycle mirrors ContinuousScheduler.run(): no
        # start-of-batch clear (a cancel can legitimately race the batch
        # boundary) but a full clear in the finally, so stale ids never
        # cancel a later batch's same-numbered request or accumulate
        # unboundedly; callers keep ids unique across cancels (the HTTP
        # batcher's rids are global)
        # injection site: same engine-level batch fault as JaxEngine — the
        # no-device arm of the chaos soak (tests/test_chaos.py)
        faults.fire("engine.batch")

        def one(req: GenerationRequest) -> GenerationResult:
            self._admit_wait(req)
            try:
                return _one_admitted(req)
            finally:
                self._admit_release()

        def _one_admitted(req: GenerationRequest) -> GenerationResult:
            tr = get_tracer()
            t0 = time.time()
            if req.draft_hint is not None:
                # recorded regardless of the spec arm: the hint is
                # advisory plumbing, and tests assert it arrived even on
                # engines that ignore it
                self.draft_hints.append(req.draft_hint)
            res = self._one(req)
            self._bill(req, res)
            if self.spec_tree and res.completion_tokens:
                # tree-spec arm: the plain iteration carries the prompt
                # only; emulated spec steps carry the decoded tokens (no
                # double-counted fetch)
                self._note_anatomy("plain",
                                   dispatch_tokens=res.prompt_tokens,
                                   fetch_tokens=0)
                self._note_spec(req, res.completion_tokens)
            else:
                # one emulated "plain" scheduler iteration per request:
                # dispatch carries the prompt, fetch the completion
                self._note_anatomy("plain",
                                   dispatch_tokens=res.prompt_tokens,
                                   fetch_tokens=res.completion_tokens)
            self.slo.observe_ttft(time.time() - t0)
            self.slo.note_result(res.finish_reason, res.completion_tokens,
                                 res.error)
            if tr:  # minimal lifecycle: the mock has no queue or slots
                # the tid is resolved AFTER _one so a handoff import's
                # adopted trace takes effect: CI's no-device disagg
                # traces stitch end-to-end through router → mock backends
                tid = _mock_tid(tr, req)
                tr.complete("generate", t0, time.time(), tid=tid,
                            args={"completion_tokens": res.completion_tokens})
                tr.instant("cancel" if res.finish_reason == "cancelled"
                           else "finish", tid=tid,
                           args={"reason": res.finish_reason})
            if on_tokens is not None and res.text:
                # no incremental decode in the mock: one delta per result
                on_tokens(res.request_id, res.text)
            return res

        self._note_mixed_batch(requests)
        try:
            if on_result is not None:
                from lmrs_tpu.engine.api import drain_with_callback

                return drain_with_callback(
                    lambda reqs: [one(r) for r in reqs], requests, on_result)
            return [one(r) for r in requests]
        finally:
            self.cancelled.clear()

    def _note_mixed_batch(self, requests: list[GenerationRequest]) -> None:
        """Mixed-batch accounting on the no-device arm: requests admitted
        behind the first in a batch are accounted as chunked prefills
        riding the earlier requests' decode steps, slice-clipped to the
        step budget — the same counters (dispatches, piggybacked tokens,
        fill) the scheduler's fused dispatcher reports, so serving/jobs
        CI can assert the knob surface end-to-end without a device.
        Deterministic and output-free: the mock's text is untouched."""
        if not self.mixed_batch or len(requests) < 2:
            return
        n_decode = len(requests) - 1  # rows decoding while the rest admit
        slice_cap = max(16, self.mixed_token_budget - n_decode)
        with self._mixed_lock:
            for req in requests[1:]:
                remaining = self._tok.count(req.prompt)
                while remaining > 0:
                    c = min(remaining, slice_cap)
                    self._mixed_dispatches += 1
                    self._mixed_piggybacked += c
                    self._mixed_fill_sum += min(
                        (n_decode + c) / self.mixed_token_budget, 1.0)
                    if self.rpa:
                        total = n_decode + c
                        self._rpa_dispatches += 1
                        self._rpa_span_tokens += total
                        # same pow2 bucket family the scheduler compiles
                        # (one shared definition — utils/perf_model)
                        bucket = pow2_bucket(total, 16)
                        self._rpa_shapes.add(bucket)
                        self._note_rpa_bucket(bucket, total)
                    # each emulated slice is one "mixed" iteration:
                    # dispatch carries the span, fetch the decode tokens
                    self._note_anatomy("mixed",
                                       dispatch_tokens=n_decode + c,
                                       fetch_tokens=n_decode)
                    remaining -= c

    def _note_spec(self, req: GenerationRequest,
                   completion_tokens: int) -> None:
        """Deterministic tree-speculation accounting for one generated
        request (no output effect; see __init__).  The emulated verify
        accepts full chain depth when the request carries a draft hint
        (cross-refresh: the previous summary restating itself) and half
        depth otherwise, so each step emits ``1 + acc`` tokens; step
        count, node count (1 + W*k drafted per row) and accepted depth
        all derive from token counts only — byte-reproducible across
        arms and hosts."""
        k, width = self.spec_k, self.spec_width
        acc = k if req.draft_hint else max(1, k // 2)
        steps = -(-completion_tokens // (1 + acc))
        with self._mixed_lock:
            self._spec_dispatches += steps
            self._spec_rows += steps
            self._spec_nodes_sum += steps * (1 + width * k)
            self._spec_depth_sum += steps * acc
            self._spec_accepted += steps * acc
        # each emulated spec step is one "spec" iteration: dispatch
        # carries the full tree span, fetch the emitted tokens; drafting
        # is fused on-device, so the draft segment stays dispatch-only
        # (zero host time) — exactly the anatomy shift the real tree
        # path exists to produce
        for _ in range(steps):
            self._note_anatomy("spec",
                               dispatch_tokens=1 + width * k,
                               fetch_tokens=1 + acc)

    def _note_anatomy(self, cls: str, *, dispatch_tokens: int,
                      fetch_tokens: int) -> None:
        """One emulated scheduler iteration (obs/anatomy.py parity, see
        __init__): fixed one-token admit/plan/finish segments plus
        token-count-derived dispatch/fetch, all at EMU_SECONDS_PER_TOKEN
        — wall equals the segment sum exactly, so the mock's anatomy is
        conservation-perfect and byte-reproducible."""
        if not anatomy_enabled():
            return
        spt = self.EMU_SECONDS_PER_TOKEN
        segs = {s: 0.0 for s in SEGMENTS}
        segs["admit"] = spt
        segs["plan"] = spt
        segs["dispatch"] = max(0, int(dispatch_tokens)) * spt
        segs["fetch"] = max(0, int(fetch_tokens)) * spt
        segs["finish"] = spt
        with self._an_lock:
            for s in SEGMENTS:
                self._an_segs[s] += segs[s]
            self._an_cls[cls].append(
                (sum(segs.values()), tuple(segs[s] for s in SEGMENTS)))

    def _note_rpa_bucket(self, tpb: int, real_tokens: int) -> None:
        """Bucket-economics parity for one emulated ragged-span dispatch:
        the real-vs-padded split the scheduler's profiler counts, with a
        deterministic emulated compile cost (bucket * EMU_SECONDS_PER_
        TOKEN) on first sight of a shape."""
        if not anatomy_enabled():
            return
        pages = -(-max(1, int(real_tokens)) // self.EMU_PAGE_TOKENS)
        w = pow2_bucket(pages, 4)
        with self._an_lock:
            first = (tpb, w) not in self._an_buckets
            rec = self._an_buckets.setdefault((tpb, w), {
                "dispatches": 0, "real": 0, "padded": 0, "compile_s": 0.0})
            rec["dispatches"] += 1
            rec["real"] += int(real_tokens)
            rec["padded"] += max(tpb - int(real_tokens), 0)
            if first:
                rec["compile_s"] = tpb * self.EMU_SECONDS_PER_TOKEN

    def anatomy_report(self) -> dict:
        """Optional Engine hook: the ``GET /v1/anatomy`` document — same
        shape as the scheduler's (obs/anatomy.py ``StepAnatomy.report``),
        deterministically derived from token counts."""
        if not anatomy_enabled():
            return {"object": "anatomy", "enabled": False}
        with self._an_lock:
            segs = dict(self._an_segs)
            cls_recs = {c: list(rs) for c, rs in self._an_cls.items()}
            bucket_recs = {k: dict(v) for k, v in self._an_buckets.items()}
        iters = sum(len(rs) for rs in cls_recs.values())
        wall = sum(segs.values())  # residual is 0 by construction
        host = wall - segs["dispatch"] - segs["fetch"]
        classes: dict[str, dict] = {}
        for cls in CLASSES:
            rs = cls_recs[cls]
            if not rs:
                continue
            walls = sorted(r[0] for r in rs)
            p50: dict[str, float] = {}
            p95: dict[str, float] = {}
            for i, s in enumerate(SEGMENTS):
                vals = sorted(r[1][i] for r in rs)
                p50[s] = round(_pct(vals, 50) * 1e6, 1)
                p95[s] = round(_pct(vals, 95) * 1e6, 1)
            p50["wall"] = round(_pct(walls, 50) * 1e6, 1)
            p95["wall"] = round(_pct(walls, 95) * 1e6, 1)
            classes[cls] = {"iterations": len(rs),
                            "p50_us": p50, "p95_us": p95}
        buckets: dict[str, dict] = {}
        tot_real = tot_pad = 0
        for (tpb, w), rec in sorted(bucket_recs.items()):
            span = rec["real"] + rec["padded"]
            buckets[f"{tpb}x{w}"] = {
                "dispatches": rec["dispatches"],
                "real_tokens": rec["real"],
                "padded_tokens": rec["padded"],
                "pad_waste": round(rec["padded"] / span, 4) if span else 0.0,
                "compile_ms": round(rec["compile_s"] * 1e3, 1),
            }
            tot_real += rec["real"]
            tot_pad += rec["padded"]
        return {
            "object": "anatomy",
            "enabled": True,
            "iterations": iters,
            "aborted_iterations": 0,
            "wall_ms": round(wall * 1e3, 3),
            "residual_ms": 0.0,
            "segments_ms": {s: round(segs[s] * 1e3, 3) for s in SEGMENTS},
            "host_overhead_us_step": (round(host * 1e6 / iters, 1)
                                      if iters > 0 else None),
            "classes": classes,
            "buckets": buckets,
            "rpa_pad_waste_ratio": (
                round(tot_pad / (tot_real + tot_pad), 4)
                if (tot_real + tot_pad) else None),
        }

    def _note_prefix(self, req: GenerationRequest) -> None:
        """Deterministic prefix-cache + spill-tier accounting for one
        generated request (no output effect; see __init__).  First sight
        of a preamble inserts it resident; a later request with the same
        preamble is a hit (tokens_reused += preamble tokens); a hit on a
        SPILLED entry additionally accounts a prefetch and promotes it
        back.  Resident capacity is ``EMU_RESIDENT_TOKENS`` LRU — over
        it, oldest entries spill (tier armed) or drop (tier off), and
        the emulated host pool drops LRU entries past ``host_kv_gb``."""
        if not self.prefix_cache:
            return
        key = preamble_key(req.system_prompt, req.prompt, req.cache_prefix)
        if key is None:
            return
        tokens = self._tok.count(preamble_text(
            req.system_prompt, req.prompt, req.cache_prefix))
        pages = -(-tokens // self.EMU_PAGE_TOKENS)
        with self._prefix_lock:
            self._prefix_tick += 1
            self._prefix_queries += 1
            ent = self._prefix.get(key)
            if ent is not None:
                self._prefix_hits += 1
                self._prefix_tokens_reused += ent["tokens"]
                spilled = ent["tier"] == "spilled"
                if spilled:
                    self._spilled_hits += 1
                    self._tokens_prefetched += ent["tokens"]
                    self._prefetch_pages += pages
                    ent["tier"] = "resident"
                self.ledger.note_saved(
                    req,
                    prefix_tokens=0 if spilled else ent["tokens"],
                    prefetched_tokens=ent["tokens"] if spilled else 0,
                    prefetched_bytes=(ent["tokens"]
                                      * self.EMU_BYTES_PER_TOKEN
                                      if spilled else 0.0))
                if self.ledger.enabled:  # popped by _bill; no entry may
                    self._billing_saved[req.request_id] = (  # outlive it
                        self._billing_saved.get(req.request_id, 0)
                        + ent["tokens"])
            else:
                ent = {"tokens": tokens, "tier": "resident", "tick": 0}
                self._prefix[key] = ent
            ent["tick"] = self._prefix_tick
            self._enforce_emulated_budgets()

    def _enforce_emulated_budgets(self) -> None:  # holds-lock: _prefix_lock
        """Caller holds self._prefix_lock."""
        def lru(tier: str):
            cands = [(e["tick"], k) for k, e in self._prefix.items()
                     if e["tier"] == tier]
            return min(cands)[1] if cands else None

        def resident_tokens() -> int:
            return sum(e["tokens"] for e in self._prefix.values()
                       if e["tier"] == "resident")

        while resident_tokens() > self.EMU_RESIDENT_TOKENS:
            key = lru("resident")
            if key is None:
                break
            ent = self._prefix[key]
            pages = -(-ent["tokens"] // self.EMU_PAGE_TOKENS)
            if (self.host_kv and ent["tokens"] * self.EMU_BYTES_PER_TOKEN
                    <= self.host_kv_budget_bytes):
                ent["tier"] = "spilled"
                self._spill_pages += pages
            else:
                del self._prefix[key]

        def spilled_bytes() -> int:
            return sum(e["tokens"] * self.EMU_BYTES_PER_TOKEN
                       for e in self._prefix.values()
                       if e["tier"] == "spilled")

        while spilled_bytes() > self.host_kv_budget_bytes:
            key = lru("spilled")
            if key is None:
                break
            ent = self._prefix.pop(key)
            self._host_dropped_pages += -(-ent["tokens"]
                                          // self.EMU_PAGE_TOKENS)

    def prefix_summary(self, top_k: int = 16) -> list[dict]:
        """Deterministic radix-summary publication (the router's routing
        feed) — same row shape as the scheduler's."""
        if not self.prefix_cache:
            return []
        with self._prefix_lock:
            rows = sorted(self._prefix.items(),
                          key=lambda kv: -kv[1]["tick"])[:top_k]
            out = []
            for key, ent in rows:
                res = ent["tier"] == "resident"
                pages = -(-ent["tokens"] // self.EMU_PAGE_TOKENS)
                out.append({
                    "hash": key,
                    "depth_tokens": ent["tokens"],
                    "tick": ent["tick"],
                    "resident_tokens": ent["tokens"] if res else 0,
                    "resident_pages": pages if res else 0,
                    "spilled_tokens": 0 if res else ent["tokens"],
                    "spilled_pages": 0 if res else pages,
                })
        return out

    # ------------------------------------------------- KV-fabric migration
    # (optional Engine surface, same getattr convention as the handoff
    # hooks): page-set export/import on the no-device arm.  The mock's
    # "page set" is the emulated prefix entry itself — tokens plus a
    # deterministic content tag — so a migrated preamble counts as a
    # prefix HIT on the importing host (the chaos gate's fabric-token
    # assertion) without any device bytes moving.

    def kv_export(self, preamble: str) -> dict | None:
        """Wire payload for one warm preamble, or None when the cache is
        off / the preamble is cold (the server's 404 path).  Read-only:
        the exporting cache keeps its entry (source stays warm until it
        drains away naturally)."""
        if not self.prefix_cache:
            return None
        faults.fire("migrate.export")
        with self._prefix_lock:
            ent = self._prefix.get(preamble)
            if ent is None:
                return None
            self._migrate_exports += 1
            return {"kind": "kv_pageset", "version": 1, "emu": True,
                    "preamble": preamble, "tokens": ent["tokens"],
                    "seed": self.seed}

    def kv_import(self, payload: dict) -> int:
        """Install a migrated page set as a warm resident prefix entry.
        Geometry mismatch (a jax page-set payload, or a mock arm with a
        different seed — different completion bytes) raises ValueError:
        the server answers 409/4xx and the router falls back to cold
        resume, never a silently-wrong cache hit."""
        if not self.prefix_cache:
            raise RuntimeError("prefix cache disabled")
        if payload.get("kind") != "kv_pageset" or not payload.get("emu"):
            raise ValueError("not an emulated kv_pageset payload")
        if payload.get("seed", self.seed) != self.seed:
            raise ValueError("mock seed mismatch: emulated KV bytes differ")
        key = payload["preamble"]
        tokens = int(payload["tokens"])
        if not key or tokens <= 0:
            raise ValueError("malformed kv_pageset payload")
        faults.fire("migrate.import")
        with self._prefix_lock:
            self._prefix_tick += 1
            self._prefix[key] = {"tokens": tokens, "tier": "resident",
                                 "tick": self._prefix_tick}
            self._migrate_imports += 1
            self._migrate_tokens += tokens
            self._enforce_emulated_budgets()
        return tokens

    def _bill(self, req: GenerationRequest,
              res: GenerationResult) -> None:
        """Deterministic ledger entry for one finished mock request:
        prompt tokens bill as prefill, completion tokens as decode, at
        EMU_SECONDS_PER_TOKEN each (emulated pages at EMU_PAGE_TOKENS
        granularity).  Token-count-derived, so two arms running the same
        traffic produce byte-identical usage sums."""
        if not self.ledger.enabled:
            return
        spt = self.EMU_SECONDS_PER_TOKEN
        with self._prefix_lock:
            saved = self._billing_saved.pop(res.request_id, 0)
        # saved tokens never entered a prefill dispatch on the real
        # scheduler, so the mock must not bill them either — with the
        # cache serving the whole prompt there is NO prefill step
        billed = max(0, res.prompt_tokens - saved)
        if billed:
            self.ledger.note_step(
                billed * spt,
                prefill_rows=[(req, billed, float(billed))],
                prefill_cost_s=1.0)
        if res.completion_tokens:
            pages = -(-(res.prompt_tokens + res.completion_tokens)
                      // self.EMU_PAGE_TOKENS)
            self.ledger.note_step(
                res.completion_tokens * spt,
                decode_rows=[(req, res.completion_tokens, pages)],
                decode_cost_s=1.0)
        res.usage = self.ledger.finish(req, res)

    def usage_report(self) -> dict:
        """Optional Engine hook: the ``GET /v1/usage`` document (same
        shape as the scheduler's)."""
        return self.ledger.usage_report()

    def slo_report(self) -> dict:
        """Optional Engine hook: the ``/healthz`` ``slo`` block."""
        return self.slo.report()

    def shutdown(self) -> None:
        pass

    def cancel(self, request_id: int) -> None:
        """Engine optional abort hook (see engine/api.py).  Recorded; any
        request of the current batch not yet generated when its id lands
        here comes back finish_reason="cancelled"."""
        self.cancelled.add(request_id)

    def engine_metrics(self) -> dict:
        out: dict = {}
        with self._mixed_lock:
            d, p, f = (self._mixed_dispatches, self._mixed_piggybacked,
                       self._mixed_fill_sum)
        if d:
            out["mixed_batch"] = {
                "enabled": self.mixed_batch,
                "token_budget": self.mixed_token_budget,
                "dispatches": d,
                "fill_ratio": round(f / d, 3) if d else 0.0,
                "prefill_tokens_piggybacked": p,
            }
        with self._mixed_lock:
            rd, rt, rs = (self._rpa_dispatches, self._rpa_span_tokens,
                          len(self._rpa_shapes))
        if rd:
            out["rpa"] = {
                "enabled": self.rpa,
                "dispatches": rd,
                "span_tokens": rt,
                "compile_shapes": rs,
            }
        with self._mixed_lock:
            sd, sr, sn, sdep, sacc = (
                self._spec_dispatches, self._spec_rows,
                self._spec_nodes_sum, self._spec_depth_sum,
                self._spec_accepted)
        if sd:
            # same keys as the scheduler's _spec_tree_report block
            out["spec_accepted_tokens"] = sacc
            out["spec_tree"] = {
                "enabled": self.spec_tree,
                "width": self.spec_width,
                "adaptive": self.spec_adaptive,
                "dispatches": sd,
                "mean_nodes": round(sn / sr, 3) if sr else 0.0,
                "mean_accept_depth": round(sdep / sr, 3) if sr else 0.0,
                "accept_per_step": round(sacc / sr, 3) if sr else 0.0,
            }
        with self._prefix_lock:
            if self._prefix_queries:
                out["prefix_cache"] = {
                    "hit_rate": round(
                        self._prefix_hits / self._prefix_queries, 3),
                    "hits": self._prefix_hits,
                    "queries": self._prefix_queries,
                    "tokens_reused": self._prefix_tokens_reused,
                    "prefill_tokens_saved": self._prefix_tokens_reused,
                    "spilled_hits": self._spilled_hits,
                    "tokens_prefetched": self._tokens_prefetched,
                }
                out["host_kv"] = {
                    "enabled": self.host_kv,
                    "budget_gb": round(
                        self.host_kv_budget_bytes / 2**30, 3),
                    "spilled_hits": self._spilled_hits,
                    "tokens_prefetched": self._tokens_prefetched,
                    "spill_pages": self._spill_pages,
                    "prefetch_pages": self._prefetch_pages,
                    "dropped_pages_total": self._host_dropped_pages,
                }
            if self._migrate_exports or self._migrate_imports:
                # same report-nothing-when-idle contract as the other
                # blocks: with LMRS_KV_MIGRATE=0 no migration ever runs,
                # so the block is absent and metrics stay byte-identical
                out["kv_migrate"] = {
                    "exports": self._migrate_exports,
                    "imports": self._migrate_imports,
                    "tokens_imported": self._migrate_tokens,
                }
        # the cost block appears once work flowed (the same
        # report-nothing-when-idle contract as the mixed/prefix blocks).
        # Deliberately NO slo block here: engine_metrics is contractually
        # deterministic for identical traffic (test_mixed asserts it) and
        # SLO burns are wall-clock-fed — consumers read slo_report()
        if self.ledger.enabled and self.ledger.finished_count:
            out["cost"] = self.ledger.report()
        # anatomy block: deterministic (token-count-derived), same
        # report-nothing-when-idle + LMRS_ANATOMY=0 shape contract as the
        # scheduler's metrics_report
        if anatomy_enabled():
            an = self.anatomy_report()
            if an.get("iterations"):
                out["anatomy"] = an
        # no work recorded at all: the mock reports no engine metrics,
        # as it always has
        return out

    # ---------------------------------------- disaggregated handoff hooks

    def export_handoff(self, request_id: int) -> dict:
        """Wire payload of a pinned mock handoff (KeyError when unknown /
        already released — the ticket 410 path)."""
        with self._pinned_lock:
            return self._pinned[request_id]["payload"]

    def release_handoff(self, request_id: int, orphaned: bool = False) -> int:
        with self._pinned_lock:
            return 1 if self._pinned.pop(request_id, None) else 0

    def sweep_handoffs(self, now: float | None = None) -> int:
        now = time.time() if now is None else now
        with self._pinned_lock:
            expired = [r for r, rec in self._pinned.items()
                       if rec["deadline_t"] <= now]
            for r in expired:
                self._pinned.pop(r)
        return len(expired)

    def pinned_handoffs(self) -> dict[int, int]:
        with self._pinned_lock:
            return {r: 1 for r in self._pinned}

    def _one(self, req: GenerationRequest) -> GenerationResult:
        def expired() -> bool:
            return (req.deadline_s is not None
                    and time.time() >= req.deadline_s)

        # deadline lifecycle on the no-device path, same split as the
        # scheduler: expired BEFORE any work -> shed (zero-cost explicit
        # rejection); expired during the simulated generation latency ->
        # deadline (work was spent)
        if expired():
            return GenerationResult(request_id=req.request_id,
                                    finish_reason="shed")
        if self.latency_s:
            time.sleep(self.latency_s)
            if expired():
                return GenerationResult(request_id=req.request_id,
                                        finish_reason="deadline")
        if req.request_id in self.cancelled:
            return GenerationResult(request_id=req.request_id,
                                    finish_reason="cancelled")
        if self.fail_pattern and self.fail_pattern in req.prompt:
            return GenerationResult(
                request_id=req.request_id,
                finish_reason="error",
                error="mock: injected failure",
            )
        if req.handoff_state is not None:
            # disaggregated decode role: resume from the TRANSFERRED state
            # — the payload's text is returned, never recomputed, so the
            # result proves the handoff actually carried the prefill pod's
            # state across (a recompute would mask a broken transfer)
            # fault degrades per request (same contract as the jax arm:
            # a marked import failure the router retries/falls back on,
            # never a whole-wave error)
            try:
                faults.fire("handoff.import")
            except Exception as e:  # noqa: BLE001 - injected fault
                return GenerationResult(
                    request_id=req.request_id, finish_reason="error",
                    error=f"handoff import failed: {type(e).__name__}: {e}")
            state = req.handoff_state
            # continue the exporter's trace across the pod boundary (the
            # same adoption rule as the scheduler's _admit_import)
            if not req.trace_id and isinstance(state.get("trace_id"), str):
                req.trace_id = state["trace_id"]
            if not req.tenant and isinstance(state.get("tenant"), str):
                req.tenant = state["tenant"]
            tr = get_tracer()
            if tr:
                tr.instant(
                    "handoff_import", tid=_mock_tid(tr, req),
                    args={"pages": 0,  # the mock's state is pageless text
                          "kv_len": int(state.get("prompt_tokens", 0))})
            text = state["text"]
            return GenerationResult(
                request_id=req.request_id,
                text=text,
                prompt_tokens=int(state.get("prompt_tokens", 0)),
                completion_tokens=self._tok.count(text),
                finish_reason=str(state.get("finish_reason", "stop")),
                stop_sequence=state.get("stop_sequence"),
            )
        # prefix-cache/spill accounting: every request that actually
        # "prefills" here (plain completions and prefill-role exports;
        # handoff imports resumed above without prefilling)
        self._note_prefix(req)
        text, stop_hit = apply_stop_sequences(
            self._extractive_sketch(req.prompt), req.stop)
        prompt_tokens = self._tok.count(req.prompt)
        if req.handoff_export:
            # prefill role: emit only the first "token" (up to the first
            # whitespace) and pin the full completion as the transferable
            # state; a completion that IS its first token returns as a
            # normal terminal result — nothing left to hand off
            cut = text.find(" ")
            first = text if cut < 0 else text[:cut + 1]
            if first != text:
                try:
                    faults.fire("handoff.export")
                except Exception as e:  # noqa: BLE001 - injected fault
                    return GenerationResult(
                        request_id=req.request_id, finish_reason="error",
                        error=f"handoff export failed: "
                              f"{type(e).__name__}: {e}")
                payload = {"text": text, "prompt_tokens": prompt_tokens,
                           "stop_sequence": stop_hit,
                           "finish_reason": "stop"}
                if req.trace_id:
                    payload["trace_id"] = req.trace_id
                if req.tenant:
                    payload["tenant"] = req.tenant
                if req.qos_class:
                    payload["qos_class"] = req.qos_class
                with self._pinned_lock:
                    self._pinned[req.request_id] = {
                        "payload": payload,
                        "deadline_t": time.time() + self.handoff_ttl_s}
                tr = get_tracer()
                if tr:  # the stitcher's skew anchor on the prefill pod
                    tr.instant(
                        "handoff_export", tid=_mock_tid(tr, req),
                        args={"pages": 0, "kv_len": prompt_tokens})
                return GenerationResult(
                    request_id=req.request_id,
                    text=first,
                    prompt_tokens=prompt_tokens,
                    completion_tokens=self._tok.count(first),
                    finish_reason="handoff",
                )
        return GenerationResult(
            request_id=req.request_id,
            text=text,
            prompt_tokens=prompt_tokens,
            completion_tokens=self._tok.count(text),
            finish_reason="stop",
            stop_sequence=stop_hit,
        )

    def _extractive_sketch(self, prompt: str) -> str:
        """First/middle/last content sentences + every timestamp, capped.

        Deterministic in (prompt, seed); no randomness so repeated runs are
        byte-identical (test requirement, SURVEY.md §4).
        """
        # Pull out the transcript / summaries body if the prompt embeds one.
        body = prompt
        for marker in ("Transcript section:", "Partial summaries:", "Intermediate summaries:"):
            if marker in body:
                body = body.split(marker, 1)[-1]
        sentences = [s.strip() for s in re.split(r"(?<=[.!?])\s+", body) if len(s.strip()) > 30]
        stamps = _TS_RE.findall(body)
        digest = hashlib.sha256(f"{self.seed}:{prompt}".encode()).hexdigest()[:8]
        picked = []
        if sentences:
            idx = sorted({0, len(sentences) // 2, len(sentences) - 1})
            picked = [sentences[i] for i in idx]
        lines = [f"[mock-{digest}] Summary:"]
        lines += [f"- {s[:240]}" for s in picked]
        if stamps:
            uniq = list(dict.fromkeys(stamps))[:12]  # cap so reduce inputs stay bounded
            lines.append("Timestamps: " + " ".join(uniq))
        return "\n".join(lines)
