"""Paged KV cache: fixed page pool + host-side page allocator.

The serving-memory design SURVEY.md §7.4 ranks as hard part #1: a fixed-size
page pool in HBM ([n_layers * num_pages, K, page_size, hd] — see PagedKVCache
for the layer-flattened layout rationale) with per-slot page tables, so KV
memory is allocated in O(page) quanta instead of one max_seq_len region per
slot.  Admission control = free pages (the reference's semaphore analog,
SURVEY.md §2.2).

The allocator is deliberately tiny and host-side (free-list); a C++
implementation with the same interface lives in runtime/native (used when
built — see lmrs_tpu.runtime.native) since allocator churn sits on the
scheduler's critical path.
"""

from __future__ import annotations

import logging
from dataclasses import dataclass

import jax
import jax.numpy as jnp
import numpy as np

from lmrs_tpu.config import ModelConfig
from lmrs_tpu.testing import faults

logger = logging.getLogger("lmrs.kv_cache")


class OutOfPages(RuntimeError):
    """Page pool exhausted — callers treat this as back-pressure, not error."""


class PageAllocator:
    """Ref-counted free-list page allocator (python reference implementation).

    Page 0 is RESERVED as the null page and never handed out: inactive batch
    rows carry all-zero page tables, and their masked-out dummy writes must
    land somewhere no live sequence owns (the vLLM null-block trick).

    Pages carry a reference count so the prefix cache can share one
    physical page read-only across live sequences (engine/prefix_cache.py):
    ``alloc`` hands out pages at refcount 1, ``incref`` adds a holder, and
    ``free`` is a decref — the page returns to the free list only when the
    last holder releases it.  Freeing a page that is already free raises
    ``ValueError`` instead of silently corrupting the pool (a double-freed
    page on the free list would be handed to two sequences at once).
    """

    RESERVED = 1  # page 0

    def __init__(self, num_pages: int):
        if num_pages <= self.RESERVED:
            raise ValueError("need at least 2 pages (page 0 is reserved)")
        self.num_pages = num_pages
        self._free = list(range(num_pages - 1, self.RESERVED - 1, -1))
        self._refs = [0] * num_pages  # refcount per page (0 == on free list)

    @property
    def free_count(self) -> int:
        return len(self._free)

    def alloc(self, n: int) -> list[int]:
        if n > len(self._free):
            raise OutOfPages(f"need {n} pages, {len(self._free)} free")
        pages = [self._free.pop() for _ in range(n)]
        for p in pages:
            self._refs[p] = 1
        return pages

    def _check(self, pages: list[int], op: str) -> None:
        """Validate a free/incref batch BEFORE any mutation (the native
        allocator's contract): range-check every id, and require each
        page's refcount to cover its multiplicity in the call — so a
        rejected call leaves the pool untouched."""
        need: dict[int, int] = {}
        for p in pages:
            if not self.RESERVED <= p < self.num_pages:
                raise ValueError(f"bad page id {p}")
            need[p] = need.get(p, 0) + 1
        for p, n in need.items():
            if self._refs[p] < n:
                raise ValueError(
                    f"{op} of page {p} with refcount {self._refs[p]} "
                    f"(x{n} in call): double-free / unowned page")

    def incref(self, pages: list[int]) -> None:
        """Add one reference per listed page (prefix-cache sharing).  Only
        live (refcount > 0) pages may gain holders."""
        self._check(pages, "incref")
        for p in pages:
            self._refs[p] += 1

    def refcount(self, page: int) -> int:
        if not 0 <= page < self.num_pages:
            raise ValueError(f"bad page id {page}")
        return self._refs[page]

    def free(self, pages: list[int]) -> None:
        """Release one reference per listed page; pages reaching refcount 0
        return to the free list.  Raises on double-free (see class doc)."""
        self._check(pages, "free")
        for p in pages:
            self._refs[p] -= 1
            if self._refs[p] == 0:
                self._free.append(p)


def make_page_allocator(num_pages: int):
    """Native C++ allocator when built, else the Python free list.

    Both implement the identical contract (parity: tests/test_native.py);
    allocator churn sits on the scheduler's critical path, so the native one
    is preferred.
    """
    try:
        from lmrs_tpu.runtime.native import NativePageAllocator, native_available

        if native_available():
            return NativePageAllocator(num_pages)
    except Exception as e:  # pragma: no cover - fallback path
        logger.debug("native allocator unavailable: %s", e)
    return PageAllocator(num_pages)


@dataclass
class SequencePages:
    """Page table of one active sequence."""

    pages: list[int]
    length: int = 0  # tokens written

    def capacity(self, page_size: int) -> int:
        return len(self.pages) * page_size


class PagedKVCache:
    """Device page pool + per-slot host page tables.

    Layout [L*P, K, page_size, hd] — PAGE-major (round 3): one page's ALL
    kv heads are a contiguous [K, page_size, hd] block, so the ragged
    decode kernel fetches a page with ONE DMA instead of one per head (the
    decode walk measured DMA-issue-bound; docs/PERF.md round 3).  The
    layer axis is FLATTENED into the page axis: layer ``li``'s copy of
    logical page ``p`` is physical page ``li * P + p``.
    That lets the per-layer decode scatter write straight into the full
    carried pool with global page ids — no per-layer slice/update round
    trip, which would otherwise move the whole layer slice every decode
    step (models/transformer.forward_paged).  A slot's logical KV position
    maps to (page_table[pos // ps], pos % ps); tables hold LOGICAL page ids
    (< P) and are globalized per layer inside the forward.
    """

    def __init__(self, model_cfg: ModelConfig, num_pages: int, page_size: int,
                 max_pages_per_slot: int, allocator: PageAllocator | None = None,
                 mesh=None, kv_dtype: str | None = None):
        hd = model_cfg.hd
        self.page_size = page_size
        self.num_pages = num_pages
        self.max_pages_per_slot = max_pages_per_slot
        # int8 pools (EngineConfig.kv_quantize): half the bytes per streamed
        # page and double the tokens per HBM GiB; scales are scheduler-owned
        # (ops/quant.py KV section)
        dt = jnp.dtype(kv_dtype) if kv_dtype else jnp.dtype(model_cfg.dtype)
        shape = (model_cfg.n_layers * num_pages, model_cfg.n_kv_heads,
                 page_size, hd)
        if mesh is not None:
            # tensor-parallel serving: pages shard on the kv-head axis,
            # matching the wk/wv head sharding — each shard's attention and
            # page writes stay local, no cross-chip KV traffic.  tp=1 still
            # places on the mesh (replicated): a DP replica's cache must pin
            # to ITS devices, not the process default device.
            from jax.sharding import NamedSharding, PartitionSpec as P

            tp = mesh.shape.get("tp", 1)
            if tp > 1 and model_cfg.n_kv_heads % tp:
                raise ValueError(
                    f"n_kv_heads={model_cfg.n_kv_heads} not divisible by "
                    f"tp={tp}")
            sh = NamedSharding(mesh, P(None, "tp") if tp > 1 else P())
            self.k = jnp.zeros(shape, dt, device=sh)
            self.v = jnp.zeros(shape, dt, device=sh)
        else:
            self.k = jnp.zeros(shape, dt)
            self.v = jnp.zeros(shape, dt)
        self.allocator = allocator or make_page_allocator(num_pages)
        # Page-pressure reclaim hook (engine/prefix_cache.py): when set, an
        # allocation that would exceed the free list first asks the hook to
        # release reclaimable pages (LRU cache eviction).  Keeps the
        # admission/growth deadlock argument intact: cached pages are never
        # pinned — under pressure they drain back into the pool on demand.
        self.reclaim_cb = None
        logger.info(
            "paged KV cache: %d pages x %d tokens (%.1f MiB)",
            num_pages, page_size,
            2 * np.prod(shape) * dt.itemsize / 2**20,
        )

    def reallocate(self) -> None:
        """Fresh zeroed pools with the same shape/dtype/sharding.  Recovery
        hook for a failed DONATED dispatch chain (roofline_microbench): the
        old buffers may already be consumed, leaving self.k/v unusable.
        Only valid while no sequence is live (content is discarded)."""
        self.k = jnp.zeros(self.k.shape, self.k.dtype, device=self.k.sharding)
        self.v = jnp.zeros(self.v.shape, self.v.dtype, device=self.v.sharding)

    def pages_needed(self, n_tokens: int) -> int:
        return -(-n_tokens // self.page_size)

    def can_admit(self, n_tokens: int) -> bool:
        return self.pages_needed(n_tokens) <= self.allocator.free_count

    def alloc_pages(self, n: int) -> list[int]:
        """``allocator.alloc`` with the reclaim hook applied: under pressure,
        ask the prefix cache to evict before declaring OutOfPages."""
        # injection site: a fired plan forces the back-pressure path even
        # with free pages on hand — every caller must already treat
        # OutOfPages as pressure, not error (tests/test_chaos.py proves it)
        faults.fire("kv_cache.allocate", OutOfPages)
        if n > self.allocator.free_count and self.reclaim_cb is not None:
            self.reclaim_cb(n - self.allocator.free_count)
        return self.allocator.alloc(n)

    def open_sequence(self, n_tokens: int) -> SequencePages:
        """Allocate pages for a sequence expected to reach n_tokens (capped
        at max_pages_per_slot — callers clamp write positions accordingly)."""
        n = min(self.pages_needed(n_tokens), self.max_pages_per_slot)
        return SequencePages(pages=self.alloc_pages(n))

    def grow(self, seq: SequencePages, n_tokens: int) -> None:
        """Ensure capacity for n_tokens, allocating more pages as needed."""
        need = self.pages_needed(n_tokens) - len(seq.pages)
        if need > 0:
            if len(seq.pages) + need > self.max_pages_per_slot:
                raise OutOfPages("sequence exceeds max_pages_per_slot")
            seq.pages.extend(self.alloc_pages(need))

    def close_sequence(self, seq: SequencePages) -> None:
        self.allocator.free(seq.pages)
        seq.pages = []
        seq.length = 0

    def page_table_array(self, seqs: list[SequencePages | None]) -> np.ndarray:
        """[B, max_pages_per_slot] int32 table; unused entries point at page 0
        (masked out by per-row lengths)."""
        out = np.zeros((len(seqs), self.max_pages_per_slot), np.int32)
        for i, s in enumerate(seqs):
            if s is not None:
                out[i, : len(s.pages)] = s.pages
        return out

    # -------------------------------------------- sequence export / import

    @property
    def n_layers(self) -> int:
        return self.k.shape[0] // self.num_pages

    def _phys_ids(self, pages: list[int]) -> np.ndarray:
        """Physical page ids of a logical page set, all layers: layer li's
        copy of logical page p is physical page ``li * P + p`` (the
        layer-flattened pool layout, class doc)."""
        pg = np.asarray(pages, np.int64)
        return (np.arange(self.n_layers)[:, None] * self.num_pages
                + pg[None, :]).reshape(-1)

    def export_sequence(self, seq: SequencePages, length: int) -> dict:
        """Gather a sequence's page set into a host-side payload — the
        transferable unit of the disaggregated prefill→decode handoff
        (serving/handoff.py carries it over the wire).

        The page-major ``[L*P, K, ps, hd]`` layout makes the page set a
        contiguous unit: ONE gather over the flattened layer×page axis
        pulls every layer's copy.  Only the pages covering ``length``
        tokens are exported (a slot grown past the handoff point for
        decode-block capacity exports its prompt prefix only); the final
        page may be partial — ``kv_len`` in the payload masks the tail,
        exactly as per-row lengths do in the decode kernels.  Works for
        bf16 and int8-quantized pools alike (raw dtype bytes travel;
        int8's per-slot scales are scheduler-owned and ride the payload
        separately).  The sequence itself is untouched: the caller keeps
        the pages pinned until the importer acks (scheduler pin class).
        """
        faults.fire("handoff.export")
        n = self.pages_needed(max(1, length))
        if n > len(seq.pages):
            raise ValueError(
                f"export of {length} tokens needs {n} pages; sequence "
                f"holds {len(seq.pages)}")
        phys = jnp.asarray(self._phys_ids(seq.pages[:n]))
        L = self.n_layers
        # one batched fetch: on a tunneled chip each device_get is a full
        # host RTT, and this runs on the scheduler thread
        k, v = (np.asarray(a)
                for a in jax.device_get((self.k[phys], self.v[phys])))
        kh, ps, hd = self.k.shape[1:]
        return {
            "version": 1,
            "kv_len": int(length),
            "n_pages": n,
            "page_size": self.page_size,
            "n_layers": L,
            "n_kv_heads": int(kh),
            "head_dim": int(hd),
            "dtype": str(self.k.dtype),
            "k": k.reshape(L, n, kh, ps, hd),
            "v": v.reshape(L, n, kh, ps, hd),
        }

    def export_pages(self, pages: list[int]) -> dict:
        """Host capture of an arbitrary page set's contents, all layers —
        the spill tier's device→host path (engine/prefix_cache.py).  Same
        single batched gather over the layer-flattened pool as
        ``export_sequence`` (one RTT on a tunneled chip), minus the
        sequence framing: the prefix cache's radix node carries the token
        labels, so the payload is just raw page content + dtype."""
        phys = jnp.asarray(self._phys_ids(pages))
        k, v = (np.asarray(a)
                for a in jax.device_get((self.k[phys], self.v[phys])))
        kh, ps, hd = (int(x) for x in self.k.shape[1:])
        L, n = self.n_layers, len(pages)
        return {
            "k": k.reshape(L, n, kh, ps, hd),
            "v": v.reshape(L, n, kh, ps, hd),
            "dtype": str(self.k.dtype),
        }

    def import_pages(self, pages: list[int], payload: dict,
                     sync: bool = False) -> None:
        """Scatter a spilled payload back into freshly allocated pages —
        the prefetch half of the host-RAM tier.  Issued ASYNCHRONOUSLY by
        default: ``jnp.asarray`` + ``.at[].set`` dispatch without a host
        sync, the device sequences the copy before the next dispatch that
        consumes the pool, and the transfer overlaps the scheduler
        thread's host-side bookkeeping (the packing-prefetch overlap,
        PAPERS.md).  ``sync=True`` blocks until the scatter lands
        (``LMRS_HOST_KV_SYNC`` A/B fallback).  Geometry/dtype mismatches
        raise ``ValueError`` — same rejection discipline as
        ``import_sequence``; the caller re-prefills."""
        n = len(pages)
        if payload.get("dtype") != str(self.k.dtype):
            raise ValueError(
                f"spill payload dtype {payload.get('dtype')!r} != pool "
                f"{self.k.dtype}")
        kh, ps, hd = (int(x) for x in self.k.shape[1:])
        shape = (self.n_layers, n, kh, ps, hd)
        k = np.asarray(payload["k"])
        v = np.asarray(payload["v"])
        if k.shape != shape or v.shape != shape:
            raise ValueError(
                f"spill payload shape {k.shape} != expected {shape}")
        phys = jnp.asarray(self._phys_ids(pages))
        flat = (self.n_layers * n, kh, ps, hd)
        self.k = self.k.at[phys].set(
            jnp.asarray(k.reshape(flat), self.k.dtype))
        self.v = self.v.at[phys].set(
            jnp.asarray(v.reshape(flat), self.v.dtype))
        if sync:
            jax.block_until_ready((self.k, self.v))

    def page_payload_bytes(self) -> int:
        """Host bytes one spilled page costs (k + v, all layers) — the
        spill tier's budget/fits arithmetic."""
        kh, ps, hd = (int(x) for x in self.k.shape[1:])
        return 2 * self.n_layers * kh * ps * hd * self.k.dtype.itemsize

    def import_sequence(self, payload: dict) -> SequencePages:
        """Scatter an exported page set into freshly allocated local pages
        and return the live sequence (``length`` = the payload's kv_len).

        The destination's free-list state is arbitrary — imported pages
        land wherever the local allocator hands them out; the page table
        indirection makes the physical ids irrelevant to attention.
        Raises ``ValueError`` on an incompatible payload (pool geometry or
        dtype mismatch — a stale ticket from a differently-configured pod
        must be rejected, not silently mis-scattered) and ``OutOfPages``
        under pool pressure (back-pressure: the importer retries, never
        corrupts).  On any failure after allocation the pages are freed —
        a failed import must not leak."""
        faults.fire("handoff.import")
        kh, ps, hd = (int(x) for x in self.k.shape[1:])
        want = {"page_size": self.page_size, "n_layers": self.n_layers,
                "n_kv_heads": kh, "head_dim": hd, "dtype": str(self.k.dtype)}
        for key, val in want.items():
            got = payload.get(key)
            if got != val:
                raise ValueError(
                    f"incompatible handoff payload: {key}={got!r}, this "
                    f"pool has {val!r}")
        n = int(payload["n_pages"])
        length = int(payload["kv_len"])
        if not 0 < n <= self.max_pages_per_slot:
            raise ValueError(f"bad handoff page count {n}")
        if self.pages_needed(max(1, length)) != n:
            raise ValueError(
                f"handoff kv_len {length} does not cover {n} pages")
        k = np.asarray(payload["k"])
        v = np.asarray(payload["v"])
        shape = (self.n_layers, n, kh, ps, hd)
        if k.shape != shape or v.shape != shape:
            raise ValueError(
                f"handoff page data shape {k.shape} != expected {shape}")
        pages = self.alloc_pages(n)
        try:
            phys = jnp.asarray(self._phys_ids(pages))
            flat = (self.n_layers * n, kh, ps, hd)
            self.k = self.k.at[phys].set(
                jnp.asarray(k.reshape(flat), self.k.dtype))
            self.v = self.v.at[phys].set(
                jnp.asarray(v.reshape(flat), self.v.dtype))
        except Exception:
            self.allocator.free(pages)
            raise
        return SequencePages(pages=pages, length=length)


def audit_allocator(allocator, num_pages: int,
                    holders: dict[int, int]) -> list[str]:
    """Page-pool invariant audit (the scheduler's ``audit()`` core).

    ``holders`` maps page id -> how many references the CALLER can account
    for (live sequences + prefix-cache retention).  Checks, returning one
    human-readable string per violation (empty list = clean):

    * conservation — every non-reserved page is either free (refcount 0)
      or held (refcount > 0), and the two partitions sum to the pool;
    * refcount balance — each page's allocator refcount equals the
      accounted holder count (a leak shows as refcount > holders == 0; a
      double-free / stray incref as a mismatch);
    * no accounted holder points at a free or reserved page.

    Works against both allocator implementations (Python free-list and the
    native C++ one) through the shared ``free_count``/``refcount`` API.
    """
    violations: list[str] = []
    reserved = getattr(type(allocator), "RESERVED", 1)
    free = allocator.free_count
    held = 0
    for p in range(reserved, num_pages):
        rc = allocator.refcount(p)
        if rc < 0:
            violations.append(f"page {p}: negative refcount {rc}")
            continue
        if rc > 0:
            held += 1
        expected = holders.get(p, 0)
        if rc != expected:
            kind = "leaked" if expected == 0 else "unbalanced"
            violations.append(
                f"page {p}: refcount {rc} but {expected} accounted "
                f"holder(s) ({kind})")
    if free + held != num_pages - reserved:
        violations.append(
            f"page conservation broken: {free} free + {held} held != "
            f"{num_pages - reserved} usable")
    for p in holders:
        if not reserved <= p < num_pages:
            violations.append(f"holder references out-of-range page {p}")
    if allocator.refcount(0) != 0:
        violations.append("reserved null page has a nonzero refcount")
    return violations
