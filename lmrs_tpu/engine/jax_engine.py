"""JAX/TPU generation engine — the in-tree replacement for the reference's
remote LLM API (SURVEY.md: "L0 and L2 fuse").

Serving shape (v1 — dense KV cache; paged/continuous batching evolves in
engine/scheduler.py):

* requests are sorted by prompt length and packed into fixed-size batches of
  ``max_batch_slots`` (the reference's ``max_concurrent_requests`` analog);
* prompt lengths bucket to powers of two → one XLA compilation per
  (batch, bucket) pair, cached across calls;
* prefill runs the whole padded batch in one [B, S] forward (MXU-sized
  matmuls), decode runs an on-device ``lax.while_loop`` — zero host↔device
  round-trips inside a generation, early-exits when every row hits EOS;
* sampler params (temperature/top-k/top-p) are arrays, so mixed greedy +
  sampled batches share one compiled function.

Everything here is single-program; multi-chip sharding comes from the mesh
passed in (params placed via parallel.sharding; XLA lowers the same code to
per-device programs with ICI collectives).
"""

from __future__ import annotations

import logging
import os
import time
from functools import partial

import jax

# Environments whose sitecustomize force-registers an accelerator backend
# (jax.config.update("jax_platforms", ...)) silently override the standard
# JAX_PLATFORMS env var; honor an explicit cpu request here.
if os.environ.get("JAX_PLATFORMS", "").strip().lower() == "cpu":
    jax.config.update("jax_platforms", "cpu")
import jax.numpy as jnp
import numpy as np

from lmrs_tpu.config import EngineConfig, MeshConfig, ModelConfig
from lmrs_tpu.data.tokenizer import ByteTokenizer, get_tokenizer
from lmrs_tpu.engine.api import (GenerationRequest, GenerationResult,
                                 apply_stop_sequences)
from lmrs_tpu.models.transformer import forward, init_kv_cache, init_params, param_count
from lmrs_tpu.ops.sampling import sample_logits

logger = logging.getLogger("lmrs.jax_engine")


def _bf16_tree_gb(cfg: ModelConfig) -> float:
    """Config-level estimate of the full-precision param tree's size —
    the device-init feasibility test for quantized random weights.
    ``matmul_params`` counts only ACTIVATED experts (its per-token-work
    purpose); init materializes ALL of them, so the resident-MoE
    remainder is added back.  ``matmul_params`` also always counts the
    [D, V] LM head (it is a matmul whether tied or not), but a TIED
    model's tree holds ONE [V, D] matrix serving both embedding and head
    — subtract the head term or e.g. gemma-2b's estimate carries a
    phantom 1.05 GB and trips the 6.0 GB host-init gate early (ADVICE
    r5)."""
    from lmrs_tpu.utils.perf_model import matmul_params

    n = matmul_params(cfg) + cfg.vocab_size * cfg.dim
    if cfg.tie_embeddings:
        n -= cfg.vocab_size * cfg.dim
    if cfg.n_experts:
        n += (cfg.n_layers * 3 * cfg.dim * cfg.hidden_dim
              * (cfg.n_experts - cfg.n_experts_per_token))
    return n * 2 / 1e9


def needs_host_quant_init(cfg: ModelConfig, quantize: str | None) -> bool:
    """True when random-init weights must be built int8 on the HOST
    (numpy) instead of full-precision on the device: the engine asked for
    weight quantization AND the bf16 tree is too big to ever materialize
    on one chip (or anywhere, under the axon tunnel — no jax CPU backend
    to stage it on).  THE one implementation of the gate: JaxEngine and
    ReplicatedEngine both route through it, so the 6.0 GB threshold and
    the tied-embedding accounting cannot drift between the two engines
    (ADVICE r5).  Small quantized models deliberately keep the device
    init — the host RNG draws DIFFERENT weights, which silently changed
    the 1B bench workload once (docs/PERF.md round 5)."""
    return bool(quantize) and _bf16_tree_gb(cfg) > 6.0


def _bucket(n: int, lo: int = 64) -> int:
    b = lo
    while b < n:
        b *= 2
    return b


class JaxEngine:
    """Single-host JAX engine over a dense KV cache."""

    def __init__(
        self,
        engine_cfg: EngineConfig,
        model_cfg: ModelConfig,
        mesh_cfg: MeshConfig | None = None,
        params=None,
        tokenizer=None,
        devices=None,
    ):
        self.cfg = engine_cfg
        self.model_cfg = model_cfg
        self.mesh_cfg = mesh_cfg
        self.tokenizer = tokenizer or self._default_tokenizer()
        # A tokenizer whose ids exceed the model vocabulary would fail
        # SILENTLY: JAX clamps out-of-range embedding gathers (every big id
        # embeds as the last row) and an out-of-range eos_id can never be
        # sampled, so requests run to budget producing garbage.  Refuse.
        if (self.tokenizer.vocab_size > model_cfg.vocab_size
                or self.tokenizer.eos_id >= model_cfg.vocab_size):
            raise ValueError(
                f"tokenizer vocab ({self.tokenizer.vocab_size}, eos "
                f"{self.tokenizer.eos_id}) does not fit model vocab "
                f"({model_cfg.vocab_size}); pick a tokenizer the model was "
                "trained with (--tokenizer) or a matching model preset")
        self._mesh = None
        # An explicit device list always builds a mesh — even a 1-device one —
        # so params/cache/dispatches PIN to those devices (a DP replica must
        # not land on the process default device; engine/replicated.py).
        if mesh_cfg is not None and (devices is not None or mesh_cfg.n_devices > 1):
            from lmrs_tpu.parallel.mesh import build_mesh

            self._mesh = build_mesh(mesh_cfg, devices)
        key = jax.random.PRNGKey(engine_cfg.seed)
        t0 = time.time()
        quantized = False
        if params is None:
            if engine_cfg.checkpoint_path:
                from lmrs_tpu.models.loader import load_checkpoint

                # restore directly onto the mesh: shards stream to their
                # devices and the full tree never materializes on one host
                params = load_checkpoint(engine_cfg.checkpoint_path, model_cfg,
                                         mesh=self._mesh)
            else:
                logger.warning(
                    "no checkpoint for %s: using random-init weights "
                    "(throughput-correct, content-free)", model_cfg.name,
                )
                if needs_host_quant_init(model_cfg, engine_cfg.quantize):
                    # quantized random init builds the int8 tree directly
                    # on the HOST (numpy): the full-precision tree of an
                    # 8B-shape model (16 GB bf16) cannot coexist with
                    # anything on a 16 GB chip, and under the axon tunnel
                    # no jax CPU backend exists to stage it on — only the
                    # ~8.6 GB quantized tree ever ships to the device.
                    # ONLY for models too big to init in bf16 (the r5
                    # criterion): the host RNG draws DIFFERENT weights
                    # than init_params, which silently changed the 1B
                    # bench's generated-token workload (reduce 4.4→5.9 s,
                    # bisected to this switch) — small models keep the
                    # device init so random-weight workloads stay
                    # comparable across rounds
                    from lmrs_tpu.ops.quant import random_quantized_init

                    params = random_quantized_init(model_cfg,
                                                   engine_cfg.seed)
                    quantized = True
                else:
                    params = init_params(model_cfg, key)
        if engine_cfg.quantize and not quantized:
            # checkpoint- or caller-provided params quantize where they live
            params = self._quantize_logged(params)
        self.params = self._place(params)
        logger.info("model %s: %.1fM params ready in %.1fs", model_cfg.name,
                    param_count(self.params) / 1e6, time.time() - t0)
        self._key = jax.random.PRNGKey(engine_cfg.seed + 1)
        self._gen_fns: dict[tuple, object] = {}  # (B, S_bucket, max_new) -> jitted
        self._scheduler = None
        self._runner = None
        self.schedules_internally = False
        if engine_cfg.scheduler == "continuous":
            from lmrs_tpu.engine.scheduler import ContinuousScheduler

            self._scheduler = ContinuousScheduler(
                engine_cfg, model_cfg, self.params, self.tokenizer,
                mesh=self._mesh,
            )
            # slot + page admission control replaces the executor's wave cap
            self.schedules_internally = True
            # Hang survival (engine/watchdog.py): with the watchdog armed
            # (LMRS_WATCHDOG, default on) dispatch moves onto a daemon
            # runner thread and the caller thread watches the scheduler's
            # heartbeat — a wedged chip becomes bounded wedged/deadline
            # results + a degraded fail-fast engine instead of a silent
            # freeze.  LMRS_WATCHDOG=0 leaves _runner None: run() executes
            # inline on the caller thread, byte-for-byte the pre-watchdog
            # dispatch path.
            if self._scheduler.watchdog is not None:
                from lmrs_tpu.engine.watchdog import WatchdogRunner

                self._runner = WatchdogRunner(self._scheduler)

    # -------------------------------------------------------------- plumbing

    def _default_tokenizer(self):
        # Model-vocab authority (SURVEY.md §7.4 item 4).  An explicit
        # engine_cfg.tokenizer spec wins (CLI --tokenizer / real-checkpoint
        # vocabularies); byte tokenizer covers random-init models.
        if self.cfg.tokenizer:
            return get_tokenizer(self.cfg.tokenizer)
        return ByteTokenizer() if self.model_cfg.vocab_size < 100000 else get_tokenizer("approx")

    def _quantize_logged(self, params):
        from lmrs_tpu.ops.quant import quantize_params, quantized_bytes

        before = quantized_bytes(params)
        params = quantize_params(params)
        logger.info("int8 weight quantization: %.1f -> %.1f MiB",
                    before / 2**20, quantized_bytes(params) / 2**20)
        return params

    def _place(self, params):
        """Put params on device(s); with a >1-device mesh, use TP layout.
        (No-op re-placement for params a sharded restore already placed.)"""
        if self._mesh is not None:
            from lmrs_tpu.parallel.sharding import shard_params

            return shard_params(params, self._mesh, self.model_cfg.tie_embeddings,
                                moe=self.model_cfg.n_experts > 0)
        return jax.device_put(params)

    def shutdown(self) -> None:
        if self._runner is not None:
            self._runner.shutdown()
        self._gen_fns.clear()

    def wedged(self) -> bool:
        """Optional Engine hook (getattr convention): True while a wedged
        dispatch still holds the runner thread — the engine is degraded
        fail-fast.  The serving layer surfaces it through /healthz (503)
        so the supervisor (serving/supervisor.py) can bounce the
        process."""
        return self._runner is not None and self._runner.wedged

    def cancel(self, request_id: int) -> None:
        """Abort a request in the current generate_batch call (Engine
        optional hook).  Continuous scheduler: slot freed at the next block
        boundary.  Static scheduler: no mid-wave abort point exists (whole
        completions decode in one on-device while_loop) — best-effort means
        a no-op there."""
        if self._scheduler is not None:
            self._scheduler.cancel(request_id)

    def engine_metrics(self) -> dict:
        return self._scheduler.metrics_report() if self._scheduler else {}

    def prefix_summary(self, top_k: int = 16) -> list[dict]:
        """Optional Engine hook (getattr convention): the compact radix
        summary the router routes on (docs/SERVING.md § prefix-aware
        routing); [] for the static scheduler or with the cache off."""
        if self._scheduler is None:
            return []
        return self._scheduler.prefix_summary(top_k)

    def usage_report(self) -> dict:
        """Optional Engine hook: per-tenant cost-ledger rollups (the
        ``GET /v1/usage`` document, docs/OBSERVABILITY.md § Request-cost
        ledger).  Empty-disabled shape for the static scheduler."""
        if self._scheduler is None:
            return {"object": "usage", "enabled": False, "tenants": {},
                    "totals": {}}
        return self._scheduler.usage_report()

    def slo_report(self) -> dict:
        """Optional Engine hook: the burn-rate SLO evaluation exported
        through ``/healthz`` (the router's placement-penalty feed)."""
        if self._scheduler is None:
            return {"enabled": False, "state": "ok", "specs": {}}
        return self._scheduler.slo_report()

    def qos_report(self) -> dict:
        """Optional Engine hook: the fair-share window state exported as
        the ``GET /v1/usage`` ``qos`` block (fleet/qos.py)."""
        if self._scheduler is None:
            return {"object": "qos", "enabled": False}
        return self._scheduler.qos_report()

    def anatomy_report(self) -> dict:
        """Optional Engine hook: the step-anatomy document behind ``GET
        /v1/anatomy`` (obs/anatomy.py).  The static scheduler has no
        iteration loop to decompose: disabled shape."""
        if self._scheduler is None:
            return {"object": "anatomy", "enabled": False}
        return self._scheduler.anatomy_report()

    # ---------------------------------------- disaggregated handoff hooks
    # (optional Engine surface, same getattr convention as ``cancel``):
    # the continuous scheduler implements the real page pin/export/import
    # lifecycle; the static scheduler has no paged pool to export, so
    # supports_handoff is False there and the serving layer ignores
    # handoff flags (graceful colocated fallback).

    @property
    def supports_handoff(self) -> bool:
        return self._scheduler is not None

    def export_handoff(self, request_id: int) -> dict:
        if self._scheduler is None:
            raise KeyError(request_id)
        return self._scheduler.export_handoff(request_id)

    def release_handoff(self, request_id: int, orphaned: bool = False) -> int:
        if self._scheduler is None:
            return 0
        return self._scheduler.release_handoff(request_id, orphaned=orphaned)

    def sweep_handoffs(self, now: float | None = None) -> int:
        if self._scheduler is None:
            return 0
        return self._scheduler.sweep_handoffs(now)

    # ------------------------------------------------- KV-fabric migration
    # (optional Engine surface, same getattr convention): page-SET
    # export/import for cross-host preamble migration — the scheduler
    # implements the real radix walk; the static scheduler has no prefix
    # cache to export, so the hooks answer cold/unsupported there.

    def kv_export(self, preamble: str) -> dict | None:
        if self._scheduler is None:
            return None
        return self._scheduler.kv_export(preamble)

    def kv_import(self, payload: dict) -> int:
        if self._scheduler is None:
            raise RuntimeError("static scheduler has no prefix cache")
        return self._scheduler.kv_import(payload)

    def metrics_registry(self):
        """Optional Engine hook (same getattr convention as ``cancel``):
        the typed registry behind engine_metrics(), or None for the static
        scheduler — serving/server.py renders Prometheus exposition from
        it."""
        return self._scheduler.registry if self._scheduler else None

    def debug_profile(self, duration_s: float,
                      out_dir: str) -> tuple[bool, str]:
        """Optional Engine hook behind ``POST /v1/debug/profile``: start a
        bounded on-demand ``jax.profiler`` capture of this process (one at
        a time; auto-stopped).  Returns ``(ok, dir_or_reason)`` — engines
        without device work (MockEngine) simply lack the hook and the
        server answers 501."""
        from lmrs_tpu.obs.perf import start_profile_capture

        return start_profile_capture(out_dir, duration_s)

    # -------------------------------------------------------------- generate

    def generate_batch(self, requests: list[GenerationRequest],
                       on_result=None, on_tokens=None) -> list[GenerationResult]:
        if not requests:
            return []
        # injection site: an engine-level batch fault — callers (executor,
        # HTTP batcher) must degrade it to per-request error results
        from lmrs_tpu.testing import faults

        faults.fire("engine.batch")
        if self._scheduler is not None:
            if self._runner is not None:
                return self._runner.run(requests, on_result=on_result,
                                        on_tokens=on_tokens)
            return self._scheduler.run(requests, on_result=on_result,
                                       on_tokens=on_tokens)
        if on_tokens is not None:
            # static scheduler decodes whole completions per wave: emulate
            # streaming with one delta per finished request (single-chunk
            # SSE semantics; the continuous scheduler streams real blocks)
            inner = on_result

            def on_result(res, submit, _inner=inner):  # noqa: F811
                if res.text:
                    on_tokens(res.request_id, res.text)
                if _inner is not None:
                    _inner(res, submit)
        if on_result is not None:
            # static scheduler has no mid-run hook: run wave-by-wave,
            # deliver post-hoc, and loop on whatever the callbacks submit
            # (semantically identical to streaming, without the overlap)
            from lmrs_tpu.engine.api import drain_with_callback

            return drain_with_callback(self._generate_static, requests, on_result)
        return self._generate_static(requests)

    def _generate_static(self, requests: list[GenerationRequest]) -> list[GenerationResult]:
        t0 = time.time()
        results: dict[int, GenerationResult] = {}
        # Deadline admission on the static path: an expired request sheds
        # before any encode/dispatch work.  IN-FLIGHT expiry is not
        # available here — whole completions decode inside one on-device
        # while_loop with no host sync to sweep at (docs/ROBUSTNESS.md
        # scheduler-coverage note); the continuous scheduler is the
        # deadline-complete path.
        live = []
        for req in requests:
            if req.deadline_s is not None and req.deadline_s <= time.time():
                results[id(req)] = GenerationResult(
                    request_id=req.request_id, finish_reason="shed")
            else:
                live.append(req)
        requests, all_requests = live, requests
        # Sort by tokenized length to minimize padding waste per bucket.
        encoded = []
        for req in requests:
            text = (req.system_prompt + "\n\n" if req.system_prompt else "") + req.prompt
            ids = [self.tokenizer.bos_id] + self.tokenizer.encode(text)
            limit = self.model_cfg.max_seq_len - self._max_new(req)
            if len(ids) > limit:
                # middle truncation: instructions usually bracket the content
                head, tail = limit // 2, limit - limit // 2
                ids = ids[:head] + ids[-tail:]
            encoded.append((req, ids))
        encoded.sort(key=lambda e: len(e[1]))

        B = max(1, self.cfg.max_batch_slots)
        for i in range(0, len(encoded), B):
            group = encoded[i : i + B]
            for req, res in self._run_group(group):
                results[id(req)] = (req, res)[1]
        out = [results[id(r)] for r in all_requests]
        logger.info("generated %d requests in %.2fs", len(all_requests),
                    time.time() - t0)
        return out

    def _max_new(self, req: GenerationRequest) -> int:
        # one decode-length bucket per engine (single compile); respect the
        # smallest of request/config/context — a budget >= max_seq_len would
        # drive the truncation limit non-positive (see scheduler._encode)
        return min(req.max_new_tokens, self.cfg.max_tokens,
                   self.model_cfg.max_seq_len - 1)

    def _run_group(self, group):
        B = max(1, self.cfg.max_batch_slots)
        n = len(group)
        s_bucket = _bucket(max(len(ids) for _, ids in group))
        s_bucket = min(s_bucket, self.model_cfg.max_seq_len)
        max_new = max(self._max_new(req) for req, _ in group)

        tokens = np.full((B, s_bucket), self.tokenizer.pad_id, dtype=np.int32)
        lengths = np.ones((B,), dtype=np.int32)  # dummy rows: length 1
        temps = np.zeros((B,), dtype=np.float32)
        top_k = np.zeros((B,), dtype=np.int32)
        top_p = np.ones((B,), dtype=np.float32)
        for j, (req, ids) in enumerate(group):
            tokens[j, : len(ids)] = ids
            lengths[j] = len(ids)
            temps[j] = req.temperature
            top_k[j] = req.top_k
            top_p[j] = min(max(req.top_p, 0.0), 1.0)

        fn = self._get_gen_fn(B, s_bucket, max_new)
        self._key, sub = jax.random.split(self._key)
        t0 = time.time()
        out_tokens, n_generated = fn(
            self.params, jnp.asarray(tokens), jnp.asarray(lengths), sub,
            jnp.asarray(temps), jnp.asarray(top_k), jnp.asarray(top_p),
        )
        out_tokens = np.asarray(jax.device_get(out_tokens))
        n_generated = np.asarray(jax.device_get(n_generated))
        dt = time.time() - t0

        results = []
        per_req_dt = dt / max(n, 1)
        for j, (req, ids) in enumerate(group):
            gen = out_tokens[j, : int(n_generated[j])].tolist()
            finish = "stop"
            if self.tokenizer.eos_id in gen:
                gen = gen[: gen.index(self.tokenizer.eos_id)]
            elif len(gen) >= max_new:
                finish = "length"
            text, stop_hit = apply_stop_sequences(
                self.tokenizer.decode(gen), req.stop)
            if stop_hit is not None:
                finish = "stop"
            results.append(
                (req, GenerationResult(
                    request_id=req.request_id,
                    text=text,
                    prompt_tokens=len(ids),
                    completion_tokens=len(gen),
                    finish_reason=finish,
                    stop_sequence=stop_hit,
                    device_seconds=per_req_dt,
                ))
            )
        return results

    # ------------------------------------------------------------- compiled

    def _get_gen_fn(self, B: int, s_bucket: int, max_new: int):
        sig = (B, s_bucket, max_new)
        if sig in self._gen_fns:
            return self._gen_fns[sig]
        cfg = self.model_cfg
        eos_id = self.tokenizer.eos_id

        @partial(jax.jit, static_argnums=())
        def gen(params, tokens, lengths, key, temps, top_k, top_p):
            b = tokens.shape[0]
            cache = init_kv_cache(cfg, b, s_bucket + max_new)
            positions = jnp.broadcast_to(jnp.arange(s_bucket)[None, :], (b, s_bucket))
            logits, cache = forward(params, cfg, tokens, positions, cache, lengths)
            last = jnp.take_along_axis(logits, (lengths - 1)[:, None, None], axis=1)[:, 0]

            out_buf = jnp.zeros((b, max_new), jnp.int32)
            done = jnp.zeros((b,), bool)

            def cond(state):
                step, _, _, _, _, done, _ = state
                return jnp.logical_and(step < max_new, ~jnp.all(done))

            def body(state):
                step, key, last, cache, out_buf, done, n_gen = state
                key, sub = jax.random.split(key)
                # while_loop context, NOT vmap: sample_logits' lax.cond
                # fast paths would silently degrade to select-both-
                # branches under vmap (ops/sampling.py NOTE)
                tok = sample_logits(last, sub, temps, top_k, top_p)
                tok = jnp.where(done, eos_id, tok)
                out_buf = out_buf.at[:, step].set(tok)
                n_gen = jnp.where(done, n_gen, step + 1)
                done = jnp.logical_or(done, tok == eos_id)
                pos = (lengths + step)[:, None]
                logits, cache = forward(
                    params, cfg, tok[:, None], pos, cache, lengths + step + 1
                )
                return (step + 1, key, logits[:, 0], cache, out_buf, done, n_gen)

            state = (0, key, last, cache, out_buf, done, jnp.zeros((b,), jnp.int32))
            state = jax.lax.while_loop(cond, body, state)
            _, _, _, _, out_buf, _, n_gen = state
            return out_buf, n_gen

        logger.info("compiling generate fn: batch=%d, prompt_bucket=%d, max_new=%d",
                    B, s_bucket, max_new)
        self._gen_fns[sig] = gen
        return gen
