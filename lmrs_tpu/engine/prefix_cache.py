"""Shared-prefix KV cache: radix-tree page reuse over the paged pool.

Every map request carries the same system-prompt + map-instruction preamble
(the reference fans identical prompt headers out per chunk; the reduce tree
repeats the reduce prompt per node), yet without this subsystem the
scheduler re-prefills that preamble from scratch for every one of the
hundreds of chunks in a long transcript.  This module lets a new request
*start* its prefill at the first uncached token: O(chunks x prefix_len)
prefill work becomes O(prefix_len) (SGLang RadixAttention / vLLM automatic
prefix caching, adapted to this engine's page-granular pool).

Design
------
* Host-side radix tree keyed on TOKEN IDS at page granularity: an edge
  labels one or more full pages' worth of tokens and owns the matching KV
  page ids in the existing pool (kv_cache.PagedKVCache).  Only whole pages
  are ever cached or matched — a page is the pool's unit of sharing, and
  partial-page reuse would need copy-on-write the decode path doesn't have.
* Pages are REF-COUNTED in the allocator (PageAllocator.incref/free): the
  cache holds one reference on every page it retains, and every live
  sequence cloning a cached prefix holds its own.  A cached page is thus
  shared read-only — sequences write only at positions past their matched
  prefix, which the page-granularity cap below guarantees live in private
  pages.
* ``match`` caps the usable prefix at the largest page multiple <= len-1:
  at least the final prompt token is always recomputed, because sampling
  the first output token needs that token's logits (which pages do not
  store), and its KV write must never land in a shared page.  A full-prefix
  hit therefore degenerates to a one-chunk tail prefill straight into
  decode — the tail is at most one page + the unpaged remainder.
* Insertion happens when a sequence's PREFILL completes (scheduler calls
  ``insert`` with the prompt ids + page table): all prompt pages are fully
  written by the already-issued dispatch chain, and adopting them early
  lets later admissions in the same run hit.  The tree adopts only pages
  it does not already cover (first writer wins; content-identical
  duplicates from concurrently-admitted sequences are simply freed when
  their sequence closes).
* Eviction is LRU over REFCOUNT-ZERO nodes — leaves no live sequence
  shares (allocator refcount 1 == the cache's own reference) — triggered
  by an explicit ``max_pages`` budget and by pool back-pressure
  (PagedKVCache.reclaim_cb -> ``evict``), so caching never deadlocks
  admission: under pressure cached pages drain back to the free list
  before the scheduler resorts to preemption or stalls.
"""

from __future__ import annotations

import logging

from lmrs_tpu.testing import faults

logger = logging.getLogger("lmrs.prefix_cache")


class _Node:
    """One radix-tree edge: ``tokens`` (length a multiple of page_size;
    empty at the root) and the KV pages holding them, one per page_size
    tokens.  ``tick`` is the LRU stamp, bumped on every match/insert walk
    through the node."""

    __slots__ = ("tokens", "pages", "children", "parent", "tick")

    def __init__(self, tokens: tuple, pages: list[int], parent: "_Node | None"):
        self.tokens = tokens
        self.pages = pages
        self.children: dict[tuple, _Node] = {}  # first-page token block -> child
        self.parent = parent
        self.tick = 0


class PrefixCache:
    """Radix tree mapping token-id prefixes to ref-counted KV pages.

    The cache owns one allocator reference per retained page; ``match``
    hands the caller pages with an EXTRA reference (the caller releases
    them through its normal ``close_sequence`` free).  All methods are
    host-side and O(prefix length); the scheduler calls them between
    dispatches.
    """

    def __init__(self, allocator, page_size: int, max_pages: int = 0):
        self.allocator = allocator
        self.page_size = page_size
        # 0 = no explicit budget: retained pages are bounded by the pool
        # itself (back-pressure eviction via evict())
        self.max_pages = max_pages
        self.root = _Node((), [], None)
        self.cached_pages = 0
        self._tick = 0
        # structural counters only — hit/query/tokens-reused accounting
        # lives in the SCHEDULER (one source of truth, counted once per
        # admission; a raw match() here may be rolled back by admission
        # back-pressure and must not inflate a hit rate)
        self.evicted_pages = 0
        self.inserted_pages = 0

    # ------------------------------------------------------------- matching

    def _touch(self, node: _Node) -> None:
        self._tick += 1
        node.tick = self._tick

    def match(self, ids: list[int]) -> tuple[list[int], int]:
        """Longest cached prefix of ``ids`` at page granularity.

        Returns ``(pages, n_tokens)`` with one extra allocator reference
        taken on every returned page (the caller owns it; releasing goes
        through the caller's normal page free).  ``n_tokens`` is capped at
        the largest page multiple <= len(ids) - 1 so the final prompt token
        is always recomputed (see module doc).
        """
        ps = self.page_size
        usable = ((len(ids) - 1) // ps) * ps
        pages: list[int] = []
        matched = 0
        node = self.root
        self._touch(node)
        while matched < usable:
            child = node.children.get(tuple(ids[matched: matched + ps]))
            if child is None:
                break
            take = 0
            for off in range(0, len(child.tokens), ps):
                if (matched + off + ps > usable
                        or tuple(ids[matched + off: matched + off + ps])
                        != child.tokens[off: off + ps]):
                    break
                take += ps
            if take == 0:
                break
            if take < len(child.tokens):
                # partial edge use: split at the page boundary so the used
                # prefix becomes its own node (per-node LRU/eviction stays
                # whole-node simple) and stop — the remainder diverges.
                child = self._split(child, take)
            pages += child.pages
            matched += take
            node = child
            self._touch(node)
        if matched:
            self.allocator.incref(pages)
        return pages, matched

    def _split(self, node: _Node, k: int) -> _Node:
        """Split ``node``'s edge after ``k`` tokens (a page multiple):
        the prefix becomes a new parent node; ``node`` keeps the suffix.
        Returns the new prefix node."""
        ps = self.page_size
        upper = _Node(node.tokens[:k], node.pages[: k // ps], node.parent)
        upper.tick = node.tick
        parent = node.parent
        parent.children[node.tokens[:ps]] = upper
        node.tokens = node.tokens[k:]
        node.pages = node.pages[k // ps:]
        node.parent = upper
        upper.children[node.tokens[:ps]] = node
        return upper

    # ------------------------------------------------------------ insertion

    def insert(self, ids: list[int], pages: list[int],
               max_tokens: int | None = None) -> int:
        """Adopt the full-page prefix of ``ids`` (KV in ``pages``, the
        sequence's page table) into the tree; returns the number of pages
        adopted.  Pages the tree already covers are skipped (the caller's
        duplicates are released by its own close).  ``max_tokens``, when
        given, caps adoption to ceil-to-page of that many leading tokens —
        the request-level ``cache_prefix`` hint, which keeps per-request
        unique suffixes (chunk bodies) from bloating the tree.

        Adopted pages gain one allocator reference (the cache's); the
        caller keeps its own reference and releases it as usual.
        """
        # injection site: fires BEFORE any tree/refcount mutation, so a
        # fault here leaves the cache exactly as it was — the scheduler
        # treats insertion failure as a lost optimization, never an error
        faults.fire("prefix_cache.insert")
        ps = self.page_size
        limit = (len(ids) // ps) * ps
        if max_tokens is not None:
            limit = min(limit, -(-max_tokens // ps) * ps)
        if limit <= 0:
            return 0
        node = self.root
        self._touch(node)
        matched = 0
        while matched < limit:
            child = node.children.get(tuple(ids[matched: matched + ps]))
            if child is None:
                break
            take = 0
            for off in range(0, len(child.tokens), ps):
                if (matched + off + ps > limit
                        or tuple(ids[matched + off: matched + off + ps])
                        != child.tokens[off: off + ps]):
                    break
                take += ps
            if take == 0:
                break
            if take < len(child.tokens):
                child = self._split(child, take)
            matched += take
            node = child
            self._touch(node)
            if take < ps:  # pragma: no cover - defensive
                break
        adopt = (limit - matched) // ps
        if adopt <= 0:
            return 0
        if self.max_pages:
            over = self.cached_pages + adopt - self.max_pages
            if over > 0:
                # pin the walk path: evicting the node we are about to
                # attach under would orphan the new leaf (and leak its
                # page accounting)
                pin = set()
                cur = node
                while cur is not None:
                    pin.add(id(cur))
                    cur = cur.parent
                self._evict_lru(over, keep=pin)
            # still over budget (live sequences pin nodes): trim adoption
            adopt = min(adopt, max(self.max_pages - self.cached_pages, 0))
            if adopt <= 0:
                return 0
        new_tokens = tuple(ids[matched: matched + adopt * ps])
        new_pages = list(pages[matched // ps: matched // ps + adopt])
        self.allocator.incref(new_pages)
        leaf = _Node(new_tokens, new_pages, node)
        node.children[new_tokens[:ps]] = leaf
        self._touch(leaf)
        self.cached_pages += adopt
        self.inserted_pages += adopt
        return adopt

    # ------------------------------------------------------------- eviction

    def _evictable(self, node: _Node) -> bool:
        """A leaf no live sequence shares: every page's only reference is
        the cache's own."""
        return (not node.children
                and all(self.allocator.refcount(p) == 1 for p in node.pages))

    def evict(self, n_pages: int) -> int:
        """Free at least ``n_pages`` pages of refcount-zero cache (LRU node
        order), or as many as exist.  Returns pages freed.  Wired into the
        pool's OutOfPages back-pressure path (PagedKVCache.reclaim_cb), so
        a full cache can never starve admission or decode growth."""
        return self._evict_lru(n_pages)

    def _evict_lru(self, n_pages: int, keep: set | None = None) -> int:
        freed = 0
        while freed < n_pages:
            victim = None
            stack = [self.root]
            while stack:
                node = stack.pop()
                stack.extend(node.children.values())
                if (node is self.root or (keep and id(node) in keep)
                        or not self._evictable(node)):
                    continue
                if victim is None or node.tick < victim.tick:
                    victim = node
            if victim is None:
                break
            freed += self._drop(victim)
        if freed:
            logger.debug("evicted %d cached pages (%d retained)",
                         freed, self.cached_pages)
        return freed

    def _drop(self, node: _Node) -> int:
        """Remove a leaf: release the cache's page references (pages return
        to the free list — nothing else holds them) and unlink."""
        self.allocator.free(node.pages)
        n = len(node.pages)
        del node.parent.children[node.tokens[: self.page_size]]
        self.cached_pages -= n
        self.evicted_pages += n
        node.parent = None
        return n

    def clear(self) -> int:
        """Drop every node no live sequence shares (kill switch / tests)."""
        return self._evict_lru(self.cached_pages or 0) if self.cached_pages else 0

    # ---------------------------------------------------------------- audit

    def retained_pages(self) -> list[int]:
        """Every page id the tree currently holds a reference on (one entry
        per retention — duplicates would themselves be a bug ``audit``
        reports)."""
        out: list[int] = []
        stack = [self.root]
        while stack:
            node = stack.pop()
            stack.extend(node.children.values())
            out.extend(node.pages)
        return out

    def audit(self) -> list[str]:
        """Radix-tree structural invariants, one string per violation:

        * every non-root node labels ``len(pages) * page_size`` tokens;
        * each child is keyed by its first page's token block and points
          back at its parent;
        * no page is retained twice; ``cached_pages`` matches the walk;
        * every retained page is live in the allocator (refcount >= 1 —
          the cache's own reference; a refcount-0 retained page means the
          cache is handing out freed pages).

        Refcount BALANCE (tree + live sequences == allocator refcounts) is
        the scheduler auditor's job — only it knows the live sequences.
        """
        ps = self.page_size
        violations: list[str] = []
        seen: dict[int, int] = {}
        total = 0
        stack = [self.root]
        while stack:
            node = stack.pop()
            if node is not self.root:
                if len(node.tokens) != len(node.pages) * ps:
                    violations.append(
                        f"node with {len(node.tokens)} tokens holds "
                        f"{len(node.pages)} pages (page_size {ps})")
                if not node.tokens:
                    violations.append("non-root node with empty edge label")
            for key, child in node.children.items():
                if child.parent is not node:
                    violations.append("child's parent link is stale")
                if tuple(child.tokens[:ps]) != key:
                    violations.append(
                        "child keyed by a block that is not its first page")
                stack.append(child)
            for p in node.pages:
                seen[p] = seen.get(p, 0) + 1
                total += 1
                if self.allocator.refcount(p) < 1:
                    violations.append(f"cache retains freed page {p}")
        for p, n in seen.items():
            if n > 1:
                violations.append(f"page {p} retained {n} times")
        if total != self.cached_pages:
            violations.append(
                f"cached_pages counter {self.cached_pages} != {total} "
                "pages found in the tree")
        return violations

    # -------------------------------------------------------------- reports

    def stats(self) -> dict:
        """Structural counters (page footprint) for metrics_report()/bench
        detail.  Hit/query/tokens-reused accounting is the scheduler's
        (see __init__)."""
        return {
            "cached_pages": self.cached_pages,
            "inserted_pages": self.inserted_pages,
            "evicted_pages": self.evicted_pages,
        }
