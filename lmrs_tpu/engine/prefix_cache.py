"""Shared-prefix KV cache: radix-tree page reuse over the paged pool.

Every map request carries the same system-prompt + map-instruction preamble
(the reference fans identical prompt headers out per chunk; the reduce tree
repeats the reduce prompt per node), yet without this subsystem the
scheduler re-prefills that preamble from scratch for every one of the
hundreds of chunks in a long transcript.  This module lets a new request
*start* its prefill at the first uncached token: O(chunks x prefix_len)
prefill work becomes O(prefix_len) (SGLang RadixAttention / vLLM automatic
prefix caching, adapted to this engine's page-granular pool).

Design
------
* Host-side radix tree keyed on TOKEN IDS at page granularity: an edge
  labels one or more full pages' worth of tokens and owns the matching KV
  page ids in the existing pool (kv_cache.PagedKVCache).  Only whole pages
  are ever cached or matched — a page is the pool's unit of sharing, and
  partial-page reuse would need copy-on-write the decode path doesn't have.
* Pages are REF-COUNTED in the allocator (PageAllocator.incref/free): the
  cache holds one reference on every page it retains, and every live
  sequence cloning a cached prefix holds its own.  A cached page is thus
  shared read-only — sequences write only at positions past their matched
  prefix, which the page-granularity cap below guarantees live in private
  pages.
* ``match`` caps the usable prefix at the largest page multiple <= len-1:
  at least the final prompt token is always recomputed, because sampling
  the first output token needs that token's logits (which pages do not
  store), and its KV write must never land in a shared page.  A full-prefix
  hit therefore degenerates to a one-chunk tail prefill straight into
  decode — the tail is at most one page + the unpaged remainder.
* Insertion happens when a sequence's PREFILL completes (scheduler calls
  ``insert`` with the prompt ids + page table): all prompt pages are fully
  written by the already-issued dispatch chain, and adopting them early
  lets later admissions in the same run hit.  The tree adopts only pages
  it does not already cover (first writer wins; content-identical
  duplicates from concurrently-admitted sequences are simply freed when
  their sequence closes).
* Eviction is LRU over REFCOUNT-ZERO nodes — nodes no live sequence shares
  (allocator refcount 1 == the cache's own reference) — triggered by an
  explicit ``max_pages`` budget and by pool back-pressure
  (PagedKVCache.reclaim_cb -> ``evict``), so caching never deadlocks
  admission: under pressure cached pages drain back to the free list
  before the scheduler resorts to preemption or stalls.

Host-RAM spill tier (engine/host_kv.py, ROADMAP item 3)
-------------------------------------------------------
With a :class:`~lmrs_tpu.engine.host_kv.HostKVPool` attached (and a
``capture_cb`` to gather page contents device→host), an HBM eviction no
longer throws the KV away: the victim node's page CONTENT is captured
into the bounded host pool and the node stays in the tree as a *spilled*
node (``pages == []``, payload on ``_Node.spill``).  A later ``match_hier``
that walks onto a spilled node reports it to the scheduler, which
allocates fresh device pages and PREFETCHES the payload back
(``prefetch_into`` → ``PagedKVCache.import_pages``) instead of
re-prefilling — the node is promoted back to resident on the new pages.
``insert`` likewise promotes spilled nodes its walk passes through (the
inserting sequence just recomputed identical KV on its own pages).  Host
budget pressure (``LMRS_HOST_KV_GB``) drops LRU spilled subtrees for
real; capture failure (or the ``prefix.spill`` fault) degrades to
today's evict-means-gone drop, byte-for-byte.  With no pool attached
(``LMRS_HOST_KV=0``) nothing here changes behavior at all.

Disk tier (engine/host_kv.DiskKVPool, ROADMAP item 4)
-----------------------------------------------------
With ``pool.disk`` attached (``LMRS_KV_DISK=1``), host-pool budget
pressure DEMOTES the LRU host entry to an mmap'd spill file instead of
dropping it: the node stays in the tree, its ``spill`` payload becomes a
disk *descriptor* (``{"disk": True, ...}``), and a later match promotes
it disk→host→device through the same ``prefetch_into`` path (the read
happens at prefetch time; the ``kv.disk_read`` fault site fires before
it).  A missing/torn/corrupt file — or the injected fault — drops the
entry and degrades to re-prefill, never a wedged admission.  Recency is
still the node's radix ``tick``: ONE LRU clock across device, host, and
disk.  Disk budget pressure drops LRU disk subtrees for real.

Threading: ALL methods run on the scheduler thread, between dispatches —
the host pool and the disk pool inherit the same contract.
"""

from __future__ import annotations

import logging
import os
import time

from lmrs_tpu.testing import faults

logger = logging.getLogger("lmrs.prefix_cache")


class _Node:
    """One radix-tree edge: ``tokens`` (length a multiple of page_size;
    empty at the root) and the KV pages holding them, one per page_size
    tokens.  ``tick`` is the LRU stamp, bumped on every match/insert walk
    through the node.  ``spill`` is the host-RAM payload of a SPILLED
    node (pages freed, content captured) — exactly one of ``pages`` /
    ``spill`` is populated on a non-root node."""

    __slots__ = ("tokens", "pages", "children", "parent", "tick", "spill")

    def __init__(self, tokens: tuple, pages: list[int], parent: "_Node | None"):
        self.tokens = tokens
        self.pages = pages
        self.children: dict[tuple, _Node] = {}  # first-page token block -> child
        self.parent = parent
        self.tick = 0
        self.spill: dict | None = None


def _payload_bytes(payload: dict) -> int:
    if payload.get("disk"):
        return int(payload["nbytes"])
    return int(payload["k"].nbytes) + int(payload["v"].nbytes)


def _spill_pages(payload: dict) -> int:
    """Payload pages of a spill entry, either tier (k is [L, n, kh, ps,
    hd]; the disk descriptor records the shape)."""
    if payload.get("disk"):
        return int(payload["k_shape"][1])
    return int(payload["k"].shape[1])


class PrefixCache:
    """Radix tree mapping token-id prefixes to ref-counted KV pages.

    The cache owns one allocator reference per retained page; ``match``
    hands the caller pages with an EXTRA reference (the caller releases
    them through its normal ``close_sequence`` free).  All methods are
    host-side and O(prefix length); the scheduler calls them between
    dispatches.

    ``spill_pool``/``capture_cb``/``page_bytes`` arm the host-RAM spill
    tier (module doc); ``metrics`` is an optional dict of registry
    instruments ({"spill_pages", "spill_dropped", "spill_capture_s",
    "pool_bytes"}) the spill paths feed — absent keys are skipped, so
    unit tests need no registry.
    """

    def __init__(self, allocator, page_size: int, max_pages: int = 0,
                 spill_pool=None, capture_cb=None, page_bytes: int = 0,
                 metrics: dict | None = None):
        self.allocator = allocator
        self.page_size = page_size
        # 0 = no explicit budget: retained pages are bounded by the pool
        # itself (back-pressure eviction via evict())
        self.max_pages = max_pages
        self.pool = spill_pool
        self.capture_cb = capture_cb
        self.page_bytes = page_bytes  # per-page payload estimate (fits())
        self.metrics = metrics or {}
        self.root = _Node((), [], None)
        self.cached_pages = 0
        self._tick = 0
        # structural counters only — hit/query/tokens-reused accounting
        # lives in the SCHEDULER (one source of truth, counted once per
        # admission; a raw match() here may be rolled back by admission
        # back-pressure and must not inflate a hit rate)
        self.evicted_pages = 0
        self.inserted_pages = 0

    @property
    def disk(self):
        """The disk tier under the host pool, or None (host_kv.DiskKVPool;
        any pool-like test double without one reads as tier-off)."""
        if self.pool is None:
            return None
        return getattr(self.pool, "disk", None)

    # ------------------------------------------------------------- matching

    def _touch(self, node: _Node) -> None:
        self._tick += 1
        node.tick = self._tick

    def _metric(self, name: str, op: str, *args) -> None:
        inst = self.metrics.get(name)
        if inst is not None:
            getattr(inst, op)(*args)

    def _note_pool(self) -> None:
        if self.pool is not None:
            self._metric("pool_bytes", "set", float(self.pool.used_bytes))
            if self.disk is not None:
                self._metric("disk_bytes", "set",
                             float(self.disk.used_bytes))

    def match(self, ids: list[int]) -> tuple[list[int], int]:
        """Longest RESIDENT cached prefix of ``ids`` at page granularity.

        Returns ``(pages, n_tokens)`` with one extra allocator reference
        taken on every returned page (the caller owns it; releasing goes
        through the caller's normal page free).  ``n_tokens`` is capped at
        the largest page multiple <= len(ids) - 1 so the final prompt token
        is always recomputed (see module doc).  The walk stops at a
        spilled node — its pages live in the host tier; ``match_hier``
        is the spill-aware probe.
        """
        pages, matched, _chain = self.match_hier(ids, with_spill=False)
        return pages, matched

    def match_hier(self, ids: list[int], with_spill: bool = True
                   ) -> tuple[list[int], int, list[tuple[_Node, int]]]:
        """Spill-aware prefix probe: the resident prefix (pages incref'd,
        exactly like ``match``) plus the chain of consecutive WHOLE
        spilled nodes extending it — ``[(node, n_tokens), ...]`` in
        positional order.  The caller allocates device pages per spilled
        node and restores each via ``prefetch_into`` (or re-prefills on
        failure — no references are held on spilled entries, so dropping
        the chain costs nothing).  The same usable-prefix cap applies
        across both tiers."""
        ps = self.page_size
        usable = ((len(ids) - 1) // ps) * ps
        pages: list[int] = []
        matched = 0
        node = self.root
        self._touch(node)
        while matched < usable:
            child = node.children.get(tuple(ids[matched: matched + ps]))
            if child is None or child.spill is not None:
                break
            take = 0
            for off in range(0, len(child.tokens), ps):
                if (matched + off + ps > usable
                        or tuple(ids[matched + off: matched + off + ps])
                        != child.tokens[off: off + ps]):
                    break
                take += ps
            if take == 0:
                break
            if take < len(child.tokens):
                # partial edge use: split at the page boundary so the used
                # prefix becomes its own node (per-node LRU/eviction stays
                # whole-node simple) and stop — the remainder diverges.
                child = self._split(child, take)
            pages += child.pages
            matched += take
            node = child
            self._touch(node)
        if matched:
            self.allocator.incref(pages)
        chain: list[tuple[_Node, int]] = []
        if with_spill and self.pool is not None:
            # extend through whole spilled nodes only (a partial spilled
            # edge would need a payload split mid-match; the lost tail is
            # at most one node) — resident-under-spilled cannot exist
            # (promotions run top-down), so the walk shape is [res*][spill*]
            pos = matched
            while pos < usable:
                child = node.children.get(tuple(ids[pos: pos + ps]))
                if (child is None or child.spill is None
                        or pos + len(child.tokens) > usable
                        or tuple(ids[pos: pos + len(child.tokens)])
                        != child.tokens):
                    break
                chain.append((child, len(child.tokens)))
                pos += len(child.tokens)
                node = child
                self._touch(node)
        return pages, matched, chain

    def peek(self, ids: list[int]) -> dict:
        """Read-only coverage probe (no incref, no LRU touch): how many
        leading tokens/pages of ``ids`` are resident vs spilled right now.
        Feeds the published radix summary (scheduler.prefix_summary) the
        router routes on; full-page granularity, no usable-1 cap — this
        is a capacity view, not an admission plan."""
        ps = self.page_size
        limit = (len(ids) // ps) * ps
        out = {"resident_tokens": 0, "resident_pages": 0,
               "spilled_tokens": 0, "spilled_pages": 0}
        node = self.root
        matched = 0
        in_spill = False
        while matched < limit:
            child = node.children.get(tuple(ids[matched: matched + ps]))
            if child is None:
                break
            take = 0
            for off in range(0, len(child.tokens), ps):
                if (matched + off + ps > limit
                        or tuple(ids[matched + off: matched + off + ps])
                        != child.tokens[off: off + ps]):
                    break
                take += ps
            if take == 0:
                break
            in_spill = in_spill or child.spill is not None
            kind = "spilled" if in_spill else "resident"
            out[f"{kind}_tokens"] += take
            out[f"{kind}_pages"] += take // ps
            if take < len(child.tokens):
                break
            matched += take
            node = child
        return out

    def _split(self, node: _Node, k: int) -> _Node | None:
        """Split ``node``'s edge after ``k`` tokens (a page multiple):
        the prefix becomes a new parent node; ``node`` keeps the suffix.
        Returns the new prefix node.  Spilled nodes split their payload
        too (both halves stay in the node's tier, bytes re-registered).
        A DISK node's split must read the file back — on a torn/corrupt
        file (or a failed re-write) the entry drops and the split returns
        None: the caller treats it as a missing child (the entry was only
        ever a cache)."""
        ps = self.page_size
        kp = k // ps
        upper = _Node(node.tokens[:k], node.pages[:kp], node.parent)
        upper.tick = node.tick
        if node.spill is not None and node.spill.get("disk"):
            disk = self.disk
            try:
                pay = self._disk_read(node.spill)
            except Exception:  # noqa: BLE001 - degrade to entry drop
                logger.warning("disk spill read failed during split; "
                               "dropping entry", exc_info=True)
                self._drop_subtree(node)
                return None
            halves = []
            try:
                for sl in (slice(None, kp), slice(kp, None)):
                    halves.append(disk.write(
                        {"k": pay["k"][:, sl].copy(),
                         "v": pay["v"][:, sl].copy(),
                         "dtype": pay.get("dtype")}))
            except OSError:
                logger.warning("disk spill write failed during split; "
                               "dropping entry", exc_info=True)
                for desc in halves:
                    disk.free(desc)
                self._drop_subtree(node)
                return None
            disk.remove(node)
            disk.free(node.spill)
            upper.spill, node.spill = halves
            # a split is not a new demotion event: re-register bytes only
            disk.add(upper, halves[0]["nbytes"], 0)
            disk.add(node, halves[1]["nbytes"], 0)
            self._note_pool()
        elif node.spill is not None:
            pay = node.spill
            self.pool.remove(node)
            upper.spill = {"k": pay["k"][:, :kp].copy(),
                           "v": pay["v"][:, :kp].copy(),
                           "dtype": pay.get("dtype")}
            node.spill = {"k": pay["k"][:, kp:].copy(),
                          "v": pay["v"][:, kp:].copy(),
                          "dtype": pay.get("dtype")}
            # a split is not a new spill event: re-register bytes only
            self.pool.add(upper, _payload_bytes(upper.spill), 0)
            self.pool.add(node, _payload_bytes(node.spill), 0)
            self._note_pool()
        parent = node.parent
        parent.children[node.tokens[:ps]] = upper
        node.tokens = node.tokens[k:]
        node.pages = node.pages[kp:]
        node.parent = upper
        upper.children[node.tokens[:ps]] = node
        return upper

    # ------------------------------------------------------------ insertion

    def insert(self, ids: list[int], pages: list[int],
               max_tokens: int | None = None) -> int:
        """Adopt the full-page prefix of ``ids`` (KV in ``pages``, the
        sequence's page table) into the tree; returns the number of pages
        adopted.  Pages the tree already covers are skipped (the caller's
        duplicates are released by its own close).  ``max_tokens``, when
        given, caps adoption to ceil-to-page of that many leading tokens —
        the request-level ``cache_prefix`` hint, which keeps per-request
        unique suffixes (chunk bodies) from bloating the tree.

        Adopted pages gain one allocator reference (the cache's); the
        caller keeps its own reference and releases it as usual.  Spilled
        nodes the walk passes through are PROMOTED back to resident on
        the caller's pages (the sequence just recomputed identical KV):
        the host payload drops and the tier self-heals.
        """
        # injection site: fires BEFORE any tree/refcount mutation, so a
        # fault here leaves the cache exactly as it was — the scheduler
        # treats insertion failure as a lost optimization, never an error
        faults.fire("prefix_cache.insert")
        ps = self.page_size
        limit = (len(ids) // ps) * ps
        if max_tokens is not None:
            limit = min(limit, -(-max_tokens // ps) * ps)
        if limit <= 0:
            return 0
        node = self.root
        self._touch(node)
        matched = 0
        promoted = 0
        while matched < limit:
            child = node.children.get(tuple(ids[matched: matched + ps]))
            if child is None:
                break
            take = 0
            for off in range(0, len(child.tokens), ps):
                if (matched + off + ps > limit
                        or tuple(ids[matched + off: matched + off + ps])
                        != child.tokens[off: off + ps]):
                    break
                take += ps
            if take == 0:
                break
            if take < len(child.tokens):
                child = self._split(child, take)
                if child is None:
                    # a disk-tier split degraded to an entry drop: the
                    # remainder adopts as a fresh leaf below
                    break
            if child.spill is not None:
                # promote on the inserting sequence's own pages for this
                # token span — identical content, freshly computed
                promoted += self._promote(
                    child, pages[matched // ps: (matched + take) // ps])
            matched += take
            node = child
            self._touch(node)
            if take < ps:  # pragma: no cover - defensive
                break
        adopt = (limit - matched) // ps
        if adopt <= 0:
            return promoted
        if self.max_pages:
            over = self.cached_pages + adopt - self.max_pages
            if over > 0:
                # pin the walk path: evicting the node we are about to
                # attach under would orphan the new leaf (and leak its
                # page accounting)
                pin = set()
                cur = node
                while cur is not None:
                    pin.add(id(cur))
                    cur = cur.parent
                self._evict_lru(over, keep=pin)
            # still over budget (live sequences pin nodes): trim adoption
            adopt = min(adopt, max(self.max_pages - self.cached_pages, 0))
            if adopt <= 0:
                return promoted
        new_tokens = tuple(ids[matched: matched + adopt * ps])
        new_pages = list(pages[matched // ps: matched // ps + adopt])
        self.allocator.incref(new_pages)
        leaf = _Node(new_tokens, new_pages, node)
        node.children[new_tokens[:ps]] = leaf
        self._touch(leaf)
        self.cached_pages += adopt
        self.inserted_pages += adopt
        return adopt + promoted

    def _promote(self, node: _Node, dest_pages: list[int]) -> int:
        """Flip a spilled node back to resident on ``dest_pages`` (the
        cache takes its own reference; the caller keeps its own).  The
        spill payload drops from its tier — host entries free their
        arrays, disk entries their file — the content is in HBM again."""
        n = len(dest_pages)
        assert n == len(node.tokens) // self.page_size
        desc = node.spill
        self.allocator.incref(list(dest_pages))
        node.pages = list(dest_pages)
        node.spill = None
        if self.pool is not None:
            if desc is not None and desc.get("disk"):
                self.disk.free(desc)
                self.disk.remove(node)
            else:
                self.pool.remove(node)
            self._note_pool()
        self.cached_pages += n
        self.inserted_pages += n
        return n

    # ------------------------------------------------------------- prefetch

    def _disk_read(self, desc: dict) -> dict:
        """Read a disk descriptor back into a host payload, firing the
        ``kv.disk_read`` fault site first and counting failures.  Raises
        on a missing/torn/corrupt file (or the injected fault) — callers
        degrade to re-prefill / entry drop."""
        try:
            faults.fire("kv.disk_read")
            return self.disk.read(desc)
        except Exception:
            self.disk.read_failures_total += 1
            self._metric("disk_read_fail", "inc")
            raise

    def prefetch_into(self, node: _Node, dest_pages: list[int],
                      kv_cache, sync: bool = False) -> int:
        """Restore a spilled node's payload into freshly allocated device
        pages (``PagedKVCache.import_pages`` — async scatter unless
        ``sync``) and promote the node to resident on them.  A DISK
        entry reads its spill file back first (disk→host→device); a
        torn/corrupt file drops the entry and raises — exactly the
        degrade-to-re-prefill contract of an entry dropped between match
        and prefetch, which also raises here.  The ``prefix.prefetch``
        fault site is the CALLER's (scheduler), fired before any
        mutation here; ``kv.disk_read`` fires inside the disk read."""
        payload = node.spill
        if payload is None:
            raise RuntimeError("spilled entry dropped before prefetch")
        was_disk = bool(payload.get("disk"))
        if was_disk:
            try:
                payload = self._disk_read(payload)
            except Exception:
                # a corrupt file would fail every future match too —
                # drop the entry so the tree stops advertising it
                self._drop_subtree(node)
                raise
        kv_cache.import_pages(dest_pages, payload, sync=sync)
        n = self._promote(node, dest_pages)
        # promotion via prefetch is a tier hit, not an insert
        self.inserted_pages -= n
        if self.pool is not None:
            if was_disk:
                self.disk.note_promote(n)
                self._metric("disk_promoted", "inc", n)
            else:
                self.pool.note_prefetch(n)
        self._touch(node)
        return n

    def spill_payload(self, node: _Node) -> dict | None:
        """In-memory payload of a spilled node, either tier, WITHOUT
        promoting it (cross-host migration export reads warm state but
        leaves this host's cache untouched).  Disk entries read their
        spill file back (``kv.disk_read`` contract); a torn/corrupt file
        drops the entry and returns None — the caller's export simply
        covers fewer tokens."""
        payload = node.spill
        if payload is None:
            return None
        if payload.get("disk"):
            try:
                return self._disk_read(payload)
            except Exception:  # noqa: BLE001 - degrade to shorter export
                logger.warning("disk spill read failed during export; "
                               "dropping entry", exc_info=True)
                self._drop_subtree(node)
                return None
        return payload

    # ------------------------------------------------------------- eviction

    def evict(self, n_pages: int) -> int:
        """Free at least ``n_pages`` DEVICE pages of refcount-zero cache
        (LRU node order), or as many as exist.  Returns pages freed.
        Wired into the pool's OutOfPages back-pressure path
        (PagedKVCache.reclaim_cb), so a full cache can never starve
        admission or decode growth.  With the host tier armed the content
        spills instead of vanishing — the device pages free either way."""
        return self._evict_lru(n_pages)

    def _evict_lru(self, n_pages: int, keep: set | None = None,
                   spill: bool = True) -> int:
        freed = 0
        while freed < n_pages:
            # Victim = LRU RESIDENT node no live sequence shares (every
            # page's only reference is the cache's own) with no resident
            # descendants — spilled descendants ride along (they
            # spill/drop with it).  Resident-descendant exclusion is one
            # ancestor-marking pass over the resident nodes (amortized
            # O(N) per scan — the former per-candidate subtree walk was
            # O(N^2) on exactly the page-starved back-pressure path).
            resident: list[_Node] = []
            stack = [self.root]
            while stack:
                node = stack.pop()
                stack.extend(node.children.values())
                if node is not self.root and node.pages:
                    resident.append(node)
            blocked: set[int] = set()
            for node in resident:
                cur = node.parent
                while cur is not None and id(cur) not in blocked:
                    blocked.add(id(cur))
                    cur = cur.parent
            victim = None
            for node in resident:
                if (id(node) in blocked or (keep and id(node) in keep)
                        or not all(self.allocator.refcount(p) == 1
                                   for p in node.pages)):
                    continue
                if victim is None or node.tick < victim.tick:
                    victim = node
            if victim is None:
                break
            freed += self._drop(victim, keep=keep, spill=spill)
        if freed:
            logger.debug("evicted %d cached pages (%d retained)",
                         freed, self.cached_pages)
        return freed

    def _drop(self, node: _Node, keep: set | None = None,
              spill: bool = True) -> int:
        """Release a victim's DEVICE pages.  With the host tier armed (and
        ``spill``), the content is captured host-side first and the node
        stays in the tree as a spilled node; otherwise — tier off, entry
        over the whole host budget, or capture failure (incl. the
        ``prefix.spill`` fault) — the node and its (spilled) descendants
        drop entirely, exactly today's evict-means-gone behavior."""
        n = len(node.pages)
        if (spill and n and self.pool is not None
                and self.capture_cb is not None
                and self.pool.fits(n * self.page_bytes)):
            payload = self._capture(node)
            if payload is not None:
                self.allocator.free(node.pages)
                node.pages = []
                node.spill = payload
                self.cached_pages -= n
                self.evicted_pages += n
                self.pool.add(node, _payload_bytes(payload), n)
                self._metric("spill_pages", "inc", n)
                self._note_pool()
                self._enforce_host_budget(keep)
                return n
        return self._drop_subtree(node)

    def _capture(self, node: _Node) -> dict | None:
        """Device→host gather of a victim's page contents (the spill
        capture).  Any failure — the ``prefix.spill`` fault or a real
        gather error — returns None: the caller frees the pages exactly
        as with the tier off; the cache is untouched."""
        try:
            faults.fire("prefix.spill")
            t0 = time.time()
            payload = self.capture_cb(node.pages)
            self._metric("spill_capture_s", "observe", time.time() - t0)
            return payload
        except Exception:  # noqa: BLE001 - degrade to evict-means-gone
            logger.warning("KV spill capture failed; pages free uncached",
                           exc_info=True)
            return None

    def _drop_subtree(self, node: _Node) -> int:
        """Remove ``node`` and everything under it: release the cache's
        device-page references (pages return to the free list — nothing
        else holds them beyond live sequences' own refs) and drop any
        spilled descendants' host entries.  Returns DEVICE pages freed."""
        ps = self.page_size
        if node.parent is not None:
            del node.parent.children[node.tokens[:ps]]
        freed = 0
        stack = [node]
        while stack:
            cur = stack.pop()
            stack.extend(cur.children.values())
            if cur.pages:
                self.allocator.free(cur.pages)
                freed += len(cur.pages)
                self.cached_pages -= len(cur.pages)
                self.evicted_pages += len(cur.pages)
            if cur.spill is not None:
                npg = len(cur.tokens) // ps
                if cur.spill.get("disk"):
                    if self.disk is not None:
                        self.disk.free(cur.spill)
                        self.disk.remove(cur, n_pages=npg, dropped=True)
                        self._metric("disk_dropped", "inc", npg)
                elif self.pool is not None:
                    self.pool.remove(cur, n_pages=npg, dropped=True)
                    self._metric("spill_dropped", "inc", npg)
                cur.spill = None
            cur.children = {}
            cur.parent = None
        self._note_pool()
        return freed

    def _enforce_host_budget(self, keep: set | None = None) -> None:
        """Re-fit the spill tiers to their budgets.  With the disk tier
        armed, host-pool pressure DEMOTES the LRU host entry to a spill
        file (the node stays in the tree, one tier down); tier off, entry
        over the whole disk budget, or a failed write drops the subtree
        exactly as before.  Disk pressure then drops LRU disk subtrees
        for real.  ``keep`` pins the current walk chain (insert/eviction
        path) — kept nodes form one root-path, so a victim outside the
        set can never contain one in its subtree."""
        if self.pool is None:
            return
        disk = self.disk
        while self.pool.over_budget():
            victim = self.pool.victim(keep=keep)
            if victim is None:
                break
            if (disk is not None
                    and disk.fits(_payload_bytes(victim.spill))
                    and self._demote(victim)):
                continue
            self._drop_subtree(victim)
        if disk is not None:
            while disk.over_budget():
                victim = disk.victim(keep=keep)
                if victim is None:
                    break
                self._drop_subtree(victim)

    def _demote(self, node: _Node) -> bool:
        """Move one host-tier entry down to the disk tier (host budget
        pressure).  Returns False on a failed spill-file write — the
        caller drops the subtree instead, exactly as with the tier off."""
        disk = self.disk
        try:
            desc = disk.write(node.spill)
        except OSError:
            logger.warning("disk spill write failed; entry drops from "
                           "the host tier uncached", exc_info=True)
            return False
        npg = len(node.tokens) // self.page_size
        self.pool.remove(node)  # demotion, not a drop: pages move tiers
        node.spill = desc
        disk.add(node, desc["nbytes"], npg)
        self._metric("disk_demoted", "inc", npg)
        self._note_pool()
        return True

    def clear(self) -> int:
        """Drop every node no live sequence shares — HARD, across every
        tier (kill switch / pool recovery / tests): resident refcount-
        zero nodes free their pages without spilling, and every spilled
        entry drops from the host and disk pools (disk entries unlink
        their spill files)."""
        freed = (self._evict_lru(self.cached_pages or 0, spill=False)
                 if self.cached_pages else 0)
        if self.pool is not None:
            for node, _nbytes in list(self.pool.entries.values()):
                if id(node) in self.pool.entries:  # sibling drop may race
                    self._drop_subtree(node)
        if self.disk is not None:
            for node, _nbytes in list(self.disk.entries.values()):
                if id(node) in self.disk.entries:
                    self._drop_subtree(node)
        return freed

    # ---------------------------------------------------------------- audit

    def retained_pages(self) -> list[int]:
        """Every DEVICE page id the tree currently holds a reference on
        (one entry per retention — duplicates would themselves be a bug
        ``audit`` reports).  Spilled nodes hold no device pages."""
        out: list[int] = []
        stack = [self.root]
        while stack:
            node = stack.pop()
            stack.extend(node.children.values())
            out.extend(node.pages)
        return out

    def audit(self) -> list[str]:
        """Radix-tree structural invariants, one string per violation:

        * every non-root RESIDENT node labels ``len(pages) * page_size``
          tokens; every non-root node is exactly one of resident/spilled
          (a page retained by both the device tree and a host-pool
          entry's claim is the double-retention bug class);
        * each child is keyed by its first page's token block and points
          back at its parent;
        * no page is retained twice; ``cached_pages`` matches the walk;
        * every retained page is live in the allocator (refcount >= 1 —
          the cache's own reference; a refcount-0 retained page means the
          cache is handing out freed pages);
        * host-pool accounting: pool entries and spilled tree nodes are
          the same set, payload page counts match edge labels, and
          ``used_bytes`` equals the sum of entry sizes.

        Refcount BALANCE (tree + live sequences == allocator refcounts) is
        the scheduler auditor's job — only it knows the live sequences.
        """
        ps = self.page_size
        violations: list[str] = []
        seen: dict[int, int] = {}
        total = 0
        spilled_nodes: list[_Node] = []
        stack = [self.root]
        while stack:
            node = stack.pop()
            if node is not self.root:
                if node.pages and node.spill is not None:
                    violations.append(
                        "node retained by BOTH tiers (device pages and a "
                        "host-pool payload)")
                if not node.pages and node.spill is None:
                    violations.append(
                        "non-root node with neither pages nor spill "
                        "payload")
                if node.pages and len(node.tokens) != len(node.pages) * ps:
                    violations.append(
                        f"node with {len(node.tokens)} tokens holds "
                        f"{len(node.pages)} pages (page_size {ps})")
                if node.spill is not None:
                    spilled_nodes.append(node)
                    if _spill_pages(node.spill) * ps != len(node.tokens):
                        violations.append(
                            f"spilled node with {len(node.tokens)} tokens "
                            f"carries {_spill_pages(node.spill)} payload "
                            "pages")
                if not node.tokens:
                    violations.append("non-root node with empty edge label")
            for key, child in node.children.items():
                if child.parent is not node:
                    violations.append("child's parent link is stale")
                if tuple(child.tokens[:ps]) != key:
                    violations.append(
                        "child keyed by a block that is not its first page")
                stack.append(child)
            for p in node.pages:
                seen[p] = seen.get(p, 0) + 1
                total += 1
                if self.allocator.refcount(p) < 1:
                    violations.append(f"cache retains freed page {p}")
        for p, n in seen.items():
            if n > 1:
                violations.append(f"page {p} retained {n} times")
        if total != self.cached_pages:
            violations.append(
                f"cached_pages counter {self.cached_pages} != {total} "
                "pages found in the tree")
        host_nodes = [n for n in spilled_nodes if not n.spill.get("disk")]
        disk_nodes = [n for n in spilled_nodes if n.spill.get("disk")]
        if self.pool is not None:
            tree_ids = {id(n) for n in host_nodes}
            pool_ids = set(self.pool.entries)
            if tree_ids != pool_ids:
                violations.append(
                    f"host-pool entries ({len(pool_ids)}) and spilled tree "
                    f"nodes ({len(tree_ids)}) diverge")
            used = sum(nbytes for _n, nbytes in self.pool.entries.values())
            if used != self.pool.used_bytes:
                violations.append(
                    f"host pool used_bytes {self.pool.used_bytes} != "
                    f"{used} summed over entries")
        elif spilled_nodes:
            violations.append("spilled nodes exist with no host pool "
                              "attached")
        disk = self.disk
        if disk is not None:
            tree_ids = {id(n) for n in disk_nodes}
            pool_ids = set(disk.entries)
            if tree_ids != pool_ids:
                violations.append(
                    f"disk-pool entries ({len(pool_ids)}) and disk-tier "
                    f"tree nodes ({len(tree_ids)}) diverge")
            used = sum(nbytes for _n, nbytes in disk.entries.values())
            if used != disk.used_bytes:
                violations.append(
                    f"disk pool used_bytes {disk.used_bytes} != "
                    f"{used} summed over entries")
            for n in disk_nodes:
                ent = disk.entries.get(id(n))
                if ent is not None and ent[1] != _payload_bytes(n.spill):
                    violations.append(
                        "disk entry bytes diverge from its descriptor")
                if not os.path.isfile(n.spill["path"]):
                    violations.append(
                        f"disk spill file missing: {n.spill['path']}")
        elif disk_nodes:
            violations.append("disk-tier nodes exist with no disk pool "
                              "attached")
        return violations

    # -------------------------------------------------------------- reports

    def spilled_pages(self) -> int:
        """Pages currently held by the host tier (capacity view)."""
        if self.pool is None:
            return 0
        return sum(len(node.tokens) // self.page_size
                   for node, _nbytes in self.pool.entries.values())

    def disk_pages(self) -> int:
        """Pages currently held by the disk tier (capacity view)."""
        if self.disk is None:
            return 0
        return sum(len(node.tokens) // self.page_size
                   for node, _nbytes in self.disk.entries.values())

    def stats(self) -> dict:
        """Structural counters (page footprint) for metrics_report()/bench
        detail.  Hit/query/tokens-reused accounting is the scheduler's
        (see __init__)."""
        out = {
            "cached_pages": self.cached_pages,
            "inserted_pages": self.inserted_pages,
            "evicted_pages": self.evicted_pages,
        }
        if self.pool is not None:
            out["spilled_pages"] = self.spilled_pages()
            out.update(self.pool.stats())
            if self.disk is not None:
                out["disk_pages"] = self.disk_pages()
        return out
