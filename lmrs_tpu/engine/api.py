"""Engine protocol: the boundary that replaces the reference's HTTP clients.

In the reference, L2's ``_call_llm_api`` dispatches to OpenAI/Anthropic HTTPS
clients (llm_executor.py:232-409) — the model lives on the far side of a
network boundary.  Here the boundary is a Python protocol and both sides live
in-tree: ``MockEngine`` (the no-device CPU test path, successor of the
reference's mock backend at llm_executor.py:411-432) and ``JaxEngine`` (the
TPU serving engine, SURVEY.md §7.1 L2/L6).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import TYPE_CHECKING, Protocol, runtime_checkable

if TYPE_CHECKING:
    from lmrs_tpu.config import EngineConfig, MeshConfig, ModelConfig


@dataclass
class GenerationRequest:
    """One unit of generation work (≙ one reference API call)."""

    prompt: str
    request_id: int = 0
    system_prompt: str | None = None
    max_new_tokens: int = 1000
    temperature: float = 0.3
    top_p: float = 1.0
    top_k: int = 0
    stop: tuple[str, ...] = ()
    seed: int | None = None
    # Prefix-cache hint (engine/prefix_cache.py): how many LEADING CHARACTERS
    # of ``prompt`` are expected to be shared with other requests (the map /
    # reduce preamble before per-chunk content).  None = no hint, cache the
    # whole full-page prompt prefix; 0 = the prompt body shares nothing (a
    # shared system prompt, encoded ahead of the prompt, is still cached);
    # negative = never cache this request's prefix.  Approximate by design —
    # the cap is rounded up to a KV page at token level, so an
    # off-by-a-few-chars hint costs nothing.  Engines without a prefix cache
    # ignore it.
    cache_prefix: int | None = None
    # Absolute deadline (``time.time()`` epoch seconds) after which this
    # request's result is worthless to its caller.  None = unbounded (the
    # pre-deadline behavior).  Contract (docs/ROBUSTNESS.md): a request
    # whose remaining budget cannot cover the engine's TTFT estimate is
    # shed BEFORE prefill (``finish_reason="shed"``, no engine work); one
    # that expires in flight is finished at the next block boundary with
    # ``finish_reason="deadline"`` keeping the tokens generated so far;
    # retries (executor + router) clip to the remaining budget.  Wire
    # clients send a RELATIVE budget (``deadline_s`` body field /
    # ``X-LMRS-Deadline`` header, seconds); the server anchors it to its
    # own clock at ingress, and the router re-derives the remaining budget
    # when forwarding — absolute wall-clock never crosses a host boundary.
    deadline_s: float | None = None
    # Disaggregated prefill/decode handoff (docs/SERVING.md).
    # ``handoff_export=True``: run prefill + first token only, then PIN
    # the sequence's KV pages for export instead of freeing them — the
    # result comes back ``finish_reason="handoff"`` and the pages stay
    # ref-counted until ``release_handoff`` (decode-side ack) or the
    # orphan sweep.  Engines without handoff support ignore the flag and
    # run the request to completion (graceful colocated fallback).
    # ``handoff_state``: an imported payload dict (in-process only, never
    # serialized with the request) — the engine resumes decoding from the
    # transferred KV pages + first token instead of prefilling.
    handoff_export: bool = False
    handoff_state: dict | None = None
    # Distributed trace id (docs/OBSERVABILITY.md § Trace propagation).
    # Minted at INGRESS (the HTTP server anchors the ``X-LMRS-Trace``
    # header, or mints one; the router mints for engine-protocol callers)
    # and carried across every hop: forwards/retries resend the header,
    # the handoff ticket + payload ride it across the prefill→decode pod
    # boundary, and the job journal persists it so a resumed job
    # continues its trace.  Engines key the request's span track on it
    # (``Tracer.track_for``) so one request's spans stitch into one
    # causal chain fleet-wide; None (engine-direct callers, the CLI
    # pipeline) falls back to the per-run request-id track.
    trace_id: str | None = None
    # Cost-attribution label (docs/OBSERVABILITY.md § Request-cost
    # ledger).  Minted at INGRESS from the ``X-LMRS-Tenant`` header (or
    # the ``tenant`` body field) and propagated exactly like the trace
    # id: router forwards resend the header, both disaggregation legs
    # and the handoff payload carry it, and the job/session journal
    # headers persist it (jobs/sessions default it to their own id when
    # the submit carried none, so ``GET /v1/usage`` rolls up per
    # job/session for free).  None = the engine bills the request to the
    # "default" tenant.
    tenant: str | None = None
    # QoS priority class (fleet/qos.py): "interactive" | "batch".
    # Stamped at ingress from the ``X-LMRS-QoS-Class`` header (or the
    # ``qos_class`` body field) and propagated like the tenant label;
    # jobs stamp their fan-out "batch", live sessions "interactive".
    # None resolves to "interactive" — QoS can never demote traffic
    # that predates the label.  Stamping only happens while LMRS_QOS is
    # armed, so the kill switch keeps the wire byte-identical.
    qos_class: str | None = None
    # Cross-refresh draft hint (ops/speculative.py tree drafting): text
    # whose tokens are LIKELY to recur in this request's completion — a
    # live session passes the previous refresh's summary, which is a
    # near-perfect draft source for the next refresh's continuation.
    # Engines with tree speculation armed seed it AHEAD of the token
    # history in the device draft buffer (scheduler.seed_history), so
    # the n-gram lookup proposes continuations out of it from the first
    # decode step.  Purely advisory: it never affects outputs (the
    # exact-distribution verify guarantees that), only acceptance rate,
    # and engines without speculation ignore it.
    draft_hint: str | None = None


def preamble_text(system_prompt: str | None, prompt: str,
                  cache_prefix: int | None) -> str:
    """A request's SHARED-PREAMBLE text region: the system prompt plus
    the ``cache_prefix``-hinted head of the prompt — exactly the region
    the scheduler donates to the radix tree (scheduler._cache_insert).
    The ONE definition shared by ``preamble_key`` (the router's routing
    hash), the scheduler's summary tokenization, and the mock's
    deterministic emulation, so the three can never drift apart.  Empty
    (or any value, ignored) when the hint is negative — the request
    declares nothing shared."""
    if cache_prefix is not None and cache_prefix < 0:
        return ""
    head = prompt[:cache_prefix] if cache_prefix is not None else ""
    return ((system_prompt + "\n\n") if system_prompt else "") + head


def preamble_key(system_prompt: str | None, prompt: str,
                 cache_prefix: int | None) -> str | None:
    """Stable hash of ``preamble_text`` — the prefix-aware placement key.
    Pure text, so the router needs no tokenizer: both sides hash what
    the wire already carries.  None when the request declares nothing
    shared (negative hint, or the preamble text is empty)."""
    text = preamble_text(system_prompt, prompt, cache_prefix)
    if not text:
        return None
    import hashlib

    return hashlib.sha256(text.encode("utf-8", "replace")).hexdigest()[:16]


def remaining_budget(req: GenerationRequest,
                     now: float | None = None) -> float | None:
    """Seconds of deadline budget left (negative = expired); None when the
    request carries no deadline.  The one remaining-time computation shared
    by scheduler shedding, executor retry clipping, and router forwarding."""
    if req.deadline_s is None:
        return None
    import time

    return req.deadline_s - (time.time() if now is None else now)


@dataclass
class GenerationResult:
    """Completion + accounting (≙ the usage block the reference reads at
    llm_executor.py:304-317)."""

    request_id: int
    text: str = ""
    prompt_tokens: int = 0
    completion_tokens: int = 0
    # stop | length | error | cancelled | deadline | shed | wedged |
    # handoff — deadline/shed are deadline-lifecycle terminals
    # (api.GenerationRequest.deadline_s): "deadline" expired in flight
    # (partial text kept), "shed" rejected at admission before any engine
    # work.  "handoff" is NOT client-terminal: the request stopped after
    # its first token with KV pages pinned for export (handoff_export);
    # only the serving layer ever sees it — it turns the result into a
    # handoff ticket, and the decode pod's continuation is the real
    # completion.  "wedged" (docs/ROBUSTNESS.md § Hang survival) is the
    # watchdog's terminal for a request abandoned inside a wedged
    # dispatch: it always carries ``error`` so the executor's retry
    # machinery re-dispatches it.  Engine-side neither sets
    # ``error`` (they are outcomes the caller asked for, not faults to
    # retry); the one exception is the executor's retry clip, which marks
    # a request that FAILED and then ran out of budget to retry with both
    # finish_reason="deadline" and the error — the failure stays visible.
    finish_reason: str = "stop"
    # which request stop string ended generation, if any — lets wire formats
    # that distinguish stop-sequence hits from EOS (Anthropic's
    # stop_reason="stop_sequence") report faithfully
    stop_sequence: str | None = None
    device_seconds: float = 0.0
    error: str | None = None
    # Per-request cost-ledger bill (obs/ledger.py): phase-split
    # device-seconds, token attribution, tokens saved, page/byte-seconds
    # — attached by engines whose ledger is armed, surfaced as the wire
    # ``usage.cost`` block and rolled up by jobs/sessions/tenant.  None
    # with ``LMRS_COST_LEDGER=0`` (outputs then byte-identical to the
    # pre-ledger wire format).
    usage: dict | None = None

    @property
    def total_tokens(self) -> int:
        return self.prompt_tokens + self.completion_tokens


def degraded_reason(res: "GenerationResult") -> str | None:
    """Why this result carries NO usable content, or None when it does:
    the error itself, or a content-less terminal outcome (``shed`` /
    ``deadline`` / ``cancelled`` with no partial text).  Pipeline
    consumers branch on THIS instead of ``res.error`` — the deadline
    terminals deliberately leave ``error`` unset, and without this check
    a shed map chunk would masquerade as a successful empty summary and
    silently drop its section from the final output.  A deadline/cancel
    result that DOES carry partial text counts as usable
    (degrade-and-continue keeps real output)."""
    if res.error is not None:
        return res.error
    if res.finish_reason in ("shed", "deadline", "cancelled") and not res.text:
        return f"request {res.finish_reason} before any output"
    return None


def apply_stop_sequences(text: str, stops: tuple[str, ...]) -> tuple[str, str | None]:
    """Truncate ``text`` at the earliest-in-text stop string (ties broken by
    list order).  Returns (truncated_text, stop_hit_or_None).  One shared
    implementation so every engine agrees on wire-visible stop semantics —
    the returned text never contains any requested stop string.  Empty stop
    strings are skipped (they'd match at position 0 and silently truncate
    the whole completion; real APIs reject them)."""
    best_pos, best_stop = len(text) + 1, None
    for stop in stops:
        if not stop:
            continue
        pos = text.find(stop)
        if pos != -1 and pos < best_pos:
            best_pos, best_stop = pos, stop
    if best_stop is None:
        return text, None
    return text[:best_pos], best_stop


@runtime_checkable
class Engine(Protocol):
    """Batch generation backend.

    ``generate_batch`` is synchronous from the caller's perspective; backends
    batch internally (continuous batching in JaxEngine).  Failures surface as
    per-result ``error`` fields, never exceptions — the map stage's
    degrade-and-continue contract (llm_executor.py:219-225) depends on it.
    """

    def generate_batch(self, requests: list[GenerationRequest],
                       on_result=None, on_tokens=None) -> list[GenerationResult]:
        """Generate all requests (plus any the callback submits).

        ``on_result(result, submit)``, when given, fires once per completed
        request; ``submit(more)`` feeds new requests into the same run.  The
        continuous scheduler interleaves submissions with in-flight work
        (map→reduce overlap); other backends deliver post-hoc and loop on
        submissions (``drain_with_callback``) — same results, no overlap.
        The returned list covers initial + submitted requests, in
        submission order.  request_ids must be unique per call.

        ``on_tokens(request_id, text_delta)``, when given, fires as text
        becomes available mid-generation (SSE streaming): per decode block
        on the continuous scheduler, one whole-text delta elsewhere.  The
        deltas' concatenation equals the final result's ``text``.
        """
        ...

    def shutdown(self) -> None: ...

    def engine_metrics(self) -> dict:
        """Serving metrics (tokens/s, occupancy, KV utilization); {} when the
        backend has none (SURVEY.md §5.5 'new build' obligation)."""
        ...

    # Optional attribute contract (checked via getattr, absent == False):
    # ``schedules_internally: bool`` — True when the backend runs its own
    # admission control (continuous batching); the executor then submits its
    # whole queue in one call instead of fixed concurrency waves, so batch
    # slots never sit idle waiting on a wave barrier.  Deliberately NOT a
    # Protocol data member: runtime_checkable isinstance would then require
    # it on every implementation, and a Protocol class default is not
    # inherited structurally anyway.
    #
    # ``cancel(request_id: int) -> None`` — optional abort hook (same
    # getattr convention).  Best-effort: aborts the id within the CURRENT
    # generate_batch call at the backend's next safe point (the continuous
    # scheduler frees the slot's pages at the next block boundary); the
    # result comes back with finish_reason="cancelled" and whatever text
    # was generated.  Callable from another thread while generate_batch
    # runs — this is how the HTTP server propagates a client disconnect
    # (the reference's asyncio gave cancellation for free,
    # llm_executor.py:290-296; a batch engine must expose it).


class TenantStampEngine:
    """Engine facade that (a) stamps a tenant label onto every request
    that carries none — how jobs and live sessions bill their chunk and
    reduce traffic to their own identity (or the submit's
    ``X-LMRS-Tenant``) without threading a label through the pipeline —
    and (b) accumulates every result's ledger ``usage`` block into one
    rollup dict (``obs.merge_usage`` semantics), the ``usage`` block of
    the job/session status doc.  Pure pass-through otherwise: optional
    engine attributes (``schedules_internally``, ``cancel``, ...) resolve
    through ``__getattr__``, so the facade composes with every engine
    the managers already accept."""

    def __init__(self, engine: "Engine", tenant: str | None,
                 publish=None, seed: dict | None = None,
                 qos_class: str | None = None,
                 draft_hint: str | None = None):
        self._engine = engine
        self.tenant = tenant
        # cross-refresh draft hint (tree speculation): stamped onto every
        # request that carries none — how a live session threads its
        # previous summary to the drafting buffer without touching the
        # pipeline
        self.draft_hint = draft_hint or None
        # priority-class stamp (fleet/qos.py): jobs pass "batch", live
        # sessions "interactive"; only applied while LMRS_QOS is armed
        # (the kill switch must keep the wire byte-identical), and never
        # over a class the submit already labeled
        from lmrs_tpu.fleet.qos import qos_enabled

        self.qos_class = qos_class if qos_enabled() else None
        # ``publish`` receives an atomic SNAPSHOT dict after every merge:
        # readers (job/session status docs on HTTP handler threads) hold
        # a reference that is replaced, never mutated — json.dumps can
        # never race a mid-merge resize.  ``seed`` carries a prior
        # rollup forward (accumulation across refreshes/resumes).
        self.usage_rollup: dict = dict(seed or {})  # guarded-by: _rollup_lock
        self._publish = publish
        import threading

        self._rollup_lock = threading.Lock()

    def generate_batch(self, requests: list["GenerationRequest"],
                       on_result=None, on_tokens=None):
        self._stamp(requests)

        def absorb(res: "GenerationResult") -> None:
            if res.usage:
                from lmrs_tpu.obs.ledger import merge_usage

                with self._rollup_lock:
                    merge_usage(self.usage_rollup, res.usage)
                    snap = dict(self.usage_rollup)
                if self._publish is not None:
                    self._publish(snap)

        if on_result is None:
            out = self._engine.generate_batch(requests, on_tokens=on_tokens)
            for res in out:
                absorb(res)
            return out

        def wrapped(res, submit):
            absorb(res)

            def stamped_submit(more: list["GenerationRequest"]) -> None:
                self._stamp(more)
                submit(more)

            on_result(res, stamped_submit)

        return self._engine.generate_batch(requests, on_result=wrapped,
                                           on_tokens=on_tokens)

    def _stamp(self, requests: list["GenerationRequest"]) -> None:
        for req in requests:
            if self.tenant and req.tenant is None:
                req.tenant = self.tenant
            if self.qos_class and req.qos_class is None:
                req.qos_class = self.qos_class
            if self.draft_hint and req.draft_hint is None:
                req.draft_hint = self.draft_hint

    def __getattr__(self, name: str):
        return getattr(self._engine, name)


def drain_with_callback(run_batch, requests: list["GenerationRequest"],
                        on_result) -> list["GenerationResult"]:
    """Streaming semantics for backends without a mid-run hook: run a wave,
    deliver each result, collect callback submissions, repeat until dry.
    Same results/ordering contract as the continuous scheduler's streaming
    path, minus the in-flight overlap."""
    all_results: list[GenerationResult] = []
    pending = list(requests)
    submitted: list[GenerationRequest] = []

    def submit(new_requests: list["GenerationRequest"]) -> None:
        submitted.extend(new_requests)

    while pending:
        results = run_batch(pending)
        all_results.extend(results)
        for res in results:
            on_result(res, submit)
        pending, submitted = submitted, []
    return all_results


def make_engine(
    engine_cfg: "EngineConfig",
    model_cfg: "ModelConfig | None" = None,
    mesh_cfg: "MeshConfig | None" = None,
) -> Engine:
    """Engine factory keyed on ``EngineConfig.backend``."""
    if engine_cfg.fault_plan:
        # arm the fault-injection plane for this process (testing/faults.py);
        # default-empty configs never touch it (module no-op stays in place)
        from lmrs_tpu.testing import faults

        faults.install_spec(engine_cfg.fault_plan)
    if engine_cfg.backend == "mock":
        from lmrs_tpu.engine.mock import MockEngine

        return MockEngine(seed=engine_cfg.seed,
                          handoff_ttl_s=engine_cfg.handoff_ttl_s,
                          mixed_batch=engine_cfg.mixed_batch,
                          mixed_token_budget=engine_cfg.mixed_token_budget,
                          prefix_cache=engine_cfg.prefix_cache,
                          host_kv=engine_cfg.host_kv,
                          host_kv_gb=engine_cfg.host_kv_gb,
                          speculate_k=engine_cfg.speculate_k)
    if engine_cfg.backend == "jax":
        from lmrs_tpu.config import ModelConfig, model_preset

        try:
            from lmrs_tpu.engine.jax_engine import JaxEngine
        except ImportError as e:
            raise ValueError(f"jax backend unavailable: {e}") from e

        # EngineConfig.model (the --model flag) names a preset; an explicitly
        # customized ModelConfig wins over the preset lookup.
        if model_cfg is None or (
            model_cfg == ModelConfig() and engine_cfg.model != model_cfg.name
        ):
            model_cfg = model_preset(engine_cfg.model)
        if mesh_cfg is not None and mesh_cfg.dp > 1:
            # dp>1 serving = independent replicas, not a dp mesh axis
            # (engine/replicated.py module doc explains why)
            from lmrs_tpu.engine.replicated import ReplicatedEngine

            return ReplicatedEngine(engine_cfg, model_cfg, mesh_cfg)
        return JaxEngine(engine_cfg, model_cfg, mesh_cfg)
    if engine_cfg.backend == "http":
        from lmrs_tpu.serving.router import RouterEngine

        if not (engine_cfg.hosts or engine_cfg.prefill_hosts
                or engine_cfg.decode_hosts):
            raise ValueError(
                "backend='http' needs hosts (--hosts host:port,... or "
                "LMRS_HOSTS; role pools via LMRS_PREFILL_HOSTS/"
                "LMRS_DECODE_HOSTS): the addresses of running lmrs-serve "
                "processes")
        # The router's timeout is a per-recv SOCKET timeout, and a
        # non-streamed generation sends nothing until it completes — the
        # reference-derived REQUEST_TIMEOUT default (60 s) would time out
        # any long completion, error it, and mark healthy hosts dead.
        # Floor it at the router's own worst-case-generation default.
        return RouterEngine(list(engine_cfg.hosts),
                            timeout_s=max(engine_cfg.request_timeout, 600.0),
                            prefill_hosts=list(engine_cfg.prefill_hosts),
                            decode_hosts=list(engine_cfg.decode_hosts))
    raise ValueError(f"unknown engine backend {engine_cfg.backend!r}")
