"""Hang-survival tier, layer 1: the dispatch watchdog.

The reference pipeline's only robustness primitive was an HTTP timeout +
retry around each API call (llm_executor.py:198-228); collapsing the API
boundary onto the TPU removed that last line of defense — a dispatch
that *wedges* (hung chip, stuck DMA, stalled collective, or an injected
``scheduler.heartbeat`` stall) used to freeze the whole engine forever,
because the scheduler loop blocks synchronously in ``jax.device_get``
with no timeout.  This module turns a wedge into a bounded, observable
failure:

* :class:`DispatchWatchdog` — monotonic heartbeat state the scheduler
  loop stamps once per iteration (``beat``).  ``LMRS_WATCHDOG_S`` sets
  the wedge threshold explicitly; the default (0 = auto) scales off an
  EMA of the observed inter-beat step time, so a chip that normally
  steps in 20 ms is declared wedged long before one that legitimately
  runs 2 s decode blocks.  Compiling shapes get a one-shot grace window
  (``grace_cold``): a first-dispatch XLA compile can take minutes and
  must never read as a hang.

* :class:`WatchdogRunner` — owned by ``JaxEngine`` when the watchdog is
  armed (``LMRS_WATCHDOG``, default on).  The scheduler's ``run()``
  moves onto a dedicated daemon dispatch thread and the CALLER thread
  becomes the watchdog: it polls the heartbeat while waiting on the run.
  When no progress lands within the threshold it declares a wedge —
  flight-recorder postmortem (``reason="watchdog"``), then synthesizes
  terminal results for every request the run never delivered:
  deadline-expired requests get their contractual ``"deadline"`` result
  (the sweep a wedged loop can never reach — docs/ROBUSTNESS.md),
  everything else ``finish_reason="wedged"`` with ``error`` set so the
  executor's retry machinery re-dispatches them.  The engine then runs
  FAIL-FAST degraded — new batches return wedged results immediately
  instead of queueing behind the dead dispatch — until the abandoned
  run's thread eventually returns (a transient stall self-heals; the
  scheduler's own recovery/finally restores the pool) or the process is
  bounced by the supervisor (serving/supervisor.py).

* :class:`DaemonExecutor` — a minimal single-worker executor whose
  thread is a DAEMON: a wedged dispatch (or probe) future must never pin
  interpreter exit the way a stuck ``ThreadPoolExecutor`` worker does.
  Shared with ``engine/replicated.py``'s per-replica pools.

``LMRS_WATCHDOG=0`` restores today's byte-for-byte behavior: the
scheduler runs inline on the caller thread, no runner thread exists, and
the heartbeat sites cost one ``None`` check each.
"""

from __future__ import annotations

import logging
import threading
import time
from concurrent.futures import Future
from concurrent.futures import TimeoutError as FutureTimeout
from dataclasses import dataclass, field

from lmrs_tpu.engine.api import GenerationRequest, GenerationResult
from lmrs_tpu.obs import dump_postmortem
from lmrs_tpu.utils.env import env_float

logger = logging.getLogger("lmrs.watchdog")

# a compiling shape's first dispatch may take minutes (multi-second XLA
# compiles at real model sizes; tens of minutes cold on the CPU CI
# emulator) — a one-shot grace window this wide keeps every legitimate
# compile out of the wedge detector without a knob nobody should tune
COLD_COMPILE_GRACE_S = 3600.0


class DaemonExecutor:
    """Single-worker executor over one DAEMON thread.

    ``concurrent.futures.ThreadPoolExecutor`` workers are non-daemon:
    one wedged future pins interpreter exit forever (the
    ``engine/replicated.py`` probe note).  This executor keeps the same
    ``submit() -> Future`` surface on a thread that can never hold the
    process hostage.  Tasks run strictly in submission order, so it is a
    drop-in for the repo's max_workers=1 serialization pools."""

    def __init__(self, thread_name: str = "lmrs-worker"):
        import queue

        self._q: queue.Queue = queue.Queue()
        # orders the shutdown flag against enqueues: without it a submit
        # racing shutdown could land its item BEHIND the stop sentinel —
        # a future that never runs and is never cancelled, which a
        # watcher would poll forever
        self._mu = threading.Lock()
        self._shutdown = False  # guarded-by: _mu
        self._thread = threading.Thread(target=self._loop,
                                        name=thread_name, daemon=True)
        self._thread.start()

    def _loop(self) -> None:
        while True:
            item = self._q.get()
            if item is None:
                return
            fut, fn, args, kwargs = item
            if not fut.set_running_or_notify_cancel():
                continue
            try:
                fut.set_result(fn(*args, **kwargs))
            except BaseException as e:  # noqa: BLE001 - future carries it
                fut.set_exception(e)

    def submit(self, fn, *args, **kwargs) -> Future:
        fut: Future = Future()
        with self._mu:
            if self._shutdown:
                raise RuntimeError("executor is shut down")
            self._q.put((fut, fn, args, kwargs))
        return fut

    def shutdown(self, wait: bool = False, cancel_futures: bool = False) -> None:
        """Stop accepting work; the daemon thread drains (or dies with
        the process).  ``cancel_futures`` cancels everything still
        queued — a wedged RUNNING task is simply abandoned (daemon)."""
        with self._mu:
            self._shutdown = True
            if cancel_futures:
                import queue

                while True:
                    try:
                        item = self._q.get_nowait()
                    except queue.Empty:
                        break
                    if item is not None:
                        item[0].cancel()
            self._q.put(None)
        if wait:
            self._thread.join(timeout=5.0)


class DispatchWatchdog:
    """Monotonic heartbeat + wedge-threshold state (see module doc).

    Thread contract: ``beat``/``grace_cold``/``run_started``/
    ``run_ended`` are called by the dispatch thread; ``stalled_for`` /
    ``timeout_s`` by the watching caller thread.  All state writes are
    single plain-float/bool stores (GIL-atomic), read racily on purpose
    — a heartbeat landing mid-check just reads as progress."""

    def __init__(self):
        self.ema_step_s: float | None = None  # inter-beat EMA (warm steps)
        self._last_beat: float | None = None  # monotonic; None = no run live
        self._grace_until = 0.0  # monotonic deadline of a cold-shape grace
        # True while the CURRENT inter-beat window contained a compile
        # grace — the next beat must skip the EMA fold even though
        # grace_end() already re-armed stall detection (folding a 120s
        # compile wall would inflate the auto threshold ~30x for the
        # rest of the run)
        self._window_graced = False

    # ------------------------------------------------- dispatch-thread side

    def run_started(self) -> None:
        self._grace_until = 0.0
        self._window_graced = False
        self._last_beat = time.monotonic()

    def run_ended(self) -> None:
        self._last_beat = None

    def beat(self) -> None:
        """One scheduler-loop iteration landed: progress.  Folds the
        inter-beat gap into the step-time EMA unless a cold-compile
        grace opened anywhere in the window (a compile wall must not
        inflate the wedge threshold for the rest of the run — the flag,
        not ``_grace_until``, carries this: ``grace_end`` re-arms stall
        detection the moment the compile lands, but the wall still
        pollutes THIS window's gap)."""
        now = time.monotonic()
        prev = self._last_beat
        if prev is not None and not self._window_graced:
            gap = now - prev
            self.ema_step_s = (gap if self.ema_step_s is None
                               else 0.8 * self.ema_step_s + 0.2 * gap)
        self._window_graced = False
        self._grace_until = 0.0
        self._last_beat = now

    def grace_cold(self) -> None:
        """The next dispatch compiles a new shape: suspend wedge
        detection for one generous window (closed by ``grace_end`` when
        the compile lands, or by the next beat)."""
        self._grace_until = time.monotonic() + COLD_COMPILE_GRACE_S
        self._window_graced = True

    def grace_end(self) -> None:
        """The cold dispatch completed: re-arm the detector NOW.  Without
        this, a grace opened for a compile in the same loop iteration
        would also mask a genuine stall at the next loop-top heartbeat
        site — the compile is done, so the wedge clock must run again."""
        self._grace_until = 0.0

    # --------------------------------------------------- watcher-thread side

    def timeout_s(self) -> float:
        """The wedge threshold: ``LMRS_WATCHDOG_S`` when set (> 0), else
        scaled off the step-time EMA — generous (30x a normal step,
        floored well above any warm dispatch) because a false positive
        abandons a healthy run.  Read per call so tests can retune
        without rebuilding the engine."""
        explicit = env_float("LMRS_WATCHDOG_S", 0.0, lo=0.0)
        if explicit > 0:
            return explicit
        if self.ema_step_s is None:
            return 300.0  # no sample yet: only a gross hang trips
        return min(max(30.0 * self.ema_step_s, 60.0), 900.0)

    def stalled_for(self) -> float:
        """Seconds since the last heartbeat, 0.0 when no run is live or
        a cold-compile grace window is open."""
        last = self._last_beat
        if last is None or time.monotonic() < self._grace_until:
            return 0.0
        return time.monotonic() - last


@dataclass
class _RunCtx:
    """Per-run bookkeeping the wedge sweep synthesizes results from.
    Mutated only by the dispatch thread (callback wrappers) until
    ``abandoned`` flips — after which the wrappers are no-ops and the
    watcher thread owns the snapshot."""

    known: list[GenerationRequest]
    results: dict[int, GenerationResult] = field(default_factory=dict)
    streamed: dict[int, str] = field(default_factory=dict)
    abandoned: bool = False


class WatchdogRunner:
    """Run ``scheduler.run()`` on a daemon dispatch thread, watched from
    the caller thread (see module doc).  One runner per scheduler; calls
    to :meth:`run` are serialized by the engine's existing callers (the
    HTTP batcher loop / the executor / a replica's worker pool) exactly
    as direct ``scheduler.run`` calls were."""

    def __init__(self, scheduler):
        self.sched = scheduler
        self._pool = DaemonExecutor(thread_name="lmrs-dispatch")
        self._lock = threading.Lock()
        self._stuck: Future | None = None  # guarded-by: _lock
        self._stuck_since = 0.0            # guarded-by: _lock

    # ---------------------------------------------------------------- state

    @property
    def wedged(self) -> bool:
        """True while a wedged run still holds the dispatch thread (the
        engine's fail-fast degraded state)."""
        with self._lock:
            return self._stuck is not None and not self._stuck.done()

    def wait_idle(self, timeout_s: float = 30.0) -> bool:
        """Block until any abandoned run finishes and clear the degraded
        state (tests; the serving layer recovers lazily at the next
        batch).  Returns True when the dispatch thread is idle."""
        with self._lock:
            fut = self._stuck
        if fut is None:
            return True
        try:
            fut.result(timeout=timeout_s)
        except Exception:  # noqa: BLE001 - the run's own failure is logged
            pass
        with self._lock:
            if self._stuck is fut and fut.done():
                self._clear_stuck_locked(fut)
        return fut.done()

    def shutdown(self) -> None:
        self._pool.shutdown(wait=False, cancel_futures=True)

    def _clear_stuck_locked(self, fut: Future) -> None:  # holds-lock: _lock
        """Caller holds self._lock."""
        exc = fut.exception() if fut.done() else None
        if exc is not None:
            # the abandoned run died; the scheduler's except path already
            # ran pool recovery, so the engine is usable again
            logger.warning("abandoned wedged run finished with %s: %s",
                           type(exc).__name__, exc)
        else:
            logger.info("wedged dispatch recovered after %.1fs; engine "
                        "re-armed", time.monotonic() - self._stuck_since)
        self._stuck = None

    # ------------------------------------------------------------------ run

    def run(self, requests: list[GenerationRequest],
            on_result=None, on_tokens=None) -> list[GenerationResult]:
        stuck_for: float | None = None
        with self._lock:
            fut = self._stuck
            if fut is not None:
                if fut.done():
                    self._clear_stuck_locked(fut)
                else:
                    stuck_for = time.monotonic() - self._stuck_since
        if stuck_for is not None:
            # fail-fast degraded: nothing queues behind a dead dispatch —
            # the caller's retry/routing layers place the work elsewhere
            # (or the supervisor bounces us).  Delivery runs OUTSIDE the
            # lock: on_result callbacks are arbitrary caller code and may
            # themselves read wedged()/wait_idle() (the non-reentrant
            # lock would deadlock), same discipline as the wedge sweep.
            return self._deliver_synthesized(
                _RunCtx(list(requests)), on_result,
                err=f"engine wedged: dispatch thread stuck for "
                    f"{stuck_for:.1f}s")
        ctx = _RunCtx(list(requests))
        run_fut = self._pool.submit(
            self.sched.run, requests,
            on_result=self._wrap_on_result(ctx, on_result),
            on_tokens=self._wrap_on_tokens(ctx, on_tokens))
        wd = self.sched.watchdog
        while True:
            try:
                # the caller thread IS the watchdog while it waits: poll
                # granularity adapts to the threshold (cache-cheap; the
                # run future wakes it immediately on completion)
                return run_fut.result(
                    timeout=max(0.05, min(wd.timeout_s() / 4.0, 2.0)))
            except FutureTimeout:
                stalled = wd.stalled_for()
                timeout = wd.timeout_s()
                if stalled <= timeout:
                    continue
                return self._declare_wedge(ctx, run_fut, on_result,
                                           stalled, timeout)

    # ------------------------------------------------------------ callbacks

    def _wrap_on_result(self, ctx: _RunCtx, user_cb):
        """Track delivery + submissions; mute everything once abandoned
        (a resumed wedged run must not double-deliver into the caller's
        queues).  Returning None keeps the scheduler's no-callback fast
        path when the caller passed none — except that delivery tracking
        still matters for the wedge sweep, so a tracker is always
        installed."""
        def wrapped(res: GenerationResult, submit) -> None:
            if ctx.abandoned:
                return
            ctx.results[res.request_id] = res
            if user_cb is not None:
                def tracked_submit(more: list[GenerationRequest]) -> None:
                    ctx.known.extend(more)
                    submit(more)

                user_cb(res, tracked_submit)

        return wrapped

    def _wrap_on_tokens(self, ctx: _RunCtx, user_cb):
        """Delta tracker (the wedge sweep's partial-text source) — but
        ONLY when the caller actually streams: installing a callback on
        non-streaming runs would force the scheduler's per-block
        frontier-trimming path and hold a second copy of every
        completion for pure overhead.  Non-streaming wedged results
        carry text="" — their callers retry on the marked error anyway."""
        if user_cb is None:
            return None

        def wrapped(rid: int, delta: str) -> None:
            if ctx.abandoned:
                return
            ctx.streamed[rid] = ctx.streamed.get(rid, "") + delta
            user_cb(rid, delta)

        return wrapped

    # ---------------------------------------------------------- wedge sweep

    def _declare_wedge(self, ctx: _RunCtx, run_fut: Future, on_result,
                       stalled: float, timeout: float
                       ) -> list[GenerationResult]:
        """No heartbeat within the threshold: abandon the run, freeze the
        evidence, and terminate every undelivered request (see module
        doc).  The abandoned thread keeps the stuck device call; if it
        ever returns, the run's own finally/except restores the pool and
        the degraded state clears at the next batch."""
        ctx.abandoned = True  # flipped BEFORE any delivery: the stuck
        # thread may resume mid-sweep and must find its callbacks muted
        with self._lock:
            self._stuck = run_fut
            self._stuck_since = time.monotonic()
        # cancel everything the abandoned run still holds: if the stall
        # is transient, its first post-stall loop iteration sweeps the
        # cancels and the run drains in ~one block instead of recomputing
        # the whole abandoned workload to muted callbacks — the engine
        # re-arms while the caller's retry budget is still alive (the
        # end-to-end wedge drive caught a degraded engine outliving
        # 3 x retry_delay without this)
        for r in ctx.known:
            self.sched.cancel(r.request_id)
        self.sched._c_watchdog_fires.inc()
        undelivered = [r for r in ctx.known
                       if r.request_id not in ctx.results]
        logger.error("dispatch wedge: no scheduler heartbeat for %.1fs "
                     "(threshold %.1fs); abandoning the run, %d request(s) "
                     "terminate wedged/deadline", stalled, timeout,
                     len(undelivered))
        # postmortem FIRST, before synthesis mutates counters: the dump
        # must show the metrics as the wedge left them (the same ordering
        # rule as the dispatch-fault recovery path).  No-op unless
        # LMRS_POSTMORTEM_DIR is armed; never raises.
        dump_postmortem(
            "watchdog", metrics=self.sched.metrics,
            extra={"stalled_s": round(stalled, 3),
                   "timeout_s": round(timeout, 3),
                   "undelivered": len(undelivered),
                   "step_ema_s": self.sched.watchdog.ema_step_s})
        return self._deliver_synthesized(
            ctx, on_result,
            err=f"engine dispatch wedged: no progress for {stalled:.1f}s")

    def _deliver_synthesized(self, ctx: _RunCtx, on_result,
                             err: str) -> list[GenerationResult]:
        """Terminal results for every request the run never delivered:
        ``"deadline"`` for expired budgets (no error — the contractual
        outcome the caller asked for; the executor must not retry it),
        ``"wedged"`` + error for the rest (the executor retries those
        once a healthy engine can take them).  Partial streamed text is
        kept — it is real output a streaming client may already hold
        (the cancel/expiry contract, scheduler.cancel docstring)."""
        now = time.time()
        out: list[GenerationResult] = []
        for req in ctx.known:
            rid = req.request_id
            res = ctx.results.get(rid)
            if res is None:
                text = ctx.streamed.get(rid, "")
                expired = req.deadline_s is not None and req.deadline_s <= now
                if expired:
                    res = GenerationResult(
                        request_id=rid, text=text,
                        finish_reason="deadline")
                    self.sched._c_deadline.inc()
                else:
                    res = GenerationResult(
                        request_id=rid, text=text,
                        finish_reason="wedged", error=err)
                    self.sched._c_wedged.inc()
                # the wedge bill still lands in the cost ledger (and the
                # SLO outcome stream): a wedged request is exactly the
                # kind of waste per-tenant accounting must show
                self.sched.cost_finish(req, res)
                if on_result is not None:
                    on_result(res, self._dead_submit)
            out.append(res)
        return out

    @staticmethod
    def _dead_submit(more: list[GenerationRequest]) -> None:
        logger.warning("submit() ignored on a wedged run: %d request(s) "
                       "dropped (the caller's retry owns them)", len(more))
