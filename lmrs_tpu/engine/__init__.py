"""L2/L6 engine layer: generation API, mock + JAX backends, map executor."""

from lmrs_tpu.engine.api import Engine, GenerationRequest, GenerationResult, make_engine
from lmrs_tpu.engine.executor import MapExecutor
from lmrs_tpu.engine.mock import MockEngine

__all__ = [
    "Engine",
    "GenerationRequest",
    "GenerationResult",
    "MapExecutor",
    "MockEngine",
    "make_engine",
]
