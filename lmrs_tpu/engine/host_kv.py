"""Host-RAM KV spill tier: the capacity layer behind the HBM prefix cache.

Today an HBM eviction throws a refcount-zero radix node's pages away and a
later request re-prefills the whole preamble from scratch.  This module is
the second tier of the cache hierarchy (ROADMAP item 3): evicted page
CONTENT is captured device→host into a bounded host-memory pool and the
radix node stays in the tree as a *spilled* node — on a later match the
payload prefetches back into freshly allocated device pages (one scatter,
issued asynchronously on the scheduler thread so the transfer overlaps the
dispatch cadence) instead of re-prefilling.  The packing-prefetch result in
the long-context acceleration paper (PAPERS.md) is the motivating shape:
KV prefetch from a slower tier hides almost entirely under ongoing compute
for exactly this long-preamble summarization workload.

Design notes
------------
* The pool stores *references to radix nodes* (engine/prefix_cache.py);
  the payload arrays live on the node itself (``_Node.spill``).  The pool
  is pure accounting: bytes used, LRU victim selection against a budget
  (``LMRS_HOST_KV_GB``), counters.  Single-threaded by contract — every
  caller runs on the scheduler thread, like the prefix cache itself.
* "Pinned" host memory is aspirational on this runtime: jax has no public
  pinned-allocation API, so payloads are plain numpy buffers.  The scatter
  path (``PagedKVCache.import_pages``) still overlaps: ``jnp.asarray`` +
  ``.at[].set`` dispatch asynchronously and the device sequences the copy
  before the next dispatch that consumes the pool.
* Victim selection respects a ``keep`` set (node ids): mid-insert the walk
  path is pinned exactly like HBM eviction pins it — dropping an ancestor
  of the node being attached would orphan the new leaf.
* An entry larger than the whole budget is refused (``fits`` is checked
  by the caller BEFORE capture, so an oversized node skips the device→host
  gather entirely and frees exactly as with the tier off).
"""

from __future__ import annotations

import logging

logger = logging.getLogger("lmrs.host_kv")


class HostKVPool:
    """Bounded host-RAM pool of spilled KV page payloads (accounting only;
    payload arrays live on the owning radix nodes).  All methods run on
    the scheduler thread — no locking, same contract as PrefixCache."""

    def __init__(self, budget_bytes: int):
        self.budget_bytes = max(0, int(budget_bytes))
        self.used_bytes = 0
        # id(node) -> (node, nbytes).  Recency is the node's own radix
        # ``tick`` (one LRU clock across both tiers — a prefetch-hit or
        # re-match bumps it exactly like a resident hit).
        self.entries: dict[int, tuple[object, int]] = {}
        # cumulative counters (PrefixCache.stats / metrics_report feed)
        self.spilled_pages_total = 0
        self.prefetched_pages_total = 0
        self.dropped_pages_total = 0

    def __len__(self) -> int:
        return len(self.entries)

    def fits(self, nbytes: int) -> bool:
        """Whether an entry of ``nbytes`` can ever be admitted."""
        return 0 < nbytes <= self.budget_bytes

    def add(self, node, nbytes: int, n_pages: int) -> None:
        """Admit a spilled node (caller guarantees ``fits``); budget
        enforcement is a separate pass (``victims``) because victim
        subtree drops need the tree, which the pool does not know."""
        self.entries[id(node)] = (node, int(nbytes))
        self.used_bytes += int(nbytes)
        self.spilled_pages_total += n_pages

    def remove(self, node, n_pages: int = 0, dropped: bool = False) -> None:
        """Forget a node (prefetch promotion, subtree drop, or budget
        eviction).  ``dropped=True`` counts the pages as lost from the
        tier (budget LRU / subtree drop) rather than promoted back."""
        ent = self.entries.pop(id(node), None)
        if ent is None:
            return
        self.used_bytes -= ent[1]
        if dropped:
            self.dropped_pages_total += n_pages

    def note_prefetch(self, n_pages: int) -> None:
        self.prefetched_pages_total += n_pages

    def over_budget(self) -> bool:
        return self.used_bytes > self.budget_bytes

    def victim(self, keep=None):
        """The LRU spilled node (min radix tick) outside ``keep`` (a set
        of ``id(node)`` the caller has pinned), or None.  The caller
        drops the victim's subtree and calls ``remove`` for every spilled
        node in it — the pool never mutates the tree."""
        best = None
        for node, _nbytes in self.entries.values():
            if keep and id(node) in keep:
                continue
            if best is None or node.tick < best.tick:
                best = node
        return best

    def stats(self) -> dict:
        return {
            "host_pool_entries": len(self.entries),
            "host_pool_bytes": self.used_bytes,
            "host_pool_budget_bytes": self.budget_bytes,
            "spilled_pages_total": self.spilled_pages_total,
            "prefetched_pages_total": self.prefetched_pages_total,
            "host_dropped_pages_total": self.dropped_pages_total,
        }
