"""Host-RAM KV spill tier: the capacity layer behind the HBM prefix cache.

Today an HBM eviction throws a refcount-zero radix node's pages away and a
later request re-prefills the whole preamble from scratch.  This module is
the second tier of the cache hierarchy (ROADMAP item 3): evicted page
CONTENT is captured device→host into a bounded host-memory pool and the
radix node stays in the tree as a *spilled* node — on a later match the
payload prefetches back into freshly allocated device pages (one scatter,
issued asynchronously on the scheduler thread so the transfer overlaps the
dispatch cadence) instead of re-prefilling.  The packing-prefetch result in
the long-context acceleration paper (PAPERS.md) is the motivating shape:
KV prefetch from a slower tier hides almost entirely under ongoing compute
for exactly this long-preamble summarization workload.

Design notes
------------
* The pool stores *references to radix nodes* (engine/prefix_cache.py);
  the payload arrays live on the node itself (``_Node.spill``).  The pool
  is pure accounting: bytes used, LRU victim selection against a budget
  (``LMRS_HOST_KV_GB``), counters.  Single-threaded by contract — every
  caller runs on the scheduler thread, like the prefix cache itself.
* "Pinned" host memory is aspirational on this runtime: jax has no public
  pinned-allocation API, so payloads are plain numpy buffers.  The scatter
  path (``PagedKVCache.import_pages``) still overlaps: ``jnp.asarray`` +
  ``.at[].set`` dispatch asynchronously and the device sequences the copy
  before the next dispatch that consumes the pool.
* Victim selection respects a ``keep`` set (node ids): mid-insert the walk
  path is pinned exactly like HBM eviction pins it — dropping an ancestor
  of the node being attached would orphan the new leaf.
* An entry larger than the whole budget is refused (``fits`` is checked
  by the caller BEFORE capture, so an oversized node skips the device→host
  gather entirely and frees exactly as with the tier off).

Disk tier (ROADMAP item 4)
--------------------------
:class:`DiskKVPool` is the THIRD tier: host-pool budget pressure demotes
the LRU host entry's payload to an mmap'd spill file instead of dropping
it (``LMRS_KV_DISK=1``, budget ``LMRS_KV_DISK_GB``, directory
``LMRS_KV_DISK_DIR``).  The radix node stays in the tree; its ``spill``
payload becomes a small *descriptor* dict (``{"disk": True, "path", ...,
"crc"}``) and promotion reads the file back (disk→host memory) on the
same prefetch path that already restores host entries to the device.
Every file is content-tagged with a crc32 the read path verifies: a
missing, torn, or corrupt file surfaces as :class:`DiskReadError` and the
caller degrades to re-prefill — never silently-wrong KV, never a wedged
admission (the ``kv.disk_read`` fault contract, docs/ROBUSTNESS.md).
Recency stays the node's radix ``tick`` — ONE LRU clock across all three
tiers.  Disk budget pressure drops LRU disk subtrees for real.
"""

from __future__ import annotations

import logging
import os
import tempfile
import zlib

import numpy as np

logger = logging.getLogger("lmrs.host_kv")


def _np_dtype(name: str) -> np.dtype:
    """Dtype from its string name, covering the ml_dtypes extensions
    (bfloat16 et al.) numpy alone does not know."""
    try:
        return np.dtype(name)
    except TypeError:
        import ml_dtypes  # jax dependency, always present with jax

        return np.dtype(getattr(ml_dtypes, name))


class DiskReadError(RuntimeError):
    """A disk-tier payload could not be read back (missing, torn, or
    corrupt spill file).  Callers degrade to re-prefill — the same
    contract as a host entry dropped between match and prefetch."""


class HostKVPool:
    """Bounded host-RAM pool of spilled KV page payloads (accounting only;
    payload arrays live on the owning radix nodes).  All methods run on
    the scheduler thread — no locking, same contract as PrefixCache."""

    def __init__(self, budget_bytes: int, disk: "DiskKVPool | None" = None):
        self.budget_bytes = max(0, int(budget_bytes))
        self.used_bytes = 0
        # optional third tier: budget pressure demotes the LRU entry here
        # instead of dropping it (PrefixCache._enforce_host_budget)
        self.disk = disk
        # id(node) -> (node, nbytes).  Recency is the node's own radix
        # ``tick`` (one LRU clock across all tiers — a prefetch-hit or
        # re-match bumps it exactly like a resident hit).
        self.entries: dict[int, tuple[object, int]] = {}
        # cumulative counters (PrefixCache.stats / metrics_report feed)
        self.spilled_pages_total = 0
        self.prefetched_pages_total = 0
        self.dropped_pages_total = 0

    def __len__(self) -> int:
        return len(self.entries)

    def fits(self, nbytes: int) -> bool:
        """Whether an entry of ``nbytes`` can ever be admitted."""
        return 0 < nbytes <= self.budget_bytes

    def add(self, node, nbytes: int, n_pages: int) -> None:
        """Admit a spilled node (caller guarantees ``fits``); budget
        enforcement is a separate pass (``victims``) because victim
        subtree drops need the tree, which the pool does not know."""
        self.entries[id(node)] = (node, int(nbytes))
        self.used_bytes += int(nbytes)
        self.spilled_pages_total += n_pages

    def remove(self, node, n_pages: int = 0, dropped: bool = False) -> None:
        """Forget a node (prefetch promotion, subtree drop, or budget
        eviction).  ``dropped=True`` counts the pages as lost from the
        tier (budget LRU / subtree drop) rather than promoted back."""
        ent = self.entries.pop(id(node), None)
        if ent is None:
            return
        self.used_bytes -= ent[1]
        if dropped:
            self.dropped_pages_total += n_pages

    def note_prefetch(self, n_pages: int) -> None:
        self.prefetched_pages_total += n_pages

    def over_budget(self) -> bool:
        return self.used_bytes > self.budget_bytes

    def victim(self, keep=None):
        """The LRU spilled node (min radix tick) outside ``keep`` (a set
        of ``id(node)`` the caller has pinned), or None.  The caller
        drops the victim's subtree and calls ``remove`` for every spilled
        node in it — the pool never mutates the tree."""
        best = None
        for node, _nbytes in self.entries.values():
            if keep and id(node) in keep:
                continue
            if best is None or node.tick < best.tick:
                best = node
        return best

    def stats(self) -> dict:
        out = {
            "host_pool_entries": len(self.entries),
            "host_pool_bytes": self.used_bytes,
            "host_pool_budget_bytes": self.budget_bytes,
            "spilled_pages_total": self.spilled_pages_total,
            "prefetched_pages_total": self.prefetched_pages_total,
            "host_dropped_pages_total": self.dropped_pages_total,
        }
        if self.disk is not None:
            out.update(self.disk.stats())
        return out


class DiskKVPool:
    """Bounded disk tier under the host pool: accounting + spill-file
    I/O.  Like :class:`HostKVPool` the pool stores references to radix
    nodes and never mutates the tree; unlike it, the node's payload is a
    *descriptor* dict pointing at one spill file (raw k-bytes then
    v-bytes, crc32 content tag).  Files land in a fresh per-pool
    subdirectory of ``dir_path`` (system temp when empty), so concurrent
    engines in one process never collide.  Single-threaded by the same
    scheduler-thread contract as the host pool."""

    def __init__(self, budget_bytes: int, dir_path: str = ""):
        self.budget_bytes = max(0, int(budget_bytes))
        if dir_path:
            os.makedirs(dir_path, exist_ok=True)
        self.dir = tempfile.mkdtemp(prefix="lmrs-kvd-",
                                    dir=dir_path or None)
        self.used_bytes = 0
        # id(node) -> (node, nbytes); recency is the node's radix tick —
        # the ONE LRU clock shared by all three tiers
        self.entries: dict[int, tuple[object, int]] = {}
        self._seq = 0
        self.demoted_pages_total = 0
        self.promoted_pages_total = 0
        self.dropped_pages_total = 0
        self.read_failures_total = 0

    def __len__(self) -> int:
        return len(self.entries)

    # ------------------------------------------------------------ accounting

    def fits(self, nbytes: int) -> bool:
        return 0 < nbytes <= self.budget_bytes

    def add(self, node, nbytes: int, n_pages: int) -> None:
        """Admit a demoted node (caller guarantees ``fits``); budget
        enforcement is the caller's separate pass, exactly like the host
        pool (victim subtree drops need the tree)."""
        self.entries[id(node)] = (node, int(nbytes))
        self.used_bytes += int(nbytes)
        self.demoted_pages_total += n_pages

    def remove(self, node, n_pages: int = 0, dropped: bool = False) -> None:
        ent = self.entries.pop(id(node), None)
        if ent is None:
            return
        self.used_bytes -= ent[1]
        if dropped:
            self.dropped_pages_total += n_pages

    def note_promote(self, n_pages: int) -> None:
        self.promoted_pages_total += n_pages

    def over_budget(self) -> bool:
        return self.used_bytes > self.budget_bytes

    def victim(self, keep=None):
        """LRU disk entry (min radix tick) outside ``keep``, or None —
        same contract as HostKVPool.victim."""
        best = None
        for node, _nbytes in self.entries.values():
            if keep and id(node) in keep:
                continue
            if best is None or node.tick < best.tick:
                best = node
        return best

    # ------------------------------------------------------------- file I/O

    def write(self, payload: dict) -> dict:
        """Persist a host payload's k/v arrays as one spill file and
        return the descriptor that replaces the node's in-memory payload.
        Raises ``OSError`` on a failed write (disk full, bad dir) — the
        caller degrades to dropping the entry."""
        k, v = payload["k"], payload["v"]
        kb = np.ascontiguousarray(k).tobytes()
        vb = np.ascontiguousarray(v).tobytes()
        crc = zlib.crc32(vb, zlib.crc32(kb))
        self._seq += 1
        path = os.path.join(self.dir, f"kv-{self._seq}.bin")
        tmp = path + ".tmp"
        # write-then-rename: a crash mid-write leaves a .tmp, never a
        # half-file under the live name; the crc catches everything else
        with open(tmp, "wb") as f:
            f.write(kb)
            f.write(vb)
        os.replace(tmp, path)
        return {"disk": True, "path": path, "nbytes": len(kb) + len(vb),
                "k_shape": [int(s) for s in k.shape],
                "v_shape": [int(s) for s in v.shape],
                "k_dtype": str(k.dtype), "v_dtype": str(v.dtype),
                "dtype": payload.get("dtype"), "crc": crc}

    def read(self, desc: dict) -> dict:
        """mmap a spill file back into a host payload (the returned k/v
        arrays are copies — the file can drop immediately after).  Raises
        :class:`DiskReadError` on a missing, short, torn, or corrupt
        file; the caller counts the failure and re-prefills."""
        path = desc["path"]
        try:
            mm = np.memmap(path, dtype=np.uint8, mode="r")
        except (OSError, ValueError) as e:
            raise DiskReadError(f"disk spill unreadable: {e}") from e
        try:
            if int(mm.shape[0]) != int(desc["nbytes"]):
                raise DiskReadError(
                    f"disk spill torn: {int(mm.shape[0])} bytes on disk, "
                    f"descriptor says {desc['nbytes']}")
            if zlib.crc32(mm) != desc["crc"]:
                raise DiskReadError("disk spill corrupt (crc mismatch)")
            kd = _np_dtype(desc["k_dtype"])
            ks = tuple(int(s) for s in desc["k_shape"])
            kn = int(np.prod(ks)) * kd.itemsize
            k = np.frombuffer(mm[:kn], dtype=kd).reshape(ks).copy()
            v = np.frombuffer(mm[kn:], dtype=_np_dtype(desc["v_dtype"])) \
                .reshape(tuple(int(s) for s in desc["v_shape"])).copy()
        except ValueError as e:
            # descriptor/file disagreement the size+crc guards missed
            raise DiskReadError(f"disk spill unparseable: {e}") from e
        finally:
            del mm
        return {"k": k, "v": v, "dtype": desc.get("dtype")}

    def free(self, desc: dict) -> None:
        """Drop an entry's spill file (promotion or subtree drop); a
        missing file is fine — free must be idempotent."""
        try:
            os.unlink(desc["path"])
        except OSError:
            pass

    def stats(self) -> dict:
        return {
            "disk_pool_entries": len(self.entries),
            "disk_pool_bytes": self.used_bytes,
            "disk_pool_budget_bytes": self.budget_bytes,
            "disk_demoted_pages_total": self.demoted_pages_total,
            "disk_promoted_pages_total": self.promoted_pages_total,
            "disk_dropped_pages_total": self.dropped_pages_total,
            "disk_read_failures_total": self.read_failures_total,
        }
