"""``lmrs-train``: fine-tune a model preset on text/summary data.

The reference has no training at all (its model is behind OpenAI's API);
this is new serving-stack surface: fine-tune the on-pod summarizer on
(transcript chunk, summary) pairs or raw text, with the same mesh axes as
serving (dp/tp/sp) and the remat/checkpoint machinery from
training/train.py + models/loader.py.

Data format: JSONL, one object per line —
    {"text": "..."}                       plain causal-LM text
    {"prompt": "...", "summary": "..."}   loss masked to the summary tokens
"""

from __future__ import annotations

import argparse
import json
import logging
import sys
import time
from pathlib import Path

import numpy as np

logger = logging.getLogger("lmrs.train")


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        "lmrs-train", description="Fine-tune a summarization model on TPU")
    p.add_argument("--data", required=True, help="JSONL training data")
    p.add_argument("--model", default="tiny", help="model preset name")
    p.add_argument("--tokenizer", default="byte",
                   help='"byte", "approx", SentencePiece path, or HF id')
    p.add_argument("--init-checkpoint", default=None,
                   help="Orbax checkpoint to start from (default: random init)")
    p.add_argument("--output", required=True, help="Orbax checkpoint output dir")
    p.add_argument("--steps", type=int, default=100)
    p.add_argument("--batch-size", type=int, default=8)
    p.add_argument("--seq-len", type=int, default=512)
    p.add_argument("--lr", type=float, default=1e-4)
    p.add_argument("--mesh", default=None,
                   help="device mesh axes dp,tp[,sp] e.g. 2,4 or 1,4,2")
    p.add_argument("--remat", action="store_true",
                   help="rematerialize layers in backward (long sequences)")
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--log-every", type=int, default=10)
    p.add_argument("--quiet", "-q", action="store_true")
    return p


def load_examples(path: str, tokenizer) -> tuple[list[list[int]], list[list[int]]]:
    """Tokenize the JSONL file; returns (token_seqs, loss_masks)."""
    seqs, masks = [], []
    for lineno, line in enumerate(
        Path(path).read_text(encoding="utf-8").splitlines(), 1
    ):
        line = line.strip()
        if not line:
            continue
        row = json.loads(line)
        if "text" in row:
            ids = [tokenizer.bos_id] + tokenizer.encode(row["text"])
            mask = [1] * len(ids)
        elif "prompt" in row and "summary" in row:
            p_ids = [tokenizer.bos_id] + tokenizer.encode(row["prompt"])
            s_ids = tokenizer.encode(row["summary"]) + [tokenizer.eos_id]
            ids = p_ids + s_ids
            mask = [0] * len(p_ids) + [1] * len(s_ids)
        else:
            raise ValueError(
                f"{path}:{lineno}: row needs 'text' or 'prompt'+'summary' "
                f"keys, got {sorted(row)}")
        seqs.append(ids)
        masks.append(mask)
    if not seqs:
        raise ValueError(f"no examples in {path}")
    return seqs, masks


def batches(seqs, masks, batch_size: int, seq_len: int, seed: int):
    """Yield (tokens [B,S], loss_mask [B,S]) forever, shuffled per epoch;
    the tail batch fills up by cycling the epoch's permutation."""
    rng = np.random.default_rng(seed)
    n = len(seqs)
    while True:
        order = rng.permutation(n)
        for i in range(0, n, batch_size):
            idx = order[i : i + batch_size]
            if len(idx) < batch_size:  # tail: top up by cycling the epoch
                idx = np.concatenate(
                    [idx, np.resize(order, batch_size - len(idx))])
            t = np.zeros((batch_size, seq_len), np.int32)
            m = np.zeros((batch_size, seq_len), np.int32)
            for r, j in enumerate(idx):
                ids = seqs[j][:seq_len]
                t[r, : len(ids)] = ids
                m[r, : len(ids)] = masks[j][: len(ids)]
            yield t, m


def main(argv=None) -> int:
    args = build_parser().parse_args(argv)
    from lmrs_tpu.utils.logging import setup_logging
    from lmrs_tpu.utils.platform import honor_platform_env

    setup_logging(quiet=args.quiet)
    honor_platform_env()

    import jax
    import jax.numpy as jnp
    import optax

    from lmrs_tpu.config import model_preset
    from lmrs_tpu.data.tokenizer import get_tokenizer
    from lmrs_tpu.models.loader import load_checkpoint, save_checkpoint
    from lmrs_tpu.models.transformer import init_params
    from lmrs_tpu.training.train import make_train_step

    try:
        cfg = model_preset(args.model)
        tokenizer = get_tokenizer(args.tokenizer)
        seqs, masks = load_examples(args.data, tokenizer)
    except (OSError, ValueError, json.JSONDecodeError) as e:
        logger.error("could not set up training: %s", e)
        return 1
    max_id = max(max(s) for s in seqs)
    if max_id >= cfg.vocab_size:
        # silently clamping would corrupt both inputs and loss targets
        logger.error(
            "tokenizer produced id %d but model %s has vocab_size %d — "
            "pick a tokenizer matching the model's vocabulary",
            max_id, cfg.name, cfg.vocab_size)
        return 1
    logger.info("loaded %d examples from %s", len(seqs), args.data)

    mesh = None
    mesh_cfg = None
    if args.mesh:
        from lmrs_tpu.config import parse_mesh
        from lmrs_tpu.parallel.mesh import build_mesh

        try:
            mesh_cfg = parse_mesh(args.mesh)
        except ValueError as e:
            logger.error("bad --mesh: %s", e)
            return 1
        mesh = build_mesh(mesh_cfg)
        logger.info("mesh: dp=%d tp=%d sp=%d pp=%d", mesh_cfg.dp,
                    mesh_cfg.tp, mesh_cfg.sp, mesh_cfg.pp)

    if args.init_checkpoint:
        params = load_checkpoint(args.init_checkpoint, cfg, mesh=mesh)
    else:
        params = init_params(cfg, jax.random.PRNGKey(args.seed))
        if mesh is not None:
            from lmrs_tpu.parallel.sharding import shard_params

            params = shard_params(params, mesh, cfg.tie_embeddings,
                                  moe=cfg.n_experts > 0)
    optimizer = optax.adamw(args.lr)
    opt_state = optimizer.init(params)
    step_fn = make_train_step(cfg, optimizer, mesh,
                              seq_sharded=bool(mesh_cfg and mesh_cfg.sp > 1),
                              remat=args.remat, masked=True)

    it = batches(seqs, masks, args.batch_size, args.seq_len, args.seed)
    t0 = time.time()
    for step in range(1, args.steps + 1):
        tokens, mask = next(it)
        params, opt_state, loss = step_fn(params, opt_state,
                                          jnp.asarray(tokens), jnp.asarray(mask))
        if step % args.log_every == 0 or step == args.steps:
            tok_s = step * args.batch_size * args.seq_len / (time.time() - t0)
            logger.info("step %d/%d  loss %.4f  %.0f tok/s",
                        step, args.steps, float(loss), tok_s)

    save_checkpoint(args.output, params)
    logger.info("saved fine-tuned checkpoint to %s", args.output)
    return 0


if __name__ == "__main__":
    sys.exit(main())
