"""Training/fine-tuning: sharded causal-LM train step (no reference
counterpart — the reference's model is a rented API; here the model is ours
to tune)."""

from lmrs_tpu.training.train import make_train_step, causal_lm_loss

__all__ = ["causal_lm_loss", "make_train_step"]
