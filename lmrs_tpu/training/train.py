"""Sharded causal-LM training step.

The full SPMD recipe: params laid out tensor-parallel (parallel.sharding),
batch sharded data-parallel (and optionally sequence-parallel), one jitted
step — XLA inserts the tp collectives inside the model and the dp gradient
all-reduce at the boundary.  Used for fine-tuning and as the multi-chip
dry-run workload (__graft_entry__.dryrun_multichip).
"""

from __future__ import annotations

from functools import partial
from typing import Any

import jax
import jax.numpy as jnp
import optax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from lmrs_tpu.config import ModelConfig
from lmrs_tpu.models.transformer import forward
from lmrs_tpu.parallel.sharding import batch_spec, param_shardings


def causal_lm_loss(params: Any, cfg: ModelConfig, tokens: jnp.ndarray,
                   loss_mask: jnp.ndarray | None = None,
                   attn_fn=None, remat: bool = False) -> jnp.ndarray:
    """Next-token cross-entropy in f32.  tokens [B, S]; predicts tokens[:,1:]."""
    b, s = tokens.shape
    positions = jnp.broadcast_to(jnp.arange(s)[None, :], (b, s))
    logits, _, aux = forward(params, cfg, tokens, positions, attn_fn=attn_fn,
                             return_aux=True, remat=remat)  # [B,S,V] f32
    logits = logits[:, :-1]
    targets = tokens[:, 1:]
    logp = jax.nn.log_softmax(logits, axis=-1)
    nll = -jnp.take_along_axis(logp, targets[..., None], axis=-1)[..., 0]
    if loss_mask is not None:
        m = loss_mask[:, 1:].astype(jnp.float32)
        loss = (nll * m).sum() / jnp.maximum(m.sum(), 1.0)
    else:
        loss = nll.mean()
    if cfg.n_experts and cfg.router_aux_coef:
        loss = loss + cfg.router_aux_coef * aux
    return loss


def make_train_step(
    cfg: ModelConfig,
    optimizer: optax.GradientTransformation,
    mesh: Mesh | None = None,
    seq_sharded: bool = False,
    remat: bool = False,
    masked: bool = False,
):
    """Build a jitted (params, opt_state, tokens) -> (params, opt_state, loss)
    step.  With a mesh: params tensor-parallel, batch over dp; when
    seq_sharded the sequence axis shards over sp and attention runs as a
    ring (parallel.ring_attention) — K/V blocks rotate over ICI instead of
    XLA all-gathering the whole sequence onto every sp shard.  ``remat``
    rematerializes each decoder layer in backward (jax.checkpoint), cutting
    activation HBM to one [B,S,D] residual per layer for long sequences."""

    attn_fn = None
    if mesh is not None and seq_sharded:
        from lmrs_tpu.parallel.ring_attention import ring_attention_sharded

        def attn_fn(q, k, v, positions):
            return ring_attention_sharded(q, k, v, positions, mesh)

    def step(params, opt_state, tokens, loss_mask=None):
        loss, grads = jax.value_and_grad(causal_lm_loss)(
            params, cfg, tokens, loss_mask=loss_mask, attn_fn=attn_fn,
            remat=remat)
        updates, opt_state = optimizer.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        return params, opt_state, loss

    if mesh is None:
        return jax.jit(step)

    pspecs = param_shardings(mesh, cfg.tie_embeddings, moe=cfg.n_experts > 0)
    batch_sh = NamedSharding(mesh, batch_spec(seq_sharded))
    in_sh = [pspecs, None, batch_sh] + ([batch_sh] if masked else [])
    # opt_state sharding left unconstrained: XLA propagates the param layout
    # into the optimizer tree (adam mu/nu mirror the params).
    return jax.jit(
        step,
        in_shardings=tuple(in_sh),
        out_shardings=(pspecs, None, None),
        donate_argnums=(0, 1),
    )
