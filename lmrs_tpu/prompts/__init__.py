"""L4 prompt system: map / system / reduce prompt triad.

Capability parity with the reference prompt layer (main.py:259-322 + prompts/
assets + reduce prompts in result_aggregator.py:404-498), with the resolution
precedence chain preserved (README.md:130-134):

* map prompt      — explicit template > ``--prompt-file`` > built-in default;
                    placeholder ``{transcript}`` (auto-appended with a warning
                    if a file omits it, main.py:274-277).
* system prompt   — explicit > file > None (main.py:160-167).
* reduce prompt   — explicit > file > role default; placeholder ``{summaries}``
                    (+ optional ``{metadata}`` / ``{num_summaries}``).

Divergence (deliberate): reduce-prompt placeholders are REALLY substituted —
the reference's defaults carry placeholders that are never ``.format()``-ed
(SURVEY.md §2.3 quirk 6).
"""

from __future__ import annotations

import logging
from pathlib import Path

logger = logging.getLogger("lmrs.prompts")

_ASSET_DIR = Path(__file__).parent / "assets"

DEFAULT_MAP_PROMPT = """\
You are summarizing one section of a much longer transcript. The section is
annotated with [MM:SS] timestamps and a header describing where it falls in
the full recording.

Write a {summary_type} of the following transcript section. Keep every
concrete fact, decision, name, and number. When you mention a specific moment,
carry its timestamp through in [MM:SS] form. Do not add greetings,
introductions, or meta-commentary — output the summary content only.

Transcript section:
{transcript}
"""

DEFAULT_REDUCE_PROMPT = """\
You are combining {num_summaries} partial summaries of consecutive sections of
one long transcript into a single coherent summary.

Transcript metadata: {metadata}

Rules:
- Merge overlapping points; never repeat the same fact twice.
- Preserve chronological order and keep [MM:SS] / [HH:MM:SS] timestamps that
  mark important moments.
- Do not mention that the input was split into sections or summaries.
- Begin directly with the summary content. No greetings, no preamble, no
  closing remarks.

Partial summaries:
{summaries}
"""

DEFAULT_BATCH_REDUCE_PROMPT = """\
You are combining {num_summaries} partial summaries that cover ONE contiguous
portion of a longer transcript ({metadata}). Produce an intermediate summary
of just this portion: merge duplicates, keep chronological order, and retain
[MM:SS] timestamps for notable moments. Output only the summary content.

Partial summaries:
{summaries}
"""

DEFAULT_FINAL_REDUCE_PROMPT = """\
The following are intermediate summaries, each covering a consecutive portion
of one long transcript ({metadata}). Weave them into one final, coherent
summary of the entire recording: chronological, non-repetitive, preserving
[MM:SS] timestamps on key moments. Begin directly with the summary — no
greeting, no preamble.

Intermediate summaries:
{summaries}
"""

DEFAULT_SYSTEM_PROMPT = None  # reference default: no system prompt (main.py:160-167)


def load_prompt_file(path: str | Path) -> str | None:
    """Read a prompt file; None (with a log line) on failure — file errors are
    never fatal mid-pipeline (main.py:280-282,317-319)."""
    try:
        return Path(path).read_text(encoding="utf-8")
    except OSError as e:
        logger.error("could not read prompt file %s: %s", path, e)
        return None


def resolve_map_prompt(
    template: str | None = None, prompt_file: str | None = None
) -> str:
    """Map-prompt precedence chain (main.py:155-157,259-300)."""
    if template is not None:
        text = template
    elif prompt_file:
        text = load_prompt_file(prompt_file) or DEFAULT_MAP_PROMPT
    else:
        text = DEFAULT_MAP_PROMPT
    if "{transcript}" not in text:
        logger.warning("map prompt lacks {transcript} placeholder; appending it")
        text = text.rstrip() + "\n\n{transcript}"
    return text


def resolve_system_prompt(
    system_prompt: str | None = None, system_prompt_file: str | None = None
) -> str | None:
    """System-prompt precedence chain (main.py:160-167,302-322)."""
    if system_prompt is not None:
        return system_prompt
    if system_prompt_file:
        return load_prompt_file(system_prompt_file)
    return DEFAULT_SYSTEM_PROMPT


def resolve_reduce_prompt(
    template: str | None = None, prompt_file: str | None = None
) -> str | None:
    """Reduce-prompt precedence; None means role defaults (main.py:209-217)."""
    if template is not None:
        return template
    if prompt_file:
        return load_prompt_file(prompt_file)
    return None


def builtin_prompt(name: str) -> str:
    """Load a shipped prompt asset by stem name (e.g. "analytical_map")."""
    path = _ASSET_DIR / f"{name}.txt"
    return path.read_text(encoding="utf-8")


def list_builtin_prompts() -> list[str]:
    return sorted(p.stem for p in _ASSET_DIR.glob("*.txt"))


def shared_prefix_chars(template: str, *varying: str, **constant) -> int | None:
    """Length of the prompt prefix SHARED by every request built from
    ``template``: substitute the constant placeholders, then cut at the
    first occurrence of any per-request (``varying``) placeholder.  Feeds
    the engine's ``GenerationRequest.cache_prefix`` hint (the prefix cache
    caps page adoption there, so per-request bodies never bloat the radix
    tree).  None when the template has no varying placeholder (the whole
    prompt is shared)."""
    head = safe_format(template, **constant)
    cuts = [c for c in (head.find("{" + v + "}") for v in varying) if c >= 0]
    return min(cuts) if cuts else None


def safe_format(template: str, **kw) -> str:
    """Substitute only known ``{placeholder}`` names; leave every other brace
    untouched.  ``str.format`` would crash on literal braces in user prompt
    files (e.g. JSON examples), so all prompt substitution routes through
    this.

    Single-pass over the TEMPLATE only: substituted values are never
    re-scanned, so transcript/summary content containing a literal
    ``{placeholder}`` cannot trigger a second expansion (template injection).
    """
    import re as _re

    if not kw:
        return template
    pattern = _re.compile("|".join("\\{" + _re.escape(k) + "\\}" for k in kw))
    return pattern.sub(lambda m: str(kw[m.group(0)[1:-1]]), template)
