"""Deterministic, seed-driven fault-injection plane.

SURVEY.md §5.3 calls out "no fault injection" as a reference gap: the
on-pod engine replaced the reference's single HTTP boundary with a
scheduler / KV-cache / router stack whose failure modes (OutOfPages
pressure, engine step faults, dead hosts, client disconnects) were each
handled ad hoc and never exercised in combination.  This module closes
the gap with NAMED INJECTION SITES threaded through the real code paths:

=========================== =============================================
site                        effect when fired
=========================== =============================================
``kv_cache.allocate``       ``OutOfPages`` from the page allocator — the
                            back-pressure path under synthetic pressure
``scheduler.step``          exception at a scheduler loop iteration — the
                            dispatch-failure recovery path
``engine.batch``            ``RuntimeError`` from ``generate_batch`` —
                            the executor/server degrade-and-retry path
``router.connect``          connection-phase failure at a backend host —
                            unhealthy marking + failover (request path
                            only; probes have their own site)
``router.probe``            /healthz recovery-probe failure — a dead host
                            stays condemned through a probe window
``router.recv``             mid-stream fault (or stall) while reading a
                            backend SSE response
``server.client_disconnect``the server's disconnect probe reports the
                            client gone — the cancel propagation path
``prefix_cache.insert``     exception inside radix-tree adoption — the
                            caching-is-an-optimization degrade path
``handoff.export``          page-set capture at pin time fails — the
                            request errors marked, the router re-prefills
``handoff.transfer``        the prefill→decode payload read dies
                            MID-PAYLOAD — truncation rejected, marked
                            import failure, re-prefill fallback
``handoff.import``          the decode-side page scatter (or mock state
                            resume) fails — marked error, pool clean
``handoff.ack``             the import ack vanishes on the wire — pages
                            stay pinned until the orphan sweep; the
                            dedup log rejects a re-delivered ticket
=========================== =============================================

Determinism: every site keeps an occurrence counter, and probabilistic
triggers draw from a per-site ``random.Random(f"{seed}:{site}")`` stream
(string seeding is stable across processes), so one ``(FaultPlan, code
path)`` pair always fires the same faults at the same occurrences —
chaos scenarios replay exactly (tests/test_chaos.py).

Zero cost when disabled: the module-level ``fire``/``check`` entry
points test one global against ``None`` and return — no plan object, no
string formatting, no RNG draw ever happens on the hot path.  A plan is
installed only via ``install`` / ``injected`` / the ``LMRS_FAULT_PLAN``
environment variable (read once at import) / ``EngineConfig.fault_plan``
(applied by ``make_engine``), so an unset env reproduces the uninjected
behavior exactly (the tier-1 greedy A/B gate asserts this).
"""

from __future__ import annotations

import json
import logging
import random
import threading
import time
from contextlib import contextmanager
from dataclasses import dataclass

logger = logging.getLogger("lmrs.faults")


class InjectedFault(RuntimeError):
    """Raised by a fired injection site (unless the site's callers specify
    a more meaningful type, e.g. ``OutOfPages`` at ``kv_cache.allocate``)."""


@dataclass
class FaultSpec:
    """One trigger rule for one site.  A spec fires at an occurrence when
    ANY of its conditions matches: ``at`` (explicit 1-based occurrence
    indices), ``every`` (each Nth occurrence), or ``p`` (per-occurrence
    probability on the site's seeded stream).  ``max_fires`` caps total
    fires (0 = unlimited); ``stall_s`` sleeps before acting; ``action``
    "raise" (default) raises at the site, "stall" only sleeps.

    Specs are immutable descriptions: all mutable evaluation state
    (occurrence counters, fire counts, RNG streams) lives on the
    FaultInjector, so one plan object can be installed any number of
    times and every installation replays identically."""

    site: str
    p: float = 0.0
    at: tuple[int, ...] = ()
    every: int = 0
    max_fires: int = 0
    stall_s: float = 0.0
    action: str = "raise"

    def __post_init__(self) -> None:
        if self.action not in ("raise", "stall"):
            raise ValueError(f"unknown fault action {self.action!r}")
        if not (self.p or self.at or self.every):
            raise ValueError(
                f"fault spec for {self.site!r} has no trigger (p/at/every)")
        self.at = tuple(self.at)


class FaultPlan:
    """A seed plus a list of :class:`FaultSpec`.  Constructable from JSON
    (the ``LMRS_FAULT_PLAN`` wire format)::

        {"seed": 7, "faults": [
            {"site": "kv_cache.allocate", "p": 0.3, "max_fires": 4},
            {"site": "scheduler.step", "at": [3]},
            {"site": "router.recv", "every": 2, "stall_s": 0.05,
             "action": "stall"}]}

    ``from_spec`` additionally accepts ``@/path/to/plan.json``.
    """

    def __init__(self, seed: int = 0, faults: list | tuple = ()):
        self.seed = seed
        self.faults = [f if isinstance(f, FaultSpec) else FaultSpec(**f)
                       for f in faults]

    @classmethod
    def from_spec(cls, spec: str) -> "FaultPlan":
        """Parse a plan from JSON text, or from a file via ``@path``."""
        text = spec.strip()
        if text.startswith("@"):
            with open(text[1:], "r", encoding="utf-8") as fh:
                text = fh.read()
        data = json.loads(text)
        return cls(seed=int(data.get("seed", 0)),
                   faults=data.get("faults", ()))


class FaultInjector:
    """Evaluates a :class:`FaultPlan` at the named sites.  Thread-safe:
    sites fire from scheduler, HTTP handler, and router dispatch threads
    concurrently; a lock guards the counters so occurrence numbering (and
    with it determinism under a single-threaded driver) stays exact."""

    def __init__(self, plan: FaultPlan):
        self.plan = plan
        self._lock = threading.Lock()
        self._occurrences: dict[str, int] = {}
        self._rngs: dict[str, random.Random] = {}
        self._fired = [0] * len(plan.faults)  # per-spec, injector-owned
        # (site, occurrence) pairs that fired — chaos-test introspection
        self.fires: list[tuple[str, int]] = []

    def _rng(self, site: str) -> random.Random:
        # string seeding: stable across processes (unlike hash())
        if site not in self._rngs:
            self._rngs[site] = random.Random(f"{self.plan.seed}:{site}")
        return self._rngs[site]

    def _trigger(self, site: str) -> FaultSpec | None:
        """Count one occurrence of ``site`` and return the spec that fires
        on it, if any.  The probabilistic draw happens exactly once per
        occurrence per spec (even when another condition already matched)
        so adding an ``at`` to a plan cannot shift later ``p`` draws."""
        with self._lock:
            n = self._occurrences.get(site, 0) + 1
            self._occurrences[site] = n
            hit: FaultSpec | None = None
            for idx, spec in enumerate(self.plan.faults):
                if spec.site != site:
                    continue
                # the draw is consumed BEFORE the max_fires check so a
                # spent spec cannot shift later draws on its site's stream
                draw = self._rng(site).random() if spec.p else 1.0
                if spec.max_fires and self._fired[idx] >= spec.max_fires:
                    continue
                fires = (n in spec.at
                         or (spec.every and n % spec.every == 0)
                         or (spec.p and draw < spec.p))
                if fires and hit is None:
                    self._fired[idx] += 1
                    hit = spec
            if hit is not None:
                self.fires.append((site, n))
            return hit

    def fire(self, site: str, exc: type = InjectedFault) -> None:
        """Raise ``exc`` (after any configured stall) when the plan fires
        at this occurrence of ``site``; no-op otherwise."""
        spec = self._trigger(site)
        if spec is None:
            return
        logger.debug("injected fault at %s (occurrence %d, action=%s)",
                     site, self._occurrences[site], spec.action)
        if spec.stall_s:
            time.sleep(spec.stall_s)
        if spec.action == "raise":
            raise exc(f"injected fault at {site} "
                      f"(occurrence {self._occurrences[site]})")

    def check(self, site: str) -> bool:
        """Boolean form for sites that signal instead of raise (e.g. the
        server's client-disconnect probe).  Stalls still apply."""
        spec = self._trigger(site)
        if spec is None:
            return False
        if spec.stall_s:
            time.sleep(spec.stall_s)
        return spec.action == "raise"

    def occurrences(self, site: str) -> int:
        with self._lock:
            return self._occurrences.get(site, 0)


# --------------------------------------------------------- module plumbing

_active: FaultInjector | None = None
_active_spec: str | None = None  # the spec string the injector came from


def active() -> FaultInjector | None:
    """The installed injector, or None (the disabled fast path)."""
    return _active


def fire(site: str, exc: type = InjectedFault) -> None:
    """Module-level injection point — the ONE call production code makes.
    Disabled (no plan installed): a global load + None test, nothing else."""
    inj = _active
    if inj is not None:
        inj.fire(site, exc)


def check(site: str) -> bool:
    """Boolean injection point (see :meth:`FaultInjector.check`)."""
    inj = _active
    return False if inj is None else inj.check(site)


def install(plan: FaultPlan | FaultInjector) -> FaultInjector:
    """Install a plan process-globally (replacing any previous one, with
    fresh evaluation state) and return its injector."""
    global _active, _active_spec
    inj = plan if isinstance(plan, FaultInjector) else FaultInjector(plan)
    _active = inj
    _active_spec = None  # object installs are not spec-keyed
    logger.info("fault plan installed: seed=%d, %d specs",
                inj.plan.seed, len(inj.plan.faults))
    return inj


def install_spec(spec: str) -> FaultInjector | None:
    """Install from the JSON / ``@path`` wire format (``LMRS_FAULT_PLAN``,
    ``EngineConfig.fault_plan``).  Empty spec uninstalls and returns None.
    IDEMPOTENT per spec string: re-arming the same spec (every
    ``make_engine`` call re-applies the env-derived config knob) keeps the
    live injector — occurrence counters and ``max_fires`` state survive,
    so "fire once" means once per PROCESS, not once per engine built."""
    global _active_spec
    if not spec.strip():
        uninstall()
        return None
    if _active is not None and spec == _active_spec:
        return _active
    inj = install(FaultPlan.from_spec(spec))
    _active_spec = spec
    return inj


def uninstall() -> None:
    global _active, _active_spec
    _active = None
    _active_spec = None


@contextmanager
def injected(plan: FaultPlan):
    """Scoped install for tests: ``with injected(plan) as inj: ...``"""
    inj = install(plan)
    try:
        yield inj
    finally:
        uninstall()


# Environment knob: importing this module with LMRS_FAULT_PLAN set arms the
# plane for the whole process (every call site imports this module, so the
# env var alone reaches server/router/engine without config plumbing).
def _install_from_env() -> None:
    from lmrs_tpu.utils.env import env_str

    spec = env_str("LMRS_FAULT_PLAN")
    if spec:
        try:
            install_spec(spec)
        except (ValueError, OSError, json.JSONDecodeError, TypeError) as e:
            raise ValueError(f"bad LMRS_FAULT_PLAN: {e}") from e


_install_from_env()
