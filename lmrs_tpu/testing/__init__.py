"""Test-support subsystems that ship in-tree because production code hooks
into them: the deterministic fault-injection plane (``testing.faults``) is
threaded through the real engine/serving code paths and compiled to a no-op
when no plan is installed."""
