"""L8 pipeline orchestrator: the ``TranscriptSummarizer`` public API.

Successor of the reference ``TranscriptSummarizer`` (main.py:45-332): wires
preprocess → chunk → map → reduce with the same knob surface and stats
contract, driven by one typed ``PipelineConfig``.  Both a sync ``summarize``
and an ``asummarize`` coroutine are provided (the reference API is async,
main.py:82-95; here the engine is local so sync is the natural form).

New over the reference:
* resumable chunk dumps — ``--save-chunks`` output can be fed back via
  ``resume_from`` to skip already-summarized chunks (SURVEY.md §5.4 suggests
  exactly this);
* stage timing spans with optional jax.profiler traces (§5.1);
* device-seconds accounting in place of dollar cost (§5.5).
"""

from __future__ import annotations

import dataclasses
import json
import logging
import time
from pathlib import Path
from typing import Any

from lmrs_tpu.config import ChunkConfig, DataConfig, EngineConfig, PipelineConfig
from lmrs_tpu.data.chunker import Chunk, TranscriptChunker
from lmrs_tpu.data.preprocessor import (
    extract_speakers,
    get_transcript_duration,
    preprocess_transcript,
)
from lmrs_tpu.engine.api import make_engine
from lmrs_tpu.engine.executor import MapExecutor
from lmrs_tpu.prompts import (
    resolve_map_prompt,
    resolve_reduce_prompt,
    resolve_system_prompt,
)
from lmrs_tpu.reduce.aggregator import ResultAggregator
from lmrs_tpu.utils.timing import StageTimer, format_duration

logger = logging.getLogger("lmrs.pipeline")


class TranscriptSummarizer:
    """End-to-end map-reduce transcript summarizer.

    Ctor knobs mirror the reference's (main.py:51-58): backend (née provider),
    model, max_tokens_per_chunk, max_concurrent_requests,
    hierarchical_aggregation — all overlaid onto a ``PipelineConfig``.
    """

    def __init__(
        self,
        config: PipelineConfig | None = None,
        *,
        backend: str | None = None,
        model: str | None = None,
        max_tokens_per_chunk: int | None = None,
        max_concurrent_requests: int | None = None,
        hierarchical_aggregation: bool | None = None,
        profile: bool = False,
    ):
        cfg = config or PipelineConfig()
        if backend is not None:
            cfg = dataclasses.replace(cfg, engine=dataclasses.replace(cfg.engine, backend=backend))
        if model is not None:
            cfg = dataclasses.replace(cfg, engine=dataclasses.replace(cfg.engine, model=model))
        if max_concurrent_requests is not None:
            cfg = dataclasses.replace(
                cfg, engine=dataclasses.replace(cfg.engine, max_concurrent_requests=max_concurrent_requests)
            )
        if max_tokens_per_chunk is not None:
            cfg = dataclasses.replace(
                cfg, chunk=dataclasses.replace(cfg.chunk, max_tokens_per_chunk=max_tokens_per_chunk)
            )
        if hierarchical_aggregation is not None:
            cfg = dataclasses.replace(
                cfg, reduce=dataclasses.replace(cfg.reduce, hierarchical=hierarchical_aggregation)
            )
        self.config = cfg
        self.profile = profile
        # Lazily constructed on first summarize() (main.py:113-127).
        self._executor: MapExecutor | None = None
        self._chunker: TranscriptChunker | None = None
        self._aggregator: ResultAggregator | None = None

    # ----------------------------------------------------------- components

    @property
    def executor(self) -> MapExecutor:
        if self._executor is None:
            engine = make_engine(self.config.engine, self.config.model, self.config.mesh)
            self._executor = MapExecutor(engine, self.config.engine)
        return self._executor

    @property
    def chunker(self) -> TranscriptChunker:
        if self._chunker is None:
            # Token-count authority is the SERVING MODEL's tokenizer
            # (SURVEY.md §7.4 item 4): when the chunker tokenizer is left at
            # its default and the engine has a real tokenizer, use that one —
            # otherwise chunk budgets (approx ~4 chars/tok) and engine limits
            # (e.g. byte-level) disagree by ~4x and chunks get truncated.
            tokenizer = self.config.chunk.tokenizer
            if tokenizer == "approx":
                engine_tok = getattr(self.executor.engine, "tokenizer", None)
                if engine_tok is not None:
                    tokenizer = engine_tok
            self._chunker = TranscriptChunker(
                max_tokens_per_chunk=self.config.chunk.max_tokens_per_chunk,
                overlap_tokens=self.config.chunk.overlap_tokens,
                context_tokens=self.config.chunk.context_tokens,
                tokenizer=tokenizer,
            )
        return self._chunker

    @property
    def aggregator(self) -> ResultAggregator:
        if self._aggregator is None:
            self._aggregator = ResultAggregator(
                self.executor, self.config.reduce, tokenizer=self.chunker.tokenizer
            )
        return self._aggregator

    # ------------------------------------------------------------------ API

    def _prep(self, transcript_data: dict[str, Any], timer: StageTimer):
        """Shared stages 1-3: limit → preprocess → chunk.
        Returns (n_input_segments, processed_segments, chunks)."""
        segments = transcript_data.get("segments", [])
        if self.config.data.limit_segments:
            segments = segments[: self.config.data.limit_segments]
        with timer.stage("preprocess"):
            processed = preprocess_transcript(
                segments,
                merge_same_speaker=self.config.data.merge_same_speaker,
                time_interval_seconds=self.config.data.time_interval_seconds,
                max_segment_duration=self.config.data.max_segment_duration,
                preserve_timestamps=self.config.data.preserve_timestamps,
            )
        with timer.stage("chunk"):
            chunks = self.chunker.chunk_transcript(processed)
        return len(segments), processed, chunks

    def summarize(
        self,
        transcript_data: dict[str, Any],
        *,
        prompt_template: str | None = None,
        prompt_file: str | None = None,
        system_prompt: str | None = None,
        system_prompt_file: str | None = None,
        aggregator_prompt: str | None = None,
        aggregator_prompt_file: str | None = None,
        summary_type: str = "summary",
        save_chunks: str | None = None,
        resume_from: str | None = None,
    ) -> dict[str, Any]:
        """Run the full pipeline; returns the stats dict (main.py:248-257)."""
        timer = StageTimer(profile=self.profile)
        t_start = time.time()

        n_input_segments, processed, chunks = self._prep(transcript_data, timer)
        duration = get_transcript_duration(processed)
        speakers = extract_speakers(processed)

        map_prompt = resolve_map_prompt(prompt_template, prompt_file)
        sys_prompt = resolve_system_prompt(system_prompt, system_prompt_file)

        resumed = 0
        todo = chunks
        if resume_from:
            resumed_chunks, todo = _load_resume(resume_from, chunks)
            resumed = len(resumed_chunks)

        reduce_prompt = resolve_reduce_prompt(aggregator_prompt, aggregator_prompt_file)
        metadata = {
            "duration": format_duration(duration),
            "speakers": ", ".join(speakers),
            "num_chunks": len(chunks),
        }

        if self.config.reduce.streaming and todo:
            # one engine stream: reduce batches ride the map stage's batch
            # slots as their member summaries complete (reduce/streaming.py)
            from lmrs_tpu.reduce.streaming import StreamingMapReduce

            smr = StreamingMapReduce(self.executor, self.aggregator)
            # dump inside the stream at map-complete, like the barrier
            # path's between-stage dump: an interrupt during the reduce
            # tail must still leave a resumable artifact
            on_map_complete = (
                (lambda cs: _dump_chunks(save_chunks, list(cs)))
                if save_chunks else None)
            agg = smr.run(chunks, map_prompt, summary_type, sys_prompt,
                          reduce_prompt, metadata,
                          on_map_complete=on_map_complete)
            # map = start → last map summary; reduce = the tail beyond it
            timer.spans["map"] = round(agg["map_seconds"], 4)
            timer.spans["reduce"] = round(agg["reduce_tail_seconds"], 4)
            processed_chunks = sorted(chunks, key=lambda c: c.chunk_index)
        else:
            with timer.stage("map"):
                if todo:
                    self.executor.process_chunks(todo, map_prompt, summary_type,
                                                 sys_prompt)
            processed_chunks = sorted(chunks, key=lambda c: c.chunk_index)
            if save_chunks:
                _dump_chunks(save_chunks, processed_chunks)
            with timer.stage("reduce"):
                agg = self.aggregator.aggregate(processed_chunks, reduce_prompt,
                                                metadata)

        stats = {
            "summary": agg["final_summary"],
            "processing_time": time.time() - t_start,
            "num_input_segments": n_input_segments,
            "num_segments": len(processed),
            "num_chunks": len(chunks),
            "num_resumed_chunks": resumed,
            "transcript_duration": duration,
            "transcript_duration_str": format_duration(duration),
            "speakers": speakers,
            "hierarchical": agg["hierarchical"],
            "reduce_levels": agg["levels"],
            "stage_times": timer.report(),
            # cumulative over this summarizer's lifetime, like the token
            # counters below (reference reuses its executor the same way)
            "engine_metrics": self.executor.engine.engine_metrics(),
            **self.executor.stats(),
        }
        logger.info(
            "pipeline done: %d chunks, %.2fs total", len(chunks), stats["processing_time"]
        )
        return stats

    def summarize_many(
        self,
        transcripts: list[dict[str, Any]],
        *,
        prompt_template: str | None = None,
        prompt_file: str | None = None,
        system_prompt: str | None = None,
        system_prompt_file: str | None = None,
        aggregator_prompt: str | None = None,
        aggregator_prompt_file: str | None = None,
        summary_type: str = "summary",
    ) -> list[dict[str, Any]]:
        """Summarize several transcripts through ONE pooled map queue
        (BASELINE config #5: multi-transcript batching).

        Every transcript's chunks feed the engine's batch slots together, so
        one transcript's decode tail overlaps the next one's prefill instead
        of draining between transcripts; each transcript then gets its own
        reduce tree and stats dict (same shape as ``summarize``'s).
        """
        timer = StageTimer(profile=self.profile)
        t_start = time.time()
        map_prompt = resolve_map_prompt(prompt_template, prompt_file)
        sys_prompt = resolve_system_prompt(system_prompt, system_prompt_file)
        reduce_prompt = resolve_reduce_prompt(aggregator_prompt, aggregator_prompt_file)

        prepped = [self._prep(data, timer) for data in transcripts]

        with timer.stage("map"):
            self.executor.process_chunk_groups(
                [chunks for _, _, chunks in prepped], map_prompt, summary_type,
                sys_prompt)

        out = []
        for n_input, processed, chunks in prepped:
            ordered = sorted(chunks, key=lambda c: c.chunk_index)
            duration = get_transcript_duration(processed)
            speakers = extract_speakers(processed)
            metadata = {
                "duration": format_duration(duration),
                "speakers": ", ".join(speakers),
                "num_chunks": len(ordered),
            }
            with timer.stage("reduce"):
                agg = self.aggregator.aggregate(ordered, reduce_prompt, metadata)
            out.append({
                "summary": agg["final_summary"],
                "num_input_segments": n_input,
                "num_segments": len(processed),
                "num_chunks": len(ordered),
                "num_resumed_chunks": 0,
                "transcript_duration": duration,
                "transcript_duration_str": format_duration(duration),
                "speakers": speakers,
                "hierarchical": agg["hierarchical"],
                "reduce_levels": agg["levels"],
            })
        total = time.time() - t_start
        # shared accounting is copied per result: these dicts are pooled
        # across the batch, and handing every caller the same mutable object
        # would let edits to one result bleed into the others
        for stats in out:
            stats.update({
                "processing_time": total,
                "stage_times": dict(timer.report()),
                "engine_metrics": dict(self.executor.engine.engine_metrics()),
                **self.executor.stats(),
            })
        logger.info("pipeline done: %d transcripts, %d chunks, %.2fs total",
                    len(transcripts), sum(s["num_chunks"] for s in out), total)
        return out

    async def asummarize(self, transcript_data: dict[str, Any], **kw: Any) -> dict[str, Any]:
        """Async facade for reference-API parity (main.py:82 is async)."""
        return self.summarize(transcript_data, **kw)

    def shutdown(self) -> None:
        if self._executor is not None:
            self._executor.engine.shutdown()


# ---------------------------------------------------------------- artifacts


def _dump_chunks(path: str, chunks: list[Chunk]) -> None:
    """Intermediate chunk-summary dump (main.py:178-201; README.md:145-158)."""
    payload = {
        "timestamp": time.time(),
        "chunks": [
            {
                "chunk_index": c.chunk_index,
                "start_time": c.start_time,
                "end_time": c.end_time,
                "summary": c.summary,
                "tokens_used": c.tokens_used,
                "error": c.error,
            }
            for c in chunks
        ],
    }
    try:
        Path(path).write_text(json.dumps(payload, indent=2), encoding="utf-8")
        logger.info("saved %d chunk summaries to %s", len(chunks), path)
    except OSError as e:  # never fatal (main.py:200-201)
        logger.error("could not save chunks to %s: %s", path, e)


def _load_resume(path: str, chunks: list[Chunk]) -> tuple[list[Chunk], list[Chunk]]:
    """Rehydrate summaries from a prior --save-chunks dump; returns
    (resumed, still_todo).  Chunks match on (chunk_index, start, end)."""
    try:
        payload = json.loads(Path(path).read_text(encoding="utf-8"))
    except (OSError, json.JSONDecodeError) as e:
        logger.error("could not resume from %s: %s", path, e)
        return [], chunks
    saved = {
        (d["chunk_index"], round(d["start_time"], 3), round(d["end_time"], 3)): d
        for d in payload.get("chunks", [])
        if d.get("summary") and not d.get("error")
    }
    resumed, todo = [], []
    for c in chunks:
        d = saved.get((c.chunk_index, round(c.start_time, 3), round(c.end_time, 3)))
        if d:
            c.summary = d["summary"]
            c.tokens_used = d.get("tokens_used", 0)
            resumed.append(c)
        else:
            todo.append(c)
    logger.info("resumed %d/%d chunk summaries from %s", len(resumed), len(chunks), path)
    return resumed, todo
