"""L8 pipeline orchestrator: the ``TranscriptSummarizer`` public API.

Successor of the reference ``TranscriptSummarizer`` (main.py:45-332): wires
preprocess → chunk → map → reduce with the same knob surface and stats
contract, driven by one typed ``PipelineConfig``.  Both a sync ``summarize``
and an ``asummarize`` coroutine are provided (the reference API is async,
main.py:82-95; here the engine is local so sync is the natural form).

New over the reference:
* resumable chunk dumps — ``--save-chunks`` output can be fed back via
  ``resume_from`` to skip already-summarized chunks (SURVEY.md §5.4 suggests
  exactly this);
* stage timing spans with optional jax.profiler traces (§5.1);
* device-seconds accounting in place of dollar cost (§5.5).
"""

from __future__ import annotations

import dataclasses
import json
import logging
import time
from pathlib import Path
from typing import Any

from lmrs_tpu.config import ChunkConfig, DataConfig, EngineConfig, PipelineConfig
from lmrs_tpu.data.chunker import Chunk, TranscriptChunker
from lmrs_tpu.data.preprocessor import (
    extract_speakers,
    get_transcript_duration,
    preprocess_transcript,
)
from lmrs_tpu.engine.api import make_engine
from lmrs_tpu.engine.executor import MapExecutor
from lmrs_tpu.prompts import (
    resolve_map_prompt,
    resolve_reduce_prompt,
    resolve_system_prompt,
)
from lmrs_tpu.reduce.aggregator import ResultAggregator
from lmrs_tpu.utils.timing import StageTimer, format_duration

logger = logging.getLogger("lmrs.pipeline")


def prepare_segments(config: PipelineConfig,
                     transcript_data: dict[str, Any]) -> tuple[int, list]:
    """Stages 1-2 (limit → preprocess), shared by the batch pipeline and
    the durable-job path (jobs/manager.py): the job token-identity
    contract depends on both paths preparing segments IDENTICALLY, so
    there is exactly one implementation.  Returns
    ``(n_input_segments, processed_segments)``."""
    segments = transcript_data.get("segments", [])
    if config.data.limit_segments:
        segments = segments[: config.data.limit_segments]
    processed = preprocess_transcript(
        segments,
        merge_same_speaker=config.data.merge_same_speaker,
        time_interval_seconds=config.data.time_interval_seconds,
        max_segment_duration=config.data.max_segment_duration,
        preserve_timestamps=config.data.preserve_timestamps,
    )
    return len(segments), processed


def build_chunker(config: PipelineConfig, engine: Any = None,
                  max_tokens_per_chunk: int | None = None
                  ) -> TranscriptChunker:
    """The one place a chunker is built from config, shared with the
    durable-job path.  With an ``engine``, a default ("approx") chunk
    tokenizer upgrades to the serving model's tokenizer (SURVEY.md §7.4
    item 4: token-count authority is the serving model); pass
    ``engine=None`` for purely config-deterministic chunking (the job
    journal's chunk-identity keys depend on it)."""
    tokenizer = config.chunk.tokenizer
    if tokenizer == "approx" and engine is not None:
        engine_tok = getattr(engine, "tokenizer", None)
        if engine_tok is not None:
            tokenizer = engine_tok
    return TranscriptChunker(
        max_tokens_per_chunk=(max_tokens_per_chunk
                              or config.chunk.max_tokens_per_chunk),
        overlap_tokens=config.chunk.overlap_tokens,
        context_tokens=config.chunk.context_tokens,
        tokenizer=tokenizer,
    )


class TranscriptSummarizer:
    """End-to-end map-reduce transcript summarizer.

    Ctor knobs mirror the reference's (main.py:51-58): backend (née provider),
    model, max_tokens_per_chunk, max_concurrent_requests,
    hierarchical_aggregation — all overlaid onto a ``PipelineConfig``.
    """

    def __init__(
        self,
        config: PipelineConfig | None = None,
        *,
        backend: str | None = None,
        model: str | None = None,
        max_tokens_per_chunk: int | None = None,
        max_concurrent_requests: int | None = None,
        hierarchical_aggregation: bool | None = None,
        profile: bool = False,
    ):
        cfg = config or PipelineConfig()
        if backend is not None:
            cfg = dataclasses.replace(cfg, engine=dataclasses.replace(cfg.engine, backend=backend))
        if model is not None:
            cfg = dataclasses.replace(cfg, engine=dataclasses.replace(cfg.engine, model=model))
        if max_concurrent_requests is not None:
            cfg = dataclasses.replace(
                cfg, engine=dataclasses.replace(cfg.engine, max_concurrent_requests=max_concurrent_requests)
            )
        if max_tokens_per_chunk is not None:
            cfg = dataclasses.replace(
                cfg, chunk=dataclasses.replace(cfg.chunk, max_tokens_per_chunk=max_tokens_per_chunk)
            )
        if hierarchical_aggregation is not None:
            cfg = dataclasses.replace(
                cfg, reduce=dataclasses.replace(cfg.reduce, hierarchical=hierarchical_aggregation)
            )
        self.config = cfg
        self.profile = profile
        # Lazily constructed on first summarize() (main.py:113-127).
        self._executor: MapExecutor | None = None
        self._chunker: TranscriptChunker | None = None
        self._aggregator: ResultAggregator | None = None

    # ----------------------------------------------------------- components

    @property
    def executor(self) -> MapExecutor:
        if self._executor is None:
            engine = make_engine(self.config.engine, self.config.model, self.config.mesh)
            self._executor = MapExecutor(engine, self.config.engine)
        return self._executor

    @property
    def chunker(self) -> TranscriptChunker:
        if self._chunker is None:
            self._chunker = build_chunker(self.config, self.executor.engine)
        return self._chunker

    @property
    def aggregator(self) -> ResultAggregator:
        if self._aggregator is None:
            self._aggregator = ResultAggregator(
                self.executor, self.config.reduce, tokenizer=self.chunker.tokenizer
            )
        return self._aggregator

    # ------------------------------------------------------------------ API

    def _map_fingerprint(self, map_prompt: str, sys_prompt: str | None,
                         summary_type: str) -> str:
        """Hash of the (prompt, model, chunking) surface that determines
        what a chunk summary MEANS — stamped into ``--save-chunks`` dumps
        and validated on ``resume_from`` (jobs/journal.py applies the same
        idea to job journals): rehydrating summaries produced under a
        different map prompt or model would silently mix stale content
        into a fresh run."""
        from lmrs_tpu.jobs.journal import config_fingerprint

        e, c = self.config.engine, self.config.chunk
        return config_fingerprint(
            map_prompt=map_prompt,
            system_prompt=sys_prompt or "",
            summary_type=summary_type,
            backend=e.backend, model=e.model, temperature=e.temperature,
            max_tokens=e.max_tokens, seed=e.seed,
            max_tokens_per_chunk=c.max_tokens_per_chunk,
            overlap_tokens=c.overlap_tokens,
            context_tokens=c.context_tokens,
            tokenizer=str(c.tokenizer))

    def _prep(self, transcript_data: dict[str, Any], timer: StageTimer):
        """Shared stages 1-3: limit → preprocess → chunk
        (``prepare_segments`` — one implementation with the job path).
        Returns (n_input_segments, processed_segments, chunks)."""
        with timer.stage("preprocess"):
            n_input, processed = prepare_segments(self.config,
                                                  transcript_data)
        with timer.stage("chunk"):
            chunks = self.chunker.chunk_transcript(processed)
        return n_input, processed, chunks

    def summarize(
        self,
        transcript_data: dict[str, Any],
        *,
        prompt_template: str | None = None,
        prompt_file: str | None = None,
        system_prompt: str | None = None,
        system_prompt_file: str | None = None,
        aggregator_prompt: str | None = None,
        aggregator_prompt_file: str | None = None,
        summary_type: str = "summary",
        save_chunks: str | None = None,
        resume_from: str | None = None,
    ) -> dict[str, Any]:
        """Run the full pipeline; returns the stats dict (main.py:248-257)."""
        timer = StageTimer(profile=self.profile)
        t_start = time.time()

        n_input_segments, processed, chunks = self._prep(transcript_data, timer)
        duration = get_transcript_duration(processed)
        speakers = extract_speakers(processed)

        map_prompt = resolve_map_prompt(prompt_template, prompt_file)
        sys_prompt = resolve_system_prompt(system_prompt, system_prompt_file)
        fingerprint = self._map_fingerprint(map_prompt, sys_prompt, summary_type)

        resumed = 0
        todo = chunks
        if resume_from:
            resumed_chunks, todo = _load_resume(resume_from, chunks,
                                                fingerprint=fingerprint)
            resumed = len(resumed_chunks)

        reduce_prompt = resolve_reduce_prompt(aggregator_prompt, aggregator_prompt_file)
        metadata = {
            "duration": format_duration(duration),
            "speakers": ", ".join(speakers),
            "num_chunks": len(chunks),
        }

        if self.config.reduce.streaming and todo:
            # one engine stream: reduce batches ride the map stage's batch
            # slots as their member summaries complete (reduce/streaming.py)
            from lmrs_tpu.reduce.streaming import StreamingMapReduce

            smr = StreamingMapReduce(self.executor, self.aggregator)
            # dump inside the stream at map-complete, like the barrier
            # path's between-stage dump: an interrupt during the reduce
            # tail must still leave a resumable artifact
            on_map_complete = (
                (lambda cs: _dump_chunks(save_chunks, list(cs),
                                         fingerprint=fingerprint))
                if save_chunks else None)
            agg = smr.run(chunks, map_prompt, summary_type, sys_prompt,
                          reduce_prompt, metadata,
                          on_map_complete=on_map_complete)
            # map = start → last map summary; reduce = the tail beyond it
            timer.spans["map"] = round(agg["map_seconds"], 4)
            timer.spans["reduce"] = round(agg["reduce_tail_seconds"], 4)
            processed_chunks = sorted(chunks, key=lambda c: c.chunk_index)
        else:
            with timer.stage("map"):
                if todo:
                    self.executor.process_chunks(todo, map_prompt, summary_type,
                                                 sys_prompt)
            processed_chunks = sorted(chunks, key=lambda c: c.chunk_index)
            if save_chunks:
                _dump_chunks(save_chunks, processed_chunks,
                             fingerprint=fingerprint)
            with timer.stage("reduce"):
                agg = self.aggregator.aggregate(processed_chunks, reduce_prompt,
                                                metadata)

        stats = {
            "summary": agg["final_summary"],
            "processing_time": time.time() - t_start,
            "num_input_segments": n_input_segments,
            "num_segments": len(processed),
            "num_chunks": len(chunks),
            "num_resumed_chunks": resumed,
            "transcript_duration": duration,
            "transcript_duration_str": format_duration(duration),
            "speakers": speakers,
            "hierarchical": agg["hierarchical"],
            "reduce_levels": agg["levels"],
            "stage_times": timer.report(),
            # cumulative over this summarizer's lifetime, like the token
            # counters below (reference reuses its executor the same way)
            "engine_metrics": self.executor.engine.engine_metrics(),
            **self.executor.stats(),
        }
        logger.info(
            "pipeline done: %d chunks, %.2fs total", len(chunks), stats["processing_time"]
        )
        return stats

    def summarize_many(
        self,
        transcripts: list[dict[str, Any]],
        *,
        prompt_template: str | None = None,
        prompt_file: str | None = None,
        system_prompt: str | None = None,
        system_prompt_file: str | None = None,
        aggregator_prompt: str | None = None,
        aggregator_prompt_file: str | None = None,
        summary_type: str = "summary",
        resume_from: list[str | None] | None = None,
    ) -> list[dict[str, Any]]:
        """Summarize several transcripts through ONE pooled map queue
        (BASELINE config #5: multi-transcript batching).

        Every transcript's chunks feed the engine's batch slots together, so
        one transcript's decode tail overlaps the next one's prefill instead
        of draining between transcripts; each transcript then gets its own
        reduce tree and stats dict (same shape as ``summarize``'s).

        ``resume_from`` aligns with ``transcripts``: entry i (None = no
        resume) names a prior ``--save-chunks`` dump for transcript i,
        fingerprint-validated like the single-transcript path; only
        un-resumed chunks enter the pooled map queue, and each result's
        ``num_resumed_chunks`` reports its transcript's REAL count.
        """
        timer = StageTimer(profile=self.profile)
        t_start = time.time()
        map_prompt = resolve_map_prompt(prompt_template, prompt_file)
        sys_prompt = resolve_system_prompt(system_prompt, system_prompt_file)
        reduce_prompt = resolve_reduce_prompt(aggregator_prompt, aggregator_prompt_file)

        prepped = [self._prep(data, timer) for data in transcripts]

        resumed_counts = [0] * len(prepped)
        if resume_from:
            if len(resume_from) != len(transcripts):
                raise ValueError(
                    f"resume_from has {len(resume_from)} entries for "
                    f"{len(transcripts)} transcripts (use None for "
                    "transcripts without a dump)")
            fingerprint = self._map_fingerprint(map_prompt, sys_prompt,
                                                summary_type)
            for i, (path, (_n, _p, chunks)) in enumerate(
                    zip(resume_from, prepped)):
                if path:
                    resumed_chunks, _todo = _load_resume(
                        path, chunks, fingerprint=fingerprint)
                    resumed_counts[i] = len(resumed_chunks)

        with timer.stage("map"):
            self.executor.process_chunk_groups(
                # only un-resumed chunks enter the pooled queue (rehydrated
                # summaries must not be recomputed — or overwritten)
                [[c for c in chunks if c.summary is None]
                 for _, _, chunks in prepped],
                map_prompt, summary_type, sys_prompt)

        out = []
        for i, (n_input, processed, chunks) in enumerate(prepped):
            ordered = sorted(chunks, key=lambda c: c.chunk_index)
            duration = get_transcript_duration(processed)
            speakers = extract_speakers(processed)
            metadata = {
                "duration": format_duration(duration),
                "speakers": ", ".join(speakers),
                "num_chunks": len(ordered),
            }
            with timer.stage("reduce"):
                agg = self.aggregator.aggregate(ordered, reduce_prompt, metadata)
            out.append({
                "summary": agg["final_summary"],
                "num_input_segments": n_input,
                "num_segments": len(processed),
                "num_chunks": len(ordered),
                "num_resumed_chunks": resumed_counts[i],
                "transcript_duration": duration,
                "transcript_duration_str": format_duration(duration),
                "speakers": speakers,
                "hierarchical": agg["hierarchical"],
                "reduce_levels": agg["levels"],
            })
        total = time.time() - t_start
        # shared accounting is copied per result: these dicts are pooled
        # across the batch, and handing every caller the same mutable object
        # would let edits to one result bleed into the others
        for stats in out:
            stats.update({
                "processing_time": total,
                "stage_times": dict(timer.report()),
                "engine_metrics": dict(self.executor.engine.engine_metrics()),
                **self.executor.stats(),
            })
        logger.info("pipeline done: %d transcripts, %d chunks, %.2fs total",
                    len(transcripts), sum(s["num_chunks"] for s in out), total)
        return out

    async def asummarize(self, transcript_data: dict[str, Any], **kw: Any) -> dict[str, Any]:
        """Async facade for reference-API parity (main.py:82 is async)."""
        return self.summarize(transcript_data, **kw)

    def shutdown(self) -> None:
        if self._executor is not None:
            self._executor.engine.shutdown()


# ---------------------------------------------------------------- artifacts


def _dump_chunks(path: str, chunks: list[Chunk],
                 fingerprint: str | None = None) -> None:
    """Intermediate chunk-summary dump (main.py:178-201; README.md:145-158).
    ``fingerprint`` (the map-surface hash, ``_map_fingerprint``) is stamped
    into the payload so a later ``resume_from`` can refuse summaries
    produced under a different prompt/model/chunking surface."""
    payload = {
        "timestamp": time.time(),
        "fingerprint": fingerprint,
        "chunks": [
            {
                "chunk_index": c.chunk_index,
                "start_time": c.start_time,
                "end_time": c.end_time,
                "summary": c.summary,
                "tokens_used": c.tokens_used,
                "error": c.error,
            }
            for c in chunks
        ],
    }
    try:
        Path(path).write_text(json.dumps(payload, indent=2), encoding="utf-8")
        logger.info("saved %d chunk summaries to %s", len(chunks), path)
    except OSError as e:  # never fatal (main.py:200-201)
        logger.error("could not save chunks to %s: %s", path, e)


def _load_resume(path: str, chunks: list[Chunk],
                 fingerprint: str | None = None) -> tuple[list[Chunk], list[Chunk]]:
    """Rehydrate summaries from a prior --save-chunks dump; returns
    (resumed, still_todo).  Chunks match on (chunk_index, start, end).

    When both the dump and the caller carry a config/prompt fingerprint
    and they disagree, NOTHING is rehydrated (warn + drop): the dump was
    produced under a different map prompt / model / chunking surface, and
    mixing its summaries into this run would silently corrupt the final
    summary.  Dumps predating the fingerprint field still load (their
    chunk-identity match is the only guard, as before)."""
    try:
        payload = json.loads(Path(path).read_text(encoding="utf-8"))
    except (OSError, json.JSONDecodeError) as e:
        logger.error("could not resume from %s: %s", path, e)
        return [], chunks
    saved_fp = payload.get("fingerprint")
    if fingerprint and saved_fp and saved_fp != fingerprint:
        logger.warning(
            "resume dump %s was produced under config/prompt fingerprint %s "
            "!= this run's %s; dropping its summaries (a different map "
            "prompt, model, or chunking surface would mix stale content "
            "into this run)", path, saved_fp, fingerprint)
        return [], chunks
    saved = {
        (d["chunk_index"], round(d["start_time"], 3), round(d["end_time"], 3)): d
        for d in payload.get("chunks", [])
        if d.get("summary") and not d.get("error")
    }
    resumed, todo = [], []
    for c in chunks:
        d = saved.get((c.chunk_index, round(c.start_time, 3), round(c.end_time, 3)))
        if d:
            c.summary = d["summary"]
            c.tokens_used = d.get("tokens_used", 0)
            resumed.append(c)
        else:
            todo.append(c)
    logger.info("resumed %d/%d chunk summaries from %s", len(resumed), len(chunks), path)
    return resumed, todo
