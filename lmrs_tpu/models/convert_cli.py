"""`lmrs-convert`: HuggingFace checkpoint → native Orbax, one command.

The missing entry point between "a user downloaded Llama-3/Gemma/Mixtral
safetensors" (the models behind the reference's API, llm_executor.py:
250-326) and this framework's serving/training stack: the converters in
``models/loader.py`` were library-only.

    lmrs-convert --src /path/to/hf-llama3-8b --model llama3-8b \
                 --output ckpt/llama3-8b
    lmrs-serve --backend jax --model llama3-8b --checkpoint ckpt/llama3-8b \
               --tokenizer /path/to/hf-llama3-8b

Family is inferred from the preset (gemma presets → the Gemma converter,
which handles tied embeddings / (1+w) norms / GeGLU; everything else takes
the Llama/Mixtral path), overridable with ``--family``.
"""

from __future__ import annotations

import argparse
import logging
import sys

logger = logging.getLogger("lmrs.convert")


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="lmrs-convert",
        description="Convert a local HF safetensors checkpoint to the "
                    "native Orbax layout")
    p.add_argument("--src", required=True,
                   help="directory with HF *.safetensors shards")
    p.add_argument("--model", required=True,
                   help="model preset the checkpoint matches "
                        "(e.g. llama3-8b, gemma-2b, mixtral-8x7b)")
    p.add_argument("--output", required=True, help="Orbax checkpoint dir")
    p.add_argument("--family", choices=["llama", "gemma"], default=None,
                   help="converter family (default: inferred from preset)")
    p.add_argument("--quiet", "-q", action="store_true")
    return p


def main(argv: list[str] | None = None) -> int:
    from lmrs_tpu.utils.logging import setup_logging

    args = build_parser().parse_args(argv)
    setup_logging(quiet=args.quiet)
    from lmrs_tpu.utils.platform import honor_platform_env

    honor_platform_env()

    from lmrs_tpu.config import model_preset
    from lmrs_tpu.models.loader import (
        convert_hf_gemma, convert_hf_llama, save_checkpoint,
    )
    from lmrs_tpu.models.transformer import param_count

    try:
        cfg = model_preset(args.model)
    except (KeyError, ValueError) as e:
        logger.error("unknown model preset %r: %s", args.model, e)
        return 1
    family = args.family or ("gemma" if "gemma" in cfg.name.lower()
                             or cfg.activation == "gelu" else "llama")
    convert = convert_hf_gemma if family == "gemma" else convert_hf_llama
    try:
        params = convert(args.src, cfg)
    except (FileNotFoundError, KeyError, ValueError) as e:
        logger.error("conversion failed: %s", e)
        return 1
    save_checkpoint(args.output, params)
    logger.info(
        "converted %s (%s family, %.1fM params) -> %s\n"
        "serve with:  lmrs-serve --backend jax --model %s --checkpoint %s",
        args.src, family, param_count(params) / 1e6, args.output,
        args.model, args.output)
    return 0


if __name__ == "__main__":
    sys.exit(main())
