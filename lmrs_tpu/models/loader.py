"""Model weight checkpointing and conversion.

The reference has no model state at all — its weights live behind OpenAI's
API (SURVEY.md §5.4 "add model-weight checkpoint loading (Orbax) as a new
subsystem").  This module provides:

* Orbax save/restore of the native param pytree (sharding-aware: restore
  places shards directly onto a mesh, so a 70B checkpoint never materializes
  unsharded on one host);
* conversion from HuggingFace Llama/Gemma checkpoints (local safetensors
  files only — this environment has no egress) into the stacked-layer layout.
"""

from __future__ import annotations

import logging
from pathlib import Path
from typing import Any

import jax
import numpy as np

from lmrs_tpu.config import ModelConfig

logger = logging.getLogger("lmrs.loader")


# ------------------------------------------------------------------- orbax


def save_checkpoint(path: str, params: Any) -> None:
    """Write the param pytree with Orbax (atomic, async-flushed)."""
    import orbax.checkpoint as ocp

    ckpt = ocp.StandardCheckpointer()
    ckpt.save(Path(path).absolute(), params, force=True)
    ckpt.wait_until_finished()
    logger.info("saved checkpoint to %s", path)


def load_checkpoint(path: str, model_cfg: ModelConfig, mesh=None) -> Any:
    """Restore a param pytree; with a mesh, restore directly sharded."""
    import orbax.checkpoint as ocp

    from lmrs_tpu.models.transformer import init_params

    target = jax.eval_shape(
        lambda: init_params(model_cfg, jax.random.PRNGKey(0))
    )
    if mesh is not None:
        from lmrs_tpu.parallel.sharding import param_shardings

        shardings = param_shardings(mesh, model_cfg.tie_embeddings,
                                    moe=model_cfg.n_experts > 0)
        target = jax.tree.map(
            lambda s, sh: jax.ShapeDtypeStruct(s.shape, s.dtype, sharding=sh),
            target, shardings,
        )
    ckpt = ocp.StandardCheckpointer()
    params = ckpt.restore(Path(path).absolute(), target)
    logger.info("restored checkpoint from %s", path)
    return params


# ------------------------------------------------- HF safetensors conversion


def convert_hf_llama(src_dir: str, cfg: ModelConfig, *, norm_offset: float = 1.0) -> Any:
    """Convert a local HF Llama-style checkpoint into the stacked layout.

    Expects ``model*.safetensors`` files in ``src_dir``.  HF per-layer names
    map to the stacked-axis pytree:

        model.layers.{i}.self_attn.{q,k,v,o}_proj.weight -> attn.w{q,k,v,o}[i]
        model.layers.{i}.mlp.{gate,up,down}_proj.weight  -> mlp.w_{...}[i]
        model.layers.{i}.(input|post_attention)_layernorm.weight -> ln_*[i]
        model.embed_tokens.weight / lm_head.weight / model.norm.weight

    MoE configs (cfg.n_experts > 0, e.g. mixtral-8x7b) read Mixtral's layout
    instead of the dense mlp keys:

        model.layers.{i}.block_sparse_moe.gate.weight          -> moe.router[i]
        model.layers.{i}.block_sparse_moe.experts.{j}.w1.weight -> moe.w_gate[i,j]
        model.layers.{i}.block_sparse_moe.experts.{j}.w3.weight -> moe.w_up[i,j]
        model.layers.{i}.block_sparse_moe.experts.{j}.w2.weight -> moe.w_down[i,j]

    HF stores projections as [out, in]; we store [in, out] (+ head split).
    ``norm_offset``: our RMSNorm multiplies by ``1 + scale``; HF Llama
    multiplies by ``w`` (offset 1.0 -> scale = w - 1), HF Gemma already by
    ``1 + w`` (offset 0.0 -> scale = w; see ``convert_hf_gemma``).
    """
    import json as _json

    try:
        from safetensors import safe_open
    except ImportError as e:  # pragma: no cover - gated dependency
        raise RuntimeError(
            "safetensors not available; convert checkpoints offline"
        ) from e

    src = Path(src_dir)
    files = sorted(src.glob("*.safetensors"))
    if not files:
        raise FileNotFoundError(f"no .safetensors under {src_dir}")

    tensors: dict[str, np.ndarray] = {}
    for f in files:
        with safe_open(str(f), framework="np") as fh:
            for name in fh.keys():
                tensors[name] = fh.get_tensor(name)

    hd = cfg.hd
    L = cfg.n_layers
    off = np.float32(norm_offset)
    dt = np.dtype(np.float32) if cfg.dtype == "float32" else np.dtype("bfloat16")

    def get(name):
        return tensors[name]

    def stack(fmt, transform):
        return np.stack([transform(get(fmt.format(i=i))) for i in range(L)]).astype(dt)

    if cfg.n_experts:
        E = cfg.n_experts

        def stack_experts(fmt):
            return np.stack([
                np.stack([get(fmt.format(i=i, j=j)).T for j in range(E)])
                for i in range(L)
            ]).astype(dt)  # [L, E, in, out]

        ffn = {
            "moe": {
                "router": stack("model.layers.{i}.block_sparse_moe.gate.weight",
                                lambda w: w.T),  # [D, E]
                "w_gate": stack_experts(
                    "model.layers.{i}.block_sparse_moe.experts.{j}.w1.weight"),
                "w_up": stack_experts(
                    "model.layers.{i}.block_sparse_moe.experts.{j}.w3.weight"),
                "w_down": stack_experts(
                    "model.layers.{i}.block_sparse_moe.experts.{j}.w2.weight"),
            }
        }
    else:
        ffn = {
            "mlp": {
                "w_gate": stack("model.layers.{i}.mlp.gate_proj.weight", lambda w: w.T),
                "w_up": stack("model.layers.{i}.mlp.up_proj.weight", lambda w: w.T),
                "w_down": stack("model.layers.{i}.mlp.down_proj.weight", lambda w: w.T),
            }
        }
    params = {
        "embed": {"weight": get("model.embed_tokens.weight").astype(dt)},
        "layers": {
            "ln_attn": {"scale": stack(
                "model.layers.{i}.input_layernorm.weight", lambda w: w - off)},
            "ln_mlp": {"scale": stack(
                "model.layers.{i}.post_attention_layernorm.weight", lambda w: w - off)},
            "attn": {
                "wq": stack("model.layers.{i}.self_attn.q_proj.weight",
                            lambda w: w.T.reshape(cfg.dim, cfg.n_heads, hd)),
                "wk": stack("model.layers.{i}.self_attn.k_proj.weight",
                            lambda w: w.T.reshape(cfg.dim, cfg.n_kv_heads, hd)),
                "wv": stack("model.layers.{i}.self_attn.v_proj.weight",
                            lambda w: w.T.reshape(cfg.dim, cfg.n_kv_heads, hd)),
                "wo": stack("model.layers.{i}.self_attn.o_proj.weight",
                            lambda w: w.T.reshape(cfg.n_heads, hd, cfg.dim)),
            },
            **ffn,
        },
        "final_norm": {"scale": (get("model.norm.weight") - off).astype(dt)},
    }
    if not cfg.tie_embeddings:
        head = tensors.get("lm_head.weight", tensors["model.embed_tokens.weight"])
        params["lm_head"] = {"weight": head.T.astype(dt)}
    logger.info("converted HF checkpoint %s (%d tensors)", src_dir, len(tensors))
    return jax.tree.map(lambda x: jax.numpy.asarray(x), params)


def convert_hf_gemma(src_dir: str, cfg: ModelConfig) -> Any:
    """Convert a local HF Gemma checkpoint (same tensor names as Llama, but
    HF GemmaRMSNorm already multiplies by ``1 + w`` — our parameterization —
    so norm weights pass through unshifted; embeddings are always tied, and
    ``cfg`` should carry Gemma's explicit head_dim / gelu activation /
    embed_scale (see the gemma presets in config.py)."""
    if not cfg.tie_embeddings:
        raise ValueError("Gemma checkpoints tie lm_head to the embedding")
    return convert_hf_llama(src_dir, cfg, norm_offset=0.0)
