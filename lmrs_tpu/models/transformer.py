"""Decoder-only transformer (Llama-3 / Gemma family) as a functional pytree.

Design choices (TPU-first, not a torch translation):

* **Pure functions over pytrees** — params are nested dicts of arrays; no
  module classes.  Plays directly with jit/shard_map/optax.
* **Stacked layers + ``lax.scan``** — all layer weights carry a leading
  ``n_layers`` axis and the layer loop is a scan, so compile time and HLO
  size are O(1) in depth (32-layer 8B compiles as fast as the 4-layer tiny).
* **Single forward for prefill AND decode** — the same traced function
  handles [B, S] prefill and [B, 1] decode against a KV cache; masking is
  driven by absolute positions + valid-length arrays (static shapes only, no
  data-dependent Python control flow).
* **GQA + RoPE + RMSNorm + SwiGLU**, optional Gemma quirks (embedding scale,
  logit softcap, tied embeddings).

The reference has no model code at all — the LLM lives behind OpenAI's API
(SURVEY.md L0, llm_executor.py:292).  This module is the heart of what the
TPU build internalizes.
"""

from __future__ import annotations

import math
from typing import Any

import jax
import jax.numpy as jnp

from lmrs_tpu.config import ModelConfig
from lmrs_tpu.ops.attention import attention
from lmrs_tpu.ops.norms import rms_norm
from lmrs_tpu.ops.quant import qeinsum
from lmrs_tpu.ops.rope import apply_rope, rope_table

Params = dict[str, Any]


def _dtype(cfg: ModelConfig):
    return jnp.dtype(cfg.dtype)


def init_params(cfg: ModelConfig, key: jax.Array) -> Params:
    """Random-init params (truncated-normal fan-in scaling), stacked layers."""
    dt = _dtype(cfg)
    hd = cfg.hd
    k_embed, k_layers, k_head = jax.random.split(key, 3)

    def tn(key, shape, fan_in):
        return (jax.random.truncated_normal(key, -2, 2, shape, jnp.float32)
                / math.sqrt(fan_in)).astype(dt)

    L = cfg.n_layers
    lk = jax.random.split(k_layers, 8)
    if cfg.n_experts:
        E = cfg.n_experts
        ffn = {
            "moe": {
                "router": tn(lk[7], (L, cfg.dim, E), cfg.dim),
                "w_gate": tn(lk[4], (L, E, cfg.dim, cfg.hidden_dim), cfg.dim),
                "w_up": tn(lk[5], (L, E, cfg.dim, cfg.hidden_dim), cfg.dim),
                "w_down": tn(lk[6], (L, E, cfg.hidden_dim, cfg.dim), cfg.hidden_dim),
            }
        }
    else:
        ffn = {
            "mlp": {
                "w_gate": tn(lk[4], (L, cfg.dim, cfg.hidden_dim), cfg.dim),
                "w_up": tn(lk[5], (L, cfg.dim, cfg.hidden_dim), cfg.dim),
                "w_down": tn(lk[6], (L, cfg.hidden_dim, cfg.dim), cfg.hidden_dim),
            }
        }
    params: Params = {
        "embed": {"weight": tn(k_embed, (cfg.vocab_size, cfg.dim), cfg.dim)},
        "layers": {
            "ln_attn": {"scale": jnp.zeros((L, cfg.dim), dt)},
            "ln_mlp": {"scale": jnp.zeros((L, cfg.dim), dt)},
            "attn": {
                "wq": tn(lk[0], (L, cfg.dim, cfg.n_heads, hd), cfg.dim),
                "wk": tn(lk[1], (L, cfg.dim, cfg.n_kv_heads, hd), cfg.dim),
                "wv": tn(lk[2], (L, cfg.dim, cfg.n_kv_heads, hd), cfg.dim),
                "wo": tn(lk[3], (L, cfg.n_heads, hd, cfg.dim), cfg.n_heads * hd),
            },
            **ffn,
        },
        "final_norm": {"scale": jnp.zeros((cfg.dim,), dt)},
    }
    if not cfg.tie_embeddings:
        params["lm_head"] = {"weight": tn(k_head, (cfg.dim, cfg.vocab_size), cfg.dim)}
    return params


def param_count(params: Params) -> int:
    return sum(x.size for x in jax.tree.leaves(params))


def init_kv_cache(cfg: ModelConfig, batch: int, max_len: int) -> dict[str, jnp.ndarray]:
    """Dense per-slot KV cache [L, B, S, K, hd] (paged cache: engine/kv_cache)."""
    hd = cfg.hd
    shape = (cfg.n_layers, batch, max_len, cfg.n_kv_heads, hd)
    dt = _dtype(cfg)
    return {"k": jnp.zeros(shape, dt), "v": jnp.zeros(shape, dt)}


def gate_act(cfg: ModelConfig, gate: jnp.ndarray) -> jnp.ndarray:
    """Gated-FFN activation in f32: SiLU (Llama SwiGLU) or tanh-approximate
    GELU (Gemma GeGLU), selected by ``cfg.activation``."""
    gf = gate.astype(jnp.float32)
    if cfg.activation == "gelu":
        return jax.nn.gelu(gf, approximate=True)
    if cfg.activation == "silu":
        return jax.nn.silu(gf)
    raise ValueError(f"unknown activation {cfg.activation!r}; silu|gelu")


def ffn_block(lp: Params, cfg: ModelConfig, h: jnp.ndarray) -> tuple[jnp.ndarray, jnp.ndarray]:
    """Post-norm FFN body: dense gated FFN or MoE.  h [B,S,D] (already normed)
    -> (out [B,S,D], aux f32 scalar — the MoE load-balance loss, 0 for dense)."""
    if cfg.n_experts:
        from lmrs_tpu.ops.moe import moe_mlp

        return moe_mlp(lp["moe"], cfg, h)
    dt = h.dtype
    gate = qeinsum("bsd,df->bsf", h, lp["mlp"]["w_gate"], dt)
    up = qeinsum("bsd,df->bsf", h, lp["mlp"]["w_up"], dt)
    ff = gate_act(cfg, gate).astype(dt) * up
    return qeinsum("bsf,fd->bsd", ff, lp["mlp"]["w_down"], dt), jnp.float32(0.0)


def qkv_proj(lp: Params, cfg: ModelConfig, h: jnp.ndarray):
    """Project a normed [B,S,D] into (q [B,S,H,hd], k, v [B,S,K,hd])."""
    dt = h.dtype
    q = qeinsum("bsd,dhk->bshk", h, lp["attn"]["wq"], dt)
    k = qeinsum("bsd,dhk->bshk", h, lp["attn"]["wk"], dt)
    v = qeinsum("bsd,dhk->bshk", h, lp["attn"]["wv"], dt)
    return q, k, v


def out_proj(lp: Params, cfg: ModelConfig, attn_out: jnp.ndarray) -> jnp.ndarray:
    """[B,S,H,hd] attention output back to [B,S,D]."""
    return qeinsum("bshk,hkd->bsd", attn_out, lp["attn"]["wo"],
                   attn_out.dtype)


def decoder_layer(
    lp: Params,               # one layer's params (no leading L axis)
    cfg: ModelConfig,
    x: jnp.ndarray,           # [B, S, D]
    positions: jnp.ndarray,   # [B, S]
    sin: jnp.ndarray,
    cos: jnp.ndarray,
    attn_fn=None,
    kv_length: jnp.ndarray | None = None,  # [B] valid-length mask (padding)
) -> tuple[jnp.ndarray, jnp.ndarray]:
    """One cache-less decoder block (attention + dense/MoE FFN, pre-norm).

    Returns (x, aux) where aux is the MoE load-balance loss for this layer
    (0 for dense).  The shared body for training/prefill paths that don't
    carry a KV cache: plain scan in ``forward``, ring attention
    (``attn_fn``), and the pipeline stages in parallel/pipeline.py.
    """
    h = rms_norm(x, lp["ln_attn"]["scale"], cfg.norm_eps)
    q, k, v = qkv_proj(lp, cfg, h)
    q = apply_rope(q, positions, sin, cos)
    k = apply_rope(k, positions, sin, cos)
    if attn_fn is not None:
        attn_out = attn_fn(q, k, v, positions)
    else:
        attn_out = attention(q, k, v, positions, kv_length, logit_softcap=None)
    x = x + out_proj(lp, cfg, attn_out)
    h = rms_norm(x, lp["ln_mlp"]["scale"], cfg.norm_eps)
    ff, aux = ffn_block(lp, cfg, h)
    return x + ff, aux


def embed_tokens(params: Params, cfg: ModelConfig, tokens: jnp.ndarray) -> jnp.ndarray:
    """Token embedding lookup (+ Gemma's sqrt(dim) scale)."""
    x = params["embed"]["weight"][tokens]
    if cfg.embed_scale:
        x = (x.astype(jnp.float32) * math.sqrt(cfg.dim)).astype(_dtype(cfg))
    return x


def lm_head(params: Params, cfg: ModelConfig, x: jnp.ndarray) -> jnp.ndarray:
    """Final norm + output projection to f32 logits (+ optional softcap)."""
    x = rms_norm(x, params["final_norm"]["scale"], cfg.norm_eps)
    if cfg.tie_embeddings:
        logits = jnp.einsum("bsd,vd->bsv", x, params["embed"]["weight"])
    else:
        logits = qeinsum("bsd,dv->bsv", x, params["lm_head"]["weight"], x.dtype)
    logits = logits.astype(jnp.float32)
    if cfg.logit_softcap:
        logits = cfg.logit_softcap * jnp.tanh(logits / cfg.logit_softcap)
    return logits


def forward(
    params: Params,
    cfg: ModelConfig,
    tokens: jnp.ndarray,      # [B, S] int32
    positions: jnp.ndarray,   # [B, S] absolute positions
    cache: dict[str, jnp.ndarray] | None = None,  # dense KV cache or None
    kv_length: jnp.ndarray | None = None,         # [B] valid KV len AFTER this call's writes
    attn_fn=None,  # optional (q, k, v, positions) -> out override (e.g. ring
                   # attention for sequence-parallel training; cache-less only)
    return_aux: bool = False,  # also return the layer-mean MoE aux loss
    remat: bool = False,  # rematerialize each layer in the backward pass
) -> tuple[jnp.ndarray, dict[str, jnp.ndarray] | None] | tuple[jnp.ndarray, Any, jnp.ndarray]:
    """Forward pass; returns (logits [B,S,V] f32, updated cache), plus the
    layer-mean MoE load-balance loss as a third element when ``return_aux``.

    With a cache: K/V for `tokens` are scattered into it at `positions` and
    attention reads the cache (prefill S>1 or decode S=1 both work).
    Without a cache: plain causal self-attention over the sequence — or
    ``attn_fn`` when given (context-parallel ring attention over ``sp``).
    """
    if cache is not None and attn_fn is not None:
        raise ValueError("attn_fn (ring attention) is cache-less only; "
                         "decode against a KV cache uses dense/paged attention")
    if kv_length is not None and attn_fn is not None:
        raise ValueError("attn_fn does not apply kv_length masking; "
                         "pad-free batches only on the ring-attention path")
    dt = _dtype(cfg)
    b, s = tokens.shape
    hd = cfg.hd
    x = embed_tokens(params, cfg, tokens)  # [B,S,D]

    max_pos = cache["k"].shape[2] if cache is not None else s
    sin, cos = rope_table(max_pos, hd, cfg.rope_theta)
    batch_idx = jnp.arange(b)[:, None]  # [B,1] for cache scatter

    if cache is not None:
        def layer_fn(carry, xs):
            # cache rides the carry, not xs/ys — as xs every iteration
            # would memcpy the full [L,B,S,K,hd] buffers into the stacked
            # scan output (see forward_paged's layer_fn note)
            x, ck_all, cv_all = carry
            lp, li = xs  # layer params, layer index
            ck = jax.lax.dynamic_index_in_dim(ck_all, li, 0, keepdims=False)
            cv = jax.lax.dynamic_index_in_dim(cv_all, li, 0, keepdims=False)
            h = rms_norm(x, lp["ln_attn"]["scale"], cfg.norm_eps)
            q, k, v = qkv_proj(lp, cfg, h)
            q = apply_rope(q, positions, sin, cos)
            k = apply_rope(k, positions, sin, cos)
            ck = ck.at[batch_idx, positions].set(k)
            cv = cv.at[batch_idx, positions].set(v)
            attn_out = attention(q, ck, cv, positions, kv_length,
                                 logit_softcap=None)
            x = x + out_proj(lp, cfg, attn_out)

            h = rms_norm(x, lp["ln_mlp"]["scale"], cfg.norm_eps)
            ff, _ = ffn_block(lp, cfg, h)
            x = x + ff
            ck_all = jax.lax.dynamic_update_index_in_dim(ck_all, ck, li, 0)
            cv_all = jax.lax.dynamic_update_index_in_dim(cv_all, cv, li, 0)
            return (x, ck_all, cv_all), None

        # lax.scan over stacked layers: wq etc. are [L, ...]; cache [L, B, ...]
        (x, new_k, new_v), _ = jax.lax.scan(
            layer_fn, (x, cache["k"], cache["v"]),
            (params["layers"], jnp.arange(cfg.n_layers)))
        new_cache = {"k": new_k, "v": new_v}
        aux = jnp.float32(0.0)
    else:
        def one_layer(lp, x):
            return decoder_layer(lp, cfg, x, positions, sin, cos,
                                 attn_fn, kv_length)

        if remat:
            # Trade FLOPs for HBM: save only each layer's input activation,
            # recompute the rest in backward — activation memory drops from
            # O(L * per-layer intermediates) to O(L * [B,S,D]).
            one_layer = jax.checkpoint(one_layer)

        def layer_fn_nocache(carry, lp):
            x, aux = carry
            x, layer_aux = one_layer(lp, x)
            return (x, aux + layer_aux), None

        (x, aux), _ = jax.lax.scan(
            layer_fn_nocache, (x, jnp.float32(0.0)), params["layers"])
        aux = aux / cfg.n_layers
        new_cache = None

    logits = lm_head(params, cfg, x)
    if return_aux:
        return logits, new_cache, aux
    return logits, new_cache


def _use_flash_prefill(seq_len: int, hd: int, interpret: bool = False) -> bool:
    """Route fresh prefill through the Pallas flash kernel: TPU backend (or
    interpret mode, for CPU-mesh tests), a sequence long enough that O(S²)
    logits materialization starts to matter, and a lane-aligned head dim
    (validated on hardware for multiples of 64; smaller head dims fail
    Mosaic lowering)."""
    from lmrs_tpu.utils.platform import on_tpu

    return seq_len >= 256 and hd % 64 == 0 and (interpret or on_tpu())


def forward_paged(
    params: Params,
    cfg: ModelConfig,
    tokens: jnp.ndarray,       # [B, S] int32
    positions: jnp.ndarray,    # [B, S] absolute positions
    k_pages: jnp.ndarray,      # [L*P, K, ps, hd] (page-major, layer-flattened)
    v_pages: jnp.ndarray,      # [L*P, K, ps, hd]
    page_tables: jnp.ndarray,  # [B, W] LOGICAL page ids (< P)
    kv_lens: jnp.ndarray,      # [B] valid tokens AFTER this call's writes
    rope_max: int,
    use_ragged_kernel: bool = False,
    window_prefill: bool = False,
    use_flash: bool = True,  # allow the flash prefill kernel (when eligible)
    mesh=None,  # tensor-parallel mesh: Pallas calls run via shard_map over tp
    interpret: bool = False,  # Pallas interpret mode (CPU-mesh tests)
    token_pages: jnp.ndarray | None = None,   # [B, S] per-token LOGICAL page
    segment_ids: jnp.ndarray | None = None,   # [B, S] packed-prompt segments
    packed_last_idx: jnp.ndarray | None = None,  # [N] last-token row indices
    use_ring: bool = False,  # sp-mesh fresh prefill: ring attention over sp
    last_pos: jnp.ndarray | None = None,  # [B] per-row last-token index
    multi_decode: bool = False,  # speculative verify: S tokens, ragged walk
    kv_scales: tuple | None = None,  # (kscale, vscale) [L, Bs, K, hd] f32:
                                     # int8 KV pools (ops/quant.py KV section)
    scale_rows: jnp.ndarray | None = None,  # [B] dispatch row -> slot id
                                            # (None: rows ARE slots); >= Bs
                                            # rows are pads (updates dropped)
    decode_row_group: int = 1,  # rows per ragged-decode program (multi-row
                                # page walk, ops/paged_attention.py); 1 =
                                # per-row grid (the LMRS_MULTIROW=0 path)
    spans: tuple | None = None,  # (q_starts [B], q_lens [B], row_flat [Tp]):
                                 # ragged span mode (LMRS_RPA) — tokens is
                                 # ONE flat [1, Tp] row holding every row's
                                 # query span; kv_lens is then the context
                                 # BEFORE this dispatch (span base), and
                                 # attention runs through the unified span
                                 # kernel (ops ragged_spans_*).  Use
                                 # packed_last_idx to gather sampled rows.
    span_anc: jnp.ndarray | None = None,  # [Tp] int32 ancestor bitmasks for
                                 # tree-speculative spans (ISSUE 19): tokens
                                 # with a nonzero mask attend context + their
                                 # ancestor offsets only; 0 keeps the linear
                                 # causal rule.  Routes to the XLA span twin
                                 # (the Pallas ancestor variant is chip debt,
                                 # docs/PERF.md).
) -> tuple:
    """Forward pass against a paged KV cache (engine/kv_cache.PagedKVCache).

    Returns (logits [B,S,V] f32, k_pages, v_pages) — plus a fourth element
    ``(kscale, vscale)`` (the updated scale buffers) when ``kv_scales`` is
    given.  K/V of `tokens` are scattered into the pages named by
    ``page_tables`` at (page_tables[b, pos//ps], pos%ps); with
    ``kv_scales`` the pools are int8 and the scattered rows quantize with
    the dispatch rows' per-(slot, kv head, channel) scales — owned by the
    prompt's FIRST prefill dispatch (fresh, or the start==0 window chunk),
    reused and clamped to by everything after.

    Prefill (S>1, fresh sequence starting at position 0) attends the current
    tokens directly (flash path eligible); decode (S==1) attends the paged
    pool — via the ragged Pallas kernel on TPU or the gather fallback.

    ``window_prefill`` is the chunked-prefill path (SARATHI-style,
    PAPERS.md): S>1 queries at positions ``>= 0`` that must also see KV
    written by EARLIER chunks of the same prompt — attention runs against
    the gathered page window (pages are in logical order, so window index
    == absolute position), masked causally by absolute position + kv_lens.

    PACKED prefill (``segment_ids`` given): several fresh prompts
    concatenated into one [1, S] row — each token's page comes from
    ``token_pages`` (host-built per segment; ``page_tables`` is then
    ignored for writes), ``positions`` restart at 0 per segment (RoPE),
    attention is same-segment causal, and ``kv_lens`` holds the TOTAL
    packed length.  With ``packed_last_idx``, the LM head runs only on the
    gathered last-token rows (logits [B, N, V]) — the padding rows' vocab
    matmul is the FLOP waste packing exists to eliminate.

    ``last_pos`` is the per-ROW version of the same gather for the fresh
    and chunked-continuation paths (one prompt per row): the LM head runs
    only on row b's token ``last_pos[b]`` and logits come back [B, 1, V].
    At a real-model vocab (Llama-3: 128,256) the full [B, S, V] head is
    ~2 TFLOPs + a ~1 GB f32 buffer per [1, 4096] prefill, all discarded
    but the last row (VERDICT r2 weak #2).

    RING prefill (``use_ring`` + ``mesh``): serving-side context
    parallelism (SURVEY.md §5.7 tier b) — fresh-prefill attention runs as
    ring attention with the sequence sharded over the ``sp`` axis, so a
    chunk longer than one chip's attention budget prefills with O(S/sp)
    attention memory per device; the SAME program scatters K/V into the
    page pool (cache-aware: what the training-only ring path could not
    do), so decode then proceeds against the pages as usual.  Pad keys are
    masked positionally (kv position pushed past every real query).
    """
    from lmrs_tpu.ops.paged_attention import (
        paged_decode_fused_sharded,
        paged_decode_multi_xla,
        paged_decode_pallas_fused,
        paged_decode_pallas_multi,
        paged_decode_xla,
        ragged_spans_pallas,
        ragged_spans_xla,
    )
    from lmrs_tpu.ops.quant import (kv_dequant, kv_quant, kv_quant_tokens,
                                    kv_scale_from)

    if kv_scales is not None:
        # int8 KV: packed prefill composes (per-SEGMENT scales, r4 — each
        # segment owns its slot's scale row, so the two headline
        # optimizations no longer subtract from each other, VERDICT r3
        # item 3); ring stays gated off at config time (sp-sharded writes
        # vs per-slot scales)
        assert not use_ring, (
            "int8 KV pools are incompatible with ring (sp) prefill "
            "(scheduler raises at construction)")

    dt = _dtype(cfg)
    b, s = tokens.shape
    hd = cfg.hd
    ps = k_pages.shape[2]
    n_pool = k_pages.shape[0] // cfg.n_layers  # logical pages per layer
    # (page-major pool [L*P, K, ps, hd]: pages are axis 0.  The round-3
    # relayout left this reading axis 1 — the kv-head count — which
    # collapsed every layer's global page ids onto the same few pages and
    # corrupted all paged generation; VERDICT r3.)
    x = params["embed"]["weight"][tokens]
    if cfg.embed_scale:
        x = (x.astype(jnp.float32) * math.sqrt(cfg.dim)).astype(dt)

    sin, cos = rope_table(rope_max, hd, cfg.rope_theta)
    is_decode = s == 1

    if spans is not None:
        # span mode: [1, Tp] flat tokens vs [B_rows, W] tables — the span
        # kernels do their own per-token page addressing
        page_idx = None
    elif token_pages is not None:
        page_idx = token_pages  # packed path: host-built per-token pages
    else:
        page_idx = jnp.take_along_axis(
            page_tables, jnp.clip(positions // ps, 0, page_tables.shape[1] - 1),
            axis=1,
        )  # [B, S] logical page per token
    offsets = positions % ps
    batch_r = jnp.arange(b)[:, None]

    def layer_fn(carry, xs):
        # The page pools ride the scan CARRY (not xs/ys) and the layer axis
        # is flattened into the page axis, so each layer scatters straight
        # into the full pool at its GLOBAL page ids.  Either a per-layer
        # stacked scan output or a slice/update round trip moves the whole
        # pool (or a whole layer slice) every decode step — measured linear
        # in pool size; this layout moves only the tokens written.
        # With int8 pools the per-(slot, kv head, channel) scales ride the
        # carry too (tiny): layer li reads/updates slice [li].
        if kv_scales is not None:
            x, kp_all, vp_all, ksc, vsc = carry
        else:
            x, kp_all, vp_all = carry  # pools: [L*P, K, ps, hd]
            ksc = vsc = None
        lp, li = xs  # layer params, layer index
        g_page_idx = (None if page_idx is None
                      else li * n_pool + page_idx)  # [B, S] global page ids
        g_tables = li * n_pool + page_tables     # [B, W]
        h = rms_norm(x, lp["ln_attn"]["scale"], cfg.norm_eps)
        q, k, v = qkv_proj(lp, cfg, h)
        q = apply_rope(q, positions, sin, cos)
        k = apply_rope(k, positions, sin, cos)

        if spans is not None:
            # ragged span mode (LMRS_RPA): every phase is a list of
            # (row, query-span) pairs — write + attention run in the ONE
            # span kernel (or its XLA twin).  kv_lens here is the context
            # BEFORE the dispatch: span token j of row r sits at absolute
            # position kv_lens[r] + j.
            span_starts, span_lens, row_flat = spans
            ss = None
            if kv_scales is not None:
                # per-row frozen scales ride the span descriptor (the
                # int8-KV x mixed unlock): a span whose base is 0 is its
                # prompt's FIRST tokens and owns its slot's scale row —
                # segment-max over its own tokens, the packed path's
                # stats exactly; every later span reuses (and clamps to)
                # the frozen scales, decode spans included.
                nb = span_starts.shape[0]
                segx = jnp.clip(row_flat, 0, nb)  # out-of-span -> dropped

                def span_scales(kv):
                    a = jnp.abs(kv[0].astype(jnp.float32))  # [Tp, K, hd]
                    m = jax.ops.segment_max(a, segx, num_segments=nb + 1)
                    return jnp.maximum(m[:nb] / 127.0, 1e-8)

                s_k, s_v = span_scales(k), span_scales(v)
                rows_i = (jnp.arange(nb, dtype=jnp.int32)
                          if scale_rows is None else scale_rows)
                ksc_l, vsc_l = ksc[li][rows_i], vsc[li][rows_i]
                own = ((kv_lens == 0) & (span_lens > 0))[:, None, None]
                s_k = jnp.where(own, s_k, ksc_l)
                s_v = jnp.where(own, s_v, vsc_l)
                ksc = ksc.at[li, rows_i].set(s_k)
                vsc = vsc.at[li, rows_i].set(s_v)
                ss = (s_k, s_v)
            if use_ragged_kernel and span_anc is None:
                attn, kp_all, vp_all = ragged_spans_pallas(
                    q[0], k[0], v[0], kp_all, vp_all, g_tables, kv_lens,
                    span_starts, span_lens, interpret=interpret,
                    max_pos=rope_max,
                    kscale=ss[0] if ss is not None else None,
                    vscale=ss[1] if ss is not None else None)
            else:
                attn, kp_all, vp_all = ragged_spans_xla(
                    q[0], k[0], v[0], kp_all, vp_all, g_tables, kv_lens,
                    span_starts, span_lens, row_flat,
                    max_pos=rope_max, kv_scales=ss, anc_masks=span_anc)
            return _finish_layer(lp, x, attn[None], kp_all, vp_all,
                                 ksc, vsc)

        row_scales = None  # (k_scale, v_scale) [B, K, hd] for THIS dispatch
        tok_scales = None  # packed: per-token (k, v) scales [B, S, K, hd]
        if kv_scales is not None:
            is_fresh = (not is_decode and not window_prefill
                        and not multi_decode)
            if segment_ids is not None:
                # PACKED fresh prefill: one [1, S] row holds many prompts —
                # each SEGMENT owns its slot's scale row, computed from its
                # own tokens only (identical stats to the same prompt
                # prefilled unpacked: max-abs over the same token set).
                # Pads (segment id -1) route to an out-of-range segment so
                # segment_max drops them; empty segments hit the 1e-8 floor
                # and their scale_rows point past the buffer (scatter drop).
                n_seg = scale_rows.shape[0]
                seg = segment_ids[0]
                segx = jnp.where(seg >= 0, seg, n_seg)

                def seg_scales(kv):
                    a = jnp.abs(kv[0].astype(jnp.float32))  # [S, K, hd]
                    m = jax.ops.segment_max(a, segx, num_segments=n_seg + 1)
                    return jnp.maximum(m[:n_seg] / 127.0, 1e-8)

                s_k, s_v = seg_scales(k), seg_scales(v)
                ksc = ksc.at[li, scale_rows].set(s_k)
                vsc = vsc.at[li, scale_rows].set(s_v)
                # per-token gather for the scatter's quantization (pad
                # tokens clamp to some segment's scales; they land on the
                # null page regardless)
                gi = jnp.clip(segx, 0, n_seg - 1)
                tok_scales = (s_k[gi][None], s_v[gi][None])
            elif is_fresh or window_prefill:
                # a prefill OWNS its slots' scales when it is the prompt's
                # FIRST tokens: one-dispatch fresh prefill always, a window
                # (chunked) dispatch only for rows whose chunk starts at
                # position 0 — later chunks reuse (and clamp to) the first
                # chunk's scales, since written pages can't be requantized
                chunk_len = (kv_lens if is_fresh
                             else kv_lens - positions[:, 0])
                valid = jnp.arange(s)[None, :] < chunk_len[:, None]
                s_k = kv_scale_from(k, valid)
                s_v = kv_scale_from(v, valid)
                rows_i = (jnp.arange(b, dtype=jnp.int32)
                          if scale_rows is None else scale_rows)
                ksc_l, vsc_l = ksc[li][rows_i], vsc[li][rows_i]
                if window_prefill:
                    own = (positions[:, 0] == 0)[:, None, None]
                    s_k = jnp.where(own, s_k, ksc_l)
                    s_v = jnp.where(own, s_v, vsc_l)
                # pad rows carry scale_rows >= Bs: scatter drops them
                ksc = ksc.at[li, rows_i].set(s_k)
                vsc = vsc.at[li, rows_i].set(s_v)
                row_scales = (s_k, s_v)
            else:
                ksc_l, vsc_l = ksc[li], vsc[li]
                if scale_rows is not None:
                    ksc_l, vsc_l = ksc_l[scale_rows], vsc_l[scale_rows]
                row_scales = (ksc_l, vsc_l)

        if multi_decode:
            # speculative verify: the S tokens sit at consecutive positions
            # kv_lens - S + j; K/V write and the per-token-causal attention
            # run in ONE ragged page walk (kernel) or one window gather
            # (XLA fallback) — never the full window_prefill gather per
            # layer that made round-2 speculation a 12x loss.  Write slots
            # derive from kv_lens, which callers pass UNCLAMPED (base must
            # be the true position); tokens overhanging rope_max are
            # neither written nor attended (max_pos cap).
            if use_ragged_kernel:
                ks_m = row_scales[0] if kv_scales is not None else None
                vs_m = row_scales[1] if kv_scales is not None else None
                attn, kp_all, vp_all = paged_decode_pallas_multi(
                    q, k, v, kp_all, vp_all, g_tables, kv_lens,
                    interpret=interpret, max_pos=rope_max,
                    kscale=ks_m, vscale=vs_m,
                    row_group=decode_row_group)
            else:
                attn, kp_all, vp_all = paged_decode_multi_xla(
                    q, k, v, kp_all, vp_all, g_tables, kv_lens,
                    max_pos=rope_max, kv_scales=row_scales)
            return _finish_layer(lp, x, attn, kp_all, vp_all, ksc, vsc)

        if is_decode and use_ragged_kernel:
            # write-fused ragged kernel: the current token's K/V lands in
            # its page by in-place DMA inside the kernel (pools are i/o
            # aliased), replacing the XLA scatter below — which was measured
            # copying the whole pool every decode step.  Under a tp mesh the
            # kernel runs per kv-head shard via shard_map (XLA cannot
            # auto-partition a pallas_call).  Int8 pools pass the dispatch
            # rows' scales; the kernel folds dequant into q/acc per head.
            ks_r = row_scales[0] if kv_scales is not None else None
            vs_r = row_scales[1] if kv_scales is not None else None
            if mesh is not None:
                attn, kp_all, vp_all = paged_decode_fused_sharded(
                    q[:, 0], k[:, 0], v[:, 0], kp_all, vp_all, g_tables,
                    kv_lens, mesh, interpret=interpret,
                    kscale=ks_r, vscale=vs_r, row_group=decode_row_group)
            else:
                attn, kp_all, vp_all = paged_decode_pallas_fused(
                    q[:, 0], k[:, 0], v[:, 0], kp_all, vp_all, g_tables,
                    kv_lens, interpret=interpret,
                    kscale=ks_r, vscale=vs_r, row_group=decode_row_group)
            attn_out = attn[:, None]  # [B, 1, H, hd]
            return _finish_layer(lp, x, attn_out, kp_all, vp_all, ksc, vsc)

        # scatter current K/V into the page-major pool: [L*P, K, ps, hd]
        # at [g_page_idx[b,s], :, offsets[b,s]] (advanced indices around
        # the head slice put the advanced dims first: updates are
        # [B, S, K, hd] — the K/V's own layout).  Int8 pools store the
        # quantized rows; attention below reads the ORIGINAL k/v wherever
        # the current tokens are the whole context (fresh prefill), so only
        # pool readers pay quantization error
        k_store, v_store = k, v
        if kv_scales is not None:
            if tok_scales is not None:  # packed: per-token segment scales
                k_store = kv_quant_tokens(k, tok_scales[0])
                v_store = kv_quant_tokens(v, tok_scales[1])
            else:
                k_store = kv_quant(k, row_scales[0])
                v_store = kv_quant(v, row_scales[1])
        kp_all = kp_all.at[g_page_idx, :, offsets].set(k_store)
        vp_all = vp_all.at[g_page_idx, :, offsets].set(v_store)

        if is_decode:
            attn = paged_decode_xla(q[:, 0], kp_all, vp_all, g_tables, kv_lens,
                                    kv_scales=row_scales)
            attn_out = attn[:, None]  # [B, 1, H, hd]
        elif segment_ids is not None:
            # packed fresh prefill: same-segment causal attention over the
            # concatenated prompts (current tokens ARE the whole context)
            if use_flash and _use_flash_prefill(s, hd, interpret):
                from lmrs_tpu.ops.flash_attention import (
                    flash_attention, flash_attention_sharded)

                if mesh is not None:
                    attn_out = flash_attention_sharded(
                        q, k, v, kv_lens, mesh, interpret=interpret,
                        segment_ids=segment_ids)
                else:
                    attn_out = flash_attention(q, k, v, kv_lens,
                                               interpret=interpret,
                                               segment_ids=segment_ids)
            else:
                from lmrs_tpu.ops.attention import packed_attention

                attn_out = packed_attention(q, k, v, segment_ids, kv_lens)
        elif window_prefill:
            # continuation prefill: attend the page window (self K/V included
            # — this chunk was scattered into its pages above)
            w = page_tables.shape[1]
            k_win = kp_all[g_tables].transpose(0, 1, 3, 2, 4).reshape(
                b, w * ps, cfg.n_kv_heads, hd)
            v_win = vp_all[g_tables].transpose(0, 1, 3, 2, 4).reshape(
                b, w * ps, cfg.n_kv_heads, hd)
            if kv_scales is not None:
                k_win = kv_dequant(k_win, row_scales[0], q.dtype)
                v_win = kv_dequant(v_win, row_scales[1], q.dtype)
            attn_out = attention(q, k_win, v_win, positions, kv_lens)
        elif use_ring and mesh is not None:
            # serving CP: ring attention over the sp-sharded sequence; pad
            # keys get a position past every real query (ring attention has
            # no kv_length mask, so masking is purely positional)
            from lmrs_tpu.parallel.ring_attention import ring_attention_sharded

            idx = jnp.arange(s)[None, :]
            kvp = jnp.where(idx < kv_lens[:, None], positions, jnp.int32(1 << 30))
            attn_out = ring_attention_sharded(q, k, v, positions, mesh,
                                              kv_pos=kvp)
        else:
            # fresh prefill: current tokens ARE the whole context.  Row i's
            # position is i (scheduler fresh-prefill contract), which is
            # exactly the flash kernel's implicit layout — use it on TPU for
            # long chunks; XLA reference elsewhere.
            if use_flash and _use_flash_prefill(s, hd, interpret):
                from lmrs_tpu.ops.flash_attention import (
                    flash_attention, flash_attention_sharded)

                if mesh is not None:
                    attn_out = flash_attention_sharded(
                        q, k, v, kv_lens, mesh, interpret=interpret)
                else:
                    attn_out = flash_attention(q, k, v, kv_lens,
                                               interpret=interpret)
            else:
                attn_out = attention(q, k, v, positions, kv_lens)
        return _finish_layer(lp, x, attn_out, kp_all, vp_all, ksc, vsc)

    def _finish_layer(lp, x, attn_out, kp_all, vp_all, ksc, vsc):
        x = x + out_proj(lp, cfg, attn_out)
        h = rms_norm(x, lp["ln_mlp"]["scale"], cfg.norm_eps)
        ff, _ = ffn_block(lp, cfg, h)
        if kv_scales is not None:
            return (x + ff, kp_all, vp_all, ksc, vsc), None
        return (x + ff, kp_all, vp_all), None

    init = ((x, k_pages, v_pages) if kv_scales is None
            else (x, k_pages, v_pages, kv_scales[0], kv_scales[1]))
    carry_out, _ = jax.lax.scan(
        layer_fn, init,
        (params["layers"], jnp.arange(cfg.n_layers)),
    )
    if kv_scales is None:
        x, new_k, new_v = carry_out
        new_scales = None
    else:
        x, new_k, new_v, new_ksc, new_vsc = carry_out
        new_scales = (new_ksc, new_vsc)
    if packed_last_idx is not None:
        # LM head only where tokens are sampled: [B, S, D] -> [B, N, D]
        x = x[:, packed_last_idx]
    elif last_pos is not None:
        # per-row gather: [B, S, D] -> [B, 1, D]
        x = jnp.take_along_axis(
            x, jnp.clip(last_pos, 0, s - 1)[:, None, None], axis=1)
    x = rms_norm(x, params["final_norm"]["scale"], cfg.norm_eps)
    if cfg.tie_embeddings:
        logits = jnp.einsum("bsd,vd->bsv", x, params["embed"]["weight"])
    else:
        logits = qeinsum("bsd,dv->bsv", x, params["lm_head"]["weight"], x.dtype)
    logits = logits.astype(jnp.float32)
    if cfg.logit_softcap:
        logits = cfg.logit_softcap * jnp.tanh(logits / cfg.logit_softcap)
    if kv_scales is not None:
        return logits, new_k, new_v, new_scales
    return logits, new_k, new_v
