"""L3 model zoo: decoder-only transformers as functional pytrees."""

from lmrs_tpu.models.transformer import (
    forward,
    init_kv_cache,
    init_params,
    param_count,
)

__all__ = ["forward", "init_kv_cache", "init_params", "param_count"]
