"""Synthetic transcript→summary pairs for the offline quality gate.

This environment has no network, so no real LLM API output and no real
pretrained checkpoint can anchor summary quality (the reference's quality
bar lives behind OpenAI's API, llm_executor.py:250-326).  What CAN be
demonstrated offline, end-to-end through the real stack, is that the
training loop + engine learn an actual summarization mapping: transcripts
are generated with known topic structure, the ground-truth summary is a
deterministic function of that structure, a model is fine-tuned on
(prompt, summary) pairs with the production loss masking
(training/cli.load_examples format), and held-out generations are ROUGE-
scored against the ground truth — with a trivial extractive baseline as
the bar to beat (tests/test_quality.py).

The task is summarization in miniature: find the topic mentions buried in
filler dialogue and emit them in a fixed report format.  Byte-level
models must learn format, topic vocabulary, and content selection; a
model that merely copies the transcript opening (the extractive baseline)
scores poorly.
"""

from __future__ import annotations

import numpy as np

TOPICS = [
    "budget", "hiring", "roadmap", "metrics", "launch", "pricing",
    "staffing", "marketing", "support", "security", "testing", "design",
]

_OPENERS = [
    "so next up we have {t}",
    "let's talk about {t} now",
    "moving on to {t} today",
    "the team walked through {t}",
    "quick update on {t} from me",
    "we spent a while on {t}",
]

_FILLER = [
    "okay everyone settle in please.",
    "sorry my audio cut out there.",
    "let me share my screen quickly.",
    "we are running a bit behind.",
    "any questions before we move on?",
    "i will post the notes after.",
]


def make_example(rng: np.random.Generator) -> dict:
    """One (prompt, summary) pair: a short timestamped transcript whose
    ground-truth summary lists the topics in order of appearance."""
    n_topics = int(rng.integers(2, 4))
    topics = [TOPICS[i] for i in rng.choice(len(TOPICS), n_topics, replace=False)]
    lines = []
    minute = 0
    for t in topics:
        if rng.random() < 0.7:
            lines.append(f"[00:{minute:02d}] {rng.choice(_FILLER)}")
            minute += int(rng.integers(1, 3))
        opener = str(rng.choice(_OPENERS)).format(t=t)
        lines.append(f"[00:{minute:02d}] {opener}.")
        minute += int(rng.integers(1, 3))
    transcript = "\n".join(lines)
    return {
        "prompt": f"List the topics.\n{transcript}\nTopics:",
        "summary": " " + ", ".join(topics) + ".",
        "topics": topics,
    }


def make_dataset(n: int, seed: int = 0) -> list[dict]:
    rng = np.random.default_rng(seed)
    return [make_example(rng) for _ in range(n)]


def extractive_baseline(prompt: str) -> str:
    """The trivial baseline the trained model must beat: parrot the first
    transcript line (classic lead-1 extraction)."""
    for line in prompt.splitlines():
        if line.startswith("["):
            return line
    return prompt[:60]
