"""Quality-parity evaluation: ROUGE metrics + the parity harness.

SURVEY.md §7.2 step 7: "Parity harness — ROUGE-L vs stored API-baseline
outputs; chunks/sec + wall-clock benchmark runner; this is the BASELINE.json
metric."  The reference has no evaluation machinery at all — its quality bar
was "whatever GPT-4o returns" — so this subsystem is new surface required by
the north-star target (BASELINE.json .metric: "ROUGE-L parity with the
GPT-4o API baseline").
"""

__all__ = [
    "rouge_l",
    "rouge_n",
    "rouge_scores",
    "ParityReport",
    "evaluate_parity",
    "run_parity",
]

_ROUGE = {"rouge_l", "rouge_n", "rouge_scores"}


def __getattr__(name: str):
    # Lazy so `python -m lmrs_tpu.eval.parity` doesn't double-import parity.
    if name in _ROUGE:
        from lmrs_tpu.eval import rouge

        return getattr(rouge, name)
    if name in __all__:
        from lmrs_tpu.eval import parity

        return getattr(parity, name)
    raise AttributeError(name)
