"""ROUGE-1 / ROUGE-2 / ROUGE-L, self-contained (no egress for rouge-score).

Standard definitions (Lin 2004): n-gram recall/precision/F1 against one or
more references; ROUGE-L from the longest common subsequence.  Tokenization
matches the common implementation: lowercase, alphanumeric runs only.

The reference repo has no metrics at all; this is the quality gate demanded
by BASELINE.json (.metric = "ROUGE-L parity with the GPT-4o API baseline").
"""

from __future__ import annotations

import re
from collections import Counter
from typing import Iterable

_TOKEN_RE = re.compile(r"[a-z0-9]+")


def tokenize(text: str) -> list[str]:
    return _TOKEN_RE.findall(text.lower())


def _f_measure(matches: int, cand_total: int, ref_total: int) -> dict[str, float]:
    p = matches / cand_total if cand_total else 0.0
    r = matches / ref_total if ref_total else 0.0
    f = 2 * p * r / (p + r) if (p + r) else 0.0
    return {"precision": p, "recall": r, "f": f}


def _ngrams(tokens: list[str], n: int) -> Counter:
    return Counter(tuple(tokens[i:i + n]) for i in range(len(tokens) - n + 1))


def rouge_n(candidate: str, reference: str, n: int = 1) -> dict[str, float]:
    """Clipped n-gram overlap between candidate and one reference."""
    cand = _ngrams(tokenize(candidate), n)
    ref = _ngrams(tokenize(reference), n)
    matches = sum((cand & ref).values())
    return _f_measure(matches, sum(cand.values()), sum(ref.values()))


def _lcs_len(a: list[str], b: list[str]) -> int:
    """Length of the longest common subsequence, O(len(a)*len(b)) time,
    O(min) memory — summaries are short enough that this is instant."""
    if len(a) < len(b):
        a, b = b, a
    prev = [0] * (len(b) + 1)
    for x in a:
        cur = [0]
        for j, y in enumerate(b, 1):
            cur.append(prev[j - 1] + 1 if x == y else max(prev[j], cur[j - 1]))
        prev = cur
    return prev[-1]


def rouge_l(candidate: str, reference: str) -> dict[str, float]:
    """Sentence-level ROUGE-L (LCS over the whole token streams)."""
    cand = tokenize(candidate)
    ref = tokenize(reference)
    return _f_measure(_lcs_len(cand, ref), len(cand), len(ref))


def rouge_scores(candidate: str, references: str | Iterable[str]) -> dict[str, dict[str, float]]:
    """ROUGE-1/2/L against one or more references (best-F per metric)."""
    if isinstance(references, str):
        references = [references]
    references = list(references)
    if not references:
        raise ValueError("rouge_scores needs at least one reference")
    best: dict[str, dict[str, float]] = {}
    for ref in references:
        for name, score in (
            ("rouge1", rouge_n(candidate, ref, 1)),
            ("rouge2", rouge_n(candidate, ref, 2)),
            ("rougeL", rouge_l(candidate, ref)),
        ):
            if name not in best or score["f"] > best[name]["f"]:
                best[name] = score
    return best
