"""Parity harness: run the pipeline and score its summary against a stored
baseline with ROUGE (BASELINE.json .metric; SURVEY.md §7.2 step 7).

The baseline file is either a plain-text summary or a JSON record
``{"summary": "...", "meta": {...}}`` (e.g. a captured GPT-4o output from the
reference pipeline).  ``run_parity`` executes the full map-reduce pipeline on
a transcript and reports ROUGE-1/2/L plus throughput; ``evaluate_parity``
scores an already-produced summary.

CLI: ``python -m lmrs_tpu.eval.parity --input t.json --baseline ref.txt``.
"""

from __future__ import annotations

import dataclasses
import json
import sys
import time
from pathlib import Path
from typing import Any

from lmrs_tpu.eval.rouge import rouge_scores


@dataclasses.dataclass
class ParityReport:
    """ROUGE scores + run stats, with a single pass/fail gate on ROUGE-L F."""

    rouge1_f: float
    rouge2_f: float
    rougeL_f: float
    threshold: float
    chunks: int = 0
    wall_s: float = 0.0
    chunks_per_sec: float = 0.0
    summary: str = ""

    @property
    def passed(self) -> bool:
        return self.rougeL_f >= self.threshold

    def to_dict(self) -> dict[str, Any]:
        d = dataclasses.asdict(self)
        d["passed"] = self.passed
        return d


def load_baseline(path: str | Path) -> str:
    """Baseline summary from plain text or a {"summary": ...} JSON record."""
    raw = Path(path).read_text(encoding="utf-8")
    try:
        obj = json.loads(raw)
    except json.JSONDecodeError:
        return raw.strip()
    if isinstance(obj, dict):
        if "summary" not in obj:
            raise ValueError(
                f"baseline {path} is JSON but has no top-level 'summary' key "
                f"(keys: {sorted(obj)[:8]}); extract the summary text first"
            )
        return str(obj["summary"]).strip()
    if isinstance(obj, list):
        raise ValueError(f"baseline {path} is a JSON array, not a summary record")
    return raw.strip()


def evaluate_parity(candidate: str, baseline: str, threshold: float = 0.3) -> ParityReport:
    scores = rouge_scores(candidate, baseline)
    return ParityReport(
        rouge1_f=scores["rouge1"]["f"],
        rouge2_f=scores["rouge2"]["f"],
        rougeL_f=scores["rougeL"]["f"],
        threshold=threshold,
        summary=candidate,
    )


def run_parity(
    transcript: dict[str, Any],
    baseline_summary: str,
    config: Any = None,
    threshold: float = 0.3,
    **summarize_kw: Any,
) -> ParityReport:
    """Full pipeline on ``transcript`` scored against ``baseline_summary``."""
    from lmrs_tpu.config import PipelineConfig
    from lmrs_tpu.pipeline import TranscriptSummarizer

    cfg = config or PipelineConfig()
    summarizer = TranscriptSummarizer(cfg)
    t0 = time.time()
    try:
        result = summarizer.summarize(transcript, **summarize_kw)
        wall = time.time() - t0  # exclude engine teardown from throughput
    finally:
        summarizer.shutdown()
    report = evaluate_parity(result["summary"], baseline_summary, threshold)
    report.chunks = result.get("num_chunks", 0)
    report.wall_s = wall
    report.chunks_per_sec = report.chunks / wall if wall > 0 else 0.0
    return report


def _main() -> int:
    import argparse

    from lmrs_tpu.config import EngineConfig, PipelineConfig

    p = argparse.ArgumentParser(description="ROUGE parity vs a stored baseline summary")
    p.add_argument("--input", "-i", required=True, help="transcript JSON")
    p.add_argument("--baseline", "-b", required=True, help="baseline summary (txt or JSON)")
    p.add_argument("--backend", default="mock", help="mock | jax")
    p.add_argument("--model", default="tiny", help="model preset name")
    p.add_argument("--threshold", type=float, default=0.3, help="ROUGE-L F gate")
    p.add_argument("--json", action="store_true", help="print the full report as JSON")
    args = p.parse_args()

    try:
        transcript = json.loads(Path(args.input).read_text(encoding="utf-8"))
    except (OSError, json.JSONDecodeError, UnicodeDecodeError) as e:
        print(f"error: cannot read transcript {args.input}: {e}", file=sys.stderr)
        return 2
    try:
        baseline = load_baseline(args.baseline)
    except (OSError, ValueError, UnicodeDecodeError) as e:
        print(f"error: {e}", file=sys.stderr)
        return 2
    # make_engine resolves EngineConfig.model to a preset itself.
    cfg = PipelineConfig(engine=EngineConfig(backend=args.backend, model=args.model))
    try:
        report = run_parity(transcript, baseline, cfg, threshold=args.threshold)
    except ValueError as e:
        print(f"error: {e}", file=sys.stderr)
        return 2
    if args.json:
        print(json.dumps(report.to_dict(), indent=2))
    else:
        print(
            f"ROUGE-1 {report.rouge1_f:.4f}  ROUGE-2 {report.rouge2_f:.4f}  "
            f"ROUGE-L {report.rougeL_f:.4f}  (gate {report.threshold})  "
            f"{report.chunks} chunks in {report.wall_s:.2f}s "
            f"({report.chunks_per_sec:.2f} chunks/s)  "
            f"{'PASS' if report.passed else 'FAIL'}"
        )
    return 0 if report.passed else 1


if __name__ == "__main__":
    raise SystemExit(_main())
