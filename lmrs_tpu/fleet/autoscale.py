"""Elastic pool autoscaling on the SLO/ledger substrate.

A control loop (default OFF — ``LMRS_AUTOSCALE=1`` arms it) over a live
:class:`~lmrs_tpu.serving.router.RouterEngine`.  Each tick reads the
signals the router already maintains — per-host published SLO burn
states (obs/slo.py, cached from ``/healthz`` summaries), per-host
in-flight leg counts, and the fleet's request throughput (served-counter
deltas smoothed into a short-horizon EWMA forecast) — and resizes the
pool:

* **scale up** when the burning fraction of the healthy fleet reaches
  half (hosts converting overload into deadline misses need relief
  BEFORE breakers start opening) or the average in-flight depth exceeds
  the high watermark while the forecast is still rising;
* **scale down** when the forecast has idled below the low-rate
  watermark with zero burn and zero in-flight work — and only ever a
  host this autoscaler spawned: operator-configured capacity is never
  torn down.  The victim DRAINS first (``router.drain_host``: it leaves
  the dispatch order but keeps its in-flight legs), is polled idle
  across ticks, then removed and torn down; a drain that cannot go idle
  within the timeout is force-removed so a wedged victim cannot pin the
  loop.

Spawning and teardown are **injectable callbacks**: production passes
:class:`SupervisedHostPool` (each scale-up launches one ``lmrs-serve
--supervise`` child, so new capacity arrives under the supervisor's
watchdog/respawn umbrella — serving/supervisor.py); tests pass fakes.
The loop only touches the router's public elasticity surface
(``add_host`` / ``drain_host`` / ``host_idle`` / ``remove_host``), so it
composes identically with mock fleets and real pods.

Kill-switch contract: with ``LMRS_AUTOSCALE=0`` (the default)
:func:`maybe_autoscaler` returns None and nothing in the serving path
changes — the knob is opt-in because resizing spawns PROCESSES.
"""

from __future__ import annotations

import logging
import threading
import time

from lmrs_tpu.obs.trace import get_tracer
from lmrs_tpu.utils.env import env_bool, env_float, env_int

logger = logging.getLogger("lmrs.fleet.autoscale")


def autoscale_enabled() -> bool:
    """The ``LMRS_AUTOSCALE`` master switch (default OFF: scaling spawns
    processes, so it is opt-in unlike the pure-bookkeeping QoS knobs)."""
    return env_bool("LMRS_AUTOSCALE", False)


class Autoscaler:
    """The control loop.  ``tick()`` makes at most one scaling decision
    and is directly callable (tests drive it with a fake clock);
    ``start()`` runs it on a daemon thread every ``interval_s``."""

    def __init__(self, router, spawn_cb, remove_cb=None,
                 clock=time.monotonic, registry=None,
                 enabled: bool | None = None,
                 interval_s: float | None = None,
                 min_hosts: int | None = None,
                 max_hosts: int | None = None,
                 role: str = "both",
                 up_inflight: float = 4.0,
                 down_rate_rps: float = 0.1,
                 ewma_alpha: float = 0.5,
                 cooldown_ticks: int = 3,
                 drain_timeout_s: float = 60.0):
        self.enabled = (autoscale_enabled() if enabled is None
                        else bool(enabled))
        self.router = router
        self.spawn_cb = spawn_cb          # () -> url | None
        self.remove_cb = remove_cb        # (netloc) -> None
        self.clock = clock
        self.interval_s = (env_float("LMRS_AUTOSCALE_INTERVAL_S", 10.0,
                                     lo=0.1)
                           if interval_s is None else float(interval_s))
        self.min_hosts = (env_int("LMRS_AUTOSCALE_MIN", 1, lo=1)
                          if min_hosts is None else int(min_hosts))
        self.max_hosts = (env_int("LMRS_AUTOSCALE_MAX", 8, lo=1)
                          if max_hosts is None else int(max_hosts))
        self.role = role
        self.up_inflight = float(up_inflight)
        self.down_rate_rps = float(down_rate_rps)
        self.ewma_alpha = float(ewma_alpha)
        self.cooldown_ticks = int(cooldown_ticks)
        self.drain_timeout_s = float(drain_timeout_s)
        # forecast + loop state: tick() runs on ONE thread (the loop or
        # a test), so these need no lock; the router calls we make are
        # individually thread-safe
        self._last_served: int | None = None
        self._last_t: float | None = None
        self._ewma_rps: float | None = None
        self._ticks_since_action = self.cooldown_ticks  # first tick may act
        self._draining: dict[str, float] = {}  # netloc -> drain start t
        self._spawned: set[str] = set()        # netlocs we created
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        self._c_up = self._c_down = self._c_drain = None
        self._g_pool = self._g_rps = None
        if registry is not None and self.enabled:
            self._c_up = registry.counter(
                "lmrs_autoscale_scale_ups_total",
                "hosts the autoscaler spawned into the fleet")
            self._c_down = registry.counter(
                "lmrs_autoscale_scale_downs_total",
                "hosts the autoscaler removed after a completed drain")
            self._c_drain = registry.counter(
                "lmrs_autoscale_drains_total",
                "scale-down drains the autoscaler started")
            self._g_pool = registry.gauge(
                "lmrs_autoscale_pool_size",
                "fleet hosts currently in the dispatch order "
                "(draining hosts excluded)")
            self._g_rps = registry.gauge(
                "lmrs_autoscale_forecast_rps",
                "EWMA short-horizon forecast of fleet request throughput",
                unit="seconds")

    # ------------------------------------------------------------- signals

    def _forecast(self, now: float) -> float:
        """Fold the served-counter delta since the last tick into the
        EWMA throughput forecast (requests/second)."""
        served = sum(h.served for h in self.router.hosts)
        if self._last_served is None or self._last_t is None:
            self._last_served, self._last_t = served, now
            return 0.0
        dt = max(now - self._last_t, 1e-6)
        rate = max(0, served - self._last_served) / dt
        self._last_served, self._last_t = served, now
        self._ewma_rps = (rate if self._ewma_rps is None
                          else self.ewma_alpha * rate
                          + (1.0 - self.ewma_alpha) * self._ewma_rps)
        if self._g_rps is not None:
            self._g_rps.set(self._ewma_rps)
        return self._ewma_rps

    # ---------------------------------------------------------------- loop

    def tick(self) -> dict:
        """One control decision.  Returns a summary of what it saw and
        did (the test/observability surface)."""
        now = self.clock()
        actions: list[str] = []
        tr = get_tracer()
        # 1. advance in-progress drains first: an idle victim completes
        #    its exit, a wedged one is force-removed at the timeout —
        #    either way the slot frees before any new decision
        for netloc, since in list(self._draining.items()):
            idle = self.router.host_idle(netloc)
            # a drain-triggered KV migration (router.migrations_pending,
            # LMRS_KV_MIGRATE) holds the removal like in-flight legs do:
            # force-removing mid-copy would tear warm pages off the pod
            # while a sibling is still pulling them.  The drain timeout
            # backstops a wedged migration exactly as it does a wedged
            # leg — getattr keeps fake routers in tests working.
            migrating = getattr(self.router, "migrations_pending",
                                lambda _n: False)(netloc)
            if ((not idle or migrating)
                    and now - since < self.drain_timeout_s):
                continue
            if self.router.remove_host(netloc, force=not idle):
                self._draining.pop(netloc, None)
                self._spawned.discard(netloc)
                if self.remove_cb is not None:
                    self.remove_cb(netloc)
                if self._c_down is not None:
                    self._c_down.inc()
                actions.append(f"removed:{netloc}"
                               + ("" if idle else ":forced"))
                if tr:
                    # fleet-drift contract (trace.py): every autoscaler
                    # resize is an auditable instant on the trace
                    tr.instant("autoscale_action",
                               args={"action": "removed", "host": netloc,
                                     "forced": not idle})
        rps = self._forecast(now)
        hosts = [h for h in self.router.hosts if not h.draining]
        healthy = [h for h in hosts if h.healthy]
        burning = sum(1 for h in healthy
                      if self.router._slo_penalty(h) >= 1)
        inflight = sum(h.inflight for h in hosts)
        avg_inflight = inflight / len(healthy) if healthy else 0.0
        size = len(hosts)
        self._ticks_since_action += 1
        if self._g_pool is not None:
            self._g_pool.set(size)
        if not self.enabled:
            return {"enabled": False, "pool": size, "actions": actions}
        # 2. at most one resize per tick, paced by the cooldown so one
        #    burst cannot staircase the fleet up before new capacity
        #    even absorbs traffic
        if self._ticks_since_action >= self.cooldown_ticks:
            want_up = (size < self.max_hosts
                       and ((healthy and 2 * burning >= len(healthy))
                            or avg_inflight > self.up_inflight))
            want_down = (size > self.min_hosts
                         and burning == 0 and inflight == 0
                         and self._ewma_rps is not None
                         and self._ewma_rps < self.down_rate_rps)
            if want_up:
                url = None
                try:
                    url = self.spawn_cb()
                except Exception:  # noqa: BLE001 - a failed spawn is a
                    # degraded tick, never a dead loop
                    logger.warning("autoscale spawn failed", exc_info=True)
                if url:
                    h = self.router.add_host(url, self.role)
                    self._spawned.add(h.netloc)
                    self._ticks_since_action = 0
                    if self._c_up is not None:
                        self._c_up.inc()
                    actions.append(f"spawned:{h.netloc}")
                    if tr:
                        tr.instant("autoscale_action",
                                   args={"action": "spawned",
                                         "host": h.netloc})
                    logger.info("autoscale UP -> %s (burning %d/%d, "
                                "inflight %.1f/host, forecast %.2f rps)",
                                h.netloc, burning, len(healthy),
                                avg_inflight, rps)
            elif want_down:
                victim = next((h for h in hosts
                               if h.netloc in self._spawned
                               and h.netloc not in self._draining), None)
                if victim is not None and self.router.drain_host(
                        victim.netloc):
                    self._draining[victim.netloc] = now
                    self._ticks_since_action = 0
                    if self._c_drain is not None:
                        self._c_drain.inc()
                    actions.append(f"draining:{victim.netloc}")
                    if tr:
                        tr.instant("autoscale_action",
                                   args={"action": "draining",
                                         "host": victim.netloc})
                    logger.info("autoscale DOWN: draining %s "
                                "(forecast %.2f rps)", victim.netloc, rps)
        return {"enabled": True, "pool": size, "healthy": len(healthy),
                "burning": burning, "inflight": inflight,
                "forecast_rps": round(rps, 3),
                "draining": sorted(self._draining), "actions": actions}

    def report(self) -> dict:
        """Observability snapshot (no side effects, no decisions)."""
        hosts = [h for h in self.router.hosts if not h.draining]
        return {"object": "autoscale", "enabled": self.enabled,
                "pool": len(hosts),
                "min": self.min_hosts, "max": self.max_hosts,
                "forecast_rps": round(self._ewma_rps or 0.0, 3),
                "spawned": sorted(self._spawned),
                "draining": sorted(self._draining)}

    def start(self) -> "Autoscaler":
        if self._thread is None and self.enabled:
            self._stop.clear()
            self._thread = threading.Thread(
                target=self._run, daemon=True, name="lmrs-autoscale")
            self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        t, self._thread = self._thread, None
        if t is not None:
            t.join(timeout=5.0)

    def _run(self) -> None:
        while not self._stop.wait(self.interval_s):
            try:
                self.tick()
            except Exception:  # noqa: BLE001 - the loop must survive a
                # transient router/API error; the next tick retries
                logger.warning("autoscale tick failed", exc_info=True)


class SupervisedHostPool:
    """Production spawn/remove callbacks: each scale-up launches one
    ``lmrs-serve --supervise`` child (serving/cli.py) on a freshly
    bound port, waits for its ``/healthz``, and hands the URL to the
    autoscaler; scale-down terminates the supervisor (which takes its
    child down with it).  Pass ``pool.spawn`` / ``pool.remove`` as the
    Autoscaler callbacks."""

    def __init__(self, base_argv=("--backend", "mock"),
                 host: str = "127.0.0.1", startup_timeout_s: float = 30.0):
        self.base_argv = list(base_argv)
        self.host = host
        self.startup_timeout_s = float(startup_timeout_s)
        self._procs: dict[str, object] = {}  # netloc -> Popen
        self._lock = threading.Lock()

    @staticmethod
    def _free_port(host: str) -> int:
        import socket

        with socket.socket() as s:
            s.bind((host, 0))
            return s.getsockname()[1]

    def _wait_healthy(self, netloc: str) -> bool:
        import http.client

        deadline = time.monotonic() + self.startup_timeout_s
        while time.monotonic() < deadline:
            conn = None
            try:
                conn = http.client.HTTPConnection(netloc, timeout=2.0)
                conn.request("GET", "/healthz")
                if conn.getresponse().status == 200:
                    return True
            except OSError:
                pass
            finally:
                if conn is not None:
                    conn.close()
            time.sleep(0.25)
        return False

    def spawn(self) -> str | None:
        import subprocess
        import sys

        port = self._free_port(self.host)
        netloc = f"{self.host}:{port}"
        argv = [sys.executable, "-m", "lmrs_tpu.serving.cli",
                "--supervise", "--host", self.host, "--port", str(port),
                "--quiet", *self.base_argv]
        try:
            proc = subprocess.Popen(argv)
        except OSError:
            logger.warning("supervised spawn exec failed", exc_info=True)
            return None
        if not self._wait_healthy(netloc):
            logger.warning("spawned host %s never became healthy; "
                           "terminating", netloc)
            proc.terminate()
            return None
        with self._lock:
            self._procs[netloc] = proc
        return f"http://{netloc}"

    def remove(self, netloc: str) -> None:
        with self._lock:
            proc = self._procs.pop(netloc, None)
        if proc is None:
            return
        proc.terminate()
        try:
            proc.wait(timeout=5.0)
        except Exception:  # noqa: BLE001 - stubborn supervisor
            proc.kill()

    def shutdown(self) -> None:
        with self._lock:
            netlocs = list(self._procs)
        for netloc in netlocs:
            self.remove(netloc)


def maybe_autoscaler(router, spawn_cb, remove_cb=None,
                     registry=None, **kw) -> Autoscaler | None:
    """The wiring-site factory: a live (not yet started) autoscaler, or
    None when ``LMRS_AUTOSCALE`` is off — callers guard on ``is not
    None`` so the disarmed serving path is byte-for-byte unchanged."""
    if not autoscale_enabled():
        return None
    return Autoscaler(router, spawn_cb, remove_cb=remove_cb,
                      registry=registry, enabled=True, **kw)
