"""Fleet-level QoS enforcement and elasticity (PR 17).

PR 14 built the measurement half of a multi-tenant platform — per-request
phase-split device-seconds (obs/ledger.py) and burn-rate SLO states
(obs/slo.py).  This package is the enforcement half:

* :mod:`lmrs_tpu.fleet.qos` — fair-share admission over a sliding window
  of ledger device-seconds, ``interactive`` > ``batch`` priority classes,
  and the preemption policy that victimizes over-quota bulk work first;
* :mod:`lmrs_tpu.fleet.autoscale` — an elastic pool control loop on the
  router that resizes prefill/decode pools from measured SLO burn and
  windowed cost, spawning supervised engines and draining hosts through
  the breaker before removal.

Both halves are pure policy over existing substrates: ``LMRS_QOS=0``
restores FIFO admission byte-for-byte, ``LMRS_AUTOSCALE=0`` (the
default) never spawns or drains anything.
"""

from lmrs_tpu.fleet.autoscale import (Autoscaler, SupervisedHostPool,
                                      autoscale_enabled, maybe_autoscaler)
from lmrs_tpu.fleet.qos import (DEFAULT_CLASS, QoSPolicy, class_rank,
                                clean_qos_class, maybe_qos, qos_enabled)

__all__ = ["Autoscaler", "DEFAULT_CLASS", "QoSPolicy",
           "SupervisedHostPool", "autoscale_enabled", "class_rank",
           "clean_qos_class", "maybe_autoscaler", "maybe_qos",
           "qos_enabled"]
