"""Fair-share admission, priority classes, and preemption policy.

The policy enforces quotas in the ledger's own currency — device-seconds
over a sliding window (``LMRS_QOS_WINDOW_S``) — never request counts:
a tenant streaming one 8k-token summarize bill equals a tenant firing
eighty 100-token probes, which is exactly the point.  Three cooperating
rules, all deterministic given the same usage window:

* **admission** (deficit-weighted round-robin): among the queue's head
  window the scheduler admits the best entry by ``(class rank, windowed
  device-seconds / weight, FIFO order)`` — an under-served tenant's
  normalized usage is lower, so it wins ties against a flooding one;
* **classes**: ``interactive`` (live sessions, default for unlabeled
  ingress) outranks ``batch`` (job fan-out) categorically — a
  live-session refresh never queues behind a map wave by luck;
* **preemption**: under page pressure the victim is the WORST active
  decode slot by ``(batch first, highest normalized usage, youngest)``
  — over-quota bulk work pays for the pool before anyone else does.

Weights come from ``LMRS_QOS_WEIGHTS`` (``tenantA:4,tenantB:1``;
unlisted tenants weigh 1).  Fair share is self-normalizing: a tenant is
over quota when its share of the window's total usage exceeds its share
of the active tenants' total weight — no capacity estimate needed.

``LMRS_QOS=0`` disables everything: :func:`maybe_qos` returns None and
the scheduler keeps today's FIFO admission and youngest-victim
preemption byte-for-byte (the policy is pure host bookkeeping — it
touches no RNG and no dispatch, so armed-vs-off differs only in
ORDER under contention, never in any request's tokens).
"""

from __future__ import annotations

import logging
import threading
import time
from collections import deque

from lmrs_tpu.utils.env import env_bool, env_float, env_list

logger = logging.getLogger("lmrs.fleet.qos")

# priority classes, best first; anything unlabeled resolves to the first
# (interactive) so QoS can never demote traffic that predates the label
CLASSES = ("interactive", "batch")
DEFAULT_CLASS = "interactive"

# fold target for usage events from requests that carried no tenant —
# mirrors obs/ledger.py DEFAULT_TENANT without importing the ledger
_DEFAULT_TENANT = "default"


def qos_enabled() -> bool:
    """The ``LMRS_QOS`` master switch (default armed — with uniform
    traffic the policy degenerates to FIFO anyway)."""
    return env_bool("LMRS_QOS", True)


def clean_qos_class(raw) -> str | None:
    """Validate a wire-supplied class label (header or body field):
    a known class lowercased, else None — garbage must degrade to the
    default class, never crash ingress or mint label cardinality."""
    if isinstance(raw, str):
        low = raw.strip().lower()
        if low in CLASSES:
            return low
    return None


def class_rank(qos_class: str | None) -> int:
    """Admission rank of a class label (lower admits first); None and
    unknown labels rank as ``interactive``."""
    return 1 if qos_class == "batch" else 0


def request_class(req) -> str:
    """A request's effective class: its stamped ``qos_class`` when valid,
    else ``interactive`` (getattr-guarded — dict-shaped fakes in tests
    and old pickled requests carry no field)."""
    return clean_qos_class(getattr(req, "qos_class", None)) or DEFAULT_CLASS


def parse_weights(items) -> dict[str, float]:
    """``tenantA:4,tenantB:0.5`` pairs -> weight map; malformed or
    non-positive entries are dropped with one warning (a typo'd weight
    must not zero a tenant's quota)."""
    out: dict[str, float] = {}
    for item in items:
        name, sep, val = item.rpartition(":")
        try:
            w = float(val)
        except ValueError:
            w = float("nan")
        if not sep or not name or not (w > 0):
            logger.warning("LMRS_QOS_WEIGHTS: ignoring malformed entry %r "
                           "(want tenant:weight, weight > 0)", item)
            continue
        out[name] = w
    return out


class QoSPolicy:
    """Sliding-window fair-share state + the three policy rules.

    Thread contract: the scheduler thread calls ``pick_index`` /
    ``victim_key`` between dispatches; the ledger observer
    (``note_usage``) fires from whichever thread finished a dispatch
    note; HTTP handlers read ``report()`` — ONE lock covers the window
    state (pure in-memory math, nothing blocking runs under it)."""

    def __init__(self, registry=None, enabled: bool | None = None,
                 clock=None):
        self.enabled = qos_enabled() if enabled is None else bool(enabled)
        self.window_s = env_float("LMRS_QOS_WINDOW_S", 60.0, lo=1.0)
        self.weights = parse_weights(env_list("LMRS_QOS_WEIGHTS"))
        self.clock = clock or time.monotonic
        self._lock = threading.Lock()
        # (t, tenant, device_seconds) usage events, oldest first, expired
        # off the left edge past window_s (guarded-by: _lock)
        self._events: deque[tuple[float, str, float]] = deque()
        self._usage: dict[str, float] = {}  # windowed sums (guarded-by: _lock)
        self._c_reorders = self._c_preempts = None
        self._g_window = None
        if registry is not None and self.enabled:
            self._c_reorders = registry.counter(
                "lmrs_qos_reorders_total",
                "admissions where fair-share picked a non-head queue entry")
            self._c_preempts = registry.counter(
                "lmrs_qos_preempt_victims_total",
                "preemption victims chosen by QoS policy (over-quota bulk "
                "first) instead of youngest-slot order")
            self._g_window = registry.gauge(
                "lmrs_qos_window_device_seconds",
                "total windowed device-seconds the fair-share policy is "
                "currently normalizing over", unit="seconds")

    # ------------------------------------------------------------ usage feed

    def weight(self, tenant: str) -> float:
        return self.weights.get(tenant, 1.0)

    def note_usage(self, pairs) -> None:
        """Absorb ``(tenant, device_seconds)`` pairs from one ledger
        apportionment (the CostLedger observer hook — fired OUTSIDE the
        ledger lock, so the two locks never nest)."""
        if not self.enabled:
            return
        now = self.clock()
        with self._lock:
            for tenant, s in pairs:
                s = float(s)
                if s <= 0.0:
                    continue
                tenant = tenant or _DEFAULT_TENANT
                self._events.append((now, tenant, s))
                self._usage[tenant] = self._usage.get(tenant, 0.0) + s
            self._expire_locked(now)
            if self._g_window is not None:
                self._g_window.set(sum(self._usage.values()))

    def _expire_locked(self, now: float) -> None:  # holds-lock: _lock
        cut = now - self.window_s
        ev = self._events
        while ev and ev[0][0] < cut:
            _, tenant, s = ev.popleft()
            left = self._usage.get(tenant, 0.0) - s
            if left <= 1e-12:
                self._usage.pop(tenant, None)
            else:
                self._usage[tenant] = left

    def _usage_snapshot(self) -> dict[str, float]:
        with self._lock:
            self._expire_locked(self.clock())
            return dict(self._usage)

    def normalized_usage(self, tenant: str | None) -> float:
        """Windowed device-seconds / weight — the deficit the admission
        and preemption rules compare (0 for a tenant idle all window)."""
        tenant = tenant or _DEFAULT_TENANT
        with self._lock:
            self._expire_locked(self.clock())
            return self._usage.get(tenant, 0.0) / self.weight(tenant)

    # --------------------------------------------------------- policy rules

    def pick_index(self, reqs) -> int:
        """Admission rule over the queue's head window: index of the
        entry to admit next — best ``(class rank, normalized windowed
        usage, FIFO position)``.  With one tenant and one class every
        key ties and FIFO wins: armed QoS on uniform traffic IS FIFO."""
        usage = self._usage_snapshot()
        best_i, best_key = 0, None
        for i, req in enumerate(reqs):
            tenant = getattr(req, "tenant", None) or _DEFAULT_TENANT
            key = (class_rank(request_class(req)),
                   usage.get(tenant, 0.0) / self.weight(tenant), i)
            if best_key is None or key < best_key:
                best_i, best_key = i, key
        if best_i and self._c_reorders is not None:
            self._c_reorders.inc()
        return best_i

    def victim_key(self, req, t_start: float):
        """Preemption rule: sort key where the MAX is the victim —
        batch before interactive, over-quota before under-served,
        youngest last (ties degrade to today's youngest-slot rule)."""
        tenant = getattr(req, "tenant", None) or _DEFAULT_TENANT
        return (class_rank(request_class(req)) == 1,
                self.normalized_usage(tenant), t_start)

    def note_preempt(self) -> None:
        if self._c_preempts is not None:
            self._c_preempts.inc()

    def over_quota(self, tenant: str | None) -> bool:
        """Self-normalizing quota check: the tenant's share of windowed
        usage exceeds its share of the ACTIVE tenants' total weight.
        A lone tenant is never over quota (its fair share is 100%)."""
        tenant = tenant or _DEFAULT_TENANT
        usage = self._usage_snapshot()
        total = sum(usage.values())
        if total <= 0.0 or tenant not in usage or len(usage) < 2:
            return False
        wsum = sum(self.weight(t) for t in usage)
        fair = total * self.weight(tenant) / wsum
        return usage[tenant] > fair

    # -------------------------------------------------------------- reports

    def report(self) -> dict:
        """The ``qos`` block of ``GET /v1/usage``: per-tenant windowed
        burn against configured weight, for chargeback."""
        if not self.enabled:
            return {"object": "qos", "enabled": False}
        usage = self._usage_snapshot()
        total = sum(usage.values())
        wsum = sum(self.weight(t) for t in usage) or 1.0
        tenants = {}
        for t, s in sorted(usage.items()):
            fair = total * self.weight(t) / wsum
            tenants[t] = {
                "weight": self.weight(t),
                "window_device_seconds": round(s, 6),
                "share": round(s / total, 4) if total > 0 else 0.0,
                "fair_share": round(self.weight(t) / wsum, 4),
                "over_quota": bool(len(usage) > 1 and s > fair),
            }
        return {"object": "qos", "enabled": True,
                "window_s": self.window_s,
                "window_device_seconds": round(total, 6),
                "classes": list(CLASSES), "tenants": tenants}


def maybe_qos(registry=None, clock=None) -> QoSPolicy | None:
    """The wiring-site factory: a live policy, or None when ``LMRS_QOS=0``
    — callers guard every hook on ``is not None`` so the disarmed path
    stays byte-for-byte today's code."""
    if not qos_enabled():
        return None
    return QoSPolicy(registry, enabled=True, clock=clock)
