"""Shared machinery for the lmrs-lint passes: findings, module loading,
inline suppressions, and the checked-in baseline.

Finding identity (the baseline key) deliberately excludes line numbers —
an accepted pre-existing finding must stay suppressed when unrelated
edits shift the file — and keys are COUNTED: two identical-looking
findings in one file occupy two baseline slots, so a third new instance
of an accepted pattern still surfaces.
"""

from __future__ import annotations

import ast
import json
import re
from dataclasses import dataclass, field
from pathlib import Path

BASELINE_SCHEMA = "lmrs-lint-baseline-v1"

# trailing same-line suppression: ``code  # lint: ignore[rule]`` — rule may
# be a prefix ("race" silences the family, "race.unguarded-write" one rule)
_IGNORE_RE = re.compile(r"#\s*lint:\s*ignore\[([\w.,\s-]+)\]")


@dataclass(frozen=True)
class Finding:
    rule: str      # "family.check-name", e.g. "race.unguarded-write"
    path: str      # repo-relative posix path
    line: int      # 1-based
    message: str
    hint: str = ""

    @property
    def family(self) -> str:
        return self.rule.split(".", 1)[0]

    @property
    def key(self) -> str:
        """Baseline identity: rule + file + message, no line number."""
        return f"{self.rule}|{self.path}|{self.message}"

    def render(self) -> str:
        out = f"{self.path}:{self.line}: [{self.rule}] {self.message}"
        if self.hint:
            out += f"\n    hint: {self.hint}"
        return out


@dataclass
class Module:
    """One parsed source file (path is repo-relative posix)."""

    path: str
    source: str
    tree: ast.Module
    lines: list[str] = field(default_factory=list)

    @classmethod
    def from_source(cls, path: str, source: str) -> "Module":
        return cls(path=path, source=source, tree=ast.parse(source),
                   lines=source.splitlines())

    def line_text(self, lineno: int) -> str:
        """1-based line text ('' out of range)."""
        if 1 <= lineno <= len(self.lines):
            return self.lines[lineno - 1]
        return ""

    def suppressed_rules(self, lineno: int) -> set[str]:
        """Rules (or rule prefixes) suppressed on this line via
        ``# lint: ignore[...]``."""
        m = _IGNORE_RE.search(self.line_text(lineno))
        if not m:
            return set()
        return {tok.strip() for tok in m.group(1).split(",") if tok.strip()}

    def is_suppressed(self, finding: Finding) -> bool:
        for tok in self.suppressed_rules(finding.line):
            if finding.rule == tok or finding.rule.startswith(tok + "."):
                return True
        return False


# default scan surface: the production package plus the bench/driver
# scripts (tests are exercised BY the analyzer, not scanned by it)
_DEFAULT_GLOBS = ("lmrs_tpu/**/*.py", "bench.py", "scripts/*.py")
_EXCLUDE_PARTS = ("__pycache__",)


def find_repo_root(start: Path | None = None) -> Path:
    """The repo checkout root: the nearest ancestor of ``start`` (default
    cwd) containing ``lmrs_tpu/``; falls back to the package's parent."""
    cur = (start or Path.cwd()).resolve()
    for cand in (cur, *cur.parents):
        if (cand / "lmrs_tpu" / "__init__.py").exists():
            return cand
    return Path(__file__).resolve().parents[2]


def load_modules(root: Path, globs: tuple[str, ...] = _DEFAULT_GLOBS
                 ) -> list[Module]:
    mods: list[Module] = []
    seen: set[str] = set()
    for pattern in globs:
        for p in sorted(root.glob(pattern)):
            rel = p.relative_to(root).as_posix()
            if rel in seen or any(part in p.parts
                                  for part in _EXCLUDE_PARTS):
                continue
            seen.add(rel)
            try:
                mods.append(Module.from_source(rel, p.read_text(
                    encoding="utf-8")))
            except (SyntaxError, UnicodeDecodeError) as e:
                # a file the analyzer cannot parse is itself a finding
                # (surfaced by run_passes via ctx.parse_failures)
                mods.append(Module(path=rel, source="",
                                   tree=ast.parse(""), lines=[]))
                mods[-1].parse_error = str(e)  # type: ignore[attr-defined]
    return mods


@dataclass
class RepoContext:
    """What a pass sees: the parsed modules plus doc text (overridable by
    tests, so fixtures can plant doc drift without touching disk)."""

    root: Path
    modules: list[Module]
    docs: dict[str, str] = field(default_factory=dict)

    @classmethod
    def load(cls, root: Path | None = None) -> "RepoContext":
        root = root or find_repo_root()
        return cls(root=root, modules=load_modules(root))

    def doc(self, rel_path: str) -> str:
        """Text of a docs file ('' when absent — the drift passes then
        report everything code-side as undocumented)."""
        if rel_path not in self.docs:
            p = self.root / rel_path
            self.docs[rel_path] = (p.read_text(encoding="utf-8")
                                   if p.exists() else "")
        return self.docs[rel_path]

    def module(self, rel_path: str) -> Module | None:
        for m in self.modules:
            if m.path == rel_path:
                return m
        return None


class Baseline:
    """Checked-in acceptance of pre-existing findings.

    The file maps finding keys to accepted counts.  ``apply`` splits a
    run's findings into (new, accepted) and reports baseline keys that no
    longer match anything ("expired" — the underlying issue was fixed, so
    the entry should be pruned; ``--write-baseline`` does it)."""

    def __init__(self, counts: dict[str, int] | None = None):
        self.counts: dict[str, int] = dict(counts or {})

    @classmethod
    def load(cls, path: str | Path) -> "Baseline":
        p = Path(path)
        if not p.exists():
            return cls()
        doc = json.loads(p.read_text(encoding="utf-8"))
        if doc.get("schema") != BASELINE_SCHEMA:
            raise ValueError(
                f"{path}: unknown baseline schema {doc.get('schema')!r}")
        counts = doc.get("findings", {})
        if not all(isinstance(v, int) and v > 0 for v in counts.values()):
            raise ValueError(f"{path}: baseline counts must be positive "
                             "integers")
        return cls(counts)

    @classmethod
    def from_findings(cls, findings: list[Finding]) -> "Baseline":
        b = cls()
        for f in findings:
            b.counts[f.key] = b.counts.get(f.key, 0) + 1
        return b

    def save(self, path: str | Path) -> None:
        doc = {"schema": BASELINE_SCHEMA,
               "findings": dict(sorted(self.counts.items()))}
        Path(path).write_text(json.dumps(doc, indent=1) + "\n",
                              encoding="utf-8")

    def apply(self, findings: list[Finding]
              ) -> tuple[list[Finding], list[Finding], list[str]]:
        """-> (new, accepted, expired_keys)."""
        budget = dict(self.counts)
        new: list[Finding] = []
        accepted: list[Finding] = []
        for f in findings:
            if budget.get(f.key, 0) > 0:
                budget[f.key] -= 1
                accepted.append(f)
            else:
                new.append(f)
        expired = sorted(k for k, n in budget.items() if n > 0)
        return new, accepted, expired


def run_passes(ctx: RepoContext,
               families: tuple[str, ...] = ("race", "tracing", "drift",
                                            "env")) -> list[Finding]:
    """Run the selected pass families; findings sorted by (path, line),
    inline suppressions already applied."""
    from lmrs_tpu.analysis import drift, envpass, locks, tracing

    passes = {"race": locks.run, "tracing": tracing.run,
              "drift": drift.run, "env": envpass.run}
    findings: list[Finding] = []
    for mod in ctx.modules:
        err = getattr(mod, "parse_error", None)
        if err:
            findings.append(Finding(rule="core.parse-error", path=mod.path,
                                    line=1, message=f"unparseable: {err}"))
    for fam in families:
        findings.extend(passes[fam](ctx))
    by_path = {m.path: m for m in ctx.modules}
    findings = [f for f in findings
                if f.path not in by_path or not by_path[f.path].
                is_suppressed(f)]
    findings.sort(key=lambda f: (f.path, f.line, f.rule))
    return findings


def run_repo(root: Path | None = None,
             baseline_path: str | Path | None = None
             ) -> tuple[list[Finding], list[Finding], list[str]]:
    """One-call repo scan -> (new, accepted, expired_baseline_keys).  The
    CI gate and the tests' repo-clean check both ride this."""
    ctx = RepoContext.load(root)
    findings = run_passes(ctx)
    if baseline_path is None:
        baseline_path = ctx.root / "lint-baseline.json"
    baseline = Baseline.load(baseline_path)
    return baseline.apply(findings)
