"""JAX tracing-hazard pass (family ``tracing``).

Finds the bug classes that only explode at trace time (or worse, silently
recompile every step) inside jitted/scanned code in ``models/``, ``ops/``,
``engine/``, and ``parallel/``:

* ``tracing.python-branch-on-traced`` — ``if``/``while`` on a traced
  value: a ``TracerBoolConversionError`` at runtime, or a silent
  recompile when the value sneaks in via ``static_argnames``;
* ``tracing.host-sync-in-jit`` — ``.item()`` / ``float()`` / ``int()`` /
  ``bool()`` / ``np.asarray()`` / ``jax.device_get`` applied to a traced
  value inside jitted code: a device round-trip per call, or a trace
  error;
* ``tracing.dynamic-shape-in-jit`` — a traced value used as a shape (or
  ``range()`` bound): every new value is a new compilation;
* ``tracing.jit-closes-over-mutable-global`` — a jitted function reading
  a module global that some function rebinds (``global X``): jit baked
  the value at first trace and will never see the update;
* ``tracing.deprecated-api`` — the deprecated/moved-API table (run on
  EVERY module): ``jax.shard_map`` / ``jax.experimental.shard_map`` /
  ``pltpu.CompilerParams`` outside ``utils/jax_compat.py`` (AttributeError
  on the pinned 0.4.x CPU build — the class behind the five pre-existing
  ``test_kernels`` failures), ``jax.tree_map`` family (removed upstream).

Traced contexts: functions decorated ``@jax.jit`` (bare or via
``partial``), functions wrapped ``jax.jit(f)``, and local functions passed
to ``lax.scan`` / ``while_loop`` / ``cond`` / ``switch`` / ``fori_loop``.
Static argnames are honored.  Heuristics lean PRECISE over complete:
``x is None`` tests, ``isinstance``, and ``.shape``/``.ndim``/``.dtype``/
``len()`` uses are static under jit and never flagged.
"""

from __future__ import annotations

import ast
from dataclasses import dataclass

from lmrs_tpu.analysis.core import Finding, Module, RepoContext

_SCOPE_PREFIXES = ("lmrs_tpu/models/", "lmrs_tpu/ops/", "lmrs_tpu/engine/",
                   "lmrs_tpu/parallel/")

_LAX_HOFS = frozenset(("scan", "while_loop", "cond", "switch", "fori_loop",
                       "associative_scan", "map"))

# dotted-name -> (replacement hint).  The shim module itself is exempt.
_DEPRECATED = {
    "jax.shard_map": "use lmrs_tpu.utils.jax_compat.shard_map (the pinned "
                     "0.4.x build has no jax.shard_map — AttributeError "
                     "at call time)",
    "jax.experimental.shard_map": "import via lmrs_tpu.utils.jax_compat."
                                  "shard_map (one bridge for both jax "
                                  "generations)",
    "pltpu.CompilerParams": "use lmrs_tpu.utils.jax_compat."
                            "tpu_compiler_params (named TPUCompilerParams "
                            "on the pinned 0.4.x build)",
    "jax.tree_map": "use jax.tree.map (removed from the jax namespace)",
    "jax.tree_multimap": "use jax.tree.map",
    "jax.tree_leaves": "use jax.tree.leaves",
}
_COMPAT_MODULE = "lmrs_tpu/utils/jax_compat.py"


def _dotted(node: ast.AST) -> str:
    parts: list[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
    return ".".join(reversed(parts))


def _static_argnames(call: ast.Call) -> set[str]:
    """Literal static_argnames from a jax.jit / partial(jax.jit, ...) call."""
    for kw in call.keywords:
        if kw.arg in ("static_argnames", "static_argnums"):
            v = kw.value
            if isinstance(v, ast.Constant) and isinstance(v.value, str):
                return {v.value}
            if isinstance(v, (ast.Tuple, ast.List)):
                return {el.value for el in v.elts
                        if isinstance(el, ast.Constant)
                        and isinstance(el.value, str)}
    return set()


def _is_jit_call(call: ast.Call) -> bool:
    name = _dotted(call.func)
    return name in ("jax.jit", "jit")


@dataclass
class _TracedFn:
    fn: ast.FunctionDef
    static: set[str]
    via: str  # "jit" | lax hof name


def _collect_traced(mod: Module) -> list[_TracedFn]:
    """Jitted / lax-traced function defs in a module."""
    out: list[_TracedFn] = []
    # local defs by name per enclosing scope, to resolve Name references
    defs: dict[str, ast.FunctionDef] = {}
    for node in ast.walk(mod.tree):
        if isinstance(node, ast.FunctionDef):
            defs[node.name] = node

    claimed: set[ast.FunctionDef] = set()

    def claim(fn: ast.FunctionDef | None, static: set[str],
              via: str) -> None:
        if fn is not None and fn not in claimed:
            claimed.add(fn)
            out.append(_TracedFn(fn, static, via))

    for node in ast.walk(mod.tree):
        if isinstance(node, ast.FunctionDef):
            for dec in node.decorator_list:
                if isinstance(dec, ast.Call):
                    name = _dotted(dec.func)
                    if name in ("jax.jit", "jit"):
                        claim(node, _static_argnames(dec), "jit")
                    elif name.endswith("partial") and dec.args and \
                            isinstance(dec.args[0], (ast.Attribute,
                                                     ast.Name)) and \
                            _dotted(dec.args[0]) in ("jax.jit", "jit"):
                        claim(node, _static_argnames(dec), "jit")
                elif _dotted(dec) in ("jax.jit", "jit"):
                    claim(node, set(), "jit")
        elif isinstance(node, ast.Call):
            name = _dotted(node.func)
            if _is_jit_call(node) and node.args and \
                    isinstance(node.args[0], ast.Name):
                claim(defs.get(node.args[0].id), _static_argnames(node),
                      "jit")
            leaf = name.rsplit(".", 1)[-1]
            if leaf in _LAX_HOFS and (name.startswith("lax.")
                                      or name.startswith("jax.lax.")):
                for arg in node.args[:2]:
                    if isinstance(arg, ast.Name):
                        claim(defs.get(arg.id), set(), leaf)
    return out


def _taint(fn: ast.FunctionDef, static: set[str]) -> set[str]:
    """Parameter-derived (traced) names: params minus statics, propagated
    through simple assignments (two passes ~= fixpoint for linear code).
    Propagation uses DYNAMIC uses only — ``b, h = q.shape``,
    ``flag = x is None``, and ``n = len(xs)`` produce static Python
    values, not tracers."""
    tainted = {a.arg for a in (fn.args.posonlyargs + fn.args.args
                               + fn.args.kwonlyargs)} - static - {"self"}
    for _ in range(2):
        for node in ast.walk(fn):
            if isinstance(node, ast.Assign):
                if _dynamic_names(node.value, tainted):
                    for t in node.targets:
                        for el in (t.elts if isinstance(
                                t, (ast.Tuple, ast.List)) else [t]):
                            if isinstance(el, ast.Name):
                                tainted.add(el.id)
    return tainted


def _dynamic_names(expr: ast.AST, tainted: set[str]) -> set[str]:
    """Tainted names used DYNAMICALLY in ``expr`` — shape/dtype/ndim/len
    reads, ``is None`` tests, and isinstance checks are static under jit
    and excluded."""
    static_spots: set[int] = set()

    for node in ast.walk(expr):
        if isinstance(node, ast.Attribute) and \
                node.attr in ("shape", "ndim", "dtype", "size") and \
                isinstance(node.value, ast.Name):
            static_spots.add(id(node.value))
        elif isinstance(node, ast.Call) and \
                isinstance(node.func, ast.Name) and \
                node.func.id in ("len", "isinstance", "getattr",
                                 "hasattr", "type"):
            for sub in ast.walk(node):
                if isinstance(sub, ast.Name):
                    static_spots.add(id(sub))
        elif isinstance(node, ast.Compare) and \
                all(isinstance(op, (ast.Is, ast.IsNot))
                    for op in node.ops):
            for sub in ast.walk(node):
                if isinstance(sub, ast.Name):
                    static_spots.add(id(sub))
    return {n.id for n in ast.walk(expr)
            if isinstance(n, ast.Name) and n.id in tainted
            and id(n) not in static_spots}


_SHAPE_MAKERS = frozenset(("zeros", "ones", "full", "empty", "arange",
                           "broadcast_to", "iota"))
_HOST_SYNC_FNS = frozenset(("float", "int", "bool"))


def _mutable_globals(mod: Module) -> set[str]:
    """Module globals some function rebinds via ``global X; X = ...``."""
    out: set[str] = set()
    for node in ast.walk(mod.tree):
        if isinstance(node, ast.Global):
            out.update(node.names)
    return out


def _check_traced_fn(mod: Module, tf: _TracedFn,
                     mutable_globals: set[str],
                     findings: list[Finding]) -> None:
    tainted = _taint(tf.fn, tf.static)
    local_names = set(tainted)
    for node in ast.walk(tf.fn):
        if isinstance(node, (ast.If, ast.While)):
            dyn = _dynamic_names(node.test, tainted)
            if dyn:
                findings.append(Finding(
                    rule="tracing.python-branch-on-traced",
                    path=mod.path, line=node.lineno,
                    message=f"Python `{'while' if isinstance(node, ast.While) else 'if'}` "
                            f"on traced value(s) {', '.join(sorted(dyn))} "
                            f"inside {tf.via}-traced `{tf.fn.name}`",
                    hint="use jnp.where / lax.cond / lax.select, or move "
                         "the branch out of the traced function (mark the "
                         "argument static if it truly is)"))
        elif isinstance(node, ast.Call):
            name = _dotted(node.func)
            leaf = name.rsplit(".", 1)[-1]
            arg_dyn = set()
            for arg in node.args:
                arg_dyn |= _dynamic_names(arg, tainted)
            if leaf == "item" and isinstance(node.func, ast.Attribute):
                base_dyn = _dynamic_names(node.func.value, tainted)
                if base_dyn:
                    findings.append(Finding(
                        rule="tracing.host-sync-in-jit",
                        path=mod.path, line=node.lineno,
                        message=f".item() on traced value inside "
                                f"{tf.via}-traced `{tf.fn.name}`",
                        hint="keep the value on device (jnp ops), or "
                             "return it and sync outside the jit"))
            elif (name in _HOST_SYNC_FNS or name in ("np.asarray",
                                                     "np.array",
                                                     "numpy.asarray",
                                                     "jax.device_get")) \
                    and arg_dyn:
                findings.append(Finding(
                    rule="tracing.host-sync-in-jit",
                    path=mod.path, line=node.lineno,
                    message=f"{name}() forces a host sync on traced "
                            f"value(s) {', '.join(sorted(arg_dyn))} inside "
                            f"{tf.via}-traced `{tf.fn.name}`",
                    hint="jnp equivalents stay on device; host conversion "
                         "belongs outside the traced function"))
            elif leaf in _SHAPE_MAKERS and node.args:
                # broadcast_to(arr, shape): the shape is the SECOND arg
                idx = 1 if leaf == "broadcast_to" else 0
                if len(node.args) <= idx:
                    continue
                shape_arg = node.args[idx]
                dyn = _dynamic_names(shape_arg, tainted)
                if dyn:
                    findings.append(Finding(
                        rule="tracing.dynamic-shape-in-jit",
                        path=mod.path, line=node.lineno,
                        message=f"traced value(s) {', '.join(sorted(dyn))} "
                                f"used as a shape in {leaf}() inside "
                                f"{tf.via}-traced `{tf.fn.name}`",
                        hint="shapes must be Python ints under jit — pad "
                             "to a bucket or hoist the shape computation; "
                             "every distinct value recompiles"))
            elif name == "range" and arg_dyn:
                findings.append(Finding(
                    rule="tracing.dynamic-shape-in-jit",
                    path=mod.path, line=node.lineno,
                    message=f"range() over traced value(s) "
                            f"{', '.join(sorted(arg_dyn))} inside "
                            f"{tf.via}-traced `{tf.fn.name}`",
                    hint="use lax.fori_loop / lax.scan for traced trip "
                         "counts"))
        elif isinstance(node, ast.Name) and isinstance(node.ctx, ast.Load):
            if node.id in mutable_globals and node.id not in local_names:
                findings.append(Finding(
                    rule="tracing.jit-closes-over-mutable-global",
                    path=mod.path, line=node.lineno,
                    message=f"{tf.via}-traced `{tf.fn.name}` reads module "
                            f"global {node.id}, which is rebound elsewhere "
                            "(`global` statement): jit baked the first-"
                            "trace value",
                    hint="pass the value as an argument (static or "
                         "traced) instead of closing over it"))


def _check_deprecated(mod: Module, findings: list[Finding]) -> None:
    if mod.path == _COMPAT_MODULE:
        return
    for node in ast.walk(mod.tree):
        name = None
        line = getattr(node, "lineno", 1)
        if isinstance(node, ast.Attribute):
            name = _dotted(node)
        elif isinstance(node, ast.ImportFrom) and node.module:
            name = node.module
        elif isinstance(node, ast.Import):
            for alias in node.names:
                if alias.name in _DEPRECATED:
                    name = alias.name
        if name in _DEPRECATED:
            findings.append(Finding(
                rule="tracing.deprecated-api",
                path=mod.path, line=line,
                message=f"deprecated/moved JAX API `{name}`",
                hint=_DEPRECATED[name]))


def run(ctx: RepoContext) -> list[Finding]:
    findings: list[Finding] = []
    for mod in ctx.modules:
        _check_deprecated(mod, findings)
        if not (mod.path.startswith(_SCOPE_PREFIXES)
                or mod.path.startswith("fixtures/")):
            continue
        mg = _mutable_globals(mod)
        for tf in _collect_traced(mod):
            _check_traced_fn(mod, tf, mg, findings)
    return findings
