"""`lmrs-lint`: repo-native static analysis (docs/ANALYSIS.md).

Four AST-based pass families over the production tree:

* **race** (`locks.py`) — lock discipline learned from ``# guarded-by:``
  annotations: unguarded writes to guarded state, lock-acquisition-order
  cycles, locks held across blocking calls;
* **tracing** (`tracing.py`) — JAX tracing hazards in jitted/scanned code
  (Python branching on traced values, host syncs, dynamic shapes, mutable
  closures) plus the deprecated-API sub-pass;
* **drift** (`drift.py`) — code-vs-docs contract drift: fault-injection
  sites vs docs/ROBUSTNESS.md, ``lmrs_*`` metric names vs
  docs/OBSERVABILITY.md, trace-instant args vs ``validate_trace_events``;
* **env** (`envpass.py`) — every ``LMRS_*`` env read must route through
  ``lmrs_tpu.utils.env`` and appear in docs/KNOBS.md.

Entry points: the ``lmrs-lint`` console script / ``python -m
lmrs_tpu.analysis`` (CI gate), or :func:`run_repo` programmatically.
"""

from lmrs_tpu.analysis.core import (Baseline, Finding, Module, RepoContext,
                                    run_passes, run_repo)

__all__ = ["Baseline", "Finding", "Module", "RepoContext", "run_passes",
           "run_repo"]
