"""Code-vs-docs contract drift checkers (family ``drift``).

Serving contracts live half in code, half in documentation consumers
read: the fault-site table in docs/ROBUSTNESS.md, the metric catalog in
docs/OBSERVABILITY.md, and the trace-instant arg contract in
``obs.trace.validate_trace_events``.  Every PR since the fault plane has
needed a by-hand reconciliation round; these checks make the drift a CI
failure instead:

* ``drift.fault-site-undocumented`` / ``drift.fault-site-stale`` —
  ``faults.fire("x.y")`` / ``faults.check`` sites vs the ROBUSTNESS.md
  site table;
* ``drift.metric-undocumented`` / ``drift.metric-stale`` — registered
  ``lmrs_*`` counter/gauge/histogram names vs the OBSERVABILITY.md
  catalog table (rows must spell FULL metric names — suffix shorthand
  like ``_hits_total`` is itself flagged);
* ``drift.trace-instant-args`` — every ``tracer.instant("name", ...)``
  emit site whose name carries a contract in
  ``_INSTANT_REQUIRED_ARGS`` must pass the required keys in a literal
  ``args={...}`` dict (the stitcher's skew anchors and the postmortem
  reader parse them).
"""

from __future__ import annotations

import ast
import re

from lmrs_tpu.analysis.core import Finding, RepoContext

ROBUSTNESS_DOC = "docs/ROBUSTNESS.md"
OBSERVABILITY_DOC = "docs/OBSERVABILITY.md"

_SITE_RE = re.compile(r"^[a-z_]+\.[a-z_.]+$")
_METRIC_RE = re.compile(r"^lmrs_[a-z0-9_]+$")
_TABLE_CELL_TOKENS = re.compile(r"`([^`]+)`")


def _table_tokens(doc_text: str, pattern: re.Pattern) -> dict[str, int]:
    """Backticked tokens matching ``pattern`` inside markdown TABLE rows,
    token -> first line number (1-based)."""
    out: dict[str, int] = {}
    for i, line in enumerate(doc_text.splitlines(), start=1):
        if not line.lstrip().startswith("|"):
            continue
        for tok in _TABLE_CELL_TOKENS.findall(line):
            tok = tok.strip()
            if pattern.match(tok):
                out.setdefault(tok, i)
    return out


def _dotted(node: ast.AST) -> str:
    parts: list[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
    return ".".join(reversed(parts))


# ------------------------------------------------------------- fault sites

def _code_fault_sites(ctx: RepoContext) -> dict[str, tuple[str, int]]:
    sites: dict[str, tuple[str, int]] = {}
    for mod in ctx.modules:
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.Call):
                continue
            name = _dotted(node.func)
            if name.endswith(("faults.fire", "faults.check")) and \
                    node.args and isinstance(node.args[0], ast.Constant) \
                    and isinstance(node.args[0].value, str):
                sites.setdefault(node.args[0].value,
                                 (mod.path, node.lineno))
    return sites


def _check_fault_sites(ctx: RepoContext, findings: list[Finding]) -> None:
    code = _code_fault_sites(ctx)
    doc_text = ctx.doc(ROBUSTNESS_DOC)
    doc = _table_tokens(doc_text, _SITE_RE)
    for site, (path, line) in sorted(code.items()):
        if site not in doc:
            findings.append(Finding(
                rule="drift.fault-site-undocumented", path=path, line=line,
                message=f"fault site {site!r} has no row in the "
                        f"{ROBUSTNESS_DOC} site table",
                hint="add a `| `site` | fires as | exercises |` row — "
                     "chaos plans are written against that table"))
    for site, line in sorted(doc.items()):
        if site not in code:
            findings.append(Finding(
                rule="drift.fault-site-stale", path=ROBUSTNESS_DOC,
                line=line,
                message=f"documented fault site {site!r} no longer exists "
                        "in code",
                hint="delete the stale row (or restore the site)"))


# ----------------------------------------------------------------- metrics

_REGISTER_METHODS = frozenset(("counter", "gauge", "histogram"))


def _register_aliases(mod_tree: ast.Module) -> set[str]:
    """Local names bound to registry register methods — the repo's
    ``c, g, h = (reg.counter, reg.gauge, reg.histogram)`` idiom."""
    aliases: set[str] = set()
    for node in ast.walk(mod_tree):
        if not isinstance(node, ast.Assign) or len(node.targets) != 1:
            continue
        tgt, val = node.targets[0], node.value
        pairs: list[tuple[ast.expr, ast.expr]] = []
        if isinstance(tgt, ast.Tuple) and isinstance(val, ast.Tuple) and \
                len(tgt.elts) == len(val.elts):
            pairs = list(zip(tgt.elts, val.elts))
        else:
            pairs = [(tgt, val)]
        for t, v in pairs:
            if isinstance(t, ast.Name) and isinstance(v, ast.Attribute) \
                    and v.attr in _REGISTER_METHODS:
                aliases.add(t.id)
    return aliases


def _code_metrics(ctx: RepoContext) -> dict[str, tuple[str, int]]:
    metrics: dict[str, tuple[str, int]] = {}
    for mod in ctx.modules:
        aliases = _register_aliases(mod.tree)
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.Call):
                continue
            fn = node.func
            is_reg = (isinstance(fn, ast.Attribute)
                      and fn.attr in _REGISTER_METHODS) or \
                     (isinstance(fn, ast.Name) and fn.id in aliases)
            if is_reg and node.args and \
                    isinstance(node.args[0], ast.Constant) and \
                    isinstance(node.args[0].value, str) and \
                    node.args[0].value.startswith("lmrs_"):
                metrics.setdefault(node.args[0].value,
                                   (mod.path, node.lineno))
    return metrics


def _check_metrics(ctx: RepoContext, findings: list[Finding]) -> None:
    code = _code_metrics(ctx)
    doc_text = ctx.doc(OBSERVABILITY_DOC)
    doc = _table_tokens(doc_text, _METRIC_RE)
    # suffix shorthand (a backticked `_hits_total` cell) defeats exact
    # matching — flag it so the catalog stays machine-checkable.
    # Histogram CHILD suffixes (`_sum`/`_count`/`_bucket`) are Prometheus
    # series the exposition derives, not registered names: legit prose.
    for i, line in enumerate(doc_text.splitlines(), start=1):
        if not line.lstrip().startswith("|"):
            continue
        for tok in _TABLE_CELL_TOKENS.findall(line):
            if tok.strip() in ("_sum", "_count", "_bucket"):
                continue
            if re.match(r"^_[a-z0-9_]+$", tok.strip()):
                findings.append(Finding(
                    rule="drift.metric-suffix-shorthand",
                    path=OBSERVABILITY_DOC, line=i,
                    message=f"catalog row abbreviates a metric name as "
                            f"`{tok.strip()}`",
                    hint="spell the full lmrs_* name — the drift checker "
                         "(and grep) match exact names"))
    for name, (path, line) in sorted(code.items()):
        if name not in doc:
            findings.append(Finding(
                rule="drift.metric-undocumented", path=path, line=line,
                message=f"metric {name!r} is registered but missing from "
                        f"the {OBSERVABILITY_DOC} catalog",
                hint="add a catalog row (type/unit/lifetime/meaning) — "
                     "dashboards are built from that table"))
    for name, line in sorted(doc.items()):
        if name not in code:
            findings.append(Finding(
                rule="drift.metric-stale", path=OBSERVABILITY_DOC,
                line=line,
                message=f"catalogued metric {name!r} is not registered "
                        "anywhere in code",
                hint="delete the stale row (or restore the metric)"))


# ----------------------------------------------------------- trace instants

def _required_instant_args() -> dict[str, tuple[str, ...]]:
    from lmrs_tpu.obs.trace import _INSTANT_REQUIRED_ARGS

    return dict(_INSTANT_REQUIRED_ARGS)


def _check_trace_instants(ctx: RepoContext, findings: list[Finding]) -> None:
    contract = _required_instant_args()
    for mod in ctx.modules:
        for node in ast.walk(mod.tree):
            if not (isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Attribute)
                    and node.func.attr == "instant" and node.args
                    and isinstance(node.args[0], ast.Constant)
                    and isinstance(node.args[0].value, str)):
                continue
            name = node.args[0].value
            want = contract.get(name)
            if want is None:
                continue
            args_kw = next((kw for kw in node.keywords
                            if kw.arg == "args"), None)
            if args_kw is None:
                findings.append(Finding(
                    rule="drift.trace-instant-args", path=mod.path,
                    line=node.lineno,
                    message=f"`{name}` instant emitted without args "
                            f"(contract requires {', '.join(want)})",
                    hint="validate_trace_events rejects the trace; "
                         "downstream readers parse these keys"))
                continue
            if not isinstance(args_kw.value, ast.Dict):
                continue  # built dynamically: can't verify statically
            keys = {k.value for k in args_kw.value.keys
                    if isinstance(k, ast.Constant)
                    and isinstance(k.value, str)}
            missing = [k for k in want if k not in keys]
            if missing:
                findings.append(Finding(
                    rule="drift.trace-instant-args", path=mod.path,
                    line=node.lineno,
                    message=f"`{name}` instant missing contract arg(s) "
                            f"{', '.join(missing)} "
                            f"(validate_trace_events requires "
                            f"{', '.join(want)})",
                    hint="add the key(s) to the args dict — the CI trace "
                         "gate fails the emitted trace otherwise"))


def run(ctx: RepoContext) -> list[Finding]:
    findings: list[Finding] = []
    _check_fault_sites(ctx, findings)
    _check_metrics(ctx, findings)
    _check_trace_instants(ctx, findings)
    return findings
