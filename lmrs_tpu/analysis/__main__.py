"""``python -m lmrs_tpu.analysis`` == the ``lmrs-lint`` console script."""

import sys

from lmrs_tpu.analysis.cli import main

sys.exit(main())
