"""``LMRS_*`` environment-knob discipline (family ``env``).

The repo's env surface is part of its serving contract, and ad-hoc
parsing produced real outages (NaN profiler duration, ``""`` disabling
the postmortem throttle, ``LMRS_FLASH_BLOCK=""`` crashing module import).
Two rules keep the class extinct:

* ``env.direct-read`` — ``os.environ``/``os.getenv`` access to an
  ``LMRS_*`` name anywhere outside ``lmrs_tpu/utils/env.py``: the knob
  bypasses the validated parser (empty-string-means-default, finite
  guard, bounds clamp, warn-once);
* ``env.knob-undocumented`` / ``env.knob-stale`` — every knob read
  through the parser (``env_str``/``env_bool``/``env_int``/``env_float``/
  ``env_list``, or a config ``_env`` field default) must have a row in
  the docs/KNOBS.md master table, and every documented knob must still
  be read somewhere.
"""

from __future__ import annotations

import ast
import re

from lmrs_tpu.analysis.core import Finding, RepoContext

KNOBS_DOC = "docs/KNOBS.md"
ENV_MODULE = "lmrs_tpu/utils/env.py"

_HELPERS = frozenset(("env_str", "env_bool", "env_int", "env_float",
                      "env_list", "_env"))
_KNOB_RE = re.compile(r"^LMRS_[A-Z0-9_]+$")
_TABLE_CELL_TOKENS = re.compile(r"`([^`]+)`")


def _dotted(node: ast.AST) -> str:
    parts: list[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
    return ".".join(reversed(parts))


def _const_knob(node: ast.expr) -> str | None:
    if isinstance(node, ast.Constant) and isinstance(node.value, str) \
            and _KNOB_RE.match(node.value):
        return node.value
    return None


def _check_direct_reads(ctx: RepoContext, findings: list[Finding],
                        reads: dict[str, tuple[str, int]]) -> None:
    for mod in ctx.modules:
        if mod.path == ENV_MODULE:
            continue
        for node in ast.walk(mod.tree):
            knob = None
            if isinstance(node, ast.Call):
                name = _dotted(node.func)
                if name in ("os.environ.get", "os.getenv") and node.args:
                    knob = _const_knob(node.args[0])
                elif name.rsplit(".", 1)[-1] in _HELPERS and node.args:
                    k = _const_knob(node.args[0])
                    if k:
                        reads.setdefault(k, (mod.path, node.lineno))
                    continue
            elif isinstance(node, ast.Subscript) and \
                    _dotted(node.value) == "os.environ":
                knob = _const_knob(node.slice)
            if knob:
                reads.setdefault(knob, (mod.path, node.lineno))
                findings.append(Finding(
                    rule="env.direct-read", path=mod.path,
                    line=node.lineno,
                    message=f"direct os.environ read of {knob} bypasses "
                            "the validated parser",
                    hint="route through lmrs_tpu.utils.env (env_str/"
                         "env_bool/env_int/env_float/env_list): empty-"
                         "means-default, finite guard, bounds, warn-once"))


def _doc_knobs(ctx: RepoContext) -> dict[str, int]:
    out: dict[str, int] = {}
    for i, line in enumerate(ctx.doc(KNOBS_DOC).splitlines(), start=1):
        if not line.lstrip().startswith("|"):
            continue
        for tok in _TABLE_CELL_TOKENS.findall(line):
            tok = tok.strip().split("=", 1)[0]
            if _KNOB_RE.match(tok):
                out.setdefault(tok, i)
    return out


def run(ctx: RepoContext) -> list[Finding]:
    findings: list[Finding] = []
    reads: dict[str, tuple[str, int]] = {}
    _check_direct_reads(ctx, findings, reads)
    doc = _doc_knobs(ctx)
    for knob, (path, line) in sorted(reads.items()):
        if knob not in doc:
            findings.append(Finding(
                rule="env.knob-undocumented", path=path, line=line,
                message=f"env knob {knob} is read but has no row in "
                        f"{KNOBS_DOC}",
                hint="add it to the master knob table (default, range, "
                     "meaning) — operators discover knobs there"))
    for knob, line in sorted(doc.items()):
        if knob not in reads:
            findings.append(Finding(
                rule="env.knob-stale", path=KNOBS_DOC, line=line,
                message=f"documented knob {knob} is never read in code",
                hint="delete the stale row (or restore the read)"))
    return findings
