"""Lock-discipline / race detector (pass family ``race``).

The repo declares its guarded-state conventions inline:

* ``self._pinned: dict = {}  # guarded-by: _pinned_lock`` on an attribute's
  defining line (usually ``__init__``) marks every later WRITE to that
  attribute as requiring ``with self._pinned_lock:``;
* ``GLOBAL = {}  # guarded-by: _some_lock`` does the same for module-level
  state and module-level locks;
* ``# holds-lock: _pinned_lock`` on (or directly above) a ``def`` line
  declares that the method is only ever called with the lock already held
  (the "Caller holds self._lock" docstring convention, machine-readable).

Checks:

* ``race.unguarded-write`` — assignment/augmented-assignment/``del``/known
  mutator-method call on a guarded attribute outside the owning ``with``;
* ``race.lock-order-cycle`` — the acquisition-order graph (lock A held
  while B is taken, lexically or via a same-class method call one level
  deep) contains a cycle: two threads taking the locks in opposite orders
  can deadlock;
* ``race.blocking-under-lock`` — a known-blocking call (sleep, fsync,
  socket/HTTP I/O, device fetches) while any declared lock is held: every
  other thread needing that lock now waits on the disk/wire/device.

Reads are deliberately NOT flagged: the repo's idiom allows GIL-atomic
snapshot reads of guarded dicts/counters, and flagging them would bury
the write races this pass exists for.
"""

from __future__ import annotations

import ast
import re
from dataclasses import dataclass, field

from lmrs_tpu.analysis.core import Finding, Module, RepoContext

_GUARDED_RE = re.compile(r"#\s*guarded-by:\s*([A-Za-z_]\w*)")
_HOLDS_RE = re.compile(r"#\s*holds-lock:\s*([A-Za-z_]\w*(?:\s*,\s*"
                       r"[A-Za-z_]\w*)*)")

# method names that mutate their receiver in place — a call on a guarded
# attribute counts as a write
_MUTATORS = frozenset((
    "append", "appendleft", "extend", "insert", "pop", "popleft",
    "popitem", "remove", "discard", "clear", "update", "add",
    "setdefault", "sort", "reverse",
))

# call names (dotted suffixes) that block the calling thread
_BLOCKING = frozenset((
    "time.sleep", "os.fsync", "os.fdatasync", "jax.device_get",
    "socket.create_connection", "select.select", "subprocess.run",
))
_BLOCKING_METHODS = frozenset((
    "getresponse", "fsync", "device_get", "_timed_get", "block_until_ready",
    "urlopen", "recv", "accept", "sleep",
))


def _dotted(node: ast.AST) -> str:
    """Best-effort dotted name of a call target ('' when dynamic)."""
    parts: list[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
    return ".".join(reversed(parts))


@dataclass
class _Scope:
    """Guarded-state declarations of one class (or the module, name='')."""

    name: str
    guarded: dict[str, tuple[str, int]] = field(default_factory=dict)
    # attr -> (lock name, decl line)
    locks: set[str] = field(default_factory=set)


def _collect_scopes(mod: Module) -> dict[str, _Scope]:
    """Parse guarded-by annotations: scope name ('' = module level) ->
    declarations.  The annotated line must define ``self.<attr>`` (class
    scope) or ``NAME = ...`` (module scope)."""
    scopes: dict[str, _Scope] = {"": _Scope("")}

    class_ranges: list[tuple[str, int, int]] = []
    for node in ast.walk(mod.tree):
        if isinstance(node, ast.ClassDef):
            end = max((getattr(n, "end_lineno", node.lineno)
                       for n in ast.walk(node)), default=node.lineno)
            class_ranges.append((node.name, node.lineno, end))
            scopes.setdefault(node.name, _Scope(node.name))

    def scope_at(lineno: int) -> _Scope:
        best = None
        for name, lo, hi in class_ranges:
            if lo <= lineno <= hi and (best is None or lo > best[1]):
                best = (name, lo)
        return scopes[best[0]] if best else scopes[""]

    for i, text in enumerate(mod.lines, start=1):
        m = _GUARDED_RE.search(text)
        if not m:
            continue
        lock = m.group(1)
        sc = scope_at(i)

        def attr_on(line_text: str, scope: _Scope):
            return (re.search(r"\bself\.(\w+)", line_text) if scope.name
                    else re.match(r"\s*(\w+)\s*[:=]", line_text))

        attr_m = attr_on(text, sc)
        decl_line = i
        if attr_m is None and text.strip().startswith("#"):
            # standalone-comment form: the annotation sits on its own
            # line directly ABOVE the attribute's defining line (used
            # when the defining line is too long to carry a trailer)
            nxt = mod.line_text(i + 1)
            sc = scope_at(i + 1)
            attr_m = attr_on(nxt, sc)
            decl_line = i + 1
        if attr_m:
            sc.guarded[attr_m.group(1)] = (lock, decl_line)
            sc.locks.add(lock)

    # every lock-object construction is a known lock too (the order/
    # blocking checks must see locks that guard nothing declared)
    for node in ast.walk(mod.tree):
        if isinstance(node, ast.Assign) and isinstance(node.value, ast.Call):
            ctor = node.value.func
            name = ctor.attr if isinstance(ctor, ast.Attribute) else (
                ctor.id if isinstance(ctor, ast.Name) else "")
            if name not in ("Lock", "RLock", "Condition", "Semaphore",
                            "BoundedSemaphore"):
                continue
            for t in node.targets:
                if isinstance(t, ast.Attribute) and \
                        isinstance(t.value, ast.Name) and \
                        t.value.id == "self":
                    scope_at(node.lineno).locks.add(t.attr)
                elif isinstance(t, ast.Name):
                    scopes[""].locks.add(t.id)
    return scopes


def _holds_locks(mod: Module, fn: ast.FunctionDef | ast.AsyncFunctionDef
                 ) -> set[str]:
    """Locks declared held on entry via ``# holds-lock:`` anywhere on the
    (possibly multi-line) def signature or the line directly above it."""
    out: set[str] = set()
    sig_end = fn.body[0].lineno if fn.body else fn.lineno + 1
    for lineno in range(fn.lineno - 1, sig_end):
        m = _HOLDS_RE.search(mod.line_text(lineno))
        if m:
            out |= {tok.strip() for tok in m.group(1).split(",")}
    return out


def _lock_name(item: ast.expr) -> str | None:
    """The lock behind a ``with`` item: ``self.<name>`` or a bare module
    global ``<name>`` that LOOKS like a lock (``*lock*`` in the name) or
    is declared one via guarded-by."""
    if isinstance(item, ast.Attribute) and isinstance(item.value, ast.Name) \
            and item.value.id in ("self", "cls"):
        return item.attr
    if isinstance(item, ast.Name):
        return item.id
    return None


class _FunctionWalker(ast.NodeVisitor):
    """Walks one function body tracking the held-lock set."""

    def __init__(self, mod: Module, scope: _Scope, module_scope: _Scope,
                 known_locks: set[str], findings: list[Finding],
                 edges: list[tuple[str, str, str, int]],
                 acquires: dict[str, set[str]], fn_name: str,
                 held: set[str]):
        self.mod = mod
        self.scope = scope
        self.module_scope = module_scope
        self.known_locks = known_locks
        self.findings = findings
        self.edges = edges          # (lock_a, lock_b, path, line)
        self.acquires = acquires    # method name -> locks it takes directly
        self.fn_name = fn_name
        self.held: list[str] = list(held)

    # -------------------------------------------------------- with / locks

    def visit_With(self, node: ast.With) -> None:
        taken: list[str] = []
        for item in node.items:
            name = _lock_name(item.context_expr)
            if name and name in self.known_locks:
                for h in self.held:
                    if h != name:
                        self.edges.append((self._qual(h), self._qual(name),
                                           self.mod.path, item.context_expr
                                           .lineno))
                self.acquires.setdefault(self.fn_name, set()).add(name)
                taken.append(name)
        self.held.extend(taken)
        for stmt in node.body:
            self.visit(stmt)
        for _ in taken:
            self.held.pop()
        # with-item expressions themselves (rare) are not revisited

    visit_AsyncWith = visit_With  # type: ignore[assignment]

    def _qual(self, lock: str) -> str:
        owner = self.scope.name if lock in self.scope.locks else ""
        prefix = f"{self.mod.path}:{owner}" if owner else self.mod.path
        return f"{prefix}.{lock}"

    # ------------------------------------------------------------- writes

    def _check_write(self, attr: str, lineno: int, what: str) -> None:
        decl = self.scope.guarded.get(attr)
        scope = self.scope
        if decl is None:
            decl = self.module_scope.guarded.get(attr)
            scope = self.module_scope
        if decl is None:
            return
        lock, decl_line = decl
        if lock in self.held:
            return
        # the declaration LINE goes in the hint, not the message: the
        # message is the baseline identity and must survive line shifts
        self.findings.append(Finding(
            rule="race.unguarded-write",
            path=self.mod.path, line=lineno,
            message=f"{what} to {scope.name + '.' if scope.name else ''}"
                    f"{attr} outside `with {lock}:`",
            hint=f"guarded-by declared at line {decl_line}; hold {lock} "
                 f"for the write, or mark the enclosing function "
                 f"`# holds-lock: {lock}` if every caller already holds "
                 "it"))

    def _write_target(self, node: ast.expr, lineno: int, what: str) -> None:
        # unwrap subscripts: self.d[k] = v mutates self.d
        while isinstance(node, ast.Subscript):
            node = node.value
        if isinstance(node, ast.Attribute) and \
                isinstance(node.value, ast.Name) and \
                node.value.id in ("self", "cls"):
            self._check_write(node.attr, lineno, what)
        elif isinstance(node, ast.Name):
            if node.id in self.module_scope.guarded:
                self._check_write(node.id, lineno, what)

    def visit_Assign(self, node: ast.Assign) -> None:
        for t in node.targets:
            for el in (t.elts if isinstance(t, (ast.Tuple, ast.List))
                       else [t]):
                self._write_target(el, node.lineno, "assignment")
        self.generic_visit(node)

    def visit_AugAssign(self, node: ast.AugAssign) -> None:
        self._write_target(node.target, node.lineno,
                           "read-modify-write (+=)")
        self.generic_visit(node)

    def visit_AnnAssign(self, node: ast.AnnAssign) -> None:
        if node.value is not None:
            self._write_target(node.target, node.lineno, "assignment")
        self.generic_visit(node)

    def visit_Delete(self, node: ast.Delete) -> None:
        for t in node.targets:
            self._write_target(t, node.lineno, "del")
        self.generic_visit(node)

    # -------------------------------------------------------------- calls

    def visit_Call(self, node: ast.Call) -> None:
        func = node.func
        # mutator-method write: self.attr.append(...)
        if isinstance(func, ast.Attribute) and func.attr in _MUTATORS:
            base = func.value
            while isinstance(base, ast.Subscript):
                base = base.value
            if isinstance(base, ast.Attribute) and \
                    isinstance(base.value, ast.Name) and \
                    base.value.id in ("self", "cls"):
                self._check_write(base.attr, node.lineno,
                                  f".{func.attr}() mutation")
            elif isinstance(base, ast.Name) and \
                    base.id in self.module_scope.guarded:
                self._check_write(base.id, node.lineno,
                                  f".{func.attr}() mutation")
        # blocking call while a lock is held
        if self.held:
            dotted = _dotted(func)
            leaf = dotted.rsplit(".", 1)[-1]
            if dotted in _BLOCKING or leaf in _BLOCKING_METHODS:
                self.findings.append(Finding(
                    rule="race.blocking-under-lock",
                    path=self.mod.path, line=node.lineno,
                    message=f"blocking call {dotted or leaf}() while "
                            f"holding {', '.join(self.held)}",
                    hint="move the I/O outside the critical section (copy "
                         "under the lock, act after), or suppress with "
                         "`# lint: ignore[race.blocking-under-lock]` if "
                         "serializing on the I/O is the point"))
        # same-class call edges: self.m() while holding A -> A precedes
        # every lock m() takes directly (one level, resolved by run())
        if isinstance(func, ast.Attribute) and \
                isinstance(func.value, ast.Name) and func.value.id == "self" \
                and self.held:
            self.edges.append(("__call__:" + func.attr,
                               ",".join(self._qual(h) for h in self.held),
                               self.mod.path, node.lineno))
        self.generic_visit(node)

    # nested defs run with an EMPTY held set (they execute later, not here)
    def visit_FunctionDef(self, node: ast.FunctionDef) -> None:
        saved, self.held = self.held, []
        for stmt in node.body:
            self.visit(stmt)
        self.held = saved

    visit_AsyncFunctionDef = visit_FunctionDef  # type: ignore[assignment]
    visit_Lambda = lambda self, node: None  # noqa: E731 - no statements


def _find_cycles(graph: dict[str, set[str]]) -> list[list[str]]:
    """Simple cycles in the acquisition-order digraph (bounded DFS)."""
    cycles: list[list[str]] = []
    seen_keys: set[tuple[str, ...]] = set()
    for start in sorted(graph):
        stack = [(start, [start])]
        while stack:
            node, path = stack.pop()
            for nxt in sorted(graph.get(node, ())):
                if nxt == start and len(path) > 1:
                    rot = min(range(len(path)),
                              key=lambda i: path[i])
                    key = tuple(path[rot:] + path[:rot])
                    if key not in seen_keys:
                        seen_keys.add(key)
                        cycles.append(path + [start])
                elif nxt not in path and len(path) < 6:
                    stack.append((nxt, path + [nxt]))
    return cycles


def run(ctx: RepoContext) -> list[Finding]:
    findings: list[Finding] = []
    edges: list[tuple[str, str, str, int]] = []
    call_edges: list[tuple[str, str, str, int]] = []

    for mod in ctx.modules:
        scopes = _collect_scopes(mod)
        module_scope = scopes[""]
        known_locks = set().union(*(s.locks for s in scopes.values()))
        if not known_locks:
            continue
        acquires: dict[str, set[str]] = {}
        raw_edges: list[tuple[str, str, str, int]] = []

        def walk_class(cls_name: str, body: list[ast.stmt]) -> None:
            scope = scopes.get(cls_name, module_scope)
            for node in body:
                if isinstance(node, (ast.FunctionDef,
                                     ast.AsyncFunctionDef)):
                    if node.name in ("__init__", "__new__"):
                        continue  # construction precedes sharing
                    held = _holds_locks(mod, node)
                    w = _FunctionWalker(mod, scope, module_scope,
                                        known_locks, findings, raw_edges,
                                        acquires, node.name, held)
                    for stmt in node.body:
                        w.visit(stmt)

        for node in mod.tree.body:
            if isinstance(node, ast.ClassDef):
                walk_class(node.name, node.body)
        walk_class("", [n for n in mod.tree.body
                        if isinstance(n, (ast.FunctionDef,
                                          ast.AsyncFunctionDef))])

        # resolve one level of same-class call edges: holding A and
        # calling self.m() orders A before every lock m() takes directly
        for a, b, path, line in raw_edges:
            if a.startswith("__call__:"):
                method = a.split(":", 1)[1]
                helds = b.split(",")
                for lock in acquires.get(method, ()):  # direct only
                    q = (f"{path}:" + next(
                        (s.name for s in scopes.values()
                         if lock in s.locks and s.name), "")).rstrip(":") \
                        + f".{lock}"
                    for h in helds:
                        if h != q:
                            edges.append((h, q, path, line))
            else:
                edges.append((a, b, path, line))

    graph: dict[str, set[str]] = {}
    locs: dict[tuple[str, str], tuple[str, int]] = {}
    for a, b, path, line in edges:
        graph.setdefault(a, set()).add(b)
        locs.setdefault((a, b), (path, line))
    for cycle in _find_cycles(graph):
        first = locs.get((cycle[0], cycle[1]), ("?", 1))
        findings.append(Finding(
            rule="race.lock-order-cycle",
            path=first[0], line=first[1],
            message="lock acquisition order cycle: "
                    + " -> ".join(c.rsplit(".", 1)[-1] for c in cycle)
                    + " (full: " + " -> ".join(cycle) + ")",
            hint="pick one global order for these locks and release "
                 "before acquiring against it"))
    _ = call_edges
    return findings
