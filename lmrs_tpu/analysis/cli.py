"""``lmrs-lint`` — run the repo's static-analysis passes (docs/ANALYSIS.md).

Exit status: 0 when no NEW findings (baseline-accepted ones don't fail;
expired baseline entries print as warnings), 1 when new findings exist,
2 on usage errors.
"""

from __future__ import annotations

import argparse
import json
import sys
from pathlib import Path

from lmrs_tpu.analysis.core import (Baseline, RepoContext, find_repo_root,
                                    run_passes)

FAMILIES = ("race", "tracing", "drift", "env")


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="lmrs-lint",
        description="repo-native static analysis: lock discipline / race "
                    "detection, JAX tracing hazards, contract drift, and "
                    "LMRS_* env discipline")
    p.add_argument("root", nargs="?", default=None,
                   help="repo root to scan (default: auto-detected from "
                        "cwd)")
    p.add_argument("--baseline", default=None, metavar="FILE",
                   help="baseline file (default: <root>/lint-baseline.json)")
    p.add_argument("--write-baseline", action="store_true",
                   help="accept the current findings: rewrite the baseline "
                        "to exactly this run's findings and exit 0")
    p.add_argument("--family", action="append", choices=FAMILIES,
                   dest="families", metavar="FAMILY",
                   help="run only this pass family (repeatable; default: "
                        "all)")
    p.add_argument("--json", action="store_true",
                   help="machine-readable output")
    p.add_argument("--no-baseline", action="store_true",
                   help="report every finding, baseline ignored")
    return p


def main(argv: list[str] | None = None) -> int:
    args = build_parser().parse_args(argv)
    root = Path(args.root).resolve() if args.root else find_repo_root()
    if not (root / "lmrs_tpu").is_dir():
        print(f"lmrs-lint: {root} does not look like the repo root "
              "(no lmrs_tpu/)", file=sys.stderr)
        return 2
    ctx = RepoContext.load(root)
    families = tuple(args.families) if args.families else FAMILIES
    findings = run_passes(ctx, families)

    baseline_path = Path(args.baseline) if args.baseline \
        else root / "lint-baseline.json"
    if args.write_baseline:
        if args.families:
            # a subset run would overwrite the ENTIRE baseline, silently
            # discarding the families that did not run
            print("lmrs-lint: --write-baseline requires a full run "
                  "(drop --family)", file=sys.stderr)
            return 2
        Baseline.from_findings(findings).save(baseline_path)
        print(f"lmrs-lint: baseline written to {baseline_path} "
              f"({len(findings)} accepted finding(s))")
        return 0
    if args.no_baseline:
        new, accepted, expired = findings, [], []
    else:
        new, accepted, expired = Baseline.load(baseline_path).apply(
            findings)

    if args.json:
        doc = {
            "new": [f.__dict__ for f in new],
            "accepted": [f.__dict__ for f in accepted],
            "expired_baseline_keys": expired,
            "families": list(families),
        }
        print(json.dumps(doc, indent=1))
        return 1 if new else 0

    for f in new:
        print(f.render())
    if expired:
        print(f"\nwarning: {len(expired)} baseline entr"
              f"{'y' if len(expired) == 1 else 'ies'} no longer match any "
              "finding (fixed — prune with --write-baseline):")
        for key in expired:
            print(f"    {key}")
    print(f"\nlmrs-lint: {len(new)} new finding(s), {len(accepted)} "
          f"baseline-accepted, {len(expired)} expired baseline entr"
          f"{'y' if len(expired) == 1 else 'ies'} "
          f"[families: {', '.join(families)}]")
    return 1 if new else 0


if __name__ == "__main__":
    sys.exit(main())
