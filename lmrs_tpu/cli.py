"""Command-line interface.

Flag surface keeps parity with the reference CLI (main.py:406-474) — same
spellings where they exist (``--input/-i``, ``--output/-o``, ``--model``,
``--max-tokens-per-chunk``, ``--max-concurrent-requests``,
``--max-segment-duration``, ``--no-merge``, ``--no-hierarchical``,
``--limit-segments``, ``--report``, ``--prompt-file``,
``--system-prompt-file``, ``--save-chunks``, ``--aggregator-prompt-file``,
``--quiet/-q``) — plus TPU-era additions (``--backend``, ``--tokenizer``,
``--mesh``, ``--resume-from``, ``--profile``, ``--time-interval``).
``--provider`` is accepted as a deprecated alias of ``--backend``.
"""

from __future__ import annotations

import argparse
import dataclasses
import json
import logging
import sys
from pathlib import Path

from lmrs_tpu.config import (
    ChunkConfig,
    DataConfig,
    EngineConfig,
    MeshConfig,
    PipelineConfig,
    ReduceConfig,
    parse_mesh,
)
from lmrs_tpu.pipeline import TranscriptSummarizer
from lmrs_tpu.utils.logging import setup_logging
from lmrs_tpu.utils.timing import format_duration

logger = logging.getLogger("lmrs.cli")


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="lmrs",
        description="TPU-native map-reduce summarization of long transcripts",
    )
    p.add_argument("--input", "-i", required=True, help="input transcript JSON")
    p.add_argument("--output", "-o", help="write final summary to this file")
    p.add_argument("--backend", "--provider", dest="backend", default=None,
                   help="engine backend: mock | jax | http (default: env/config)")
    p.add_argument("--hosts", default=None,
                   help="backend=http: comma-separated lmrs-serve addresses "
                        "(host:port,...) the map/reduce waves fan over")
    p.add_argument("--model", default=None, help="model preset or checkpoint name")
    p.add_argument("--checkpoint", default=None,
                   help="Orbax checkpoint directory with the model weights "
                        "(lmrs-train output or lmrs-convert from HF)")
    p.add_argument("--max-tokens-per-chunk", type=int, default=4000)
    p.add_argument("--overlap-tokens", type=int, default=200)
    p.add_argument("--max-concurrent-requests", type=int, default=None)
    p.add_argument("--max-segment-duration", type=float, default=120.0)
    p.add_argument("--time-interval", type=float, default=None,
                   help="re-bucket segments into fixed intervals (seconds)")
    p.add_argument("--no-merge", action="store_true", help="skip same-speaker merging")
    p.add_argument("--no-hierarchical", action="store_true", help="single-pass reduce only")
    p.add_argument("--stream-reduce", action="store_true",
                   help="feed reduce batches into the map stage's engine "
                        "stream as summaries complete (best for long-decode "
                        "workloads; see ReduceConfig.streaming)")
    p.add_argument("--limit-segments", type=int, default=None)
    p.add_argument("--report", action="store_true", help="write <output>.report.json stats")
    p.add_argument("--prompt-file", help="map prompt file ({transcript} placeholder)")
    p.add_argument("--system-prompt-file", help="system prompt file")
    p.add_argument("--aggregator-prompt-file", help="reduce prompt file ({summaries})")
    p.add_argument("--save-chunks", help="dump per-chunk summaries JSON after map stage")
    p.add_argument("--resume-from", help="reuse summaries from a prior --save-chunks dump")
    p.add_argument("--summary-type", default="summary")
    p.add_argument("--tokenizer", default="approx",
                   help='token-count authority: "approx", "byte", sp model path, HF id')
    p.add_argument("--mesh", default=None,
                   help="device mesh axes as dp,tp[,sp[,pp]] e.g. 2,4 or 1,4,2,1")
    p.add_argument("--profile", action="store_true", help="emit jax.profiler spans")
    p.add_argument("--quantize", default=None, choices=["int8"],
                   help="weight-only quantization for the jax backend")
    p.add_argument("--kv-quantize", default=None, choices=["int8"],
                   help="int8 KV-cache pages (halves decode KV bytes, "
                        "doubles tokens per HBM GiB; page_size %% 32 == 0)")
    p.add_argument("--speculate-k", type=int, default=None,
                   help="prompt-lookup speculative decoding draft length "
                        "(0 = off; output distribution is unchanged)")
    p.add_argument("--no-prefix-cache", action="store_true",
                   help="disable shared-prefix KV reuse (the map/reduce "
                        "preamble normally prefills once and is shared "
                        "read-only across requests; greedy output is "
                        "identical either way)")
    p.add_argument("--trace-out", default=None, metavar="FILE",
                   help="record per-request lifecycle spans and write a "
                        "Chrome-trace JSON loadable in Perfetto "
                        "(docs/OBSERVABILITY.md)")
    p.add_argument("--no-trace", action="store_true",
                   help="force tracing off even if --trace-out is given "
                        "(overhead A/B control)")
    p.add_argument("--quiet", "-q", action="store_true")
    return p


def config_from_args(args: argparse.Namespace) -> PipelineConfig:
    mesh = parse_mesh(args.mesh) if args.mesh else MeshConfig()
    engine = EngineConfig()
    if args.backend:
        engine = dataclasses.replace(engine, backend=args.backend)
    if args.model:
        engine = dataclasses.replace(engine, model=args.model)
    if args.max_concurrent_requests is not None:
        engine = dataclasses.replace(engine, max_concurrent_requests=args.max_concurrent_requests)
    if args.hosts:
        engine = dataclasses.replace(
            engine,
            hosts=tuple(h.strip() for h in args.hosts.split(",") if h.strip()))
    if args.checkpoint:
        engine = dataclasses.replace(engine, checkpoint_path=args.checkpoint)
    if args.quantize:
        engine = dataclasses.replace(engine, quantize=args.quantize)
    if args.kv_quantize:
        engine = dataclasses.replace(engine, kv_quantize=args.kv_quantize)
    if args.speculate_k is not None:
        engine = dataclasses.replace(engine, speculate_k=args.speculate_k)
    if args.no_prefix_cache:
        engine = dataclasses.replace(engine, prefix_cache=False)
    if args.tokenizer and args.tokenizer != "approx":
        # ONE token authority (SURVEY §7.4 item 4): an explicit --tokenizer
        # names the serving tokenizer too, not just the chunker's counter
        engine = dataclasses.replace(engine, tokenizer=args.tokenizer)
    return PipelineConfig(
        data=DataConfig(
            merge_same_speaker=not args.no_merge,
            time_interval_seconds=args.time_interval,
            max_segment_duration=args.max_segment_duration,
            limit_segments=args.limit_segments,
        ),
        chunk=ChunkConfig(
            max_tokens_per_chunk=args.max_tokens_per_chunk,
            overlap_tokens=args.overlap_tokens,
            tokenizer=args.tokenizer,
        ),
        engine=engine,
        mesh=mesh,
        reduce=ReduceConfig(hierarchical=not args.no_hierarchical,
                            streaming=args.stream_reduce),
    )


def _export_trace(trace_out: str) -> None:
    from lmrs_tpu.obs import export_current

    n, err = export_current(trace_out)
    if err is None:
        logger.info("wrote %d trace events to %s (open in "
                    "https://ui.perfetto.dev)", n, trace_out)
    else:  # degraded, not fatal (same as --output)
        logger.error("could not write trace %s: %s", trace_out, err)


def main(argv: list[str] | None = None) -> int:
    args = build_parser().parse_args(argv)
    setup_logging(quiet=args.quiet)
    trace_out = None if args.no_trace else args.trace_out
    if trace_out:
        from lmrs_tpu.obs import enable_tracing

        enable_tracing()
    # an explicit JAX_PLATFORMS=cpu must beat any sitecustomize that
    # force-registers an accelerator (utils/platform.py) — without this a
    # wedged tunnel hangs even pure-CPU runs
    from lmrs_tpu.utils.platform import honor_platform_env

    honor_platform_env()

    try:
        transcript = json.loads(Path(args.input).read_text(encoding="utf-8"))
    except (OSError, json.JSONDecodeError) as e:
        logger.error("could not read transcript %s: %s", args.input, e)
        return 1

    summarizer = TranscriptSummarizer(config_from_args(args), profile=args.profile)
    try:
        try:
            stats = summarizer.summarize(
                transcript,
                prompt_file=args.prompt_file,
                system_prompt_file=args.system_prompt_file,
                aggregator_prompt_file=args.aggregator_prompt_file,
                summary_type=args.summary_type,
                save_chunks=args.save_chunks,
                resume_from=args.resume_from,
            )
        except ValueError as e:
            logger.error("pipeline configuration error: %s", e)
            return 1
    finally:
        # export whatever the ring buffer holds even when the pipeline
        # fails — a failed run is exactly when the trace matters most
        if trace_out:
            _export_trace(trace_out)
    summarizer.shutdown()

    summary = stats["summary"]
    if not args.quiet:
        # final stats banner (main.py:370-379)
        print("\n" + "=" * 60)
        print("SUMMARY")
        print("=" * 60)
        print(summary)
        print("=" * 60)
        print(
            f"segments: {stats['num_input_segments']} -> {stats['num_segments']}  "
            f"chunks: {stats['num_chunks']}  "
            f"duration: {stats['transcript_duration_str']}  "
            f"tokens: {stats['total_tokens_used']}  "
            f"device-s: {stats['total_device_seconds']}  "
            f"wall: {format_duration(stats['processing_time'])}"
        )
        em = stats.get("engine_metrics") or {}
        if "prefill_tokens_per_sec" in em:  # scheduler-shaped metrics
            print(
                f"engine: prefill {em['prefill_tokens_per_sec']} tok/s  "
                f"decode {em['decode_tokens_per_sec']} tok/s  "
                f"occupancy {em['mean_decode_occupancy']}  "
                f"kv-pages {em['peak_kv_page_utilization']}"
            )
        elif "hosts" in em:  # router-shaped metrics (backend=http)
            print(f"engine: {em['healthy_hosts']}/{em['hosts']} hosts healthy  "
                  + "  ".join(
                      f"{row['host']}: {row['served']} served"
                      for row in em.get("per_host", [])))

    if args.output:
        try:
            Path(args.output).write_text(summary, encoding="utf-8")
        except OSError as e:  # degraded, not fatal (main.py:400-402)
            logger.error("could not write output %s: %s", args.output, e)
        if args.report:
            report_path = str(args.output) + ".report.json"
            report = {k: v for k, v in stats.items() if k != "summary"}
            try:
                Path(report_path).write_text(json.dumps(report, indent=2), encoding="utf-8")
            except OSError as e:
                logger.error("could not write report %s: %s", report_path, e)
    return 0


if __name__ == "__main__":
    sys.exit(main())
