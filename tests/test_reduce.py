"""Tests for the reduce tree (single-pass vs hierarchical, batching math,
placeholder substitution)."""

import pytest

from lmrs_tpu.config import EngineConfig, ReduceConfig
from lmrs_tpu.data.chunker import Chunk
from lmrs_tpu.engine.executor import MapExecutor
from lmrs_tpu.engine.mock import MockEngine
from lmrs_tpu.reduce.aggregator import ResultAggregator, SimpleAggregator, _safe_format


def _executor():
    return MapExecutor(MockEngine(), EngineConfig(backend="mock", retry_delay=0.0))


def _chunks(n, words_per_summary=40):
    out = []
    for i in range(n):
        c = Chunk(chunk_index=i, start_time=i * 60.0, end_time=(i + 1) * 60.0)
        c.summary = " ".join(f"fact{i}_{j} is important." for j in range(words_per_summary))
        out.append(c)
    return out


def test_safe_format_substitutes_known_placeholders():
    s = _safe_format("A {summaries} B {metadata} C {num_summaries} D {unknown}",
                     summaries="S", metadata="M", num_summaries=3)
    assert s == "A S B M C 3 D {unknown}"


def test_single_pass_when_under_budget():
    agg = ResultAggregator(_executor(), ReduceConfig(max_tokens_per_batch=100000))
    res = agg.aggregate(_chunks(3))
    assert res["hierarchical"] is False
    assert res["levels"] == 1
    assert res["final_summary"]


def test_hierarchical_when_over_budget():
    agg = ResultAggregator(_executor(),
                           ReduceConfig(max_tokens_per_batch=300, reserve_tokens=50))
    res = agg.aggregate(_chunks(30))
    assert res["hierarchical"] is True
    assert res["levels"] >= 2
    assert res["final_summary"]


def test_recursive_tree_goes_past_two_levels():
    """Unlike the reference's fixed two-level tree (quirk 11), the reduce
    recurses until the batch fits."""
    cfg = ReduceConfig(max_tokens_per_batch=200, reserve_tokens=20,
                       max_summaries_per_batch=3, max_levels=6)
    agg = ResultAggregator(_executor(), cfg)
    res = agg.aggregate(_chunks(40, words_per_summary=60))
    assert res["levels"] >= 2  # mock summaries compress fast; >=2 proves recursion ran


def test_batch_size_math():
    agg = ResultAggregator(_executor(),
                           ReduceConfig(max_tokens_per_batch=6000, reserve_tokens=1000,
                                        max_summaries_per_batch=10))
    # avg 100 tokens -> budget 5000 -> 50 -> capped at 10
    summaries = ["w " * 400] * 20  # ~100 approx-tokens each
    assert agg._calculate_batch_size(summaries) == 10
    # huge summaries -> at least 1
    summaries = ["w " * 40000] * 5
    assert agg._calculate_batch_size(summaries) == 1


def test_time_tags_prepended():
    ex = _executor()
    seen = {}

    class SpyEngine(MockEngine):
        def generate_batch(self, requests):
            seen["prompt"] = requests[0].prompt
            return super().generate_batch(requests)

    ex.engine = SpyEngine()
    agg = ResultAggregator(ex, ReduceConfig(max_tokens_per_batch=10**6))
    agg.aggregate(_chunks(2))
    assert "[Time: 00:00 - 01:00]" in seen["prompt"]


def test_custom_reduce_template_is_honored():
    ex = _executor()
    seen = {}

    class SpyEngine(MockEngine):
        def generate_batch(self, requests):
            seen["prompt"] = requests[0].prompt
            return super().generate_batch(requests)

    ex.engine = SpyEngine()
    agg = ResultAggregator(ex, ReduceConfig(max_tokens_per_batch=10**6))
    agg.aggregate(_chunks(2), prompt_template="CUSTOM HEADER {num_summaries}\n{summaries}")
    assert seen["prompt"].startswith("CUSTOM HEADER 2")
    assert "SUMMARY 1:" in seen["prompt"]


def test_reduce_error_degrades_to_string():
    ex = MapExecutor(MockEngine(fail_pattern="SUMMARY 1:"),
                     EngineConfig(backend="mock", retry_delay=0.0, retry_attempts=1))
    agg = ResultAggregator(ex, ReduceConfig(max_tokens_per_batch=10**6))
    res = agg.aggregate(_chunks(2))
    assert res["final_summary"].startswith("[Error aggregating summaries:")


def test_simple_aggregator():
    simple = SimpleAggregator(_executor())
    out = simple.aggregate(["summary one.", "summary two."])
    assert out
