"""`lmrs-convert`: the HF-checkpoint → Orbax → serving path, end to end.

VERDICT r2 missing #2's actionable half: the converters existed but had
no user entry point and the converted-weights → engine path never ran.
Here a synthetic HF Gemma checkpoint (correct names/shapes for the
tiny-gemma preset) goes through the CLI, lands as an Orbax checkpoint,
and SERVES through the continuous-batching engine via
``EngineConfig.checkpoint_path`` — the full journey a reference user
takes with real downloaded weights, minus the download."""

from __future__ import annotations

import dataclasses

import numpy as np
import pytest

from lmrs_tpu.config import EngineConfig, model_preset
from lmrs_tpu.models.convert_cli import main as convert_main


@pytest.fixture(scope="module")
def hf_gemma_dir(tmp_path_factory):
    """Synthetic HF-format Gemma checkpoint matching tiny-gemma's shapes."""
    from safetensors.numpy import save_file

    cfg = model_preset("tiny-gemma")
    rng = np.random.default_rng(5)
    hd = cfg.hd

    def w(*shape):
        return (rng.normal(size=shape) * 0.05).astype(np.float32)

    t = {"model.embed_tokens.weight": w(cfg.vocab_size, cfg.dim),
         "model.norm.weight": np.full(cfg.dim, 0.1, np.float32)}
    for i in range(cfg.n_layers):
        p = f"model.layers.{i}"
        t[f"{p}.input_layernorm.weight"] = np.full(cfg.dim, 0.1, np.float32)
        t[f"{p}.post_attention_layernorm.weight"] = np.full(cfg.dim, 0.1, np.float32)
        t[f"{p}.self_attn.q_proj.weight"] = w(cfg.n_heads * hd, cfg.dim)
        t[f"{p}.self_attn.k_proj.weight"] = w(cfg.n_kv_heads * hd, cfg.dim)
        t[f"{p}.self_attn.v_proj.weight"] = w(cfg.n_kv_heads * hd, cfg.dim)
        t[f"{p}.self_attn.o_proj.weight"] = w(cfg.dim, cfg.n_heads * hd)
        t[f"{p}.mlp.gate_proj.weight"] = w(cfg.hidden_dim, cfg.dim)
        t[f"{p}.mlp.up_proj.weight"] = w(cfg.hidden_dim, cfg.dim)
        t[f"{p}.mlp.down_proj.weight"] = w(cfg.dim, cfg.hidden_dim)
    d = tmp_path_factory.mktemp("hf_gemma")
    save_file(t, str(d / "model.safetensors"))
    return str(d)


def test_convert_cli_to_orbax_to_serving(hf_gemma_dir, tmp_path):
    """convert CLI -> Orbax checkpoint -> engine restore -> generation."""
    from lmrs_tpu.engine.api import GenerationRequest
    from lmrs_tpu.engine.jax_engine import JaxEngine

    out = tmp_path / "ckpt"
    rc = convert_main(["--src", hf_gemma_dir, "--model", "tiny-gemma",
                       "--output", str(out), "--quiet"])
    assert rc == 0
    assert out.exists()

    # serve from the converted checkpoint (shorter max_seq_len: the param
    # shapes are seq-len independent, and 8192 shapes compile slowly on CPU)
    cfg = dataclasses.replace(model_preset("tiny-gemma"), max_seq_len=256)
    eng = JaxEngine(
        EngineConfig(backend="jax", scheduler="continuous", max_tokens=12,
                     max_batch_slots=2, seed=0, decode_block=6,
                     checkpoint_path=str(out)), cfg)
    out_res = eng.generate_batch([
        GenerationRequest(prompt="the plan covers hiring and budget",
                          request_id=0, temperature=0.0, max_new_tokens=12)])
    eng.shutdown()
    assert out_res[0].error is None
    assert out_res[0].completion_tokens > 0


def test_convert_cli_family_inference_and_errors(tmp_path):
    # gemma inferred from the preset (activation/gelu), llama otherwise
    from lmrs_tpu.models.convert_cli import build_parser

    assert build_parser().parse_args(
        ["--src", "x", "--model", "m", "--output", "y"]).family is None
    # unknown preset -> clean exit 1, no traceback
    assert convert_main(["--src", str(tmp_path), "--model", "nope",
                         "--output", str(tmp_path / "o"), "--quiet"]) == 1
    # missing source files -> clean exit 1
    assert convert_main(["--src", str(tmp_path), "--model", "tiny-gemma",
                         "--output", str(tmp_path / "o"), "--quiet"]) == 1
