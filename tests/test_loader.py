"""Checkpoint save/restore (Orbax) + HF safetensors conversion (models/loader).

The reference has no model weights at all (SURVEY.md §5.4: checkpoint loading
is new-build surface); these tests pin the round-trip and the HF layout
mapping (dense Llama-style and Mixtral MoE)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from lmrs_tpu.config import MeshConfig, ModelConfig
from lmrs_tpu.models.loader import convert_hf_llama, load_checkpoint, save_checkpoint
from lmrs_tpu.models.transformer import forward, init_params


def _cfg(**kw) -> ModelConfig:
    base = dict(vocab_size=64, dim=32, n_layers=2, n_heads=4, n_kv_heads=2,
                hidden_dim=48, max_seq_len=128, dtype="float32",
                tie_embeddings=False)
    base.update(kw)
    return ModelConfig(name="test", **base)


def _trees_equal(a, b):
    flat_a, tree_a = jax.tree.flatten(a)
    flat_b, tree_b = jax.tree.flatten(b)
    assert tree_a == tree_b
    for x, y in zip(flat_a, flat_b):
        np.testing.assert_array_equal(np.asarray(x), np.asarray(y))


def test_orbax_roundtrip_dense(tmp_path):
    cfg = _cfg()
    params = init_params(cfg, jax.random.PRNGKey(0))
    save_checkpoint(str(tmp_path / "ckpt"), params)
    restored = load_checkpoint(str(tmp_path / "ckpt"), cfg)
    _trees_equal(params, restored)


def test_orbax_roundtrip_moe_on_mesh(tmp_path):
    """MoE checkpoint restores directly sharded onto an ep mesh."""
    from lmrs_tpu.parallel.mesh import build_mesh

    cfg = _cfg(n_experts=4, n_experts_per_token=2)
    params = init_params(cfg, jax.random.PRNGKey(0))
    save_checkpoint(str(tmp_path / "ckpt"), params)
    mesh = build_mesh(MeshConfig(dp=2, tp=2, ep=2), jax.devices()[:8])
    restored = load_checkpoint(str(tmp_path / "ckpt"), cfg, mesh=mesh)
    assert restored["layers"]["moe"]["w_gate"].sharding.spec[1] == "ep"
    _trees_equal(params, restored)


def _write_safetensors(path, tensors):
    from safetensors.numpy import save_file

    save_file(tensors, str(path))


def _hf_dense_tensors(cfg: ModelConfig, rng) -> dict:
    hd = cfg.dim // cfg.n_heads
    t = {}
    t["model.embed_tokens.weight"] = rng.normal(size=(cfg.vocab_size, cfg.dim)).astype(np.float32)
    t["lm_head.weight"] = rng.normal(size=(cfg.vocab_size, cfg.dim)).astype(np.float32)
    t["model.norm.weight"] = np.ones(cfg.dim, np.float32)
    for i in range(cfg.n_layers):
        p = f"model.layers.{i}"
        t[f"{p}.input_layernorm.weight"] = np.ones(cfg.dim, np.float32)
        t[f"{p}.post_attention_layernorm.weight"] = np.ones(cfg.dim, np.float32)
        t[f"{p}.self_attn.q_proj.weight"] = rng.normal(size=(cfg.n_heads * hd, cfg.dim)).astype(np.float32)
        t[f"{p}.self_attn.k_proj.weight"] = rng.normal(size=(cfg.n_kv_heads * hd, cfg.dim)).astype(np.float32)
        t[f"{p}.self_attn.v_proj.weight"] = rng.normal(size=(cfg.n_kv_heads * hd, cfg.dim)).astype(np.float32)
        t[f"{p}.self_attn.o_proj.weight"] = rng.normal(size=(cfg.dim, cfg.n_heads * hd)).astype(np.float32)
        t[f"{p}.mlp.gate_proj.weight"] = rng.normal(size=(cfg.hidden_dim, cfg.dim)).astype(np.float32)
        t[f"{p}.mlp.up_proj.weight"] = rng.normal(size=(cfg.hidden_dim, cfg.dim)).astype(np.float32)
        t[f"{p}.mlp.down_proj.weight"] = rng.normal(size=(cfg.dim, cfg.hidden_dim)).astype(np.float32)
    return t


def test_convert_hf_llama_dense(tmp_path):
    cfg = _cfg()
    rng = np.random.default_rng(0)
    _write_safetensors(tmp_path / "model.safetensors", _hf_dense_tensors(cfg, rng))
    params = convert_hf_llama(str(tmp_path), cfg)

    # structure matches init_params exactly
    want = jax.eval_shape(lambda: init_params(cfg, jax.random.PRNGKey(0)))
    assert jax.tree.structure(params) == jax.tree.structure(want)
    for got, exp in zip(jax.tree.leaves(params), jax.tree.leaves(want)):
        assert got.shape == exp.shape

    # converted weights run
    tokens = jnp.zeros((1, 8), jnp.int32)
    pos = jnp.broadcast_to(jnp.arange(8)[None], (1, 8))
    logits, _ = forward(params, cfg, tokens, pos)
    assert np.isfinite(np.asarray(logits)).all()

    # HF [out,in] -> ours [in,out]: spot-check one projection
    np.testing.assert_allclose(
        np.asarray(params["layers"]["mlp"]["w_gate"][0]),
        _hf_dense_tensors(cfg, np.random.default_rng(0))["model.layers.0.mlp.gate_proj.weight"].T,
        rtol=1e-6)


def test_convert_hf_mixtral_moe(tmp_path):
    cfg = _cfg(n_experts=4, n_experts_per_token=2)
    rng = np.random.default_rng(1)
    t = _hf_dense_tensors(cfg, rng)
    # replace dense mlp keys with Mixtral's block_sparse_moe layout
    for i in range(cfg.n_layers):
        p = f"model.layers.{i}"
        for k in ("gate_proj", "up_proj", "down_proj"):
            del t[f"{p}.mlp.{k}.weight"]
        t[f"{p}.block_sparse_moe.gate.weight"] = rng.normal(
            size=(cfg.n_experts, cfg.dim)).astype(np.float32)
        for j in range(cfg.n_experts):
            e = f"{p}.block_sparse_moe.experts.{j}"
            t[f"{e}.w1.weight"] = rng.normal(size=(cfg.hidden_dim, cfg.dim)).astype(np.float32)
            t[f"{e}.w3.weight"] = rng.normal(size=(cfg.hidden_dim, cfg.dim)).astype(np.float32)
            t[f"{e}.w2.weight"] = rng.normal(size=(cfg.dim, cfg.hidden_dim)).astype(np.float32)
    _write_safetensors(tmp_path / "model.safetensors", t)

    params = convert_hf_llama(str(tmp_path), cfg)
    want = jax.eval_shape(lambda: init_params(cfg, jax.random.PRNGKey(0)))
    assert jax.tree.structure(params) == jax.tree.structure(want)
    moe = params["layers"]["moe"]
    assert moe["router"].shape == (cfg.n_layers, cfg.dim, cfg.n_experts)
    assert moe["w_gate"].shape == (cfg.n_layers, cfg.n_experts, cfg.dim, cfg.hidden_dim)

    tokens = jnp.zeros((1, 8), jnp.int32)
    pos = jnp.broadcast_to(jnp.arange(8)[None], (1, 8))
    logits, _ = forward(params, cfg, tokens, pos)
    assert np.isfinite(np.asarray(logits)).all()


def test_convert_hf_missing_files_raises(tmp_path):
    with pytest.raises(FileNotFoundError):
        convert_hf_llama(str(tmp_path), _cfg())


def test_convert_hf_gemma(tmp_path):
    """Gemma conversion: unshifted norm weights, tied embeddings, explicit
    head_dim != dim/n_heads."""
    from lmrs_tpu.models.loader import convert_hf_gemma

    cfg = _cfg(tie_embeddings=True, head_dim=16, activation="gelu",
               norm_eps=1e-6, embed_scale=True)
    assert cfg.hd == 16 and cfg.hd != cfg.dim // cfg.n_heads
    rng = np.random.default_rng(2)
    t = _hf_dense_tensors(cfg, rng)
    del t["lm_head.weight"]  # tied
    # Gemma norm weights: stored w, applied as (1 + w) — give them a
    # recognizable non-trivial value to pin the no-offset conversion
    for k in list(t):
        if k.endswith("norm.weight"):
            t[k] = np.full_like(t[k], 0.25)
    # head_dim-sized projections
    for i in range(cfg.n_layers):
        p = f"model.layers.{i}.self_attn"
        t[f"{p}.q_proj.weight"] = rng.normal(size=(cfg.n_heads * 16, cfg.dim)).astype(np.float32)
        t[f"{p}.k_proj.weight"] = rng.normal(size=(cfg.n_kv_heads * 16, cfg.dim)).astype(np.float32)
        t[f"{p}.v_proj.weight"] = rng.normal(size=(cfg.n_kv_heads * 16, cfg.dim)).astype(np.float32)
        t[f"{p}.o_proj.weight"] = rng.normal(size=(cfg.dim, cfg.n_heads * 16)).astype(np.float32)
    _write_safetensors(tmp_path / "model.safetensors", t)

    params = convert_hf_gemma(str(tmp_path), cfg)
    want = jax.eval_shape(lambda: init_params(cfg, jax.random.PRNGKey(0)))
    assert jax.tree.structure(params) == jax.tree.structure(want)
    assert "lm_head" not in params
    assert params["layers"]["attn"]["wq"].shape == (
        cfg.n_layers, cfg.dim, cfg.n_heads, 16)
    # no -1 shift: scale == stored weight
    np.testing.assert_allclose(
        np.asarray(params["final_norm"]["scale"]), 0.25, rtol=1e-6)

    tokens = jnp.zeros((1, 8), jnp.int32)
    pos = jnp.broadcast_to(jnp.arange(8)[None], (1, 8))
    logits, _ = forward(params, cfg, tokens, pos)
    assert np.isfinite(np.asarray(logits)).all()


def test_convert_hf_gemma_rejects_untied(tmp_path):
    from lmrs_tpu.models.loader import convert_hf_gemma

    with pytest.raises(ValueError, match="tie"):
        convert_hf_gemma(str(tmp_path), _cfg(tie_embeddings=False))


def test_gelu_activation_forward():
    """activation="gelu" changes the FFN (and runs finite); bad names raise."""
    cfg_s = _cfg(tie_embeddings=True)
    cfg_g = _cfg(tie_embeddings=True, activation="gelu")
    params = init_params(cfg_s, jax.random.PRNGKey(0))
    tokens = jnp.asarray(np.random.default_rng(0).integers(0, 64, (1, 8)), jnp.int32)
    pos = jnp.broadcast_to(jnp.arange(8)[None], (1, 8))
    l_s, _ = forward(params, cfg_s, tokens, pos)
    l_g, _ = forward(params, cfg_g, tokens, pos)
    assert np.isfinite(np.asarray(l_g)).all()
    assert np.abs(np.asarray(l_s) - np.asarray(l_g)).max() > 1e-6

    import dataclasses
    cfg_bad = dataclasses.replace(cfg_s, activation="relu")
    with pytest.raises(ValueError, match="activation"):
        forward(params, cfg_bad, tokens, pos)


def test_checkpoint_mesh_portability(tmp_path):
    """VERDICT r5 item 7: a checkpoint SAVED from a tp=2-sharded tree must
    restore onto a DIFFERENT topology — tp=4 and a dp×tp mesh — with
    forward parity.  Orbax stores the logical array regardless of the
    save-time sharding; this pins that no shard-layout detail leaks into
    the checkpoint and that restore re-shards to whatever mesh serves."""
    from lmrs_tpu.parallel.mesh import build_mesh
    from lmrs_tpu.parallel.sharding import shard_params

    # n_kv_heads=4 so kv heads divide the widest tp axis under test (4)
    cfg = _cfg(n_kv_heads=4)
    params = init_params(cfg, jax.random.PRNGKey(3))
    tokens = jnp.asarray(
        np.random.default_rng(3).integers(1, cfg.vocab_size, (2, 16)),
        jnp.int32)
    pos = jnp.broadcast_to(jnp.arange(16)[None], (2, 16))
    want, _ = forward(params, cfg, tokens, pos)

    mesh_save = build_mesh(MeshConfig(tp=2), jax.devices()[:2])
    sharded = shard_params(params, mesh_save, cfg.tie_embeddings)
    assert sharded["layers"]["attn"]["wq"].sharding.spec[2] == "tp"
    save_checkpoint(str(tmp_path / "ckpt"), sharded)

    for mesh_cfg in (MeshConfig(tp=4), MeshConfig(dp=2, tp=2)):
        mesh = build_mesh(mesh_cfg, jax.devices()[: mesh_cfg.n_devices])
        restored = load_checkpoint(str(tmp_path / "ckpt"), cfg, mesh=mesh)
        # the tree restored onto the NEW topology, values intact
        _trees_equal(params, restored)
        got, _ = forward(restored, cfg, tokens, pos)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   rtol=1e-5, atol=1e-5)
