"""Shared-prefix KV cache tests: radix tree semantics, allocator refcount
interplay, LRU eviction, and scheduler integration (the acceptance bar: a
shared map preamble across many chunks halves prefill work while greedy
outputs stay token-identical to a cache-off run)."""

from __future__ import annotations

import pytest

from lmrs_tpu.config import EngineConfig, ModelConfig
from lmrs_tpu.engine.api import GenerationRequest
from lmrs_tpu.engine.jax_engine import JaxEngine
from lmrs_tpu.engine.kv_cache import PageAllocator
from lmrs_tpu.engine.prefix_cache import PrefixCache

PS = 4  # page size for the pure-host tree tests


def _cache(num_pages: int = 64, max_pages: int = 0):
    a = PageAllocator(num_pages)
    return a, PrefixCache(a, PS, max_pages=max_pages)


def _seq(a: PageAllocator, ids: list[int]) -> list[int]:
    return a.alloc(-(-len(ids) // PS))


# ------------------------------------------------------------- radix tree


def test_insert_and_match_page_granular():
    a, c = _cache()
    ids = list(range(100, 114))  # 14 tokens: 3 full pages + remainder
    pages = _seq(a, ids)
    assert c.insert(ids, pages) == 3  # only full pages adopted
    # matching the same ids caps at the largest page multiple <= len-1
    got, n = c.match(ids)
    assert n == 12 and got == pages[:3]
    assert [a.refcount(p) for p in pages[:3]] == [3, 3, 3]  # cache+seq+match
    a.free(got)  # the match reference
    a.free(pages)  # the sequence closes; cached pages stay live
    assert all(a.refcount(p) == 1 for p in pages[:3])
    assert a.refcount(pages[3]) == 0  # the partial page went back


def test_match_always_leaves_a_tail_to_prefill():
    """A full-prefix hit must leave >= 1 token uncached: the first output
    token is sampled from the last prompt token's logits, and its KV write
    must land in a private page."""
    a, c = _cache()
    ids = list(range(50, 58))  # exactly 2 pages
    pages = _seq(a, ids)
    c.insert(ids, pages)
    got, n = c.match(ids)  # same 8 tokens: usable = ((8-1)//4)*4 = 4
    assert n == 4 and got == pages[:1]
    a.free(got)
    a.free(pages)


def test_edge_split_at_page_boundary():
    a, c = _cache()
    ids1 = list(range(0, 12))  # 3 pages
    p1 = _seq(a, ids1 + [0])  # 4th page holds a remainder token
    c.insert(ids1 + [0], p1)
    # second sequence shares the first 2 pages, diverges at page 3
    ids2 = ids1[:8] + [99] * 5
    got, n = c.match(ids2)
    assert n == 8 and got == p1[:2]  # the 3-page edge split at the boundary
    a.free(got)
    p2 = _seq(a, ids2)
    assert c.insert(ids2, p2) == 1  # adopts only its divergent 3rd page
    assert c.cached_pages == 4
    # both full prefixes still match exactly: ids1 its 3 original pages,
    # ids2 the 2 shared pages plus its own adopted divergent page
    m1, n1 = c.match(ids1 + [0])
    m2, n2 = c.match(ids2)
    assert (n1, m1) == (12, p1[:3]) and (n2, m2) == (12, p1[:2] + [p2[2]])
    a.free(m1)
    a.free(m2)
    a.free(p1)
    a.free(p2)


def test_disjoint_prefixes_do_not_match():
    a, c = _cache()
    ids1, ids2 = [1] * 9, [2] * 9
    p1 = _seq(a, ids1)
    c.insert(ids1, p1)
    got, n = c.match(ids2)
    assert (got, n) == ([], 0)
    a.free(p1)


def test_lru_eviction_order():
    a, c = _cache()
    seqs = []
    for base in (10, 20, 30):  # three disjoint 2-page prefixes
        ids = [base + i for i in range(9)]
        pages = _seq(a, ids)
        c.insert(ids, pages)
        a.free(pages)  # sequences close: all nodes refcount-zero
        seqs.append((ids, pages[:2]))
    assert c.cached_pages == 6
    # touch the OLDEST entry so it becomes most-recently-used
    got, _ = c.match(seqs[0][0])
    a.free(got)
    # evicting 2 pages must drop the LRU node: seqs[1], not seqs[0]
    assert c.evict(2) == 2
    m0, n0 = c.match(seqs[0][0])
    m1, n1 = c.match(seqs[1][0])
    m2, n2 = c.match(seqs[2][0])
    assert n0 == 8 and n1 == 0 and n2 == 8
    a.free(m0)
    a.free(m2)


def test_shared_nodes_are_not_evictable():
    """A node a live sequence shares (allocator refcount > 1) must survive
    eviction; refcount-zero nodes drain."""
    a, c = _cache()
    ids = list(range(200, 209))
    pages = _seq(a, ids)
    c.insert(ids, pages)
    # the sequence is still live (holds its own reference): nothing evictable
    assert c.evict(10) == 0
    a.free(pages)  # sequence closes
    assert c.evict(10) == 2
    assert c.cached_pages == 0
    assert a.free_count == 63


def test_max_pages_budget_evicts_then_trims():
    a, c = _cache(max_pages=2)
    ids1 = [1] * 9
    p1 = _seq(a, ids1)
    c.insert(ids1, p1)
    a.free(p1)
    assert c.cached_pages == 2
    ids2 = [2] * 13  # wants 3 pages: over budget -> evict LRU, trim to 2
    p2 = _seq(a, ids2)
    c.insert(ids2, p2)
    a.free(p2)
    assert c.cached_pages <= 2
    got, n = c.match(ids2)
    assert n == 8  # the trimmed 2-page prefix is cached
    a.free(got)


def test_insert_hint_caps_adoption():
    a, c = _cache()
    ids = list(range(0, 16))
    pages = _seq(a, ids)
    # hint 5 tokens -> ceil to page = 2 pages adopted, not 4
    assert c.insert(ids, pages, max_tokens=5) == 2
    assert c.cached_pages == 2
    a.free(pages)


def test_pool_accounting_invariant():
    """No page may be both free and cache-referenced; free + live + cached
    always covers the pool exactly."""
    a, c = _cache(num_pages=32)
    live = []
    for base in (0, 40, 80):
        ids = [base + i for i in range(11)]
        pages = _seq(a, ids)
        c.insert(ids, pages)
        live.append(pages)
    got, n = c.match([0, 1, 2, 3] + [7] * 5)  # partial hit on the first
    assert n == 4
    a.free(got)
    held = {p for pages in live for p in pages}
    assert all(a.refcount(p) >= 1 for p in held)
    # usable pool = free + pages held by sequences and/or the cache
    distinct_held = len(held)
    assert a.free_count + distinct_held == 31
    for pages in live:
        a.free(pages)
    c.evict(10_000)
    assert a.free_count == 31
    assert all(a.refcount(p) == 0 for p in held)


# ---------------------------------------------------- scheduler integration


def tiny_model():
    return ModelConfig(vocab_size=512, dim=64, n_layers=2, n_heads=4,
                       n_kv_heads=2, hidden_dim=128, max_seq_len=256,
                       dtype="float32")


PREAMBLE = ("You are summarizing one section of a much longer transcript. "
            "Keep every fact, decision, name, and number. ")


def _map_requests(n: int, hint: bool = True) -> list[GenerationRequest]:
    """A demo-style map workload: shared system+map preamble, distinct
    per-chunk bodies."""
    return [GenerationRequest(
        prompt=PREAMBLE + f"Chunk {i}: the team discussed milestone {i}.",
        request_id=i, temperature=0.0, max_new_tokens=8,
        system_prompt="Respond with the summary content only.",
        cache_prefix=len(PREAMBLE) if hint else None)
        for i in range(n)]


def _engine(**kw):
    cfg = dict(backend="jax", scheduler="continuous", max_tokens=8,
               max_batch_slots=2, seed=0, page_size=16, decode_block=4)
    cfg.update(kw)
    return JaxEngine(EngineConfig(**cfg), tiny_model())


def test_map_preamble_halves_prefill_and_keeps_outputs():
    """The acceptance bar: >= 8 chunks sharing the system+map preamble,
    prefill_tokens drops >= 50% cache-on vs cache-off, greedy outputs
    token-identical in both modes."""
    reqs = _map_requests(10)
    on = _engine()
    got = [r.text for r in on.generate_batch(reqs)]
    m_on = dict(on._scheduler.metrics)
    report = on.engine_metrics()
    on.shutdown()

    off = _engine(prefix_cache=False)
    assert off._scheduler._prefix_cache is None
    want = [r.text for r in off.generate_batch(reqs)]
    m_off = dict(off._scheduler.metrics)
    off.shutdown()

    assert got == want, "prefix cache changed greedy outputs"
    assert m_on["prefill_tokens"] <= 0.5 * m_off["prefill_tokens"], (
        m_on["prefill_tokens"], m_off["prefill_tokens"])
    # the first admission wave (2 slots) misses, the rest hit
    assert m_on["prefix_hits"] >= 8
    assert m_on["prefix_tokens_reused"] > 0
    pc = report["prefix_cache"]
    assert pc["hit_rate"] >= 0.8
    assert pc["prefill_tokens_saved"] == m_on["prefix_tokens_reused"]
    assert pc["tokens_reused"] == pc["prefill_tokens_saved"]


def test_identical_prompt_rerun_hits_cache():
    """A repeated identical prompt (full-prefix hit) re-prefills only the
    capped tail and produces identical text across engine runs."""
    eng = _engine()
    req = GenerationRequest(prompt="canonical probe " * 8, temperature=0.0,
                            max_new_tokens=8)
    first = eng.generate_batch([req])[0].text
    m0 = eng._scheduler.metrics["prefill_tokens"]
    second = eng.generate_batch([req])[0].text
    m1 = eng._scheduler.metrics["prefill_tokens"]
    eng.shutdown()
    assert first == second
    # the second run prefilled only the uncached tail (< one page + budget)
    assert m1 - m0 < m0


def test_cache_off_via_env_kill_switch(monkeypatch):
    monkeypatch.setenv("LMRS_PREFIX_CACHE", "0")
    eng = _engine()
    assert eng._scheduler._prefix_cache is None
    out = eng.generate_batch(_map_requests(3))
    assert all(r.error is None for r in out)
    assert eng._scheduler.metrics["prefix_queries"] == 0
    eng.shutdown()


def test_kv_quantize_gates_cache_off():
    eng = _engine(kv_quantize="int8", page_size=32)
    assert eng._scheduler._prefix_cache is None
    eng.shutdown()


def test_eviction_under_page_pressure_no_deadlock():
    """A pool near the floor with the cache retaining pages: admissions and
    decode growth must drain the cache (back-pressure eviction) instead of
    deadlocking, and every request completes with outputs identical to a
    roomy-pool run."""
    mc = ModelConfig(vocab_size=512, dim=64, n_layers=2, n_heads=4,
                     n_kv_heads=2, hidden_dim=128, max_seq_len=96,
                     dtype="float32")
    reqs = _map_requests(6)

    def run(num_pages):
        eng = JaxEngine(EngineConfig(
            backend="jax", scheduler="continuous", max_tokens=16,
            max_batch_slots=3, seed=0, page_size=16, num_pages=num_pages,
            decode_block=4), mc)
        out = eng.generate_batch(reqs)
        sched = eng._scheduler
        stats = sched._prefix_cache.stats()
        free = sched.cache.allocator.free_count
        total = sched.cache.num_pages
        eng.shutdown()
        return out, stats, free, total

    roomy, _, _, _ = run(1)  # worst-case pool: no pressure
    tight, stats, free, total = run(8)  # floor-sized budget: heavy pressure
    assert all(r.error is None for r in tight)
    assert [r.text for r in tight] == [r.text for r in roomy]
    assert stats["evicted_pages"] > 0, stats  # pressure drained the cache
    # invariant: free + cache-retained covers the whole usable pool
    assert free == total - 1 - stats["cached_pages"]


def test_map_executor_sets_cache_prefix_hint():
    from lmrs_tpu.data.chunker import Chunk
    from lmrs_tpu.engine.executor import MapExecutor
    from lmrs_tpu.engine.mock import MockEngine
    from lmrs_tpu.prompts import DEFAULT_MAP_PROMPT

    ex = MapExecutor(MockEngine())
    chunk = Chunk(chunk_index=0, total_chunks=1)
    chunk.text_with_context = "body text"
    req = ex.build_map_request(chunk, DEFAULT_MAP_PROMPT)
    assert req.cache_prefix == DEFAULT_MAP_PROMPT.replace(
        "{summary_type}", "summary").index("{transcript}")


def test_reduce_aggregator_sets_cache_prefix_hint():
    from lmrs_tpu.engine.executor import MapExecutor
    from lmrs_tpu.engine.mock import MockEngine
    from lmrs_tpu.prompts import DEFAULT_REDUCE_PROMPT
    from lmrs_tpu.reduce.aggregator import ResultAggregator

    agg = ResultAggregator(MapExecutor(MockEngine()))
    req = agg._build_request(["s1", "s2"], DEFAULT_REDUCE_PROMPT, None)
    assert req.cache_prefix is not None
    # the default reduce template varies at {num_summaries} on line 1
    assert req.cache_prefix == DEFAULT_REDUCE_PROMPT.index("{num_summaries}")
