"""Pipeline-parallel (pp axis) tests on the virtual CPU mesh."""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from lmrs_tpu.config import MeshConfig, ModelConfig
from lmrs_tpu.models.transformer import init_params
from lmrs_tpu.parallel.mesh import build_mesh
from lmrs_tpu.parallel.pipeline import (
    make_pp_train_step,
    pipeline_causal_lm_loss,
)
from lmrs_tpu.training.train import causal_lm_loss


def cfg4():
    # 4 layers -> 2 per stage at pp=2; f32 so loss parity is tight
    return ModelConfig(vocab_size=256, dim=64, n_layers=4, n_heads=4,
                       n_kv_heads=2, hidden_dim=128, max_seq_len=128,
                       dtype="float32")


@pytest.fixture(scope="module")
def setup():
    cfg = cfg4()
    params = init_params(cfg, jax.random.PRNGKey(0))
    tokens = jnp.asarray(
        np.random.default_rng(0).integers(0, cfg.vocab_size, (8, 32)),
        jnp.int32)
    return cfg, params, tokens


def test_pp_loss_matches_dense(setup):
    cfg, params, tokens = setup
    mesh = build_mesh(MeshConfig(dp=2, tp=1, sp=1, pp=2), jax.devices()[:4])
    ref = causal_lm_loss(params, cfg, tokens)
    pp = pipeline_causal_lm_loss(params, cfg, tokens, mesh, n_micro=2)
    np.testing.assert_allclose(float(pp), float(ref), rtol=1e-5)


def test_pp_loss_matches_dense_pp4(setup):
    cfg, params, tokens = setup
    mesh = build_mesh(MeshConfig(dp=1, tp=1, sp=1, pp=4), jax.devices()[:4])
    ref = causal_lm_loss(params, cfg, tokens)
    pp = pipeline_causal_lm_loss(params, cfg, tokens, mesh, n_micro=4)
    np.testing.assert_allclose(float(pp), float(ref), rtol=1e-5)


def test_pp_grads_match_dense(setup):
    cfg, params, tokens = setup
    mesh = build_mesh(MeshConfig(dp=1, tp=1, sp=1, pp=2), jax.devices()[:2])
    g_ref = jax.grad(lambda p: causal_lm_loss(p, cfg, tokens))(params)
    g_pp = jax.grad(
        lambda p: pipeline_causal_lm_loss(p, cfg, tokens, mesh, n_micro=4)
    )(params)
    flat_ref, _ = jax.tree.flatten(g_ref)
    flat_pp, _ = jax.tree.flatten(g_pp)
    for a, b in zip(flat_ref, flat_pp):
        np.testing.assert_allclose(np.asarray(b), np.asarray(a),
                                   rtol=2e-4, atol=2e-5)


def test_pp_train_step_runs(setup):
    cfg, params, tokens = setup
    mesh = build_mesh(MeshConfig(dp=2, tp=1, sp=1, pp=2), jax.devices()[:4])
    opt = optax.adamw(1e-3)
    # the step donates params/opt_state; feed it copies so the shared
    # module fixture (and the post-step comparison below) stay alive
    donated = jax.tree.map(jnp.copy, params)
    opt_state = opt.init(donated)
    step = make_pp_train_step(cfg, opt, mesh, n_micro=2)
    p2, opt_state, loss = step(donated, opt_state, tokens)
    assert np.isfinite(float(loss))
    # params actually moved
    moved = jax.tree.map(
        lambda a, b: float(jnp.abs(a - b).max()), params, p2)
    assert max(jax.tree.leaves(moved)) > 0


def test_pp_rejects_indivisible_layers(setup):
    cfg, params, tokens = setup
    mesh = build_mesh(MeshConfig(dp=1, tp=1, sp=1, pp=3), jax.devices()[:3])
    with pytest.raises(ValueError, match="divisible"):
        pipeline_causal_lm_loss(params, cfg, tokens, mesh, n_micro=2)
