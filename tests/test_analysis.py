"""lmrs-lint analyzer tests: planted-fixture positives, clean negatives,
golden finding output, baseline add/expire semantics, the repo-clean CI
gate, and regression tests for the real findings the first run surfaced
(router host-counter lost updates, Tracer.recorded increments, the env
parser's NaN/empty-string discipline)."""

from __future__ import annotations

import json
import threading
from pathlib import Path

import pytest

from lmrs_tpu.analysis import (Baseline, Module, RepoContext, run_passes,
                               run_repo)
from lmrs_tpu.analysis import drift, envpass, locks, tracing

REPO_ROOT = Path(__file__).resolve().parents[1]


def ctx_for(sources: dict[str, str], docs: dict[str, str] | None = None
            ) -> RepoContext:
    mods = [Module.from_source(p, s) for p, s in sources.items()]
    return RepoContext(root=REPO_ROOT, modules=mods, docs=dict(docs or {}))


def rules(findings) -> set[str]:
    return {f.rule for f in findings}


# --------------------------------------------------------------- race pass

RACE_POSITIVE = '''
import threading

class Pool:
    def __init__(self):
        self._lock = threading.Lock()
        self._pinned = {}  # guarded-by: _lock
        self.count = 0  # guarded-by: _lock

    def bad_write(self, k, v):
        self._pinned[k] = v          # write without the lock

    def bad_increment(self):
        self.count += 1              # lost-update RMW

    def bad_mutator(self, k):
        self._pinned.pop(k, None)    # mutator call without the lock
'''

RACE_NEGATIVE = '''
import threading

class Pool:
    def __init__(self):
        self._lock = threading.Lock()
        self._pinned = {}  # guarded-by: _lock
        self.count = 0  # guarded-by: _lock

    def good_write(self, k, v):
        with self._lock:
            self._pinned[k] = v
            self.count += 1

    def reads_are_fine(self):
        return dict(self._pinned)

    def _helper(self, k):  # holds-lock: _lock
        self._pinned.pop(k, None)
'''


def test_race_unguarded_writes_detected():
    findings = locks.run(ctx_for({"lmrs_tpu/x.py": RACE_POSITIVE}))
    unguarded = [f for f in findings if f.rule == "race.unguarded-write"]
    assert len(unguarded) == 3
    lines = {f.line for f in unguarded}
    assert len(lines) == 3  # one per planted site, each with a location
    assert all("with _lock" in f.message for f in unguarded)
    assert all("guarded-by declared" in f.hint for f in unguarded)


def test_race_clean_equivalent_is_silent():
    assert locks.run(ctx_for({"lmrs_tpu/x.py": RACE_NEGATIVE})) == []


def test_race_comment_above_annotation_binds_to_next_line():
    """The standalone-comment form: `# guarded-by:` on its own line
    directly above the attribute's defining line (used when the defining
    line is too long for a trailer) must register — a silently-ignored
    annotation is worse than none."""
    src = '''
import threading

class Pool:
    def __init__(self):
        self._lock = threading.Lock()
        # guarded-by: _lock
        self._deferred = []

    def bad(self, item):
        self._deferred.append(item)
'''
    findings = locks.run(ctx_for({"lmrs_tpu/x.py": src}))
    assert [f.rule for f in findings] == ["race.unguarded-write"]
    assert "_deferred" in findings[0].message


def test_race_module_level_guarded_global():
    src = '''
import threading

_lock = threading.Lock()
_last = {}  # guarded-by: _lock

def bad(reason, t):
    _last[reason] = t

def good(reason, t):
    with _lock:
        _last[reason] = t
'''
    findings = locks.run(ctx_for({"lmrs_tpu/x.py": src}))
    assert [f.rule for f in findings] == ["race.unguarded-write"]
    assert findings[0].line == 8


def test_race_lock_order_cycle_detected():
    src = '''
import threading

class C:
    def __init__(self):
        self._a = threading.Lock()
        self._b = threading.Lock()

    def m1(self):
        with self._a:
            with self._b:
                pass

    def m2(self):
        with self._b:
            with self._a:
                pass
'''
    findings = locks.run(ctx_for({"lmrs_tpu/x.py": src}))
    assert "race.lock-order-cycle" in rules(findings)


def test_race_consistent_order_no_cycle():
    src = '''
import threading

class C:
    def __init__(self):
        self._a = threading.Lock()
        self._b = threading.Lock()

    def m1(self):
        with self._a:
            with self._b:
                pass

    def m2(self):
        with self._a:
            with self._b:
                pass
'''
    assert locks.run(ctx_for({"lmrs_tpu/x.py": src})) == []


def test_race_cycle_via_same_class_call():
    src = '''
import threading

class C:
    def __init__(self):
        self._a = threading.Lock()
        self._b = threading.Lock()

    def outer(self):
        with self._a:
            self.inner()

    def inner(self):
        with self._b:
            pass

    def other(self):
        with self._b:
            with self._a:
                pass
'''
    findings = locks.run(ctx_for({"lmrs_tpu/x.py": src}))
    assert "race.lock-order-cycle" in rules(findings)


def test_race_blocking_under_lock():
    src = '''
import os
import time
import threading

class J:
    def __init__(self):
        self._lock = threading.Lock()

    def bad(self, fh):
        with self._lock:
            time.sleep(0.1)
            os.fsync(fh.fileno())

    def good(self, fh):
        with self._lock:
            data = fh.name
        time.sleep(0.1)
        return data
'''
    findings = locks.run(ctx_for({"lmrs_tpu/x.py": src}))
    blocking = [f for f in findings
                if f.rule == "race.blocking-under-lock"]
    assert len(blocking) == 2  # sleep + fsync, nothing from good()


def test_race_inline_suppression():
    src = '''
import os
import threading

class J:
    def __init__(self):
        self._lock = threading.Lock()

    def append(self, fh):
        with self._lock:
            os.fsync(fh.fileno())  # lint: ignore[race.blocking-under-lock]
'''
    ctx = ctx_for({"lmrs_tpu/x.py": src})
    assert run_passes(ctx, families=("race",)) == []


# ------------------------------------------------------------ tracing pass

def test_tracing_python_branch_on_traced():
    src = '''
import jax

@jax.jit
def f(x):
    if x > 0:
        return x
    return -x
'''
    findings = tracing.run(ctx_for({"lmrs_tpu/ops/x.py": src}))
    assert "tracing.python-branch-on-traced" in rules(findings)


def test_tracing_static_uses_not_flagged():
    src = '''
import functools
import jax
import jax.numpy as jnp

@functools.partial(jax.jit, static_argnames=("block",))
def f(x, scale, block):
    b, s = x.shape
    if scale is None:          # is-None test: static
        scale = jnp.ones((b,))
    if b > 8:                  # shape-derived: static
        x = x[:8]
    if block > 128:            # static argname
        x = x * 2
    return x * scale
'''
    findings = tracing.run(ctx_for({"lmrs_tpu/ops/x.py": src}))
    assert rules(findings) == set()


def test_tracing_host_sync_and_dynamic_shape():
    src = '''
import jax
import jax.numpy as jnp
import numpy as np

@jax.jit
def f(x, n):
    v = float(x)               # host sync
    arr = np.asarray(x)        # host sync
    z = jnp.zeros((n, 4))      # traced shape
    for i in range(n):         # traced trip count
        z = z + 1
    return v, arr, z
'''
    findings = tracing.run(ctx_for({"lmrs_tpu/ops/x.py": src}))
    assert rules(findings) >= {"tracing.host-sync-in-jit",
                               "tracing.dynamic-shape-in-jit"}


def test_tracing_lax_scan_body_covered():
    src = '''
from jax import lax

def run(xs):
    def body(carry, x):
        if x > 0:
            carry = carry + x
        return carry, x
    return lax.scan(body, 0, xs)
'''
    findings = tracing.run(ctx_for({"lmrs_tpu/engine/x.py": src}))
    assert "tracing.python-branch-on-traced" in rules(findings)
    assert any("scan-traced" in f.message for f in findings)


def test_tracing_mutable_global_closure():
    src = '''
import jax

_STATE = {"n": 0}

def bump():
    global _STATE
    _STATE = {"n": 1}

@jax.jit
def f(x):
    return x + _STATE["n"]
'''
    findings = tracing.run(ctx_for({"lmrs_tpu/models/x.py": src}))
    assert "tracing.jit-closes-over-mutable-global" in rules(findings)


def test_tracing_deprecated_api_table():
    src = '''
import jax

def f(g, mesh, specs):
    return jax.shard_map(g, mesh=mesh, in_specs=specs, out_specs=specs)
'''
    findings = tracing.run(ctx_for({"lmrs_tpu/serving/x.py": src}))
    dep = [f for f in findings if f.rule == "tracing.deprecated-api"]
    assert dep and "jax_compat" in dep[0].hint


def test_tracing_compat_shim_module_exempt():
    real = (REPO_ROOT / "lmrs_tpu/utils/jax_compat.py").read_text(
        encoding="utf-8")
    findings = tracing.run(ctx_for({"lmrs_tpu/utils/jax_compat.py": real}))
    assert [f for f in findings if f.rule == "tracing.deprecated-api"] == []


# -------------------------------------------------------------- drift pass

DOC_SITES = """
| site | fires as | exercises |
|---|---|---|
| `kv.allocate` | OutOfPages | back-pressure |
| `ghost.site` | nothing | stale row |
"""

DRIFT_SRC = '''
from lmrs_tpu.testing import faults

def step():
    faults.fire("kv.allocate")
    faults.fire("scheduler.newsite")
'''


def test_drift_fault_sites_both_directions():
    ctx = ctx_for({"lmrs_tpu/x.py": DRIFT_SRC},
                  docs={"docs/ROBUSTNESS.md": DOC_SITES,
                        "docs/OBSERVABILITY.md": "", "docs/KNOBS.md": ""})
    findings = drift.run(ctx)
    assert "drift.fault-site-undocumented" in rules(findings)
    assert "drift.fault-site-stale" in rules(findings)
    messages = " ".join(f.message for f in findings)
    assert "scheduler.newsite" in messages and "ghost.site" in messages


METRIC_SRC = '''
class S:
    def __init__(self, registry):
        c, g, h = (registry.counter, registry.gauge, registry.histogram)
        self._c = c("lmrs_widgets_total", "widgets")
        self._g = registry.gauge("lmrs_live_widgets", "live")
'''

METRIC_DOC = """
### Catalog

| metric | type |
|---|---|
| `lmrs_widgets_total` | counter |
| `lmrs_gone_metric` | counter |
"""


def test_drift_metrics_alias_resolution_and_both_directions():
    ctx = ctx_for({"lmrs_tpu/x.py": METRIC_SRC},
                  docs={"docs/OBSERVABILITY.md": METRIC_DOC,
                        "docs/ROBUSTNESS.md": "", "docs/KNOBS.md": ""})
    findings = drift.run(ctx)
    msgs = {f.rule: f.message for f in findings}
    assert "lmrs_live_widgets" in msgs["drift.metric-undocumented"]
    assert "lmrs_gone_metric" in msgs["drift.metric-stale"]


def test_drift_suffix_shorthand_flagged():
    doc = "| `lmrs_widgets_total` / `_live` | counter |\n"
    ctx = ctx_for({}, docs={"docs/OBSERVABILITY.md": doc,
                            "docs/ROBUSTNESS.md": "", "docs/KNOBS.md": ""})
    findings = drift.run(ctx)
    assert "drift.metric-suffix-shorthand" in rules(findings)


def test_drift_trace_instant_args_contract():
    src = '''
def emit(tr, pages, kv_len):
    tr.instant("handoff_export", args={"pages": pages})
    tr.instant("handoff_import", args={"pages": pages, "kv_len": kv_len})
    tr.instant("job_done")
'''
    ctx = ctx_for({"lmrs_tpu/x.py": src},
                  docs={"docs/ROBUSTNESS.md": "",
                        "docs/OBSERVABILITY.md": "", "docs/KNOBS.md": ""})
    findings = [f for f in drift.run(ctx)
                if f.rule == "drift.trace-instant-args"]
    assert len(findings) == 2  # missing kv_len + missing args entirely
    assert any("kv_len" in f.message for f in findings)


# ---------------------------------------------------------------- env pass

def test_env_direct_read_flagged_and_parser_reads_tracked():
    src = '''
import os
from lmrs_tpu.utils.env import env_int

BAD = os.environ.get("LMRS_BAD_KNOB", "1")
GOOD = env_int("LMRS_GOOD_KNOB", 4)
'''
    doc = "| `LMRS_GOOD_KNOB` | 4 | a knob |\n| `LMRS_GONE` | - | stale |\n"
    ctx = ctx_for({"lmrs_tpu/x.py": src}, docs={"docs/KNOBS.md": doc})
    findings = envpass.run(ctx)
    assert "env.direct-read" in rules(findings)
    undocumented = [f for f in findings
                    if f.rule == "env.knob-undocumented"]
    assert ["LMRS_BAD_KNOB" in f.message for f in undocumented] == [True]
    assert any(f.rule == "env.knob-stale" and "LMRS_GONE" in f.message
               for f in findings)


def test_env_module_itself_exempt():
    real = (REPO_ROOT / "lmrs_tpu/utils/env.py").read_text(encoding="utf-8")
    ctx = ctx_for({"lmrs_tpu/utils/env.py": real},
                  docs={"docs/KNOBS.md": ""})
    assert [f for f in envpass.run(ctx)
            if f.rule == "env.direct-read"] == []


# --------------------------------------------------------- golden rendering

def test_golden_finding_output():
    findings = locks.run(ctx_for({"lmrs_tpu/x.py": RACE_POSITIVE}))
    got = "\n".join(f.render() for f in findings)
    want = """\
lmrs_tpu/x.py:11: [race.unguarded-write] assignment to Pool._pinned outside `with _lock:`
    hint: guarded-by declared at line 7; hold _lock for the write, or mark the enclosing function `# holds-lock: _lock` if every caller already holds it
lmrs_tpu/x.py:14: [race.unguarded-write] read-modify-write (+=) to Pool.count outside `with _lock:`
    hint: guarded-by declared at line 8; hold _lock for the write, or mark the enclosing function `# holds-lock: _lock` if every caller already holds it
lmrs_tpu/x.py:17: [race.unguarded-write] .pop() mutation to Pool._pinned outside `with _lock:`
    hint: guarded-by declared at line 7; hold _lock for the write, or mark the enclosing function `# holds-lock: _lock` if every caller already holds it"""
    assert got == want


# ----------------------------------------------------------- baseline file

def test_baseline_accepts_counts_and_expires(tmp_path):
    findings = locks.run(ctx_for({"lmrs_tpu/x.py": RACE_POSITIVE}))
    assert len(findings) == 3
    path = tmp_path / "baseline.json"
    Baseline.from_findings(findings).save(path)

    # same findings -> all accepted, none new, none expired
    new, accepted, expired = Baseline.load(path).apply(findings)
    assert (len(new), len(accepted), expired) == (0, 3, [])

    # one fixed -> its key expires; the rest stay accepted
    new, accepted, expired = Baseline.load(path).apply(findings[:2])
    assert (len(new), len(accepted)) == (0, 2)
    assert len(expired) == 1 and "race.unguarded-write" in expired[0]

    # a NEW duplicate of an accepted key exceeds its count -> new
    new, accepted, expired = Baseline.load(path).apply(
        findings + [findings[0]])
    assert len(new) == 1 and len(accepted) == 3

    # schema is versioned
    doc = json.loads(path.read_text())
    assert doc["schema"] == "lmrs-lint-baseline-v1"
    with pytest.raises(ValueError):
        bad = tmp_path / "bad.json"
        bad.write_text('{"schema": "nope", "findings": {}}')
        Baseline.load(bad)


def test_baseline_keys_survive_line_shifts():
    f1 = locks.run(ctx_for({"lmrs_tpu/x.py": RACE_POSITIVE}))
    shifted = "\n\n\n" + RACE_POSITIVE
    f2 = locks.run(ctx_for({"lmrs_tpu/x.py": shifted}))
    assert [f.key for f in f1] != [] and \
        [f.key for f in f1] == [f.key for f in f2]
    assert [f.line for f in f1] != [f.line for f in f2]


def test_write_baseline_refuses_family_subset_runs():
    """--write-baseline from a --family subset would overwrite the whole
    baseline, silently discarding the families that did not run."""
    from lmrs_tpu.analysis.cli import main

    rc = main(["--family", "race", "--write-baseline",
               str(REPO_ROOT)])
    assert rc == 2
    # the checked-in baseline must be untouched (still valid + loadable)
    Baseline.load(REPO_ROOT / "lint-baseline.json")


# --------------------------------------------------------- repo-clean gate

def test_repo_is_lint_clean_against_checked_in_baseline():
    """The CI contract: the tree as committed has no NEW findings."""
    new, _accepted, expired = run_repo(REPO_ROOT)
    assert new == [], "\n" + "\n".join(f.render() for f in new)
    assert expired == [], f"prune expired baseline entries: {expired}"


# ----------------------------------------------- regression: fixed races

def test_tracer_recorded_counts_exactly_under_concurrency():
    """Tracer.recorded was a bare += from concurrent recorder threads —
    lost updates under load.  It now counts under the trace lock."""
    from lmrs_tpu.obs.trace import Tracer

    tr = Tracer(capacity=64)  # tiny ring: drops must not affect recorded
    threads, per = 8, 500

    def hammer():
        for i in range(per):
            tr.instant("spam", tid=1)

    ts = [threading.Thread(target=hammer) for _ in range(threads)]
    for t in ts:
        t.start()
    for t in ts:
        t.join()
    assert tr.recorded == threads * per


def test_router_host_counters_count_exactly_under_concurrency():
    """_Host.served/_Host.failed were bare += from dispatch-pool threads
    (one per in-flight request) — the PR 6 lost-update class, now routed
    through the per-host lock."""
    from lmrs_tpu.serving.router import _Host

    host = _Host("127.0.0.1:1")
    threads, per = 8, 500

    def hammer():
        for _ in range(per):
            host.note_served()
            host.note_failed()

    ts = [threading.Thread(target=hammer) for _ in range(threads)]
    for t in ts:
        t.start()
    for t in ts:
        t.join()
    assert host.served == threads * per
    assert host.failed == threads * per


# ------------------------------------------- regression: env parser bugs

def test_env_parser_empty_and_nonfinite_fall_back(monkeypatch):
    """The LMRS_POSTMORTEM_MIN_S=\"\" and NaN-duration bug class: empty
    means default, non-finite numbers never escape."""
    from lmrs_tpu.utils import env

    monkeypatch.setenv("LMRS_T_EMPTY", "")
    assert env.env_float("LMRS_T_EMPTY", 5.0) == 5.0
    assert env.env_int("LMRS_T_EMPTY", 7) == 7
    assert env.env_str("LMRS_T_EMPTY", "dflt") == "dflt"

    for bad in ("nan", "inf", "-inf", "NaN"):
        monkeypatch.setenv("LMRS_T_NUM", bad)
        assert env.env_float("LMRS_T_NUM", 5.0) == 5.0

    monkeypatch.setenv("LMRS_T_BOOL", "false")
    assert env.env_bool("LMRS_T_BOOL", True) is False
    monkeypatch.setenv("LMRS_T_BOOL", "banana")
    assert env.env_bool("LMRS_T_BOOL", True) is True

    monkeypatch.setenv("LMRS_T_CLAMP", "2")
    assert env.env_int("LMRS_T_CLAMP", 8, lo=4) == 4


def test_postmortem_throttle_survives_nan(monkeypatch):
    """A NaN LMRS_POSTMORTEM_MIN_S used to win every max() comparison's
    false branch and disable throttling (dump storm); the shared parser
    keeps the documented 5 s default."""
    from lmrs_tpu.obs import flight

    monkeypatch.setenv("LMRS_POSTMORTEM_MIN_S", "nan")
    assert flight._min_interval_s() == 5.0
    monkeypatch.setenv("LMRS_POSTMORTEM_MIN_S", "")
    assert flight._min_interval_s() == 5.0


def test_flash_block_empty_string_does_not_crash(monkeypatch):
    """LMRS_FLASH_BLOCK=\"\" used to raise ValueError at module import
    (int(\"\") at module scope); the parser folds it to the default."""
    from lmrs_tpu.utils.env import env_int

    monkeypatch.setenv("LMRS_FLASH_BLOCK", "")
    assert env_int("LMRS_FLASH_BLOCK", 1024, lo=128) == 1024


# ------------------------------------------------------------ shim smoke

def test_jax_compat_shard_map_resolves():
    """The compat shim must resolve on whichever jax is pinned — the
    class behind the five pre-existing test_kernels AttributeErrors."""
    from lmrs_tpu.utils.jax_compat import shard_map, tpu_compiler_params

    assert callable(shard_map)
    params = tpu_compiler_params(
        dimension_semantics=("parallel", "arbitrary"))
    assert params is not None
