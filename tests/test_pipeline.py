"""End-to-end pipeline + CLI tests (mock engine, CPU-only) — BASELINE.json
config #1."""

import asyncio
import json

import pytest

from lmrs_tpu.cli import main as cli_main
from lmrs_tpu.config import (
    ChunkConfig,
    DataConfig,
    EngineConfig,
    PipelineConfig,
    ReduceConfig,
)
from lmrs_tpu.pipeline import TranscriptSummarizer


def _cfg(**over):
    base = dict(
        chunk=ChunkConfig(max_tokens_per_chunk=200, overlap_tokens=0, context_tokens=40),
        engine=EngineConfig(backend="mock", retry_delay=0.0),
        reduce=ReduceConfig(max_tokens_per_batch=400, reserve_tokens=50),
    )
    base.update(over)
    return PipelineConfig(**base)


def test_end_to_end_mock(transcript):
    s = TranscriptSummarizer(_cfg())
    stats = s.summarize(transcript)
    assert stats["summary"]
    assert stats["num_chunks"] > 1
    assert stats["num_segments"] <= stats["num_input_segments"]
    assert stats["total_tokens_used"] > 0
    assert stats["failed_requests"] == 0
    assert set(stats["stage_times"]) >= {"preprocess", "chunk", "map", "reduce", "total"}


def test_async_facade(transcript):
    s = TranscriptSummarizer(_cfg())
    stats = asyncio.run(s.asummarize(transcript))
    assert stats["summary"]


def test_ctor_overrides():
    s = TranscriptSummarizer(
        backend="mock", model="tiny", max_tokens_per_chunk=512,
        max_concurrent_requests=3, hierarchical_aggregation=False,
    )
    assert s.config.engine.backend == "mock"
    assert s.config.chunk.max_tokens_per_chunk == 512
    assert s.config.engine.max_concurrent_requests == 3
    assert s.config.reduce.hierarchical is False


def test_limit_segments(transcript):
    cfg = _cfg(data=DataConfig(limit_segments=20))
    stats = TranscriptSummarizer(cfg).summarize(transcript)
    assert stats["num_input_segments"] == 20


def test_save_chunks_and_resume(transcript, tmp_path):
    dump = tmp_path / "chunks.json"
    cfg = _cfg()
    s1 = TranscriptSummarizer(cfg)
    stats1 = s1.summarize(transcript, save_chunks=str(dump))
    payload = json.loads(dump.read_text())
    assert len(payload["chunks"]) == stats1["num_chunks"]
    assert all(c["summary"] for c in payload["chunks"])

    # resume: all chunks rehydrated, no new map work
    s2 = TranscriptSummarizer(cfg)
    stats2 = s2.summarize(transcript, resume_from=str(dump))
    assert stats2["num_resumed_chunks"] == stats1["num_chunks"]
    # only reduce-stage requests were issued (num map requests == 0)
    assert stats2["total_requests"] < stats1["total_requests"]
    assert stats2["summary"]


def test_custom_prompts_flow_through(transcript, tmp_path):
    pf = tmp_path / "map.txt"
    pf.write_text("MYMAP {transcript}")
    sf = tmp_path / "sys.txt"
    sf.write_text("You are terse.")
    af = tmp_path / "agg.txt"
    af.write_text("MYREDUCE {summaries}")
    stats = TranscriptSummarizer(_cfg()).summarize(
        transcript,
        prompt_file=str(pf),
        system_prompt_file=str(sf),
        aggregator_prompt_file=str(af),
    )
    assert stats["summary"]


def test_prompt_missing_placeholder_is_fixed(transcript, tmp_path):
    pf = tmp_path / "map.txt"
    pf.write_text("No placeholder at all")
    stats = TranscriptSummarizer(_cfg()).summarize(transcript, prompt_file=str(pf))
    assert stats["summary"]


def test_cli_end_to_end(transcript, tmp_path, capsys):
    inp = tmp_path / "t.json"
    inp.write_text(json.dumps(transcript))
    out = tmp_path / "summary.txt"
    rc = cli_main([
        "--input", str(inp), "--output", str(out), "--backend", "mock",
        "--max-tokens-per-chunk", "300", "--report", "--quiet",
    ])
    assert rc == 0
    assert out.read_text()
    report = json.loads((tmp_path / "summary.txt.report.json").read_text())
    assert report["num_chunks"] >= 1
    assert "summary" not in report


def test_cli_missing_input(tmp_path):
    assert cli_main(["--input", str(tmp_path / "nope.json"), "--quiet"]) == 1


def test_reference_example_end_to_end(example_transcript):
    """Full 7.4h reference fixture through the mock pipeline (parity with the
    reference's offline mock run, BASELINE.md)."""
    cfg = PipelineConfig(
        engine=EngineConfig(backend="mock", retry_delay=0.0),
        chunk=ChunkConfig(max_tokens_per_chunk=4000, context_tokens=150),
    )
    stats = TranscriptSummarizer(cfg).summarize(example_transcript)
    assert stats["num_input_segments"] == 4778
    # reference baseline: 4778 -> ~171 merged segments, ~23 chunks (BASELINE.md)
    assert 50 <= stats["num_segments"] <= 400
    assert 10 <= stats["num_chunks"] <= 60
    assert stats["summary"]


def test_prompt_file_with_literal_braces(transcript, tmp_path):
    """User prompt files may embed JSON examples; literal braces must not
    crash formatting (safe_format, not str.format)."""
    pf = tmp_path / "map.txt"
    pf.write_text('Return JSON like {"topic": "..."}\n\n{transcript}')
    stats = TranscriptSummarizer(_cfg()).summarize(transcript, prompt_file=str(pf))
    assert stats["summary"]


def test_unknown_backend_is_value_error(transcript):
    from lmrs_tpu.config import EngineConfig as EC
    cfg = _cfg(engine=EC(backend="nope"))
    with pytest.raises(ValueError):
        TranscriptSummarizer(cfg).summarize(transcript)


def test_summarize_many_pools_map_requests():
    """Multi-transcript batching (BASELINE config #5): one pooled map queue,
    per-transcript reduce + stats."""
    from lmrs_tpu.config import PipelineConfig, EngineConfig, ChunkConfig
    from lmrs_tpu.pipeline import TranscriptSummarizer

    def transcript(n, tag):
        return {"segments": [
            {"start": i * 2.0, "end": i * 2.0 + 1.5,
             "text": f"{tag} segment {i} talks about item {i % 7}.",
             "speaker": f"SPEAKER_0{i % 2}"}
            for i in range(n)]}

    s = TranscriptSummarizer(PipelineConfig(
        engine=EngineConfig(backend="mock"),
        chunk=ChunkConfig(max_tokens_per_chunk=256, tokenizer="approx"),
    ))
    results = s.summarize_many([transcript(40, "alpha"), transcript(25, "beta")])
    assert len(results) == 2
    for r in results:
        assert r["summary"]
        assert r["num_chunks"] >= 1
    assert results[0].get("failed_requests") == 0
    # per-transcript fields differ, pooled accounting is shared
    assert results[0]["num_input_segments"] == 40
    assert results[1]["num_input_segments"] == 25
    assert results[0]["total_requests"] == results[1]["total_requests"]


def test_resume_fingerprint_mismatch_drops_stale_summaries(transcript, tmp_path):
    """ISSUE 7 satellite: a --save-chunks dump produced under a different
    map prompt / model surface must NOT rehydrate — _load_resume compares
    the dump's config/prompt fingerprint and drops everything on
    mismatch (warn + drop), instead of silently mixing stale summaries
    into the fresh run."""
    dump = tmp_path / "chunks.json"
    cfg = _cfg()
    stats1 = TranscriptSummarizer(cfg).summarize(transcript,
                                                 save_chunks=str(dump))
    payload = json.loads(dump.read_text())
    assert payload["fingerprint"]  # dumps are stamped now

    # same config, DIFFERENT map prompt -> different fingerprint
    stats2 = TranscriptSummarizer(cfg).summarize(
        transcript, resume_from=str(dump),
        prompt_template="Changed prompt {transcript}")
    assert stats2["num_resumed_chunks"] == 0
    assert stats2["total_requests"] >= stats1["total_requests"]

    # a dump predating the fingerprint field still loads (chunk-identity
    # match stays the only guard, as before)
    payload.pop("fingerprint")
    dump.write_text(json.dumps(payload))
    stats3 = TranscriptSummarizer(cfg).summarize(transcript,
                                                 resume_from=str(dump))
    assert stats3["num_resumed_chunks"] == stats1["num_chunks"]


def test_summarize_many_threads_real_resume_counts(tmp_path):
    """ISSUE 7 satellite: summarize_many no longer hardcodes
    num_resumed_chunks=0 — resume_from aligns per transcript, rehydrated
    chunks skip the pooled map queue, and each stats dict reports its
    transcript's real count."""
    from lmrs_tpu.config import ChunkConfig, EngineConfig, PipelineConfig
    from lmrs_tpu.pipeline import TranscriptSummarizer

    def transcript(n, tag):
        return {"segments": [
            {"start": i * 2.0, "end": i * 2.0 + 1.5,
             "text": f"{tag} segment {i} talks about item {i % 7}.",
             "speaker": f"SPEAKER_0{i % 2}"}
            for i in range(n)]}

    cfg = PipelineConfig(
        engine=EngineConfig(backend="mock", retry_delay=0.0),
        chunk=ChunkConfig(max_tokens_per_chunk=200, tokenizer="approx"))
    a, b = transcript(40, "alpha"), transcript(25, "beta")
    dump = tmp_path / "alpha.json"
    ref = TranscriptSummarizer(cfg).summarize(a, save_chunks=str(dump))
    assert ref["num_chunks"] > 1

    s = TranscriptSummarizer(cfg)
    out = s.summarize_many([a, b], resume_from=[str(dump), None])
    assert out[0]["num_resumed_chunks"] == ref["num_chunks"]
    assert out[1]["num_resumed_chunks"] == 0
    assert out[0]["summary"] and out[1]["summary"]
    # alpha's rehydrated chunks never re-entered the pooled queue: the
    # shared accounting only paid for beta's map + both reduce trees
    assert out[0]["total_requests"] < ref["total_requests"] + out[1]["num_chunks"]

    with pytest.raises(ValueError, match="resume_from"):
        s.summarize_many([a, b], resume_from=[str(dump)])
