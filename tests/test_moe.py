"""MoE FFN (ops/moe.py), the ep mesh axis, and MoE end-to-end paths."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from lmrs_tpu.config import MeshConfig, ModelConfig, model_preset
from lmrs_tpu.models.transformer import forward, init_kv_cache, init_params
from lmrs_tpu.ops.moe import expert_capacity, moe_mlp


def _moe_cfg(**kw) -> ModelConfig:
    base = dict(vocab_size=512, dim=64, n_layers=2, n_heads=4, n_kv_heads=2,
                hidden_dim=96, n_experts=4, n_experts_per_token=2,
                max_seq_len=256)
    base.update(kw)
    return ModelConfig(name="test-moe", **base)


def test_expert_capacity_bounds():
    cfg = _moe_cfg(n_experts=4, n_experts_per_token=2, expert_capacity_factor=1.0)
    # 32 tokens, k=2, E=4 -> 16 per expert at factor 1.0
    assert expert_capacity(32, cfg) == 16
    assert expert_capacity(1, cfg) == 1  # floor at 1
    cfg_big = _moe_cfg(expert_capacity_factor=100.0)
    assert expert_capacity(8, cfg_big) == 8  # capped at n_tokens


def test_moe_single_expert_equals_dense():
    """E=1, k=1: routing is a no-op, so MoE == dense SwiGLU on same weights."""
    cfg = _moe_cfg(n_experts=1, n_experts_per_token=1,
                   expert_capacity_factor=4.0, dtype="float32")
    key = jax.random.PRNGKey(0)
    d, f = cfg.dim, cfg.hidden_dim
    w_gate = jax.random.normal(key, (1, d, f), jnp.float32) * 0.05
    w_up = jax.random.normal(jax.random.fold_in(key, 1), (1, d, f), jnp.float32) * 0.05
    w_down = jax.random.normal(jax.random.fold_in(key, 2), (1, f, d), jnp.float32) * 0.05
    mp = {"router": jnp.zeros((d, 1), jnp.float32),
          "w_gate": w_gate, "w_up": w_up, "w_down": w_down}
    x = jax.random.normal(jax.random.fold_in(key, 3), (2, 8, d), jnp.float32)

    out, aux = moe_mlp(mp, cfg, x)
    gate = jnp.einsum("bsd,df->bsf", x, w_gate[0])
    up = jnp.einsum("bsd,df->bsf", x, w_up[0])
    dense = jnp.einsum("bsf,fd->bsd", jax.nn.silu(gate) * up, w_down[0])
    np.testing.assert_allclose(np.asarray(out), np.asarray(dense), rtol=1e-5, atol=1e-5)
    assert float(aux) == pytest.approx(1.0)  # E=1 is perfectly "balanced"


def test_moe_uniform_router_aux_is_one():
    """Zero router -> uniform probs; Switch aux = E * sum(f*P) with P=1/E
    sums to exactly 1 regardless of how ties break."""
    cfg = _moe_cfg(dtype="float32")
    d, f, e = cfg.dim, cfg.hidden_dim, cfg.n_experts
    key = jax.random.PRNGKey(1)
    mp = {"router": jnp.zeros((d, e), jnp.float32),
          "w_gate": jax.random.normal(key, (e, d, f)) * 0.05,
          "w_up": jax.random.normal(key, (e, d, f)) * 0.05,
          "w_down": jax.random.normal(key, (e, f, d)) * 0.05}
    x = jax.random.normal(jax.random.fold_in(key, 1), (1, 16, d), jnp.float32)
    _, aux = moe_mlp(mp, cfg, x)
    assert float(aux) == pytest.approx(1.0, abs=1e-5)


def test_moe_capacity_overflow_is_finite_and_lossy():
    """Starved capacity drops expert contributions but never NaNs."""
    cfg_full = _moe_cfg(expert_capacity_factor=8.0, dtype="float32")
    cfg_starved = _moe_cfg(expert_capacity_factor=0.05, dtype="float32")
    d, f, e = cfg_full.dim, cfg_full.hidden_dim, cfg_full.n_experts
    key = jax.random.PRNGKey(2)
    # skewed router: all tokens prefer expert 0 -> overflow under low capacity
    router = jnp.zeros((d, e), jnp.float32).at[:, 0].set(0.1)
    mp = {"router": router,
          "w_gate": jax.random.normal(key, (e, d, f)) * 0.05,
          "w_up": jax.random.normal(jax.random.fold_in(key, 1), (e, d, f)) * 0.05,
          "w_down": jax.random.normal(jax.random.fold_in(key, 2), (e, f, d)) * 0.05}
    x = jax.random.normal(jax.random.fold_in(key, 3), (2, 32, d), jnp.float32)
    out_full, _ = moe_mlp(mp, cfg_full, x)
    out_starved, _ = moe_mlp(mp, cfg_starved, x)
    assert np.isfinite(np.asarray(out_starved)).all()
    # overflow must actually change the result (contributions dropped)
    assert not np.allclose(np.asarray(out_full), np.asarray(out_starved))


def test_moe_forward_cache_matches_nocache():
    """Prefill through the dense KV cache == cache-less forward (same tokens)."""
    cfg = _moe_cfg(dtype="float32", expert_capacity_factor=8.0)
    params = init_params(cfg, jax.random.PRNGKey(0))
    b, s = 2, 12
    tokens = jax.random.randint(jax.random.PRNGKey(1), (b, s), 0, cfg.vocab_size)
    positions = jnp.broadcast_to(jnp.arange(s)[None], (b, s))
    logits_nc, _ = forward(params, cfg, tokens, positions)
    cache = init_kv_cache(cfg, b, 32)
    kv_len = jnp.full((b,), s, jnp.int32)
    logits_c, _ = forward(params, cfg, tokens, positions, cache=cache, kv_length=kv_len)
    np.testing.assert_allclose(np.asarray(logits_nc), np.asarray(logits_c),
                               rtol=2e-4, atol=2e-4)


def test_moe_presets():
    tiny = model_preset("tiny-moe")
    assert tiny.n_experts == 4
    mix = model_preset("mixtral-8x7b")
    assert mix.n_experts == 8 and mix.n_experts_per_token == 2
    assert mix.vocab_size == 32000


def test_moe_train_step_on_ep_mesh():
    """One sharded train step on a dp=2 x tp=2 x ep=2 mesh: loss finite,
    expert weights actually sharded over ep."""
    import optax

    from lmrs_tpu.parallel.mesh import build_mesh
    from lmrs_tpu.parallel.sharding import shard_params
    from lmrs_tpu.training.train import make_train_step

    cfg = _moe_cfg(vocab_size=256)
    mesh_cfg = MeshConfig(dp=2, tp=2, ep=2)
    mesh = build_mesh(mesh_cfg, jax.devices()[:8])
    params = init_params(cfg, jax.random.PRNGKey(0))
    params = shard_params(params, mesh, cfg.tie_embeddings, moe=True)
    # expert axis [L, E, D, F] sharded over ep=2
    wg = params["layers"]["moe"]["w_gate"]
    assert wg.sharding.spec[1] == "ep"

    optimizer = optax.adamw(1e-3)
    opt_state = optimizer.init(params)
    step = make_train_step(cfg, optimizer, mesh)
    tokens = jnp.asarray(
        np.random.default_rng(0).integers(0, cfg.vocab_size, (4, 64), dtype=np.int32))
    params, opt_state, loss = step(params, opt_state, tokens)
    assert np.isfinite(float(loss))


def test_moe_pp_loss_includes_router_aux():
    """Pipeline-parallel loss must include the router load-balance term:
    changing router_aux_coef changes the pp loss (it is not silently dropped)."""
    import dataclasses

    from lmrs_tpu.parallel.mesh import build_mesh
    from lmrs_tpu.parallel.pipeline import pipeline_causal_lm_loss

    cfg0 = _moe_cfg(vocab_size=256, router_aux_coef=0.0)
    cfg1 = dataclasses.replace(cfg0, router_aux_coef=0.5)
    mesh = build_mesh(MeshConfig(dp=2, pp=2), jax.devices()[:4])
    params = init_params(cfg0, jax.random.PRNGKey(0))
    tokens = jnp.asarray(
        np.random.default_rng(2).integers(0, 256, (8, 32), dtype=np.int32))

    loss0 = float(pipeline_causal_lm_loss(params, cfg0, tokens, mesh, n_micro=2))
    loss1 = float(pipeline_causal_lm_loss(params, cfg1, tokens, mesh, n_micro=2))
    assert np.isfinite(loss0) and np.isfinite(loss1)
    # aux ~ O(1), coef 0.5 -> visible difference
    assert abs(loss1 - loss0) > 1e-3


def test_moe_generation_through_engine():
    """tiny-moe generates through the continuous-batching engine."""
    from lmrs_tpu.config import EngineConfig
    from lmrs_tpu.engine.api import GenerationRequest, make_engine

    eng_cfg = EngineConfig(backend="jax", model="tiny-moe", max_tokens=8,
                           max_batch_slots=2, num_pages=64, page_size=16)
    engine = make_engine(eng_cfg, model_cfg=_moe_cfg(expert_capacity_factor=8.0))
    try:
        reqs = [GenerationRequest(prompt="hello world", request_id=i, max_new_tokens=8)
                for i in range(3)]
        results = engine.generate_batch(reqs)
    finally:
        engine.shutdown()
    assert len(results) == 3
    for r in results:
        assert r.error is None
        assert isinstance(r.text, str)
