"""Host-RAM KV spill tier (ISSUE 12 / ROADMAP item 3): spilled radix
nodes, device→host capture, prefetch-on-match promotion, host budget LRU,
fault degradation, and the scheduler-level greedy token-identity A/B —
spill on vs off, bf16 and int8 pools — plus an eviction/spill/prefetch
interleave fuzz closed on the allocator/radix auditors."""

from __future__ import annotations

import numpy as np
import pytest

from lmrs_tpu.config import EngineConfig, ModelConfig
from lmrs_tpu.engine.api import GenerationRequest
from lmrs_tpu.engine.host_kv import DiskKVPool, HostKVPool
from lmrs_tpu.engine.jax_engine import JaxEngine
from lmrs_tpu.engine.kv_cache import PageAllocator, audit_allocator
from lmrs_tpu.engine.prefix_cache import PrefixCache
from lmrs_tpu.testing import faults
from lmrs_tpu.testing.faults import FaultPlan

PS = 4  # page size for the pure-host tree tests
PAGE_BYTES = 2 * PS  # fake payload: k+v, one "layer/head/dim" byte per token


class _FakeKV:
    """Stands in for PagedKVCache in pure-host tests: capture returns a
    content-tagged payload, import records the scatter so tests can
    assert the round trip without a device."""

    def __init__(self):
        self.imports: list[tuple[tuple[int, ...], dict]] = []

    def capture(self, pages: list[int]) -> dict:
        n = len(pages)
        k = np.asarray(pages, np.uint8).reshape(1, n, 1, 1, 1)
        k = np.broadcast_to(k, (1, n, 1, PS, 1)).copy()
        return {"k": k, "v": k.copy(), "dtype": "uint8"}

    def import_pages(self, pages, payload, sync=False):
        self.imports.append((tuple(pages), payload))


def _cache(num_pages: int = 64, budget_pages: int = 1 << 20, **kw):
    a = PageAllocator(num_pages)
    pool = HostKVPool(budget_pages * PAGE_BYTES)
    kv = _FakeKV()
    c = PrefixCache(a, PS, spill_pool=pool, capture_cb=kv.capture,
                    page_bytes=PAGE_BYTES, **kw)
    return a, c, kv


def _audit_clean(a: PageAllocator, c: PrefixCache, live: list[list[int]]):
    holders: dict[int, int] = {}
    for pages in live:
        for p in pages:
            holders[p] = holders.get(p, 0) + 1
    for p in c.retained_pages():
        holders[p] = holders.get(p, 0) + 1
    violations = c.audit() + audit_allocator(a, a.num_pages, holders)
    assert violations == [], violations


# ------------------------------------------------------------- pure tree


def test_evict_spills_and_match_prefetches():
    a, c, kv = _cache()
    ids = list(range(100, 113))  # 3 full pages + remainder
    seq = a.alloc(4)
    c.insert(ids, seq)
    a.free(seq)  # sequence closes: nodes refcount-zero
    assert c.evict(10) == 3  # device pages freed...
    assert c.cached_pages == 0
    assert c.spilled_pages() == 3  # ...but the content spilled, not gone
    _audit_clean(a, c, [])

    # a legacy match() sees nothing (resident walk stops at the spill)
    got, n = c.match(ids)
    assert (got, n) == ([], 0)

    # the spill-aware probe reports the chain; prefetch promotes it back
    pages, res_tok, chain = c.match_hier(ids)
    assert (pages, res_tok) == ([], 0)
    assert len(chain) == 1 and chain[0][1] == 12
    node, n_tok = chain[0]
    dest = a.alloc(3)
    assert c.prefetch_into(node, dest, kv) == 3
    assert kv.imports and kv.imports[0][0] == tuple(dest)
    # the payload round-tripped the original page ids as content
    assert kv.imports[0][1]["k"][0, :, 0, 0, 0].tolist() == seq[:3]
    assert c.cached_pages == 3 and c.spilled_pages() == 0
    _audit_clean(a, c, [dest])  # dest doubles as "the sequence's" pages
    a.free(dest)
    # now resident again: a plain match hits
    got, n = c.match(ids)
    assert n == 12 and got == dest
    a.free(got)
    _audit_clean(a, c, [])


def test_insert_promotes_spilled_nodes():
    """A sequence that re-prefilled a spilled span donates its own pages:
    the node promotes back to resident and the host entry drops."""
    a, c, _kv = _cache()
    ids = [7] * 9  # 2 full pages
    p1 = a.alloc(3)
    c.insert(ids, p1)
    a.free(p1)
    c.evict(10)
    assert c.spilled_pages() == 2
    p2 = a.alloc(3)  # the re-prefilled sequence
    assert c.insert(ids, p2) == 2  # promotion counts as adoption
    assert c.cached_pages == 2 and c.spilled_pages() == 0
    got, n = c.match(ids)
    assert n == 8 and got == p2[:2]
    a.free(got)
    _audit_clean(a, c, [p2])
    a.free(p2)


def test_host_budget_lru_drops_oldest_subtree():
    a, c, _kv = _cache(budget_pages=4)  # host pool holds 4 pages
    entries = []
    for base in (10, 40, 70):  # three disjoint 2-page prefixes
        ids = [base + i for i in range(9)]
        pages = a.alloc(2)
        c.insert(ids, pages)
        a.free(pages)
        entries.append(ids)
    assert c.evict(100) == 6  # all spill; pool budget 4 -> oldest drops
    assert c.spilled_pages() == 4
    assert c.pool.dropped_pages_total == 2
    _audit_clean(a, c, [])
    # the dropped (oldest) prefix is gone; the two recent ones survive
    assert c.match_hier(entries[0])[2] == []
    assert c.match_hier(entries[1])[2] != []
    assert c.match_hier(entries[2])[2] != []


def test_oversized_entry_skips_spill_entirely():
    a, c, kv = _cache(budget_pages=1)  # nothing with >1 page ever fits
    ids = list(range(0, 13))
    pages = a.alloc(4)
    c.insert(ids, pages)
    a.free(pages)
    assert c.evict(10) == 3
    assert c.spilled_pages() == 0  # dropped, not spilled
    assert kv.imports == []
    _audit_clean(a, c, [])


def test_spill_fault_degrades_to_plain_drop():
    a, c, _kv = _cache()
    ids = [3] * 9
    pages = a.alloc(3)
    c.insert(ids, pages)
    a.free(pages)
    with faults.injected(FaultPlan(faults=[
            {"site": "prefix.spill", "p": 1.0}])):
        assert c.evict(10) == 2
    assert c.spilled_pages() == 0  # capture faulted: evict-means-gone
    assert c.match_hier(ids) == ([], 0, [])
    _audit_clean(a, c, [])


def test_prefetch_raises_after_host_drop():
    """An entry the host budget dropped between match and prefetch must
    raise (the scheduler then re-prefills) — never import stale state."""
    a, c, kv = _cache(budget_pages=4)
    ids = [9] * 9
    pages = a.alloc(3)
    c.insert(ids, pages)
    a.free(pages)
    c.evict(10)
    _pages, _tok, chain = c.match_hier(ids)
    node, _n = chain[0]
    # host pressure drops the entry under us
    c.pool.budget_bytes = 0
    c._enforce_host_budget()
    dest = a.alloc(2)
    with pytest.raises(RuntimeError):
        c.prefetch_into(node, dest, kv)
    a.free(dest)
    _audit_clean(a, c, [])


def test_shared_nodes_never_spill():
    a, c, _kv = _cache()
    ids = list(range(200, 209))
    pages = a.alloc(3)
    c.insert(ids, pages)
    assert c.evict(10) == 0  # live sequence shares the pages
    assert c.spilled_pages() == 0
    a.free(pages)
    assert c.evict(10) == 2
    assert c.spilled_pages() == 2
    _audit_clean(a, c, [])


def test_clear_drops_both_tiers():
    a, c, _kv = _cache()
    for base in (10, 40):
        ids = [base + i for i in range(9)]
        pages = a.alloc(2)
        c.insert(ids, pages)
        a.free(pages)
    c.evict(2)  # one prefix spilled, one resident
    assert c.spilled_pages() == 2 and c.cached_pages == 2
    c.clear()
    assert c.spilled_pages() == 0 and c.cached_pages == 0
    assert c.pool.used_bytes == 0
    assert a.free_count == a.num_pages - 1
    _audit_clean(a, c, [])


# --------------------------------------------------------- interleave fuzz


@pytest.mark.parametrize("seed", [0, 1])
def test_fuzzed_spill_prefetch_interleave(seed):
    """Random insert/close/evict/match+prefetch/budget-squeeze interleave:
    the radix auditor, the host-pool accounting cross-check, and the
    allocator page-conservation audit stay clean after EVERY op."""
    rng = np.random.default_rng(seed)
    a, c, kv = _cache(num_pages=48, budget_pages=8)
    live: list[list[int]] = []
    prefixes = [[int(b) + i for i in range(int(rng.integers(5, 14)))]
                for b in (10, 40, 70, 100)]
    for _step in range(120):
        op = rng.integers(0, 5)
        if op == 0 and a.free_count >= 6:  # open+insert a sharing seq
            ids = list(prefixes[int(rng.integers(0, len(prefixes)))]) + [
                int(t) for t in rng.integers(200, 250, 4)]
            pages = a.alloc(-(-len(ids) // PS))
            c.insert(ids, pages)
            live.append(pages)
        elif op == 1 and live:  # close a live sequence
            a.free(live.pop(int(rng.integers(0, len(live)))))
        elif op == 2:  # device pressure
            c.evict(int(rng.integers(1, 6)))
        elif op == 3:  # match + prefetch (a spilled-hit admission)
            ids = list(prefixes[int(rng.integers(0, len(prefixes)))]) + [99]
            pages, _tok, chain = c.match_hier(ids)
            got = list(pages)
            for node, n_tok in chain:
                need = n_tok // PS
                if a.free_count < need:
                    break
                dest = a.alloc(need)
                try:
                    c.prefetch_into(node, dest, kv)
                except RuntimeError:
                    a.free(dest)
                    break
                got += dest
            if got:
                live.append(got)  # the admitted sequence's cloned prefix
        else:  # host budget squeeze + restore
            c.pool.budget_bytes = int(rng.integers(0, 8)) * PAGE_BYTES
            c._enforce_host_budget()
            c.pool.budget_bytes = 8 * PAGE_BYTES
        _audit_clean(a, c, live)
    for pages in live:
        a.free(pages)
    c.clear()
    _audit_clean(a, c, [])
    assert a.free_count == a.num_pages - 1


# ------------------------------------------------------------- disk tier


def _cache3(tmp_path, num_pages: int = 64, host_pages: int = 1 << 20,
            disk_pages: int = 1 << 20, **kw):
    """Three-tier pure-host fixture: HBM tree + host pool + disk pool."""
    a = PageAllocator(num_pages)
    disk = DiskKVPool(disk_pages * PAGE_BYTES, str(tmp_path))
    pool = HostKVPool(host_pages * PAGE_BYTES, disk=disk)
    kv = _FakeKV()
    c = PrefixCache(a, PS, spill_pool=pool, capture_cb=kv.capture,
                    page_bytes=PAGE_BYTES, **kw)
    return a, c, kv


def test_disk_demote_promote_round_trip(tmp_path):
    """Host pressure demotes to a content-tagged spill file; a later
    match promotes disk→host→device with the ORIGINAL bytes."""
    a, c, kv = _cache3(tmp_path)
    ids = list(range(100, 113))
    seq = a.alloc(4)
    c.insert(ids, seq)
    a.free(seq)
    assert c.evict(10) == 3
    assert c.spilled_pages() == 3 and c.disk_pages() == 0
    # host squeeze: the entry moves DOWN a tier instead of dropping
    c.pool.budget_bytes = 0
    c._enforce_host_budget()
    c.pool.budget_bytes = 1 << 30
    assert c.spilled_pages() == 0 and c.disk_pages() == 3
    disk = c.disk
    assert disk.demoted_pages_total == 3
    assert c.pool.dropped_pages_total == 0  # a demotion is not a loss
    desc = next(iter(disk.entries.values()))[0].spill
    assert desc["disk"] and desc["crc"]
    _audit_clean(a, c, [])

    _pages, _tok, chain = c.match_hier(ids)
    assert len(chain) == 1 and chain[0][1] == 12
    node, _n = chain[0]
    dest = a.alloc(3)
    assert c.prefetch_into(node, dest, kv) == 3
    # content round-tripped THROUGH the file: k still tags the original
    # device page ids the _FakeKV capture encoded
    assert kv.imports[0][1]["k"][0, :, 0, 0, 0].tolist() == seq[:3]
    assert disk.promoted_pages_total == 3
    assert c.disk_pages() == 0 and c.cached_pages == 3
    assert disk.used_bytes == 0
    _audit_clean(a, c, [dest])
    a.free(dest)
    _audit_clean(a, c, [])


def test_one_lru_clock_across_tiers(tmp_path):
    """Budget pressure cascades host→disk→gone in ONE recency order:
    the newest prefix stays on the host, the middle demotes to disk,
    and the oldest falls off the end of the disk budget."""
    a, c, _kv = _cache3(tmp_path, host_pages=2, disk_pages=2)
    entries = []
    for base in (10, 40, 70):  # three disjoint 2-page prefixes, in age order
        ids = [base + i for i in range(9)]
        pages = a.alloc(2)
        c.insert(ids, pages)
        a.free(pages)
        entries.append(ids)
    assert c.evict(100) == 6
    assert c.spilled_pages() == 2 and c.disk_pages() == 2
    assert c.disk.dropped_pages_total == 2  # the oldest fell off disk
    _audit_clean(a, c, [])
    assert c.match_hier(entries[0])[2] == []   # oldest: gone
    assert c.match_hier(entries[1])[2] != []   # middle: survives (disk)
    assert c.match_hier(entries[2])[2] != []   # newest: survives (host)
    mid = c.match_hier(entries[1])[2][0][0]
    new = c.match_hier(entries[2])[2][0][0]
    assert mid.spill.get("disk") and not new.spill.get("disk")


def test_torn_disk_file_degrades_to_reprefill(tmp_path):
    """A truncated spill file fails the size/crc gate: the prefetch
    raises (the scheduler re-prefills), the entry drops so the tree
    stops advertising it, and the auditors stay clean."""
    a, c, kv = _cache3(tmp_path)
    ids = [7] * 9
    pages = a.alloc(3)
    c.insert(ids, pages)
    a.free(pages)
    c.evict(10)
    c.pool.budget_bytes = 0
    c._enforce_host_budget()
    c.pool.budget_bytes = 1 << 30
    node = c.match_hier(ids)[2][0][0]
    with open(node.spill["path"], "r+b") as f:  # tear the file
        f.truncate(3)
    dest = a.alloc(2)
    with pytest.raises(RuntimeError):
        c.prefetch_into(node, dest, kv)
    a.free(dest)
    assert kv.imports == []  # nothing ever scattered to the device
    assert c.disk.read_failures_total == 1
    assert c.match_hier(ids) == ([], 0, [])
    assert c.disk_pages() == 0 and c.disk.used_bytes == 0
    _audit_clean(a, c, [])


def test_corrupt_disk_file_fails_crc(tmp_path):
    """Same size, different bytes: the crc content tag catches it."""
    a, c, kv = _cache3(tmp_path)
    ids = [5] * 9
    pages = a.alloc(3)
    c.insert(ids, pages)
    a.free(pages)
    c.evict(10)
    c.pool.budget_bytes = 0
    c._enforce_host_budget()
    c.pool.budget_bytes = 1 << 30
    node = c.match_hier(ids)[2][0][0]
    with open(node.spill["path"], "r+b") as f:
        f.seek(0)
        f.write(b"\xff")
    dest = a.alloc(2)
    with pytest.raises(RuntimeError):
        c.prefetch_into(node, dest, kv)
    a.free(dest)
    assert c.disk.read_failures_total == 1
    assert c.match_hier(ids) == ([], 0, [])
    _audit_clean(a, c, [])


def test_disk_read_fault_site(tmp_path):
    """The injected kv.disk_read fault degrades exactly like a torn
    file: raise, drop, re-prefill — never a wedged admission."""
    a, c, kv = _cache3(tmp_path)
    ids = [3] * 9
    pages = a.alloc(3)
    c.insert(ids, pages)
    a.free(pages)
    c.evict(10)
    c.pool.budget_bytes = 0
    c._enforce_host_budget()
    c.pool.budget_bytes = 1 << 30
    node = c.match_hier(ids)[2][0][0]
    dest = a.alloc(2)
    with faults.injected(FaultPlan(faults=[
            {"site": "kv.disk_read", "p": 1.0}])):
        with pytest.raises(RuntimeError):
            c.prefetch_into(node, dest, kv)
    a.free(dest)
    assert c.match_hier(ids) == ([], 0, [])
    _audit_clean(a, c, [])


def test_spill_payload_reads_either_tier_without_promoting(tmp_path):
    """Migration export reads warm state in place: a host entry returns
    its payload, a disk entry reads its file back — neither promotes;
    a torn disk file returns None and drops the entry."""
    a, c, _kv = _cache3(tmp_path, host_pages=2)
    host_ids = [9] * 9
    disk_ids = [4] * 9
    for ids in (disk_ids, host_ids):  # disk_ids older -> demotes first
        pages = a.alloc(3)
        c.insert(ids, pages)
        a.free(pages)
    c.evict(100)  # 4 spilled pages vs 2-page host budget: LRU demotes
    assert c.spilled_pages() == 2 and c.disk_pages() == 2
    hn = c.match_hier(host_ids)[2][0][0]
    dn = c.match_hier(disk_ids)[2][0][0]
    assert not hn.spill.get("disk") and dn.spill.get("disk")
    for node in (hn, dn):
        pay = c.spill_payload(node)
        assert pay is not None and "k" in pay and not pay.get("disk")
    # reading promoted nothing: both entries still live in their tiers
    assert c.spilled_pages() == 2 and c.disk_pages() == 2
    with open(dn.spill["path"], "r+b") as f:
        f.truncate(1)
    assert c.spill_payload(dn) is None
    assert c.match_hier(disk_ids)[2] == []
    _audit_clean(a, c, [])


def test_clear_drops_disk_tier_and_files(tmp_path):
    a, c, _kv = _cache3(tmp_path, host_pages=2)
    for base in (10, 40):
        ids = [base + i for i in range(9)]
        pages = a.alloc(2)
        c.insert(ids, pages)
        a.free(pages)
    c.evict(100)
    assert c.disk_pages() > 0
    paths = [node.spill["path"]
             for node, _nb in c.disk.entries.values()]
    c.clear()
    assert c.disk_pages() == 0 and c.disk.used_bytes == 0
    assert c.spilled_pages() == 0
    assert all(not __import__("os").path.exists(p) for p in paths)
    _audit_clean(a, c, [])


@pytest.mark.parametrize("seed", [0, 1, 2])
def test_fuzzed_three_tier_interleave(seed, tmp_path):
    """Random insert/close/evict/prefetch/host-squeeze/disk-squeeze
    interleave across ALL THREE tiers, auditors clean after every op
    (the radix auditor cross-checks both pools' accounting and the
    spill files' existence)."""
    rng = np.random.default_rng(seed)
    a, c, kv = _cache3(tmp_path, num_pages=48, host_pages=6, disk_pages=6)
    live: list[list[int]] = []
    prefixes = [[int(b) + i for i in range(int(rng.integers(5, 14)))]
                for b in (10, 40, 70, 100)]
    for _step in range(150):
        op = rng.integers(0, 6)
        if op == 0 and a.free_count >= 6:
            ids = list(prefixes[int(rng.integers(0, len(prefixes)))]) + [
                int(t) for t in rng.integers(200, 250, 4)]
            pages = a.alloc(-(-len(ids) // PS))
            c.insert(ids, pages)
            live.append(pages)
        elif op == 1 and live:
            a.free(live.pop(int(rng.integers(0, len(live)))))
        elif op == 2:
            c.evict(int(rng.integers(1, 6)))
        elif op == 3:  # match + prefetch (either spilled tier)
            ids = list(prefixes[int(rng.integers(0, len(prefixes)))]) + [99]
            pages, _tok, chain = c.match_hier(ids)
            got = list(pages)
            for node, n_tok in chain:
                need = n_tok // PS
                if a.free_count < need:
                    break
                dest = a.alloc(need)
                try:
                    c.prefetch_into(node, dest, kv)
                except RuntimeError:
                    a.free(dest)
                    break
                got += dest
            if got:
                live.append(got)
        elif op == 4:  # host squeeze: demotions cascade to disk
            c.pool.budget_bytes = int(rng.integers(0, 6)) * PAGE_BYTES
            c._enforce_host_budget()
            c.pool.budget_bytes = 6 * PAGE_BYTES
        else:  # disk squeeze: LRU disk subtrees drop for real
            c.disk.budget_bytes = int(rng.integers(0, 6)) * PAGE_BYTES
            c._enforce_host_budget()
            c.disk.budget_bytes = 6 * PAGE_BYTES
        _audit_clean(a, c, live)
    for pages in live:
        a.free(pages)
    c.clear()
    _audit_clean(a, c, [])
    assert a.free_count == a.num_pages - 1
    assert c.disk.used_bytes == 0


def test_disk_tier_identity_and_scheduler_accounting(tmp_path):
    """Engine-level: a host budget too small for the workload, disk tier
    on vs off — greedy outputs token-identical, the armed arm lands
    entries on disk and reports them, auditors clean."""
    budget_pages = 2
    probe = _engine()
    page_b = probe._scheduler.cache.page_payload_bytes()
    probe.shutdown()
    kw = dict(host_kv_gb=budget_pages * page_b / 2**30)
    on = _engine(kv_disk=True, kv_disk_dir=str(tmp_path), **kw)
    sched = on._scheduler
    assert sched._prefix_cache.disk is not None
    first_on, second_on, _pf1, _pf2 = _evict_rerun(on)
    rep = sched.metrics_report()
    assert rep["host_kv"]["disk_demoted_pages_total"] > 0
    assert sched.audit() == []
    on.shutdown()

    off = _engine(**kw)  # LMRS_KV_DISK default: OFF (opt-in tier)
    assert off._scheduler._prefix_cache.disk is None
    first_off, second_off, _p1, _p2 = _evict_rerun(off)
    assert "disk_demoted_pages_total" not in \
        off._scheduler.metrics_report()["host_kv"]
    off.shutdown()

    assert first_on == first_off, "disk tier changed greedy outputs"
    assert second_on == second_off, "disk promote diverged from re-prefill"


# ------------------------------------------------- scheduler integration


def tiny_model():
    return ModelConfig(vocab_size=512, dim=64, n_layers=2, n_heads=4,
                       n_kv_heads=2, hidden_dim=128, max_seq_len=256,
                       dtype="float32")


PREAMBLE = ("You are summarizing one section of a much longer transcript. "
            "Keep every fact, decision, name, and number. ")


def _map_requests(n: int, lo: int = 0) -> list[GenerationRequest]:
    return [GenerationRequest(
        prompt=PREAMBLE + f"Chunk {i}: the team discussed milestone {i}.",
        request_id=lo + i, temperature=0.0, max_new_tokens=8,
        system_prompt="Respond with the summary content only.",
        cache_prefix=len(PREAMBLE)) for i in range(n)]


def _engine(**kw):
    cfg = dict(backend="jax", scheduler="continuous", max_tokens=8,
               max_batch_slots=2, seed=0, page_size=16, decode_block=4)
    cfg.update(kw)
    return JaxEngine(EngineConfig(**cfg), tiny_model())


def _evict_rerun(eng):
    """Force a full HBM eviction, then re-run the shared-preamble batch —
    the spilled-hit path when the tier is armed, a plain re-prefill
    otherwise.  Returns both runs' texts and prefill-token costs."""
    reqs = _map_requests(4)
    sched = eng._scheduler
    first = [r.text for r in eng.generate_batch(reqs)]
    pf1 = sched.metrics["prefill_tokens"]
    sched._prefix_cache.evict(10_000)
    assert sched.audit() == []
    second = [r.text for r in eng.generate_batch(reqs)]
    pf2 = sched.metrics["prefill_tokens"] - pf1
    assert sched.audit() == []
    return first, second, pf1, pf2


def test_spill_tier_identity_and_accounting():
    """Greedy outputs token-identical with the spill tier on vs off, the
    armed arm actually prefetches (re-prefills only the tail), and the
    kill switch restores evict-means-gone exactly."""
    on = _engine()
    sched = on._scheduler
    assert sched._prefix_cache.pool is not None
    first_on, second_on, pf1, pf2 = _evict_rerun(on)
    m = sched.metrics
    assert m["prefix_spilled_hits"] == 4
    assert m["prefix_tokens_prefetched"] > 0
    assert m["prefix_spill_pages"] == m["prefix_prefetch_pages"] > 0
    # the re-run after eviction prefilled only the per-chunk tails: the
    # prefetched preamble made it cheaper than the warm first run
    assert pf2 < pf1
    rep = sched.metrics_report()
    assert rep["host_kv"]["enabled"]
    assert rep["prefix_cache"]["tokens_prefetched"] > 0
    on.shutdown()

    off = _engine(host_kv=False)
    assert off._scheduler._prefix_cache.pool is None
    first_off, second_off, _pf1, pf2_off = _evict_rerun(off)
    assert off._scheduler.metrics["prefix_spill_pages"] == 0
    assert not off._scheduler.metrics_report()["host_kv"]["enabled"]
    # the tier's whole point: the armed arm re-prefilled less
    assert pf2 < pf2_off
    off.shutdown()

    assert first_on == first_off, "spill tier changed greedy outputs"
    assert second_on == second_off, "prefetched KV diverged from re-prefill"
    assert first_on == second_on


def test_env_kill_switch(monkeypatch):
    monkeypatch.setenv("LMRS_HOST_KV", "0")
    eng = _engine()
    assert eng._scheduler._prefix_cache.pool is None
    eng.shutdown()


def test_int8_pool_arm_disarms_with_prefix_cache():
    """int8 KV disables the prefix cache (per-slot scales), so the spill
    tier is vacuously off — outputs stay identical on/off and nothing
    spills (the documented composition, docs/SERVING.md)."""
    reqs = _map_requests(3)
    on = _engine(kv_quantize="int8", page_size=32)
    assert on._scheduler._prefix_cache is None
    got = [r.text for r in on.generate_batch(reqs)]
    assert on._scheduler.metrics["prefix_spill_pages"] == 0
    assert not on._scheduler.metrics_report()["host_kv"]["enabled"]
    on.shutdown()
    off = _engine(kv_quantize="int8", page_size=32, host_kv=False)
    want = [r.text for r in off.generate_batch(reqs)]
    off.shutdown()
    assert got == want


def test_budget_pressure_keeps_pool_bounded():
    """A host budget sized for ~5 pages: spills stay within it, overflow
    drops for real, auditors clean, outputs unchanged."""
    budget_pages = 5
    eng = _engine()
    page_b = eng._scheduler.cache.page_payload_bytes()
    eng.shutdown()
    eng = _engine(host_kv_gb=budget_pages * page_b / 2**30)
    sched = eng._scheduler
    first, second, _pf1, _pf2 = _evict_rerun(eng)
    assert first == second
    pool = sched._prefix_cache.pool
    assert pool.used_bytes <= pool.budget_bytes
    # a second eviction wave spills again within the budget
    sched._prefix_cache.evict(10_000)
    assert pool.used_bytes <= pool.budget_bytes
    assert sched.audit() == []
    assert (pool.dropped_pages_total > 0
            or sched._prefix_cache.spilled_pages() <= budget_pages)
    eng.shutdown()


def test_prefetch_fault_reprefills_and_stays_clean():
    """prefix.prefetch firing on every spilled hit: the match truncates,
    segments re-prefill, outputs stay identical, auditors clean."""
    eng = _engine()
    sched = eng._scheduler
    reqs = _map_requests(4)
    first = [r.text for r in eng.generate_batch(reqs)]
    sched._prefix_cache.evict(10_000)
    with faults.injected(FaultPlan(faults=[
            {"site": "prefix.prefetch", "p": 1.0}])):
        second = [r.text for r in eng.generate_batch(reqs)]
    assert sched.audit() == []
    assert first == second
    assert sched.metrics["prefix_spilled_hits"] == 0  # nothing restored
    eng.shutdown()


def test_prefix_summary_published():
    eng = _engine()
    eng.generate_batch(_map_requests(3))
    rows = eng.prefix_summary()
    assert rows and rows[0]["resident_tokens"] > 0
    assert rows[0]["depth_tokens"] >= rows[0]["resident_tokens"]
    sched = eng._scheduler
    sched._prefix_cache.evict(10_000)
    sched._summary_memo = None  # drop the 1 s memo for the re-probe
    rows = eng.prefix_summary()
    assert rows[0]["resident_tokens"] == 0
    assert rows[0]["spilled_tokens"] > 0
    eng.shutdown()


def test_preamble_lru_learns_past_capacity():
    """The published-summary preamble table must keep learning past its
    32-entry cap: the NEWEST preamble survives the LRU trim (regression:
    a zero-tick insert made the new entry its own victim)."""
    from lmrs_tpu.engine.api import preamble_key

    eng = _engine()
    sched = eng._scheduler
    for i in range(40):
        sched._note_preamble(GenerationRequest(
            prompt=f"preamble {i} body " * 4, request_id=i,
            system_prompt=f"sys {i}", cache_prefix=24))
    assert len(sched._preambles) == 32
    newest = preamble_key("sys 39", "preamble 39 body " * 4, 24)
    oldest = preamble_key("sys 0", "preamble 0 body " * 4, 24)
    assert newest in sched._preambles
    assert oldest not in sched._preambles
    eng.shutdown()
