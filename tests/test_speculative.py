"""Speculative decoding ops (ops/speculative.py) + engine integration."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from lmrs_tpu.ops.speculative import draft_lookup, verify_tokens


def test_draft_lookup_finds_latest_bigram():
    # history: 5 6 7 8 5 6 9 9 [5 6] -> latest earlier (5,6) at pos 4,
    # drafted continuation = 9 9
    buf = np.zeros((1, 16), np.int32)
    hist = [5, 6, 7, 8, 5, 6, 9, 9, 5, 6]
    buf[0, : len(hist)] = hist
    draft, n = draft_lookup(jnp.asarray(buf), jnp.asarray([len(hist)]), k=3)
    assert int(n[0]) == 3
    assert draft[0, :3].tolist() == [9, 9, 5]


def test_draft_lookup_no_match():
    buf = np.zeros((1, 8), np.int32)
    buf[0, :4] = [1, 2, 3, 4]
    draft, n = draft_lookup(jnp.asarray(buf), jnp.asarray([4]), k=2)
    assert int(n[0]) == 0


def test_draft_lookup_short_history():
    buf = np.zeros((1, 8), np.int32)
    buf[0, 0] = 3
    _, n = draft_lookup(jnp.asarray(buf), jnp.asarray([1]), k=2)
    assert int(n[0]) == 0


def test_verify_tokens_greedy_acceptance():
    """Greedy rows (one-hot probs): accept exactly the matching prefix and
    emit the argmax at the first mismatch."""
    v = 8
    # model "wants" tokens 3, 5, 2 at the three slots
    probs = np.zeros((1, 3, v), np.float32)
    for slot, tok in enumerate((3, 5, 2)):
        probs[0, slot, tok] = 1.0
    # draft matches slot 0, diverges at slot 1
    draft = jnp.asarray([[3, 7]], jnp.int32)
    emit, count = verify_tokens(jnp.asarray(probs), draft,
                                jnp.asarray([2], jnp.int32),
                                jax.random.PRNGKey(0))
    assert int(count[0]) == 2          # accepted [3], emitted argmax 5
    assert emit[0, :2].tolist() == [3, 5]

    # fully-accepted draft earns the bonus token
    draft = jnp.asarray([[3, 5]], jnp.int32)
    emit, count = verify_tokens(jnp.asarray(probs), draft,
                                jnp.asarray([2], jnp.int32),
                                jax.random.PRNGKey(1))
    assert int(count[0]) == 3
    assert emit[0, :3].tolist() == [3, 5, 2]


def test_verify_tokens_preserves_marginal_distribution():
    """The first emitted token's marginal must equal the model's p0 exactly
    (the speculative-sampling guarantee), draft-independent."""
    v = 4
    rng = np.random.default_rng(0)
    p0 = rng.dirichlet(np.ones(v)).astype(np.float32)
    p1 = rng.dirichlet(np.ones(v)).astype(np.float32)
    probs = jnp.asarray(np.stack([p0, p1])[None])  # [1, 2, V]
    draft = jnp.asarray([[2]], jnp.int32)  # always draft token 2
    n_valid = jnp.asarray([1], jnp.int32)

    n = 4000
    emit, _ = jax.vmap(
        lambda key: verify_tokens(probs, draft, n_valid, key)
    )(jax.random.split(jax.random.PRNGKey(42), n))
    first = np.asarray(emit[:, 0, 0])
    freq = np.bincount(first, minlength=v) / n
    np.testing.assert_allclose(freq, p0, atol=0.03)


def test_verify_tokens_count_bounds():
    v, k = 8, 4
    rng = np.random.default_rng(1)
    probs = jnp.asarray(rng.dirichlet(np.ones(v), size=(2, k + 1)).astype(np.float32))
    draft = jnp.asarray(rng.integers(0, v, (2, k)), jnp.int32)
    for nv in ([0, 0], [k, 2]):
        emit, count = verify_tokens(probs, draft, jnp.asarray(nv, jnp.int32),
                                    jax.random.PRNGKey(3))
        assert ((1 <= np.asarray(count)) & (np.asarray(count) <= np.asarray(nv) + 1)).all()


def _tiny_engine(**ekw):
    from lmrs_tpu.config import EngineConfig, ModelConfig
    from lmrs_tpu.engine.jax_engine import JaxEngine

    model = ModelConfig(vocab_size=512, dim=64, n_layers=2, n_heads=4,
                        n_kv_heads=2, hidden_dim=128, max_seq_len=256,
                        dtype="float32")
    return JaxEngine(EngineConfig(backend="jax", scheduler="continuous",
                                  max_tokens=24, max_batch_slots=2, seed=0,
                                  **ekw), model)


def test_spec_greedy_matches_plain_decode():
    """Greedy speculative decode must emit token-for-token what plain decode
    emits (speculation is a pure scheduling optimization)."""
    from lmrs_tpu.engine.api import GenerationRequest

    # repetitive prompts make the bigram lookup actually fire
    reqs = [GenerationRequest(prompt="the cat sat on the mat the cat sat " * 3,
                              request_id=i, max_new_tokens=16, temperature=0.0)
            for i in range(3)]
    plain = _tiny_engine(speculate_k=0)
    want = [r.text for r in plain.generate_batch(reqs)]
    plain.shutdown()

    spec = _tiny_engine(speculate_k=4)
    got_res = spec.generate_batch(reqs)
    got = [r.text for r in got_res]
    m = spec.engine_metrics()
    spec.shutdown()
    assert got == want
    assert all(r.error is None for r in got_res)
    assert "spec_accepted_tokens" in m


def test_spec_sampling_runs_and_respects_budget():
    from lmrs_tpu.engine.api import GenerationRequest

    reqs = [GenerationRequest(prompt="alpha beta gamma alpha beta " * 4,
                              request_id=i, max_new_tokens=10 + i,
                              temperature=0.8, top_k=50)
            for i in range(3)]
    eng = _tiny_engine(speculate_k=3)
    out = eng.generate_batch(reqs)
    eng.shutdown()
    for i, r in enumerate(out):
        assert r.error is None
        assert 0 < r.completion_tokens <= 10 + i


def test_spec_greedy_through_multi_kernel_matches_plain(monkeypatch):
    """The RAGGED multi-token verify KERNEL path (interpret mode; the gate
    needs hd%128==0) must also emit token-for-token what plain decode
    emits — the kernel replaces the window gather, never the math."""
    import jax

    from lmrs_tpu.config import EngineConfig, ModelConfig
    from lmrs_tpu.engine.api import GenerationRequest
    from lmrs_tpu.engine.jax_engine import JaxEngine

    monkeypatch.setenv("LMRS_FORCE_KERNELS", "interpret")
    mc = ModelConfig(vocab_size=512, dim=512, n_layers=2, n_heads=4,
                     n_kv_heads=2, hidden_dim=256, max_seq_len=256,
                     dtype="float32")
    reqs = [GenerationRequest(prompt="the cat sat on the mat the cat sat " * 2,
                              request_id=i, max_new_tokens=12, temperature=0.0)
            for i in range(2)]

    def make(k):
        return JaxEngine(EngineConfig(
            backend="jax", scheduler="continuous", max_tokens=12,
            max_batch_slots=2, seed=0, decode_block=6, page_size=16,
            speculate_k=k), mc)

    plain = make(0)
    assert plain._scheduler._use_ragged  # the kernel gate really is on
    want = [r.text for r in plain.generate_batch(reqs)]
    plain.shutdown()

    spec = make(4)
    got_res = spec.generate_batch(reqs)
    got = [r.text for r in got_res]
    spec.shutdown()
    assert all(r.error is None for r in got_res)
    assert got == want


def test_draft_lookup_match_near_buffer_end_regression():
    """A match whose k-token source window runs past the unpadded buffer
    end — the LIVE context, exactly the occurrence worth drafting from —
    used to be dropped (or slid onto unrelated tokens by the dynamic-
    slice clip).  The padded buffer keeps it, clipped to real history."""
    hist = [7, 7, 5, 6, 9, 5, 6]
    buf = jnp.asarray([hist])  # NO slack: L == hist_len
    draft, n = draft_lookup(buf, jnp.asarray([len(hist)]), k=3)
    assert int(n[0]) == 3
    assert draft[0].tolist() == [9, 5, 6]


def test_draft_lookup_never_matches_query_itself():
    """The query n-gram's own occurrence (idx + n == hist_len) must not
    count as a match — a self-match would draft the padding after the
    history end."""
    hist = [1, 2, 3, 4, 1, 2]
    buf = jnp.asarray([hist + [0] * 4])
    draft, n = draft_lookup(buf, jnp.asarray([len(hist)]), k=2)
    assert int(n[0]) == 2
    assert draft[0].tolist() == [3, 4]  # from pos 0, not the query at 4


def test_draft_lookup_ngram3_rejects_bigram_collision():
    """n=3 must skip a position where only the last TWO tokens match — the
    byte-vocab collision class that capped trained-model acceptance at ~1
    token/step (docs/PERF.md round 4)."""
    import jax.numpy as jnp

    # history: 7 8 9 1 2 5 5 8 9 1 -> query 3-gram (8, 9, 1); the early
    # "8 9 1" at positions 1..3 is the ONLY 3-gram match (continuation 2 5);
    # a bigram matcher would also accept nothing else here, so add a decoy
    # "9 1" with a different predecessor: ... 4 9 1 ...
    hist = [7, 8, 9, 1, 2, 5, 4, 9, 1, 6, 8, 9, 1]
    buf = [hist + [0] * 7]
    draft, n = draft_lookup(jnp.asarray(buf), jnp.asarray([len(hist)]), k=2,
                            n=3)
    assert int(n[0]) == 2
    assert draft[0].tolist() == [2, 5]  # from the true 3-gram match

    # bigram matching at the same history picks the MOST RECENT "9 1"
    # (position 7), drafting its continuation (6, 8) — the collision
    draft2, n2 = draft_lookup(jnp.asarray(buf), jnp.asarray([len(hist)]),
                              k=2, n=2)
    assert int(n2[0]) == 2
    assert draft2[0].tolist() == [6, 8]
