"""Unit tests for the L1 preprocessor (deterministic pure functions)."""

from lmrs_tpu.data.preprocessor import (
    aggregate_by_time_interval,
    clean_text,
    combine_same_speaker_segments,
    extract_speakers,
    format_timestamp,
    get_transcript_duration,
    preprocess_transcript,
)


def test_clean_text_collapses_whitespace():
    assert clean_text("hello   world\n\tfoo") == "hello world foo"


def test_clean_text_dedups_repeated_words():
    assert clean_text("the the the cat sat sat down") == "the cat sat down"


def test_clean_text_fixes_missing_space_after_punctuation():
    assert clean_text("It ended.Next began") == "It ended. Next began"


def test_clean_text_empty():
    assert clean_text("") == ""
    assert clean_text("   ") == ""


def test_format_timestamp():
    assert format_timestamp(0) == "00:00"
    assert format_timestamp(65) == "01:05"
    assert format_timestamp(3599) == "59:59"
    assert format_timestamp(3661) == "1:01:01"


def test_drop_empty_segments():
    segs = [
        {"start": 0, "end": 1, "text": "  ", "speaker": "A"},
        {"start": 1, "end": 2, "text": "hi there", "speaker": "A"},
    ]
    out = preprocess_transcript(segs, merge_same_speaker=False)
    assert len(out) == 1
    assert out[0]["text"] == "hi there"


def test_same_speaker_merge_respects_duration_cap():
    segs = [
        {"start": 0.0, "end": 50.0, "text": "part one.", "speaker": "A"},
        {"start": 50.0, "end": 100.0, "text": "part two.", "speaker": "A"},
        {"start": 100.0, "end": 150.0, "text": "part three.", "speaker": "A"},
    ]
    merged = combine_same_speaker_segments(segs, max_segment_duration=120.0)
    # first two merge (span 100s); third would span 150s > cap
    assert len(merged) == 2
    assert merged[0]["start"] == 0.0 and merged[0]["end"] == 100.0


def test_merge_embeds_timestamp_markers():
    segs = [
        {"start": 0.0, "end": 5.0, "text": "first.", "speaker": "A"},
        {"start": 65.0, "end": 70.0, "text": "second.", "speaker": "A"},
    ]
    merged = combine_same_speaker_segments(segs)
    assert len(merged) == 1
    assert "[00:00]" in merged[0]["text"]
    assert "[01:05]" in merged[0]["text"]
    assert merged[0]["segment_timestamps"] == [(0.0, 5.0), (65.0, 70.0)]


def test_speaker_change_breaks_merge():
    segs = [
        {"start": 0, "end": 5, "text": "a.", "speaker": "A"},
        {"start": 5, "end": 10, "text": "b.", "speaker": "B"},
        {"start": 10, "end": 15, "text": "c.", "speaker": "A"},
    ]
    merged = combine_same_speaker_segments(segs)
    assert [m["speaker"] for m in merged] == ["A", "B", "A"]


def test_time_interval_aggregation():
    segs = [
        {"start": 0, "end": 10, "text": "a.", "speaker": "A"},
        {"start": 70, "end": 80, "text": "b.", "speaker": "B"},
        {"start": 75, "end": 85, "text": "c.", "speaker": "A"},
    ]
    out = aggregate_by_time_interval(segs, 60.0)
    assert len(out) == 2
    assert out[1]["speaker"] == "MULTIPLE"
    assert "SPEAKER" not in out[0]["text"]  # single-speaker bucket: no prefix
    assert "B:" in out[1]["text"] or "B: " in out[1]["text"]


def test_extract_speakers_order_and_uniqueness(segments):
    sp = extract_speakers(segments)
    assert sp == ["SPEAKER_00", "SPEAKER_01"]


def test_transcript_duration(segments):
    d = get_transcript_duration(segments)
    assert d > 0
    assert d == max(s["end"] for s in segments) - min(s["start"] for s in segments)


def test_preprocess_merge_reduces_segment_count(segments):
    out = preprocess_transcript(segments)
    assert 0 < len(out) < len(segments)
