"""DP serving replicas (engine/replicated.py) on the virtual 8-device mesh."""

from __future__ import annotations

import jax
import pytest

from lmrs_tpu.config import EngineConfig, MeshConfig, ModelConfig
from lmrs_tpu.engine.api import GenerationRequest, make_engine
from lmrs_tpu.engine.replicated import ReplicatedEngine

TINY = ModelConfig(name="tiny-test", vocab_size=512, dim=64, n_layers=2,
                   n_heads=4, n_kv_heads=2, hidden_dim=128, max_seq_len=512)

ECFG = EngineConfig(backend="jax", max_tokens=16, max_batch_slots=4,
                    retry_delay=0.0, seed=0, decode_block=4, prefill_chunk=128,
                    num_pages=64, page_size=16)


def _reqs(n: int) -> list[GenerationRequest]:
    return [
        GenerationRequest(prompt=f"summarize item {i}: the plan shipped.",
                          request_id=i, max_new_tokens=8)
        for i in range(n)
    ]


@pytest.fixture(scope="module")
def dp2tp2():
    eng = ReplicatedEngine(ECFG, TINY, MeshConfig(dp=2, tp=2))
    yield eng
    eng.shutdown()


def test_replicas_use_disjoint_devices(dp2tp2):
    sets = [frozenset(r._mesh.devices.flat) for r in dp2tp2.replicas]
    assert len(sets) == 2
    assert not (sets[0] & sets[1])


def test_results_align_with_request_order(dp2tp2):
    reqs = _reqs(7)  # odd count: shards of 4 and 3
    results = dp2tp2.generate_batch(reqs)
    assert len(results) == 7
    for req, res in zip(reqs, results):
        assert res.request_id == req.request_id
        assert res.error is None
        assert res.completion_tokens > 0


def test_single_device_replicas_pin_to_distinct_devices():
    eng = ReplicatedEngine(ECFG, TINY, MeshConfig(dp=2, tp=1))
    try:
        devs = [set(r._mesh.devices.flat) for r in eng.replicas]
        assert devs[0] != devs[1]
        # cache pinned to the replica's device, not the default device
        for r, dset in zip(eng.replicas, devs):
            cache_devs = set(r._scheduler.cache.k.devices())
            assert cache_devs == dset
        results = eng.generate_batch(_reqs(4))
        assert all(r.error is None for r in results)
    finally:
        eng.shutdown()


def test_metrics_merge(dp2tp2):
    m = dp2tp2.engine_metrics()
    assert m["replicas"] == 2
    assert m["decode_tokens"] > 0
    assert len(m["per_replica"]) == 2


def test_make_engine_routes_dp_to_replicated():
    eng = make_engine(
        EngineConfig(backend="jax", model="tiny", max_batch_slots=2,
                     retry_delay=0.0, num_pages=64, page_size=16,
                     decode_block=4),
        TINY,
        MeshConfig(dp=2, tp=1),
    )
    try:
        assert isinstance(eng, ReplicatedEngine)
    finally:
        eng.shutdown()


def test_dp1_rejected():
    with pytest.raises(ValueError):
        ReplicatedEngine(ECFG, TINY, MeshConfig(dp=1, tp=2))


def test_failed_replica_is_routed_around_then_probed_back(dp2tp2):
    """SURVEY §5.3 elastic recovery: after a replica-level fault, user
    retries land on healthy replicas only (the dead one sees nothing but
    synthetic health probes), and a successful probe re-admits it."""
    import time

    victim = dp2tp2.replicas[0]
    orig = victim.generate_batch
    seen_prompts: list[str] = []

    def dying(requests):
        seen_prompts.extend(r.prompt for r in requests)
        raise RuntimeError("injected device failure")

    victim.generate_batch = dying
    try:
        first = dp2tp2.generate_batch(_reqs(4))
        errs = [r for r in first if r.error is not None]
        assert errs, "victim replica's shard should have failed"
        assert dp2tp2._healthy == [False, True]
        user_calls = len(seen_prompts)
        # retry wave: user requests route to the surviving replica only
        second = dp2tp2.generate_batch(_reqs(4))
        assert all(r.error is None for r in second)
        new = seen_prompts[user_calls:]
        assert all(p == "health probe" for p in new), \
            f"dead replica received user traffic: {new}"
    finally:
        victim.generate_batch = orig
    # recovery: keep driving waves until a probe re-admits the replica
    deadline = time.time() + 60
    while not all(dp2tp2._healthy) and time.time() < deadline:
        assert all(r.error is None
                   for r in dp2tp2.generate_batch(_reqs(2)))
        time.sleep(0.2)
    assert all(dp2tp2._healthy), "probe never re-admitted the replica"
    assert dp2tp2.engine_metrics()["healthy_replicas"] == 2


def test_executor_retry_completes_over_surviving_replica(dp2tp2):
    """End-to-end degrade-and-continue: MapExecutor retry + unhealthy
    routing yields zero failed requests despite a dead replica."""
    from lmrs_tpu.engine.executor import MapExecutor

    victim = dp2tp2.replicas[1]
    orig = victim.generate_batch

    def dying(requests):
        raise RuntimeError("injected device failure")

    victim.generate_batch = dying
    try:
        ex = MapExecutor(dp2tp2, EngineConfig(retry_attempts=2, retry_delay=0.0,
                                              max_tokens=8))
        results = ex.run_requests(_reqs(6))
        assert all(r.error is None for r in results)
        assert ex.failed_requests == 0
    finally:
        victim.generate_batch = orig
        # drain any in-flight probe against the restored replica, then reset
        for fut in dp2tp2._probes.values():
            try:
                fut.result(timeout=30)
            except Exception:
                pass
        dp2tp2._probes.clear()
        dp2tp2._healthy = [True, True]
