"""Multi-PROCESS distributed comm backend (SURVEY §2.2 / §5.8).

Round-2 VERDICT scored the comm backend "partial": `jax.distributed`
bring-up existed but had never executed across >1 process.  These tests
run it for real: two OS processes (2 local CPU devices each) form one
4-device global mesh through ``parallel.mesh.initialize_distributed``,
and a data-parallel train step's gradient psum crosses the process
boundary over the gloo backend — topologically exactly where a TPU pod
crosses DCN (each process ≙ one host; its local devices ≙ one slice's
chips).

The cross-process loss must equal a single-process dp=4 run of the same
step: the collective's VALUE is checked, not just liveness.
"""

from __future__ import annotations

import subprocess
import sys
from pathlib import Path

import numpy as np
import pytest

from tests._dist_worker import make_cfg, make_global_tokens

WORKER = Path(__file__).parent / "_dist_worker.py"


from tests.conftest import free_port as _free_port


def _run_pair(d: Path) -> tuple[bool, list[str], list]:
    coordinator = f"127.0.0.1:{_free_port()}"
    outs = [d / "p0.txt", d / "p1.txt"]
    procs = [
        subprocess.Popen(
            [sys.executable, str(WORKER), str(i), coordinator, str(outs[i])],
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True)
        for i in range(2)
    ]
    logs = []
    for p in procs:
        try:
            out, _ = p.communicate(timeout=300)
        except subprocess.TimeoutExpired:
            for q in procs:
                q.kill()
            pytest.fail("distributed worker pair timed out")
        logs.append(out)
    ok = all(p.returncode == 0 for p in procs)
    return ok, logs, outs


@pytest.fixture(scope="module")
def dist_losses(tmp_path_factory):
    """Run the 2-process worker pair once; yield each process's losses.
    One retry with a fresh port: _free_port's probe socket closes before
    the coordinator binds, so a colliding bind is possible (rare TOCTOU)."""
    for attempt in range(2):
        ok, logs, outs = _run_pair(tmp_path_factory.mktemp(f"dist{attempt}"))
        if ok:
            return [outs[i].read_text().split() for i in range(2)]
    pytest.fail("worker pair failed twice:\n"
                + "\n".join(log[-3000:] for log in logs))


def test_two_process_global_mesh_forms(dist_losses):
    for i, row in enumerate(dist_losses):
        assert int(row[2]) == i  # process_index
        assert int(row[3]) == 2  # process_count


def test_cross_process_psum_is_consistent(dist_losses):
    """Both processes must observe the SAME replicated loss — the gradient
    and loss psums crossed the process boundary and agreed."""
    (l0a, l0b, *_), (l1a, l1b, *_) = dist_losses
    assert l0a == l1a and l0b == l1b
    assert float(l0b) < float(l0a)  # the psummed update actually trained


def test_single_and_multi_process_losses_agree(dist_losses):
    """The 2-process dp=4 step computes the same math as a single-process
    dp=4 mesh on the same data (collective VALUE parity, not liveness).
    Workload comes from the SAME helpers the worker uses."""
    import jax
    import optax
    from jax.sharding import NamedSharding, PartitionSpec as P

    from lmrs_tpu.config import MeshConfig
    from lmrs_tpu.models.transformer import init_params
    from lmrs_tpu.parallel.mesh import build_mesh
    from lmrs_tpu.training.train import make_train_step

    cfg = make_cfg()
    mesh = build_mesh(MeshConfig(dp=4), devices=jax.devices()[:4])
    params = init_params(cfg, jax.random.PRNGKey(0))
    optimizer = optax.sgd(1e-2)
    step = make_train_step(cfg, optimizer, mesh)
    tokens = jax.device_put(make_global_tokens(),
                            NamedSharding(mesh, P("dp", None)))
    _, _, loss = step(params, optimizer.init(params), tokens)

    multi = float(dist_losses[0][0])
    assert abs(float(loss) - multi) < 1e-4, (float(loss), multi)
    assert np.isfinite(multi)
