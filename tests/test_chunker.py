"""Unit tests for the token-budget chunker."""

import pytest

from lmrs_tpu.data.chunker import Chunk, TranscriptChunker, split_sentences
from lmrs_tpu.data.preprocessor import preprocess_transcript
from lmrs_tpu.data.tokenizer import ApproxTokenizer, ByteTokenizer


def test_split_sentences_basic():
    out = split_sentences("First point. Second point! Third? Done.")
    assert out == ["First point.", "Second point!", "Third?", "Done."]


def test_split_sentences_protects_abbreviations():
    out = split_sentences("Dr. Smith arrived. He spoke.")
    assert out == ["Dr. Smith arrived.", "He spoke."]


def test_split_sentences_empty():
    assert split_sentences("") == []


def _chunker(**kw):
    defaults = dict(max_tokens_per_chunk=120, overlap_tokens=0, context_tokens=20,
                    tokenizer="approx")
    defaults.update(kw)
    return TranscriptChunker(**defaults)


def test_budget_respected(segments):
    processed = preprocess_transcript(segments)
    ck = _chunker()
    chunks = ck.chunk_transcript(processed)
    assert len(chunks) > 1
    for c in chunks:
        # packed token total must respect the effective budget (oversized
        # single segments are split, so no chunk's packed content exceeds it)
        packed = sum(ck.tokenizer.count(s["text"]) for s in c.segments)
        assert packed <= ck.effective_max_tokens


def test_chunk_metadata(segments):
    processed = preprocess_transcript(segments)
    chunks = _chunker().chunk_transcript(processed)
    total = len(chunks)
    for i, c in enumerate(chunks):
        assert c.chunk_index == i
        assert c.total_chunks == total
        assert c.start_time <= c.end_time
        assert c.speakers
        assert 0.0 <= c.position_percentage <= 100.0
    # position percentage measured on the WHOLE transcript: monotone increasing
    pos = [c.position_percentage for c in chunks]
    assert pos == sorted(pos)
    assert pos[0] == pytest.approx(0.0)
    assert pos[-1] > 50.0


def test_context_header_contents(segments):
    processed = preprocess_transcript(segments)
    chunks = _chunker().chunk_transcript(processed)
    c = chunks[1]
    head = c.text_with_context
    assert f"[TRANSCRIPT SECTION {c.chunk_index + 1} of {c.total_chunks}]" in head
    assert "[TIME RANGE:" in head
    assert "[SPEAKERS:" in head
    assert "% through the transcript]" in head
    assert head.endswith(c.text)


def test_oversized_segment_is_sentence_split():
    long_text = " ".join(f"Sentence number {i} has several words in it." for i in range(200))
    seg = {"start": 0.0, "end": 400.0, "text": long_text, "speaker": "A"}
    ck = _chunker(max_tokens_per_chunk=150, context_tokens=30)
    chunks = ck.chunk_transcript([seg])
    assert len(chunks) > 1
    # interpolated timestamps: monotone, within the segment span
    starts = [c.start_time for c in chunks]
    assert starts == sorted(starts)
    assert all(0.0 <= c.start_time <= 400.0 for c in chunks)
    assert chunks[-1].end_time == pytest.approx(400.0, abs=1.0)


def test_pathological_sentence_clause_split():
    mono = "word " * 800  # one 800-word "sentence", no punctuation
    seg = {"start": 0.0, "end": 100.0, "text": mono.strip(), "speaker": "A"}
    ck = _chunker(max_tokens_per_chunk=120, context_tokens=20)
    chunks = ck.chunk_transcript([seg])
    assert len(chunks) >= 2
    assert all(c.token_count > 0 for c in chunks)


def test_overlap_is_real():
    segs = [
        {"start": float(i), "end": float(i + 1),
         "text": f"Unique sentence number {i} with recognizable content here.",
         "speaker": "A"}
        for i in range(40)
    ]
    no_overlap = _chunker(overlap_tokens=0).chunk_transcript([dict(s) for s in segs])
    with_overlap = _chunker(overlap_tokens=30).chunk_transcript([dict(s) for s in segs])
    assert len(no_overlap) > 1
    # overlapped chunks must carry context from the previous chunk
    assert any("context from previous chunk" in c.text for c in with_overlap[1:])


def test_empty_input():
    assert _chunker().chunk_transcript([]) == []


def test_byte_tokenizer_roundtrip():
    tok = ByteTokenizer()
    s = "Hello, TPU world! é世界"
    assert tok.decode(tok.encode(s)) == s
    assert tok.count(s) == len(s.encode("utf-8"))


def test_approx_tokenizer_count_scales():
    tok = ApproxTokenizer()
    assert tok.count("") == 0
    assert tok.count("word " * 100) > tok.count("word " * 10)


def test_chunker_rejects_bad_budget():
    with pytest.raises(ValueError):
        TranscriptChunker(max_tokens_per_chunk=100, context_tokens=150)


def test_overlap_never_exceeds_budget():
    """Budget invariant with overlap enabled (review finding)."""
    segs = [{"start": float(i), "end": float(i + 1),
             "text": ("Sentence %d has words. " % i) * 6, "speaker": "A"}
            for i in range(60)]
    ck = TranscriptChunker(max_tokens_per_chunk=400, overlap_tokens=100,
                           context_tokens=150)
    chunks = ck.chunk_transcript(segs)
    assert len(chunks) > 2
    for c in chunks:
        packed = sum(ck.tokenizer.count(s["text"]) for s in c.segments)
        assert packed <= ck.effective_max_tokens


def test_long_sentence_pieces_get_distinct_timestamps():
    """Interior flushes of a mega-sentence must interpolate by char position
    (review finding: stale cursor gave every piece start=end=0)."""
    long_sentence = "word " * 2500  # no sentence boundaries
    seg = {"start": 0.0, "end": 100.0, "text": long_sentence.strip(), "speaker": "A"}
    ck = TranscriptChunker(max_tokens_per_chunk=150, overlap_tokens=0,
                           context_tokens=30)
    chunks = ck.chunk_transcript([seg])
    assert len(chunks) > 3
    starts = [c.start_time for c in chunks]
    assert starts == sorted(starts)
    assert len(set(starts)) == len(starts)  # all distinct
    assert all(c.end_time > c.start_time for c in chunks)


def test_safe_format_single_pass_no_injection():
    from lmrs_tpu.prompts import safe_format
    out = safe_format("A {transcript} B", transcript="evil {summary_type} text",
                      summary_type="SHOULD NOT APPEAR")
    assert out == "A evil {summary_type} text B"
