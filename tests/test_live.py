"""Live sessions (ISSUE 13): incremental chunking parity, the stable
rolling reduce tree, session lifecycle + journal resume, SIGKILL chaos,
append/refresh/cancel fuzz, and the /v1/sessions serving surface.

The tier-1 ``live-session`` gate (tier1.yml) runs this whole file.
"""

from __future__ import annotations

import json
import os
import random
import signal
import subprocess
import sys
import threading
import time
from pathlib import Path

import pytest

import _live_worker as lw
from conftest import free_port, make_segments
from lmrs_tpu.config import (ChunkConfig, EngineConfig, LiveConfig,
                             PipelineConfig, ReduceConfig)
from lmrs_tpu.data.chunker import Chunk, TranscriptChunker
from lmrs_tpu.data.preprocessor import preprocess_transcript
from lmrs_tpu.engine.executor import MapExecutor
from lmrs_tpu.engine.mock import MockEngine
from lmrs_tpu.jobs import journal as jl
from lmrs_tpu.live import SessionManager, rebuild_live_state
from lmrs_tpu.live.session import REC_SEGMENTS, REC_SUMMARY
from lmrs_tpu.reduce.aggregator import ResultAggregator, content_node_id


# --------------------------------------------------------------------------
# incremental chunker: parity + boundary stability
# --------------------------------------------------------------------------


def _chunker(**kw) -> TranscriptChunker:
    defaults = dict(max_tokens_per_chunk=120, overlap_tokens=0,
                    context_tokens=20, tokenizer="approx")
    defaults.update(kw)
    return TranscriptChunker(**defaults)


@pytest.mark.parametrize("overlap", [0, 40])
@pytest.mark.parametrize("seed", [0, 1, 2])
def test_incremental_matches_oneshot_over_every_prefix(seed, overlap):
    """Property (ISSUE 13 satellite): for random segment streams chopped
    into random append batches, the incremental snapshot after each
    append is BYTE-IDENTICAL to a one-shot ``chunk_transcript`` over the
    same prefix — including overlap seeding and oversized-segment
    splits."""
    segs = preprocess_transcript(make_segments(140, seed=seed),
                                 merge_same_speaker=False)
    # plant an oversized segment so the sentence-split path is exercised
    segs[17] = dict(segs[17], text=" ".join(
        f"Fact {k} about the roadmap milestone." for k in range(120)))
    inc = _chunker(overlap_tokens=overlap).incremental()
    rng = random.Random(seed)
    i = 0
    while i < len(segs):
        k = rng.randrange(1, 13)
        inc.append(segs[i:i + k])
        i += k
        snap = [c.to_dict() for c in inc.chunks()]
        ref = [c.to_dict() for c in
               _chunker(overlap_tokens=overlap).chunk_transcript(segs[:i])]
        assert json.dumps(snap, sort_keys=True) == \
            json.dumps(ref, sort_keys=True)


def test_incremental_sealed_boundaries_never_move():
    """Previously sealed ``(index, start, end)`` identities and chunk
    text are frozen across appends; only the open tail extends."""
    segs = preprocess_transcript(make_segments(120, seed=4),
                                 merge_same_speaker=False)
    inc = _chunker().incremental()
    seen: dict[int, tuple] = {}
    tail_history: list[tuple] = []
    for i in range(0, len(segs), 7):
        inc.append(segs[i:i + 7])
        snap = inc.chunks()
        for c in snap[:inc.sealed_count]:
            ident = (c.start_time, c.end_time, c.text)
            if c.chunk_index in seen:
                assert seen[c.chunk_index] == ident, \
                    f"sealed chunk {c.chunk_index} moved"
            seen[c.chunk_index] = ident
        if snap:
            tail = snap[-1]
            tail_history.append((tail.chunk_index, tail.start_time,
                                 tail.end_time))
    # the tail only ever extends: same index keeps its start, end grows
    for (i1, s1, e1), (i2, s2, e2) in zip(tail_history, tail_history[1:]):
        assert i2 >= i1
        if i2 == i1:
            assert s2 == s1 and e2 >= e1


def test_incremental_empty_and_accessors():
    inc = _chunker().incremental()
    assert inc.chunks() == [] and inc.chunk_count == 0
    inc.append([])
    assert inc.chunks() == []
    segs = preprocess_transcript(make_segments(10, seed=0),
                                 merge_same_speaker=False)
    inc.append(segs)
    assert inc.chunk_count == len(inc.chunks()) > 0
    assert inc.n_segments == len(segs)


# --------------------------------------------------------------------------
# stable reduce tree + content-derived node identity
# --------------------------------------------------------------------------


class DictCache:
    """Minimal node cache recording what the aggregator asked of it."""

    def __init__(self):
        self.store: dict[str, str] = {}
        self.computed: list[str] = []
        self.hits: list[str] = []

    def lookup(self, node_id, summaries, template, metadata):
        text = self.store.get(jl.node_key(summaries, template, metadata))
        if text is not None:
            self.hits.append(node_id)
        return text

    def record(self, node_id, summaries, template, metadata, text):
        self.store[jl.node_key(summaries, template, metadata)] = text
        self.computed.append(node_id)


def _leaf_chunks(n: int) -> list[Chunk]:
    return [Chunk(chunk_index=i, start_time=i * 60.0,
                  end_time=(i + 1) * 60.0, speakers=["S"],
                  summary=f"Summary {i}: findings about item {i}.")
            for i in range(n)]


def _stable_agg(cache_cfg=None):
    cfg = cache_cfg or ReduceConfig(stable_tree=True,
                                    max_summaries_per_batch=3,
                                    max_tokens_per_batch=50,
                                    reserve_tokens=0)
    return ResultAggregator(
        MapExecutor(MockEngine(), EngineConfig(temperature=0.0)), cfg)


def test_stable_tree_append_invalidates_only_root_path():
    """ISSUE 13 satellite regression: with content-derived node identity
    and the stable tree, appending a leaf recomputes ONLY the batch it
    lands in plus the root path — every sibling subtree answers from the
    cache, and the result equals a cold run of the grown input."""
    agg = _stable_agg()
    cache = DictCache()
    agg.aggregate(_leaf_chunks(12), node_cache=cache)
    first_round = set(cache.computed)
    assert len(first_round) == 7  # L1 x4, L2 x2, final
    cache.computed, cache.hits = [], []

    grown = agg.aggregate(_leaf_chunks(13), node_cache=cache)
    # dirty: the new leaf's L1 batch, the L2 batch above it, the root
    assert len(cache.computed) == 3, cache.computed
    assert [n.split("@")[0] for n in cache.computed] == \
        ["L1.B4", "L2.B1", "L3.final"]
    # sibling subtrees reused — the poisoned-positional-key failure mode
    assert {n.split("@")[0] for n in cache.hits} == \
        {"L1.B0", "L1.B1", "L1.B2", "L1.B3", "L2.B0"}
    cold = _stable_agg().aggregate(_leaf_chunks(13))
    assert grown["final_summary"] == cold["final_summary"]


def test_node_identity_is_content_derived():
    a = content_node_id("L1.B0", ["x", "y"], "T")
    assert a.startswith("L1.B0@")
    assert a == content_node_id("L1.B0", ["x", "y"], "T")
    assert a != content_node_id("L1.B0", ["x", "z"], "T")
    # metadata is substituted into the prompt, so it is content too
    assert a != content_node_id("L1.B0", ["x", "y"], "T", {"batch": "1/2"})
    assert a.split("@")[1] == \
        content_node_id("L9.B9", ["x", "y"], "T").split("@")[1]


def test_stable_tree_single_pass_below_arity():
    agg = _stable_agg()
    out = agg.aggregate(_leaf_chunks(3))
    assert out["hierarchical"] is False and out["levels"] == 1


# --------------------------------------------------------------------------
# session manager: incremental == cold, resume, classes, lifecycle
# --------------------------------------------------------------------------


def _live_cfg(**live_kw) -> PipelineConfig:
    live = dict(class_default="bulk")
    live.update(live_kw)
    return PipelineConfig(
        chunk=ChunkConfig(max_tokens_per_chunk=150, overlap_tokens=0,
                          context_tokens=30, tokenizer="approx"),
        engine=EngineConfig(backend="mock", temperature=0.0, seed=0,
                            max_tokens=48, retry_delay=0.0),
        reduce=ReduceConfig(max_summaries_per_batch=3),
        live=LiveConfig(**live))


def test_session_incremental_refresh_equals_cold(tmp_path):
    """The acceptance identity: N appends + refreshes produce the same
    greedy summary as a cold session fed the grown transcript at once,
    while recomputing only the dirty tail chunks and root path."""
    segs = make_segments(120, seed=3)
    m1 = SessionManager(MockEngine(seed=0), tmp_path / "a",
                        config=_live_cfg())
    m1.create(session_id="inc")
    last = None
    for i in range(0, 120, 30):
        last = m1.append("inc", segs[i:i + 30], refresh=True)["refresh"]
    assert last["dirty_chunks"] < last["num_chunks"]
    assert last["reduce_nodes_reused"] > 0
    m2 = SessionManager(MockEngine(seed=0), tmp_path / "b",
                        config=_live_cfg())
    m2.create(session_id="cold")
    cold = m2.append("cold", segs, refresh=True)["refresh"]
    assert last["summary"] == cold["summary"]
    # dirty fraction: the 30-segment append touched the tail, not the body
    assert last["dirty_chunks"] <= cold["num_chunks"] // 2


def test_session_restart_resumes_without_recompute(tmp_path):
    """SIGKILL-shaped restart (graceful variant): a new manager over the
    same live dir rehydrates segments, summaries, nodes, and the current
    summary — and the next refresh recomputes NOTHING when nothing
    changed."""
    segs = make_segments(90, seed=7)
    d = tmp_path / "live"
    m1 = SessionManager(MockEngine(seed=0), d, config=_live_cfg())
    m1.create(session_id="s")
    ref = m1.append("s", segs, refresh=True)["refresh"]
    m1.shutdown()

    m2 = SessionManager(MockEngine(seed=0), d, config=_live_cfg())
    assert m2.recover() == 1
    doc = m2.summary_doc("s")
    assert doc["summary"] == ref["summary"]
    assert doc["staleness"]["stale"] is False
    r = m2.refresh("s")
    assert r["dirty_chunks"] == 0
    assert r["reduce_nodes_computed"] == 0
    assert r["summary"] == ref["summary"]
    # append after resume: clean subtrees stay cached
    r2 = m2.append("s", make_segments(20, seed=8), refresh=True)["refresh"]
    assert r2["reduce_nodes_reused"] > 0
    assert r2["dirty_chunks"] < r2["num_chunks"]


def test_session_journal_replay_idempotent(tmp_path):
    segs = make_segments(40, seed=9)
    m = SessionManager(MockEngine(seed=0), tmp_path, config=_live_cfg())
    m.create(session_id="s")
    m.append("s", segs[:20], refresh=True)
    m.append("s", segs[20:], refresh=True)
    session = m.get("s")
    records, meta = jl.replay(session.wal_path)
    assert not meta["torn"] and not meta["corrupt"]
    s1 = jl.canonical_json(
        {k: v for k, v in rebuild_live_state(records).items()})
    s2 = jl.canonical_json(
        {k: v for k, v in rebuild_live_state(records + records).items()})
    assert s1 == s2
    kinds = {r.get("type") for r in records}
    assert {REC_SEGMENTS, REC_SUMMARY, jl.REC_CHUNK, jl.REC_NODE} <= kinds


def test_session_fingerprint_gate_keeps_transcript(tmp_path):
    """A restart under a different prompt/chunking surface must NOT
    rehydrate stale summaries — but the transcript itself (the part only
    the WAL holds) always survives."""
    segs = make_segments(60, seed=5)
    d = tmp_path / "live"
    m1 = SessionManager(MockEngine(seed=0), d, config=_live_cfg())
    m1.create(session_id="s")
    m1.append("s", segs, refresh=True)
    m1.shutdown()

    changed = _live_cfg()
    changed = PipelineConfig(
        chunk=ChunkConfig(max_tokens_per_chunk=100, overlap_tokens=0,
                          context_tokens=30, tokenizer="approx"),
        engine=changed.engine, reduce=changed.reduce, live=changed.live)
    m2 = SessionManager(MockEngine(seed=0), d, config=changed)
    assert m2.recover() == 1
    s = m2.get("s")
    assert s.n_raw_segments == len(segs)          # transcript survived
    assert s.summary is None                       # stale summary dropped
    assert (d / "s.wal.stale").exists()
    r = m2.refresh("s")
    assert r["dirty_chunks"] == r["num_chunks"]    # full recompute
    # and the recompute matches a cold run under the NEW surface
    m3 = SessionManager(MockEngine(seed=0), tmp_path / "c", config=changed)
    m3.create(session_id="cold")
    assert r["summary"] == \
        m3.append("cold", segs, refresh=True)["refresh"]["summary"]


def test_cross_refresh_draft_hint_reaches_engine(tmp_path):
    """Tree-speculation cross-refresh drafting (ISSUE 19): the SECOND
    refresh's engine requests must carry the FIRST refresh's summary as
    their draft hint (the previous summary is a near-perfect n-gram
    draft source for a rolling summary restating itself).  The hint is
    advisory — summary equality with a hint-free cold session is already
    pinned by test_session_incremental_refresh_equals_cold."""
    segs = make_segments(60, seed=9)
    eng = MockEngine(seed=0)
    m = SessionManager(eng, tmp_path, config=_live_cfg())
    m.create(session_id="s")
    r1 = m.append("s", segs[:30], refresh=True)["refresh"]
    assert eng.draft_hints == []  # nothing to draft from on refresh 1
    m.append("s", segs[30:], refresh=True)
    assert eng.draft_hints, "second refresh carried no draft hint"
    assert set(eng.draft_hints) == {r1["summary"]}


def test_session_auto_refresh_threshold(tmp_path):
    """LMRS_LIVE_REFRESH_TOKENS semantics: appends auto-trigger a refresh
    once the appended-but-unsummarized token estimate crosses the
    threshold; below it they only mark the summary stale."""
    segs = make_segments(60, seed=6)
    m = SessionManager(MockEngine(seed=0), tmp_path,
                       config=_live_cfg(refresh_tokens=400))
    m.create(session_id="s")
    doc = m.append("s", segs[:2])   # tiny: under the threshold
    assert "refresh" not in doc
    assert doc["staleness"]["pending_tokens"] > 0
    doc = m.append("s", segs[2:40])  # crosses it
    assert doc["refresh"]["auto"] is True
    assert doc["staleness"]["pending_tokens"] == 0
    # explicit refresh=False suppresses the auto trigger
    doc = m.append("s", segs[40:], refresh=False)
    assert "refresh" not in doc


def test_session_deadline_classes(tmp_path):
    """``interactive`` refreshes carry a real deadline budget end to end
    (map + reduce requests shed/expire under PR 5's lifecycle);
    ``bulk`` runs unbounded.  Failed chunks are NOT cached — the next
    bulk refresh retries them and converges on the clean summary."""
    segs = make_segments(60, seed=2)
    m = SessionManager(MockEngine(seed=0, latency_s=0.03), tmp_path,
                       config=_live_cfg(interactive_deadline_s=0.02))
    m.create(session_id="s")
    m.append("s", segs)
    r_int = m.refresh("s", klass="interactive")
    assert r_int["class"] == "interactive"
    assert r_int["map_failed"] > 0 or r_int["reduce_errors"] > 0
    r_bulk = m.refresh("s", klass="bulk")
    assert r_bulk["map_failed"] == 0 and r_bulk["reduce_errors"] == 0
    cold = SessionManager(MockEngine(seed=0), tmp_path / "c",
                          config=_live_cfg())
    cold.create(session_id="c")
    assert r_bulk["summary"] == \
        cold.append("c", segs, refresh=True)["refresh"]["summary"]
    # a fully degraded refresh (final reduce = error marker) must never
    # overwrite the good summary or clear the staleness that keeps the
    # auto-refresh threshold armed
    good = m.summary_doc("s")["summary"]
    m.append("s", make_segments(10, seed=9))
    r_deg = m.refresh("s", klass="interactive")
    assert r_deg["final_error"] is True
    doc = m.summary_doc("s")
    assert doc["summary"] == good
    assert doc["staleness"]["stale"] is True
    with pytest.raises(ValueError):
        m.refresh("s", klass="warp")


def test_tail_chunk_grown_without_end_moving_recomputes(tmp_path):
    """Identity edge: a zero-duration append grows the open tail chunk's
    TEXT without moving its (index, start, end) key — the text-hash
    component of the cache check must mark it dirty, or the stale
    summary would rehydrate over the grown content."""
    m = SessionManager(MockEngine(seed=0), tmp_path / "a",
                       config=_live_cfg())
    m.create(session_id="s")
    base = [{"start": 0.0, "end": 10.0, "speaker": "A",
             "text": "The roadmap review covered kernels."}]
    grow = [{"start": 10.0, "end": 10.0, "speaker": "A",
             "text": "Budget moved to serving."}]
    m.append("s", base, refresh=True)
    r = m.append("s", grow, refresh=True)["refresh"]
    assert r["dirty_chunks"] >= 1  # the tail recomputed despite same key
    cold = SessionManager(MockEngine(seed=0), tmp_path / "b",
                          config=_live_cfg())
    cold.create(session_id="c")
    assert r["summary"] == \
        cold.append("c", base + grow, refresh=True)["refresh"]["summary"]


def test_append_validation_never_journals(tmp_path):
    """A malformed batch 400s BEFORE anything reaches the WAL: replay
    must never meet a record only a pre-validation build could write."""
    d = tmp_path / "live"
    m = SessionManager(MockEngine(seed=0), d, config=_live_cfg())
    m.create(session_id="s")
    ref = m.append("s", make_segments(20, seed=0),
                   refresh=True)["refresh"]
    for bad in ([{"start": "abc", "end": 5.0, "text": "hi"}],
                [{"start": 0.0, "end": float("nan"), "text": "hi"}],
                [{"start": 9.0, "end": 1.0, "text": "hi"}],
                [{"start": 0.0, "end": 1.0, "text": None}]):
        with pytest.raises(ValueError):
            m.append("s", bad)
    assert m.get("s").append_seq == 1  # nothing journaled, seq unmoved
    m.shutdown()
    m2 = SessionManager(MockEngine(seed=0), d, config=_live_cfg())
    assert m2.recover() == 1
    assert m2.summary_doc("s")["summary"] == ref["summary"]


def test_recovered_staleness_counts_uncovered_batches_only(tmp_path):
    """A restart between an append and its refresh must report the
    staleness of THAT batch, not of the whole transcript (a whole-
    transcript count would spuriously fire the auto-refresh threshold)."""
    d = tmp_path / "live"
    m1 = SessionManager(MockEngine(seed=0), d, config=_live_cfg())
    m1.create(session_id="s")
    m1.append("s", make_segments(60, seed=1), refresh=True)
    m1.append("s", make_segments(5, seed=2))  # appended, NOT summarized
    pending_before = m1.get("s").stale_tokens
    assert pending_before > 0
    m1.shutdown()
    m2 = SessionManager(MockEngine(seed=0), d, config=_live_cfg())
    assert m2.recover() == 1
    doc = m2.summary_doc("s")
    assert doc["staleness"]["stale"] is True
    assert doc["staleness"]["pending_tokens"] == pending_before


def test_session_close_deletes(tmp_path):
    m = SessionManager(MockEngine(seed=0), tmp_path, config=_live_cfg())
    m.create(session_id="s")
    m.append("s", make_segments(10, seed=0), refresh=True)
    wal = m.get("s").wal_path
    assert wal.exists()
    assert m.close("s") is not None
    assert not wal.exists()
    assert m.get("s") is None
    with pytest.raises(KeyError):
        m.refresh("s")
    assert m.close("nope") is None
    # a fresh manager over the dir finds nothing to recover
    m2 = SessionManager(MockEngine(seed=0), tmp_path, config=_live_cfg())
    assert m2.recover() == 0


def test_session_param_validation(tmp_path):
    m = SessionManager(MockEngine(seed=0), tmp_path, config=_live_cfg())
    with pytest.raises(ValueError):
        m.create({"bogus_knob": 1})
    with pytest.raises(ValueError):
        m.create({"class": "warp"})
    with pytest.raises(ValueError):
        m.create(session_id="bad/../id")
    m.create(session_id="ok")
    with pytest.raises(ValueError):
        m.append("ok", [{"start": 0}])  # malformed segment
    with pytest.raises(KeyError):
        m.append("missing", make_segments(2, seed=0))


# --------------------------------------------------------------------------
# SIGKILL chaos: resume with the rolling tree intact
# --------------------------------------------------------------------------


def _wait_for_wal(wal: Path, rec_type: str, n: int,
                  deadline_s: float = 120.0) -> int:
    t0 = time.time()
    while time.time() - t0 < deadline_s:
        if wal.exists():
            recs, _ = jl.replay(wal)
            have = sum(1 for r in recs if r.get("type") == rec_type)
            if have >= n:
                return have
        time.sleep(0.02)
    raise TimeoutError(f"never saw {n} {rec_type} records in {wal}")


def test_sigkill_mid_refresh_resumes_token_identical(tmp_path):
    """The ISSUE 13 chaos contract: SIGKILL a live-session process
    mid-refresh (journal paced by an append-stall plan), resume the
    journal in a new manager, and the next refresh is token-identical to
    an uninterrupted run — with the clean subtrees answered from the
    journal, not recomputed."""
    segs = lw.live_segments(60)
    batches = [segs[:40], segs[40:]]

    # uninterrupted reference in its own dir
    ref_mgr = lw.build_manager(str(tmp_path / "ref"))
    ref_mgr.create(session_id="live")
    ref = None
    for b in batches:
        ref = ref_mgr.append("live", b, refresh=True)["refresh"]

    live_dir = tmp_path / "live"
    live_dir.mkdir()
    spec = tmp_path / "spec.json"
    spec.write_text(json.dumps({"live_dir": str(live_dir),
                                "session_id": "live",
                                "batches": batches}))
    env = dict(os.environ, JAX_PLATFORMS="cpu",
               LMRS_FAULT_PLAN=json.dumps({"faults": [
                   {"site": "journal.append", "every": 1,
                    "action": "stall", "stall_s": 0.1}]}))
    proc = subprocess.Popen(
        [sys.executable,
         os.path.join(os.path.dirname(__file__), "_live_worker.py"),
         str(spec)],
        env=env, stdout=subprocess.DEVNULL, stderr=subprocess.PIPE)
    wal = live_dir / "live.wal"
    try:
        # phase 1 summary lands, then kill inside phase 2's map stream:
        # after the first summary_done, wait for fresh chunk records
        _wait_for_wal(wal, REC_SUMMARY, 1)
        recs, _ = jl.replay(wal)
        chunks_at_p1 = sum(1 for r in recs if r.get("type") == jl.REC_CHUNK)
        _wait_for_wal(wal, jl.REC_CHUNK, chunks_at_p1 + 1)
        os.kill(proc.pid, signal.SIGKILL)
        proc.wait(timeout=10)
    finally:
        if proc.poll() is None:
            proc.kill()
            proc.wait(timeout=10)

    state = rebuild_live_state(jl.replay(wal)[0])
    assert (state["summary"] or {}).get("seq") == 1, \
        "kill landed after the second refresh completed"

    mgr = lw.build_manager(str(live_dir))
    assert mgr.recover() == 1
    s = mgr.get("live")
    assert s.recovered and s.n_raw_segments == len(segs)
    r = mgr.refresh("live")
    assert r["summary"] == ref["summary"], "resume diverged from control"
    # the rolling tree survived: phase-1 subtrees answered from the
    # journal (strictly fewer recomputes than a cold run of everything)
    assert r["chunk_summaries_reused"] > 0
    assert r["reduce_nodes_reused"] > 0
    assert r["dirty_chunks"] < r["num_chunks"]


# --------------------------------------------------------------------------
# fuzz: interleaved append/refresh/close, auditor clean
# --------------------------------------------------------------------------


def test_fuzz_append_refresh_close_mock(tmp_path):
    """Seeded interleave over two sessions on one manager: appends of
    random size, refreshes under random classes, closes/recreates — the
    journal must replay idempotently after every wave and the surviving
    session's final summary must equal a cold rebuild."""
    rng = random.Random(0xC0FFEE)
    cfg = _live_cfg()
    m = SessionManager(MockEngine(seed=0), tmp_path / "live", config=cfg)
    stream: dict[str, list] = {"a": [], "b": []}
    m.create(session_id="a")
    m.create(session_id="b")
    pool = make_segments(400, seed=12)
    cursor = 0
    for _ in range(40):
        sid = rng.choice(("a", "b"))
        op = rng.random()
        if op < 0.55 and cursor < len(pool):
            k = rng.randrange(1, 9)
            batch = pool[cursor:cursor + k]
            cursor += k
            m.append(sid, batch)
            stream[sid].extend(batch)
        elif op < 0.85:
            if stream[sid]:
                m.refresh(sid, klass=rng.choice(("interactive", "bulk")))
        else:
            m.close(sid)
            stream[sid] = []
            m.create(session_id=sid)
        session = m.get(sid)
        records, meta = jl.replay(session.wal_path)
        assert not meta["corrupt"]
        s1 = jl.canonical_json(rebuild_live_state(records))
        s2 = jl.canonical_json(rebuild_live_state(records + records))
        assert s1 == s2
    for sid in ("a", "b"):
        if not stream[sid]:
            continue
        final = m.refresh(sid, klass="bulk")
        cold = SessionManager(MockEngine(seed=0), tmp_path / f"cold-{sid}",
                              config=cfg)
        cold.create(session_id="c")
        expect = cold.append("c", stream[sid], refresh=True)["refresh"]
        assert final["summary"] == expect["summary"]


def test_close_during_refresh_cancels_cleanly(tmp_path):
    """A DELETE racing a slow refresh: the refresh aborts through the
    executor cancel hooks, close() wins, and the manager stays usable."""
    m = SessionManager(MockEngine(seed=0, latency_s=0.05), tmp_path,
                       config=_live_cfg())
    m.create(session_id="s")
    m.append("s", make_segments(40, seed=1))
    out: dict = {}

    def do_refresh():
        try:
            out["r"] = m.refresh("s", klass="bulk")
        except KeyError:
            out["r"] = {"cancelled": True}

    t = threading.Thread(target=do_refresh)
    t.start()
    time.sleep(0.08)  # inside the map stream
    m.close("s")
    t.join(timeout=30)
    assert not t.is_alive()
    assert m.get("s") is None
    # cancelled refreshes report so (or completed just before the close)
    assert "r" in out
    m.create(session_id="s")  # id reusable after close
    assert m.get("s") is not None


@pytest.mark.parametrize("seed", [0, 1])
def test_fuzz_live_waves_scheduler_audit_clean(tmp_path, seed):
    """The jax arm (ISSUE 13 satellite): interleaved append/refresh
    waves through a REAL continuous scheduler — after every refresh the
    scheduler's invariant auditor (page conservation, refcount balance,
    radix structure) must be clean.  Token identity is asserted on the
    mock arm only: a content-free random-init argmax is knife-edge under
    partial recompute on a differently-warmed engine (the PR 7 chaos
    rationale)."""
    from lmrs_tpu.config import ModelConfig
    from lmrs_tpu.engine.jax_engine import JaxEngine

    model = ModelConfig(vocab_size=512, dim=64, n_layers=2, n_heads=4,
                        n_kv_heads=2, hidden_dim=128, max_seq_len=256,
                        dtype="float32")
    eng = JaxEngine(
        EngineConfig(backend="jax", scheduler="continuous", max_tokens=48,
                     temperature=0.0, max_batch_slots=2, seed=0,
                     decode_block=4, page_size=16, num_pages=48),
        model)
    cfg = PipelineConfig(
        chunk=ChunkConfig(max_tokens_per_chunk=120, overlap_tokens=0,
                          context_tokens=30, tokenizer="approx"),
        engine=EngineConfig(backend="jax", temperature=0.0, seed=0,
                            max_tokens=16, retry_delay=0.0),
        reduce=ReduceConfig(max_summaries_per_batch=3,
                            max_tokens_per_batch=12, reserve_tokens=0),
        live=LiveConfig(class_default="bulk"))
    try:
        m = SessionManager(eng, tmp_path, config=cfg)
        m.create(session_id="s")
        rng = random.Random(seed)
        pool = lw.live_segments(36, seed=20 + seed)
        cursor = 0
        refreshes = 0
        while cursor < len(pool):
            k = rng.randrange(4, 12)
            m.append("s", pool[cursor:cursor + k])
            cursor += k
            r = m.refresh("s", klass=rng.choice(("interactive", "bulk")))
            refreshes += 1
            assert eng._scheduler.audit() == [], "auditor dirty after wave"
            assert r["num_chunks"] > 0
        assert refreshes >= 3
        # resume path against the same engine: audit stays clean
        m2 = SessionManager(eng, tmp_path, config=cfg)
        assert m2.recover() == 1
        r = m2.refresh("s", klass="bulk")
        assert eng._scheduler.audit() == []
        assert r["dirty_chunks"] == 0 or r["summary"]
    finally:
        eng.shutdown()


# --------------------------------------------------------------------------
# serving surface: /v1/sessions*, restart, router stickiness
# --------------------------------------------------------------------------


def _call(port, method, path, body=None, host="127.0.0.1"):
    import http.client

    conn = http.client.HTTPConnection(host, port, timeout=60)
    conn.request(method, path,
                 body=None if body is None else json.dumps(body),
                 headers={"Content-Type": "application/json"})
    r = conn.getresponse()
    data = json.loads(r.read())
    conn.close()
    return r.status, data


def test_http_session_lifecycle(tmp_path):
    from lmrs_tpu.serving.server import EngineHTTPServer

    srv = EngineHTTPServer(MockEngine(seed=0), port=0,
                           batch_window_s=0.01,
                           live_dir=str(tmp_path / "live"),
                           pipeline_config=_live_cfg())
    srv.start_background()
    segs = make_segments(60, seed=5)
    try:
        p = srv.port
        st, doc = _call(p, "POST", "/v1/sessions",
                        {"session_id": "abc", "params": {"class": "bulk"}})
        assert st == 200 and doc["id"] == "abc"
        # idempotent re-create
        st, doc = _call(p, "POST", "/v1/sessions", {"session_id": "abc"})
        assert st == 200 and doc["id"] == "abc"
        st, doc = _call(p, "POST", "/v1/sessions/abc/segments",
                        {"segments": segs[:30], "refresh": True})
        assert st == 200 and doc["refresh"]["summary"]
        st, doc = _call(p, "GET", "/v1/sessions/abc/summary")
        assert st == 200 and doc["summary"]
        assert doc["staleness"]["stale"] is False
        st, doc = _call(p, "POST", "/v1/sessions/abc/segments",
                        {"segments": segs[30:]})
        assert st == 200 and "refresh" not in doc
        st, doc = _call(p, "GET", "/v1/sessions/abc/summary")
        assert doc["staleness"]["stale"] is True
        st, doc = _call(p, "GET", "/v1/sessions/abc/summary?refresh=1")
        assert doc["staleness"]["stale"] is False
        st, doc = _call(p, "POST", "/v1/sessions/abc/refresh",
                        {"class": "bulk"})
        assert st == 200 and doc["dirty_chunks"] == 0
        st, doc = _call(p, "GET", "/v1/sessions")
        assert st == 200 and len(doc["data"]) == 1
        st, doc = _call(p, "GET", "/v1/sessions/abc")
        assert st == 200 and doc["num_chunks"] > 0
        # error surfaces
        st, doc = _call(p, "GET", "/v1/sessions/nope")
        assert st == 404
        st, doc = _call(p, "POST", "/v1/sessions",
                        {"params": {"bogus": 1}})
        assert st == 400
        st, doc = _call(p, "POST", "/v1/sessions/abc/segments",
                        {"segments": "no"})
        assert st == 400
        # metrics exposure
        st, doc = _call(p, "GET", "/metrics")
        assert doc["live"]["sessions"] == 1
        import urllib.request

        req = urllib.request.Request(f"http://127.0.0.1:{p}/metrics",
                                     headers={"Accept": "text/plain"})
        text = urllib.request.urlopen(req, timeout=10).read().decode()
        assert "lmrs_live_sessions_active 1" in text
        assert "lmrs_live_refreshes_total" in text
        st, doc = _call(p, "DELETE", "/v1/sessions/abc")
        assert st == 200 and doc["status"] == "closed"
        st, doc = _call(p, "GET", "/v1/sessions/abc")
        assert st == 404
    finally:
        srv.shutdown()


def test_http_session_api_disabled_501():
    from lmrs_tpu.serving.server import EngineHTTPServer

    srv = EngineHTTPServer(MockEngine(seed=0), port=0, batch_window_s=0.01)
    srv.start_background()
    try:
        st, doc = _call(srv.port, "POST", "/v1/sessions", {})
        assert st == 501 and "live-dir" in doc["error"]["message"]
    finally:
        srv.shutdown()


def test_http_session_survives_server_restart(tmp_path):
    from lmrs_tpu.serving.server import EngineHTTPServer

    d = str(tmp_path / "live")
    segs = make_segments(50, seed=8)
    srv = EngineHTTPServer(MockEngine(seed=0), port=0, batch_window_s=0.01,
                           live_dir=d, pipeline_config=_live_cfg())
    srv.start_background()
    try:
        st, _ = _call(srv.port, "POST", "/v1/sessions",
                      {"session_id": "s"})
        st, doc = _call(srv.port, "POST", "/v1/sessions/s/segments",
                        {"segments": segs, "refresh": True})
        summary = doc["refresh"]["summary"]
    finally:
        srv.shutdown()
    srv2 = EngineHTTPServer(MockEngine(seed=0), port=0, batch_window_s=0.01,
                            live_dir=d, pipeline_config=_live_cfg())
    srv2.start_background()
    try:
        st, doc = _call(srv2.port, "GET", "/v1/sessions/s/summary")
        assert st == 200 and doc["summary"] == summary
        assert doc["staleness"]["stale"] is False
        st, doc = _call(srv2.port, "GET", "/v1/sessions/s")
        assert doc["recovered"] is True
    finally:
        srv2.shutdown()


def test_router_sessions_sticky_and_rescan(tmp_path):
    """Fleet deployments: the front router-backed server has no local
    SessionManager — /v1/sessions* forwards sticky by session id (the
    journal AND the warm prefix tree live on one backend), and a fresh
    router re-locates sessions by fleet scan."""
    from lmrs_tpu.serving.router import RouterEngine
    from lmrs_tpu.serving.server import EngineHTTPServer

    segs = make_segments(60, seed=5)
    backends = [
        EngineHTTPServer(MockEngine(seed=0), port=0, batch_window_s=0.01,
                         live_dir=str(tmp_path / f"b{i}"),
                         pipeline_config=_live_cfg())
        for i in range(2)]
    for b in backends:
        b.start_background()
    hosts = [f"127.0.0.1:{b.port}" for b in backends]
    router = RouterEngine(hosts)
    front = EngineHTTPServer(router, port=0, batch_window_s=0.01)
    front.start_background()
    try:
        p = front.port
        st, doc = _call(p, "POST", "/v1/sessions", {"session_id": "r1"})
        assert st == 200 and doc["id"] == "r1"
        st, doc = _call(p, "POST", "/v1/sessions/r1/segments",
                        {"segments": segs[:30], "refresh": True})
        assert st == 200 and doc["refresh"]["summary"]
        st, doc = _call(p, "POST", "/v1/sessions/r1/segments",
                        {"segments": segs[30:], "refresh": True})
        summary = doc["refresh"]["summary"]
        # exactly one backend owns it (journal + warm tree colocated)
        statuses = sorted(_call(b.port, "GET", "/v1/sessions/r1")[0]
                          for b in backends)
        assert statuses == [200, 404]
        # a second session with another id may land anywhere, but stays
        # pinned wherever it landed
        st, doc = _call(p, "POST", "/v1/sessions", {"session_id": "r2"})
        st, doc = _call(p, "POST", "/v1/sessions/r2/segments",
                        {"segments": segs[:10], "refresh": True})
        assert st == 200
        # fresh router (restart): unknown id re-locates by fleet scan
        router2 = RouterEngine(hosts)
        st, doc = router2.session_request(
            "GET", "/v1/sessions/r1/summary", None)
        assert st == 200 and doc["summary"] == summary
        st, doc = router2.session_request("GET", "/v1/sessions", None)
        assert {d["id"] for d in doc["data"]} == {"r1", "r2"}
        st, doc = router2.session_request(
            "GET", "/v1/sessions/missing/summary", None)
        assert st == 404
        # create-retry convergence: a router with a DIFFERENT fleet view
        # re-creating an existing id must land on the backend that holds
        # it (the existing journal wins), never fork a second journal
        router3 = RouterEngine(list(reversed(hosts)))
        st, doc = router3.session_request(
            "POST", "/v1/sessions", {"session_id": "r1"})
        assert st == 200 and doc["num_segments"] > 0  # the EXISTING one
        statuses = sorted(_call(b.port, "GET", "/v1/sessions/r1")[0]
                          for b in backends)
        assert statuses == [200, 404], "create retry forked the session"
        router3.shutdown()
        router2.shutdown()
        st, doc = _call(p, "DELETE", "/v1/sessions/r1")
        assert st == 200
        st, doc = _call(p, "GET", "/v1/sessions/r1")
        assert st == 404
    finally:
        front.shutdown()
        router.shutdown()
        for b in backends:
            b.shutdown()
