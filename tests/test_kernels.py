"""Pallas flash-attention kernel vs the XLA reference (interpret mode on CPU).

The kernel's correctness contract (ops/flash_attention.py): match
ops.attention.attention() to f32 tolerance on fresh (position 0-based)
self-attention, including GQA, ragged lengths, and non-divisible shapes.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from lmrs_tpu.ops.attention import attention
from lmrs_tpu.ops.flash_attention import flash_attention


def _ref(q, k, v, lengths):
    b, s = q.shape[:2]
    positions = jnp.broadcast_to(jnp.arange(s)[None], (b, s))
    return attention(q, k, v, positions, lengths)


@pytest.mark.parametrize("h,kh", [(4, 4), (8, 2)])
def test_flash_matches_reference(h, kh):
    b, s, hd = 2, 512, 64
    ks = jax.random.split(jax.random.PRNGKey(0), 3)
    q = jax.random.normal(ks[0], (b, s, h, hd), jnp.float32)
    k = jax.random.normal(ks[1], (b, s, kh, hd), jnp.float32)
    v = jax.random.normal(ks[2], (b, s, kh, hd), jnp.float32)
    lengths = jnp.asarray([s, s // 3], jnp.int32)
    got = flash_attention(q, k, v, lengths, q_block=128, kv_block=128,
                          interpret=True)
    want = _ref(q, k, v, lengths)
    # rows past a sequence's valid length are garbage on both paths; compare
    # only valid rows
    for i, n in enumerate([s, s // 3]):
        np.testing.assert_allclose(np.asarray(got[i, :n]),
                                   np.asarray(want[i, :n]),
                                   rtol=2e-5, atol=2e-5)


def test_flash_non_divisible_seq():
    b, s, h, kh, hd = 1, 300, 4, 2, 64  # not a multiple of the block size
    ks = jax.random.split(jax.random.PRNGKey(1), 3)
    q = jax.random.normal(ks[0], (b, s, h, hd), jnp.float32)
    k = jax.random.normal(ks[1], (b, s, kh, hd), jnp.float32)
    v = jax.random.normal(ks[2], (b, s, kh, hd), jnp.float32)
    got = flash_attention(q, k, v, None, q_block=128, kv_block=128,
                          interpret=True)
    want = _ref(q, k, v, jnp.asarray([s], jnp.int32))
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-5, atol=2e-5)


def test_use_flash_prefill_gate():
    from lmrs_tpu.models.transformer import _use_flash_prefill

    assert not _use_flash_prefill(128, 128)  # short: XLA always
    assert not _use_flash_prefill(2048, 80)  # unaligned head dim
    # on the CPU test backend the long-seq gate must still say no
    assert not _use_flash_prefill(2048, 128)
