"""Pallas flash-attention kernel vs the XLA reference (interpret mode on CPU).

The kernel's correctness contract (ops/flash_attention.py): match
ops.attention.attention() to f32 tolerance on fresh (position 0-based)
self-attention, including GQA, ragged lengths, and non-divisible shapes.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from lmrs_tpu.ops.attention import attention
from lmrs_tpu.ops.flash_attention import flash_attention


def _ref(q, k, v, lengths):
    b, s = q.shape[:2]
    positions = jnp.broadcast_to(jnp.arange(s)[None], (b, s))
    return attention(q, k, v, positions, lengths)


@pytest.mark.parametrize("h,kh", [(4, 4), (8, 2)])
def test_flash_matches_reference(h, kh):
    b, s, hd = 2, 512, 64
    ks = jax.random.split(jax.random.PRNGKey(0), 3)
    q = jax.random.normal(ks[0], (b, s, h, hd), jnp.float32)
    k = jax.random.normal(ks[1], (b, s, kh, hd), jnp.float32)
    v = jax.random.normal(ks[2], (b, s, kh, hd), jnp.float32)
    lengths = jnp.asarray([s, s // 3], jnp.int32)
    got = flash_attention(q, k, v, lengths, q_block=128, kv_block=128,
                          interpret=True)
    want = _ref(q, k, v, lengths)
    # rows past a sequence's valid length are zeros (kernel, skip_padded_q)
    # vs garbage (XLA reference); compare only valid rows
    for i, n in enumerate([s, s // 3]):
        np.testing.assert_allclose(np.asarray(got[i, :n]),
                                   np.asarray(want[i, :n]),
                                   rtol=2e-5, atol=2e-5)


def test_flash_non_divisible_seq():
    b, s, h, kh, hd = 1, 300, 4, 2, 64  # not a multiple of the block size
    ks = jax.random.split(jax.random.PRNGKey(1), 3)
    q = jax.random.normal(ks[0], (b, s, h, hd), jnp.float32)
    k = jax.random.normal(ks[1], (b, s, kh, hd), jnp.float32)
    v = jax.random.normal(ks[2], (b, s, kh, hd), jnp.float32)
    got = flash_attention(q, k, v, None, q_block=128, kv_block=128,
                          interpret=True)
    want = _ref(q, k, v, jnp.asarray([s], jnp.int32))
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-5, atol=2e-5)


def test_use_flash_prefill_gate():
    from lmrs_tpu.models.transformer import _use_flash_prefill

    assert not _use_flash_prefill(128, 128)  # short: XLA always
    assert not _use_flash_prefill(2048, 80)  # unaligned head dim
    # on the CPU test backend the long-seq gate must still say no
    assert not _use_flash_prefill(2048, 128)


def test_fused_decode_matches_scatter_plus_xla():
    """The write-fused ragged decode kernel (interpret mode) must produce
    the same attention output AND the same pool contents as the XLA
    scatter + gather fallback."""
    import jax.numpy as jnp
    from lmrs_tpu.ops.paged_attention import (
        paged_decode_pallas_fused,
        paged_decode_xla,
    )

    b, h, kh, hd, ps, n_pages = 2, 4, 4, 128, 16, 12
    rng = jax.random.split(jax.random.PRNGKey(0), 5)
    k_pages = jax.random.normal(rng[0], (n_pages, kh, ps, hd), jnp.float32)
    v_pages = jax.random.normal(rng[1], (n_pages, kh, ps, hd), jnp.float32)
    q = jax.random.normal(rng[2], (b, h, hd), jnp.float32)
    k_new = jax.random.normal(rng[3], (b, kh, hd), jnp.float32)
    v_new = jax.random.normal(rng[4], (b, kh, hd), jnp.float32)
    # row 0: 29 tokens live (pos 28 = page 1, off 12 -> RMW window start 8);
    # row 1: 5 tokens (off 4 -> window start 0) — covers both w0 cases
    tables = jnp.asarray([[3, 5, 7], [9, 0, 0]], jnp.int32)
    kv_lens = jnp.asarray([29, 5], jnp.int32)

    # reference: XLA scatter of the new token, then gather-attend
    pos = kv_lens - 1
    page = jnp.take_along_axis(tables, (pos // ps)[:, None], 1)[:, 0]
    off = pos % ps
    k_ref = k_pages.at[page, :, off].set(k_new)
    v_ref = v_pages.at[page, :, off].set(v_new)
    want = paged_decode_xla(q, k_ref, v_ref, tables, kv_lens)

    got, k_out, v_out = paged_decode_pallas_fused(
        q, k_new, v_new, k_pages, v_pages, tables, kv_lens, interpret=True)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-5, atol=2e-5)
    np.testing.assert_array_equal(np.asarray(k_out), np.asarray(k_ref))
    np.testing.assert_array_equal(np.asarray(v_out), np.asarray(v_ref))


def test_ragged_decode_clamps_stale_lengths():
    """Regression: a row whose kv_len exceeds its page table's width (a
    freed slot's stale length, or any degenerate input) must clamp its
    page walk and write index to the table instead of indexing SMEM out
    of bounds — on real TPUs the unclamped read DMA'd from garbage page
    ids (fixed alongside scheduler-side zeroing; see scheduler admit()/
    _maybe_finish)."""
    import jax.numpy as jnp
    from lmrs_tpu.ops.paged_attention import paged_decode_pallas_fused

    b, h, kh, hd, ps, n_pages = 2, 4, 4, 128, 16, 12
    rng = jax.random.split(jax.random.PRNGKey(1), 5)
    k_pages = jax.random.normal(rng[0], (n_pages, kh, ps, hd), jnp.float32)
    v_pages = jax.random.normal(rng[1], (n_pages, kh, ps, hd), jnp.float32)
    q = jax.random.normal(rng[2], (b, h, hd), jnp.float32)
    k_new = jax.random.normal(rng[3], (b, kh, hd), jnp.float32)
    v_new = jax.random.normal(rng[4], (b, kh, hd), jnp.float32)
    from lmrs_tpu.ops.paged_attention import paged_decode_xla

    tables = jnp.asarray([[3, 5], [9, 0]], jnp.int32)  # width 2 = 32 tokens
    # row 0 normal; row 1 claims 180 tokens (needs 12 pages > width 2)
    kv_lens = jnp.asarray([20, 180], jnp.int32)
    clamped = jnp.minimum(kv_lens, tables.shape[1] * ps)

    got, k_out, v_out = paged_decode_pallas_fused(
        q, k_new, v_new, k_pages, v_pages, tables, kv_lens, interpret=True)

    # reference mirrors the kernel: the degenerate row's write is SKIPPED
    # entirely (its position lies past the table span — a clipped-page
    # write would alias/scribble another window's rows), and the walk
    # attends each tabled page exactly once with the length capped at the
    # table capacity.  An unclamped kernel would re-attend its last
    # column's page for every overflow walk step, shifting row 1's softmax
    # — so output parity here genuinely discriminates fixed vs broken.
    pos0 = int(kv_lens[0]) - 1  # row 0 only; row 1's write is skipped
    page0, off0 = int(tables[0, pos0 // ps]), pos0 % ps
    k_ref = k_pages.at[page0, :, off0].set(k_new[0])
    v_ref = v_pages.at[page0, :, off0].set(v_new[0])
    want = paged_decode_xla(q, k_ref, v_ref, tables, clamped)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-5, atol=2e-5)
    # writes land ONLY on row 0's write page (pos 19 -> column 1 -> page
    # 5); row 1's out-of-span write is skipped, not clipped; K and V both
    for name, out_pool, in_pool in (("k", k_out, k_pages), ("v", v_out, v_pages)):
        touched = set(np.flatnonzero(
            (np.asarray(out_pool) != np.asarray(in_pool)).any(axis=(1, 2, 3))))
        assert touched == {5}, f"{name} wrote pages {touched}, want {{5}}"


def _tp_mesh(tp=2):
    from jax.sharding import Mesh

    return Mesh(np.array(jax.devices()[:tp]).reshape(1, tp, 1, 1, 1),
                ("dp", "tp", "sp", "ep", "pp"))


def test_fused_decode_sharded_matches_xla():
    """The shard_map-wrapped fused decode kernel under a tp=2 mesh must
    match the XLA scatter+gather reference — pools kv-head-sharded, tables
    and lengths replicated (the TP serving layout, kv_cache.py)."""
    import jax.numpy as jnp
    from lmrs_tpu.ops.paged_attention import (
        paged_decode_fused_sharded,
        paged_decode_xla,
    )

    b, h, kh, hd, ps, n_pages = 3, 8, 2, 128, 16, 12
    rng = jax.random.split(jax.random.PRNGKey(2), 5)
    k_pages = jax.random.normal(rng[0], (n_pages, kh, ps, hd), jnp.float32)
    v_pages = jax.random.normal(rng[1], (n_pages, kh, ps, hd), jnp.float32)
    q = jax.random.normal(rng[2], (b, h, hd), jnp.float32)
    k_new = jax.random.normal(rng[3], (b, kh, hd), jnp.float32)
    v_new = jax.random.normal(rng[4], (b, kh, hd), jnp.float32)
    tables = jnp.asarray([[1, 2, 3, 0], [4, 5, 0, 0], [6, 7, 8, 0]], jnp.int32)
    kv_lens = jnp.asarray([40, 17, 33], jnp.int32)

    pos = kv_lens - 1
    page = jnp.take_along_axis(tables, (pos // ps)[:, None], 1)[:, 0]
    off = pos % ps
    k_ref = k_pages.at[page, :, off].set(k_new)
    v_ref = v_pages.at[page, :, off].set(v_new)
    want = paged_decode_xla(q, k_ref, v_ref, tables, kv_lens)

    got, k_out, v_out = paged_decode_fused_sharded(
        q, k_new, v_new, k_pages, v_pages, tables, kv_lens,
        _tp_mesh(), interpret=True)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-5, atol=2e-5)
    np.testing.assert_array_equal(np.asarray(k_out), np.asarray(k_ref))
    np.testing.assert_array_equal(np.asarray(v_out), np.asarray(v_ref))


def test_flash_sharded_matches_reference():
    """The shard_map-wrapped flash prefill kernel under a tp=2 mesh must
    match the XLA attention reference (GQA heads shard with their kv head)."""
    from lmrs_tpu.ops.flash_attention import flash_attention_sharded

    b, s, h, kh, hd = 2, 512, 8, 2, 64
    ks = jax.random.split(jax.random.PRNGKey(3), 3)
    q = jax.random.normal(ks[0], (b, s, h, hd), jnp.float32)
    k = jax.random.normal(ks[1], (b, s, kh, hd), jnp.float32)
    v = jax.random.normal(ks[2], (b, s, kh, hd), jnp.float32)
    lengths = jnp.asarray([s, s // 3], jnp.int32)
    got = flash_attention_sharded(q, k, v, lengths, _tp_mesh(), interpret=True)
    want = _ref(q, k, v, lengths)
    for i, n in enumerate([s, s // 3]):
        np.testing.assert_allclose(np.asarray(got[i, :n]),
                                   np.asarray(want[i, :n]),
                                   rtol=2e-5, atol=2e-5)


def test_multi_token_verify_matches_xla_reference():
    """The ragged multi-token verify kernel (speculative decode: T
    consecutive tokens written + attended with per-token causality in one
    page walk) must match the scatter+gather XLA reference — outputs AND
    pool contents.  Lengths chosen so the T-token span straddles a page
    boundary and an 8-row RMW window boundary."""
    import jax.numpy as jnp
    from lmrs_tpu.ops.paged_attention import (
        paged_decode_multi_xla,
        paged_decode_pallas_multi,
    )

    b, t, h, kh, hd, ps, n_pages = 3, 5, 8, 4, 128, 16, 16
    rng = jax.random.split(jax.random.PRNGKey(3), 5)
    k_pages = jax.random.normal(rng[0], (n_pages, kh, ps, hd), jnp.float32)
    v_pages = jax.random.normal(rng[1], (n_pages, kh, ps, hd), jnp.float32)
    q = jax.random.normal(rng[2], (b, t, h, hd), jnp.float32)
    k_new = jax.random.normal(rng[3], (b, t, kh, hd), jnp.float32)
    v_new = jax.random.normal(rng[4], (b, t, kh, hd), jnp.float32)
    tables = jnp.asarray([[1, 2, 3], [4, 5, 6], [7, 8, 9]], jnp.int32)
    # row 0: span 13..17 straddles page 0->1; row 1: span 1..5 in-page but
    # crosses the 8-row window at base offset 1; row 2: base offset 30
    # straddles page AND window
    kv_lens = jnp.asarray([18, 6, 35], jnp.int32)

    want, k_ref, v_ref = paged_decode_multi_xla(
        q, k_new, v_new, k_pages, v_pages, tables, kv_lens)
    got, k_out, v_out = paged_decode_pallas_multi(
        q, k_new, v_new, k_pages, v_pages, tables, kv_lens, interpret=True)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-5, atol=2e-5)
    np.testing.assert_array_equal(np.asarray(k_out), np.asarray(k_ref))
    np.testing.assert_array_equal(np.asarray(v_out), np.asarray(v_ref))


def test_multi_token_verify_gqa_and_t1_degenerate():
    """GQA head grouping through the multi kernel, plus T=1 degenerating to
    the single-token contract (same mask, same write)."""
    import jax.numpy as jnp
    from lmrs_tpu.ops.paged_attention import (
        paged_decode_multi_xla,
        paged_decode_pallas_multi,
    )

    b, h, kh, hd, ps, n_pages = 2, 8, 2, 128, 16, 8
    for t in (1, 4):
        rng = jax.random.split(jax.random.PRNGKey(10 + t), 5)
        k_pages = jax.random.normal(rng[0], (n_pages, kh, ps, hd), jnp.float32)
        v_pages = jax.random.normal(rng[1], (n_pages, kh, ps, hd), jnp.float32)
        q = jax.random.normal(rng[2], (b, t, h, hd), jnp.float32)
        k_new = jax.random.normal(rng[3], (b, t, kh, hd), jnp.float32)
        v_new = jax.random.normal(rng[4], (b, t, kh, hd), jnp.float32)
        tables = jnp.asarray([[1, 2], [3, 4]], jnp.int32)
        kv_lens = jnp.asarray([t + 7, t], jnp.int32)  # row 1: fresh row
        want, k_ref, v_ref = paged_decode_multi_xla(
            q, k_new, v_new, k_pages, v_pages, tables, kv_lens)
        got, k_out, v_out = paged_decode_pallas_multi(
            q, k_new, v_new, k_pages, v_pages, tables, kv_lens, interpret=True)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   rtol=2e-5, atol=2e-5)
        np.testing.assert_array_equal(np.asarray(k_out), np.asarray(k_ref))
        np.testing.assert_array_equal(np.asarray(v_out), np.asarray(v_ref))


def test_multi_token_verify_max_pos_boundary():
    """Drafts overhanging max_pos (the max-seq-len cap) must be NEITHER
    written (earlier real cache entries stay intact — a clamped length
    would slide the write span backwards over them) NOR attended."""
    import jax.numpy as jnp
    from lmrs_tpu.ops.paged_attention import (
        paged_decode_multi_xla,
        paged_decode_pallas_multi,
    )

    b, t, h, kh, hd, ps, n_pages = 2, 4, 4, 2, 128, 16, 8
    max_pos = 32  # 2 pages of capacity
    rng = jax.random.split(jax.random.PRNGKey(5), 5)
    k_pages = jax.random.normal(rng[0], (n_pages, kh, ps, hd), jnp.float32)
    v_pages = jax.random.normal(rng[1], (n_pages, kh, ps, hd), jnp.float32)
    q = jax.random.normal(rng[2], (b, t, h, hd), jnp.float32)
    k_new = jax.random.normal(rng[3], (b, t, kh, hd), jnp.float32)
    v_new = jax.random.normal(rng[4], (b, t, kh, hd), jnp.float32)
    tables = jnp.asarray([[1, 2], [3, 4]], jnp.int32)
    # row 0: base 30 -> tokens at 30,31 valid, 32,33 overhang the cap;
    # row 1: fully inside
    kv_lens = jnp.asarray([34, 20], jnp.int32)  # UNclamped lengths

    want, k_ref, v_ref = paged_decode_multi_xla(
        q, k_new, v_new, k_pages, v_pages, tables, kv_lens, max_pos=max_pos)
    got, k_out, v_out = paged_decode_pallas_multi(
        q, k_new, v_new, k_pages, v_pages, tables, kv_lens, interpret=True,
        max_pos=max_pos)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-5, atol=2e-5)
    # pool parity on the real pages (null page 0 excluded: the reference
    # parks overhang writes there by contract)
    np.testing.assert_array_equal(np.asarray(k_out[1:5]),
                                  np.asarray(k_ref[1:5]))
    np.testing.assert_array_equal(np.asarray(v_out[1:5]),
                                  np.asarray(v_ref[1:5]))
    # and the overhang really was suppressed: row 0's pre-cap cache entries
    # at positions 28..29 (page 2, offsets 12..13) are untouched
    np.testing.assert_array_equal(np.asarray(k_out[2, :, 12:14]),
                                  np.asarray(k_pages[2, :, 12:14]))


def test_multi_token_verify_no_window_alias_at_table_edge():
    """Regression (round-3 review): with small pages an OVERHANGING padded
    RMW window clipped onto the last table column aliases an earlier
    window's physical rows — its stale write-back would revert freshly
    written K/V.  page_size=8, T=5, span ending exactly at the table edge:
    windows at offsets 0 (valid) and 8 (overhang, must be SKIPPED)."""
    import jax.numpy as jnp
    from lmrs_tpu.ops.paged_attention import (
        paged_decode_multi_xla,
        paged_decode_pallas_multi,
    )

    b, t, h, kh, hd, ps, n_pages = 1, 5, 4, 2, 128, 8, 8
    rng = jax.random.split(jax.random.PRNGKey(9), 5)
    k_pages = jax.random.normal(rng[0], (n_pages, kh, ps, hd), jnp.float32)
    v_pages = jax.random.normal(rng[1], (n_pages, kh, ps, hd), jnp.float32)
    q = jax.random.normal(rng[2], (b, t, h, hd), jnp.float32)
    k_new = jax.random.normal(rng[3], (b, t, kh, hd), jnp.float32)
    v_new = jax.random.normal(rng[4], (b, t, kh, hd), jnp.float32)
    tables = jnp.asarray([[1, 2]], jnp.int32)  # capacity 16 tokens
    # base = 11: tokens at 11..15 — all valid, spanning windows 8..15 of
    # page 2 AND the padded window at global offset 16 (start >= capacity)
    kv_lens = jnp.asarray([16], jnp.int32)

    want, k_ref, v_ref = paged_decode_multi_xla(
        q, k_new, v_new, k_pages, v_pages, tables, kv_lens)
    got, k_out, v_out = paged_decode_pallas_multi(
        q, k_new, v_new, k_pages, v_pages, tables, kv_lens, interpret=True)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-5, atol=2e-5)
    # the freshly written rows must SURVIVE (an aliased stale write-back
    # reverted them before this fix); pages 1-2 are the row's real pages
    np.testing.assert_array_equal(np.asarray(k_out[1:3]),
                                  np.asarray(k_ref[1:3]))
    np.testing.assert_array_equal(np.asarray(v_out[1:3]),
                                  np.asarray(v_ref[1:3]))


# --------------------------------------------------- multi-row page walk
# Parity contract (ISSUE 4): with row_group > 1 every decode kernel's
# output AND pool contents must be BIT-IDENTICAL to the per-row grid
# (row_group=1, the LMRS_MULTIROW=0 path) across ragged lengths, inactive
# rows, batch sizes that don't divide the group, bf16 and int8 pools, and
# the n_tokens > 1 speculative-verify shape.  Page 0 (the reserved null
# page) is excluded from pool comparison: padded group rows park their
# masked writes there by the same convention as inactive dispatch rows.


def _ragged_fixture(seed, b=5, h=8, kh=4, hd=128, ps=16, n_pages=32):
    rng = jax.random.split(jax.random.PRNGKey(seed), 5)
    k_pages = jax.random.normal(rng[0], (n_pages, kh, ps, hd), jnp.float32)
    v_pages = jax.random.normal(rng[1], (n_pages, kh, ps, hd), jnp.float32)
    q = jax.random.normal(rng[2], (b, h, hd), jnp.float32)
    k_new = jax.random.normal(rng[3], (b, kh, hd), jnp.float32)
    v_new = jax.random.normal(rng[4], (b, kh, hd), jnp.float32)
    tables = jnp.asarray(
        np.random.default_rng(seed).permutation(n_pages - 1)[: b * 3]
        .reshape(b, 3) + 1, jnp.int32)
    # ragged: multi-page, inactive (0), single-token, page-boundary rows
    kv_lens = jnp.asarray([40, 0, 17, 48, 1], jnp.int32)
    return q, k_new, v_new, k_pages, v_pages, tables, kv_lens


def test_multirow_walk_parity():
    """Walk-only group kernel vs the per-row grid: bit-identical outputs
    across group sizes, including g not dividing B (padded tail group)."""
    from lmrs_tpu.ops.paged_attention import paged_decode_pallas

    q, _, _, kp, vp, tables, kv_lens = _ragged_fixture(0)
    want = paged_decode_pallas(q, kp, vp, tables, kv_lens, interpret=True)
    for g in (2, 3, 5):
        got = paged_decode_pallas(q, kp, vp, tables, kv_lens,
                                  interpret=True, row_group=g)
        np.testing.assert_array_equal(np.asarray(got), np.asarray(want))


def test_multirow_fused_parity_bf16():
    """Fused walk+RMW group kernel vs per-row: outputs and REAL pool pages
    bit-identical (the cross-row RMW pipeline crossing group boundaries)."""
    from lmrs_tpu.ops.paged_attention import paged_decode_pallas_fused

    q, kn, vn, kp, vp, tables, kv_lens = _ragged_fixture(1)
    want, k_ref, v_ref = paged_decode_pallas_fused(
        q, kn, vn, kp, vp, tables, kv_lens, interpret=True)
    for g in (2, 4, 5):
        got, k_out, v_out = paged_decode_pallas_fused(
            q, kn, vn, kp, vp, tables, kv_lens, interpret=True, row_group=g)
        np.testing.assert_array_equal(np.asarray(got), np.asarray(want))
        np.testing.assert_array_equal(np.asarray(k_out[1:]),
                                      np.asarray(k_ref[1:]))
        np.testing.assert_array_equal(np.asarray(v_out[1:]),
                                      np.asarray(v_ref[1:]))


def test_multirow_fused_parity_int8():
    """Group kernel over int8 pools (32-row RMW windows, folded per-channel
    dequant): bit-identical to the per-row int8 kernel — the quantize →
    clip → store path must round identically through the group pipeline."""
    from lmrs_tpu.ops.paged_attention import paged_decode_pallas_fused

    rng = np.random.default_rng(7)
    B, H, K, hd, ps, P = 5, 4, 2, 128, 64, 16
    kq = jnp.asarray(rng.integers(-127, 128, (P, K, ps, hd)), jnp.int8)
    vq = jnp.asarray(rng.integers(-127, 128, (P, K, ps, hd)), jnp.int8)
    tables = jnp.asarray(rng.permutation(P - 1)[: B * 3].reshape(B, 3) + 1,
                         jnp.int32)
    lens = jnp.asarray([ps * 2 + 17, 33, 0, ps * 3, 1], jnp.int32)
    q = jnp.asarray(rng.standard_normal((B, H, hd)), jnp.float32)
    kn = jnp.asarray(rng.standard_normal((B, K, hd)), jnp.float32)
    vn = jnp.asarray(rng.standard_normal((B, K, hd)), jnp.float32)
    ks = jnp.asarray(rng.uniform(0.01, 0.05, (B, K, hd)), jnp.float32)
    vs = jnp.asarray(rng.uniform(0.01, 0.05, (B, K, hd)), jnp.float32)

    want, k_ref, v_ref = paged_decode_pallas_fused(
        q, kn, vn, kq, vq, tables, lens, interpret=True,
        kscale=ks, vscale=vs)
    for g in (2, 5):
        got, k_out, v_out = paged_decode_pallas_fused(
            q, kn, vn, kq, vq, tables, lens, interpret=True,
            kscale=ks, vscale=vs, row_group=g)
        np.testing.assert_array_equal(np.asarray(got), np.asarray(want))
        np.testing.assert_array_equal(np.asarray(k_out[1:]),
                                      np.asarray(k_ref[1:]))
        np.testing.assert_array_equal(np.asarray(v_out[1:]),
                                      np.asarray(v_ref[1:]))


def test_multirow_multi_token_verify_parity():
    """Speculative-verify shape (n_tokens > 1) through the group kernel:
    bit-identical emit-path outputs and pool contents vs per-row, with
    token spans straddling pages and RMW windows, an out-of-span
    stale-length row, and a fresh (length == T) row."""
    from lmrs_tpu.ops.paged_attention import paged_decode_pallas_multi

    b, t, h, kh, hd, ps, n_pages = 5, 3, 8, 4, 128, 16, 32
    rng = jax.random.split(jax.random.PRNGKey(11), 5)
    k_pages = jax.random.normal(rng[0], (n_pages, kh, ps, hd), jnp.float32)
    v_pages = jax.random.normal(rng[1], (n_pages, kh, ps, hd), jnp.float32)
    q = jax.random.normal(rng[2], (b, t, h, hd), jnp.float32)
    k_new = jax.random.normal(rng[3], (b, t, kh, hd), jnp.float32)
    v_new = jax.random.normal(rng[4], (b, t, kh, hd), jnp.float32)
    tables = jnp.asarray(
        np.random.default_rng(11).permutation(n_pages - 1)[: b * 3]
        .reshape(b, 3) + 1, jnp.int32)
    # spans: page-straddling, in-page, stale (out-of-span), window-
    # straddling, fresh row (length == T)
    kv_lens = jnp.asarray([18, 6, 100, 35, t], jnp.int32)

    want, k_ref, v_ref = paged_decode_pallas_multi(
        q, k_new, v_new, k_pages, v_pages, tables, kv_lens, interpret=True)
    for g in (2, 5):
        got, k_out, v_out = paged_decode_pallas_multi(
            q, k_new, v_new, k_pages, v_pages, tables, kv_lens,
            interpret=True, row_group=g)
        np.testing.assert_array_equal(np.asarray(got), np.asarray(want))
        np.testing.assert_array_equal(np.asarray(k_out[1:]),
                                      np.asarray(k_ref[1:]))
        np.testing.assert_array_equal(np.asarray(v_out[1:]),
                                      np.asarray(v_ref[1:]))


def test_multirow_multi_token_verify_parity_int8():
    """n_tokens > 1 over int8 pools through the group kernel: the draft
    rows' RMW quantization and the walk's folded dequant must reproduce
    the per-row kernel bit-for-bit."""
    from lmrs_tpu.ops.paged_attention import paged_decode_pallas_multi

    rng = np.random.default_rng(13)
    B, T, H, K, hd, ps, P = 3, 4, 4, 2, 128, 64, 12
    kq = jnp.asarray(rng.integers(-127, 128, (P, K, ps, hd)), jnp.int8)
    vq = jnp.asarray(rng.integers(-127, 128, (P, K, ps, hd)), jnp.int8)
    tables = jnp.asarray(rng.permutation(P - 1)[: B * 2].reshape(B, 2) + 1,
                         jnp.int32)
    lens = jnp.asarray([ps + 9, T, 70], jnp.int32)
    q = jnp.asarray(rng.standard_normal((B, T, H, hd)), jnp.float32)
    kn = jnp.asarray(rng.standard_normal((B, T, K, hd)), jnp.float32)
    vn = jnp.asarray(rng.standard_normal((B, T, K, hd)), jnp.float32)
    ks = jnp.asarray(rng.uniform(0.01, 0.05, (B, K, hd)), jnp.float32)
    vs = jnp.asarray(rng.uniform(0.01, 0.05, (B, K, hd)), jnp.float32)

    want, k_ref, v_ref = paged_decode_pallas_multi(
        q, kn, vn, kq, vq, tables, lens, interpret=True,
        kscale=ks, vscale=vs)
    got, k_out, v_out = paged_decode_pallas_multi(
        q, kn, vn, kq, vq, tables, lens, interpret=True,
        kscale=ks, vscale=vs, row_group=2)
    np.testing.assert_array_equal(np.asarray(got), np.asarray(want))
    np.testing.assert_array_equal(np.asarray(k_out[1:]), np.asarray(k_ref[1:]))
    np.testing.assert_array_equal(np.asarray(v_out[1:]), np.asarray(v_ref[1:]))


def test_multirow_balanced_row_order():
    """Host-side length-balanced row→group assignment: a valid permutation,
    near-equal group sums, deterministic, short-tail-group aware."""
    from lmrs_tpu.ops.paged_attention import balanced_row_order

    lens = np.array([100, 1, 50, 49, 2, 99])
    perm = balanced_row_order(lens, 2)
    assert sorted(perm.tolist()) == list(range(6))
    sums = lens[perm.reshape(3, 2)].sum(axis=1)
    assert sums.max() - sums.min() <= 2, sums
    # deterministic
    np.testing.assert_array_equal(perm, balanced_row_order(lens, 2))
    # b % g != 0: the LAST group keeps the short seat count (kernel pads)
    perm5 = balanced_row_order(np.array([5, 4, 3, 2, 1]), 2)
    assert sorted(perm5.tolist()) == list(range(5))
    # identity-friendly degenerates
    np.testing.assert_array_equal(balanced_row_order(np.array([3, 3]), 1),
                                  np.argsort(-np.array([3, 3]), kind="stable"))


def test_multirow_sharded_fused_matches_xla():
    """The shard_map-wrapped fused kernel with row grouping under a tp=2
    mesh keeps the XLA reference contract (per-shard group walks)."""
    import jax.numpy as jnp
    from lmrs_tpu.ops.paged_attention import (
        paged_decode_fused_sharded,
        paged_decode_xla,
    )

    b, h, kh, hd, ps, n_pages = 3, 8, 2, 128, 16, 12
    rng = jax.random.split(jax.random.PRNGKey(2), 5)
    k_pages = jax.random.normal(rng[0], (n_pages, kh, ps, hd), jnp.float32)
    v_pages = jax.random.normal(rng[1], (n_pages, kh, ps, hd), jnp.float32)
    q = jax.random.normal(rng[2], (b, h, hd), jnp.float32)
    k_new = jax.random.normal(rng[3], (b, kh, hd), jnp.float32)
    v_new = jax.random.normal(rng[4], (b, kh, hd), jnp.float32)
    tables = jnp.asarray([[1, 2, 3, 0], [4, 5, 0, 0], [6, 7, 8, 0]], jnp.int32)
    kv_lens = jnp.asarray([40, 17, 33], jnp.int32)

    pos = kv_lens - 1
    page = jnp.take_along_axis(tables, (pos // ps)[:, None], 1)[:, 0]
    off = pos % ps
    k_ref = k_pages.at[page, :, off].set(k_new)
    v_ref = v_pages.at[page, :, off].set(v_new)
    want = paged_decode_xla(q, k_ref, v_ref, tables, kv_lens)

    got, k_out, v_out = paged_decode_fused_sharded(
        q, k_new, v_new, k_pages, v_pages, tables, kv_lens,
        _tp_mesh(), interpret=True, row_group=2)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-5, atol=2e-5)
    # page 0 is the reserved null page (engine contract: never read as
    # data); with b % G != 0 the grouped kernel's padded row RMWs it as
    # scratch, so the pool comparison starts at page 1
    np.testing.assert_array_equal(np.asarray(k_out)[1:], np.asarray(k_ref)[1:])
    np.testing.assert_array_equal(np.asarray(v_out)[1:], np.asarray(v_ref)[1:])


def test_multi_token_verify_out_of_span_skips_on_both_paths():
    """A degenerate row whose length exceeds the table span (stale-length
    class) must write NOTHING on BOTH implementations — the XLA reference
    previously clipped onto the last tabled page and scribbled real rows
    (round-3 review finding); real pages must be untouched and the two
    paths must agree."""
    import jax.numpy as jnp
    from lmrs_tpu.ops.paged_attention import (
        paged_decode_multi_xla,
        paged_decode_pallas_multi,
    )

    b, t, h, kh, hd, ps, n_pages = 2, 3, 4, 2, 128, 16, 8
    rng = jax.random.split(jax.random.PRNGKey(21), 5)
    k_pages = jax.random.normal(rng[0], (n_pages, kh, ps, hd), jnp.float32)
    v_pages = jax.random.normal(rng[1], (n_pages, kh, ps, hd), jnp.float32)
    q = jax.random.normal(rng[2], (b, t, h, hd), jnp.float32)
    k_new = jax.random.normal(rng[3], (b, t, kh, hd), jnp.float32)
    v_new = jax.random.normal(rng[4], (b, t, kh, hd), jnp.float32)
    tables = jnp.asarray([[1, 2], [3, 4]], jnp.int32)  # span 32 tokens
    # row 0 normal; row 1 claims 100 tokens — its whole T-token span lies
    # past the table capacity, so no write may land anywhere real
    kv_lens = jnp.asarray([10, 100], jnp.int32)

    want, k_ref, v_ref = paged_decode_multi_xla(
        q, k_new, v_new, k_pages, v_pages, tables, kv_lens)
    got, k_out, v_out = paged_decode_pallas_multi(
        q, k_new, v_new, k_pages, v_pages, tables, kv_lens, interpret=True)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-5, atol=2e-5)
    # row 1's real pages (3, 4) untouched on BOTH paths
    for pool_out, pool_in in ((k_ref, k_pages), (v_ref, v_pages),
                              (k_out, k_pages), (v_out, v_pages)):
        np.testing.assert_array_equal(np.asarray(pool_out[3:5]),
                                      np.asarray(pool_in[3:5]))


def test_multirow_engine_greedy_ab_parity(monkeypatch):
    """End-to-end A/B through the real continuous scheduler (interpret
    kernels): greedy output with the multi-row kernel + length-balanced
    dispatch permutation must be token-identical to LMRS_MULTIROW=0 (the
    per-row control) — the same convention as the LMRS_PACK_PREFILL A/B.
    Ragged prompt lengths so the balancer actually permutes."""
    from lmrs_tpu.config import EngineConfig, ModelConfig
    from lmrs_tpu.engine.api import GenerationRequest
    from lmrs_tpu.engine.jax_engine import JaxEngine

    monkeypatch.setenv("LMRS_FORCE_KERNELS", "interpret")
    mc = ModelConfig(vocab_size=512, dim=512, n_layers=2, n_heads=4,
                     n_kv_heads=2, hidden_dim=256, max_seq_len=256,
                     dtype="float32")

    def run():
        ec = EngineConfig(backend="jax", scheduler="continuous",
                          max_tokens=8, max_batch_slots=3, seed=0,
                          page_size=32, decode_block=4, retry_delay=0.0,
                          decode_row_group=2)
        eng = JaxEngine(ec, mc)
        reqs = [GenerationRequest(prompt=f"multi row probe {i} " * (1 + 3 * i),
                                  request_id=i, temperature=0.0,
                                  max_new_tokens=8) for i in range(3)]
        out = eng.generate_batch(reqs)
        assert all(r.error is None for r in out)
        return [r.text for r in out]

    monkeypatch.setenv("LMRS_MULTIROW", "0")
    want = run()
    monkeypatch.delenv("LMRS_MULTIROW")
    got = run()
    assert got == want
