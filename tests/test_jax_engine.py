"""JaxEngine tests (tiny model, CPU)."""

import jax
import pytest

from lmrs_tpu.config import EngineConfig, ModelConfig
from lmrs_tpu.engine.api import GenerationRequest, make_engine
from lmrs_tpu.engine.jax_engine import JaxEngine, _bucket


def tiny_model():
    return ModelConfig(vocab_size=512, dim=64, n_layers=2, n_heads=4, n_kv_heads=2,
                       hidden_dim=128, max_seq_len=512, dtype="float32")


@pytest.fixture(scope="module")
def engine():
    ec = EngineConfig(backend="jax", max_tokens=16, max_batch_slots=4, seed=0)
    return JaxEngine(ec, tiny_model())


def test_bucket():
    assert _bucket(1) == 64
    assert _bucket(64) == 64
    assert _bucket(65) == 128
    assert _bucket(300) == 512


def test_generate_fills_results(engine):
    reqs = [GenerationRequest(prompt=f"request number {i}", request_id=i,
                              temperature=0.5, max_new_tokens=16) for i in range(5)]
    out = engine.generate_batch(reqs)
    assert [r.request_id for r in out] == [0, 1, 2, 3, 4]
    for r in out:
        assert r.error is None
        assert r.prompt_tokens > 0
        assert 0 <= r.completion_tokens <= 16
        assert r.finish_reason in ("stop", "length")


def test_greedy_is_deterministic(engine):
    req = GenerationRequest(prompt="determinism check", temperature=0.0,
                            max_new_tokens=12)
    a = engine.generate_batch([req])[0]
    b = engine.generate_batch([req])[0]
    assert a.text == b.text


def test_long_prompt_truncated_not_crashing(engine):
    req = GenerationRequest(prompt="word " * 2000, temperature=0.0, max_new_tokens=8)
    r = engine.generate_batch([req])[0]
    assert r.error is None
    assert r.prompt_tokens <= engine.model_cfg.max_seq_len


def test_empty_request_list(engine):
    assert engine.generate_batch([]) == []


def test_make_engine_resolves_preset(monkeypatch):
    """--model names a preset; the factory must honor it (review finding)."""
    captured = {}

    class FakeJaxEngine:
        def __init__(self, ec, mc, mesh):
            captured["model"] = mc.name

    import lmrs_tpu.engine.api as api_mod
    monkeypatch.setitem(
        __import__("sys").modules, "lmrs_tpu.engine.jax_engine",
        type("M", (), {"JaxEngine": FakeJaxEngine}),
    )
    from lmrs_tpu.config import EngineConfig as EC, ModelConfig as MC
    api_mod.make_engine(EC(backend="jax", model="gemma-2b"), MC(), None)
    assert captured["model"] == "gemma-2b"


def test_engine_restores_checkpoint_sharded(tmp_path):
    """checkpoint_path + mesh: weights restore directly sharded (never
    materializing unsharded) and generation matches the in-memory params."""
    from lmrs_tpu.config import EngineConfig, MeshConfig, ModelConfig
    from lmrs_tpu.engine.api import GenerationRequest
    from lmrs_tpu.engine.jax_engine import JaxEngine
    from lmrs_tpu.models.loader import save_checkpoint
    from lmrs_tpu.models.transformer import init_params

    mc = ModelConfig(vocab_size=512, dim=64, n_layers=2, n_heads=4,
                     n_kv_heads=2, hidden_dim=128, max_seq_len=256,
                     dtype="float32")
    params = init_params(mc, jax.random.PRNGKey(7))
    save_checkpoint(str(tmp_path / "ckpt"), params)

    req = GenerationRequest(prompt="restore probe restore probe",
                            max_new_tokens=8)
    direct = JaxEngine(EngineConfig(backend="jax", seed=0), mc, params=params)
    want = direct.generate_batch([req])[0].text
    direct.shutdown()

    ec = EngineConfig(backend="jax", seed=0,
                      checkpoint_path=str(tmp_path / "ckpt"))
    eng = JaxEngine(ec, mc, mesh_cfg=MeshConfig(dp=1, tp=2))
    wq = eng.params["layers"]["attn"]["wq"]
    assert wq.sharding.shard_shape(wq.shape)[2] == mc.n_heads // 2
    got = eng.generate_batch([req])[0].text
    eng.shutdown()
    assert got == want


def test_tokenizer_vocab_mismatch_refused():
    """An engine tokenizer whose ids exceed the model vocabulary must be
    refused loudly at construction — JAX clamps out-of-range embedding
    gathers silently and an unreachable eos_id never terminates decode
    (round-3 review finding)."""
    from lmrs_tpu.config import EngineConfig, ModelConfig
    from lmrs_tpu.engine.jax_engine import JaxEngine

    class BigVocabTok:
        vocab_size = 128256
        bos_id, eos_id, pad_id = 1, 128001, 0

        def encode(self, text):
            return [5]

        def decode(self, ids):
            return ""

        def count(self, text):
            return 1

    mc = ModelConfig(vocab_size=512, dim=64, n_layers=1, n_heads=4,
                     n_kv_heads=2, hidden_dim=128, max_seq_len=64,
                     dtype="float32")
    with pytest.raises(ValueError, match="does not fit model vocab"):
        JaxEngine(EngineConfig(backend="jax", scheduler="continuous",
                               max_batch_slots=1, seed=0), mc,
                  tokenizer=BigVocabTok())


def test_bf16_tree_gb_tied_embeddings_not_double_counted():
    """Regression (ADVICE r5): ``matmul_params`` always counts the [D, V]
    LM-head matmul, so adding the embedding term double-counted the ONE
    shared [V, D] matrix of tied models — gemma-2b's estimate carried a
    phantom ~1.05 GB toward the 6.0 GB host-init gate.  The estimate must
    track the REAL tree (eval_shape of init_params, no allocation) within
    1% for tied and untied shapes; the residual is the norm scales."""
    import dataclasses

    import numpy as np

    from lmrs_tpu.config import model_preset
    from lmrs_tpu.engine.jax_engine import _bf16_tree_gb, needs_host_quant_init
    from lmrs_tpu.models.transformer import init_params

    def actual_gb(cfg):
        shapes = jax.eval_shape(
            lambda: init_params(cfg, jax.random.PRNGKey(0)))
        n = sum(int(np.prod(l.shape)) for l in jax.tree.leaves(shapes))
        return n * 2 / 1e9

    for name in ("llama3-8b", "gemma-2b"):
        cfg = model_preset(name)
        for tied in (False, True):
            c = dataclasses.replace(cfg, tie_embeddings=tied)
            est, real = _bf16_tree_gb(c), actual_gb(c)
            assert abs(est - real) / real < 0.01, (name, tied, est, real)
        # tied vs untied estimates differ by exactly the [V, D] matrix
    c_t = dataclasses.replace(cfg, tie_embeddings=True)
    c_u = dataclasses.replace(cfg, tie_embeddings=False)
    np.testing.assert_allclose(
        _bf16_tree_gb(c_u) - _bf16_tree_gb(c_t),
        cfg.vocab_size * cfg.dim * 2 / 1e9, rtol=1e-9)

    # the shared gate both engines route through (jax_engine + replicated)
    assert needs_host_quant_init(model_preset("llama3-8b"), "int8")
    assert not needs_host_quant_init(model_preset("llama3-8b"), None)
    assert not needs_host_quant_init(tiny_model(), "int8")
