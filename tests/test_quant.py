"""Int8 weight-only quantization (ops/quant.py)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from lmrs_tpu.config import MeshConfig, ModelConfig
from lmrs_tpu.models.transformer import forward, init_params
from lmrs_tpu.ops.quant import (
    deq,
    is_quantized,
    qeinsum,
    quantize_params,
    quantize_weight,
    quantized_bytes,
)


def _cfg(**kw) -> ModelConfig:
    base = dict(vocab_size=128, dim=64, n_layers=2, n_heads=4, n_kv_heads=2,
                hidden_dim=96, max_seq_len=128, dtype="float32",
                tie_embeddings=False)
    base.update(kw)
    return ModelConfig(name="test-q", **base)


def test_quantize_weight_roundtrip_error():
    w = jax.random.normal(jax.random.PRNGKey(0), (2, 64, 48), jnp.float32) * 0.1
    q = quantize_weight(w, axes=(1,))
    assert q["q"].dtype == jnp.int8
    assert q["s"].shape == (2, 1, 48)  # per-layer, per-out-channel scales
    back = deq(q, jnp.float32)
    # max error is half a quantization step = s/2 per element
    err = np.abs(np.asarray(back) - np.asarray(w))
    bound = np.asarray(q["s"]) * 0.5 + 1e-8
    assert (err <= bound + 1e-7).all()


def test_deq_passthrough():
    w = jnp.ones((4, 4), jnp.bfloat16)
    assert deq(w, jnp.bfloat16) is w


def test_quantize_params_structure_and_size():
    cfg = _cfg()
    params = init_params(cfg, jax.random.PRNGKey(0))
    qparams = quantize_params(params)
    # projections quantized, embeddings/norms untouched
    assert is_quantized(qparams["layers"]["attn"]["wq"])
    assert is_quantized(qparams["layers"]["mlp"]["w_gate"])
    assert is_quantized(qparams["lm_head"]["weight"])
    assert not is_quantized(qparams["embed"])
    assert qparams["embed"]["weight"].dtype == params["embed"]["weight"].dtype
    assert not is_quantized(qparams["layers"]["ln_attn"])
    # big weights at 1/4 the bytes (f32 model) -> sizable total shrink
    assert quantized_bytes(qparams) < 0.6 * quantized_bytes(params)


def test_quantized_forward_close_to_full_precision():
    cfg = _cfg()
    params = init_params(cfg, jax.random.PRNGKey(0))
    qparams = quantize_params(params)
    tokens = jax.random.randint(jax.random.PRNGKey(1), (2, 16), 0, cfg.vocab_size)
    pos = jnp.broadcast_to(jnp.arange(16)[None], (2, 16))
    full, _ = forward(params, cfg, tokens, pos)
    quant, _ = forward(qparams, cfg, tokens, pos)
    # int8 noise is small relative to logit scale; top-1 agreement is the bar
    assert np.isfinite(np.asarray(quant)).all()
    top_full = np.asarray(jnp.argmax(full, -1))
    top_quant = np.asarray(jnp.argmax(quant, -1))
    assert (top_full == top_quant).mean() > 0.9


def test_quantize_params_moe():
    cfg = _cfg(n_experts=4, n_experts_per_token=2)
    params = init_params(cfg, jax.random.PRNGKey(0))
    qparams = quantize_params(params)
    moe = qparams["layers"]["moe"]
    assert is_quantized(moe["w_gate"])
    # per (layer, expert, out-channel) scales: [L, E, 1, F]
    assert moe["w_gate"]["s"].shape == (cfg.n_layers, cfg.n_experts, 1, cfg.hidden_dim)
    assert not is_quantized(moe["router"])  # router stays full precision
    tokens = jnp.zeros((1, 8), jnp.int32)
    pos = jnp.broadcast_to(jnp.arange(8)[None], (1, 8))
    logits, _ = forward(qparams, cfg, tokens, pos)
    assert np.isfinite(np.asarray(logits)).all()


def test_quantized_shard_params_on_mesh():
    from lmrs_tpu.parallel.mesh import build_mesh
    from lmrs_tpu.parallel.sharding import shard_params

    cfg = _cfg()
    params = quantize_params(init_params(cfg, jax.random.PRNGKey(0)))
    mesh = build_mesh(MeshConfig(dp=2, tp=2), jax.devices()[:4])
    sharded = shard_params(params, mesh, cfg.tie_embeddings)
    wq = sharded["layers"]["attn"]["wq"]
    # q sharded like the original weight (heads over tp), scales replicated
    assert wq["q"].sharding.shard_shape(wq["q"].shape)[2] == cfg.n_heads // 2
    assert wq["s"].sharding.is_fully_replicated

    tokens = jnp.zeros((2, 8), jnp.int32)
    pos = jnp.broadcast_to(jnp.arange(8)[None], (2, 8))
    logits, _ = jax.jit(lambda p, t, q: forward(p, cfg, t, q))(sharded, tokens, pos)
    assert np.isfinite(np.asarray(logits)).all()


def test_engine_generates_with_int8():
    from lmrs_tpu.config import EngineConfig
    from lmrs_tpu.engine.api import GenerationRequest, make_engine

    eng_cfg = EngineConfig(backend="jax", model="tiny", quantize="int8",
                           max_batch_slots=2, num_pages=64, page_size=16)
    engine = make_engine(eng_cfg)
    try:
        reqs = [GenerationRequest(prompt="quantized decode test", request_id=0,
                                  max_new_tokens=8)]
        results = engine.generate_batch(reqs)
    finally:
        engine.shutdown()
    assert results[0].error is None
    assert results[0].completion_tokens > 0


def test_engine_rejects_unknown_quantize_mode():
    from lmrs_tpu.config import EngineConfig
    from lmrs_tpu.engine.api import make_engine

    with pytest.raises(ValueError, match="unknown quantize mode"):
        make_engine(EngineConfig(backend="jax", model="tiny", quantize="fp4"))


def test_qeinsum_matches_dequantize_then_einsum():
    """The round-5 scale-folding algebra: for every quantized weight
    family, ``qeinsum(spec, x, leaf)`` must match the r4 formulation
    ``einsum(spec, x, deq(leaf))`` to bf16 rounding (scales are
    per-output-channel, so they commute out of the contraction; the
    qeinsum path has strictly one FEWER rounding step, so agreement is
    bounded by the deq path's own bf16 weight rounding)."""
    rng = np.random.default_rng(3)
    dt = jnp.bfloat16
    cases = [
        # (spec, x shape, w shape, contract axes)  — mirrors _contract_axes
        ("bsd,df->bsf", (2, 3, 16), (16, 24), (0,)),        # dense FFN
        ("bsd,dhk->bshk", (2, 3, 16), (16, 4, 8), (0,)),    # wq/wk/wv
        ("bshk,hkd->bsd", (2, 3, 4, 8), (4, 8, 16), (0, 1)),  # wo
        ("ecd,edf->ecf", (3, 5, 16), (3, 16, 24), (1,)),    # MoE expert FFN
        ("bsd,dv->bsv", (2, 3, 16), (16, 32), (0,)),        # lm_head
    ]
    for spec, xs, ws, axes in cases:
        x = jnp.asarray(rng.standard_normal(xs), dt)
        w = jnp.asarray(rng.standard_normal(ws) * 0.3, jnp.float32)
        leaf = quantize_weight(w, axes)
        want = jnp.einsum(spec, x, deq(leaf, dt)).astype(jnp.float32)
        got = qeinsum(spec, x, leaf, dt).astype(jnp.float32)
        scale = max(float(jnp.max(jnp.abs(want))), 1e-6)
        assert float(jnp.max(jnp.abs(got - want))) / scale < 0.02, spec
