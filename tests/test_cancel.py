"""Request cancellation (VERDICT r3 item 4).

The reference gets cancellation for free from HTTP/asyncio — a dropped
connection kills the task (llm_executor.py:290-296).  A continuous-batching
engine must build it: ``Engine.cancel(request_id)`` aborts at the next block
boundary, the slot's pages free immediately, and the result carries
``finish_reason="cancelled"`` with whatever text was generated.
"""

from __future__ import annotations

import json
import random
import socket
import threading
import time

import pytest

from lmrs_tpu.config import EngineConfig, ModelConfig
from lmrs_tpu.engine.api import GenerationRequest, GenerationResult
from lmrs_tpu.engine.jax_engine import JaxEngine
from lmrs_tpu.engine.mock import MockEngine
from lmrs_tpu.serving.server import EngineHTTPServer


def tiny_model():
    return ModelConfig(vocab_size=512, dim=64, n_layers=2, n_heads=4,
                       n_kv_heads=2, hidden_dim=128, max_seq_len=256,
                       dtype="float32")


def _expect_free(sched) -> int:
    """Free pages once every sequence has closed: the usable pool minus
    pages the prefix cache legitimately retains (each at refcount 1)."""
    cached = sched._prefix_cache.cached_pages if sched._prefix_cache else 0
    return sched.cache.num_pages - 1 - cached


def test_cancel_mid_decode_frees_slot_and_pages():
    """Cancelling a decoding request must end it at the next block boundary
    (completion well under budget), free its KV pages back to the pool, and
    surface finish_reason='cancelled' — the abandoned request must NOT
    decode to max_tokens holding its slot."""
    eng = JaxEngine(EngineConfig(backend="jax", scheduler="continuous",
                                 max_tokens=64, max_batch_slots=2, seed=0,
                                 decode_block=4), tiny_model())
    sched = eng._scheduler
    usable = sched.cache.num_pages - 1
    assert sched.cache.allocator.free_count == usable

    fired = []

    def on_tokens(rid, delta):
        if not fired:
            fired.append(rid)
            eng.cancel(rid)  # from inside the loop: swept next boundary

    req = GenerationRequest(prompt="cancel me please " * 4, request_id=0,
                            temperature=0.8, max_new_tokens=64)
    res = eng.generate_batch([req], on_tokens=on_tokens)[0]
    assert res.finish_reason == "cancelled"
    # swept within ~2 decode blocks of the first delta, far under budget
    assert res.completion_tokens < 64
    assert res.completion_tokens >= 1  # pre-cancel tokens are real output
    assert sched.metrics["cancelled"] == 1
    # the slot's pages went back to the pool when the sweep ran (minus the
    # prompt prefix the cache retains)
    assert sched.cache.allocator.free_count == _expect_free(sched)
    eng.shutdown()


def test_cancel_queued_request_never_prefills():
    """A cancelled request still in the admission queue is dropped without
    prefilling (zero engine work spent on it)."""
    eng = JaxEngine(EngineConfig(backend="jax", scheduler="continuous",
                                 max_tokens=16, max_batch_slots=1, seed=0,
                                 decode_block=4), tiny_model())
    fired = []

    def on_tokens(rid, delta):
        # request 0 holds the ONLY slot; cancel the queued request 1
        if not fired:
            fired.append(rid)
            eng.cancel(1)

    reqs = [GenerationRequest(prompt="first long request " * 3, request_id=0,
                              temperature=0.8, max_new_tokens=16),
            GenerationRequest(prompt="second, never runs", request_id=1,
                              temperature=0.8, max_new_tokens=16)]
    out = eng.generate_batch(reqs, on_tokens=on_tokens)
    assert out[0].finish_reason in ("stop", "length")  # undisturbed
    assert out[1].finish_reason == "cancelled"
    assert out[1].completion_tokens == 0 and out[1].text == ""
    assert eng._scheduler.metrics["cancelled"] == 1
    eng.shutdown()


def test_cancel_unknown_id_is_noop():
    """Stale/unknown ids (client raced a finish) must not disturb the run
    or leak into later runs."""
    eng = JaxEngine(EngineConfig(backend="jax", scheduler="continuous",
                                 max_tokens=8, max_batch_slots=1, seed=0),
                    tiny_model())
    eng.cancel(999)
    res = eng.generate_batch([GenerationRequest(prompt="hello", request_id=0,
                                                temperature=0.0,
                                                max_new_tokens=8)])[0]
    assert res.finish_reason in ("stop", "length")
    assert eng._scheduler.metrics["cancelled"] == 0
    # the stale id was cleared at run end, not left to hit a future rid 999
    assert not eng._scheduler._cancelled
    eng.shutdown()


class SlowStreamEngine:
    """Engine that streams many deltas slowly and honors cancel() — stands
    in for the continuous scheduler in the server-level disconnect test
    (deterministic timing, no XLA compiles)."""

    def __init__(self, n_deltas: int = 60, delay_s: float = 0.05):
        self.n_deltas = n_deltas
        self.delay_s = delay_s
        self.cancelled: set[int] = set()
        self.cancel_calls: list[int] = []
        self.deltas_emitted = 0

    def generate_batch(self, requests, on_result=None, on_tokens=None):
        results = []
        for req in requests:
            text = ""
            reason = "stop"
            for i in range(self.n_deltas):
                if req.request_id in self.cancelled:
                    reason = "cancelled"
                    break
                time.sleep(self.delay_s)
                piece = f"tok{i} "
                text += piece
                self.deltas_emitted += 1
                if on_tokens is not None:
                    on_tokens(req.request_id, piece)
            results.append(GenerationResult(request_id=req.request_id,
                                            text=text, finish_reason=reason,
                                            completion_tokens=len(text.split())))
        return results

    def cancel(self, request_id: int) -> None:
        self.cancel_calls.append(request_id)
        self.cancelled.add(request_id)

    def shutdown(self):
        pass

    def engine_metrics(self):
        return {}


def _post_raw(host, port, body_dict) -> socket.socket:
    """POST a chat-completions body over a raw socket and return the live
    socket (abandoned-client pattern, part 1)."""
    body = json.dumps(body_dict).encode()
    s = socket.create_connection((host, port), timeout=30)
    s.sendall(b"POST /v1/chat/completions HTTP/1.1\r\n"
              b"Host: x\r\nContent-Type: application/json\r\n"
              + f"Content-Length: {len(body)}\r\n\r\n".encode() + body)
    return s


def _rst_close(s: socket.socket) -> None:
    """Vanish with an RST (SO_LINGER 0) so the server's next write on the
    socket fails fast instead of filling the socket buffer (abandoned-
    client pattern, part 2)."""
    import struct

    s.setsockopt(socket.SOL_SOCKET, socket.SO_LINGER, struct.pack("ii", 1, 0))
    s.close()


def _stream_then_rst(host, port, body_dict, until):
    """POST a streaming request, recv until ``until(got)`` says generation
    is provably in flight, then RST — shared by the SSE disconnect tests."""
    s = _post_raw(host, port, body_dict)
    got = b""
    while not until(got):
        chunk = s.recv(1024)
        if not chunk:
            break
        got += chunk
    _rst_close(s)
    return got


def test_server_disconnect_cancels_generation():
    """A streaming client that closes its socket mid-stream must propagate
    a cancel into the running engine call (server write fails -> batcher
    cancel -> engine.cancel), ending generation early — the slot must not
    run to max_tokens for a client that is gone."""
    engine = SlowStreamEngine(n_deltas=60, delay_s=0.05)  # 3s if uncancelled
    srv = EngineHTTPServer(engine, port=0, batch_window_s=0.01)
    srv.start_background()
    try:
        # wait for the FIRST content delta — the engine wave is then
        # provably in flight (closing earlier exercises the easier
        # pre-dispatch drop, test_batcher_drops_cancelled_before_dispatch)
        _stream_then_rst(srv.host, srv.port,
                         {"messages": [{"role": "user", "content": "hi"}],
                          "stream": True},
                         until=lambda got: b"tok0" in got)
        deadline = time.time() + 10
        while time.time() < deadline and not engine.cancel_calls:
            time.sleep(0.05)
        assert engine.cancel_calls, "disconnect never reached engine.cancel"
        # generation actually stopped early (not just recorded)
        settled = engine.deltas_emitted
        time.sleep(0.4)
        assert engine.deltas_emitted in (settled, settled + 1)
        assert engine.deltas_emitted < engine.n_deltas
    finally:
        srv.shutdown()


def test_batcher_drops_cancelled_before_dispatch():
    """A job cancelled while queued (client gone before its wave started)
    must be finished without engine work."""
    from lmrs_tpu.serving.server import _Batcher

    class BlockingEngine(MockEngine):
        """First wave blocks until released — pins later jobs in the queue."""

        def __init__(self):
            super().__init__()
            self.release = threading.Event()
            self.first = True

        def generate_batch(self, requests, on_result=None, on_tokens=None):
            if self.first:
                self.first = False
                self.release.wait(timeout=10)
            return super().generate_batch(requests, on_result=on_result,
                                          on_tokens=on_tokens)

    eng = BlockingEngine()
    b = _Batcher(eng, window_s=0.01)
    try:
        first = threading.Thread(
            target=b.submit, args=(GenerationRequest(prompt="wave one"),),
            daemon=True)
        first.start()
        time.sleep(0.2)  # wave 1 is now inside the blocked engine call
        job = b.submit_stream(GenerationRequest(prompt="queued victim"))
        b.cancel(job)  # client disconnects while the job waits its turn
        eng.release.set()
        assert job.deltas.get(timeout=10) is None  # stream ends immediately
        assert job.result.finish_reason == "cancelled"
        assert job.result.text == ""  # no engine work spent
    finally:
        eng.release.set()
        b.shutdown()


@pytest.mark.parametrize("seed", [7, 19, 43])
def test_fuzzed_cancellation_keeps_pool_consistent(seed):
    """Random cancels fired from the streaming callback at random points,
    across random scheduler shapes: every request resolves (cancelled or
    finished, never errored), no KV page leaks, and freed-row invariants
    hold well enough for the run to complete — the fuzz analog of
    tests/test_fuzz_scheduler.py for the abort path."""
    rng = random.Random(seed)
    eng = JaxEngine(
        EngineConfig(backend="jax", scheduler="continuous",
                     max_tokens=24, seed=0,
                     max_batch_slots=rng.choice((1, 2, 3)),
                     page_size=rng.choice((16, 32)),
                     num_pages=rng.choice((1, 40)),
                     decode_block=rng.choice((2, 4))),
        tiny_model())
    sched = eng._scheduler
    n = rng.randint(3, 7)
    reqs = [GenerationRequest(prompt=f"fuzz cancel {i} " * rng.randint(1, 6),
                              request_id=i, temperature=0.8,
                              max_new_tokens=rng.randint(4, 24))
            for i in range(n)]
    to_cancel = {i for i in range(n) if rng.random() < 0.5}
    calls = [0]

    def on_tokens(rid, delta):
        calls[0] += 1
        # cancel a random victim (possibly the streaming request itself,
        # possibly one still queued) on a random subset of callbacks
        if to_cancel and calls[0] % 3 == 0:
            eng.cancel(to_cancel.pop())

    out = eng.generate_batch(reqs, on_tokens=on_tokens)
    assert [r.request_id for r in out] == list(range(n))
    by_id = {r.request_id: r for r in reqs}
    for r in out:
        assert r.error is None
        assert r.finish_reason in ("stop", "length", "cancelled")
        # per-request budget, not the global cap (matches the sibling
        # fuzz contract): the sweep's _trimmed_output must keep capping
        assert r.completion_tokens <= by_id[r.request_id].max_new_tokens
    # the abort path actually ran (verified: every seed lands >= 1 cancel
    # — without this the test could silently stop testing cancellation)
    assert sched.metrics["cancelled"] >= 1
    # every page went back to the pool, cancelled or not (the prefix cache
    # keeps donated prompt prefixes at refcount 1)
    assert sched.cache.allocator.free_count == _expect_free(sched)
    eng.shutdown()


def test_server_disconnect_cancels_real_scheduler():
    """The gold path: a REAL socket disconnect, through the live HTTP
    server, into the REAL continuous-batching scheduler — cancel crosses
    threads (HTTP handler -> batcher -> engine while the dispatcher thread
    is inside run()), the slot's pages free, and the engine finishes the
    request as cancelled well under budget."""
    eng = JaxEngine(EngineConfig(backend="jax", scheduler="continuous",
                                 max_tokens=192, max_batch_slots=2, seed=0,
                                 decode_block=2), tiny_model())
    sched = eng._scheduler
    srv = EngineHTTPServer(eng, port=0, batch_window_s=0.01)
    srv.start_background()
    try:
        # the role chunk is frame 1 and also contains '"content": ""' —
        # a real content DELTA is only proven by a SECOND data: frame
        _stream_then_rst(srv.host, srv.port,
                         {"messages": [{"role": "user",
                                        "content": "stream then vanish"}],
                          "max_tokens": 192, "temperature": 0.8,
                          "stream": True},
                         until=lambda got: got.count(b"data:") >= 2)
        deadline = time.time() + 60
        while time.time() < deadline and sched.metrics["cancelled"] == 0:
            time.sleep(0.1)
        assert sched.metrics["cancelled"] == 1, "cancel never reached scheduler"
        # the run loop ends (no other work) and the pages are back
        deadline = time.time() + 60
        while (time.time() < deadline
               and sched.cache.allocator.free_count != _expect_free(sched)):
            time.sleep(0.1)
        assert sched.cache.allocator.free_count == _expect_free(sched)
    finally:
        srv.shutdown()
        eng.shutdown()

def test_nonstream_disconnect_cancels_generation():
    """ADVICE r4: a NON-streaming client that disconnects mid-generation
    must also be detected (MSG_PEEK poll inside _Batcher.submit) and
    cancelled — previously only SSE paths noticed (OSError on a stream
    write), so a dropped non-stream request decoded to max_tokens holding
    its slot and pages."""
    engine = SlowStreamEngine(n_deltas=60, delay_s=0.05)  # 3s if uncancelled
    srv = EngineHTTPServer(engine, port=0, batch_window_s=0.01)
    srv.start_background()
    try:
        s = _post_raw(srv.host, srv.port,
                      {"messages": [{"role": "user", "content": "hi"}]})
        # no bytes ever reach a non-streaming client before completion —
        # wait until the engine is provably generating, then vanish
        deadline = time.time() + 10
        while time.time() < deadline and engine.deltas_emitted == 0:
            time.sleep(0.02)
        assert engine.deltas_emitted > 0, "wave never started"
        _rst_close(s)
        deadline = time.time() + 10
        while time.time() < deadline and not engine.cancel_calls:
            time.sleep(0.05)
        assert engine.cancel_calls, \
            "non-stream disconnect never reached engine.cancel"
        settled = engine.deltas_emitted
        time.sleep(0.4)
        assert engine.deltas_emitted in (settled, settled + 1)
        assert engine.deltas_emitted < engine.n_deltas
    finally:
        srv.shutdown()
