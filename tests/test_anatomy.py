"""Step-anatomy profiler + bucket economics (ISSUE 18, obs/anatomy.py).

Four layers of coverage:

* unit — ``StepAnatomy`` with an injected clock: pause semantics,
  conservation identity, abort/discard accounting, bucket arithmetic
  against hand-computed span lists, stale-RTT report gating, merge rules;
* engine — real CPU JaxEngines through the live scheduler loop: plain /
  mixed / spec / fault-armed chaos arms all end with
  ``scheduler.audit()`` clean (the conservation identity holds through
  dispatch faults by construction, not luck);
* parity — the ``LMRS_ANATOMY=0`` kill switch is byte-identical (greedy
  output, metrics_report keys) and the mock's deterministic anatomy
  matches the scheduler's report schema exactly;
* wire — ``GET /v1/anatomy`` serves the document, 501s when the switch
  is off or the backend has no hook, and the router's fleet merge rides
  the same endpoint.
"""

from __future__ import annotations

import json
import urllib.error
import urllib.request

import pytest

from lmrs_tpu.config import EngineConfig, ModelConfig
from lmrs_tpu.engine.api import GenerationRequest
from lmrs_tpu.engine.jax_engine import JaxEngine
from lmrs_tpu.engine.mock import MockEngine
from lmrs_tpu.obs.anatomy import (CLASSES, SEGMENTS, StepAnatomy,
                                  merge_anatomy)
from lmrs_tpu.obs.metrics import MetricsRegistry


def tiny_model() -> ModelConfig:
    return ModelConfig(vocab_size=512, dim=64, n_layers=2, n_heads=4,
                       n_kv_heads=2, hidden_dim=128, max_seq_len=256,
                       dtype="float32")


def _cfg(**kw) -> EngineConfig:
    base = dict(backend="jax", scheduler="continuous", max_tokens=32,
                max_batch_slots=2, seed=0, decode_block=4, page_size=16,
                num_pages=24, retry_delay=0.0)
    base.update(kw)
    return EngineConfig(**base)


def _reqs(n: int = 3, start: int = 0, budget: int = 8):
    return [GenerationRequest(prompt=f"anatomy probe {start + i} alpha "
                                     "bravo charlie",
                              request_id=start + i, temperature=0.0,
                              max_new_tokens=budget) for i in range(n)]


# ------------------------------------------------------------------ unit


class FakeClock:
    def __init__(self):
        self.t = 1000.0

    def __call__(self) -> float:
        return self.t

    def tick(self, dt: float) -> float:
        self.t += dt
        return self.t


@pytest.fixture
def an():
    clock = FakeClock()
    a = StepAnatomy(MetricsRegistry(), clock=clock)
    a.clock = clock  # test-side handle
    return a


def test_seg_pause_semantics_and_conservation(an):
    """Entering an inner segment pauses the outer one: elapsed time lands
    in exactly one segment, the explicit residual covers the rest, and
    wall == segments + residual EXACTLY on the fake clock."""
    c = an.clock
    an.iter_begin()
    c.tick(0.010)                      # residual (outside any segment)
    with an.seg("plan"):
        c.tick(0.020)                  # plan
        with an.seg("dispatch"):
            c.tick(0.030)              # dispatch — plan is paused
        c.tick(0.005)                  # plan resumes
    an.iter_end("plain")
    assert an.audit() == []
    rep = an.report()
    assert rep["iterations"] == 1
    assert rep["segments_ms"]["plan"] == pytest.approx(25.0)
    assert rep["segments_ms"]["dispatch"] == pytest.approx(30.0)
    assert rep["residual_ms"] == pytest.approx(10.0)
    assert rep["wall_ms"] == pytest.approx(65.0)
    # host overhead excludes dispatch+fetch: 65 - 30 = 35 ms = 35000 µs
    assert rep["host_overhead_us_step"] == pytest.approx(35000.0)
    p50 = rep["classes"]["plain"]["p50_us"]
    assert p50["plan"] == pytest.approx(25000.0)
    assert p50["wall"] == pytest.approx(65000.0)


def test_abort_discards_and_discard_counts_nothing(an):
    c = an.clock
    an.iter_begin()
    with an.seg("dispatch"):
        c.tick(0.5)
    an.iter_abort()                    # fault unwind: contributes nothing
    an.iter_begin()
    c.tick(0.1)
    an.iter_discard()                  # run-exit pass: not even "aborted"
    rep = an.report()
    assert rep["iterations"] == 0
    assert rep["aborted_iterations"] == 1
    assert rep["wall_ms"] == 0.0
    assert rep["segments_ms"]["dispatch"] == 0.0
    assert an.audit() == []


def test_unknown_segment_rejected(an):
    with pytest.raises(ValueError):
        an.seg("warp")


def test_audit_detects_broken_conservation(an):
    """The auditor must be PROVEN able to fail (same discipline as the
    page auditor's negative cases): corrupting a segment total breaks the
    wall == segments + residual identity."""
    c = an.clock
    an.iter_begin()
    with an.seg("fetch"):
        c.tick(0.010)
    an.iter_end("plain")
    assert an.audit() == []
    an._segs["fetch"] += 1.0
    assert any("conservation" in v for v in an.audit())
    an._segs["fetch"] -= 1.0
    assert an.audit() == []


def test_bucket_economics_hand_computed(an):
    """Bucket counters vs a hand-computed span list: three dispatches on
    bucket (32, 4) carrying 20/32/7 real tokens -> 59 real, 37 padded,
    real + padded == dispatches * 32, pad_waste 37/96."""
    for real in (20, 32, 7):
        an.note_bucket(32, 4, real)
    an.note_compile(32, 4, 0.25)
    an.note_bucket(64, 8, 50)
    assert an.audit() == []
    rep = an.report()
    b = rep["buckets"]["32x4"]
    assert b["dispatches"] == 3
    assert b["real_tokens"] == 59
    assert b["padded_tokens"] == 37
    assert b["pad_waste"] == pytest.approx(37 / 96, abs=1e-4)
    assert b["compile_ms"] == pytest.approx(250.0)
    assert rep["buckets"]["64x8"]["padded_tokens"] == 14
    # overall ratio spans both buckets: (37+14) / (96+64)
    assert rep["rpa_pad_waste_ratio"] == pytest.approx(51 / 160, abs=1e-4)
    # negative case: a corrupted count is a conservation violation
    an._buckets[(32, 4)]["real"] += 1
    assert any("bucket 32x4" in v for v in an.audit())
    an._buckets[(32, 4)]["real"] -= 1
    assert an.audit() == []


def test_report_stale_rtt_guard(an):
    """Satellite 3: a fresh RTT sample yields the device-wait split; a
    STALE one (older than 2x the resample cadence) is flagged and the
    split is withheld rather than skewed."""
    c = an.clock
    an.iter_begin()
    with an.seg("fetch"):
        c.tick(0.010)
    an.iter_end("plain")
    fresh = an.report(rtt=(0.002, 1.0))
    assert fresh["rtt_ms"] == pytest.approx(2.0)
    assert fresh["rtt_stale"] is False
    # fetch 10 ms minus one 2 ms RTT -> 8 ms of true device wait
    assert fresh["device_wait_us_step"] == pytest.approx(8000.0)
    stale = an.report(rtt=(0.002, 100000.0))
    assert stale["rtt_stale"] is True
    assert "device_wait_us_step" not in stale
    none = an.report(rtt=(None, None))
    assert "rtt_ms" not in none and "device_wait_us_step" not in none


def test_ensure_rtt_resamples_on_slow_cadence(monkeypatch):
    """Satellite 3 regression (injected clock): within the cadence the
    cached sample is returned untouched; past it the probe re-runs and
    refreshes the timestamp, so a long-lived process tracks link drift."""
    from lmrs_tpu.obs.perf import DispatchAttribution

    da = DispatchAttribution(tiny_model(), EngineConfig(backend="jax"),
                             MetricsRegistry())
    clock = FakeClock()
    da._clock = clock
    monkeypatch.setenv("LMRS_RTT_RESAMPLE_S", "100")
    da._rtt, da._rtt_t = 0.5, clock.t  # implausible cached sample
    clock.tick(99.0)
    assert da.ensure_rtt() == 0.5      # inside the cadence: no probe
    assert da._rtt_t == pytest.approx(1000.0)
    clock.tick(2.0)                    # past the cadence: re-probe
    rtt = da.ensure_rtt()
    assert rtt != 0.5                  # a real CPU probe is far below 0.5 s
    assert da._rtt_t == pytest.approx(clock.t)
    sample, age = da.rtt_sample()
    assert sample == rtt and age == 0.0


def test_merge_anatomy_sums_and_disabled_shape():
    a = {"object": "anatomy", "enabled": True, "iterations": 4,
         "aborted_iterations": 1, "wall_ms": 10.0, "residual_ms": 1.0,
         "segments_ms": {s: 1.0 for s in SEGMENTS},
         "host_overhead_us_step": 100.0,
         "classes": {"plain": {"iterations": 4,
                               "p50_us": {"wall": 100.0},
                               "p95_us": {"wall": 200.0}}},
         "buckets": {"32x4": {"dispatches": 2, "real_tokens": 40,
                              "padded_tokens": 24, "pad_waste": 0.375,
                              "compile_ms": 5.0}},
         "rpa_pad_waste_ratio": 0.375}
    b = dict(a, iterations=12, host_overhead_us_step=200.0,
             classes={"plain": {"iterations": 12,
                                "p50_us": {"wall": 300.0},
                                "p95_us": {"wall": 400.0}}})
    merged = merge_anatomy([a, b, {"object": "anatomy", "enabled": False}])
    assert merged["enabled"] is True
    assert merged["iterations"] == 16
    assert merged["aborted_iterations"] == 2
    assert merged["wall_ms"] == pytest.approx(20.0)
    assert merged["segments_ms"]["dispatch"] == pytest.approx(2.0)
    # iteration-weighted means: (100*4 + 200*12) / 16 = 175
    assert merged["host_overhead_us_step"] == pytest.approx(175.0)
    assert merged["classes"]["plain"]["p50_us"]["wall"] == pytest.approx(
        (100.0 * 4 + 300.0 * 12) / 16)
    mb = merged["buckets"]["32x4"]
    assert mb["dispatches"] == 4 and mb["padded_tokens"] == 48
    assert mb["pad_waste"] == pytest.approx(0.375)
    assert merge_anatomy([]) == {"object": "anatomy", "enabled": False}
    assert merge_anatomy([{"enabled": False}])["enabled"] is False


# ------------------------------------------------------ engine (CPU jax)


@pytest.fixture(scope="module")
def mixed_engine():
    eng = JaxEngine(_cfg(mixed_batch=True), tiny_model())
    yield eng
    eng.shutdown()


def test_jax_plain_and_mixed_arms_conserve(mixed_engine):
    """Real scheduler-loop traffic: the conservation identity holds, the
    report carries per-class percentiles, and every ragged-span bucket's
    token counts reconcile against its dispatch count."""
    sched = mixed_engine._scheduler
    an0 = sched.anatomy_snapshot()
    out = mixed_engine.generate_batch(_reqs(3))
    assert all(r.error is None for r in out)
    assert sched.audit() == []
    rep = sched.anatomy_report(an0)
    assert rep["enabled"] and rep["iterations"] > 0
    assert rep["wall_ms"] > 0.0
    assert set(rep["segments_ms"]) == set(SEGMENTS)
    assert set(rep["classes"]) <= set(CLASSES)
    assert rep["host_overhead_us_step"] > 0.0
    for cls_rep in rep["classes"].values():
        assert cls_rep["p95_us"]["wall"] >= cls_rep["p50_us"]["wall"]
    for key, b in rep["buckets"].items():
        tpb = int(key.split("x")[0])
        assert (b["real_tokens"] + b["padded_tokens"]
                == b["dispatches"] * tpb), key
        assert 0.0 <= b["pad_waste"] < 1.0
    # the anatomy block rides metrics_report under the same key
    assert sched.metrics_report()["anatomy"]["enabled"] is True


def test_jax_spec_arm_reports_nonzero_draft():
    """The spec-verify arm: draft plumbing (seed_history, reseeds) is a
    named segment and must be nonzero — the 3x spec-step mystery's
    attribution target (acceptance criterion)."""
    eng = JaxEngine(_cfg(speculate_k=4), tiny_model())
    try:
        sched = eng._scheduler
        out = eng.generate_batch(_reqs(2, budget=8))
        assert all(r.error is None for r in out)
        assert sched.audit() == []
        rep = sched.anatomy_report()
        assert "spec" in rep["classes"]
        assert rep["segments_ms"]["draft"] > 0.0
    finally:
        eng.shutdown()


def test_jax_fault_armed_chaos_arm_conserves(mixed_engine):
    """A dispatch fault kills an iteration mid-segment: the open record is
    DISCARDED (iter_abort), so wall == segments + residual still
    reconciles in scheduler.audit() and the abort shows up as an aborted
    iteration, never as skew."""
    from lmrs_tpu.engine.executor import MapExecutor
    from lmrs_tpu.testing import faults
    from lmrs_tpu.testing.faults import FaultPlan

    sched = mixed_engine._scheduler
    an0 = sched.anatomy_snapshot()
    ex = MapExecutor(mixed_engine, EngineConfig(retry_attempts=3,
                                                retry_delay=0.01))
    with faults.injected(FaultPlan(seed=13, faults=[
            {"site": "scheduler.step", "at": [3], "max_fires": 1}])):
        out = ex.run_requests(_reqs(3, start=50))
    assert all(r.finish_reason is not None for r in out)
    assert sched.audit() == []
    rep = sched.anatomy_report(an0)
    assert rep["aborted_iterations"] >= 1
    assert rep["iterations"] > 0


def test_slow_step_postmortem_schema(mixed_engine, monkeypatch, tmp_path):
    """LMRS_ANATOMY_SLOW_MS armed at a hair-trigger threshold: every
    iteration files a schema-valid slow_step postmortem whose extra block
    carries the full segment split of the offending step."""
    from lmrs_tpu.obs import validate_postmortem_file

    monkeypatch.setenv("LMRS_POSTMORTEM_DIR", str(tmp_path))
    monkeypatch.setenv("LMRS_POSTMORTEM_MIN_S", "0")
    monkeypatch.setenv("LMRS_ANATOMY_SLOW_MS", "0.0001")
    mixed_engine.generate_batch(_reqs(1, start=70))
    dumps = sorted(tmp_path.glob("postmortem-slow_step-*.json"))
    assert dumps, "hair-trigger threshold produced no slow_step postmortem"
    doc = validate_postmortem_file(dumps[0])
    assert doc["reason"] == "slow_step"
    an = doc["extra"]["anatomy"]
    assert an["class"] in CLASSES
    assert an["wall_ms"] > an["threshold_ms"] == 0.0001
    assert set(an["segments_ms"]) == set(SEGMENTS)
    assert "residual_ms" in an
    # wall reconciles against the dumped split too (rounded to µs)
    assert an["wall_ms"] == pytest.approx(
        sum(an["segments_ms"].values()) + an["residual_ms"], abs=0.05)


def test_slow_step_disabled_by_default(mixed_engine, monkeypatch,
                                       tmp_path):
    monkeypatch.setenv("LMRS_POSTMORTEM_DIR", str(tmp_path))
    monkeypatch.delenv("LMRS_ANATOMY_SLOW_MS", raising=False)
    mixed_engine.generate_batch(_reqs(1, start=80))
    assert not list(tmp_path.glob("postmortem-slow_step-*.json"))


def test_scheduler_report_flags_stale_rtt(mixed_engine):
    """The scheduler's report wires the perf RTT sample through the stale
    guard: an aged sample is flagged, never subtracted."""
    sched = mixed_engine._scheduler
    clock = FakeClock()
    perf = sched._perf
    old = (perf._rtt, perf._rtt_t, perf._clock)
    try:
        perf._clock = clock
        perf._rtt, perf._rtt_t = 0.001, clock.t
        rep = sched.anatomy_report()
        assert rep["rtt_stale"] is False
        clock.tick(10_000.0)           # far past 2x the 300 s cadence
        rep = sched.anatomy_report()
        assert rep["rtt_stale"] is True
        assert "device_wait_us_step" not in rep
    finally:
        perf._rtt, perf._rtt_t, perf._clock = old


# -------------------------------------------------- kill-switch parity


def test_kill_switch_byte_parity(monkeypatch):
    """LMRS_ANATOMY=0 must be byte-identical: same greedy text, and
    metrics_report's key set is EXACTLY the on-report's minus "anatomy"
    (the pre-anatomy shape restored, nothing else disturbed)."""
    def run(off: bool):
        if off:
            monkeypatch.setenv("LMRS_ANATOMY", "0")
        else:
            monkeypatch.delenv("LMRS_ANATOMY", raising=False)
        eng = JaxEngine(_cfg(mixed_batch=True), tiny_model())
        try:
            out = eng.generate_batch(_reqs(2))
            rep = eng._scheduler.metrics_report()
            assert eng._scheduler.audit() == []
            return [(r.text, r.finish_reason) for r in out], rep
        finally:
            eng.shutdown()

    on_out, on_rep = run(off=False)
    off_out, off_rep = run(off=True)
    assert off_out == on_out
    assert "anatomy" not in off_rep
    assert set(off_rep) == set(on_rep) - {"anatomy"}


def test_mock_kill_switch_parity(monkeypatch):
    """The mock reads the switch live: identical results either way, no
    anatomy key in engine_metrics when off."""
    def run():
        eng = MockEngine(seed=0, mixed_batch=True)
        out = eng.generate_batch(_reqs(4, budget=12))
        return ([(r.text, r.completion_tokens, r.finish_reason)
                 for r in out], eng.engine_metrics())

    monkeypatch.delenv("LMRS_ANATOMY", raising=False)
    on_out, on_metrics = run()
    assert on_metrics["anatomy"]["enabled"] is True
    monkeypatch.setenv("LMRS_ANATOMY", "0")
    off_out, off_metrics = run()
    assert off_out == on_out
    assert "anatomy" not in off_metrics
    assert set(off_metrics) == set(on_metrics) - {"anatomy"}


# ----------------------------------------------------------- mock parity


def test_mock_anatomy_is_deterministic_and_schema_matched():
    """Two mock runs over identical traffic produce byte-identical
    anatomy documents (token-count-derived, never wall clocks), with the
    scheduler report's exact top-level schema and residual 0."""
    def doc():
        eng = MockEngine(seed=0, mixed_batch=True)
        eng.generate_batch(_reqs(4, budget=12))
        return eng.anatomy_report()

    a, b = doc(), doc()
    assert a == b
    assert a["residual_ms"] == 0.0
    assert a["iterations"] > 0
    # schema parity with the scheduler's report (the rtt keys are
    # optional extras the scheduler adds when a sample exists)
    want = {"object", "enabled", "iterations", "aborted_iterations",
            "wall_ms", "residual_ms", "segments_ms",
            "host_overhead_us_step", "classes", "buckets",
            "rpa_pad_waste_ratio"}
    assert set(a) == want
    # residual-0 construction: wall is exactly the segment sum
    assert a["wall_ms"] == pytest.approx(sum(a["segments_ms"].values()),
                                         abs=1e-6)
    for cls_rep in a["classes"].values():
        assert set(cls_rep) == {"iterations", "p50_us", "p95_us"}


def test_mock_bucket_math_hand_computed():
    """The emulated bucket note against hand arithmetic: 20 real tokens
    in a 32-token bucket -> 1 page -> window 4; padded 12; first sight
    charges the deterministic emulated compile (32 tokens * 1 µs)."""
    eng = MockEngine(seed=0, mixed_batch=True)
    eng._note_rpa_bucket(32, 20)
    eng._note_rpa_bucket(32, 30)
    rep = eng.anatomy_report()
    b = rep["buckets"]["32x4"]
    assert b["dispatches"] == 2
    assert b["real_tokens"] == 50
    assert b["padded_tokens"] == 14
    assert b["real_tokens"] + b["padded_tokens"] == 2 * 32
    assert b["pad_waste"] == pytest.approx(14 / 64, abs=1e-4)
    # first sight charged 32 µs of emulated compile exactly once (the
    # report's ms column rounds that to 0.0 at its 0.1 ms precision)
    assert eng._an_buckets[(32, 4)]["compile_s"] == pytest.approx(32e-6)
    assert b["compile_ms"] == 0.0
    assert rep["rpa_pad_waste_ratio"] == pytest.approx(14 / 64, abs=1e-4)


# ------------------------------------------------------------------ wire


def _get_json(host: str, port: int, path: str):
    with urllib.request.urlopen(f"http://{host}:{port}{path}",
                                timeout=10) as resp:
        return resp.status, json.loads(resp.read())


def test_v1_anatomy_endpoint_and_501s(monkeypatch):
    from lmrs_tpu.serving.server import EngineHTTPServer

    eng = MockEngine(seed=0, mixed_batch=True)
    srv = EngineHTTPServer(eng, port=0, batch_window_s=0.01)
    srv.start_background()
    try:
        eng.generate_batch(_reqs(2, budget=8))
        status, doc = _get_json(srv.host, srv.port, "/v1/anatomy")
        assert status == 200
        assert doc["object"] == "anatomy" and doc["enabled"] is True
        assert doc["iterations"] > 0
        # switch off live: the endpoint refuses rather than serving an
        # empty shell (explicit 501, typed error)
        monkeypatch.setenv("LMRS_ANATOMY", "0")
        with pytest.raises(urllib.error.HTTPError) as ei:
            urllib.request.urlopen(
                f"http://{srv.host}:{srv.port}/v1/anatomy", timeout=10)
        assert ei.value.code == 501
        err = json.loads(ei.value.read())
        assert err["error"]["type"] == "anatomy_error"
    finally:
        srv.shutdown()


def test_v1_anatomy_501_without_hook():
    from lmrs_tpu.serving.server import EngineHTTPServer

    class Bare:
        def generate_batch(self, requests, on_tokens=None):
            return []

        def shutdown(self):
            pass

    srv = EngineHTTPServer(Bare(), port=0, batch_window_s=0.01)
    srv.start_background()
    try:
        with pytest.raises(urllib.error.HTTPError) as ei:
            urllib.request.urlopen(
                f"http://{srv.host}:{srv.port}/v1/anatomy", timeout=10)
        assert ei.value.code == 501
    finally:
        srv.shutdown()


def test_router_fleet_anatomy_merge():
    """The router pulls every backend's /v1/anatomy page and serves the
    merged view with per-host raw documents alongside."""
    from lmrs_tpu.serving.router import RouterEngine
    from lmrs_tpu.serving.server import EngineHTTPServer

    eng = MockEngine(seed=0, mixed_batch=True)
    srv = EngineHTTPServer(eng, port=0, batch_window_s=0.01)
    srv.start_background()
    router = RouterEngine([f"127.0.0.1:{srv.port}"])
    try:
        router.generate_batch(_reqs(2, budget=8))
        doc = router.anatomy_report()
        assert doc["enabled"] is True and doc["fleet"] is True
        assert doc["iterations"] > 0
        assert len(doc["per_host"]) == 1
        assert doc["per_host"][0]["host"] == f"127.0.0.1:{srv.port}"
        assert doc["unreachable"] == []
        # the merged totals equal the single host's (one-backend fleet)
        assert doc["iterations"] == doc["per_host"][0]["iterations"]
    finally:
        router.shutdown()
        srv.shutdown()
