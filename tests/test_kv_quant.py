"""Int8 KV-cache quantization (EngineConfig.kv_quantize, ops/quant.py KV
section): per-(slot, kv head, channel) scales fixed at prefill, int8 page
pools, dequant at every pool reader.  No reference counterpart — the
reference has no KV cache at all (the model is behind OpenAI's API,
/root/reference/llm_executor.py:250-326)."""

import jax.numpy as jnp
import numpy as np
import pytest

from lmrs_tpu.config import EngineConfig, ModelConfig
from lmrs_tpu.engine.api import GenerationRequest
from lmrs_tpu.engine.jax_engine import JaxEngine
from lmrs_tpu.ops.quant import kv_dequant, kv_quant, kv_scale_from


def tiny_model():
    # page_size 32 gate: int8 VMEM tiles are (32, 128)
    return ModelConfig(vocab_size=512, dim=64, n_layers=2, n_heads=4,
                       n_kv_heads=2, hidden_dim=128, max_seq_len=256,
                       dtype="float32")


def make_engine(kv: str | None, **kw):
    kw.setdefault("page_size", 32)
    ec = EngineConfig(backend="jax", scheduler="continuous", max_tokens=24,
                      max_batch_slots=2, seed=0, kv_quantize=kv,
                      retry_delay=0.0, **kw)
    return JaxEngine(ec, tiny_model())


# ------------------------------------------------------------- unit level


def test_kv_roundtrip_error_bound():
    """Symmetric per-channel int8: |x - deq(quant(x))| <= scale/2, with the
    scale computed only from VALID rows."""
    rng = np.random.default_rng(0)
    kv = jnp.asarray(rng.standard_normal((3, 16, 2, 8)) * 4.0, jnp.float32)
    valid = jnp.asarray(np.arange(16)[None, :] < np.array([16, 7, 1])[:, None])
    s = kv_scale_from(kv, valid)
    assert s.shape == (3, 2, 8)
    back = kv_dequant(kv_quant(kv, s), s, jnp.float32)
    err = jnp.abs(back - kv) * valid[:, :, None, None]
    assert float(jnp.max(err - s[:, None] / 2)) <= 1e-6


def test_kv_scale_ignores_masked_rows():
    """A huge outlier in a masked (padding) position must not inflate the
    scale."""
    kv = jnp.zeros((1, 4, 1, 4), jnp.float32).at[0, 3].set(1e6)
    kv = kv.at[0, 0].set(2.0)
    valid = jnp.asarray([[True, True, True, False]])
    s = kv_scale_from(kv, valid)
    assert float(jnp.max(s)) <= 2.0 / 127.0 + 1e-6


# ----------------------------------------------------------- engine level


@pytest.fixture(scope="module")
def engines():
    return make_engine(None), make_engine("int8")


def test_int8_pools_and_scales_materialize(engines):
    bf, q = engines
    assert bf._scheduler.cache.k.dtype == jnp.dtype(jnp.float32)
    assert q._scheduler.cache.k.dtype == jnp.dtype(jnp.int8)
    assert q._scheduler.kscale.shape == (2, 2, 2, 16)  # [L, B, K, hd]
    assert bf._scheduler.kscale is None


def test_generation_close_to_fullprecision(engines):
    """Greedy decode with int8 KV must track the full-precision engine: the
    first continuation token comes from a prefill whose attention reads the
    FRESH K/V (no quant error), so it must match exactly; later tokens may
    diverge on a random-weight model, but output must be well-formed and
    deterministic."""
    bf, q = engines
    reqs = [GenerationRequest(prompt="the quick brown fox jumps", request_id=0,
                              temperature=0.0, max_new_tokens=10)]
    out_bf = bf.generate_batch(list(reqs))
    out_q = q.generate_batch(list(reqs))
    assert out_q[0].error is None
    assert out_q[0].completion_tokens > 0
    # same first sampled token: prefill logits see no pool reads
    assert out_q[0].text[:1] == out_bf[0].text[:1]
    # deterministic under the same seed: rerun reproduces exactly
    q2 = make_engine("int8")
    out_q2 = q2.generate_batch(
        [GenerationRequest(prompt="the quick brown fox jumps", request_id=0,
                           temperature=0.0, max_new_tokens=10)])
    assert out_q2[0].text == out_q[0].text


def test_scales_land_on_the_right_slots(engines):
    """After serving requests, each slot's scale rows hold real (non-init)
    values set by ITS prefill — the row->slot scatter contract."""
    _, q = engines
    reqs = [GenerationRequest(prompt=f"slot check {i} " * (i + 2),
                              request_id=i, temperature=0.0, max_new_tokens=3)
            for i in range(2)]
    out = q.generate_batch(reqs)
    assert all(r.error is None for r in out)
    ks = np.asarray(q._scheduler.kscale)
    # both slots served a prompt: no row can still be all-ones init
    for b in range(2):
        assert not np.allclose(ks[:, b], 1.0), f"slot {b} scales never set"


def test_decode_logits_match_fake_quant_reference():
    """The int8 pool path must equal a full-precision run whose pool
    CONTENTS were quantize-dequantized with the same scales — same math,
    different storage — to float tolerance.  Wires checked: scatter
    quantizes with the right rows' scales, gather dequantizes with the
    same, scale rows map dispatch rows to slots."""
    from lmrs_tpu.models.transformer import forward_paged, init_params

    cfg = tiny_model()
    import jax

    params = init_params(cfg, jax.random.PRNGKey(0))
    B, S, K, hd, ps = 2, 16, cfg.n_kv_heads, cfg.hd, 32
    npages = cfg.n_layers * 8
    rng = np.random.default_rng(1)
    tokens = jnp.asarray(rng.integers(1, 500, (B, S), dtype=np.int32))
    positions = jnp.broadcast_to(jnp.arange(S)[None], (B, S))
    tables = jnp.asarray([[1, 2], [3, 4]], jnp.int32)  # logical pages
    lens = jnp.asarray([S, S - 5], jnp.int32)

    # int8 run: fresh prefill computes scales + writes int8
    kq = jnp.zeros((npages, K, ps, hd), jnp.int8)
    vq = jnp.zeros((npages, K, ps, hd), jnp.int8)
    ksc = jnp.ones((cfg.n_layers, B, K, hd), jnp.float32)
    vsc = jnp.ones((cfg.n_layers, B, K, hd), jnp.float32)
    lg_q, kq, vq, (ksc, vsc) = forward_paged(
        params, cfg, tokens, positions, kq, vq, tables, lens,
        cfg.max_seq_len, kv_scales=(ksc, vsc))

    # full-precision run, then fake-quantize the pool contents in place
    kf = jnp.zeros((npages, K, ps, hd), jnp.float32)
    vf = jnp.zeros((npages, K, ps, hd), jnp.float32)
    lg_f, kf, vf = forward_paged(
        params, cfg, tokens, positions, kf, vf, tables, lens,
        cfg.max_seq_len)
    assert np.allclose(np.asarray(lg_q), np.asarray(lg_f), atol=1e-3), \
        "prefill logits must be identical: attention reads fresh K/V"

    # decode one token on both; the int8 path reads the quantized pool, the
    # reference reads a pool holding deq(quant(.)) of the same values
    ksc_n = np.asarray(ksc)
    vsc_n = np.asarray(vsc)
    kf_n, vf_n = np.array(kf), np.array(vf)  # writable copies
    for li in range(cfg.n_layers):
        for b in range(B):
            for w_, pg in enumerate(np.asarray(tables)[b]):
                g = li * 8 + pg
                sk = ksc_n[li, b][:, None]  # [K, 1, hd]
                sv = vsc_n[li, b][:, None]
                kf_n[g] = np.clip(np.round(kf_n[g] / sk), -127, 127) * sk
                vf_n[g] = np.clip(np.round(vf_n[g] / sv), -127, 127) * sv
    # the WRITE path must be exact: dequantizing the int8 pool reproduces
    # the fake-quantized full-precision pool bit-for-bit (same scales, same
    # round/clip) on every tabled page
    for li in range(cfg.n_layers):
        for b in range(B):
            n_valid = int(np.asarray(lens)[b])
            for w_, pg in enumerate(np.asarray(tables)[b]):
                g = li * 8 + pg
                rows = slice(0, max(0, min(ps, n_valid - w_ * ps)))
                deq_k = np.asarray(kq)[g].astype(np.float32) \
                    * ksc_n[li, b][:, None]
                np.testing.assert_allclose(
                    deq_k[:, rows], kf_n[g][:, rows], atol=1e-5)

    tok = jnp.asarray([[7], [9]], jnp.int32)
    pos1 = lens[:, None]
    lens1 = lens + 1
    lg_q1, *_ = forward_paged(
        params, cfg, tok, pos1, kq, vq, tables, lens1, cfg.max_seq_len,
        kv_scales=(ksc, vsc))
    lg_f1, *_ = forward_paged(
        params, cfg, tok, pos1, jnp.asarray(kf_n), jnp.asarray(vf_n),
        tables, lens1, cfg.max_seq_len)
    # the one remaining divergence source: the int8 path quantizes the NEW
    # decode token's K/V write, the reference writes it full-precision — a
    # single attended row of quant error, bounded well under a wiring bug
    # (wrong scale rows / pages show up as O(1) diffs)
    d = np.abs(np.asarray(lg_q1) - np.asarray(lg_f1)).max()
    assert d < 0.2, d


def test_kv_quant_gates():
    with pytest.raises(ValueError, match="page_size"):
        make_engine("int8", page_size=24)
    with pytest.raises(ValueError, match="kv_quantize"):
        EngineConfig(kv_quantize="int4")


def test_spec_int8_greedy_matches_plain_int8():
    """Speculation composes with int8 KV (VERDICT r4 item 4: the
    construction gate fell): greedy speculative decode on int8 pools must
    emit token-for-token what plain int8 decode emits — speculation is a
    scheduling optimization, and the draft rows are quantized with the
    same frozen slot scales the plain path uses."""
    reqs = [GenerationRequest(
        prompt="the cat sat on the mat the cat sat " * 3,
        request_id=i, max_new_tokens=16, temperature=0.0) for i in range(2)]
    plain = make_engine("int8")
    want = [r.text for r in plain.generate_batch(list(reqs))]
    plain.shutdown()

    spec = make_engine("int8", speculate_k=4)
    got_res = spec.generate_batch(list(reqs))
    m = spec.engine_metrics()
    spec.shutdown()
    assert all(r.error is None for r in got_res)
    assert [r.text for r in got_res] == want
    assert "spec_accepted_tokens" in m


def test_spec_int8_through_multi_kernel_matches_plain(monkeypatch):
    """The dequantizing RAGGED multi-token verify kernel (interpret mode)
    must match plain int8 decode token-for-token: the RMW quantizes draft
    rows with the slot's scales and the walk folds K/V dequant per head —
    same math as the single-token fused kernel, T rows at a time."""
    monkeypatch.setenv("LMRS_FORCE_KERNELS", "interpret")
    mc = ModelConfig(vocab_size=512, dim=512, n_layers=2, n_heads=4,
                     n_kv_heads=2, hidden_dim=256, max_seq_len=256,
                     dtype="float32")
    reqs = [GenerationRequest(
        prompt="the cat sat on the mat the cat sat " * 2,
        request_id=i, max_new_tokens=12, temperature=0.0) for i in range(2)]

    def make(k):
        return JaxEngine(EngineConfig(
            backend="jax", scheduler="continuous", max_tokens=12,
            max_batch_slots=2, seed=0, decode_block=6, page_size=32,
            kv_quantize="int8", speculate_k=k, retry_delay=0.0), mc)

    plain = make(0)
    assert plain._scheduler._use_ragged, "interpret mode should enable kernels"
    want = [r.text for r in plain.generate_batch(list(reqs))]
    plain.shutdown()

    spec = make(4)
    got_res = spec.generate_batch(list(reqs))
    spec.shutdown()
    assert all(r.error is None for r in got_res)
    assert [r.text for r in got_res] == want


def test_int8_fused_kernel_matches_xla(monkeypatch):
    """Interpret-mode parity: the dequantizing fused kernel (32-row RMW
    windows, q/acc-folded per-channel dequant) must match the int8 XLA
    scatter+gather path on the same pools and scales."""
    import jax

    from lmrs_tpu.ops.paged_attention import (
        paged_decode_pallas_fused, paged_decode_xla)

    rng = np.random.default_rng(3)
    B, H, K, hd, ps, P = 3, 4, 2, 128, 64, 16
    W = 3
    kq = jnp.asarray(rng.integers(-127, 128, (P, K, ps, hd)), jnp.int8)
    vq = jnp.asarray(rng.integers(-127, 128, (P, K, ps, hd)), jnp.int8)
    tables = jnp.asarray(rng.permutation(P - 1)[: B * W].reshape(B, W) + 1,
                         jnp.int32)
    lens = jnp.asarray([ps * 2 + 17, 33, ps * 3], jnp.int32)
    q = jnp.asarray(rng.standard_normal((B, H, hd)), jnp.float32)
    kn = jnp.asarray(rng.standard_normal((B, K, hd)), jnp.float32)
    vn = jnp.asarray(rng.standard_normal((B, K, hd)), jnp.float32)
    ks = jnp.asarray(rng.uniform(0.01, 0.05, (B, K, hd)), jnp.float32)
    vs = jnp.asarray(rng.uniform(0.01, 0.05, (B, K, hd)), jnp.float32)

    got, kq1, vq1 = paged_decode_pallas_fused(
        q, kn, vn, kq, vq, tables, lens, interpret=True,
        kscale=ks, vscale=vs)

    # reference: quantized scatter + dequantized gather (the phase-1 path)
    from lmrs_tpu.ops.quant import kv_quant

    pos = lens - 1
    page = jnp.take_along_axis(tables, (pos // ps)[:, None], 1)[:, 0]
    off = pos % ps
    kq_ref = kq.at[page, :, off].set(kv_quant(kn[:, None], ks)[:, 0])
    vq_ref = vq.at[page, :, off].set(kv_quant(vn[:, None], vs)[:, 0])
    want = paged_decode_xla(q, kq_ref, vq_ref, tables, lens,
                            kv_scales=(ks, vs))

    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               atol=2e-3, rtol=2e-3)
    # pool contents: the kernel's RMW must equal the XLA scatter
    np.testing.assert_array_equal(np.asarray(kq1), np.asarray(kq_ref))
    np.testing.assert_array_equal(np.asarray(vq1), np.asarray(vq_ref))


def test_int8_engine_with_interpret_kernels(monkeypatch):
    """The full continuous scheduler with kv int8 + the Pallas kernel path
    (interpret; needs the kernel-eligible head_dim 128): generation
    completes through the dequantizing fused kernel."""
    monkeypatch.setenv("LMRS_FORCE_KERNELS", "interpret")
    mc = ModelConfig(vocab_size=512, dim=512, n_layers=2, n_heads=4,
                     n_kv_heads=2, hidden_dim=256, max_seq_len=256,
                     dtype="float32")
    ec = EngineConfig(backend="jax", scheduler="continuous", max_tokens=24,
                      max_batch_slots=2, seed=0, page_size=32,
                      kv_quantize="int8", retry_delay=0.0)
    q = JaxEngine(ec, mc)
    assert q._scheduler._use_ragged, "interpret mode should enable kernels"
    out = q.generate_batch(
        [GenerationRequest(prompt="kernel path check", request_id=0,
                           temperature=0.0, max_new_tokens=6)])
    assert out[0].error is None and out[0].completion_tokens > 0


def test_chunked_prefill_sets_scales():
    """A prompt longer than prefill_chunk reaches the engine through the
    WINDOW (chunked) prefill path; its first chunk must still compute and
    store the slot's scales (review-caught: the window path previously
    quantized every long prompt with the all-ones init scales, silently
    zeroing small K/V values)."""
    q = make_engine("int8", prefill_chunk=64)
    prompt = "long prompt " * 30  # ~360 bytes >> 64-token chunks
    out = q.generate_batch(
        [GenerationRequest(prompt=prompt, request_id=0,
                           temperature=0.0, max_new_tokens=4)])
    assert out[0].error is None
    ks = np.asarray(q._scheduler.kscale)
    assert not np.allclose(ks[:, 0], 1.0), (
        "chunked prefill left slot 0's scales at init")
    # and the scale really is the FIRST chunk's: values are plausible
    # K-magnitudes (tiny), not the 1.0 init
    assert float(ks[:, 0].max()) < 0.5


def test_int8_composes_with_packed_prefill(monkeypatch):
    """int8 KV + packed prefill (VERDICT r3 item 3): same-wave fresh
    prompts concatenate into one segment-masked dispatch whose per-SEGMENT
    scales land on each segment's slot row — greedy output must match the
    unpacked int8 run (a segment's max-abs stats are identical to the same
    prompt prefilled alone), and the packed program must actually run."""
    reqs = [GenerationRequest(prompt=f"pack quant probe {i} " * (2 + 2 * i),
                              request_id=i, temperature=0.0, max_new_tokens=8)
            for i in range(2)]

    monkeypatch.setenv("LMRS_PACK_PREFILL", "0")
    plain = make_engine("int8")
    want = [r.text for r in plain.generate_batch(list(reqs))]
    assert not plain._scheduler._packed_prefill_fns
    plain.shutdown()

    monkeypatch.setenv("LMRS_PACK_PREFILL", "1")
    packed = make_engine("int8")
    got = [r.text for r in packed.generate_batch(list(reqs))]
    assert packed._scheduler._packed_prefill_fns, "packed path not exercised"
    # per-segment scales landed on their slots (not left at the ones init)
    ks = np.asarray(packed._scheduler.kscale)
    for b in range(2):
        assert not np.allclose(ks[:, b], 1.0), f"slot {b} scales never set"
    packed.shutdown()
    assert got == want
